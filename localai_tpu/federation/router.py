"""Federation router: balance requests across serving processes.

TPU redesign of the reference's federated mode (core/p2p/federated_server.go:
66-99 — a libp2p tunnel picking a worker per connection with random or
least-used selection; node discovery over a DHT). Here discovery is explicit
registration over HTTP (TPU pods live on flat DCN networks — no NAT traversal
to solve), and proxying happens at the HTTP layer so SSE streams pass through
chunk-by-chunk.

Strategies (reference parity):
- least-used: fewest in-flight requests (federated_server.go LoadBalanced)
- random: uniform pick
- targeted: honor a `LocalAI-Worker` header naming one worker
- affinity: delegate the pick to the cluster scheduler (ISSUE 6,
  docs/CLUSTER.md) — chained byte-span hashes of the request's prompt
  material route repeats to the worker whose prefix cache likely holds
  them, scored against in-flight load; health/backoff/flap machinery stays
  exactly as below, the scheduler only chooses among workers this registry
  says are alive.
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

log = logging.getLogger("localai_tpu.federation")

HOP_HEADERS = {
    "connection", "keep-alive", "transfer-encoding", "te", "upgrade",
    "proxy-authorization", "proxy-authenticate", "host", "content-length",
}


@dataclass
class Worker:
    name: str
    url: str  # base URL, e.g. http://10.0.0.2:8080
    in_flight: int = 0
    total_served: int = 0
    healthy: bool = True
    last_seen: float = field(default_factory=time.monotonic)
    # Unhealthy re-probe backoff (ISSUE 4): consecutive failed probes and
    # the earliest time the health loop may probe this worker again. A
    # worker previously flapped straight back — every tick re-probed it and
    # one lucky /healthz marked it healthy again mid-outage, routing user
    # traffic into the failure.
    fail_count: int = 0
    next_probe: float = 0.0
    # Health-transition counters (monitoring): healthy→unhealthy and back.
    went_unhealthy: int = 0
    went_healthy: int = 0
    # Cluster role (ISSUE 6): learned from the LocalAI-Cluster-Role header
    # a worker sends on health-probe responses (server/app.py); the
    # affinity scheduler role-types its picks with it.
    role: str = "mixed"
    # Remote engine gauges (ISSUE 13): the last localai_engine_* /metrics
    # scrape and when it landed. The affinity scheduler reads these through
    # a STALENESS BOUND — gauges older than gauge_stale_s re-scrape, and a
    # worker unreachable past the dead bound scores as loop_dead. The
    # /federation/workers view surfaces the age so operators can see WHY
    # the scheduler skipped a replica.
    gauges: dict = field(default_factory=dict)
    last_gauge_at: float = 0.0


class WorkerRegistry:
    def __init__(self, backoff_base_s: float = 1.0,
                 backoff_max_s: float = 60.0) -> None:
        self._lock = threading.Lock()
        self._workers: dict[str, Worker] = {}
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s

    def add(self, name: str, url: str) -> None:
        with self._lock:
            w = self._workers.get(name)
            if w is not None:
                w.url = url.rstrip("/")
                w.healthy = True
                w.fail_count = 0
                w.next_probe = 0.0
                w.last_seen = time.monotonic()
            else:
                self._workers[name] = Worker(name=name, url=url.rstrip("/"))

    def remove(self, name: str) -> bool:
        with self._lock:
            return self._workers.pop(name, None) is not None

    def list(self) -> list[Worker]:
        with self._lock:
            return list(self._workers.values())

    def pick(self, strategy: str, target: Optional[str] = None) -> Optional[Worker]:
        with self._lock:
            if target:
                w = self._workers.get(target)
                return w if w is not None and w.healthy else None
            healthy = [w for w in self._workers.values() if w.healthy]
            if not healthy:
                # ISSUE 6 satellite: a fully-unhealthy fleet previously
                # 503'd until the next health-loop tick even when workers
                # had already recovered. Serve the recovery probe INLINE:
                # hand the request to the least-recently-failed worker whose
                # re-probe backoff has expired (due_for_probe semantics) —
                # success marks it healthy via the normal proxy path, and a
                # still-dead worker just burns one request that would have
                # 503'd anyway.
                now = time.monotonic()
                due = [w for w in self._workers.values() if now >= w.next_probe]
                if not due:
                    return None
                return min(due, key=lambda w: (w.next_probe, w.name))
            if strategy == "random":
                return random.choice(healthy)
            # least-used (default — federated_server.go LoadBalanced); ties
            # break by fewest total served, i.e. round-robin when idle.
            return min(healthy, key=lambda w: (w.in_flight, w.total_served, w.name))

    def acquire(self, w: Worker) -> None:
        with self._lock:
            w.in_flight += 1
            w.total_served += 1

    def release(self, w: Worker) -> None:
        with self._lock:
            w.in_flight = max(0, w.in_flight - 1)

    def mark(self, w: Worker, healthy: bool) -> None:
        with self._lock:
            if healthy:
                if not w.healthy:
                    w.went_healthy += 1
                    log.info("worker %s (%s) healthy again after %d failed "
                             "probes", w.name, w.url, w.fail_count)
                w.healthy = True
                w.fail_count = 0
                w.next_probe = 0.0
                w.last_seen = time.monotonic()
                return
            if w.healthy:
                w.went_unhealthy += 1
            w.healthy = False
            # Exponential re-probe backoff: 1 failure → base, then doubling
            # to the cap. A mid-outage worker is probed ever more rarely
            # instead of every tick (where one lucky probe flapped it back
            # into rotation while still broken).
            w.fail_count += 1
            backoff = min(
                self.backoff_base_s * (2 ** (w.fail_count - 1)),
                self.backoff_max_s,
            )
            w.next_probe = time.monotonic() + backoff

    def due_for_probe(self, w: Worker) -> bool:
        """Healthy workers probe every tick; unhealthy ones only after
        their current backoff expires."""
        with self._lock:
            return w.healthy or time.monotonic() >= w.next_probe


class FederatedServer:
    """HTTP front door proxying to registered workers.

    Control endpoints (served locally, never proxied):
      GET  /federation/workers       — registry snapshot
      POST /federation/register      — {name, url} joins the pool
      POST /federation/unregister    — {name} leaves
    Everything else proxies to a worker chosen by the strategy, or by the
    `LocalAI-Worker: <name>` request header (targeted mode).
    """

    def __init__(
        self,
        address: str = "127.0.0.1",
        port: int = 9090,
        strategy: str = "least-used",
        workers: Optional[list[tuple[str, str]]] = None,
        health_interval_s: float = 5.0,
        token: Optional[str] = None,
        probe_backoff_s: float = 1.0,
        probe_backoff_max_s: float = 60.0,
        gauge_stale_s: float = 5.0,
    ):
        # Shared-token gate on the control plane (reference parity:
        # core/p2p/p2p.go:31-64 — the libp2p overlay requires a shared
        # TOKEN). Without it any host reaching the front door could insert
        # itself as a worker and receive proxied user traffic. Defaults to
        # $LOCALAI_P2P_TOKEN; empty string/None leaves registration open
        # (single-trust-domain deployments).
        import os as _os

        self.token = token if token is not None else _os.environ.get("LOCALAI_P2P_TOKEN", "")
        self.registry = WorkerRegistry(
            backoff_base_s=probe_backoff_s, backoff_max_s=probe_backoff_max_s
        )
        self.strategy = strategy
        # Affinity strategy (ISSUE 6): the cluster scheduler owns the pick;
        # this registry keeps owning health, backoff, and flap counters.
        # cluster.affinity/scheduler are numpy-only — no jax import here.
        self.scheduler = None
        self.affinity_span_bytes = 256
        self.gauge_stale_s = gauge_stale_s
        if strategy == "affinity":
            from localai_tpu.cluster.scheduler import ClusterScheduler

            self.scheduler = ClusterScheduler(
                span_tokens=0,  # byte-span hashing happens in pick_worker
                gauge_refresh_s=min(1.0, health_interval_s or 1.0),
            )
        for name, url in workers or []:
            self.registry.add(name, url)
        self._health_interval = health_interval_s
        self._stop = threading.Event()
        self._server = self._build(address, port)
        self._health_thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="fed-server").start()
        if self._health_interval > 0:
            self._health_thread = threading.Thread(
                target=self._health_loop, daemon=True, name="fed-health"
            )
            self._health_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._server.shutdown()

    # ------------------------------------------------------------------ #

    def _health_loop(self) -> None:
        while not self._stop.wait(self._health_interval):
            for w in self.registry.list():
                if not self.registry.due_for_probe(w):
                    continue  # unhealthy and still inside its backoff
                try:
                    with urllib.request.urlopen(w.url + "/healthz", timeout=3) as resp:
                        role = resp.headers.get("LocalAI-Cluster-Role", "")
                    self.registry.mark(w, True)
                    # Role discovery (ISSUE 6): workers advertise their
                    # cluster role on every response; the affinity
                    # scheduler role-types picks with it.
                    if role in ("prefill", "decode", "mixed") and role != w.role:
                        w.role = role
                        if self.scheduler is not None:
                            self.scheduler.set_role(w.name, role)
                except Exception:  # noqa: BLE001
                    log.warning("worker %s (%s) unhealthy (probe #%d)",
                                w.name, w.url, w.fail_count + 1)
                    self.registry.mark(w, False)

    # ---------------- affinity delegation (ISSUE 6) ---------------- #

    def _worker_gauges(self, w: Worker) -> dict:
        """Remote load for the affinity scheduler (ISSUE 13): the worker's
        own localai_engine_* gauges scraped over HTTP with a staleness
        bound. An unreachable worker keeps serving its last scrape until
        the bound expires, then scores as dead (the scheduler drains its
        affinity); the front door's in-flight count rides on top so
        proxied-but-unadmitted requests still register as load."""
        if not w.healthy:
            return {"loop_dead": 1.0}
        now = time.monotonic()
        if now - w.last_gauge_at >= self.gauge_stale_s:
            from localai_tpu.cluster.replica import scrape_engine_gauges

            try:
                g = scrape_engine_gauges(w.url, timeout=2.0)
                w.gauges, w.last_gauge_at = g, time.monotonic()
            except Exception:  # noqa: BLE001 — scrape failure ages out
                if now - w.last_gauge_at > 3 * self.gauge_stale_s:
                    return {"loop_dead": 1.0}
        g = dict(w.gauges)
        g["queue_depth"] = g.get("queue_depth", 0.0) + float(w.in_flight)
        return g

    def _sync_scheduler(self) -> None:
        """Mirror the registry into the scheduler (workers join/leave at
        runtime). Existing replicas keep their affinity maps."""
        workers = {w.name: w for w in self.registry.list()}
        known = set(self.scheduler.names())
        for name in known - set(workers):
            self.scheduler.remove_replica(name)
        for name, w in workers.items():
            if name not in known:
                self.scheduler.add_replica(
                    name, target=w, role=w.role,
                    gauge_fn=(lambda w=w: self._worker_gauges(w)),
                )

    @staticmethod
    def _affinity_material(raw_body: Optional[bytes]) -> bytes:
        """Prompt bytes for byte-span hashing: the front door has no
        tokenizer, so it hashes the prompt TEXT (identical text tokenizes
        identically on every worker). Falls back to the raw body."""
        if not raw_body:
            return b""
        try:
            body = json.loads(raw_body)
        except (ValueError, UnicodeDecodeError):
            return raw_body
        if not isinstance(body, dict):
            return raw_body
        msgs = body.get("messages")
        if isinstance(msgs, list):
            parts = []
            for m in msgs:
                if isinstance(m, dict):
                    parts.append(f"{m.get('role', '')}\x00{m.get('content', '')}")
            return "\x1e".join(parts).encode("utf-8", "replace")
        for key in ("prompt", "input"):
            if key in body:
                return json.dumps(body[key], sort_keys=True).encode()
        return raw_body

    def pick_worker(self, target: Optional[str],
                    raw_body: Optional[bytes]) -> Optional[Worker]:
        """One worker for this request. Targeted and non-affinity picks go
        straight to the registry; affinity picks hash the prompt material
        and delegate to the cluster scheduler, falling back to least-used
        when the scheduler abstains (e.g. every worker just registered)."""
        if self.scheduler is None or target:
            return self.registry.pick(self.strategy, target)
        self._sync_scheduler()
        from localai_tpu.cluster.affinity import byte_span_hashes

        hashes = byte_span_hashes(
            self._affinity_material(raw_body),
            span_bytes=self.affinity_span_bytes,
        )
        name = self.scheduler.pick(hashes)
        worker = self.registry.pick("least-used", name) if name else None
        if worker is None:
            # Scheduler and registry disagree (a worker died inside the
            # gauge-refresh window) or everything is dead — the registry's
            # least-used/recovery logic is the backstop.
            return self.registry.pick("least-used", None)
        self.scheduler.record(name, hashes)
        return worker

    def _build(self, address: str, port: int) -> ThreadingHTTPServer:
        fed = self

        class Proxy(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = "localai-tpu-federation"

            def log_message(self, fmt, *args):
                log.debug("%s " + fmt, self.address_string(), *args)

            def _json(self, status: int, body: dict) -> None:
                data = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _authorized(self) -> bool:
                if not fed.token:
                    return True
                import hmac

                auth = self.headers.get("Authorization", "")
                bearer = auth[7:] if auth.startswith("Bearer ") else ""
                supplied = self.headers.get("LocalAI-P2P-Token", bearer)
                # bytes, not str: compare_digest raises TypeError on
                # non-ASCII str input (headers are latin-1 decoded).
                return hmac.compare_digest(
                    supplied.encode("utf-8", "surrogateescape"),
                    fed.token.encode("utf-8", "surrogateescape"),
                )

            def _control(self) -> bool:
                if self.path == "/federation/workers" and self.command == "GET":
                    # The listing leaks worker names/URLs/load — gate it with
                    # the same shared token as join/leave (the reference's
                    # token gates the whole p2p overlay, p2p.go:31-64;
                    # explorer/worker callers already hold it).
                    if not self._authorized():
                        self._json(401, {"error": "federation token required"})
                        return True
                    now = time.monotonic()
                    self._json(200, {"workers": [
                        {
                            "name": w.name, "url": w.url, "healthy": w.healthy,
                            "in_flight": w.in_flight,
                            "fail_count": w.fail_count,
                            "went_unhealthy": w.went_unhealthy,
                            "went_healthy": w.went_healthy,
                            # Discovered cluster role + gauge freshness
                            # (ISSUE 13 satellite): why the affinity
                            # scheduler skipped a replica — wrong role for
                            # the pick, or gauges stale past the bound.
                            "role": w.role,
                            "last_gauge_age_s": (
                                round(now - w.last_gauge_at, 2)
                                if w.last_gauge_at else None),
                            "queue_depth": w.gauges.get("queue_depth"),
                        }
                        for w in fed.registry.list()
                    ], "strategy": fed.strategy})
                    return True
                if self.path == "/federation/register" and self.command == "POST":
                    # Read the body before any response: leaving it unread
                    # would corrupt the next request on a keep-alive
                    # connection (protocol_version is HTTP/1.1).
                    body = self._read_json()
                    if not self._authorized():
                        self._json(401, {"error": "federation token required"})
                        return True
                    if not body or "name" not in body or "url" not in body:
                        self._json(400, {"error": "name and url required"})
                        return True
                    fed.registry.add(body["name"], body["url"])
                    self._json(200, {"status": "registered"})
                    return True
                if self.path == "/federation/unregister" and self.command == "POST":
                    body = self._read_json()  # drain before responding (as above)
                    if not self._authorized():
                        self._json(401, {"error": "federation token required"})
                        return True
                    ok = bool(body) and fed.registry.remove(body.get("name", ""))
                    self._json(200 if ok else 404, {"status": "ok" if ok else "unknown"})
                    return True
                return False

            def _read_json(self) -> Optional[dict]:
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    return json.loads(self.rfile.read(n)) if n else None
                except (ValueError, json.JSONDecodeError):
                    return None

            def _proxy(self) -> None:
                target = self.headers.get("LocalAI-Worker")
                # Body first: the affinity pick hashes the prompt material
                # (and the stream must be drained before any response on a
                # keep-alive connection anyway).
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else None
                worker = fed.pick_worker(target, body)
                if worker is None:
                    self._json(503, {"error": {
                        "message": "no healthy federation worker available",
                        "type": "server_error",
                    }})
                    return
                headers = {
                    k: v for k, v in self.headers.items()
                    if k.lower() not in HOP_HEADERS and k != "LocalAI-Worker"
                }
                if not any(k.lower() == "traceparent" for k in headers):
                    # Trace propagation (ISSUE 11): clients that sent no
                    # W3C traceparent still get ONE trace id across every
                    # worker hop — the front door mints it.
                    from localai_tpu.observe.trace import new_traceparent

                    headers["traceparent"] = new_traceparent()
                req = urllib.request.Request(
                    worker.url + self.path, data=body, headers=headers,
                    method=self.command,
                )
                fed.registry.acquire(worker)
                try:
                    resp = urllib.request.urlopen(req, timeout=600)
                except urllib.error.HTTPError as e:
                    resp = e  # proxy error bodies through unchanged
                except Exception as e:  # noqa: BLE001
                    fed.registry.mark(worker, False)
                    self._json(502, {"error": {
                        "message": f"worker {worker.name} failed: {e}",
                        "type": "server_error",
                    }})
                    fed.registry.release(worker)
                    return
                if not worker.healthy:
                    # The all-unhealthy recovery path routed here and the
                    # worker answered — it is back (the health loop would
                    # only notice at its next due probe).
                    fed.registry.mark(worker, True)
                try:
                    self.send_response(resp.status)
                    is_stream = False
                    for k, v in resp.headers.items():
                        if k.lower() in HOP_HEADERS:
                            continue
                        if k.lower() == "content-type" and "event-stream" in v:
                            is_stream = True
                        self.send_header(k, v)
                    self.send_header("LocalAI-Served-By", worker.name)
                    if is_stream:
                        # Chunked pass-through so tokens stream live.
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        while True:
                            chunk = resp.read1(65536) if hasattr(resp, "read1") else resp.read(4096)
                            if not chunk:
                                break
                            self.wfile.write(f"{len(chunk):X}\r\n".encode() + chunk + b"\r\n")
                            self.wfile.flush()
                        self.wfile.write(b"0\r\n\r\n")
                    else:
                        data = resp.read()
                        self.send_header("Content-Length", str(len(data)))
                        self.end_headers()
                        if self.command != "HEAD":
                            self.wfile.write(data)
                except (BrokenPipeError, ConnectionResetError):
                    log.debug("federation client disconnected")
                finally:
                    resp.close()
                    fed.registry.release(worker)

            def _handle(self) -> None:
                if not self._control():
                    self._proxy()

            do_GET = do_POST = do_DELETE = do_PUT = do_HEAD = _handle

        return ThreadingHTTPServer((address, port), Proxy)


def register_with_federator(
    federator_url: str, name: str, my_url: str, token: Optional[str] = None
) -> bool:
    """Worker-side join (reference: p2p node announcing on the DHT)."""
    import os as _os

    token = token if token is not None else _os.environ.get("LOCALAI_P2P_TOKEN", "")
    try:
        headers = {"Content-Type": "application/json"}
        if token:
            headers["LocalAI-P2P-Token"] = token
        req = urllib.request.Request(
            federator_url.rstrip("/") + "/federation/register",
            data=json.dumps({"name": name, "url": my_url}).encode(),
            headers=headers,
        )
        with urllib.request.urlopen(req, timeout=10):
            return True
    except Exception:  # noqa: BLE001
        log.warning("could not register with federator %s", federator_url)
        return False
