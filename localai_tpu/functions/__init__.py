"""Tool-calling: tool→prompt rendering and function-call parsing.

Reference: pkg/functions (2,436 LoC — grammar generation in grammars/,
ParseFunctionCall in parse.go:16-376). TPU-native difference (SURVEY.md §7
build plan item 6): instead of GBNF inside the engine, constrained decoding is
token-mask biasing computed host-side and applied in the jitted sample step
(see localai_tpu.functions.jsonschema).
"""

from localai_tpu.functions.parse import parse_function_calls, tools_prompt_for  # noqa: F401
