"""JSON-Schema → token-level DFA tables for on-device constrained decoding.

The pushdown machine in functions/jsonschema.py is the semantic source of
truth; this module compiles it to a finite automaton so the constraint can
run INSIDE the fused decode blocks instead of a host round-trip per token
(SURVEY §7 hard part: "grammar decode without host round-trips per token —
mask precomputation / on-device DFA"; reference: llama.cpp applies its GBNF
grammar inside the sampler, grpc-server.cpp).

Why this terminates: a JSON Schema without recursive $refs has bounded
nesting, so the set of reachable machine configurations is finite — the
machine is effectively a DFA for a fixed schema. We enumerate reachable
configurations by BFS over a small character-class alphabet, then lift the
char-level DFA to the token vocabulary: for each (state, token) pair, walk
the token's characters through the DFA. The result is three small tables
the engine keeps on device (see TokenTables):

  mask_bits uint8 [S+1, ceil(V/8)]  bit v of row s = token v legal in state s
  trans     int16 [S+1, C]          char-class transitions (walked on device
                                    for the sampled token — no [S, V] table)
  tok_cls   int16 [V, MAX_TOK_LEN]  each token's char-class sequence

Row 0 is the reserved FREE state (everything legal, self-loop): slots not
under a grammar run through the same program unmasked, so constrained and
unconstrained requests batch together. The EOS column is legal exactly in
accepting states, which is also how a finished value terminates: a state
whose only legal continuation is EOS forces the model to stop.

Build cost is host-side and cached per (schema, tokenizer); schemas that
exceed the state budget raise DfaUnsupported and the engine falls back to
the host candidate-walk path.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Any, Optional

import numpy as np

from localai_tpu.functions.jsonschema import (
    JsonSchemaMachine,
    _Array,
    _Frame,
    _Object,
)

# Alphabet: every printable ASCII char is its own class (structure chars,
# literal/property-name spelling), plus \t \n \r, a control-char class, any
# non-ASCII chars that appear in the schema's own literals, and OTHER for
# every remaining char (string bodies treat them all alike).
_PRINTABLE = [chr(c) for c in range(0x20, 0x7F)]
_OTHER_REP = ""  # private-use: never appears in schemas
_CTRL_REP = "\x01"


class DfaUnsupported(Exception):
    """Schema doesn't fit the DFA budget — use the host-walk fallback."""


def _schema_strings(schema: Any, out: Optional[list] = None) -> list:
    """Every string literal a schema can force into the output."""
    if out is None:
        out = []
    if isinstance(schema, dict):
        for v in schema.values():
            _schema_strings(v, out)
        for k in schema.get("properties", {}) or {}:
            out.append(k)
    elif isinstance(schema, list):
        for v in schema:
            _schema_strings(v, out)
    elif isinstance(schema, str):
        out.append(schema)
    return out


def _clone_frame(f: _Frame, machine) -> _Frame:
    new = object.__new__(type(f))
    for k, v in f.__dict__.items():
        if k == "machine":
            v = machine
        elif isinstance(v, _Frame):
            v = _clone_frame(v, machine)
        elif isinstance(v, set):
            v = set(v)
        elif isinstance(v, list) and not (v and isinstance(v[0], (dict, _Frame))):
            v = list(v)
        # dicts (schemas) are read-only by construction — share them.
        new.__dict__[k] = v
    return new


def _clone_machine(m: JsonSchemaMachine) -> JsonSchemaMachine:
    """Structure-sharing clone: frames copy their scalar state but share the
    (immutable) schema dicts — orders of magnitude cheaper than deepcopy and
    keeps schema identity stable for config hashing."""
    new = object.__new__(JsonSchemaMachine)
    new.max_ws_run = m.max_ws_run
    new.ws_run = m.ws_run
    new.stack = []
    new.stack.extend(_clone_frame(f, new) for f in m.stack)
    return new


def _key_val(v: Any) -> Any:
    if isinstance(v, _Frame):
        return _frame_key(v)
    if isinstance(v, dict):
        return id(v)  # shared schema object — identity is stable (see clone)
    if isinstance(v, set):
        return frozenset(v)
    if isinstance(v, list):
        return tuple(_key_val(x) for x in v)
    return v


def _frame_key(f: _Frame) -> tuple:
    d = dict(f.__dict__)
    # Saturate unbounded counters so the config space stays finite: an
    # array's item count only matters against min/maxItems (without
    # maxItems, every n >= minItems behaves identically), and an object's
    # count is only ever compared against 0.
    if isinstance(f, _Array) and f.max_items is None:
        d["n"] = min(f.n, f.min_items)
    elif isinstance(f, _Object):
        d["n"] = min(f.n, 1)
    items = tuple(
        (k, _key_val(v)) for k, v in sorted(d.items()) if k != "machine"
    )
    return (type(f).__name__, items)


def _config_key(m: JsonSchemaMachine) -> tuple:
    return (m.ws_run, tuple(_frame_key(f) for f in m.stack))


class CharDFA:
    def __init__(self, trans: np.ndarray, accept: np.ndarray,
                 classes: dict[str, int], other_class: int, ctrl_class: int):
        self.trans = trans  # [S, C] int32, -1 = reject
        self.accept = accept  # [S] bool
        self.classes = classes
        self.other_class = other_class
        self.ctrl_class = ctrl_class

    def class_of(self, ch: str) -> int:
        cid = self.classes.get(ch)
        if cid is not None:
            return cid
        if ch < " ":  # control chars share one (rejected-in-strings) class
            return self.ctrl_class
        return self.other_class


def compile_schema_dfa(schema: Any, max_states: int = 3072,
                       max_ws_run: int = 1) -> CharDFA:
    """BFS over reachable machine configurations → char-class DFA.

    A `{"__gbnf__": <text>}` marker (functions/gbnf.py GbnfConstraint.schema)
    routes to the GBNF machine's compiler — raw grammars ride the same
    token-table path and cache as JSON schemas."""
    if isinstance(schema, dict) and "__gbnf__" in schema:
        from localai_tpu.functions.gbnf import compile_gbnf_dfa

        return compile_gbnf_dfa(schema["__gbnf__"], max_states=max_states)
    extra = sorted({ch for s in _schema_strings(schema) for ch in s
                    if ord(ch) > 0x7E})
    reps = _PRINTABLE + ["\t", "\n", "\r", _CTRL_REP] + extra + [_OTHER_REP]
    classes = {ch: i for i, ch in enumerate(reps)}
    other_class = len(reps) - 1
    C = len(reps)

    start = JsonSchemaMachine(schema, max_ws_run=max_ws_run)
    states: list[JsonSchemaMachine] = [start]
    keys = {_config_key(start): 0}
    rows: list[np.ndarray] = []
    queue = deque([0])
    while queue:
        i = queue.popleft()
        while len(rows) <= i:
            rows.append(np.full((C,), -1, np.int32))
        m = states[i]
        row = rows[i]
        for cid, ch in enumerate(reps):
            c = _clone_machine(m)
            if not c.feed(ch):
                continue
            k = _config_key(c)
            j = keys.get(k)
            if j is None:
                if len(states) >= max_states:
                    raise DfaUnsupported(
                        f"schema needs > {max_states} DFA states"
                    )
                j = len(states)
                keys[k] = j
                states.append(c)
                queue.append(j)
            row[cid] = j
    trans = np.stack(rows)
    accept = np.asarray([s.is_complete() for s in states], bool)
    # Strictly-complete states (empty stack) admit ONLY EOS: the machine
    # itself tolerates one trailing whitespace char, but emitting it would
    # append junk to structured output — the host-walk path avoids that by
    # finishing at strictly_complete(), and the DFA must match ('false\r'
    # is not 'false'). Trailing-number states keep their digits (non-empty
    # stack), so "12" can still extend to "123".
    strict = np.asarray([not s.stack for s in states], bool)
    trans[strict] = -1
    return CharDFA(trans, accept, classes, other_class, classes[_CTRL_REP])


# Tokens longer than this many characters are never grammar-legal (the
# device transition walk is a fixed-length scan). Real vocabularies keep
# structural tokens short; only exotic whitespace/indent tokens exceed it.
MAX_TOK_LEN = 32


# A [S, V] next-state-by-token table costs (S+1)·V·2 bytes on device; for
# small automata that is cheap (64 MB at 256 states × 128k vocab) and
# replaces the per-step 32-gather char walk with ONE gather — worth ~40% of
# constrained decode throughput. Bigger automata keep the char walk.
NEXT_TOK_MAX_STATES = 256


class TokenTables:
    """Device-ready constraint tables.

    mask_bits uint8 [S+1, ceil(V/8)] — bit v of row s: token v legal in
      state s. Row 0 is FREE (everything legal); DFA state s is row s+1.
    trans     int16 [S+1, C] — char-class transition table (row 0
      self-loops); the decode block walks the SAMPLED token's classes
      through it to get the next state when next_tok is absent.
    tok_cls   int16 [V, MAX_TOK_LEN] — each token's char-class sequence,
      -1 padded.
    next_tok  int16 [S+1, V] or None — direct state-after-token table,
      built for automata with ≤ NEXT_TOK_MAX_STATES states (values for
      illegal tokens are meaningless; the mask rules them out first).
    init_state = 1 (the machine's start configuration).
    """

    def __init__(self, mask_bits, trans, tok_cls, accept, next_tok=None):
        self.mask_bits = mask_bits
        self.trans = trans
        self.tok_cls = tok_cls
        self.accept = accept  # [S+1] bool (FREE row accepting)
        self.next_tok = next_tok
        self.init_state = 1


def build_token_tables(
    dfa: CharDFA,
    tok_strs: list[str],
    eos_ids: set[int],
    vocab_size: int,
    chunk: int = 16384,
) -> TokenTables:
    """Lift the char DFA to the token vocabulary (mask only — transitions
    stay char-level and are walked on device).

    Raises DfaUnsupported if some reachable non-accepting state has no legal
    token (the constraint would wedge there).
    """
    S, C = dfa.trans.shape
    if S + 1 > np.iinfo(np.int16).max:
        raise DfaUnsupported("state count exceeds int16 table range")
    V = vocab_size
    n_tok = min(len(tok_strs), V)

    # Token → class-id sequences, grouped by length so the vectorized walk
    # only advances positions that still have characters.
    lens = np.zeros((V,), np.int32)
    seqs: list[list[int]] = [[] for _ in range(V)]
    for t in range(n_tok):
        s = tok_strs[t]
        if t in eos_ids or len(s) > MAX_TOK_LEN:
            continue
        lens[t] = len(s)
        seqs[t] = [dfa.class_of(ch) for ch in s]
    order = np.argsort(lens, kind="stable")

    build_next = S + 1 <= NEXT_TOK_MAX_STATES
    allowed = np.zeros((S, V), bool)
    final = np.zeros((S, V), np.int32) if build_next else None
    for c0 in range(0, V, chunk):
        ids = order[c0: c0 + chunk]
        clen = int(lens[ids].max()) if len(ids) else 0
        if clen == 0:
            continue
        cls_seq = np.full((len(ids), clen), -1, np.int16)
        for j, t in enumerate(ids):
            cls_seq[j, : lens[t]] = seqs[t]
        cur = np.broadcast_to(np.arange(S, dtype=np.int32)[:, None],
                              (S, len(ids))).copy()
        alive = np.ones((S, len(ids)), bool)
        for p in range(clen):
            csel = cls_seq[:, p]
            act = csel >= 0
            if not act.any():
                break
            step = dfa.trans[np.maximum(cur, 0), np.maximum(csel, 0)[None, :]]
            upd = act[None, :] & alive
            cur = np.where(upd, step, cur)
            alive = np.where(upd, step >= 0, alive)
        ok = alive & (lens[ids] > 0)[None, :]
        allowed[:, ids] = ok
        if build_next:
            final[:, ids] = np.where(ok, cur, 0)

    # EOS legal exactly in accepting states.
    for e in eos_ids:
        if 0 <= e < V:
            allowed[:, e] = dfa.accept

    wedged = ~dfa.accept & ~allowed.any(axis=1)
    if wedged.any():
        raise DfaUnsupported(
            f"{int(wedged.sum())} reachable states admit no token from this "
            "vocabulary"
        )

    # Prepend FREE row 0; DFA state s lives at row s+1.
    mask = np.zeros((S + 1, V), bool)
    mask[0] = True
    mask[1:] = allowed
    mask_bits = np.packbits(mask, axis=1, bitorder="little")

    trans = np.zeros((S + 1, C), np.int16)  # FREE row self-loops at 0
    trans[1:] = np.where(dfa.trans >= 0, dfa.trans + 1, 0).astype(np.int16)

    tok_cls = np.full((V, MAX_TOK_LEN), -1, np.int16)
    for t in range(n_tok):
        if lens[t]:
            tok_cls[t, : lens[t]] = seqs[t]

    accept = np.zeros((S + 1,), bool)
    accept[0] = True
    accept[1:] = dfa.accept
    next_tok = None
    if build_next:
        next_tok = np.zeros((S + 1, V), np.int16)  # FREE row self-loops at 0
        next_tok[1:] = np.where(allowed, final + 1, 0).astype(np.int16)
    return TokenTables(mask_bits, trans, tok_cls, accept, next_tok)


# Host-side cache: schemas repeat across requests (tool-calling reuses one
# schema for a whole deployment), so compiled tables are memoized. Both maps
# are bounded — schemas arrive from the serving API, so unbounded growth
# would be a client-drivable leak.
_CACHE: dict[tuple, TokenTables] = {}
_CACHE_MAX = 8
_FAILED: dict[tuple, bool] = {}  # insertion-ordered — evicted FIFO
_FAILED_MAX = 256
_PINNED: set = set()  # prewarmed keys exempt from LRU eviction (operator-controlled)
_BUILDING: dict = {}  # key -> threading.Event, dedupes concurrent builds
_LOCK = threading.Lock()


def schema_key(schema: Any) -> str:
    return json.dumps(schema, sort_keys=True, separators=(",", ":"), default=str)


def unpin(tokenizer_id: Any = None) -> None:
    """Drop pinned tables (all, or those for one tokenizer fingerprint).

    Engines call this with their fingerprint at stop so prewarmed tables
    keyed to a dead tokenizer don't leak for the process lifetime across
    model hot-swaps."""
    with _LOCK:
        for key in list(_PINNED):
            if tokenizer_id is None or key[1] == tokenizer_id:
                _PINNED.discard(key)
                _CACHE.pop(key, None)


def is_cached(schema: Any, tokenizer_id: Any, vocab_size: int) -> bool:
    """True when tables_for will return instantly (hit or known-failure)."""
    key = (schema_key(schema), tokenizer_id, vocab_size)
    with _LOCK:
        return key in _CACHE or key in _FAILED


def tables_for(schema: Any, tok_strs: list[str], eos_ids: set[int],
               vocab_size: int, tokenizer_id: Any = None,
               max_states: int = 3072, pin: bool = False,
               cached_only: bool = False) -> Optional[TokenTables]:
    """Cached TokenTables for a schema, or None if unsupported.

    Concurrent calls for the same key build once: the second caller blocks
    on the first build's completion instead of burning a redundant
    multi-second compile. `pin=True` (prewarm path) exempts the entry from
    LRU eviction so a warmed schema stays resident regardless of how many
    request-driven schemas churn through the bounded cache. `cached_only`
    never builds — it returns the hit or None, so latency-critical threads
    (the engine loop) cannot become the builder even if the entry was
    evicted between an `is_cached` check and this call.
    """
    key = (schema_key(schema), tokenizer_id, vocab_size)
    while True:
        with _LOCK:
            if key in _FAILED:
                return None
            hit = _CACHE.pop(key, None)
            if hit is not None:
                _CACHE[key] = hit  # LRU bump
                if pin:
                    _PINNED.add(key)
                return hit
            if cached_only:
                return None
            ev = _BUILDING.get(key)
            if ev is None:
                ev = threading.Event()
                _BUILDING[key] = ev
                break  # we build
        ev.wait()  # someone else is building this key; wait and re-check
    try:
        try:
            dfa = compile_schema_dfa(schema, max_states=max_states)
            tables = build_token_tables(dfa, tok_strs, eos_ids, vocab_size)
        except Exception as ex:  # noqa: BLE001 — any build failure (incl. a
            # compiler bug on one input) must record the key as failed, or
            # the engine respawns doomed background builds for it forever.
            if not isinstance(ex, DfaUnsupported):
                import logging

                logging.getLogger("localai_tpu.dfa").exception(
                    "grammar DFA build failed unexpectedly"
                )
            with _LOCK:
                _FAILED[key] = True
                while len(_FAILED) > _FAILED_MAX:
                    _FAILED.pop(next(iter(_FAILED)))
            return None
        with _LOCK:
            _CACHE[key] = tables
            if pin:
                _PINNED.add(key)
            # Request-driven (unpinned) entries stay bounded; pinned entries
            # are operator-controlled and never evicted.
            evictable = [k for k in _CACHE if k not in _PINNED]
            while len(evictable) > _CACHE_MAX:
                _CACHE.pop(evictable.pop(0))
        return tables
    finally:
        with _LOCK:
            _BUILDING.pop(key, None)
        ev.set()
