"""Raw GBNF grammar support: llama.cpp's grammar format as a first-class
constrained-decoding input.

Reference: the reference forwards an arbitrary `Grammar` string to llama.cpp
(backend.proto:139; grpc-server.cpp params_parse → llama_sampler_init_grammar)
and ships its own GBNF builders (pkg/functions/grammars/). The repo's JSON-
Schema path (functions/jsonschema.py) covers schema-driven constraints; this
module adds the externally-authored-grammar entry point.

Design (original, TPU-serving-shaped — not a port of llama.cpp's sampler):

  * parse GBNF → immutable rule table (groups/repetitions become synthesized
    rules, llama.cpp-style);
  * run it as a breadth-wise pushdown machine: the parse state is a
    frozenset of expanded stacks (tuples of elements), so cloning is free
    and states hash — which makes the machine BFS-compilable;
  * compile to the SAME device DFA/token-table path as JSON schemas
    (functions/dfa.py): character classes come from interval-splitting every
    range endpoint in the grammar, so the class alphabet is exact for any
    grammar (no ASCII-only approximation); grammars whose reachable config
    space exceeds the state budget host-walk instead, same as big schemas.

The host-walk constraint object (GbnfConstraint) speaks the exact interface
the engine already consumes (allowed/advance/complete/strictly_complete) and
carries `.schema = {"__gbnf__": text}` so the engine's untouched _dfa_for
path compiles and caches it like any schema.
"""

from __future__ import annotations

import bisect
from typing import Any, Optional

import numpy as np

# Elements: ("c", ranges, negated) matches one char (ranges: sorted tuple of
# inclusive (lo, hi) codepoint pairs); ("r", rule_id) invokes a rule.
MAX_STACKS = 512  # breadth cap: deterministic prune keeps serving bounded
MAX_STACK_DEPTH = 256


class GbnfParseError(ValueError):
    pass


# --------------------------------------------------------------------------- #
# Parser (GBNF: rules `name ::= alternates`, literals, char classes, groups,
# *, +, ?, {m}, {m,}, {m,n}, # comments)
# --------------------------------------------------------------------------- #


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.pos = 0
        self.rules: dict[str, list[list[tuple]]] = {}
        self.anon = 0

    # -- lexing helpers ---------------------------------------------------- #

    def _ws(self, newlines: bool) -> None:
        """Skip spaces/comments; newlines only when `newlines` (a newline not
        followed by indentation/continuation ends a rule)."""
        t = self.text
        while self.pos < len(t):
            ch = t[self.pos]
            if ch == "#":
                while self.pos < len(t) and t[self.pos] != "\n":
                    self.pos += 1
            elif ch in " \t":
                self.pos += 1
            elif ch in "\r\n":
                if not newlines:
                    return
                self.pos += 1
            else:
                return

    def _name(self) -> str:
        t, start = self.text, self.pos
        while self.pos < len(t) and (t[self.pos].isalnum() or t[self.pos] in "-_"):
            self.pos += 1
        if self.pos == start:
            raise GbnfParseError(f"expected rule name at offset {start}")
        return t[start: self.pos]

    def _expect(self, s: str) -> None:
        if not self.text.startswith(s, self.pos):
            raise GbnfParseError(
                f"expected {s!r} at offset {self.pos}: "
                f"{self.text[self.pos: self.pos + 20]!r}"
            )
        self.pos += len(s)

    def _char(self, in_class: bool) -> int:
        """One (possibly escaped) character → codepoint."""
        t = self.text
        if self.pos >= len(t):
            raise GbnfParseError("unexpected end of grammar in character")
        ch = t[self.pos]
        self.pos += 1
        if ch != "\\":
            return ord(ch)
        if self.pos >= len(t):
            raise GbnfParseError("dangling escape")
        e = t[self.pos]
        self.pos += 1
        simple = {"n": 10, "r": 13, "t": 9, "\\": 92, '"': 34, "[": 91,
                  "]": 93, "-": 45, "^": 94, "'": 39}
        if e in simple:
            return simple[e]
        if e in ("x", "u", "U"):
            n = {"x": 2, "u": 4, "U": 8}[e]
            hexs = t[self.pos: self.pos + n]
            if len(hexs) != n:
                raise GbnfParseError(f"bad \\{e} escape")
            self.pos += n
            cp = int(hexs, 16)
            if cp > 0x10FFFF:
                raise GbnfParseError(f"\\{e}{hexs} is beyond U+10FFFF")
            return cp
        raise GbnfParseError(f"unknown escape \\{e}")

    # -- grammar productions ----------------------------------------------- #

    def parse(self) -> dict[str, list[list[tuple]]]:
        self._ws(True)
        while self.pos < len(self.text):
            name = self._name()
            self._ws(False)
            self._expect("::=")
            # llama.cpp allows the rule body to start on the next line
            # (parse_space after "::=" has newline_ok=true).
            self._ws(True)
            alts = self._alternates(name)
            if name in self.rules:
                raise GbnfParseError(f"duplicate rule {name!r}")
            self.rules[name] = alts
            self._ws(True)
        if "root" not in self.rules:
            raise GbnfParseError("grammar has no 'root' rule")
        return self.rules

    def _alternates(self, rule_name: str) -> list[list[tuple]]:
        alts = [self._sequence(rule_name)]
        self._ws(False)
        while self.text.startswith("|", self.pos):
            self.pos += 1
            self._ws(False)
            # an alternate may continue on the next line after '|'
            self._ws(True)
            alts.append(self._sequence(rule_name))
            self._ws(False)
        return alts

    def _sequence(self, rule_name: str) -> list[tuple]:
        seq: list[tuple] = []
        while True:
            self._ws(False)
            if self.pos >= len(self.text):
                break
            ch = self.text[self.pos]
            if ch in "|)\r\n":
                break
            # `unit` is what a postfix operator repeats: the WHOLE quoted
            # literal, but a single char class / ref / group (llama.cpp's
            # last_sym_start semantics).
            if ch == '"':
                self.pos += 1
                lits = []
                while not self.text.startswith('"', self.pos):
                    if self.pos >= len(self.text):
                        raise GbnfParseError("unterminated string literal")
                    lits.append(self._char(False))
                self.pos += 1
                unit = [("c", ((cp, cp),), False) for cp in lits]
            elif ch == "[":
                self.pos += 1
                neg = False
                if self.text.startswith("^", self.pos):
                    neg = True
                    self.pos += 1
                ranges = []
                while not self.text.startswith("]", self.pos):
                    if self.pos >= len(self.text):
                        raise GbnfParseError("unterminated char class")
                    lo = self._char(True)
                    hi = lo
                    if (self.text.startswith("-", self.pos)
                            and not self.text.startswith("-]", self.pos)):
                        self.pos += 1
                        hi = self._char(True)
                    if hi < lo:
                        raise GbnfParseError(f"inverted range in char class")
                    ranges.append((lo, hi))
                self.pos += 1
                if not ranges and not neg:
                    raise GbnfParseError("empty char class")
                unit = [("c", tuple(sorted(ranges)), neg)]
            elif ch == "(":
                self.pos += 1
                self._ws(True)
                sub = self._alternates(rule_name)
                self._ws(True)
                self._expect(")")
                unit = [("r", self._anon_rule(rule_name, sub))]
            else:
                unit = [("r", self._name())]

            rep = self._repetition()
            if rep is not None:
                if len(unit) != 1:
                    # repeat a multi-char (or empty) literal as one group
                    unit = [("r", self._anon_rule(rule_name, [unit]))]
                unit = [self._repeat(rule_name, unit[0], *rep)]
            seq.extend(unit)
        return seq

    def _repetition(self) -> Optional[tuple]:
        t = self.text
        if self.pos >= len(t):
            return None
        ch = t[self.pos]
        if ch == "*":
            self.pos += 1
            return (0, None)
        if ch == "+":
            self.pos += 1
            return (1, None)
        if ch == "?":
            self.pos += 1
            return (0, 1)
        if ch == "{":
            self.pos += 1
            start = self.pos
            while self.pos < len(t) and t[self.pos] != "}":
                self.pos += 1
            if self.pos >= len(t):
                raise GbnfParseError("unterminated {m,n} repetition")
            body = t[start: self.pos]
            self.pos += 1
            try:
                if "," in body:
                    lo_s, hi_s = body.split(",", 1)
                    lo = int(lo_s) if lo_s.strip() else 0
                    hi = int(hi_s) if hi_s.strip() else None
                else:
                    lo = hi = int(body)
            except ValueError:
                raise GbnfParseError(f"bad repetition {{{body}}}") from None
            if hi is not None and hi < lo:
                raise GbnfParseError(f"bad repetition {{{body}}}")
            return (lo, hi)
        return None

    def _anon_rule(self, base: str, alts: list[list[tuple]]) -> str:
        self.anon += 1
        name = f"{base}@{self.anon}"
        self.rules[name] = alts
        return name

    def _repeat(self, base: str, elem: tuple, lo: int, hi: Optional[int]) -> tuple:
        """elem{lo,hi} → synthesized rules (llama.cpp rewrites the same way)."""
        if hi is None:
            # elem{lo,} = elem^lo  rest ;  rest ::= elem rest | ε
            rest = self._anon_rule(base, [[], []])
            self.rules[rest] = [[elem, ("r", rest)], []]
            return ("r", self._anon_rule(base, [[elem] * lo + [("r", rest)]]))
        # elem{lo,hi} = elem^lo (elem (elem (...)?)?)?  — nested optionals
        chain: list[tuple] = []
        for _ in range(hi - lo):
            if chain:
                chain = [elem, ("r", self._anon_rule(base, [chain, []]))]
            else:
                chain = [elem]
        tail = [("r", self._anon_rule(base, [chain, []]))] if chain else []
        return ("r", self._anon_rule(base, [[elem] * lo + tail]))


class CompiledGrammar:
    """Immutable rule table: rules[rid] = list of alternates, each a tuple of
    elements; refs hold integer rule ids."""

    def __init__(self, text: str):
        named = _Parser(text).parse()
        ids = {name: i for i, name in enumerate(named)}
        for alts in named.values():
            for alt in alts:
                for e in alt:
                    if e[0] == "r" and e[1] not in ids:
                        raise GbnfParseError(f"undefined rule {e[1]!r}")
        self.rules: list[list[tuple]] = [
            [tuple(("r", ids[e[1]]) if e[0] == "r" else e for e in alt)
             for alt in alts]
            for alts in named.values()
        ]
        self.root = ids["root"]
        self.text = text
        self._check_left_recursion()

    def _check_left_recursion(self) -> None:
        """Reject left-recursive grammars: stack expansion would not
        terminate (llama.cpp overflows on these; failing at parse is the
        honest version)."""
        # nullable rules (can derive ε) by fixpoint
        nullable = [False] * len(self.rules)
        changed = True
        while changed:
            changed = False
            for rid, alts in enumerate(self.rules):
                if nullable[rid]:
                    continue
                for alt in alts:
                    if all(e[0] == "r" and nullable[e[1]] for e in alt):
                        nullable[rid] = True
                        changed = True
                        break
        # left-ref graph: R → S when an alternate of R starts with refs of
        # nullable rules followed by a ref to S
        edges: list[set[int]] = [set() for _ in self.rules]
        for rid, alts in enumerate(self.rules):
            for alt in alts:
                for e in alt:
                    if e[0] != "r":
                        break
                    edges[rid].add(e[1])
                    if not nullable[e[1]]:
                        break
        # Iterative cycle check (user-supplied rule chains must not be able
        # to blow the Python stack): 0 unvisited, 1 in-stack, 2 done.
        state = [0] * len(self.rules)
        for r0 in range(len(self.rules)):
            if state[r0]:
                continue
            work: list[tuple[int, Any]] = [(r0, iter(edges[r0]))]
            state[r0] = 1
            while work:
                r, it = work[-1]
                nxt = next(it, None)
                if nxt is None:
                    state[r] = 2
                    work.pop()
                elif state[nxt] == 1:
                    raise GbnfParseError("left-recursive grammar is not supported")
                elif state[nxt] == 0:
                    state[nxt] = 1
                    work.append((nxt, iter(edges[nxt])))


# --------------------------------------------------------------------------- #
# Breadth-wise pushdown machine
# --------------------------------------------------------------------------- #


def _match(elem: tuple, cp: int) -> bool:
    _, ranges, neg = elem
    hit = any(lo <= cp <= hi for lo, hi in ranges)
    return hit != neg


def _expand(g: CompiledGrammar, stack: tuple, out: set, seen: set) -> None:
    """Resolve leading rule refs until the top element is a char matcher (or
    the stack is empty). Branches into one stack per viable alternate.
    Iterative: grammar depth must not be able to blow the Python stack."""
    work = [stack]
    while work:
        st = work.pop()
        if not st or st[0][0] == "c":
            if len(st) <= MAX_STACK_DEPTH:
                out.add(st)
            continue
        if st in seen:
            continue  # ε-cycle (e.g. r ::= s, s ::= r): already expanding
        seen.add(st)
        rest = st[1:]
        for alt in g.rules[st[0][1]]:
            work.append(alt + rest)


def initial_state(g: CompiledGrammar) -> frozenset:
    out: set = set()
    _expand(g, (("r", g.root),), out, set())
    return frozenset(out)


def step_state(g: CompiledGrammar, stacks: frozenset, ch: str) -> frozenset:
    """Advance every viable stack past `ch`; empty result = rejected."""
    cp = ord(ch)
    out: set = set()
    for st in stacks:
        if st and st[0][0] == "c" and _match(st[0], cp):
            _expand(g, st[1:], out, set())
    if len(out) > MAX_STACKS:
        # Deterministic prune: keep the shallowest stacks (most likely to
        # complete). Pathological ambiguity only — real grammars stay tiny.
        out = set(sorted(out, key=lambda s: (len(s), s))[:MAX_STACKS])
    return frozenset(out)


def state_complete(stacks: frozenset) -> bool:
    return () in stacks


def state_strict(stacks: frozenset) -> bool:
    return bool(stacks) and all(not s for s in stacks)


class GbnfConstraint:
    """Engine-facing constraint (same interface as GrammarConstraint:
    allowed/advance/complete/strictly_complete). State is an immutable
    frozenset, so candidate checks need no deepcopy — they just walk a
    local variable."""

    def __init__(self, grammar: CompiledGrammar | str):
        if isinstance(grammar, str):
            grammar = CompiledGrammar(grammar)
        self.grammar = grammar
        self.state = initial_state(grammar)
        # The engine's untouched DFA path keys and compiles on .schema;
        # the marker dict routes compile_schema_dfa to the GBNF compiler.
        self.schema = {"__gbnf__": grammar.text}

    def _walk(self, stacks: frozenset, text: str) -> Optional[frozenset]:
        for ch in text:
            stacks = step_state(self.grammar, stacks, ch)
            if not stacks:
                return None
        return stacks

    def allowed(self, token_text: str) -> bool:
        if not token_text:
            return False
        return self._walk(self.state, token_text) is not None

    def advance(self, token_text: str) -> bool:
        nxt = self._walk(self.state, token_text)
        if nxt is None:
            return False
        self.state = nxt
        return True

    def complete(self) -> bool:
        return state_complete(self.state)

    def strictly_complete(self) -> bool:
        return state_strict(self.state)


# --------------------------------------------------------------------------- #
# DFA compilation (plugs into functions/dfa.py's token-table path)
# --------------------------------------------------------------------------- #


class GbnfCharDFA:
    """CharDFA-shaped object whose char classes are the intervals induced by
    every range endpoint in the grammar — exact for any codepoint."""

    def __init__(self, trans: np.ndarray, accept: np.ndarray, bounds: list[int]):
        self.trans = trans  # [S, C] int32, -1 = reject
        self.accept = accept  # [S] bool
        self.bounds = bounds  # sorted interval starts; class i = [b[i], b[i+1])

    def class_of(self, ch: str) -> int:
        return bisect.bisect_right(self.bounds, ord(ch)) - 1


def _interval_bounds(g: CompiledGrammar) -> list[int]:
    """Split [0, 0x110000) at every range endpoint: inside one interval all
    codepoints are indistinguishable to every char element."""
    pts = {0}
    for alts in g.rules:
        for alt in alts:
            for e in alt:
                if e[0] == "c":
                    for lo, hi in e[1]:
                        pts.add(lo)
                        pts.add(hi + 1)
    pts.discard(0x110000)
    return sorted(pts)


def compile_gbnf_dfa(text: str, max_states: int = 3072) -> GbnfCharDFA:
    """BFS over reachable machine states → char-class DFA (the GBNF analogue
    of dfa.compile_schema_dfa; raises dfa.DfaUnsupported past the budget)."""
    from localai_tpu.functions.dfa import DfaUnsupported

    try:
        g = CompiledGrammar(text)
    except GbnfParseError as e:  # API validation already rejected bad text;
        raise DfaUnsupported(str(e)) from None  # belt-and-braces for the cache

    bounds = _interval_bounds(g)
    reps = [chr(b) for b in bounds]
    C = len(reps)

    start = initial_state(g)
    states: list[frozenset] = [start]
    keys = {start: 0}
    rows: list[np.ndarray] = []
    from collections import deque

    queue = deque([0])
    while queue:
        i = queue.popleft()
        while len(rows) <= i:
            rows.append(np.full((C,), -1, np.int32))
        st = states[i]
        row = rows[i]
        for cid, ch in enumerate(reps):
            nxt = step_state(g, st, ch)
            if not nxt:
                continue
            j = keys.get(nxt)
            if j is None:
                if len(states) >= max_states:
                    raise DfaUnsupported(f"grammar needs > {max_states} DFA states")
                j = len(states)
                keys[nxt] = j
                states.append(nxt)
                queue.append(j)
            row[cid] = j
    trans = np.stack(rows) if rows else np.full((1, C), -1, np.int32)
    accept = np.asarray([state_complete(s) for s in states], bool)
    return GbnfCharDFA(trans, accept, bounds)
