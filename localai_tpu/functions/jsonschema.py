"""Incremental JSON-Schema constraint machine for grammar-constrained decoding.

Reference: pkg/functions/grammars/json_schema.go converts JSON-Schema to GBNF
and llama.cpp enforces it inside the engine. TPU-native re-design (SURVEY.md
§7 item 6): the constraint runs host-side as a character-level pushdown
machine; the engine consults it to pick the best valid token from the model's
top-k candidates each step (a logit mask evaluated lazily on candidates
instead of a [V]-sized mask per step — no device round-trip for the mask).

Supported schema subset: object (properties / required / additionalProperties),
array (items / minItems / maxItems), string, number, integer, boolean, null,
enum (of scalars), const, anyOf-by-type via "type": [...], and {} (any JSON).
"""

from __future__ import annotations

import copy
import json
from typing import Any, Optional

# feed() results
CONSUMED = 0  # char accepted, frame continues
DONE = 1  # char accepted and frame finished — pop
END = 2  # char NOT accepted because frame already finished — pop, re-feed
REJECT = 3  # char invalid here
REPLACE = 4  # dispatch resolved to a concrete frame (in .replacement) — re-feed

_WS = " \t\n\r"


def _quote(s: str) -> str:
    return json.dumps(s)


class _Frame:
    replacement: Optional["_Frame"] = None

    def feed(self, ch: str) -> int:  # pragma: no cover - interface
        raise NotImplementedError

    def on_child_done(self) -> None:
        pass

    def in_string_body(self) -> bool:
        """True when chars are string content — exempt from the structural
        whitespace cap."""
        return False


class _Literal(_Frame):
    """Match exactly one of several literal strings (true/false/null, enum
    values, const — pre-rendered as JSON text)."""

    def __init__(self, options: list[str]):
        self.options = options
        self.pos = 0

    def feed(self, ch: str) -> int:
        viable = [o for o in self.options if self.pos < len(o) and o[self.pos] == ch]
        if not viable:
            # allow pop if some option is fully matched at pos
            if any(len(o) == self.pos for o in self.options):
                return END
            return REJECT
        self.options = viable
        self.pos += 1
        if len(self.options) == 1 and self.pos == len(self.options[0]):
            return DONE
        return CONSUMED


class _String(_Frame):
    """A JSON string: '"' chars* '"' with escapes."""

    def __init__(self):
        self.state = "open"  # open -> body -> (esc|hex*) -> closed

        self.hex_left = 0

    def feed(self, ch: str) -> int:
        s = self.state
        if s == "open":
            if ch == '"':
                self.state = "body"
                return CONSUMED
            return REJECT
        if s == "esc":
            if ch in '"\\/bfnrt':
                self.state = "body"
                return CONSUMED
            if ch == "u":
                self.state = "hex"
                self.hex_left = 4
                return CONSUMED
            return REJECT
        if s == "hex":
            if ch in "0123456789abcdefABCDEF":
                self.hex_left -= 1
                if self.hex_left == 0:
                    self.state = "body"
                return CONSUMED
            return REJECT
        # body
        if ch == '"':
            return DONE
        if ch == "\\":
            self.state = "esc"
            return CONSUMED
        if ch >= " ":
            return CONSUMED
        return REJECT

    def in_string_body(self) -> bool:
        return self.state != "open"


class _Number(_Frame):
    """-?(0|[1-9][0-9]*)(\\.[0-9]+)?([eE][+-]?[0-9]+)? ; integer forbids frac/exp.

    Numbers have no terminator: any non-number char pops with END once the
    DFA is in an accepting state.
    """

    def __init__(self, integer: bool):
        self.integer = integer
        self.state = "start"  # start sign int_zero int_digits frac_start frac exp_start exp_sign exp

    _ACCEPTING = {"int_zero", "int_digits", "frac", "exp"}

    def feed(self, ch: str) -> int:
        s = self.state
        if s == "start":
            if ch == "-":
                self.state = "sign"
                return CONSUMED
            if ch == "0":
                self.state = "int_zero"
                return CONSUMED
            if ch in "123456789":
                self.state = "int_digits"
                return CONSUMED
            return REJECT
        if s == "sign":
            if ch == "0":
                self.state = "int_zero"
                return CONSUMED
            if ch in "123456789":
                self.state = "int_digits"
                return CONSUMED
            return REJECT
        if s in ("int_zero", "int_digits"):
            if ch in "0123456789" and s == "int_digits":
                return CONSUMED
            if not self.integer:
                if ch == ".":
                    self.state = "frac_start"
                    return CONSUMED
                if ch in "eE":
                    self.state = "exp_start"
                    return CONSUMED
            return END
        if s == "frac_start":
            if ch in "0123456789":
                self.state = "frac"
                return CONSUMED
            return REJECT
        if s == "frac":
            if ch in "0123456789":
                return CONSUMED
            if ch in "eE":
                self.state = "exp_start"
                return CONSUMED
            return END
        if s == "exp_start":
            if ch in "+-":
                self.state = "exp_sign"
                return CONSUMED
            if ch in "0123456789":
                self.state = "exp"
                return CONSUMED
            return REJECT
        if s == "exp_sign":
            if ch in "0123456789":
                self.state = "exp"
                return CONSUMED
            return REJECT
        if s == "exp":
            if ch in "0123456789":
                return CONSUMED
            return END
        return REJECT


class _Object(_Frame):
    def __init__(self, schema: dict, machine: "JsonSchemaMachine"):
        self.machine = machine
        self.props: dict[str, Any] = schema.get("properties", {}) or {}
        self.required = set(schema.get("required", []) or [])
        ap = schema.get("additionalProperties")
        # Constrained mode default: closed objects when properties declared.
        self.additional = ap if ap is not None else (not self.props)
        self.seen: set[str] = set()
        self.state = "open"  # open key_or_close key colon value comma_or_close
        self.key_literal: Optional[_Literal] = None
        self.key_string: Optional[_String] = None
        self.current_key = ""
        self.n = 0

    def _key_options(self) -> list[str]:
        return [_quote(k) for k in self.props if k not in self.seen]

    def _close_ok(self) -> bool:
        return self.required <= self.seen

    def on_child_done(self) -> None:
        if self.state == "value":
            self.n += 1
            self.state = "comma_or_close"

    def feed(self, ch: str) -> int:
        s = self.state
        if s in ("open", "key_or_close", "colon", "comma_or_close") and ch in _WS:
            return CONSUMED
        if s == "open":
            if ch == "{":
                self.state = "key_or_close"
                return CONSUMED
            return REJECT
        if s == "key_or_close":
            if ch == "}" and self._close_ok() and self.n == 0:
                return DONE
            if ch == '"':
                opts = self._key_options()
                if opts:
                    self.key_literal = _Literal(opts)
                    self.key_literal.feed('"')
                    self.key_string = _String() if self.additional else None
                    if self.key_string:
                        self.key_string.feed('"')
                elif self.additional:
                    self.key_literal = None
                    self.key_string = _String()
                    self.key_string.feed('"')
                else:
                    return REJECT
                self.state = "key"
                self.key_chars = '"'
                return CONSUMED
            return REJECT
        if s == "key":
            lit_r = self.key_literal.feed(ch) if self.key_literal else REJECT
            str_r = self.key_string.feed(ch) if self.key_string else REJECT
            if lit_r in (CONSUMED, DONE):
                self.key_chars += ch
                if lit_r == DONE:
                    self.current_key = json.loads(self.key_chars)
                    self.key_literal = None
                    self.key_string = None
                    self.state = "colon"
                elif str_r not in (CONSUMED, DONE):
                    self.key_string = None
                return CONSUMED
            if str_r in (CONSUMED, DONE):
                self.key_chars += ch
                self.key_literal = None
                if str_r == DONE:
                    self.current_key = json.loads(self.key_chars)
                    self.key_string = None
                    self.state = "colon"
                return CONSUMED
            return REJECT
        if s == "colon":
            if ch == ":":
                if self.current_key in self.seen:
                    return REJECT  # duplicate key via the additionalProperties path
                self.seen.add(self.current_key)
                schema = self.props.get(self.current_key)
                if schema is None:
                    schema = self.additional if isinstance(self.additional, dict) else {}
                self.state = "value"
                self.machine.push(_Dispatch(schema, self.machine))
                return CONSUMED
            return REJECT
        if s == "value":
            # child frame handles chars; reaching here means child popped via
            # END and on_child_done already ran — retry in new state
            return REJECT
        if s == "comma_or_close":
            if ch in _WS:
                return CONSUMED
            if ch == ",":
                if self._key_options() or self.additional:
                    self.state = "key_or_close_after_comma"
                    return CONSUMED
                return REJECT
            if ch == "}" and self._close_ok():
                return DONE
            return REJECT
        if s == "key_or_close_after_comma":
            if ch in _WS:
                return CONSUMED
            if ch == '"':
                self.state = "key_or_close"
                return self.feed(ch)
            return REJECT
        return REJECT

    def in_string_body(self) -> bool:
        return self.state == "key"


class _Array(_Frame):
    def __init__(self, schema: dict, machine: "JsonSchemaMachine"):
        self.machine = machine
        self.items = schema.get("items", {}) or {}
        self.min_items = int(schema.get("minItems", 0) or 0)
        self.max_items = schema.get("maxItems")
        self.n = 0
        self.state = "open"  # open value_or_close value comma_or_close

    def on_child_done(self) -> None:
        if self.state == "value":
            self.n += 1
            self.state = "comma_or_close"

    def feed(self, ch: str) -> int:
        if ch in _WS and self.state != "value":
            return CONSUMED
        s = self.state
        if s == "open":
            if ch == "[":
                self.state = "value_or_close"
                return CONSUMED
            return REJECT
        if s == "value_or_close":
            if ch == "]" and self.n >= self.min_items:
                return DONE
            if self.max_items is not None and self.n >= int(self.max_items):
                return REJECT
            self.state = "value"
            self.machine.push(_Dispatch(self.items, self.machine))
            return self._refeed(ch)
        if s == "value":
            return REJECT
        if s == "comma_or_close":
            if ch == ",":
                if self.max_items is not None and self.n >= int(self.max_items):
                    return REJECT
                self.state = "value_or_close_no_close"
                return CONSUMED
            if ch == "]" and self.n >= self.min_items:
                return DONE
            return REJECT
        if s == "value_or_close_no_close":
            if ch in _WS:
                return CONSUMED
            self.state = "value"
            self.machine.push(_Dispatch(self.items, self.machine))
            return self._refeed(ch)
        return REJECT

    def _refeed(self, ch: str) -> int:
        # The char belongs to the just-pushed child: signal the machine to
        # re-dispatch without consuming.
        return REPLACE  # machine re-feeds ch to the new top frame


class _Dispatch(_Frame):
    """Resolves a schema to a concrete frame on the first non-ws char."""

    def __init__(self, schema: Any, machine: "JsonSchemaMachine"):
        self.schema = schema if isinstance(schema, dict) else {}
        self.machine = machine

    def feed(self, ch: str) -> int:
        if ch in _WS:
            return CONSUMED
        sch = self.schema
        if "const" in sch:
            self.replacement = _Literal([json.dumps(sch["const"])])
            return REPLACE
        if "enum" in sch:
            self.replacement = _Literal([json.dumps(v) for v in sch["enum"]])
            return REPLACE
        types = sch.get("type")
        if isinstance(types, str):
            types = [types]
        if not types:
            # any JSON value — infer from char
            if ch == "{":
                types = ["object"]
            elif ch == "[":
                types = ["array"]
            elif ch == '"':
                types = ["string"]
            elif ch in "-0123456789":
                types = ["number"]
            elif ch == "t" or ch == "f":
                types = ["boolean"]
            elif ch == "n":
                types = ["null"]
            else:
                return REJECT
        # choose the branch whose first char matches (cheap static probe —
        # no deepcopy; every frame type has a distinct start set)
        first_ok = {
            "object": ch == "{",
            "array": ch == "[",
            "string": ch == '"',
            "number": ch in "-0123456789",
            "integer": ch in "-0123456789",
            "boolean": ch in "tf",
            "null": ch == "n",
        }
        for t in types:
            if not first_ok.get(t, False):
                continue
            frame = self._frame_for(t)
            if frame is not None:
                self.replacement = frame
                return REPLACE
        return REJECT

    def _frame_for(self, t: str) -> Optional[_Frame]:
        sch = self.schema
        if t == "object":
            return _Object(sch, self.machine)
        if t == "array":
            return _Array(sch, self.machine)
        if t == "string":
            return _String()
        if t == "number":
            return _Number(integer=False)
        if t == "integer":
            return _Number(integer=True)
        if t == "boolean":
            return _Literal(["true", "false"])
        if t == "null":
            return _Literal(["null"])
        return None


class JsonSchemaMachine:
    """Feed characters; tells you whether a prefix stays schema-valid.

    Structural whitespace is capped at `max_ws_run` consecutive chars (and
    none before the first token): without the cap a constrained model can
    satisfy the grammar forever with whitespace and never emit content.
    """

    def __init__(self, schema: Any = None, max_ws_run: int = 1):
        self.stack: list[_Frame] = []
        self.push(_Dispatch(schema or {}, self))
        self.max_ws_run = max_ws_run
        self.ws_run = max_ws_run  # blocks leading whitespace

    def push(self, frame: _Frame) -> None:
        self.stack.append(frame)

    def feed(self, ch: str) -> bool:
        structural = not (self.stack and self.stack[-1].in_string_body())
        if ch in _WS and structural:
            if self.ws_run >= self.max_ws_run:
                return False
        guard = 0
        while True:
            guard += 1
            if guard > 200:
                return False
            if not self.stack:
                if ch in _WS and self.ws_run < self.max_ws_run:
                    self.ws_run += 1
                    return True
                return False
            top = self.stack[-1]
            r = top.feed(ch)
            if r == CONSUMED:
                self.ws_run = (self.ws_run + 1) if (ch in _WS and structural) else 0
                return True
            if r == DONE:
                self.stack.pop()
                if self.stack:
                    self.stack[-1].on_child_done()
                self.ws_run = 0
                return True
            if r == END:
                self.stack.pop()
                if self.stack:
                    self.stack[-1].on_child_done()
                continue
            if r == REPLACE:
                if top.replacement is not None:
                    self.stack[-1] = top.replacement
                # else: a child was pushed (array refeed path)
                continue
            return False

    def feed_text(self, text: str) -> bool:
        return all(self.feed(c) for c in text)

    def is_complete(self) -> bool:
        st = self.stack
        if not st:
            return True
        # A trailing _Number in accepting state (nothing else on the stack)
        # also counts as complete.
        if len(st) == 1 and isinstance(st[0], _Number):
            return st[0].state in _Number._ACCEPTING
        return False


class GrammarConstraint:
    """Per-request constrained-decoding state used by the engine.

    The engine asks `allowed(text)` for candidate token strings, commits with
    `advance(text)`, and may emit EOS only when `complete()`.
    """

    def __init__(self, schema: Any = None):
        self.schema = schema  # retained for DFA compilation (functions/dfa.py)
        self.machine = JsonSchemaMachine(schema)

    def allowed(self, token_text: str) -> bool:
        if not token_text:
            return False
        clone = copy.deepcopy(self.machine)
        return clone.feed_text(token_text)

    def advance(self, token_text: str) -> bool:
        return self.machine.feed_text(token_text)

    def complete(self) -> bool:
        """Output is a full value (EOS becomes legal)."""
        return self.machine.is_complete()

    def strictly_complete(self) -> bool:
        """Output cannot be extended — the engine may finish the request.

        Differs from complete() for trailing numbers: "12" is a complete
        integer but "123" remains valid, so generation must not be cut there
        (the model ends it with EOS instead)."""
        return not self.machine.stack


def tool_call_schema(tools: list[dict[str, Any]]) -> dict[str, Any]:
    """Schema for one tool call: {"name": <enum>, "arguments": <params>}.

    Reference: json_schema.go builds a GBNF alternation over functions; here
    the name enum and per-tool argument schemas combine into one object schema
    whose `arguments` accepts any declared tool's parameters. (Exact
    name→arguments coupling needs oneOf; the engine still validates the parse
    on the way out, matching the reference's parse step.)
    """
    names = []
    for t in tools:
        fn = t.get("function", t)
        if fn.get("name"):
            names.append(fn["name"])
    if len(names) == 1:
        fn = tools[0].get("function", tools[0])
        params = fn.get("parameters") or {}
        return {
            "type": "object",
            "properties": {"name": {"const": names[0]}, "arguments": params},
            "required": ["name", "arguments"],
        }
    return {
        "type": "object",
        "properties": {"name": {"enum": names}, "arguments": {}},
        "required": ["name", "arguments"],
    }
