"""Tool definitions → prompt text; model output → OpenAI tool_calls.

Reference behaviors reproduced (pkg/functions/parse.go):
- JSON mode: the model emits one or more JSON objects with name+arguments
  (parse.go ParseFunctionCall JSON branch); we scan balanced JSON objects so
  surrounding prose or multiple calls are tolerated.
- llama3.1-style `<function=name>{...}</function>` tags
  (grammars/llama31_schema.go).
- Regex mode via config `options.function_response_regex` with named groups
  (parse.go ResponseRegex).
"""

from __future__ import annotations

import json
import re
import uuid
from typing import Any, Optional

from localai_tpu.config.model_config import ModelConfig

_LLAMA31_RE = re.compile(r"<function=(\w+)>(.*?)</function>", re.DOTALL)


def tools_prompt_for(tools: list[dict[str, Any]]) -> str:
    """System-prompt suffix describing available tools and the call format.

    The reference injects grammar + a Functions template
    (evaluator.go:96-230); the prompt contract here matches what
    parse_function_calls accepts.
    """
    defs = []
    for t in tools:
        fn = t.get("function", t)
        defs.append(
            {
                "name": fn.get("name", ""),
                "description": fn.get("description", ""),
                "parameters": fn.get("parameters", {}),
            }
        )
    return (
        "You have access to the following tools:\n"
        + json.dumps(defs, indent=2)
        + "\n\nTo call a tool, respond ONLY with a JSON object of the form "
        '{"name": "<tool name>", "arguments": {...}} — one JSON object per call, '
        "no other text. If no tool is needed, answer normally."
    )


def _balanced_json_objects(text: str) -> list[dict[str, Any]]:
    """Extract every balanced top-level JSON object from free-form text."""
    out = []
    depth = 0
    start: Optional[int] = None
    in_str = False
    esc = False
    for i, ch in enumerate(text):
        if in_str:
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch == "{":
            if depth == 0:
                start = i
            depth += 1
        elif ch == "}":
            if depth > 0:
                depth -= 1
                if depth == 0 and start is not None:
                    try:
                        out.append(json.loads(text[start : i + 1]))
                    except json.JSONDecodeError:
                        pass
                    start = None
    return out


def _to_tool_call(name: str, arguments: Any) -> dict[str, Any]:
    if not isinstance(arguments, str):
        arguments = json.dumps(arguments or {})
    return {
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


def parse_function_calls(text: str, cfg: Optional[ModelConfig] = None) -> list[dict[str, Any]]:
    """Parse model output into OpenAI tool_calls; [] when no call is found."""
    calls: list[dict[str, Any]] = []

    # Regex mode from model config (parse.go ResponseRegex named groups).
    pattern = (cfg.options.get("function_response_regex") if cfg else None)
    if pattern:
        for m in re.finditer(pattern, text, re.DOTALL):
            groups = m.groupdict()
            if "name" in groups:
                calls.append(_to_tool_call(groups["name"], groups.get("arguments", "{}")))
        if calls:
            return calls

    # llama3.1 <function=...> tags.
    for m in _LLAMA31_RE.finditer(text):
        args = m.group(2).strip()
        try:
            parsed = json.loads(args) if args else {}
        except json.JSONDecodeError:
            parsed = {"raw": args}
        calls.append(_to_tool_call(m.group(1), parsed))
    if calls:
        return calls

    # JSON objects with name/function + arguments.
    for obj in _balanced_json_objects(text):
        name = obj.get("name") or obj.get("function")
        if not isinstance(name, str) or not name:
            continue
        args = obj.get("arguments", obj.get("parameters", {}))
        calls.append(_to_tool_call(name, args))
    return calls
