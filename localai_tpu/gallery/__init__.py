"""Model gallery: downloadable model artifacts + async install jobs.

Reference: core/gallery (models.go:75 InstallModelFromGallery, :159
InstallModel, :363 DeleteModelFromSystem; gallery.go:22-80 YAML-over-URI
index fetch) driven through core/services/gallery.go's job queue with
progress polling. Backend-bundle galleries (OCI images keyed on GPU
capability, backends.go:73) have no TPU analogue — there is one resident
engine, not per-model binaries — so only the *model* gallery is ported.
"""

from localai_tpu.gallery.gallery import Gallery, GalleryEntry, load_index  # noqa: F401
from localai_tpu.gallery.service import GalleryService, InstallJob  # noqa: F401


def builtin_gallery_url() -> str:
    """file:// URL of the packaged starter index (localai_tpu/gallery/index.yaml)."""
    import os

    return "file://" + os.path.join(os.path.dirname(os.path.abspath(__file__)), "index.yaml")
