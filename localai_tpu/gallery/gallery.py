"""Gallery index: YAML-over-URI model listings.

Reference format (gallery/index.yaml + core/gallery/gallery.go:22-80): a YAML
list of entries with `name`, `description`, `license`, `tags`, `files`
(filename/uri/sha256) and config `overrides`. This loader accepts the same
shape; `config` / `overrides` become localai_tpu ModelConfig fields.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Any, Optional

import yaml

from localai_tpu.downloader import download


@dataclasses.dataclass
class GalleryEntry:
    name: str
    description: str = ""
    license: str = ""
    tags: list[str] = dataclasses.field(default_factory=list)
    # Artifact files: [{"filename": ..., "uri": ..., "sha256": ...}]
    files: list[dict[str, str]] = dataclasses.field(default_factory=list)
    # ModelConfig overrides written into the installed YAML.
    overrides: dict[str, Any] = dataclasses.field(default_factory=dict)
    gallery: str = ""  # owning gallery name

    @property
    def id(self) -> str:
        return f"{self.gallery}@{self.name}" if self.gallery else self.name

    @classmethod
    def from_dict(cls, d: dict[str, Any], gallery: str = "") -> "GalleryEntry":
        return cls(
            name=str(d.get("name", "")),
            description=str(d.get("description", "")),
            license=str(d.get("license", "")),
            tags=list(d.get("tags") or []),
            files=[dict(f) for f in (d.get("files") or [])],
            overrides=dict(d.get("overrides") or d.get("config") or {}),
            gallery=gallery,
        )


@dataclasses.dataclass
class Gallery:
    name: str
    url: str


_INDEX_TTL_S = 30.0
_index_cache: dict[str, tuple[float, list[GalleryEntry]]] = {}


def load_index(gallery: Gallery, ttl: float = _INDEX_TTL_S) -> list[GalleryEntry]:
    """Fetch and parse a gallery's index.yaml (file:// or http(s)).

    A short-TTL in-memory cache keeps UI polling of /models/available from
    re-fetching every index on every request."""
    import time

    cached = _index_cache.get(gallery.url)
    if cached is not None and time.monotonic() - cached[0] < ttl:
        return cached[1]
    with tempfile.TemporaryDirectory() as td:
        path = download(gallery.url, os.path.join(td, "index.yaml"))
        with open(path) as f:
            docs = yaml.safe_load(f)
    if docs is None:
        return []
    if not isinstance(docs, list):
        raise ValueError(f"gallery {gallery.name}: index must be a YAML list")
    out = []
    for d in docs:
        if isinstance(d, dict) and d.get("name"):
            out.append(GalleryEntry.from_dict(d, gallery=gallery.name))
    _index_cache[gallery.url] = (time.monotonic(), out)
    return out


def find_entry(
    galleries: list[Gallery], entry_id: str
) -> Optional[GalleryEntry]:
    """Resolve "gallery@name" or bare "name" across configured galleries.

    Per-gallery fetch failures are isolated (like list_available) so one
    broken gallery cannot mask an entry present in a healthy one."""
    import logging

    want_gallery, _, want_name = entry_id.rpartition("@")
    for g in galleries:
        if want_gallery and g.name != want_gallery:
            continue
        try:
            entries = load_index(g)
        except Exception as e:  # noqa: BLE001 — skip broken galleries
            logging.getLogger("localai_tpu.gallery").warning("gallery %s: %s", g.name, e)
            continue
        for e in entries:
            if e.name == want_name:
                return e
    return None
