"""Async model-install jobs with progress polling.

Reference: core/services/gallery.go (job queue consumed by a worker
goroutine; per-op status in an OpCache polled at /models/jobs/:uuid) +
core/gallery/models.go:75-159 (resolve entry → download files with
resume+SHA → write per-model YAML) and :363 DeleteModelFromSystem.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import queue
import re
import shutil
import threading
import time
import uuid as uuidlib
from typing import Any, Optional

import yaml

from localai_tpu.downloader import download
from localai_tpu.gallery.gallery import Gallery, GalleryEntry, find_entry, load_index

log = logging.getLogger("localai_tpu.gallery")

_NAME_RE = re.compile(r"^[a-zA-Z0-9_][a-zA-Z0-9_\-.]*$")


def _safe_name(name: str) -> str:
    """Reject path separators / traversal in model names — these become
    filesystem paths under models_dir (reference: model_config.go:480-508)."""
    if not name or not _NAME_RE.match(name) or ".." in name:
        raise ValueError(f"invalid model name {name!r}")
    return name


def _safe_artifact_path(target_dir: str, filename: str) -> str:
    """Join an index-provided filename under target_dir, refusing escapes —
    a malicious gallery index must not be able to write outside its dir."""
    dest = os.path.realpath(os.path.join(target_dir, filename))
    root = os.path.realpath(target_dir)
    if not (dest == root or dest.startswith(root + os.sep)):
        raise ValueError(f"artifact filename escapes install dir: {filename!r}")
    return dest


@dataclasses.dataclass
class InstallJob:
    uuid: str
    name: str
    status: str = "pending"  # pending | downloading | done | error
    progress: float = 0.0  # 0..100
    message: str = ""
    error: Optional[str] = None
    downloaded_files: list[str] = dataclasses.field(default_factory=list)
    created_at: float = dataclasses.field(default_factory=time.time)

    def to_dict(self) -> dict[str, Any]:
        return {
            "uuid": self.uuid,
            "name": self.name,
            "processed": self.status in ("done", "error"),
            "status": self.status,
            "progress": round(self.progress, 1),
            "message": self.message,
            "error": self.error,
            "downloaded_files": self.downloaded_files,
        }


class GalleryService:
    """Owns the configured galleries and the install worker."""

    def __init__(self, models_dir: str, config_loader=None, galleries: Optional[list[Gallery]] = None):
        self.models_dir = models_dir
        self.config_loader = config_loader  # ModelConfigLoader to refresh after installs
        self.galleries: list[Gallery] = list(galleries or [])
        self.jobs: dict[str, InstallJob] = {}
        self._lock = threading.Lock()
        self._q: "queue.Queue[tuple[InstallJob, GalleryEntry, dict]]" = queue.Queue()
        self._worker: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #
    # Galleries
    # ------------------------------------------------------------------ #

    def add_gallery(self, name: str, url: str) -> None:
        with self._lock:
            if any(g.name == name for g in self.galleries):
                raise ValueError(f"gallery {name!r} already configured")
            self.galleries.append(Gallery(name=name, url=url))

    def remove_gallery(self, name: str) -> bool:
        with self._lock:
            before = len(self.galleries)
            self.galleries = [g for g in self.galleries if g.name != name]
            return len(self.galleries) < before

    def list_available(self) -> list[dict[str, Any]]:
        out = []
        for g in list(self.galleries):
            try:
                for e in load_index(g):
                    out.append({
                        "id": e.id, "name": e.name, "description": e.description,
                        "license": e.license, "tags": e.tags, "gallery": g.name,
                        "installed": self.installed(e.name),
                    })
            except Exception as err:  # noqa: BLE001 — one bad gallery must not hide others
                log.warning("gallery %s: %s", g.name, err)
        return out

    def _installed(self, name: str) -> bool:
        if not _NAME_RE.match(name or ""):
            return False  # never turn an index-supplied name into a path
        return os.path.exists(os.path.join(self.models_dir, f"{name}.yaml"))

    # ------------------------------------------------------------------ #
    # Install jobs
    # ------------------------------------------------------------------ #

    def apply(
        self,
        entry_id: Optional[str] = None,
        name: Optional[str] = None,
        overrides: Optional[dict[str, Any]] = None,
        files: Optional[list[dict[str, str]]] = None,
    ) -> str:
        """Queue an install; returns the job uuid (poll via `job()`).

        Either `entry_id` resolves against the configured galleries, or an
        inline entry is given via `files` (+ overrides) — mirroring the
        reference's /models/apply accepting both gallery ids and raw URLs.
        """
        if entry_id:
            entry = find_entry(self.galleries, entry_id)
            if entry is None:
                raise KeyError(f"gallery entry {entry_id!r} not found")
        elif files or overrides:
            if not name:
                raise ValueError("name is required for inline installs")
            entry = GalleryEntry(name=name, files=list(files or []), overrides=dict(overrides or {}))
        else:
            raise ValueError("either id or files/overrides is required")

        job = InstallJob(uuid=str(uuidlib.uuid4()), name=_safe_name(name or entry.name))
        with self._lock:
            self.jobs[job.uuid] = job
        self._q.put((job, entry, dict(overrides or {})))
        self._ensure_worker()
        return job.uuid

    def job(self, job_uuid: str) -> Optional[dict[str, Any]]:
        with self._lock:
            j = self.jobs.get(job_uuid)
            return j.to_dict() if j else None

    def installed(self, name: str) -> bool:
        """Is this model present — via gallery install or any loaded config
        (covers .yml files and multi-doc models.yaml too)?"""
        if not _NAME_RE.match(name or ""):
            return False
        if self.config_loader is not None and getattr(self.config_loader, "get", None):
            if self.config_loader.get(name) is not None:
                return True
        return self._installed(name)

    def delete_model(self, name: str) -> bool:
        """Remove an installed model's YAML + artifact dir (models.go:363)."""
        _safe_name(name)
        removed = False
        ypath = os.path.join(self.models_dir, f"{name}.yaml")
        if os.path.exists(ypath):
            os.remove(ypath)
            removed = True
        adir = os.path.join(self.models_dir, name)
        if os.path.isdir(adir):
            shutil.rmtree(adir)
            removed = True
        if removed and self.config_loader is not None:
            self.config_loader.load_all()
        return removed

    # ------------------------------------------------------------------ #
    # Worker
    # ------------------------------------------------------------------ #

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, daemon=True, name="gallery-install"
            )
            self._worker.start()

    def _run(self) -> None:
        while True:
            job, entry, overrides = self._q.get()
            try:
                self._install(job, entry, overrides)
                job.status = "done"
                job.progress = 100.0
                job.message = f"installed {job.name}"
            except Exception as e:  # noqa: BLE001 — job must record its failure
                log.exception("install %s failed", job.name)
                job.status = "error"
                job.error = f"{type(e).__name__}: {e}"

    def _install(self, job: InstallJob, entry: GalleryEntry, overrides: dict[str, Any]) -> None:
        job.status = "downloading"
        name = job.name
        target_dir = os.path.join(self.models_dir, name)
        nfiles = max(1, len(entry.files))
        for i, f in enumerate(entry.files):
            fname = f.get("filename") or os.path.basename(f["uri"])
            job.message = f"downloading {fname}"

            def progress(done: int, total: int, _i=i) -> None:
                frac = (done / total) if total > 0 else 0.0
                job.progress = 95.0 * (_i + min(1.0, frac)) / nfiles

            dest = download(
                f["uri"], _safe_artifact_path(target_dir, fname),
                sha256=f.get("sha256"), progress=progress,
            )
            job.downloaded_files.append(dest)
            job.progress = 95.0 * (i + 1) / nfiles

        cfg: dict[str, Any] = {"name": name}
        if entry.files:
            cfg["model"] = target_dir
        cfg.update(entry.overrides)
        cfg.update(overrides)
        cfg["name"] = name  # overrides must not detach the config from the job
        for field in ("model", "tokenizer", "draft_model"):
            val = cfg.get(field)
            if isinstance(val, str) and val.startswith("hf://"):
                # Whole-repo HF checkpoint: fetch config + safetensors +
                # tokenizer with resume (downloader/hf_api.py) instead of
                # enumerating shard filenames in the index.
                from localai_tpu.downloader.hf_api import fetch_hf_model

                repo = val[len("hf://"):]
                job.message = f"fetching {repo}"

                def progress(path, done, total):
                    job.message = f"downloading {os.path.basename(path)}"

                sub = target_dir if field == "model" else os.path.join(
                    target_dir, field
                )
                fetch_hf_model(repo, sub, progress=progress)
                job.downloaded_files.append(sub)
                cfg[field] = sub
        with open(os.path.join(self.models_dir, f"{name}.yaml"), "w") as f:
            yaml.safe_dump(cfg, f)
        if self.config_loader is not None:
            self.config_loader.load_all()
