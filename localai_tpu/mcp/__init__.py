"""MCP (Model Context Protocol) client + agent loop."""

from localai_tpu.mcp.client import MCPClient, MCPError, StdioMCPClient  # noqa: F401
from localai_tpu.mcp.agent import agent_loop, collect_tools  # noqa: F401
