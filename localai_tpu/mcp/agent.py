"""Agent loop: chat completions that can call MCP tools until done.

Reference: endpoints/localai/mcp.go:326 (POST /mcp/v1/chat/completions runs
the model in a loop, executing MCP tool calls and feeding results back as
tool messages, bounded by max iterations).

The loop is decoupled from the serving stack through a `chat_fn` callable
(messages, tools) → assistant message dict, so it drives either a loaded
engine (server path) or any scripted function (tests, cron jobs).
"""

from __future__ import annotations

import json
import logging
from typing import Any, Callable, Optional

log = logging.getLogger("localai_tpu.mcp")

ChatFn = Callable[[list[dict], list[dict]], dict]


def collect_tools(clients: list) -> tuple[list[dict], dict[str, Any]]:
    """Gather tools from every MCP server → (OpenAI tool specs, name→client)."""
    specs: list[dict] = []
    owners: dict[str, Any] = {}
    for c in clients:
        try:
            for t in c.list_tools():
                name = t.get("name")
                if not name or name in owners:
                    continue
                owners[name] = c
                specs.append({
                    "type": "function",
                    "function": {
                        "name": name,
                        "description": t.get("description", ""),
                        "parameters": t.get("inputSchema") or {"type": "object"},
                    },
                })
        except Exception as e:  # noqa: BLE001 — a dead server loses its tools only
            log.warning("MCP server %s unavailable: %s", getattr(c, "name", c), e)
    return specs, owners


def agent_loop(
    chat_fn: ChatFn,
    messages: list[dict],
    clients: list,
    max_iterations: int = 10,
) -> dict:
    """Run the agent until a plain answer. Returns
    {message, iterations, tool_calls: [{name, arguments, result|error}]}."""
    specs, owners = collect_tools(clients)
    history = list(messages)
    executed: list[dict] = []
    for it in range(max_iterations):
        msg = chat_fn(history, specs)
        calls = msg.get("tool_calls") or []
        if not calls or not specs:
            return {"message": msg, "iterations": it + 1, "tool_calls": executed}
        history.append(msg)
        for call in calls:
            fn = (call.get("function") or {})
            name = fn.get("name", "")
            try:
                args = json.loads(fn.get("arguments") or "{}")
            except json.JSONDecodeError:
                args = {}
            record: dict[str, Any] = {"name": name, "arguments": args}
            client = owners.get(name)
            if client is None:
                record["error"] = f"unknown tool {name!r}"
                content = record["error"]
            else:
                try:
                    content = client.call_tool(name, args)
                    record["result"] = content
                except Exception as e:  # noqa: BLE001 — feed the error back
                    record["error"] = str(e)
                    content = f"tool error: {e}"
            executed.append(record)
            history.append({
                "role": "tool",
                "tool_call_id": call.get("id", name),
                "content": content,
            })
    return {
        "message": {"role": "assistant",
                    "content": "agent reached max iterations without a final answer"},
        "iterations": max_iterations,
        "tool_calls": executed,
    }


def make_engine_chat_fn(lm, max_tokens: int = 512,
                        temperature: Optional[float] = None) -> ChatFn:
    """chat_fn over a loaded text model (same path as /v1/chat/completions)."""
    from localai_tpu.engine import GenRequest
    from localai_tpu.functions import parse_function_calls, tools_prompt_for

    def chat(messages: list[dict], tools: list[dict]) -> dict:
        tprompt = tools_prompt_for(tools) if tools else ""
        prompt = lm.evaluator.template_messages(messages, tools_prompt=tprompt)
        ids = lm.engine.tokenizer.encode(
            prompt, add_bos=not lm.cfg.template.use_tokenizer_template
        )
        text, _final = lm.engine.submit(GenRequest(
            prompt_ids=ids,
            max_new_tokens=max_tokens,
            temperature=lm.cfg.temperature if temperature is None else temperature,
            stop=lm.evaluator.stop_sequences(),
        )).result()
        if tools:
            calls = parse_function_calls(text, lm.cfg)
            if calls:
                return {"role": "assistant", "content": None, "tool_calls": calls}
        return {"role": "assistant", "content": text}

    return chat
