"""MCP clients: JSON-RPC 2.0 over streamable HTTP and over stdio.

Reference: endpoints/localai/mcp.go wires remote/stdio MCP servers from the
model config's `mcp:` block and exposes their tools to an agent loop. The
protocol subset here is what tool use needs: initialize, tools/list,
tools/call.
"""

from __future__ import annotations

import json
import logging
import subprocess
import threading
import urllib.request
from typing import Any, Optional

log = logging.getLogger("localai_tpu.mcp")

PROTOCOL_VERSION = "2024-11-05"


class MCPError(Exception):
    pass


class MCPClient:
    """Remote MCP server over streamable HTTP (JSON-RPC request/response)."""

    def __init__(self, url: str, token: str = "", name: str = ""):
        self.url = url
        self.token = token
        self.name = name or url
        self._id = 0
        self._lock = threading.Lock()
        self._initialized = False

    def _rpc(self, method: str, params: Optional[dict] = None) -> Any:
        with self._lock:
            self._id += 1
            rid = self._id
        payload = {"jsonrpc": "2.0", "id": rid, "method": method}
        if params is not None:
            payload["params"] = params
        headers = {
            "Content-Type": "application/json",
            "Accept": "application/json, text/event-stream",
        }
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(
            self.url, data=json.dumps(payload).encode(), headers=headers
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            raw = r.read().decode()
            ctype = r.headers.get("Content-Type", "")
        if "text/event-stream" in ctype:  # single-response SSE framing
            for line in raw.splitlines():
                if line.startswith("data:"):
                    raw = line[5:].strip()
                    break
        out = json.loads(raw)
        if "error" in out:
            raise MCPError(f"{self.name}: {out['error'].get('message')}")
        return out.get("result")

    def initialize(self) -> dict:
        result = self._rpc("initialize", {
            "protocolVersion": PROTOCOL_VERSION,
            "capabilities": {},
            "clientInfo": {"name": "localai-tpu", "version": "1"},
        })
        try:
            self._rpc("notifications/initialized")
        except Exception:  # noqa: BLE001 — some servers reject notification POSTs
            pass
        self._initialized = True
        return result or {}

    def list_tools(self) -> list[dict]:
        if not self._initialized:
            self.initialize()
        result = self._rpc("tools/list") or {}
        return result.get("tools", [])

    def call_tool(self, name: str, arguments: dict) -> str:
        if not self._initialized:
            self.initialize()
        result = self._rpc("tools/call", {"name": name, "arguments": arguments}) or {}
        parts = []
        for c in result.get("content", []):
            if c.get("type") == "text":
                parts.append(c.get("text", ""))
            else:
                parts.append(json.dumps(c))
        if result.get("isError"):
            raise MCPError(f"{self.name}.{name}: {' '.join(parts)}")
        return "\n".join(parts)


class StdioMCPClient:
    """MCP server launched as a subprocess, JSON-RPC over stdin/stdout
    (reference: mcp.go stdio transport for local tool servers)."""

    def __init__(self, command: list[str], env: Optional[dict] = None, name: str = ""):
        self.name = name or command[0]
        self._proc = subprocess.Popen(
            command, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, env=env, text=True, bufsize=1,
        )
        self._id = 0
        self._lock = threading.Lock()
        self._initialized = False

    def _rpc(self, method: str, params: Optional[dict] = None) -> Any:
        with self._lock:
            self._id += 1
            payload = {"jsonrpc": "2.0", "id": self._id, "method": method}
            if params is not None:
                payload["params"] = params
            assert self._proc.stdin and self._proc.stdout
            self._proc.stdin.write(json.dumps(payload) + "\n")
            self._proc.stdin.flush()
            while True:
                line = self._proc.stdout.readline()
                if not line:
                    raise MCPError(f"{self.name}: server exited")
                try:
                    out = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if out.get("id") == self._id:
                    break
        if "error" in out:
            raise MCPError(f"{self.name}: {out['error'].get('message')}")
        return out.get("result")

    initialize = MCPClient.initialize
    list_tools = MCPClient.list_tools
    call_tool = MCPClient.call_tool

    def close(self) -> None:
        try:
            self._proc.terminate()
        except OSError:
            pass


def clients_from_config(mcp_cfg: dict) -> list:
    """Build clients from a model config `mcp:` block:
    {remote: [{name, url, token}], stdio: [{name, command: [...], env}]}."""
    out: list = []
    for r in mcp_cfg.get("remote") or []:
        out.append(MCPClient(r["url"], token=r.get("token", ""), name=r.get("name", "")))
    for s in mcp_cfg.get("stdio") or []:
        out.append(StdioMCPClient(s["command"], env=s.get("env"), name=s.get("name", "")))
    return out
