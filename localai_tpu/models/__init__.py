"""Model families.

Registry mapping architecture-family names to their JAX builders, the moral
equivalent of the reference's backend dispatch table
(/root/reference/pkg/model/initializers.go:20-37 alias table) — except every
family compiles into the same persistent engine instead of spawning a
per-model subprocess.
"""

from localai_tpu.models.config import ArchConfig, PRESETS, get_arch  # noqa: F401
