"""BERT-family encoder as pure-functional JAX: sentence embeddings
(bge / sentence-transformers class) and cross-encoder reranking.

Reference: backend/python/transformers/backend.py SentenceTransformer branch
(BASELINE.json names bge-* embedding models) and the rerankers backend
(cross-encoder scoring). TPU shape: stacked-layer pytree + lax.scan,
post-LN blocks per original BERT, masked mean / CLS pooling, L2-normalized
outputs; an optional classification head turns the same stack into a
cross-encoder reranker.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    name: str = "bert"
    vocab_size: int = 30522
    hidden_size: int = 384  # bge-small
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 1536
    max_position: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    pooling: str = "cls"  # "cls" | "mean" (sentence-transformers pooling_mode)
    num_labels: int = 0  # >0 adds the cross-encoder classification head

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


BERT_PRESETS: dict[str, BertConfig] = {
    "bert-test": BertConfig(
        name="bert-test", vocab_size=512, hidden_size=32, num_layers=2,
        num_heads=2, intermediate_size=64, max_position=128,
    ),
    "bert-rerank-test": BertConfig(
        name="bert-rerank-test", vocab_size=512, hidden_size=32, num_layers=2,
        num_heads=2, intermediate_size=64, max_position=128, num_labels=1,
    ),
    "bge-small": BertConfig(name="bge-small"),
    "bge-base": BertConfig(
        name="bge-base", hidden_size=768, intermediate_size=3072
    ),
    "bge-large": BertConfig(
        name="bge-large", hidden_size=1024, num_layers=24, num_heads=16,
        intermediate_size=4096,
    ),
}


def init_params(cfg: BertConfig, key: jnp.ndarray, scale: float = 0.02) -> Params:
    keys = iter(jax.random.split(key, 32))
    D, L, F = cfg.hidden_size, cfg.num_layers, cfg.intermediate_size

    def rnd(shape):
        return jax.random.normal(next(keys), shape, jnp.float32) * scale

    params: Params = {
        "word_embed": rnd((cfg.vocab_size, D)),
        "pos_embed": rnd((cfg.max_position, D)),
        "type_embed": rnd((cfg.type_vocab_size, D)),
        "embed_ln_w": jnp.ones((D,)), "embed_ln_b": jnp.zeros((D,)),
        "layers": {
            "q_w": rnd((L, D, D)), "q_b": jnp.zeros((L, D)),
            "k_w": rnd((L, D, D)), "k_b": jnp.zeros((L, D)),
            "v_w": rnd((L, D, D)), "v_b": jnp.zeros((L, D)),
            "ao_w": rnd((L, D, D)), "ao_b": jnp.zeros((L, D)),
            "attn_ln_w": jnp.ones((L, D)), "attn_ln_b": jnp.zeros((L, D)),
            "fc1_w": rnd((L, D, F)), "fc1_b": jnp.zeros((L, F)),
            "fc2_w": rnd((L, F, D)), "fc2_b": jnp.zeros((L, D)),
            "out_ln_w": jnp.ones((L, D)), "out_ln_b": jnp.zeros((L, D)),
        },
        "pooler_w": rnd((D, D)), "pooler_b": jnp.zeros((D,)),
    }
    if cfg.num_labels > 0:
        params["cls_w"] = rnd((D, cfg.num_labels))
        params["cls_b"] = jnp.zeros((cfg.num_labels,))
    return params


def _ln(x, w, b, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + eps) * w + b


def encode_hidden(
    cfg: BertConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32, right-padded
    lengths: jnp.ndarray,  # [B]
    token_types: Optional[jnp.ndarray] = None,  # [B, S]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Full encoder forward → (hidden [B, S, D], mask [B, S])."""
    B, S = tokens.shape
    mask = jnp.arange(S)[None, :] < lengths[:, None]
    tt = token_types if token_types is not None else jnp.zeros((B, S), jnp.int32)
    h = (
        params["word_embed"][tokens]
        + params["pos_embed"][jnp.arange(S)][None]
        + params["type_embed"][tt]
    )
    h = _ln(h, params["embed_ln_w"], params["embed_ln_b"], cfg.layer_norm_eps)
    H, Dh = cfg.num_heads, cfg.head_dim
    attn_bias = jnp.where(mask[:, None, None, :], 0.0, -1e30)  # [B,1,1,S]

    def layer(h, lp):
        q = (h @ lp["q_w"] + lp["q_b"]).reshape(B, S, H, Dh)
        k = (h @ lp["k_w"] + lp["k_b"]).reshape(B, S, H, Dh)
        v = (h @ lp["v_w"] + lp["v_b"]).reshape(B, S, H, Dh)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * Dh**-0.5 + attn_bias
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, cfg.hidden_size)
        # post-LN (original BERT): sublayer → residual add → LayerNorm
        h = _ln(h + attn @ lp["ao_w"] + lp["ao_b"],
                lp["attn_ln_w"], lp["attn_ln_b"], cfg.layer_norm_eps)
        ffn = jax.nn.gelu(h @ lp["fc1_w"] + lp["fc1_b"], approximate=False)
        h = _ln(h + ffn @ lp["fc2_w"] + lp["fc2_b"],
                lp["out_ln_w"], lp["out_ln_b"], cfg.layer_norm_eps)
        return h, None

    h, _ = jax.lax.scan(layer, h, params["layers"])
    return h, mask


def embed(
    cfg: BertConfig,
    params: Params,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
) -> jnp.ndarray:
    """L2-normalized sentence embeddings [B, D] (bge: CLS pooling; mean
    pooling selectable per config — sentence-transformers semantics)."""
    h, mask = encode_hidden(cfg, params, tokens, lengths)
    if cfg.pooling == "mean":
        m = mask[..., None].astype(jnp.float32)
        pooled = (h * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
    else:  # CLS token
        pooled = h[:, 0]
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)


def score_pairs(
    cfg: BertConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, S] — [CLS] query [SEP] doc [SEP] rows
    lengths: jnp.ndarray,
    token_types: jnp.ndarray,  # 0 for query segment, 1 for doc segment
) -> jnp.ndarray:
    """Cross-encoder relevance scores [B] (bge-reranker class)."""
    assert cfg.num_labels > 0, "score_pairs needs a classification head"
    h, _ = encode_hidden(cfg, params, tokens, lengths, token_types)
    pooled = jnp.tanh(h[:, 0] @ params["pooler_w"] + params["pooler_b"])
    logits = pooled @ params["cls_w"] + params["cls_b"]  # [B, num_labels]
    return logits[:, 0]


# --------------------------------------------------------------------------- #
# HF checkpoint I/O (BertModel names, with/without "bert." prefix)
# --------------------------------------------------------------------------- #

_TOP_MAP = {
    "word_embed": ("embeddings.word_embeddings.weight", False),
    "pos_embed": ("embeddings.position_embeddings.weight", False),
    "type_embed": ("embeddings.token_type_embeddings.weight", False),
    "embed_ln_w": ("embeddings.LayerNorm.weight", False),
    "embed_ln_b": ("embeddings.LayerNorm.bias", False),
    "pooler_w": ("pooler.dense.weight", True),
    "pooler_b": ("pooler.dense.bias", False),
}

_LAYER_MAP = {
    "q_w": ("attention.self.query.weight", True),
    "q_b": ("attention.self.query.bias", False),
    "k_w": ("attention.self.key.weight", True),
    "k_b": ("attention.self.key.bias", False),
    "v_w": ("attention.self.value.weight", True),
    "v_b": ("attention.self.value.bias", False),
    "ao_w": ("attention.output.dense.weight", True),
    "ao_b": ("attention.output.dense.bias", False),
    "attn_ln_w": ("attention.output.LayerNorm.weight", False),
    "attn_ln_b": ("attention.output.LayerNorm.bias", False),
    "fc1_w": ("intermediate.dense.weight", True),
    "fc1_b": ("intermediate.dense.bias", False),
    "fc2_w": ("output.dense.weight", True),
    "fc2_b": ("output.dense.bias", False),
    "out_ln_w": ("output.LayerNorm.weight", False),
    "out_ln_b": ("output.LayerNorm.bias", False),
}


def load_hf_bert(cfg: BertConfig, ckpt_dir: str) -> Params:
    from localai_tpu.engine.weights import _ShardReader

    reader = _ShardReader(ckpt_dir)
    prefix = "bert." if "bert.embeddings.word_embeddings.weight" in reader else ""

    def grab(name: str, transpose: bool) -> jnp.ndarray:
        arr = reader.get(prefix + name)
        if transpose and arr.ndim == 2:
            arr = arr.T
        return jnp.asarray(np.ascontiguousarray(arr))

    params: Params = {}
    for our, (suffix, tr) in _TOP_MAP.items():
        if prefix + suffix in reader:
            params[our] = grab(suffix, tr)
        elif our.startswith("pooler"):  # some bge exports drop the pooler
            D = cfg.hidden_size
            params[our] = jnp.eye(D) if our.endswith("_w") else jnp.zeros((D,))
    layers: Params = {}
    for our, (suffix, tr) in _LAYER_MAP.items():
        rows = [grab(f"encoder.layer.{i}.{suffix}", tr) for i in range(cfg.num_layers)]
        layers[our] = jnp.stack(rows)
    params["layers"] = layers
    if cfg.num_labels > 0:
        # BertForSequenceClassification keeps the head OUTSIDE the "bert."
        # prefix; handle both layouts.
        if "classifier.weight" in reader:
            w = reader.get("classifier.weight")
            params["cls_w"] = jnp.asarray(np.ascontiguousarray(w.T))
            params["cls_b"] = jnp.asarray(reader.get("classifier.bias"))
        elif prefix + "classifier.weight" in reader:
            params["cls_w"] = grab("classifier.weight", True)
            params["cls_b"] = grab("classifier.bias", False)
        else:
            params["cls_w"] = jnp.zeros((cfg.hidden_size, cfg.num_labels))
            params["cls_b"] = jnp.zeros((cfg.num_labels,))
    return params


def save_hf_bert(cfg: BertConfig, params: Params, ckpt_dir: str) -> None:
    from safetensors.numpy import save_file

    os.makedirs(ckpt_dir, exist_ok=True)
    tensors: dict[str, np.ndarray] = {}

    def emit(name: str, arr, transpose=False):
        a = np.asarray(jnp.asarray(arr, jnp.float32))
        if transpose and a.ndim == 2:
            a = a.T
        tensors[name] = np.ascontiguousarray(a)

    for our, (suffix, tr) in _TOP_MAP.items():
        emit(suffix, params[our], tr)
    for our, (suffix, tr) in _LAYER_MAP.items():
        for i in range(cfg.num_layers):
            emit(f"encoder.layer.{i}.{suffix}", params["layers"][our][i], tr)
    if cfg.num_labels > 0 and "cls_w" in params:
        emit("classifier.weight", params["cls_w"], True)
        emit("classifier.bias", params["cls_b"])
    save_file(tensors, os.path.join(ckpt_dir, "model.safetensors"))
    with open(os.path.join(ckpt_dir, "config.json"), "w") as f:
        json.dump({
            "model_type": "bert",
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_hidden_layers": cfg.num_layers,
            "num_attention_heads": cfg.num_heads,
            "intermediate_size": cfg.intermediate_size,
            "max_position_embeddings": cfg.max_position,
            "type_vocab_size": cfg.type_vocab_size,
            "layer_norm_eps": cfg.layer_norm_eps,
            **({"num_labels": cfg.num_labels} if cfg.num_labels else {}),
        }, f, indent=1)


def bert_config_from_hf(ckpt_dir: str) -> BertConfig:
    with open(os.path.join(ckpt_dir, "config.json")) as f:
        hf = json.load(f)
    return BertConfig(
        name=hf.get("_name_or_path", "bert"),
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        intermediate_size=hf["intermediate_size"],
        max_position=hf.get("max_position_embeddings", 512),
        type_vocab_size=hf.get("type_vocab_size", 2),
        layer_norm_eps=hf.get("layer_norm_eps", 1e-12),
        num_labels=hf.get("num_labels", 0) if hf.get("architectures", [""])[0].endswith("SequenceClassification") else hf.get("num_labels", 0),
    )
