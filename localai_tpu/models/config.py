"""Architecture configs for decoder-only transformer families.

The reference delegates architecture to llama.cpp GGUF metadata
(/root/reference/core/config/gguf.go:15-60 introspects a GGUF to guess context
size and layout). Here architectures are first-class dataclasses so the JAX
model builders, the sharding planner (localai_tpu.parallel.sharding), and the
engine all agree on shapes statically — XLA requires static shapes to tile
matmuls onto the MXU.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ArchConfig:
    """Shape/hyperparameter description of a Llama-family decoder.

    Covers Llama 2/3, Mistral, Qwen2 (qkv biases), TinyLlama and friends —
    the same families the reference serves through llama.cpp GGUFs.
    """

    name: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None  # defaults to hidden_size // num_heads
    rope_theta: float = 10000.0
    # None | "linear" | "llama3" | "yarn" | "longrope" — the reference
    # forwards the same knob set to llama.cpp (model_config.go:231-237).
    rope_scaling: Optional[str] = None
    rope_scaling_factor: float = 1.0
    # llama3-style rope scaling extras
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_position: int = 8192
    # yarn extras (NTK-by-parts ramp bounds, HF defaults)
    rope_beta_fast: float = 32.0
    rope_beta_slow: float = 1.0
    # longrope (phi-3 "su") per-frequency rescale tables [head_dim/2]
    rope_long_factor: Optional[tuple] = None
    rope_short_factor: Optional[tuple] = None
    # Explicit attention-amplitude factor (yarn mscale / longrope scaling);
    # None = derive from the scaling type's published formula.
    rope_attn_factor: Optional[float] = None
    # Gemma-3: local (sliding) layers run their own unscaled rope base while
    # global layers use rope_theta (+ scaling). 0 = single schedule.
    rope_local_theta: float = 0.0
    max_position: int = 8192
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    attn_qkv_bias: bool = False  # Qwen2-style
    # Gemma-family: GeGLU MLP ("gelu_tanh"), embeddings scaled by sqrt(D)
    # at lookup (the tied unembed reads the raw matrix), and (1+w) RMSNorm
    # weights — the +1 is folded into the tree at load, so only the first
    # two need runtime branches.
    activation: str = "silu"  # "silu" | "gelu_tanh"
    embed_scale: bool = False
    norm_plus_one: bool = False  # load-time fold (engine/weights.py)
    # Gemma-2: sandwich norms (post-attention and post-feedforward RMSNorms
    # inside the residual adds), tanh softcapping on attention scores and
    # final logits, q scaled by query_pre_attn_scalar^-0.5 instead of
    # head_dim^-0.5, and sliding-window attention on even layers.
    post_norms: bool = False
    attn_softcap: float = 0.0  # 0 = off
    final_softcap: float = 0.0
    query_scale: float = 0.0  # 0 = default head_dim^-0.5
    sliding_window: int = 0  # 0 = full attention on every layer
    # Which layers slide: layer li is sliding iff li % pattern != pattern-1.
    # Gemma-2 alternates (2); gemma-3 runs 5 local : 1 global (6).
    sliding_pattern: int = 2
    # Gemma-3: per-head RMS norms on q and k (after projection, before rope).
    qk_norm: bool = False
    # Mixture-of-experts (Mixtral/DeepSeek-style); 0 experts = dense MLP
    num_experts: int = 0
    num_experts_per_token: int = 2
    # Capacity factor for the expert-parallel (ep>1) GShard dispatch path:
    # each expert processes at most ceil(top_k·N/E·cf) tokens per block.
    moe_capacity_factor: float = 2.0
    dtype: str = "bfloat16"

    @property
    def head_dim_(self) -> int:
        return self.head_dim if self.head_dim is not None else self.hidden_size // self.num_heads

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0


# ---------------------------------------------------------------------------
# Presets. Shapes match the public model cards for the configs listed in
# /root/repo/BASELINE.json; weights are loaded from local safetensors when
# available or randomly initialized for benchmarking.
# ---------------------------------------------------------------------------

PRESETS: dict[str, ArchConfig] = {
    # Tiny configs for tests / CI on the virtual CPU mesh.
    "tiny": ArchConfig(
        name="tiny",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        max_position=256,
        rope_theta=10000.0,
    ),
    "tiny-moe": ArchConfig(
        name="tiny-moe",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        max_position=256,
        num_experts=4,
        num_experts_per_token=2,
    ),
    "llama-3.2-1b": ArchConfig(
        name="llama-3.2-1b",
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        head_dim=64,
        rope_theta=500000.0,
        rope_scaling="llama3",
        rope_scaling_factor=32.0,
        max_position=131072,
        tie_embeddings=True,
    ),
    "llama-3-8b": ArchConfig(
        name="llama-3-8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=500000.0,
        max_position=8192,
    ),
    "mistral-7b": ArchConfig(
        name="mistral-7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=10000.0,
        max_position=32768,
    ),
    "qwen2-7b": ArchConfig(
        name="qwen2-7b",
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        rope_theta=1000000.0,
        max_position=32768,
        attn_qkv_bias=True,
    ),
    "mixtral-8x7b": ArchConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=1000000.0,
        max_position=32768,
        num_experts=8,
        num_experts_per_token=2,
    ),
}


def get_arch(name: str) -> ArchConfig:
    try:
        return PRESETS[name]
    except KeyError:
        raise KeyError(f"unknown architecture preset {name!r}; known: {sorted(PRESETS)}") from None
