"""Architecture configs for decoder-only transformer families.

The reference delegates architecture to llama.cpp GGUF metadata
(/root/reference/core/config/gguf.go:15-60 introspects a GGUF to guess context
size and layout). Here architectures are first-class dataclasses so the JAX
model builders, the sharding planner (localai_tpu.parallel.sharding), and the
engine all agree on shapes statically — XLA requires static shapes to tile
matmuls onto the MXU.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ArchConfig:
    """Shape/hyperparameter description of a Llama-family decoder.

    Covers Llama 2/3, Mistral, Qwen2 (qkv biases), TinyLlama and friends —
    the same families the reference serves through llama.cpp GGUFs.
    """

    name: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None  # defaults to hidden_size // num_heads
    rope_theta: float = 10000.0
    # None | "linear" | "llama3" | "yarn" | "longrope" — the reference
    # forwards the same knob set to llama.cpp (model_config.go:231-237).
    rope_scaling: Optional[str] = None
    rope_scaling_factor: float = 1.0
    # llama3-style rope scaling extras
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_position: int = 8192
    # yarn extras (NTK-by-parts ramp bounds, HF defaults)
    rope_beta_fast: float = 32.0
    rope_beta_slow: float = 1.0
    # longrope (phi-3 "su") per-frequency rescale tables [head_dim/2]
    rope_long_factor: Optional[tuple] = None
    rope_short_factor: Optional[tuple] = None
    # Explicit attention-amplitude factor (yarn mscale / longrope scaling);
    # None = derive from the scaling type's published formula.
    rope_attn_factor: Optional[float] = None
    # Gemma-3: local (sliding) layers run their own unscaled rope base while
    # global layers use rope_theta (+ scaling). 0 = single schedule.
    rope_local_theta: float = 0.0
    max_position: int = 8192
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    attn_qkv_bias: bool = False  # Qwen2-style
    # Gemma-family: GeGLU MLP ("gelu_tanh"), embeddings scaled by sqrt(D)
    # at lookup (the tied unembed reads the raw matrix), and (1+w) RMSNorm
    # weights — the +1 is folded into the tree at load, so only the first
    # two need runtime branches.
    activation: str = "silu"  # "silu" | "gelu_tanh"
    embed_scale: bool = False
    norm_plus_one: bool = False  # load-time fold (engine/weights.py)
    # Gemma-2: sandwich norms (post-attention and post-feedforward RMSNorms
    # inside the residual adds), tanh softcapping on attention scores and
    # final logits, q scaled by query_pre_attn_scalar^-0.5 instead of
    # head_dim^-0.5, and sliding-window attention on even layers.
    post_norms: bool = False
    attn_softcap: float = 0.0  # 0 = off
    final_softcap: float = 0.0
    query_scale: float = 0.0  # 0 = default head_dim^-0.5
    sliding_window: int = 0  # 0 = full attention on every layer
    # Which layers slide: layer li is sliding iff li % pattern != pattern-1.
    # Gemma-2 alternates (2); gemma-3 runs 5 local : 1 global (6).
    sliding_pattern: int = 2
    # Gemma-3: per-head RMS norms on q and k (after projection, before rope).
    qk_norm: bool = False
    # Mixture-of-experts (Mixtral/DeepSeek-style); 0 experts = dense MLP
    num_experts: int = 0
    num_experts_per_token: int = 2
    # Capacity factor for the expert-parallel (ep>1) GShard dispatch path:
    # each expert processes at most ceil(top_k·N/E·cf) tokens per block.
    moe_capacity_factor: float = 2.0
    # DeepSeek-V2/V3 MoE layout (HF DeepseekV2Config/DeepseekV3Config;
    # reference serves these via vLLM passthrough, vllm/backend.py:92-141):
    # the first `first_k_dense` layers run a dense MLP, the rest route
    # `num_experts_per_token` of `num_experts` routed experts (intermediate
    # size `moe_intermediate_size`) plus an always-on shared-expert MLP of
    # size n_shared_experts·moe_intermediate_size.
    first_k_dense: int = 0
    n_shared_experts: int = 0
    moe_intermediate_size: Optional[int] = None
    routed_scaling_factor: float = 1.0
    # Router family: "mixtral" softmaxes the top-k logits; "deepseek"
    # scores ALL experts (softmax/sigmoid per scoring_func) and then
    # selects — the two orders give different weights, so this is explicit.
    moe_family: str = "mixtral"
    # Router scoring: "softmax" (Mixtral/DeepSeek-V2) or "sigmoid"
    # (DeepSeek-V3/R1, selection biased by a learned per-expert correction).
    scoring_func: str = "softmax"
    router_bias: bool = False  # V3 e_score_correction_bias
    norm_topk_prob: bool = False  # V3: renormalize the selected weights
    # Group-limited routing (device-limited in the paper): experts are split
    # into n_group groups; selection is restricted to the topk_group
    # best-scoring groups (V2 scores a group by its max, V3 by the sum of
    # its top-2 biased scores).
    n_group: int = 1
    topk_group: int = 1
    # Multi-head Latent Attention (DeepSeek-V2/V3): q/kv project through
    # low-rank bottlenecks and the KV cache stores ONE latent row per token
    # ([kv_lora_rank | roped qk_rope_head_dim]) instead of per-head k/v.
    # kv_lora_rank > 0 switches the whole attention stack to MLA.
    kv_lora_rank: int = 0
    q_lora_rank: Optional[int] = None  # None = direct q projection (V2-Lite)
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    # HF deepseek checkpoints store the rope dims pair-interleaved (V2
    # always — complex rope; V3 per config.rope_interleave). The loader
    # de-interleaves the affected projection columns so runtime rope stays
    # the one half-split (neox) implementation.
    rope_interleave: bool = False
    # Qwen2-VL multimodal rope: (t, h, w) section split of head_dim/2.
    # Non-empty → image-bearing prompts prefill with 3D position streams
    # (ops/rope.mrope_angles); text-only paths reduce to plain rope.
    mrope_section: tuple = ()
    dtype: str = "bfloat16"
    # Quantized-matmul kernel choice threaded to every model-side matmul
    # (ISSUE 9): "auto" (fused Pallas dequant-matmul on TPU, XLA dequant
    # elsewhere) | "pallas" | "xla". Lives on ArchConfig — not a shape, but
    # cfg is the one static object every layer helper already receives, so
    # the engine's EngineConfig.quant_kernel knob reaches models/quant.py
    # through `dataclasses.replace(cfg, quant_kernel=...)` without
    # re-plumbing ~30 call sites (the paged_impl treatment at entry-point
    # granularity; quant matmuls live one level deeper).
    quant_kernel: str = "auto"
    # Ragged per-slot LoRA delta kernel choice (ISSUE 10,
    # docs/LORA_SERVING.md): "auto" (Pallas segmented matmul on TPU, XLA
    # gather elsewhere) | "pallas" | "xla". Threaded exactly like
    # quant_kernel — EngineConfig.lora_kernel reaches ops/lora_matmul.py
    # through `dataclasses.replace(cfg, lora_kernel=...)`.
    lora_kernel: str = "auto"
    # Self-draft early-exit prefix (ISSUE 12, docs/SPECULATIVE.md): > 0
    # means `spec_mode=self_draft` drafts with the target's OWN first k
    # layers + final norm + unembed — `llama.self_draft_view` slices the
    # stacked layer tensors to [:k] inside the traced program, so the
    # draft shares the sharded weight buffers (no second checkpoint in
    # HBM). Lives on ArchConfig like quant_kernel/lora_kernel: the engine's
    # EngineConfig.self_draft_layers knob reaches the layer-scan helpers
    # through `dataclasses.replace(cfg, self_draft_layers=...)`.
    self_draft_layers: int = 0
    # Windowed+sink long-context serving (ISSUE 14, docs/LONG_CONTEXT.md):
    # when attention_window > 0, decode (and the chunked-prefill prefix
    # walk under the paged pool) attends only rows with position < sink or
    # within `attention_window` of the query — StreamingLLM-style, with
    # ABSOLUTE rope positions (rows keep their original positions; no
    # re-rope). Lives on ArchConfig like quant_kernel: the engine's
    # EngineConfig knobs reach every attention call through
    # `dataclasses.replace(cfg, ...)`. 0/0 = full attention (default).
    attention_sink: int = 0
    attention_window: int = 0

    @property
    def head_dim_(self) -> int:
        return self.head_dim if self.head_dim is not None else self.hidden_size // self.num_heads

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def is_mla(self) -> bool:
        return self.kv_lora_rank > 0

    @property
    def qk_head_dim(self) -> int:
        """Per-head q/k width under MLA (nope ⊕ rope)."""
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    # Cache layout: the engine, pool allocator, and sharding planner size the
    # KV cache from these three, so MLA's latent layout (one pseudo-head of
    # [kv_lora_rank + rope] per token, no separate V — values are read back
    # out of the same latent) threads through every cache variant (dense /
    # windowed / paged / fp8) without per-call-site branches.
    @property
    def cache_kv_heads(self) -> int:
        return 1 if self.is_mla else self.num_kv_heads

    @property
    def cache_k_dim(self) -> int:
        return self.kv_lora_rank + self.qk_rope_head_dim if self.is_mla else self.head_dim_

    @property
    def cache_v_dim(self) -> int:
        return 0 if self.is_mla else self.head_dim_

    @property
    def moe_inter_size(self) -> int:
        return self.moe_intermediate_size or self.intermediate_size


# ---------------------------------------------------------------------------
# Presets. Shapes match the public model cards for the configs listed in
# /root/repo/BASELINE.json; weights are loaded from local safetensors when
# available or randomly initialized for benchmarking.
# ---------------------------------------------------------------------------

PRESETS: dict[str, ArchConfig] = {
    # Tiny configs for tests / CI on the virtual CPU mesh.
    "tiny": ArchConfig(
        name="tiny",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        max_position=256,
        rope_theta=10000.0,
    ),
    "tiny-moe": ArchConfig(
        name="tiny-moe",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        max_position=256,
        num_experts=4,
        num_experts_per_token=2,
    ),
    "tiny-mla": ArchConfig(
        # DeepSeek-V3-shaped tiny: MLA with q-lora, sigmoid router with
        # correction bias, group-limited top-k, shared expert, dense-first
        # layer — every R1 mechanism at test scale.
        name="tiny-mla",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=3,
        num_heads=4,
        num_kv_heads=4,
        head_dim=16,  # rope table width = qk_rope_head_dim
        max_position=256,
        moe_family="deepseek",
        num_experts=8,
        num_experts_per_token=3,
        first_k_dense=1,
        n_shared_experts=1,
        moe_intermediate_size=48,
        routed_scaling_factor=2.5,
        scoring_func="sigmoid",
        router_bias=True,
        norm_topk_prob=True,
        n_group=4,
        topk_group=2,
        kv_lora_rank=32,
        q_lora_rank=24,
        qk_nope_head_dim=24,
        qk_rope_head_dim=16,
        v_head_dim=24,
    ),
    "llama-3.2-1b": ArchConfig(
        name="llama-3.2-1b",
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        head_dim=64,
        rope_theta=500000.0,
        rope_scaling="llama3",
        rope_scaling_factor=32.0,
        max_position=131072,
        tie_embeddings=True,
    ),
    "llama-3-8b": ArchConfig(
        name="llama-3-8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=500000.0,
        max_position=8192,
    ),
    "mistral-7b": ArchConfig(
        name="mistral-7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=10000.0,
        max_position=32768,
    ),
    "qwen2-7b": ArchConfig(
        name="qwen2-7b",
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        rope_theta=1000000.0,
        max_position=32768,
        attn_qkv_bias=True,
    ),
    "mixtral-8x7b": ArchConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        rope_theta=1000000.0,
        max_position=32768,
        num_experts=8,
        num_experts_per_token=2,
    ),
    "deepseek-v2-lite": ArchConfig(
        # Published card: 27 layers, 16B total / 2.4B active, MLA without
        # q-lora, 64 routed + 2 shared experts, first layer dense.
        name="deepseek-v2-lite",
        vocab_size=102400,
        hidden_size=2048,
        intermediate_size=10944,
        num_layers=27,
        num_heads=16,
        num_kv_heads=16,
        head_dim=64,
        rope_theta=10000.0,
        max_position=163840,
        moe_family="deepseek",
        num_experts=64,
        num_experts_per_token=6,
        first_k_dense=1,
        n_shared_experts=2,
        moe_intermediate_size=1408,
        routed_scaling_factor=1.0,
        scoring_func="softmax",
        rope_interleave=True,
        kv_lora_rank=512,
        q_lora_rank=None,
        qk_nope_head_dim=128,
        qk_rope_head_dim=64,
        v_head_dim=128,
    ),
    "deepseek-r1": ArchConfig(
        # DeepSeek-V3/R1 (BASELINE.json configs[4]): 61 layers (3 dense),
        # 256 routed experts top-8 in 8 groups, sigmoid router with
        # correction bias, MLA with q-lora. Serving shapes for the EP mesh
        # dryrun and decode benchmarks; full weights need a multi-host pod.
        name="deepseek-r1",
        vocab_size=129280,
        hidden_size=7168,
        intermediate_size=18432,
        num_layers=61,
        num_heads=128,
        num_kv_heads=128,
        head_dim=64,
        rope_theta=10000.0,
        max_position=163840,
        moe_family="deepseek",
        num_experts=256,
        num_experts_per_token=8,
        first_k_dense=3,
        n_shared_experts=1,
        moe_intermediate_size=2048,
        routed_scaling_factor=2.5,
        scoring_func="sigmoid",
        router_bias=True,
        norm_topk_prob=True,
        n_group=8,
        topk_group=4,
        rope_interleave=True,
        kv_lora_rank=512,
        q_lora_rank=1536,
        qk_nope_head_dim=128,
        qk_rope_head_dim=64,
        v_head_dim=128,
    ),
}


def get_arch(name: str) -> ArchConfig:
    try:
        return PRESETS[name]
    except KeyError:
        raise KeyError(f"unknown architecture preset {name!r}; known: {sorted(PRESETS)}") from None
