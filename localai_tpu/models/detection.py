"""Object detection as pure-functional JAX: a DETR-style set predictor.

The reference serves detection through RF-DETR (backend/python/rfdetr,
RPC Detect → core/backend/detection.go:12, endpoint /v1/detection). Same
capability, TPU-first shape: patchify → transformer encoder → learned object
queries cross-attending in a decoder → per-query class logits + sigmoid box
regression (cx, cy, w, h in [0,1]). Fixed query count keeps every shape
static; confidence filtering happens on the host.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]

COCO_CLASSES = (
    "person bicycle car motorcycle airplane bus train truck boat traffic-light "
    "fire-hydrant stop-sign parking-meter bench bird cat dog horse sheep cow "
    "elephant bear zebra giraffe backpack umbrella handbag tie suitcase frisbee "
    "skis snowboard sports-ball kite baseball-bat baseball-glove skateboard "
    "surfboard tennis-racket bottle wine-glass cup fork knife spoon bowl banana "
    "apple sandwich orange broccoli carrot hot-dog pizza donut cake chair couch "
    "potted-plant bed dining-table toilet tv laptop mouse remote keyboard "
    "cell-phone microwave oven toaster sink refrigerator book clock vase "
    "scissors teddy-bear hair-drier toothbrush"
).split()


@dataclasses.dataclass(frozen=True)
class DetectionConfig:
    name: str = "detr"
    image_size: int = 256
    patch: int = 16
    d_model: int = 256
    n_heads: int = 8
    enc_layers: int = 4
    dec_layers: int = 4
    ffn_mult: int = 4
    n_queries: int = 50
    class_names: tuple[str, ...] = tuple(COCO_CLASSES)

    @property
    def n_classes(self) -> int:
        return len(self.class_names)

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def ffn(self) -> int:
        return self.d_model * self.ffn_mult

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch * self.patch * 3


DETECTION_PRESETS: dict[str, DetectionConfig] = {
    "detr-test": DetectionConfig(
        name="detr-test", image_size=32, patch=8, d_model=32, n_heads=2,
        enc_layers=1, dec_layers=1, n_queries=8,
        class_names=("cat", "dog", "car"),
    ),
    "detr-base": DetectionConfig(name="detr-base"),
}


def _block_params(rnd, L, d, ffn, cross: bool) -> Params:
    p = {
        "ln1_w": jnp.ones((L, d)), "ln1_b": jnp.zeros((L, d)),
        "q_w": rnd((L, d, d)), "k_w": rnd((L, d, d)), "v_w": rnd((L, d, d)),
        "o_w": rnd((L, d, d)),
        "ln2_w": jnp.ones((L, d)), "ln2_b": jnp.zeros((L, d)),
        "fc1_w": rnd((L, d, ffn)), "fc1_b": jnp.zeros((L, ffn)),
        "fc2_w": rnd((L, ffn, d)), "fc2_b": jnp.zeros((L, d)),
    }
    if cross:
        p.update({
            "lnx_w": jnp.ones((L, d)), "lnx_b": jnp.zeros((L, d)),
            "xq_w": rnd((L, d, d)), "xk_w": rnd((L, d, d)), "xv_w": rnd((L, d, d)),
            "xo_w": rnd((L, d, d)),
        })
    return p


def init_params(cfg: DetectionConfig, key: jnp.ndarray, scale: float = 0.02) -> Params:
    keys = iter(jax.random.split(key, 64))
    d = cfg.d_model

    def rnd(shape):
        return jax.random.normal(next(keys), shape, jnp.float32) * scale

    return {
        "patch_w": rnd((cfg.patch_dim, d)), "patch_b": jnp.zeros((d,)),
        "pos": rnd((cfg.n_patches, d)),
        "queries": rnd((cfg.n_queries, d)),
        "enc": _block_params(rnd, cfg.enc_layers, d, cfg.ffn, cross=False),
        "dec": _block_params(rnd, cfg.dec_layers, d, cfg.ffn, cross=True),
        "ln_f_w": jnp.ones((d,)), "ln_f_b": jnp.zeros((d,)),
        # +1 class for "no object" (DETR convention)
        "cls_w": rnd((d, cfg.n_classes + 1)), "cls_b": jnp.zeros((cfg.n_classes + 1,)),
        "box_w": rnd((d, 4)), "box_b": jnp.zeros((4,)),
    }


def _ln(x, w, b, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + eps) * w + b


def _attn(cfg, q, k, v):
    B, Tq = q.shape[:2]
    H, Dh = cfg.n_heads, cfg.head_dim
    qh = q.reshape(B, Tq, H, Dh)
    kh = k.reshape(B, k.shape[1], H, Dh)
    vh = v.reshape(B, v.shape[1], H, Dh)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * Dh**-0.5
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, vh).reshape(B, Tq, cfg.d_model)


def forward(cfg: DetectionConfig, params: Params, img: jnp.ndarray):
    """img [B, H, W, 3] in [0,1] → (class_logits [B, Q, C+1], boxes [B, Q, 4]).

    Boxes are (cx, cy, w, h) normalized to [0, 1]."""
    B = img.shape[0]
    p, n = cfg.patch, cfg.image_size // cfg.patch
    x = img.reshape(B, n, p, n, p, 3).transpose(0, 1, 3, 2, 4, 5).reshape(B, n * n, cfg.patch_dim)
    h = x @ params["patch_w"] + params["patch_b"] + params["pos"][None]

    def enc_layer(h, lp):
        x = _ln(h, lp["ln1_w"], lp["ln1_b"])
        h = h + _attn(cfg, x @ lp["q_w"], x @ lp["k_w"], x @ lp["v_w"]) @ lp["o_w"]
        x = _ln(h, lp["ln2_w"], lp["ln2_b"])
        h = h + jax.nn.gelu(x @ lp["fc1_w"] + lp["fc1_b"]) @ lp["fc2_w"] + lp["fc2_b"]
        return h, None

    mem, _ = jax.lax.scan(enc_layer, h, params["enc"])

    q = jnp.broadcast_to(params["queries"][None], (B, cfg.n_queries, cfg.d_model))

    def dec_layer(q, lp):
        x = _ln(q, lp["ln1_w"], lp["ln1_b"])
        q = q + _attn(cfg, x @ lp["q_w"], x @ lp["k_w"], x @ lp["v_w"]) @ lp["o_w"]
        x = _ln(q, lp["lnx_w"], lp["lnx_b"])
        q = q + _attn(cfg, x @ lp["xq_w"], mem @ lp["xk_w"], mem @ lp["xv_w"]) @ lp["xo_w"]
        x = _ln(q, lp["ln2_w"], lp["ln2_b"])
        q = q + jax.nn.gelu(x @ lp["fc1_w"] + lp["fc1_b"]) @ lp["fc2_w"] + lp["fc2_b"]
        return q, None

    q, _ = jax.lax.scan(dec_layer, q, params["dec"])
    q = _ln(q, params["ln_f_w"], params["ln_f_b"])
    cls_logits = q @ params["cls_w"] + params["cls_b"]
    boxes = jax.nn.sigmoid(q @ params["box_w"] + params["box_b"])
    return cls_logits, boxes


def save_detection(cfg: DetectionConfig, params: Params, ckpt_dir: str) -> None:
    from safetensors.numpy import save_file

    os.makedirs(ckpt_dir, exist_ok=True)
    flat = {}
    for k, v in params.items():
        if isinstance(v, dict):
            for k2, v2 in v.items():
                flat[f"{k}.{k2}"] = np.asarray(v2, np.float32)
        else:
            flat[k] = np.asarray(v, np.float32)
    save_file(flat, os.path.join(ckpt_dir, "model.safetensors"))
    d = dataclasses.asdict(cfg)
    d["class_names"] = list(cfg.class_names)
    with open(os.path.join(ckpt_dir, "config.json"), "w") as f:
        json.dump({"model_type": "localai-detr", **d}, f, indent=1)


def load_detection(ckpt_dir: str) -> tuple[DetectionConfig, Params]:
    from safetensors import safe_open

    with open(os.path.join(ckpt_dir, "config.json")) as f:
        hf = json.load(f)
    hf.pop("model_type", None)
    hf["class_names"] = tuple(hf.get("class_names", COCO_CLASSES))
    cfg = DetectionConfig(**hf)
    params: Params = {}
    with safe_open(os.path.join(ckpt_dir, "model.safetensors"), framework="numpy") as f:
        for name in f.keys():
            arr = jnp.asarray(f.get_tensor(name))
            if "." in name:
                grp, sub = name.split(".", 1)
                params.setdefault(grp, {})[sub] = arr
            else:
                params[name] = arr
    return cfg, params
