"""Text-to-image diffusion as pure-functional JAX: a DiT (diffusion
transformer) with a DDIM sampler and classifier-free guidance.

The reference serves image generation through torch diffusers pipelines
(backend/python/diffusers/backend.py:27-120, endpoint core/http/endpoints/
openai/image.go) and a GGML stable-diffusion backend. This is a TPU-first
redesign of the capability, not a port of either:

- Pixel-space DiT: patchify → transformer with adaLN timestep modulation and
  cross-attention over a byte-level text encoder → unpatchify to noise
  prediction. Every op is a matmul/attention that tiles onto the MXU; no
  UNet conv pyramids (XLA fuses DiT blocks better than deep conv stacks).
- The entire sampler (all DDIM steps, both CFG branches) is ONE jitted
  program via `lax.scan` — zero host round-trips per image.
- Weights: own safetensors layout (save_diffusion / load_diffusion); tiny
  random-init preset for hermetic tests.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class DiffusionConfig:
    name: str = "dit"
    image_size: int = 64
    channels: int = 3
    patch: int = 8
    d_model: int = 256
    n_heads: int = 4
    layers: int = 6
    ffn_mult: int = 4
    text_vocab: int = 256  # utf-8 bytes
    text_ctx: int = 64
    text_layers: int = 2
    n_steps_train: int = 1000  # diffusion timesteps

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def ffn(self) -> int:
        return self.d_model * self.ffn_mult

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch * self.patch * self.channels


DIFFUSION_PRESETS: dict[str, DiffusionConfig] = {
    "dit-test": DiffusionConfig(
        name="dit-test", image_size=16, patch=4, d_model=32, n_heads=2,
        layers=2, text_ctx=16, text_layers=1,
    ),
    "dit-base": DiffusionConfig(name="dit-base"),
    "dit-512": DiffusionConfig(name="dit-512", image_size=512, patch=16,
                               d_model=1024, n_heads=16, layers=24),
}


def _sinusoids(length: int, channels: int) -> np.ndarray:
    log_ts = np.log(10000.0) / max(channels // 2 - 1, 1)
    inv = np.exp(-log_ts * np.arange(channels // 2))
    scaled = np.arange(length)[:, None] * inv[None, :]
    return np.concatenate([np.sin(scaled), np.cos(scaled)], axis=1).astype(np.float32)


def timestep_embedding(t: jnp.ndarray, dim: int) -> jnp.ndarray:
    """t [B] float → [B, dim] sinusoidal features."""
    half = dim // 2
    freqs = jnp.exp(-np.log(10000.0) * jnp.arange(half) / max(half - 1, 1))
    args = t[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)


def init_params(cfg: DiffusionConfig, key: jnp.ndarray, scale: float = 0.02) -> Params:
    keys = iter(jax.random.split(key, 128))
    d, L = cfg.d_model, cfg.layers

    def rnd(shape, s=scale):
        return jax.random.normal(next(keys), shape, jnp.float32) * s

    blocks = {
        # adaLN modulation: time embedding → per-block scale/shift/gate ×2
        "mod_w": jnp.zeros((L, d, 6 * d)),  # zero-init (adaLN-zero)
        "mod_b": jnp.zeros((L, 6 * d)),
        "q_w": rnd((L, d, d)), "k_w": rnd((L, d, d)), "v_w": rnd((L, d, d)),
        "o_w": rnd((L, d, d)),
        "xq_w": rnd((L, d, d)), "xk_w": rnd((L, d, d)), "xv_w": rnd((L, d, d)),
        "xo_w": rnd((L, d, d)),
        "lnx_w": jnp.ones((L, d)), "lnx_b": jnp.zeros((L, d)),
        "fc1_w": rnd((L, d, cfg.ffn)), "fc1_b": jnp.zeros((L, cfg.ffn)),
        "fc2_w": rnd((L, cfg.ffn, d)), "fc2_b": jnp.zeros((L, d)),
    }
    text_blocks = {
        "ln1_w": jnp.ones((cfg.text_layers, d)), "ln1_b": jnp.zeros((cfg.text_layers, d)),
        "q_w": rnd((cfg.text_layers, d, d)), "k_w": rnd((cfg.text_layers, d, d)),
        "v_w": rnd((cfg.text_layers, d, d)), "o_w": rnd((cfg.text_layers, d, d)),
        "ln2_w": jnp.ones((cfg.text_layers, d)), "ln2_b": jnp.zeros((cfg.text_layers, d)),
        "fc1_w": rnd((cfg.text_layers, d, cfg.ffn)), "fc1_b": jnp.zeros((cfg.text_layers, cfg.ffn)),
        "fc2_w": rnd((cfg.text_layers, cfg.ffn, d)), "fc2_b": jnp.zeros((cfg.text_layers, d)),
    }
    return {
        "patch_w": rnd((cfg.patch_dim, d)), "patch_b": jnp.zeros((d,)),
        "pos": rnd((cfg.n_patches, d)),
        "t_w1": rnd((d, d)), "t_b1": jnp.zeros((d,)),
        "t_w2": rnd((d, d)), "t_b2": jnp.zeros((d,)),
        "text_embed": rnd((cfg.text_vocab, d)),
        "text_pos": jnp.asarray(_sinusoids(cfg.text_ctx, d)),
        "text": text_blocks,
        "null_text": rnd((cfg.text_ctx, d)),  # CFG unconditional context
        "blocks": blocks,
        "ln_f_w": jnp.ones((d,)), "ln_f_b": jnp.zeros((d,)),
        "out_w": jnp.zeros((d, cfg.patch_dim)), "out_b": jnp.zeros((cfg.patch_dim,)),
    }


def _ln(x, w, b, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + eps) * w + b


def _ln_nomod(x, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + eps)


def _attn(cfg, q, k, v):
    B, Tq = q.shape[:2]
    H, Dh = cfg.n_heads, cfg.head_dim
    qh = q.reshape(B, Tq, H, Dh)
    kh = k.reshape(B, k.shape[1], H, Dh)
    vh = v.reshape(B, v.shape[1], H, Dh)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * Dh**-0.5
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, vh).reshape(B, Tq, cfg.d_model)


def encode_text(cfg: DiffusionConfig, params: Params, text_ids: jnp.ndarray) -> jnp.ndarray:
    """text_ids [B, text_ctx] (zero-padded) → context [B, text_ctx, d]."""
    h = params["text_embed"][text_ids] + params["text_pos"][None]

    def layer(h, lp):
        x = _ln(h, lp["ln1_w"], lp["ln1_b"])
        h = h + _attn(cfg, x @ lp["q_w"], x @ lp["k_w"], x @ lp["v_w"]) @ lp["o_w"]
        x = _ln(h, lp["ln2_w"], lp["ln2_b"])
        h = h + jax.nn.gelu(x @ lp["fc1_w"] + lp["fc1_b"]) @ lp["fc2_w"] + lp["fc2_b"]
        return h, None

    h, _ = jax.lax.scan(layer, h, params["text"])
    return h


def patchify(cfg: DiffusionConfig, img: jnp.ndarray) -> jnp.ndarray:
    """[B, H, W, C] → [B, n_patches, patch_dim]"""
    B = img.shape[0]
    p, n = cfg.patch, cfg.image_size // cfg.patch
    x = img.reshape(B, n, p, n, p, cfg.channels)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, n * n, cfg.patch_dim)


def unpatchify(cfg: DiffusionConfig, x: jnp.ndarray) -> jnp.ndarray:
    B = x.shape[0]
    p, n = cfg.patch, cfg.image_size // cfg.patch
    x = x.reshape(B, n, n, p, p, cfg.channels)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, cfg.image_size, cfg.image_size, cfg.channels)


def denoise(
    cfg: DiffusionConfig,
    params: Params,
    img: jnp.ndarray,  # [B, H, W, C] noisy image
    t: jnp.ndarray,  # [B] float timestep in [0, n_steps_train)
    ctx: jnp.ndarray,  # [B, text_ctx, d] text context
) -> jnp.ndarray:
    """Predict the noise ε for `img` at timestep t. Returns [B, H, W, C]."""
    h = patchify(cfg, img) @ params["patch_w"] + params["patch_b"]
    h = h + params["pos"][None]
    temb = timestep_embedding(t, cfg.d_model)
    temb = jax.nn.silu(temb @ params["t_w1"] + params["t_b1"])
    temb = temb @ params["t_w2"] + params["t_b2"]  # [B, d]

    def layer(h, lp):
        mod = jax.nn.silu(temb) @ lp["mod_w"] + lp["mod_b"]  # [B, 6d]
        s1, sh1, g1, s2, sh2, g2 = jnp.split(mod, 6, axis=-1)
        x = _ln_nomod(h) * (1 + s1[:, None]) + sh1[:, None]
        attn = _attn(cfg, x @ lp["q_w"], x @ lp["k_w"], x @ lp["v_w"]) @ lp["o_w"]
        h = h + g1[:, None] * attn
        # Cross-attention over the text context (un-modulated pre-LN).
        x = _ln(h, lp["lnx_w"], lp["lnx_b"])
        xattn = _attn(cfg, x @ lp["xq_w"], ctx @ lp["xk_w"], ctx @ lp["xv_w"]) @ lp["xo_w"]
        h = h + xattn
        x = _ln_nomod(h) * (1 + s2[:, None]) + sh2[:, None]
        mlp = jax.nn.gelu(x @ lp["fc1_w"] + lp["fc1_b"]) @ lp["fc2_w"] + lp["fc2_b"]
        h = h + g2[:, None] * mlp
        return h, None

    h, _ = jax.lax.scan(layer, h, params["blocks"])
    h = _ln(h, params["ln_f_w"], params["ln_f_b"])
    out = h @ params["out_w"] + params["out_b"]
    return unpatchify(cfg, out)


def _ddim_schedule(n_train: int, n_steps: int) -> np.ndarray:
    """Evenly-spaced DDIM timestep subsequence (descending)."""
    ts = np.linspace(0, n_train - 1, n_steps).round().astype(np.int64)
    return ts[::-1].copy()


def _alpha_bar(t: jnp.ndarray, n_train: int) -> jnp.ndarray:
    """Cosine noise schedule (Nichol & Dhariwal)."""
    f = jnp.cos(((t / n_train) + 0.008) / 1.008 * (np.pi / 2)) ** 2
    f0 = np.cos((0.008 / 1.008) * (np.pi / 2)) ** 2
    return jnp.clip(f / f0, 1e-5, 1.0)


def generate(
    cfg: DiffusionConfig,
    params: Params,
    text_ids: jnp.ndarray,  # [B, text_ctx] int32
    key: jnp.ndarray,  # PRNG key
    steps: int = 20,
    guidance: float = 4.0,
) -> jnp.ndarray:
    """DDIM sampling with classifier-free guidance. Returns [B, H, W, C] in
    [0, 1]. One jitted program: the step loop is lax.scan."""
    B = text_ids.shape[0]
    ctx_c = encode_text(cfg, params, text_ids)
    ctx_u = jnp.broadcast_to(params["null_text"][None], ctx_c.shape)
    ctx = jnp.concatenate([ctx_c, ctx_u], axis=0)  # [2B, ...]

    x = jax.random.normal(key, (B, cfg.image_size, cfg.image_size, cfg.channels), jnp.float32)
    ts = jnp.asarray(_ddim_schedule(cfg.n_steps_train, steps), jnp.float32)

    def step(x, i):
        t = ts[i]
        t_prev = jnp.where(i + 1 < steps, ts[jnp.minimum(i + 1, steps - 1)], -1.0)
        tb = jnp.full((2 * B,), t, jnp.float32)
        eps = denoise(cfg, params, jnp.concatenate([x, x], axis=0), tb, ctx)
        eps_c, eps_u = eps[:B], eps[B:]
        eps_g = eps_u + guidance * (eps_c - eps_u)

        ab_t = _alpha_bar(t, cfg.n_steps_train)
        ab_prev = jnp.where(t_prev >= 0, _alpha_bar(t_prev, cfg.n_steps_train), 1.0)
        x0 = (x - jnp.sqrt(1 - ab_t) * eps_g) / jnp.sqrt(ab_t)
        x0 = jnp.clip(x0, -3.0, 3.0)
        x_prev = jnp.sqrt(ab_prev) * x0 + jnp.sqrt(1 - ab_prev) * eps_g
        return x_prev, None

    x, _ = jax.lax.scan(step, x, jnp.arange(steps))
    return jnp.clip(x * 0.5 + 0.5, 0.0, 1.0)


def inpaint(
    cfg: DiffusionConfig,
    params: Params,
    text_ids: jnp.ndarray,  # [B, text_ctx] int32
    image: jnp.ndarray,  # [B, H, W, C] in [0, 1] — the original
    mask: jnp.ndarray,  # [B, H, W] — 1.0 where content is REPAINTED
    key: jnp.ndarray,
    steps: int = 20,
    guidance: float = 4.0,
) -> jnp.ndarray:
    """DDIM inpainting (RePaint-style known-region replay): at every step the
    kept region is replaced by the original image noised to the step's level,
    so only the masked region is synthesized. Reference endpoint:
    /v1/images/inpainting (endpoints/openai/inpainting.go → diffusers
    inpaint pipelines). Returns [B, H, W, C] in [0, 1]."""
    B = text_ids.shape[0]
    ctx_c = encode_text(cfg, params, text_ids)
    ctx_u = jnp.broadcast_to(params["null_text"][None], ctx_c.shape)
    ctx = jnp.concatenate([ctx_c, ctx_u], axis=0)

    x0_known = image.astype(jnp.float32) * 2.0 - 1.0
    m = mask.astype(jnp.float32)[..., None]  # [B, H, W, 1]
    key, nk = jax.random.split(key)
    x = jax.random.normal(nk, x0_known.shape, jnp.float32)
    ts = jnp.asarray(_ddim_schedule(cfg.n_steps_train, steps), jnp.float32)
    noise_keys = jax.random.split(key, steps)

    def step(x, i):
        t = ts[i]
        t_prev = jnp.where(i + 1 < steps, ts[jnp.minimum(i + 1, steps - 1)], -1.0)
        tb = jnp.full((2 * B,), t, jnp.float32)
        eps = denoise(cfg, params, jnp.concatenate([x, x], axis=0), tb, ctx)
        eps_g = eps[B:] + guidance * (eps[:B] - eps[B:])
        ab_t = _alpha_bar(t, cfg.n_steps_train)
        ab_prev = jnp.where(t_prev >= 0, _alpha_bar(t_prev, cfg.n_steps_train), 1.0)
        x0 = jnp.clip((x - jnp.sqrt(1 - ab_t) * eps_g) / jnp.sqrt(ab_t), -3.0, 3.0)
        x_prev = jnp.sqrt(ab_prev) * x0 + jnp.sqrt(1 - ab_prev) * eps_g
        # Replay the known region at the new noise level.
        noise = jax.random.normal(noise_keys[i], x.shape, jnp.float32)
        known_prev = jnp.sqrt(ab_prev) * x0_known + jnp.sqrt(1 - ab_prev) * noise
        return m * x_prev + (1 - m) * known_prev, None

    x, _ = jax.lax.scan(step, x, jnp.arange(steps))
    return jnp.clip(x * 0.5 + 0.5, 0.0, 1.0)


# --------------------------------------------------------------------------- #
# Checkpoint I/O (own safetensors layout, like models/tts.py)
# --------------------------------------------------------------------------- #


def save_diffusion(cfg: DiffusionConfig, params: Params, ckpt_dir: str) -> None:
    from safetensors.numpy import save_file

    os.makedirs(ckpt_dir, exist_ok=True)
    flat = {}
    for k, v in params.items():
        if isinstance(v, dict):
            for k2, v2 in v.items():
                flat[f"{k}.{k2}"] = np.asarray(v2, np.float32)
        else:
            flat[k] = np.asarray(v, np.float32)
    save_file(flat, os.path.join(ckpt_dir, "model.safetensors"))
    with open(os.path.join(ckpt_dir, "config.json"), "w") as f:
        json.dump({"model_type": "localai-dit", **dataclasses.asdict(cfg)}, f, indent=1)


def load_diffusion(ckpt_dir: str) -> tuple[DiffusionConfig, Params]:
    from safetensors import safe_open

    with open(os.path.join(ckpt_dir, "config.json")) as f:
        hf = json.load(f)
    hf.pop("model_type", None)
    cfg = DiffusionConfig(**hf)
    params: Params = {}
    with safe_open(os.path.join(ckpt_dir, "model.safetensors"), framework="numpy") as f:
        for name in f.keys():
            arr = jnp.asarray(f.get_tensor(name))
            if "." in name:
                grp, sub = name.split(".", 1)
                params.setdefault(grp, {})[sub] = arr
            else:
                params[name] = arr
    return cfg, params
