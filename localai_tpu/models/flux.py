"""Flux.1-class rectified-flow MMDiT text→image pipeline in JAX.

Reference: the diffusers backend special-cases the Flux family —
/root/reference/backend/python/diffusers/backend.py:36 (FLUX import),
:218-224 (FluxPipeline / FluxTransformer2DModel routing) and :594-603
(the fp8-quantized transformer path). BASELINE.json's image config names
Flux.1-dev alongside SDXL.

TPU-native shape: the whole sampler is one `lax.scan` over flow-matching
Euler steps; every step is a single fused MMDiT forward — large matmuls on
the MXU in bfloat16-friendly shapes (joint text+image sequence attention,
no CFG doubling: Flux is guidance-distilled, guidance enters as an
embedding). The 2x2 latent patchify turns the 16-channel VAE latent into
64-dim tokens so the attention ops stay dense and static-shaped.

Checkpoint layout (diffusers FluxPipeline save format):
  model_index.json            _class_name: "Flux*"
  text_encoder/               CLIPTextModel (pooled conditioning, 768)
  text_encoder_2/             T5EncoderModel (sequence conditioning, 4096)
  tokenizer/ tokenizer_2/     CLIPTokenizer, T5Tokenizer(Fast)
  transformer/                FluxTransformer2DModel (double+single stream)
  vae/                        AutoencoderKL, 16 latent channels, no quant
                              convs, shift_factor
  scheduler/                  FlowMatchEulerDiscreteScheduler

Weights load into flat name→array dicts 1:1 with the published tensor
names (convs OIHW→HWIO, linears transposed to [in, out] at load) so parity
against the released checkpoints is auditable.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.models.latent_diffusion import (
    CLIPTextConfig,
    VAEConfig,
    _load_safetensors_dir,
    clip_hidden_states,
    clip_pooled_projection,
    get_timestep_embedding,
    vae_decode,
    vae_encode,
)

Params = dict[str, jnp.ndarray]


# --------------------------------------------------------------------------- #
# Configs
# --------------------------------------------------------------------------- #


@dataclass
class T5EncoderConfig:
    """Subset of the HF T5 config the encoder path consumes (T5-XXL for
    Flux: d_model 4096, 24 layers, gated-gelu)."""

    vocab_size: int = 32128
    d_model: int = 4096
    d_kv: int = 64
    d_ff: int = 10240
    num_layers: int = 24
    num_heads: int = 64
    rel_buckets: int = 32
    rel_max_distance: int = 128
    gated_ff: bool = True
    # feed_forward_proj "gated-gelu" selects HF's NewGELU (tanh approx)
    gelu_tanh: bool = True
    eps: float = 1e-6


@dataclass
class FluxTransformerConfig:
    """FluxTransformer2DModel geometry (transformer/config.json)."""

    in_channels: int = 64  # packed: vae latent channels x 2x2 patch
    num_layers: int = 19  # double-stream (joint text/image) blocks
    num_single_layers: int = 38  # single-stream blocks over the fused seq
    attention_head_dim: int = 128
    num_attention_heads: int = 24
    joint_attention_dim: int = 4096  # T5 d_model
    pooled_projection_dim: int = 768  # CLIP hidden size
    guidance_embeds: bool = True  # dev: distilled guidance; schnell: False
    axes_dims_rope: tuple = (16, 56, 56)  # (frame, height, width) rope split
    rope_theta: float = 10000.0

    @property
    def inner_dim(self) -> int:
        return self.num_attention_heads * self.attention_head_dim


@dataclass
class FluxSchedulerConfig:
    """FlowMatchEulerDiscreteScheduler knobs (scheduler_config.json)."""

    shift: float = 3.0
    use_dynamic_shifting: bool = True
    base_shift: float = 0.5
    max_shift: float = 1.15
    base_image_seq_len: int = 256
    max_image_seq_len: int = 4096


@dataclass
class FluxPipelineConfig:
    clip: CLIPTextConfig = field(default_factory=CLIPTextConfig)
    t5: T5EncoderConfig = field(default_factory=T5EncoderConfig)
    transformer: FluxTransformerConfig = field(default_factory=FluxTransformerConfig)
    vae: VAEConfig = field(default_factory=lambda: VAEConfig(
        latent_channels=16, scaling_factor=0.3611, shift_factor=0.1159,
    ))
    sched: FluxSchedulerConfig = field(default_factory=FluxSchedulerConfig)
    t5_max_length: int = 512  # dev; schnell ships 256


# --------------------------------------------------------------------------- #
# T5 encoder (relative-position bias, RMS pre-norms, gated tanh-gelu)
# --------------------------------------------------------------------------- #


def _rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w.astype(x.dtype)


def _t5_bucket(rel_pos: jnp.ndarray, num_buckets: int, max_dist: int) -> jnp.ndarray:
    """Bidirectional T5 relative-position bucketing (HF modeling_t5.py
    _relative_position_bucket semantics)."""
    nb = num_buckets // 2
    buckets = (rel_pos > 0).astype(jnp.int32) * nb
    n = jnp.abs(rel_pos)
    max_exact = nb // 2
    large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / math.log(max_dist / max_exact) * (nb - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, nb - 1)
    return buckets + jnp.where(n < max_exact, n, large)


def t5_encode(cfg: T5EncoderConfig, p: Params, ids: jnp.ndarray,
              mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """ids [B, T] int32 (pad = 0) → hidden [B, T, d_model].

    T5 semantics: RMS pre-norms, un-scaled attention logits, one relative-
    position bias table (block 0) shared by all layers. Weights are
    pre-transposed to [in, out] at load (see load_flux_pipeline)."""
    h = p["shared.weight"][ids]
    B, T, _ = h.shape
    H, Dk = cfg.num_heads, cfg.d_kv

    rel = jnp.arange(T)[None, :] - jnp.arange(T)[:, None]  # memory - query
    bucket = _t5_bucket(rel, cfg.rel_buckets, cfg.rel_max_distance)
    table = p["encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"]
    bias = table[bucket].transpose(2, 0, 1)[None].astype(jnp.float32)  # [1,H,T,T]
    if mask is not None:
        bias = bias + (1.0 - mask[:, None, None, :].astype(jnp.float32)) * -1e9

    for i in range(cfg.num_layers):
        pre = f"encoder.block.{i}"
        x = _rms_norm(h, p[f"{pre}.layer.0.layer_norm.weight"], cfg.eps)
        q = (x @ p[f"{pre}.layer.0.SelfAttention.q.weight"]).reshape(B, T, H, Dk)
        k = (x @ p[f"{pre}.layer.0.SelfAttention.k.weight"]).reshape(B, T, H, Dk)
        v = (x @ p[f"{pre}.layer.0.SelfAttention.v.weight"]).reshape(B, T, H, Dk)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) + bias
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, T, H * Dk)
        h = h + attn @ p[f"{pre}.layer.0.SelfAttention.o.weight"]

        x = _rms_norm(h, p[f"{pre}.layer.1.layer_norm.weight"], cfg.eps)
        if cfg.gated_ff:
            y = jax.nn.gelu(x @ p[f"{pre}.layer.1.DenseReluDense.wi_0.weight"],
                            approximate=cfg.gelu_tanh)
            y = y * (x @ p[f"{pre}.layer.1.DenseReluDense.wi_1.weight"])
        else:
            y = jax.nn.relu(x @ p[f"{pre}.layer.1.DenseReluDense.wi.weight"])
        h = h + y @ p[f"{pre}.layer.1.DenseReluDense.wo.weight"]
    return _rms_norm(h, p["encoder.final_layer_norm.weight"], cfg.eps)


# --------------------------------------------------------------------------- #
# Rotary embedding over (frame, row, col) position ids
# --------------------------------------------------------------------------- #


def rope_cos_sin(ids: jnp.ndarray, axes_dims: tuple, theta: float
                 ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """ids [N, len(axes_dims)] → (cos [N, D/2], sin [N, D/2]) with
    D = sum(axes_dims); per-axis frequency ladders concatenated (diffusers
    FluxPosEmbed / get_1d_rotary_pos_embed with repeat_interleave_real, kept
    un-interleaved here — the rotation below indexes pairs directly)."""
    parts_c, parts_s = [], []
    for a, d in enumerate(axes_dims):
        freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
        ang = ids[:, a].astype(jnp.float32)[:, None] * freqs[None, :]
        parts_c.append(jnp.cos(ang))
        parts_s.append(jnp.sin(ang))
    return jnp.concatenate(parts_c, axis=-1), jnp.concatenate(parts_s, axis=-1)


def _apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x [B, H, N, D] with interleaved pairs (x0, x1): standard complex
    rotation (diffusers apply_rotary_emb, use_real_unbind_dim=-1)."""
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    out_even = x1 * cos - x2 * sin
    out_odd = x2 * cos + x1 * sin
    return jnp.stack([out_even, out_odd], axis=-1).reshape(x.shape).astype(x.dtype)


# --------------------------------------------------------------------------- #
# MMDiT transformer
# --------------------------------------------------------------------------- #


def _ln(x: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """LayerNorm without affine (Flux uses elementwise_affine=False)."""
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    return ((xf - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype)


def _lin(x: jnp.ndarray, p: Params, name: str) -> jnp.ndarray:
    y = x @ p[f"{name}.weight"].astype(x.dtype)
    b = p.get(f"{name}.bias")
    return y if b is None else y + b.astype(x.dtype)


def _qkv_heads(x: jnp.ndarray, p: Params, pre: str, names: tuple,
               heads: int, norm_names: tuple, eps: float = 1e-6):
    """Project to per-head q/k/v with Flux's per-head-dim RMS q/k norms."""
    B, N, _ = x.shape
    out = []
    for name, nname in zip(names, norm_names):
        y = _lin(x, p, f"{pre}.{name}")
        y = y.reshape(B, N, heads, -1).transpose(0, 2, 1, 3)  # [B,H,N,D]
        if nname is not None:
            y = _rms_norm(y, p[f"{pre}.{nname}.weight"], eps)
        out.append(y)
    return out


def _joint_attention(q, k, v) -> jnp.ndarray:
    """[B,H,N,D] x3 → [B,N,H*D]; fp32 softmax."""
    B, H, N, D = q.shape
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / math.sqrt(D)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return out.transpose(0, 2, 1, 3).reshape(B, N, H * D)


def _gelu_tanh(x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.gelu(x, approximate=True)


def flux_forward(
    cfg: FluxTransformerConfig,
    p: Params,
    img_tokens: jnp.ndarray,  # [B, L, in_channels] packed 2x2 latents
    txt_hidden: jnp.ndarray,  # [B, T, joint_attention_dim] T5 states
    pooled: jnp.ndarray,  # [B, pooled_projection_dim] CLIP pooled
    timestep: jnp.ndarray,  # [B] in [0, 1] (sigma; scaled x1000 inside)
    img_ids: jnp.ndarray,  # [L, 3] (0, row, col)
    txt_ids: Optional[jnp.ndarray] = None,  # [T, 3]; zeros if None
    guidance: Optional[jnp.ndarray] = None,  # [B]; required iff guidance_embeds
) -> jnp.ndarray:
    """FluxTransformer2DModel forward → velocity prediction [B, L, in_ch]."""
    H = cfg.num_attention_heads
    B, T = txt_hidden.shape[:2]
    L = img_tokens.shape[1]

    h = _lin(img_tokens, p, "x_embedder")
    ctx = _lin(txt_hidden.astype(h.dtype), p, "context_embedder")

    # Combined timestep (+guidance) + pooled-text conditioning vector.
    temb = get_timestep_embedding(
        timestep.astype(jnp.float32) * 1000.0, 256, flip_sin_to_cos=True,
    ).astype(h.dtype)
    temb = _lin(temb, p, "time_text_embed.timestep_embedder.linear_1")
    temb = _lin(jax.nn.silu(temb), p, "time_text_embed.timestep_embedder.linear_2")
    if cfg.guidance_embeds:
        g = get_timestep_embedding(
            guidance.astype(jnp.float32) * 1000.0, 256, flip_sin_to_cos=True,
        ).astype(h.dtype)
        g = _lin(g, p, "time_text_embed.guidance_embedder.linear_1")
        g = _lin(jax.nn.silu(g), p, "time_text_embed.guidance_embedder.linear_2")
        temb = temb + g
    pe = _lin(pooled.astype(h.dtype), p, "time_text_embed.text_embedder.linear_1")
    pe = _lin(jax.nn.silu(pe), p, "time_text_embed.text_embedder.linear_2")
    temb = temb + pe
    semb = jax.nn.silu(temb)

    if txt_ids is None:
        txt_ids = jnp.zeros((T, 3), jnp.float32)
    ids = jnp.concatenate([txt_ids, img_ids.astype(txt_ids.dtype)], axis=0)
    cos, sin = rope_cos_sin(ids, cfg.axes_dims_rope, cfg.rope_theta)
    cos, sin = cos[None, None], sin[None, None]  # broadcast over [B, H]

    # --- double-stream (joint) blocks: text and image keep separate
    # projections/FFNs but attend over the concatenated sequence.
    for i in range(cfg.num_layers):
        pre = f"transformer_blocks.{i}"
        mod = _lin(semb, p, f"{pre}.norm1.linear")
        sh_a, sc_a, g_a, sh_m, sc_m, g_m = jnp.split(mod, 6, axis=-1)
        mod_c = _lin(semb, p, f"{pre}.norm1_context.linear")
        csh_a, csc_a, cg_a, csh_m, csc_m, cg_m = jnp.split(mod_c, 6, axis=-1)

        nh = _ln(h) * (1 + sc_a[:, None]) + sh_a[:, None]
        nc = _ln(ctx) * (1 + csc_a[:, None]) + csh_a[:, None]

        q, k, v = _qkv_heads(nh, p, f"{pre}.attn", ("to_q", "to_k", "to_v"),
                             H, ("norm_q", "norm_k", None))
        cq, ck, cv = _qkv_heads(
            nc, p, f"{pre}.attn", ("add_q_proj", "add_k_proj", "add_v_proj"),
            H, ("norm_added_q", "norm_added_k", None),
        )
        # text first, then image (diffusers FluxAttnProcessor order)
        q = _apply_rope(jnp.concatenate([cq, q], axis=2), cos, sin)
        k = _apply_rope(jnp.concatenate([ck, k], axis=2), cos, sin)
        v = jnp.concatenate([cv, v], axis=2)
        attn = _joint_attention(q, k, v)
        a_ctx, a_img = attn[:, :T], attn[:, T:]

        h = h + g_a[:, None] * _lin(a_img, p, f"{pre}.attn.to_out.0")
        nh2 = _ln(h) * (1 + sc_m[:, None]) + sh_m[:, None]
        ff = _lin(_gelu_tanh(_lin(nh2, p, f"{pre}.ff.net.0.proj")), p, f"{pre}.ff.net.2")
        h = h + g_m[:, None] * ff

        ctx = ctx + cg_a[:, None] * _lin(a_ctx, p, f"{pre}.attn.to_add_out")
        nc2 = _ln(ctx) * (1 + csc_m[:, None]) + csh_m[:, None]
        cff = _lin(_gelu_tanh(_lin(nc2, p, f"{pre}.ff_context.net.0.proj")),
                   p, f"{pre}.ff_context.net.2")
        ctx = ctx + cg_m[:, None] * cff

    # --- single-stream blocks over the fused [text; image] sequence with a
    # parallel attention+MLP trunk (proj_out consumes both).
    x = jnp.concatenate([ctx, h], axis=1)
    for i in range(cfg.num_single_layers):
        pre = f"single_transformer_blocks.{i}"
        mod = _lin(semb, p, f"{pre}.norm.linear")
        sh, sc, gate = jnp.split(mod, 3, axis=-1)
        nx = _ln(x) * (1 + sc[:, None]) + sh[:, None]
        q, k, v = _qkv_heads(nx, p, f"{pre}.attn", ("to_q", "to_k", "to_v"),
                             H, ("norm_q", "norm_k", None))
        q = _apply_rope(q, cos, sin)
        k = _apply_rope(k, cos, sin)
        attn = _joint_attention(q, k, v)
        mlp = _gelu_tanh(_lin(nx, p, f"{pre}.proj_mlp"))
        x = x + gate[:, None] * _lin(
            jnp.concatenate([attn, mlp], axis=-1), p, f"{pre}.proj_out"
        )

    h = x[:, T:]
    # AdaLayerNormContinuous: chunk order is (scale, shift) — unlike the
    # zero-init block modulations above.
    mod = _lin(semb, p, "norm_out.linear")
    sc, sh = jnp.split(mod, 2, axis=-1)
    h = _ln(h) * (1 + sc[:, None]) + sh[:, None]
    return _lin(h, p, "proj_out")


# --------------------------------------------------------------------------- #
# Latent packing + flow-matching schedule
# --------------------------------------------------------------------------- #


def pack_latents(lat: jnp.ndarray) -> jnp.ndarray:
    """NHWC [B, h, w, C] → [B, (h/2)(w/2), 4C]; feature order (c, dh, dw)
    matches the torch NCHW view/permute in FluxPipeline._pack_latents."""
    B, Hh, Ww, C = lat.shape
    x = lat.reshape(B, Hh // 2, 2, Ww // 2, 2, C)
    x = x.transpose(0, 1, 3, 5, 2, 4)  # [B, h/2, w/2, C, 2, 2]
    return x.reshape(B, (Hh // 2) * (Ww // 2), C * 4)


def unpack_latents(tokens: jnp.ndarray, lat_h: int, lat_w: int) -> jnp.ndarray:
    """[B, L, 4C] → NHWC [B, lat_h, lat_w, C]."""
    B, L, F = tokens.shape
    C = F // 4
    x = tokens.reshape(B, lat_h // 2, lat_w // 2, C, 2, 2)
    x = x.transpose(0, 1, 4, 2, 5, 3)  # [B, h/2, 2, w/2, 2, C]
    return x.reshape(B, lat_h, lat_w, C)


def image_ids(lat_h: int, lat_w: int) -> np.ndarray:
    """[L, 3] (0, row, col) position ids for the packed latent grid."""
    ids = np.zeros((lat_h // 2, lat_w // 2, 3), np.float32)
    ids[..., 1] = np.arange(lat_h // 2)[:, None]
    ids[..., 2] = np.arange(lat_w // 2)[None, :]
    return ids.reshape(-1, 3)


def flow_sigmas(sched: FluxSchedulerConfig, steps: int, image_seq_len: int
                ) -> np.ndarray:
    """[steps + 1] descending sigmas (terminal 0) for the flow-matching
    Euler sampler; dynamic time-shift by image sequence length (dev) or the
    static `shift` (schnell), matching FlowMatchEulerDiscreteScheduler."""
    sigmas = np.linspace(1.0, 1.0 / steps, steps, dtype=np.float64)
    if sched.use_dynamic_shifting:
        m = (sched.max_shift - sched.base_shift) / (
            sched.max_image_seq_len - sched.base_image_seq_len
        )
        b = sched.base_shift - m * sched.base_image_seq_len
        mu = image_seq_len * m + b
        sigmas = np.exp(mu) / (np.exp(mu) + (1.0 / sigmas - 1.0))
    else:
        sigmas = sched.shift * sigmas / (1.0 + (sched.shift - 1.0) * sigmas)
    return np.append(sigmas, 0.0).astype(np.float32)


# --------------------------------------------------------------------------- #
# Generation
# --------------------------------------------------------------------------- #


def generate(
    cfg: FluxPipelineConfig,
    params: dict[str, Params],  # {"clip", "t5", "transformer", "vae"}
    clip_ids: jnp.ndarray,  # [B, 77]
    t5_ids: jnp.ndarray,  # [B, T]
    key: jnp.ndarray,
    steps: int = 20,
    guidance: float = 3.5,
    height: int = 1024,
    width: int = 1024,
    init_image: Optional[jnp.ndarray] = None,  # [B, H, W, 3] in [0,1]
    strength: float = 0.8,
) -> jnp.ndarray:
    """Full Flux text→image; returns [B, H, W, 3] float32 in [0,1].
    jit-able: shapes depend only on (B, T, steps, H, W, strength)."""
    B = clip_ids.shape[0]
    vs = cfg.vae.spatial_scale
    lat_h, lat_w = height // vs, width // vs
    L = (lat_h // 2) * (lat_w // 2)

    _, fin = clip_hidden_states(cfg.clip, params["clip"], clip_ids)
    pooled = clip_pooled_projection(cfg.clip, params["clip"], clip_ids, fin)
    txt = t5_encode(cfg.t5, params["t5"], t5_ids)

    img_ids = jnp.asarray(image_ids(lat_h, lat_w))
    txt_ids = jnp.zeros((t5_ids.shape[1], 3), jnp.float32)
    sigmas = jnp.asarray(flow_sigmas(cfg.sched, steps, L))

    noise = jax.random.normal(key, (B, lat_h, lat_w, cfg.vae.latent_channels),
                              jnp.float32)
    x = pack_latents(noise)
    i0 = 0
    if init_image is not None:
        # img2img: truncate the schedule and start from the re-noised source
        # (FluxImg2ImgPipeline: x = (1-σ)·x0 + σ·noise at the entry sigma).
        i0 = steps - max(1, min(steps, int(round(steps * strength))))
        lat0 = vae_encode(cfg.vae, params["vae"], init_image)
        # vae_encode returns mean*scale; Flux wants (mean - shift)*scale
        lat0 = lat0 - cfg.vae.shift_factor * cfg.vae.scaling_factor
        x0 = pack_latents(lat0)
        s0 = sigmas[i0]
        x = (1.0 - s0) * x0 + s0 * x

    gvec = jnp.full((B,), guidance, jnp.float32) if cfg.transformer.guidance_embeds else None

    def step(x, i):
        t = jnp.full((B,), sigmas[i], jnp.float32)
        v = flux_forward(
            cfg.transformer, params["transformer"], x.astype(jnp.float32),
            txt, pooled, t, img_ids, txt_ids, gvec,
        )
        return x + (sigmas[i + 1] - sigmas[i]) * v.astype(jnp.float32), None

    x, _ = jax.lax.scan(step, x, jnp.arange(i0, steps))
    lat = unpack_latents(x, lat_h, lat_w)
    lat = lat / cfg.vae.scaling_factor + cfg.vae.shift_factor
    return vae_decode(cfg.vae, params["vae"], lat)


# --------------------------------------------------------------------------- #
# Checkpoint loading (diffusers FluxPipeline layout)
# --------------------------------------------------------------------------- #


def is_flux_dir(path: str) -> bool:
    idx = os.path.join(path, "model_index.json")
    if not os.path.isfile(idx):
        return False
    try:
        with open(idx) as f:
            return "flux" in str(json.load(f).get("_class_name", "")).lower()
    except (OSError, ValueError):
        return False


_NO_TRANSPOSE = ("shared.weight", "relative_attention_bias",
                 "token_embedding", "position_embedding")


def _prep(tensors: dict[str, np.ndarray], dtype) -> Params:
    """torch layouts → ours: convs OIHW→HWIO, linears [out,in]→[in,out];
    embedding tables keep their lookup orientation."""
    out: Params = {}
    for name, arr in tensors.items():
        if arr.ndim == 4:
            arr = arr.transpose(2, 3, 1, 0)
        elif (arr.ndim == 2 and name.endswith(".weight")
              and not any(t in name for t in _NO_TRANSPOSE)):
            arr = arr.T
        out[name] = jnp.asarray(np.ascontiguousarray(arr), dtype)
    return out


def _cfg_json(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def load_flux_pipeline(ckpt_dir: str, dtype=jnp.bfloat16):
    """(FluxPipelineConfig, params, (clip_tokenizer, t5_tokenizer)).

    bfloat16 by default: Flux.1-dev is a 12B MMDiT + 4.8B T5-XXL — fp32 is
    ~68 GB of weights and can never fit single-chip HBM, while the module's
    compute is bfloat16-friendly throughout. Pass jnp.float32 explicitly
    for full-precision parity work (the reference-comparison tests do)."""
    tc = _cfg_json(os.path.join(ckpt_dir, "text_encoder", "config.json"))
    t5c = _cfg_json(os.path.join(ckpt_dir, "text_encoder_2", "config.json"))
    xc = _cfg_json(os.path.join(ckpt_dir, "transformer", "config.json"))
    vc = _cfg_json(os.path.join(ckpt_dir, "vae", "config.json"))
    sp = os.path.join(ckpt_dir, "scheduler", "scheduler_config.json")
    sc = _cfg_json(sp) if os.path.isfile(sp) else {}

    ff_proj = t5c.get("feed_forward_proj", "gated-gelu")
    cfg = FluxPipelineConfig(
        clip=CLIPTextConfig(
            vocab_size=tc.get("vocab_size", 49408),
            hidden_size=tc.get("hidden_size", 768),
            intermediate_size=tc.get("intermediate_size", 3072),
            num_hidden_layers=tc.get("num_hidden_layers", 12),
            num_attention_heads=tc.get("num_attention_heads", 12),
            max_position_embeddings=tc.get("max_position_embeddings", 77),
            hidden_act=tc.get("hidden_act", "quick_gelu"),
            eos_token_id=tc.get("eos_token_id", 49407),
        ),
        t5=T5EncoderConfig(
            vocab_size=t5c.get("vocab_size", 32128),
            d_model=t5c.get("d_model", 4096),
            d_kv=t5c.get("d_kv", 64),
            d_ff=t5c.get("d_ff", 10240),
            num_layers=t5c.get("num_layers", 24),
            num_heads=t5c.get("num_heads", 64),
            rel_buckets=t5c.get("relative_attention_num_buckets", 32),
            rel_max_distance=t5c.get("relative_attention_max_distance", 128),
            gated_ff="gated" in ff_proj,
            gelu_tanh="gelu" in ff_proj,
            eps=t5c.get("layer_norm_epsilon", 1e-6),
        ),
        transformer=FluxTransformerConfig(
            in_channels=xc.get("in_channels", 64),
            num_layers=xc.get("num_layers", 19),
            num_single_layers=xc.get("num_single_layers", 38),
            attention_head_dim=xc.get("attention_head_dim", 128),
            num_attention_heads=xc.get("num_attention_heads", 24),
            joint_attention_dim=xc.get("joint_attention_dim", 4096),
            pooled_projection_dim=xc.get("pooled_projection_dim", 768),
            guidance_embeds=xc.get("guidance_embeds", True),
            axes_dims_rope=tuple(xc.get("axes_dims_rope", (16, 56, 56))),
        ),
        vae=VAEConfig(
            in_channels=vc.get("in_channels", 3),
            out_channels=vc.get("out_channels", 3),
            latent_channels=vc.get("latent_channels", 16),
            block_out_channels=tuple(vc.get("block_out_channels", (128, 256, 512, 512))),
            layers_per_block=vc.get("layers_per_block", 2),
            norm_num_groups=vc.get("norm_num_groups", 32),
            scaling_factor=vc.get("scaling_factor", 0.3611),
            shift_factor=vc.get("shift_factor", 0.1159) or 0.0,
        ),
        sched=FluxSchedulerConfig(
            shift=sc.get("shift", 3.0),
            use_dynamic_shifting=sc.get("use_dynamic_shifting", True),
            base_shift=sc.get("base_shift", 0.5),
            max_shift=sc.get("max_shift", 1.15),
            base_image_seq_len=sc.get("base_image_seq_len", 256),
            max_image_seq_len=sc.get("max_image_seq_len", 4096),
        ),
    )

    params = {
        "clip": _prep(_load_safetensors_dir(os.path.join(ckpt_dir, "text_encoder")), dtype),
        "t5": _prep(_load_safetensors_dir(os.path.join(ckpt_dir, "text_encoder_2")), dtype),
        "transformer": _prep(
            _load_safetensors_dir(os.path.join(ckpt_dir, "transformer")), dtype
        ),
        "vae": _prep(_load_safetensors_dir(os.path.join(ckpt_dir, "vae")), dtype),
    }

    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(
        os.path.join(ckpt_dir, "tokenizer"), local_files_only=True
    )
    tok2 = AutoTokenizer.from_pretrained(
        os.path.join(ckpt_dir, "tokenizer_2"), local_files_only=True
    )
    t5_max = 512
    tk2 = os.path.join(ckpt_dir, "tokenizer_2", "tokenizer_config.json")
    if os.path.isfile(tk2):
        t5_max = int(_cfg_json(tk2).get("model_max_length", 512) or 512)
    cfg.t5_max_length = min(t5_max, 512)
    return cfg, params, (tok, tok2)
