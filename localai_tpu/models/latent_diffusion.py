"""Latent diffusion (Stable-Diffusion-1.5-class) in JAX, loading real HF
diffusers-layout checkpoints.

Reference: the diffusers backend's dynamic pipeline registry
(/root/reference/backend/python/diffusers/backend.py:27-120) serves SD/SDXL
class models; the GGML SD backend (backend/go/stablediffusion-ggml) covers
the same ground natively. TPU-native shape: the three submodels (CLIP text
encoder, UNet2DCondition, VAE) are plain jitted functions over NHWC arrays —
convs lower to MXU through XLA, the denoise step jits once per (batch, size)
and lax.scan's over scheduler steps on device.

Checkpoint layout (diffusers): model_index.json + {text_encoder,unet,vae}/
config.json + *.safetensors with torch names. Weights load into flat
name→array dicts (1:1 with the published names, so parity is auditable);
convs transpose OIHW→HWIO, linears transpose to [in, out] at load.

Schedulers: DDIM (eta=0) and Euler-ancestral, both over the scaled-linear
beta schedule the SD family trains with.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger("localai_tpu.latent_diffusion")

Params = dict[str, jnp.ndarray]

# The serving scheduler surface (reference: diffusers backend.py:100-168
# A1111 name mapping). "_karras" suffix and "k_" prefix both select Karras
# sigma spacing for the k-diffusion family.
K_SCHEDULERS = ("euler", "euler_a", "dpmpp_2m", "heun", "lms", "dpm_2",
                "dpm_2_a", "dpmpp_sde", "dpmpp_2m_sde")
T_SCHEDULERS = ("ddim", "pndm", "unipc")
SUPPORTED_SCHEDULERS = frozenset(
    T_SCHEDULERS + K_SCHEDULERS
    + tuple(f"{s}_karras" for s in K_SCHEDULERS)
    + tuple(f"k_{s}" for s in K_SCHEDULERS)
)


# --------------------------------------------------------------------------- #
# Configs (subset of the diffusers configs we consume)
# --------------------------------------------------------------------------- #


@dataclass
class CLIPTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 77
    hidden_act: str = "quick_gelu"
    layer_norm_eps: float = 1e-5
    projection_dim: int = 0  # >0: CLIPTextModelWithProjection (SDXL encoder 2)
    eos_token_id: int = 49407  # pooling position (HF CLIP semantics)


@dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    sample_size: int = 64
    block_out_channels: tuple = (320, 640, 1280, 1280)
    down_block_types: tuple = (
        "CrossAttnDownBlock2D", "CrossAttnDownBlock2D",
        "CrossAttnDownBlock2D", "DownBlock2D",
    )
    up_block_types: tuple = (
        "UpBlock2D", "CrossAttnUpBlock2D",
        "CrossAttnUpBlock2D", "CrossAttnUpBlock2D",
    )
    layers_per_block: int = 2
    attention_head_dim: Any = 8  # int or per-block list
    cross_attention_dim: int = 768
    norm_num_groups: int = 32
    flip_sin_to_cos: bool = True
    freq_shift: int = 0
    # SDXL: transformer depth per level ([1, 2, 10] for the base model) and
    # the "text_time" micro-conditioning pathway (pooled text embedding +
    # six size/crop ids fourier-embedded into the time embedding).
    transformer_layers_per_block: Any = 1  # int or per-block list
    addition_embed_type: str = ""  # "" | "text_time"
    addition_time_embed_dim: int = 256
    projection_class_embeddings_input_dim: int = 0

    def heads_for(self, block_idx: int) -> int:
        # diffusers quirk: UNet2DConditionModel's `attention_head_dim` is
        # used as the NUMBER of heads (upstream keeps the misnomer for
        # back-compat; SD1.5's 8 and SDXL's [5,10,20] are head counts).
        if isinstance(self.attention_head_dim, (list, tuple)):
            return int(self.attention_head_dim[block_idx])
        return int(self.attention_head_dim)

    def tx_depth_for(self, block_idx: int) -> int:
        if isinstance(self.transformer_layers_per_block, (list, tuple)):
            return int(self.transformer_layers_per_block[block_idx])
        return int(self.transformer_layers_per_block)


@dataclass
class VAEConfig:
    in_channels: int = 3
    out_channels: int = 3
    latent_channels: int = 4
    block_out_channels: tuple = (128, 256, 512, 512)
    layers_per_block: int = 2
    norm_num_groups: int = 32
    scaling_factor: float = 0.18215
    # Flux-class VAEs recenter latents: z_model = (z - shift) * scale.
    shift_factor: float = 0.0

    @property
    def spatial_scale(self) -> int:
        """Pixel-per-latent factor: one 2x resampler between each block
        pair (8 for the SD family's 4-block VAE)."""
        return 2 ** (len(self.block_out_channels) - 1)


@dataclass
class SDPipelineConfig:
    text: CLIPTextConfig = field(default_factory=CLIPTextConfig)
    unet: UNetConfig = field(default_factory=UNetConfig)
    vae: VAEConfig = field(default_factory=VAEConfig)
    # SDXL second text encoder (OpenCLIP bigG class); None for the SD family.
    text2: Optional[CLIPTextConfig] = None
    # scaled-linear schedule (SD family)
    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012
    prediction_type: str = "epsilon"  # | "v_prediction"

    @property
    def is_xl(self) -> bool:
        return self.text2 is not None


# --------------------------------------------------------------------------- #
# Primitive layers (NHWC)
# --------------------------------------------------------------------------- #


def _conv(x: jnp.ndarray, w: jnp.ndarray, b: jnp.ndarray,
          stride: int = 1, pad: int = 1) -> jnp.ndarray:
    y = jax.lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=(stride, stride),
        padding=[(pad, pad), (pad, pad)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + b.astype(x.dtype)


def _linear(x: jnp.ndarray, p: Params, name: str) -> jnp.ndarray:
    return x @ p[f"{name}.weight"].astype(x.dtype) + p[f"{name}.bias"].astype(x.dtype)


def _group_norm(x: jnp.ndarray, w, b, groups: int = 32, eps: float = 1e-6) -> jnp.ndarray:
    c = x.shape[-1]
    g = groups
    # normalize over all spatial positions and the in-group channels
    xf = x.astype(jnp.float32).reshape(x.shape[0], -1, g, c // g)
    mean = xf.mean(axis=(1, 3), keepdims=True)
    var = xf.var(axis=(1, 3), keepdims=True)
    xn = (xf - mean) * jax.lax.rsqrt(var + eps)
    xn = xn.reshape(x.shape).astype(x.dtype)
    return xn * w.astype(x.dtype) + b.astype(x.dtype)


def _layer_norm(x: jnp.ndarray, w, b, eps: float = 1e-5) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y.astype(x.dtype)) * w.astype(x.dtype) + b.astype(x.dtype)


def _attention(q, k, v, heads: int) -> jnp.ndarray:
    """q [B, Nq, C], k/v [B, Nk, C] → [B, Nq, C]."""
    B, Nq, C = q.shape
    hd = C // heads
    q = q.reshape(B, Nq, heads, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, -1, heads, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, -1, heads, hd).transpose(0, 2, 1, 3)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / np.sqrt(hd)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return out.transpose(0, 2, 1, 3).reshape(B, Nq, C)


def get_timestep_embedding(t: jnp.ndarray, dim: int,
                           flip_sin_to_cos: bool = True,
                           freq_shift: float = 0.0) -> jnp.ndarray:
    """diffusers get_timestep_embedding semantics (t [B] → [B, dim])."""
    half = dim // 2
    exponent = -np.log(10000.0) * jnp.arange(half, dtype=jnp.float32)
    exponent = exponent / (half - freq_shift)
    emb = jnp.exp(exponent)[None, :] * t.astype(jnp.float32)[:, None]
    sin, cos = jnp.sin(emb), jnp.cos(emb)
    return jnp.concatenate([cos, sin] if flip_sin_to_cos else [sin, cos], axis=-1)


# --------------------------------------------------------------------------- #
# CLIP text encoder (causal; quick-gelu)
# --------------------------------------------------------------------------- #


def clip_hidden_states(cfg: CLIPTextConfig, p: Params,
                       ids: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[B, 77] int32 → (penultimate hidden [B, 77, C], final normed [B, 77, C]).

    The penultimate state (hidden_states[-2], no final norm) is what SDXL
    conditions on from both encoders; the final normed state is SD1.5's
    context and the source of the pooled projection."""
    B, S = ids.shape
    h = p["text_model.embeddings.token_embedding.weight"][ids]
    h = h + p["text_model.embeddings.position_embedding.weight"][None, :S]
    mask = jnp.triu(jnp.full((S, S), -jnp.inf, jnp.float32), k=1)

    def act(x):
        if cfg.hidden_act == "quick_gelu":
            return x * jax.nn.sigmoid(1.702 * x)
        return jax.nn.gelu(x, approximate=False)

    penultimate = h
    for i in range(cfg.num_hidden_layers):
        if i == cfg.num_hidden_layers - 1:
            penultimate = h  # hidden_states[-2]: before the last layer
        pre = f"text_model.encoder.layers.{i}"
        r = h
        h = _layer_norm(h, p[f"{pre}.layer_norm1.weight"], p[f"{pre}.layer_norm1.bias"],
                        cfg.layer_norm_eps)
        q = _linear(h, p, f"{pre}.self_attn.q_proj")
        k = _linear(h, p, f"{pre}.self_attn.k_proj")
        v = _linear(h, p, f"{pre}.self_attn.v_proj")
        hd = cfg.hidden_size // cfg.num_attention_heads
        qh = q.reshape(B, S, -1, hd).transpose(0, 2, 1, 3)
        kh = k.reshape(B, S, -1, hd).transpose(0, 2, 1, 3)
        vh = v.reshape(B, S, -1, hd).transpose(0, 2, 1, 3)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh).astype(jnp.float32) / np.sqrt(hd)
        probs = jax.nn.softmax(scores + mask, axis=-1).astype(vh.dtype)
        a = jnp.einsum("bhqk,bhkd->bhqd", probs, vh).transpose(0, 2, 1, 3).reshape(B, S, -1)
        h = r + _linear(a, p, f"{pre}.self_attn.out_proj")
        r = h
        h = _layer_norm(h, p[f"{pre}.layer_norm2.weight"], p[f"{pre}.layer_norm2.bias"],
                        cfg.layer_norm_eps)
        h = r + _linear(act(_linear(h, p, f"{pre}.mlp.fc1")), p, f"{pre}.mlp.fc2")
    final = _layer_norm(
        h, p["text_model.final_layer_norm.weight"],
        p["text_model.final_layer_norm.bias"], cfg.layer_norm_eps,
    )
    return penultimate, final


def clip_encode(cfg: CLIPTextConfig, p: Params, ids: jnp.ndarray) -> jnp.ndarray:
    """[B, 77] int32 → last hidden state [B, 77, C] (what SD conditions on)."""
    return clip_hidden_states(cfg, p, ids)[1]


def clip_pooled_projection(cfg: CLIPTextConfig, p: Params, ids: jnp.ndarray,
                           final: jnp.ndarray) -> jnp.ndarray:
    """CLIPTextModelWithProjection pooling: the first EOS position's final
    hidden state through text_projection (no bias). HF semantics: legacy
    configs (eos_token_id == 2) take argmax of the ids (EOS is the highest
    id in the CLIP vocab); otherwise the first eos_token_id occurrence."""
    if cfg.eos_token_id == 2:
        eos_pos = jnp.argmax(ids, axis=-1)
    else:
        eos_pos = jnp.argmax((ids == cfg.eos_token_id).astype(jnp.int32), axis=-1)
    pooled = jnp.take_along_axis(final, eos_pos[:, None, None], axis=1)[:, 0]
    if "text_projection.weight" in p:
        pooled = pooled @ p["text_projection.weight"].astype(pooled.dtype)
    return pooled


# --------------------------------------------------------------------------- #
# UNet2DCondition
# --------------------------------------------------------------------------- #


def _resnet(p: Params, pre: str, x: jnp.ndarray, temb: jnp.ndarray,
            groups: int) -> jnp.ndarray:
    h = _group_norm(x, p[f"{pre}.norm1.weight"], p[f"{pre}.norm1.bias"], groups)
    h = _conv(jax.nn.silu(h), p[f"{pre}.conv1.weight"], p[f"{pre}.conv1.bias"])
    if f"{pre}.time_emb_proj.weight" in p:
        t = _linear(jax.nn.silu(temb), p, f"{pre}.time_emb_proj")
        h = h + t[:, None, None, :]
    h = _group_norm(h, p[f"{pre}.norm2.weight"], p[f"{pre}.norm2.bias"], groups)
    h = _conv(jax.nn.silu(h), p[f"{pre}.conv2.weight"], p[f"{pre}.conv2.bias"])
    if f"{pre}.conv_shortcut.weight" in p:
        x = _conv(x, p[f"{pre}.conv_shortcut.weight"], p[f"{pre}.conv_shortcut.bias"], pad=0)
    return x + h


def _basic_transformer(p: Params, pre: str, h: jnp.ndarray, ctx: jnp.ndarray,
                       heads: int) -> jnp.ndarray:
    # self-attention
    r = h
    n = _layer_norm(h, p[f"{pre}.norm1.weight"], p[f"{pre}.norm1.bias"])
    q = n @ p[f"{pre}.attn1.to_q.weight"].astype(h.dtype)
    k = n @ p[f"{pre}.attn1.to_k.weight"].astype(h.dtype)
    v = n @ p[f"{pre}.attn1.to_v.weight"].astype(h.dtype)
    h = r + _linear(_attention(q, k, v, heads), p, f"{pre}.attn1.to_out.0")
    # cross-attention over text states
    r = h
    n = _layer_norm(h, p[f"{pre}.norm2.weight"], p[f"{pre}.norm2.bias"])
    q = n @ p[f"{pre}.attn2.to_q.weight"].astype(h.dtype)
    k = ctx @ p[f"{pre}.attn2.to_k.weight"].astype(ctx.dtype)
    v = ctx @ p[f"{pre}.attn2.to_v.weight"].astype(ctx.dtype)
    h = r + _linear(_attention(q, k.astype(h.dtype), v.astype(h.dtype), heads),
                    p, f"{pre}.attn2.to_out.0")
    # geglu feed-forward
    r = h
    n = _layer_norm(h, p[f"{pre}.norm3.weight"], p[f"{pre}.norm3.bias"])
    proj = _linear(n, p, f"{pre}.ff.net.0.proj")
    a, gate = jnp.split(proj, 2, axis=-1)
    return r + _linear(a * jax.nn.gelu(gate), p, f"{pre}.ff.net.2")


def _spatial_transformer(p: Params, pre: str, x: jnp.ndarray, ctx: jnp.ndarray,
                         heads: int, groups: int, depth: int = 1) -> jnp.ndarray:
    B, H, W, C = x.shape
    r = x
    h = _group_norm(x, p[f"{pre}.norm.weight"], p[f"{pre}.norm.bias"], groups)
    use_linear = p[f"{pre}.proj_in.weight"].ndim == 2
    if use_linear:
        h = h.reshape(B, H * W, C)
        h = _linear(h, p, f"{pre}.proj_in")
    else:
        h = _conv(h, p[f"{pre}.proj_in.weight"], p[f"{pre}.proj_in.bias"], pad=0)
        h = h.reshape(B, H * W, C)
    for d in range(depth):  # SDXL stacks up to 10 blocks per attention
        h = _basic_transformer(p, f"{pre}.transformer_blocks.{d}", h, ctx, heads)
    if use_linear:
        h = _linear(h, p, f"{pre}.proj_out").reshape(B, H, W, C)
    else:
        h = h.reshape(B, H, W, C)
        h = _conv(h, p[f"{pre}.proj_out.weight"], p[f"{pre}.proj_out.bias"], pad=0)
    return h + r


def unet_forward(cfg: UNetConfig, p: Params, sample: jnp.ndarray,
                 t: jnp.ndarray, ctx: jnp.ndarray,
                 added_text: Optional[jnp.ndarray] = None,
                 added_time_ids: Optional[jnp.ndarray] = None,
                 ctrl_residuals: Optional[tuple] = None) -> jnp.ndarray:
    """sample [B, H, W, C_lat], t [B], ctx [B, S, C_txt] → eps/v pred.

    SDXL micro-conditioning (addition_embed_type "text_time"): added_text
    [B, 1280] (encoder-2 pooled projection) and added_time_ids [B, 6]
    (orig_h, orig_w, crop_top, crop_left, target_h, target_w) are fourier-
    embedded and added into the time embedding.

    ctrl_residuals: (down_residuals list, mid_residual) from
    controlnet_forward — added to the matching skip connections and the mid
    block output (diffusers ControlNetModel consumption contract)."""
    g = cfg.norm_num_groups
    temb = get_timestep_embedding(
        t, cfg.block_out_channels[0], cfg.flip_sin_to_cos, cfg.freq_shift
    ).astype(sample.dtype)
    temb = _linear(temb, p, "time_embedding.linear_1")
    temb = _linear(jax.nn.silu(temb), p, "time_embedding.linear_2")
    if cfg.addition_embed_type == "text_time":
        B = sample.shape[0]
        tids = get_timestep_embedding(
            added_time_ids.reshape(-1), cfg.addition_time_embed_dim,
            cfg.flip_sin_to_cos, cfg.freq_shift,
        ).reshape(B, -1).astype(sample.dtype)  # [B, 6*addition_dim]
        add = jnp.concatenate([added_text.astype(sample.dtype), tids], axis=-1)
        aug = _linear(add, p, "add_embedding.linear_1")
        aug = _linear(jax.nn.silu(aug), p, "add_embedding.linear_2")
        temb = temb + aug

    h = _conv(sample, p["conv_in.weight"], p["conv_in.bias"])
    skips = [h]
    for bi, btype in enumerate(cfg.down_block_types):
        pre = f"down_blocks.{bi}"
        heads = cfg.heads_for(bi)
        for li in range(cfg.layers_per_block):
            h = _resnet(p, f"{pre}.resnets.{li}", h, temb, g)
            if btype == "CrossAttnDownBlock2D":
                h = _spatial_transformer(
                    p, f"{pre}.attentions.{li}", h, ctx, heads, g,
                    cfg.tx_depth_for(bi),
                )
            skips.append(h)
        if f"{pre}.downsamplers.0.conv.weight" in p:
            h = _conv(h, p[f"{pre}.downsamplers.0.conv.weight"],
                      p[f"{pre}.downsamplers.0.conv.bias"], stride=2)
            skips.append(h)

    if ctrl_residuals is not None:
        down_res, mid_res = ctrl_residuals
        skips = [s + r for s, r in zip(skips, down_res)]

    last = len(cfg.block_out_channels) - 1
    h = _resnet(p, "mid_block.resnets.0", h, temb, g)
    h = _spatial_transformer(
        p, "mid_block.attentions.0", h, ctx,
        cfg.heads_for(last), g, cfg.tx_depth_for(last),
    )
    h = _resnet(p, "mid_block.resnets.1", h, temb, g)
    if ctrl_residuals is not None:
        h = h + mid_res

    for bi, btype in enumerate(cfg.up_block_types):
        pre = f"up_blocks.{bi}"
        heads = cfg.heads_for(last - bi)
        for li in range(cfg.layers_per_block + 1):
            skip = skips.pop()
            h = jnp.concatenate([h, skip], axis=-1)
            h = _resnet(p, f"{pre}.resnets.{li}", h, temb, g)
            if btype == "CrossAttnUpBlock2D":
                h = _spatial_transformer(
                    p, f"{pre}.attentions.{li}", h, ctx, heads, g,
                    cfg.tx_depth_for(last - bi),
                )
        if f"{pre}.upsamplers.0.conv.weight" in p:
            B, H, W, C = h.shape
            h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
            h = _conv(h, p[f"{pre}.upsamplers.0.conv.weight"],
                      p[f"{pre}.upsamplers.0.conv.bias"])

    h = _group_norm(h, p["conv_norm_out.weight"], p["conv_norm_out.bias"], g)
    return _conv(jax.nn.silu(h), p["conv_out.weight"], p["conv_out.bias"])


def controlnet_forward(cfg: UNetConfig, p: Params, sample: jnp.ndarray,
                       t: jnp.ndarray, ctx: jnp.ndarray, cond: jnp.ndarray,
                       scale: float = 1.0) -> tuple:
    """diffusers ControlNetModel: a copy of the UNet encoder whose skip
    outputs pass through zero-initialized 1x1 convs, plus a small conv
    tower embedding the PIXEL-SPACE condition image into latent resolution.

    sample [B, h, w, C_lat]; cond [B, 8·h?, 8·w?, 3] in [0, 1] (the control
    image at pixel resolution); returns (down_residuals, mid_residual) for
    unet_forward's ctrl_residuals."""
    g = cfg.norm_num_groups
    temb = get_timestep_embedding(
        t, cfg.block_out_channels[0], cfg.flip_sin_to_cos, cfg.freq_shift
    ).astype(sample.dtype)
    temb = _linear(temb, p, "time_embedding.linear_1")
    temb = _linear(jax.nn.silu(temb), p, "time_embedding.linear_2")

    # Condition embedding tower: stride-2 conv pairs down to latent res,
    # final conv zero-initialized at training start.
    c = _conv(cond.astype(sample.dtype),
              p["controlnet_cond_embedding.conv_in.weight"],
              p["controlnet_cond_embedding.conv_in.bias"])
    c = jax.nn.silu(c)
    nblk = 0
    while f"controlnet_cond_embedding.blocks.{nblk}.weight" in p:
        nblk += 1
    for i in range(nblk):
        stride = 2 if i % 2 == 1 else 1  # diffusers alternates ch-up, down-2
        c = _conv(c, p[f"controlnet_cond_embedding.blocks.{i}.weight"],
                  p[f"controlnet_cond_embedding.blocks.{i}.bias"], stride=stride)
        c = jax.nn.silu(c)
    c = _conv(c, p["controlnet_cond_embedding.conv_out.weight"],
              p["controlnet_cond_embedding.conv_out.bias"])

    h = _conv(sample, p["conv_in.weight"], p["conv_in.bias"]) + c
    skips = [h]
    for bi, btype in enumerate(cfg.down_block_types):
        pre = f"down_blocks.{bi}"
        heads = cfg.heads_for(bi)
        for li in range(cfg.layers_per_block):
            h = _resnet(p, f"{pre}.resnets.{li}", h, temb, g)
            if btype == "CrossAttnDownBlock2D":
                h = _spatial_transformer(
                    p, f"{pre}.attentions.{li}", h, ctx, heads, g,
                    cfg.tx_depth_for(bi),
                )
            skips.append(h)
        if f"{pre}.downsamplers.0.conv.weight" in p:
            h = _conv(h, p[f"{pre}.downsamplers.0.conv.weight"],
                      p[f"{pre}.downsamplers.0.conv.bias"], stride=2)
            skips.append(h)

    last = len(cfg.block_out_channels) - 1
    h = _resnet(p, "mid_block.resnets.0", h, temb, g)
    h = _spatial_transformer(
        p, "mid_block.attentions.0", h, ctx,
        cfg.heads_for(last), g, cfg.tx_depth_for(last),
    )
    h = _resnet(p, "mid_block.resnets.1", h, temb, g)

    down = [
        scale * _conv(s, p[f"controlnet_down_blocks.{i}.weight"],
                      p[f"controlnet_down_blocks.{i}.bias"], pad=0)
        for i, s in enumerate(skips)
    ]
    mid = scale * _conv(h, p["controlnet_mid_block.weight"],
                        p["controlnet_mid_block.bias"], pad=0)
    return down, mid


# --------------------------------------------------------------------------- #
# VAE
# --------------------------------------------------------------------------- #


def _vae_attn(p: Params, pre: str, x: jnp.ndarray, groups: int) -> jnp.ndarray:
    B, H, W, C = x.shape
    h = _group_norm(x, p[f"{pre}.group_norm.weight"], p[f"{pre}.group_norm.bias"], groups)
    h = h.reshape(B, H * W, C)
    q = _linear(h, p, f"{pre}.to_q")
    k = _linear(h, p, f"{pre}.to_k")
    v = _linear(h, p, f"{pre}.to_v")
    h = _attention(q, k, v, heads=1)
    h = _linear(h, p, f"{pre}.to_out.0").reshape(B, H, W, C)
    return x + h


def vae_decode(cfg: VAEConfig, p: Params, latents: jnp.ndarray) -> jnp.ndarray:
    """[B, h, w, C_lat] (already unscaled) → images [B, 8h, 8w, 3] in [0,1]."""
    g = cfg.norm_num_groups
    zero_t = jnp.zeros((latents.shape[0],), latents.dtype)
    h = latents
    if "post_quant_conv.weight" in p:  # Flux-class VAEs omit the quant convs
        h = _conv(h, p["post_quant_conv.weight"], p["post_quant_conv.bias"], pad=0)
    h = _conv(h, p["decoder.conv_in.weight"], p["decoder.conv_in.bias"])
    h = _resnet(p, "decoder.mid_block.resnets.0", h, zero_t, g)
    h = _vae_attn(p, "decoder.mid_block.attentions.0", h, g)
    h = _resnet(p, "decoder.mid_block.resnets.1", h, zero_t, g)
    n_blocks = len(cfg.block_out_channels)
    for bi in range(n_blocks):
        pre = f"decoder.up_blocks.{bi}"
        for li in range(cfg.layers_per_block + 1):
            h = _resnet(p, f"{pre}.resnets.{li}", h, zero_t, g)
        if f"{pre}.upsamplers.0.conv.weight" in p:
            B, H, W, C = h.shape
            h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
            h = _conv(h, p[f"{pre}.upsamplers.0.conv.weight"],
                      p[f"{pre}.upsamplers.0.conv.bias"])
    h = _group_norm(h, p["decoder.conv_norm_out.weight"],
                    p["decoder.conv_norm_out.bias"], g)
    img = _conv(jax.nn.silu(h), p["decoder.conv_out.weight"], p["decoder.conv_out.bias"])
    return jnp.clip(img.astype(jnp.float32) / 2.0 + 0.5, 0.0, 1.0)


def vae_encode(cfg: VAEConfig, p: Params, img: jnp.ndarray,
               key: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """images [B, H, W, 3] in [0,1] → scaled latents [B, H/8, W/8, C_lat].
    Deterministic (mode) unless a key is given."""
    g = cfg.norm_num_groups
    x = img.astype(jnp.float32) * 2.0 - 1.0
    zero_t = jnp.zeros((x.shape[0],), x.dtype)
    h = _conv(x, p["encoder.conv_in.weight"], p["encoder.conv_in.bias"])
    n_blocks = len(cfg.block_out_channels)
    for bi in range(n_blocks):
        pre = f"encoder.down_blocks.{bi}"
        for li in range(cfg.layers_per_block):
            h = _resnet(p, f"{pre}.resnets.{li}", h, zero_t, g)
        if f"{pre}.downsamplers.0.conv.weight" in p:
            # diffusers pads asymmetrically (0,1,0,1) for stride-2 convs
            h = jnp.pad(h, ((0, 0), (0, 1), (0, 1), (0, 0)))
            h = jax.lax.conv_general_dilated(
                h, p[f"{pre}.downsamplers.0.conv.weight"].astype(h.dtype),
                window_strides=(2, 2), padding=[(0, 0), (0, 0)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ) + p[f"{pre}.downsamplers.0.conv.bias"].astype(h.dtype)
    h = _resnet(p, "encoder.mid_block.resnets.0", h, zero_t, g)
    h = _vae_attn(p, "encoder.mid_block.attentions.0", h, g)
    h = _resnet(p, "encoder.mid_block.resnets.1", h, zero_t, g)
    h = _group_norm(h, p["encoder.conv_norm_out.weight"],
                    p["encoder.conv_norm_out.bias"], g)
    moments = _conv(jax.nn.silu(h), p["encoder.conv_out.weight"],
                    p["encoder.conv_out.bias"])
    if "quant_conv.weight" in p:  # Flux-class VAEs omit the quant convs
        moments = _conv(moments, p["quant_conv.weight"], p["quant_conv.bias"], pad=0)
    mean, logvar = jnp.split(moments, 2, axis=-1)
    if key is not None:
        mean = mean + jnp.exp(0.5 * jnp.clip(logvar, -30, 20)) * jax.random.normal(
            key, mean.shape, mean.dtype
        )
    return mean * cfg.scaling_factor


# --------------------------------------------------------------------------- #
# Schedulers
# --------------------------------------------------------------------------- #


def alphas_cumprod(cfg: SDPipelineConfig) -> np.ndarray:
    betas = np.linspace(
        cfg.beta_start ** 0.5, cfg.beta_end ** 0.5, cfg.num_train_timesteps,
        dtype=np.float64,
    ) ** 2  # "scaled_linear"
    return np.cumprod(1.0 - betas).astype(np.float32)


def ddim_timesteps(cfg: SDPipelineConfig, steps: int) -> np.ndarray:
    ratio = cfg.num_train_timesteps // steps
    return (np.arange(steps) * ratio).round()[::-1].astype(np.int32)  # "leading"


def _pred_x0_eps(cfg: SDPipelineConfig, model_out, x, acp_t):
    """(x0, eps) from the model output under the configured prediction type."""
    sq_a, sq_1ma = jnp.sqrt(acp_t), jnp.sqrt(1.0 - acp_t)
    if cfg.prediction_type == "v_prediction":
        x0 = sq_a * x - sq_1ma * model_out
        eps = sq_a * model_out + sq_1ma * x
    else:
        x0 = (x - sq_1ma * model_out) / sq_a
        eps = model_out
    return x0, eps


def ddim_step(cfg: SDPipelineConfig, acp: jnp.ndarray, model_out: jnp.ndarray,
              t: jnp.ndarray, t_prev: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    acp_t = acp[t]
    acp_prev = jnp.where(t_prev >= 0, acp[jnp.maximum(t_prev, 0)], 1.0)
    x0, eps = _pred_x0_eps(cfg, model_out.astype(jnp.float32), x.astype(jnp.float32), acp_t)
    return (jnp.sqrt(acp_prev) * x0 + jnp.sqrt(1.0 - acp_prev) * eps).astype(x.dtype)


def euler_a_sigmas(cfg: SDPipelineConfig, steps: int) -> np.ndarray:
    acp = alphas_cumprod(cfg)
    sig = np.sqrt((1 - acp) / acp)
    ts = ddim_timesteps(cfg, steps).astype(np.float64)
    sigmas = np.interp(ts, np.arange(len(sig)), sig)
    return np.append(sigmas, 0.0).astype(np.float32)


def k_schedule(cfg: SDPipelineConfig, steps: int, karras: bool):
    """(sigmas [steps+1], timesteps [steps]) for the k-diffusion samplers.

    karras=True uses the Karras et al. (2022) rho-7 spacing over the
    model's trained sigma range (diffusers use_karras_sigmas; what the
    *_karras scheduler names select); timesteps come back from inverting
    the training sigma table so the model is queried at the right t."""
    acp = alphas_cumprod(cfg)
    sig = np.sqrt((1 - acp) / acp)
    if not karras:
        ts = ddim_timesteps(cfg, steps).astype(np.float64)
        sigmas = np.interp(ts, np.arange(len(sig)), sig)
        return (np.append(sigmas, 0.0).astype(np.float32),
                ts.astype(np.float32))
    rho = 7.0
    smin, smax = float(sig[0]), float(sig[-1])
    ramp = np.linspace(0.0, 1.0, steps)
    sigmas = (smax ** (1 / rho) + ramp * (smin ** (1 / rho) - smax ** (1 / rho))) ** rho
    # invert the (monotonic) training sigma table: sigma -> fractional t
    ts = np.interp(np.log(sigmas), np.log(sig), np.arange(len(sig)))
    return (np.append(sigmas, 0.0).astype(np.float32), ts.astype(np.float32))


def ancestral_sigmas(sigma, sigma_next):
    """(sigma_down, sigma_up) for an eta-1 ancestral step (k-diffusion
    get_ancestral_step)."""
    s2, sn2 = sigma ** 2, sigma_next ** 2
    sigma_up = jnp.sqrt(jnp.maximum(sn2 * (s2 - sn2) / jnp.maximum(s2, 1e-12), 0.0))
    sigma_down = jnp.sqrt(jnp.maximum(sn2 - sigma_up ** 2, 0.0))
    return sigma_down, sigma_up


def euler_a_step(model_out, x, sigma, sigma_next, noise):
    """k-diffusion Euler-ancestral over eps-prediction in sigma space."""
    mo = model_out.astype(jnp.float32)
    xf = x.astype(jnp.float32)
    x0 = xf - sigma * mo
    sigma_down, sigma_up = ancestral_sigmas(sigma, sigma_next)
    d = (xf - x0) / jnp.maximum(sigma, 1e-12)
    xf = xf + d * (sigma_down - sigma) + noise * sigma_up
    return xf.astype(x.dtype)


def _denoised_sigma(cfg: SDPipelineConfig, model_out, x, sigma):
    """k-diffusion denoiser output D(x, σ) for the configured prediction
    type (eps: D = x − σ·ε; v: D = x/(σ²+1) − σ/√(σ²+1)·v)."""
    mo = model_out.astype(jnp.float32)
    xf = x.astype(jnp.float32)
    if cfg.prediction_type == "v_prediction":
        return xf / (sigma**2 + 1.0) - sigma / jnp.sqrt(sigma**2 + 1.0) * mo
    return xf - sigma * mo


def lms_coefficients(sigmas: np.ndarray, order: int = 4) -> np.ndarray:
    """Adams-Bashforth coefficients over the (static) sigma trajectory:
    ∫ over [σ_i, σ_{i+1}] of each Lagrange basis through the last `order`
    sigmas (k-diffusion sample_lms). Host-side, per compile."""
    from scipy.integrate import quad

    steps = len(sigmas) - 1
    co = np.zeros((steps, order), np.float64)
    for i in range(steps):
        cur = min(i + 1, order)
        for j in range(cur):
            def basis(tau, j=j, cur=cur, i=i):
                prod = 1.0
                for k in range(cur):
                    if k != j:
                        prod *= (tau - sigmas[i - k]) / (sigmas[i - j] - sigmas[i - k])
                return prod

            co[i, j] = quad(basis, sigmas[i], sigmas[i + 1], epsrel=1e-5)[0]
    return co.astype(np.float32)


# --------------------------------------------------------------------------- #
# Generation
# --------------------------------------------------------------------------- #


def generate(
    cfg: SDPipelineConfig,
    params: dict[str, Params],  # {"text": ..., "unet": ..., "vae": ...}
    cond_ids: jnp.ndarray,  # [B, 77]
    uncond_ids: jnp.ndarray,
    key: jnp.ndarray,
    steps: int = 20,
    guidance: float = 7.5,
    height: int = 512,
    width: int = 512,
    scheduler: str = "ddim",
    init_noise: Optional[jnp.ndarray] = None,  # [B, h/8, w/8, C] unit normal
    known_latent: Optional[jnp.ndarray] = None,  # scaled latents to keep
    known_mask: Optional[jnp.ndarray] = None,  # [B, h/8, w/8, 1]; 1 = repaint
    cond_ids2: Optional[jnp.ndarray] = None,  # SDXL: tokenizer_2 ids
    uncond_ids2: Optional[jnp.ndarray] = None,
    control_image: Optional[jnp.ndarray] = None,  # [B, H, W, 3] in [0,1]
    control_scale: float = 1.0,
    init_image: Optional[jnp.ndarray] = None,  # img2img source [B, H, W, 3]
    strength: float = 0.8,  # img2img: fraction of the schedule re-noised
) -> jnp.ndarray:
    """Full text→image pipeline; returns [B, H, W, 3] float32 in [0,1].
    jit-able: shapes depend only on (B, steps, H, W, scheduler).

    SDXL checkpoints (cfg.text2 set) condition on the CONCATENATED
    penultimate states of both encoders plus encoder 2's pooled projection
    and size/crop time-ids (StableDiffusionXLPipeline semantics).

    With known_latent/known_mask set, runs SD-style inpainting on a vanilla
    checkpoint: after every step the preserved region is replaced with the
    source latent re-noised to the current timestep (diffusers'
    StableDiffusionInpaintPipelineLegacy behavior)."""
    B = cond_ids.shape[0]
    added = None
    if cfg.is_xl:
        ids2_c = cond_ids if cond_ids2 is None else cond_ids2
        ids2_u = uncond_ids if uncond_ids2 is None else uncond_ids2
        pen1_c, _ = clip_hidden_states(cfg.text, params["text"], cond_ids)
        pen1_u, _ = clip_hidden_states(cfg.text, params["text"], uncond_ids)
        pen2_c, fin2_c = clip_hidden_states(cfg.text2, params["text2"], ids2_c)
        pen2_u, fin2_u = clip_hidden_states(cfg.text2, params["text2"], ids2_u)
        ctx = jnp.concatenate([
            jnp.concatenate([pen1_u, pen2_u], axis=-1),
            jnp.concatenate([pen1_c, pen2_c], axis=-1),
        ], axis=0)
        pooled = jnp.concatenate([
            clip_pooled_projection(cfg.text2, params["text2"], ids2_u, fin2_u),
            clip_pooled_projection(cfg.text2, params["text2"], ids2_c, fin2_c),
        ], axis=0)
        time_ids = jnp.broadcast_to(
            jnp.asarray([height, width, 0, 0, height, width], jnp.float32),
            (2 * B, 6),
        )
        added = (pooled, time_ids)
    else:
        ctx_c = clip_encode(cfg.text, params["text"], cond_ids)
        ctx_u = clip_encode(cfg.text, params["text"], uncond_ids)
        ctx = jnp.concatenate([ctx_u, ctx_c], axis=0)
    vs = cfg.vae.spatial_scale
    lat_h, lat_w = height // vs, width // vs
    acp = jnp.asarray(alphas_cumprod(cfg))
    key, nk = jax.random.split(key)
    lat_c = cfg.unet.in_channels
    x = init_noise if init_noise is not None else jax.random.normal(
        nk, (B, lat_h, lat_w, lat_c), jnp.float32
    )
    # img2img: encode the source, start `strength` of the way up the noise
    # schedule and run only the remaining steps (diffusers
    # StableDiffusionImg2ImgPipeline semantics; reference backend.py:198).
    i0 = 0
    init_lat = None
    if init_image is not None:
        i0 = steps - max(1, min(steps, int(round(steps * strength))))
        init_lat = vae_encode(cfg.vae, params["vae"], init_image)

    use_ctrl = control_image is not None and "controlnet" in params
    ctrl_cond2 = (jnp.concatenate([control_image, control_image], axis=0)
                  if use_ctrl else None)

    def cfg_eps(x_in, t):
        both = jnp.concatenate([x_in, x_in], axis=0)
        tt = jnp.full((2 * B,), t, jnp.float32)
        ctrl = None
        if use_ctrl:
            ctrl = controlnet_forward(
                cfg.unet, params["controlnet"], both, tt, ctx, ctrl_cond2,
                scale=control_scale,
            )
        out = unet_forward(
            cfg.unet, params["unet"], both, tt, ctx,
            added_text=added[0] if added else None,
            added_time_ids=added[1] if added else None,
            ctrl_residuals=ctrl,
        )
        eps_u, eps_c = jnp.split(out, 2, axis=0)
        return eps_u + guidance * (eps_c - eps_u)

    inpainting = known_latent is not None and known_mask is not None
    if inpainting and scheduler != "ddim":
        # The preserved-region replay (blend) is DDIM-space math; silently
        # ignoring the mask under another sampler would "inpaint" nothing.
        raise ValueError("inpainting requires the ddim scheduler")

    def blend(xc, t_prev, k):
        """Replace the preserved region with the source re-noised to t_prev."""
        if not inpainting:
            return xc
        acp_prev = jnp.where(t_prev >= 0, acp[jnp.maximum(t_prev, 0)], 1.0)
        noise = jax.random.normal(k, xc.shape, jnp.float32)
        noised = jnp.sqrt(acp_prev) * known_latent + jnp.sqrt(1.0 - acp_prev) * noise
        return known_mask * xc + (1.0 - known_mask) * noised.astype(xc.dtype)

    k_schedulers, t_schedulers = K_SCHEDULERS, T_SCHEDULERS
    karras = False
    if scheduler.startswith("k_"):
        karras = True
        scheduler = scheduler[2:]
    if scheduler.endswith("_karras"):
        karras = True
        scheduler = scheduler[: -len("_karras")]
    base_sched = scheduler
    if (base_sched not in k_schedulers + t_schedulers
            or (karras and base_sched in t_schedulers)):
        raise ValueError(
            f"unknown scheduler {scheduler!r} (supported: "
            + ", ".join(t_schedulers + k_schedulers)
            + ", plus _karras/k_ variants of "
            + ", ".join(k_schedulers) + ")"
        )
    scheduler = base_sched
    if scheduler in k_schedulers:
        sigmas_np, ts_np = k_schedule(cfg, steps, karras)
        sigmas = jnp.asarray(sigmas_np)
        ts = jnp.asarray(ts_np)
        if init_lat is not None:
            x = init_lat + x * sigmas[i0]
        else:
            x = x * sigmas[0]

        def denoised_at(xc, i):
            sig = sigmas[i]
            x_in = xc.astype(jnp.float32) / jnp.sqrt(sig**2 + 1.0)
            out = cfg_eps(x_in, ts[i])
            return _denoised_sigma(cfg, out, xc, sig)

        # For samplers that query the model at off-grid sigmas (dpm_2* mid-
        # points, dpmpp_sde half-steps): invert the training sigma table to
        # a fractional timestep on device.
        sig_train = jnp.sqrt((1.0 - acp) / acp)
        log_sig_train = jnp.log(sig_train)
        t_grid = jnp.arange(sig_train.shape[0], dtype=jnp.float32)

        def denoised_at_sigma(xc, sig):
            t = jnp.interp(jnp.log(jnp.maximum(sig, 1e-10)),
                           log_sig_train, t_grid)
            x_in = xc.astype(jnp.float32) / jnp.sqrt(sig**2 + 1.0)
            out = cfg_eps(x_in, t)
            return _denoised_sigma(cfg, out, xc, sig)

        ancestral = ancestral_sigmas

        if scheduler == "euler":
            # k-diffusion sample_euler (churn 0): one deterministic slope
            # step per sigma interval.
            def step(xc, i):
                sig, sig_n = sigmas[i], sigmas[i + 1]
                den = denoised_at(xc, i)
                d = (xc.astype(jnp.float32) - den) / sig
                return (xc.astype(jnp.float32) + d * (sig_n - sig)).astype(xc.dtype), None

            x, _ = jax.lax.scan(step, x, jnp.arange(i0, steps))
        elif scheduler in ("dpm_2", "dpm_2_a"):
            # k-diffusion sample_dpm_2(_ancestral): midpoint (log-sigma
            # lerp 0.5) second-order correction; the ancestral variant
            # steps to sigma_down and re-noises by sigma_up.
            anc = scheduler == "dpm_2_a"

            def step(carry, i):
                xc, k = carry
                k, nk2 = jax.random.split(k)
                xcf = xc.astype(jnp.float32)
                sig, sig_n = sigmas[i], sigmas[i + 1]
                den = denoised_at(xc, i)
                d = (xcf - den) / sig
                x_eul = xcf + d * (sig_n - sig)  # final-step fallback
                tgt, su = (ancestral(sig, sig_n) if anc
                           else (sig_n, jnp.float32(0.0)))
                sig_mid = jnp.exp(0.5 * (
                    jnp.log(sig) + jnp.log(jnp.maximum(tgt, 1e-10))))
                x_2 = xcf + d * (sig_mid - sig)
                den2 = denoised_at_sigma(x_2.astype(xc.dtype), sig_mid)
                d2 = (x_2 - den2) / sig_mid
                xn = xcf + d2 * (tgt - sig)
                if anc:
                    xn = xn + jax.random.normal(nk2, xc.shape, jnp.float32) * su
                xn = jnp.where(sig_n == 0.0, x_eul, xn)
                return (xn.astype(xc.dtype), k), None

            (x, _), _ = jax.lax.scan(step, (x, key), jnp.arange(i0, steps))
        elif scheduler == "dpmpp_sde":
            # k-diffusion sample_dpmpp_sde (r=1/2, eta=1): an SDE half-step
            # to the ancestral midpoint, then a full step from the midpoint
            # estimate (fac = 1/(2r) = 1 → the second eval carries it).
            def step(carry, i):
                xc, k = carry
                k, k1, k2 = jax.random.split(k, 3)
                xcf = xc.astype(jnp.float32)
                sig, sig_n = sigmas[i], sigmas[i + 1]
                den = denoised_at(xc, i)
                t_c = -jnp.log(sig)
                s_mid = jnp.exp(-(t_c + 0.5 * (
                    -jnp.log(jnp.maximum(sig_n, 1e-10)) - t_c)))
                sd1, su1 = ancestral(sig, s_mid)
                s_ = -jnp.log(jnp.maximum(sd1, 1e-10))
                x_2 = (sd1 / sig) * xcf - jnp.expm1(t_c - s_) * den
                x_2 = x_2 + jax.random.normal(k1, xc.shape, jnp.float32) * su1
                den2 = denoised_at_sigma(x_2.astype(xc.dtype), s_mid)
                sd2, su2 = ancestral(sig, sig_n)
                t_n_ = -jnp.log(jnp.maximum(sd2, 1e-10))
                xn = (sd2 / sig) * xcf - jnp.expm1(t_c - t_n_) * den2
                xn = xn + jax.random.normal(k2, xc.shape, jnp.float32) * su2
                # k-diffusion falls back to a plain step when σ_next == 0;
                # x − σ·d = denoised exactly there.
                xn = jnp.where(sig_n == 0.0, den, xn)
                return (xn.astype(xc.dtype), k), None

            (x, _), _ = jax.lax.scan(step, (x, key), jnp.arange(i0, steps))
        elif scheduler == "dpmpp_2m_sde":
            # k-diffusion sample_dpmpp_2m_sde (eta=1, midpoint solver):
            # exponential-integrator SDE multistep over λ = -log σ.
            def step(carry, i):
                xc, old_den, k = carry
                k, nk2 = jax.random.split(k)
                xcf = xc.astype(jnp.float32)
                sig, sig_n = sigmas[i], sigmas[i + 1]
                den = denoised_at(xc, i)
                t_c = -jnp.log(sig)
                t_n = -jnp.log(jnp.maximum(sig_n, 1e-10))
                h = t_n - t_c  # eta_h = h (eta = 1)
                xn = (sig_n / sig) * jnp.exp(-h) * xcf \
                    - jnp.expm1(-2.0 * h) * den
                sig_prev = sigmas[jnp.maximum(i - 1, 0)]
                h_last = t_c - (-jnp.log(sig_prev))
                r = h_last / h
                second = -0.5 * jnp.expm1(-2.0 * h) * (1.0 / r) * (den - old_den)
                xn = xn + jnp.where(i == i0, 0.0, second)
                noise = jax.random.normal(nk2, xc.shape, jnp.float32)
                xn = xn + noise * sig_n * jnp.sqrt(
                    jnp.maximum(-jnp.expm1(-2.0 * h), 0.0))
                # Final σ = 0 step: the multistep correction's 1/r blows up
                # (h → ∞); the exact limit of the update is the denoised
                # sample itself.
                xn = jnp.where(sig_n == 0.0, den, xn)
                return (xn.astype(xc.dtype), den, k), None

            (x, _, _), _ = jax.lax.scan(
                step, (x, jnp.zeros_like(x), key), jnp.arange(i0, steps))
        elif scheduler == "euler_a":

            def step(carry, i):
                xc, k = carry
                k, nk2 = jax.random.split(k)
                sig, sig_n = sigmas[i], sigmas[i + 1]
                x_in = xc / jnp.sqrt(sig ** 2 + 1.0)
                eps = cfg_eps(x_in, ts[i])
                noise = jax.random.normal(nk2, xc.shape, jnp.float32)
                return (euler_a_step(eps, xc, sig, sig_n, noise), k), None

            (x, _), _ = jax.lax.scan(step, (x, key), jnp.arange(i0, steps))
        elif scheduler == "dpmpp_2m":
            # DPM-Solver++(2M): deterministic multistep over λ = −log σ
            # (k-diffusion sample_dpmpp_2m; first and last steps are 1st
            # order).
            def step(carry, i):
                xc, old_d = carry
                den = denoised_at(xc, i)
                sig, sig_n = sigmas[i], sigmas[i + 1]
                t_c, t_n = -jnp.log(sig), -jnp.log(jnp.maximum(sig_n, 1e-10))
                h = t_n - t_c
                sig_prev = sigmas[jnp.maximum(i - 1, 0)]
                h_last = t_c - (-jnp.log(sig_prev))
                r = h_last / h
                den_d = (1 + 1 / (2 * r)) * den - (1 / (2 * r)) * old_d
                use_first = (i == i0) | (sig_n == 0.0)
                den_use = jnp.where(use_first, den, den_d)
                xn = (sig_n / sig) * xc.astype(jnp.float32) \
                    - jnp.expm1(-h) * den_use
                return (xn.astype(xc.dtype), den), None

            (x, _), _ = jax.lax.scan(step, (x, jnp.zeros_like(x)),
                                     jnp.arange(i0, steps))
        elif scheduler == "heun":
            # Heun's 2nd order (k-diffusion sample_heun, churn 0): trapezoid
            # correction with a second model eval; plain Euler when the next
            # sigma is 0 (the correction's slope is undefined there).
            def step(carry, i):
                xc, _ = carry
                sig, sig_n = sigmas[i], sigmas[i + 1]
                den = denoised_at(xc, i)
                d = (xc.astype(jnp.float32) - den) / sig
                dt = sig_n - sig
                x_eul = xc.astype(jnp.float32) + d * dt
                den2 = denoised_at(x_eul.astype(xc.dtype),
                                   jnp.minimum(i + 1, steps - 1))
                d2 = (x_eul - den2) / jnp.maximum(sig_n, 1e-10)
                x_heun = xc.astype(jnp.float32) + (d + d2) / 2 * dt
                xn = jnp.where(sig_n == 0.0, x_eul, x_heun)
                return (xn.astype(xc.dtype), 0.0), None

            (x, _), _ = jax.lax.scan(step, (x, 0.0), jnp.arange(i0, steps))
        else:  # lms
            # coefficients over the REMAINING trajectory: starting mid-
            # schedule (img2img) must not weight history that never ran
            order = min(4, steps - i0)
            co = jnp.asarray(lms_coefficients(sigmas_np[i0:], order))

            def step(carry, i):
                xc, hist = carry
                den = denoised_at(xc, i)
                d = (xc.astype(jnp.float32) - den) / sigmas[i]
                hist = jnp.concatenate([d[None], hist[:-1]], axis=0)
                xn = xc.astype(jnp.float32) + jnp.einsum(
                    "j,j...->...", co[i - i0], hist
                )
                return (xn.astype(xc.dtype), hist), None

            hist0 = jnp.zeros((order,) + x.shape, jnp.float32)
            (x, _), _ = jax.lax.scan(step, (x, hist0), jnp.arange(i0, steps))
    else:
        ts = jnp.asarray(ddim_timesteps(cfg, steps))
        ratio = cfg.num_train_timesteps // steps
        if init_lat is not None:
            acp0 = acp[ts[i0]]
            x = jnp.sqrt(acp0) * init_lat + jnp.sqrt(1.0 - acp0) * x

        if scheduler == "pndm":
            # PLMS (Liu et al. 2022): Adams-Bashforth eps history (orders
            # 1→4 warmup) through the pseudo-linear transfer function
            # (diffusers PNDMScheduler._get_prev_sample). Deliberate
            # difference from diffusers' skip_prk warmup: the first
            # timestep runs ONE order-1 step instead of diffusers'
            # duplicated-timestep two-eval average — steps model evals
            # total, converging to the same trajectory as history fills.
            def transfer(xcf, eps, t, t_prev):
                a_t = acp[t]
                a_p = jnp.where(t_prev >= 0, acp[jnp.maximum(t_prev, 0)], 1.0)
                coeff = jnp.sqrt(a_p / a_t)
                denom = a_t * jnp.sqrt(1.0 - a_p) + jnp.sqrt(
                    a_t * (1.0 - a_t) * a_p)
                return coeff * xcf - (a_p - a_t) * eps / denom

            def step(carry, idx):
                xc, e1, e2, e3, cnt = carry  # e1 newest
                t = ts[idx]
                eps = cfg_eps(xc, t.astype(jnp.float32)).astype(jnp.float32)
                if cfg.prediction_type == "v_prediction":
                    # diffusers PNDMScheduler converts v → eps before the
                    # transfer function: eps = √ᾱ·v + √(1−ᾱ)·x
                    a_t = acp[t]
                    eps = (jnp.sqrt(a_t) * eps
                           + jnp.sqrt(1.0 - a_t) * xc.astype(jnp.float32))
                ep = jnp.where(
                    cnt == 0, eps, jnp.where(
                        cnt == 1, (3.0 * eps - e1) / 2.0, jnp.where(
                            cnt == 2, (23.0 * eps - 16.0 * e1 + 5.0 * e2) / 12.0,
                            (55.0 * eps - 59.0 * e1 + 37.0 * e2 - 9.0 * e3) / 24.0,
                        )))
                xn = transfer(xc.astype(jnp.float32), ep, t, t - ratio)
                return (xn.astype(xc.dtype), eps, e1, e2, cnt + 1), None

            z = jnp.zeros_like(x)
            (x, _, _, _, _), _ = jax.lax.scan(
                step, (x, z, z, z, jnp.int32(0)), jnp.arange(i0, steps))
        elif scheduler == "unipc":
            # UniPC (Zhao et al. 2023), bh2 variant: data-prediction
            # multistep over λ = log(α/σ) with a p=2 predictor and a
            # single-order corrector applied to the previous step once this
            # step's model output is known (the predictor-corrector
            # framework of diffusers UniPCMultistepScheduler, order 2).
            alphas = jnp.sqrt(acp)
            sigmas_t = jnp.sqrt(1.0 - acp)

            def at(t):
                a = jnp.where(t >= 0, alphas[jnp.maximum(t, 0)], 1.0)
                s = jnp.where(t >= 0, sigmas_t[jnp.maximum(t, 0)], 0.0)
                lam = jnp.log(a) - jnp.log(jnp.maximum(s, 1e-10))
                return a, jnp.maximum(s, 1e-10), lam

            def x0_of(xc, t):
                eps = cfg_eps(xc, t.astype(jnp.float32)).astype(jnp.float32)
                a_t, s_t, _ = at(t)
                if cfg.prediction_type == "v_prediction":
                    return a_t * xc.astype(jnp.float32) - s_t * eps
                return (xc.astype(jnp.float32) - s_t * eps) / a_t

            def step(carry, idx):
                xc, x_prev, m_prev, t_prev_step, cnt = carry
                t = ts[idx]
                a_t, s_t, lam_t = at(t)
                m_t = x0_of(xc, t)
                # UniC: correct THIS sample using the fresh model output
                # (rhos_c = 1/2, B_h = h_phi_1 for bh2).
                _, s_p, lam_p = at(t_prev_step)
                h_c = lam_t - lam_p
                phi_c = jnp.expm1(-h_c)
                x_corr = (s_t / s_p) * x_prev.astype(jnp.float32) \
                    - a_t * phi_c * m_prev \
                    - a_t * phi_c * 0.5 * (m_t - m_prev)
                xcf = jnp.where(cnt > 0, x_corr, xc.astype(jnp.float32))
                # UniP to the next timestep: p=1 on the first step, p=2 after.
                t_n = t - ratio
                a_n, s_n, lam_n = at(t_n)
                h = lam_n - lam_t
                phi = jnp.expm1(-h)
                x1 = (s_n / s_t) * xcf - a_n * phi * m_t
                r0 = (lam_p - lam_t) / h
                d1 = (m_prev - m_t) / jnp.where(cnt > 0, r0, 1.0)
                x2 = x1 - a_n * phi * 0.5 * d1
                # lower_order_final (diffusers UniPCMultistepScheduler): on
                # the LAST step t_n < 0 clamps sigma to 1e-10, so h ≈ 20+
                # while r0 = (lam_p - lam_t)/h is tiny — the D1 term then
                # amplifies m_prev - m_t ~25x and corrupts the output
                # latent. Order drops to 1 whenever the target time leaves
                # the schedule.
                xn = jnp.where((cnt > 0) & (t_n >= 0), x2, x1)
                return (xn.astype(xc.dtype), xcf.astype(xc.dtype), m_t, t,
                        cnt + 1), None

            (x, _, _, _, _), _ = jax.lax.scan(
                step, (x, x, jnp.zeros_like(x), ts[i0], jnp.int32(0)),
                jnp.arange(i0, steps))
        else:  # ddim

            def step(carry, i):
                xc, k = carry
                k, bk = jax.random.split(k)
                t = ts[i]
                eps = cfg_eps(xc, t.astype(jnp.float32))
                xn = ddim_step(cfg, acp, eps, t, t - ratio, xc)
                return (blend(xn, t - ratio, bk), k), None

            (x, _), _ = jax.lax.scan(step, (x, key), jnp.arange(i0, steps))

    return vae_decode(cfg.vae, params["vae"], x / cfg.vae.scaling_factor)


# --------------------------------------------------------------------------- #
# Checkpoint loading (diffusers layout)
# --------------------------------------------------------------------------- #


def is_diffusers_dir(path: str) -> bool:
    return os.path.isfile(os.path.join(path, "model_index.json"))


def _load_safetensors_dir(subdir: str) -> dict[str, np.ndarray]:
    from safetensors import safe_open

    out: dict[str, np.ndarray] = {}
    files = sorted(
        f for f in os.listdir(subdir)
        if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors under {subdir}")
    for fname in files:
        with safe_open(os.path.join(subdir, fname), framework="numpy") as f:
            for name in f.keys():
                out[name] = f.get_tensor(name)
    return out


def _prep(tensors: dict[str, np.ndarray], dtype) -> Params:
    """torch layouts → ours: convs OIHW→HWIO, 2D linears [out,in]→[in,out]."""
    out: Params = {}
    lookup_tables = ("token_embedding", "position_embedding")
    for name, arr in tensors.items():
        if arr.ndim == 4:
            arr = arr.transpose(2, 3, 1, 0)
        elif (arr.ndim == 2 and name.endswith(".weight")
              and not any(t in name for t in lookup_tables)):
            arr = arr.T
        out[name] = jnp.asarray(np.ascontiguousarray(arr), dtype)
    return out


def _cfg_json(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def load_diffusion_lora(path: str, params: dict[str, Params],
                        multiplier: float = 1.0) -> int:
    """Merge a kohya-format LoRA safetensors file (the Civitai SD-LoRA
    ecosystem format: `lora_unet_*` / `lora_te_*` layers with
    `lora_down.weight` / `lora_up.weight` / `alpha`) into an already-loaded
    pipeline's params IN PLACE, scaled by `multiplier`. Returns the number
    of base tensors patched.

    Reference: the diffusers backend's load_lora_weights walks the module
    tree merging up@down*alpha/rank*multiplier into each target
    (/root/reference/backend/python/diffusers/backend.py:456-533); here the
    flat name→array dicts make the walk a direct name lookup. SDXL LoRAs
    use lora_te1_/lora_te2_ for the two encoders."""
    from safetensors import safe_open

    tensors: dict[str, np.ndarray] = {}
    with safe_open(path, framework="numpy") as f:
        for name in f.keys():
            tensors[name] = f.get_tensor(name)

    # group "lora_unet_..._to_q.lora_down.weight" by the layer part;
    # Civitai files sometimes bundle extra top-level tensors (textual
    # inversions etc.) — skip anything that isn't layer.elem shaped
    groups: dict[str, dict[str, np.ndarray]] = {}
    for name, arr in tensors.items():
        if "." not in name:
            log.warning("lora: ignoring non-LoRA tensor %r", name)
            continue
        layer, elem = name.split(".", 1)
        groups.setdefault(layer, {})[elem] = arr

    # kohya flattens module paths with "_": undo it by name lookup against
    # the loaded params (keys are the published dotted names).
    lookups: dict[str, dict[str, str]] = {}

    def lookup_for(part: str) -> dict[str, str]:
        if part not in lookups:
            lookups[part] = {
                k[: -len(".weight")].replace(".", "_"): k
                for k in params.get(part, {}) if k.endswith(".weight")
            }
        return lookups[part]

    prefixes = (
        ("lora_unet_", "unet"), ("lora_te1_", "text"),
        ("lora_te2_", "text2"), ("lora_te_", "text"),
    )
    merged = 0
    for layer, elems in groups.items():
        target = None
        for pref, part in prefixes:
            if layer.startswith(pref):
                target, rest = part, layer[len(pref):]
                break
        if target is None or target not in params:
            continue
        key = lookup_for(target).get(rest)
        down = elems.get("lora_down.weight")
        up = elems.get("lora_up.weight")
        if key is None or down is None or up is None:
            if key is None:
                log.warning("lora: no target for %s (skipped)", layer)
            continue
        rank = down.shape[0]
        alpha = float(elems["alpha"]) if "alpha" in elems else float(rank)
        scale = multiplier * alpha / rank
        base = params[target][key]
        if down.ndim == 4:  # conv: up [O,r,1,1] @ down [r,I,kh,kw]
            delta = np.einsum(
                "or,rikl->oikl", up.reshape(up.shape[0], rank),
                down.astype(np.float32),
            ) * scale
            delta = delta.transpose(2, 3, 1, 0)  # OIHW → HWIO (as _prep)
        else:  # linear: [out,r] @ [r,in] → [out,in]; ours is [in,out]
            delta = (up.astype(np.float32) @ down.astype(np.float32)).T * scale
        if delta.shape != base.shape:
            log.warning("lora: %s shape %s != base %s (skipped)",
                        layer, delta.shape, base.shape)
            continue
        params[target][key] = (
            base.astype(jnp.float32) + jnp.asarray(delta)
        ).astype(base.dtype)
        merged += 1
    return merged


def load_pipeline(ckpt_dir: str, dtype=jnp.float32):
    """(SDPipelineConfig, params, tokenizer) from a diffusers checkpoint dir.

    Matches the reference's dynamic pipeline load
    (backend/python/diffusers/backend.py) for the SD-1.5 class; the tokenizer
    is the checkpoint's own CLIPTokenizer(Fast) via transformers.
    """
    tc = _cfg_json(os.path.join(ckpt_dir, "text_encoder", "config.json"))
    uc = _cfg_json(os.path.join(ckpt_dir, "unet", "config.json"))
    vc = _cfg_json(os.path.join(ckpt_dir, "vae", "config.json"))
    sched_path = os.path.join(ckpt_dir, "scheduler", "scheduler_config.json")
    sc = _cfg_json(sched_path) if os.path.isfile(sched_path) else {}

    cfg = SDPipelineConfig(
        text=CLIPTextConfig(
            vocab_size=tc.get("vocab_size", 49408),
            hidden_size=tc.get("hidden_size", 768),
            intermediate_size=tc.get("intermediate_size", 3072),
            num_hidden_layers=tc.get("num_hidden_layers", 12),
            num_attention_heads=tc.get("num_attention_heads", 12),
            max_position_embeddings=tc.get("max_position_embeddings", 77),
            hidden_act=tc.get("hidden_act", "quick_gelu"),
        ),
        unet=UNetConfig(
            in_channels=uc.get("in_channels", 4),
            out_channels=uc.get("out_channels", 4),
            sample_size=uc.get("sample_size", 64),
            block_out_channels=tuple(uc.get("block_out_channels", (320, 640, 1280, 1280))),
            down_block_types=tuple(uc.get("down_block_types", ())),
            up_block_types=tuple(uc.get("up_block_types", ())),
            layers_per_block=uc.get("layers_per_block", 2),
            attention_head_dim=uc.get("attention_head_dim", 8),
            cross_attention_dim=uc.get("cross_attention_dim", 768),
            norm_num_groups=uc.get("norm_num_groups", 32),
            flip_sin_to_cos=uc.get("flip_sin_to_cos", True),
            freq_shift=uc.get("freq_shift", 0),
            transformer_layers_per_block=uc.get("transformer_layers_per_block", 1),
            addition_embed_type=uc.get("addition_embed_type") or "",
            addition_time_embed_dim=uc.get("addition_time_embed_dim", 256),
            projection_class_embeddings_input_dim=uc.get(
                "projection_class_embeddings_input_dim", 0
            ),
        ),
        vae=VAEConfig(
            in_channels=vc.get("in_channels", 3),
            out_channels=vc.get("out_channels", 3),
            latent_channels=vc.get("latent_channels", 4),
            block_out_channels=tuple(vc.get("block_out_channels", (128, 256, 512, 512))),
            layers_per_block=vc.get("layers_per_block", 2),
            norm_num_groups=vc.get("norm_num_groups", 32),
            scaling_factor=vc.get("scaling_factor", 0.18215),
        ),
        num_train_timesteps=sc.get("num_train_timesteps", 1000),
        beta_start=sc.get("beta_start", 0.00085),
        beta_end=sc.get("beta_end", 0.012),
        prediction_type=sc.get("prediction_type", "epsilon"),
    )
    params = {
        "text": _prep(_load_safetensors_dir(os.path.join(ckpt_dir, "text_encoder")), dtype),
        "unet": _prep(_load_safetensors_dir(os.path.join(ckpt_dir, "unet")), dtype),
        "vae": _prep(_load_safetensors_dir(os.path.join(ckpt_dir, "vae")), dtype),
    }
    from transformers import AutoTokenizer, CLIPTokenizer

    def load_tok(sub: str):
        tok_dir = os.path.join(ckpt_dir, sub)
        try:
            return AutoTokenizer.from_pretrained(tok_dir, local_files_only=True)
        except Exception:  # noqa: BLE001 — vocab.json/merges.txt direct load
            return CLIPTokenizer.from_pretrained(tok_dir, local_files_only=True)

    tokenizer = load_tok("tokenizer")

    # ControlNet: a `controlnet/` subdir in the checkpoint (the diffusers
    # StableDiffusionControlNetPipeline save layout). Its encoder copies the
    # UNet's geometry, so cfg.unet describes both.
    ctrl_dir = os.path.join(ckpt_dir, "controlnet")
    if os.path.isdir(ctrl_dir):
        params["controlnet"] = _prep(_load_safetensors_dir(ctrl_dir), dtype)

    # SDXL layout: a second (OpenCLIP-bigG-class) text encoder + tokenizer.
    te2 = os.path.join(ckpt_dir, "text_encoder_2")
    if os.path.isdir(te2):
        t2 = _cfg_json(os.path.join(te2, "config.json"))
        cfg.text2 = CLIPTextConfig(
            vocab_size=t2.get("vocab_size", 49408),
            hidden_size=t2.get("hidden_size", 1280),
            intermediate_size=t2.get("intermediate_size", 5120),
            num_hidden_layers=t2.get("num_hidden_layers", 32),
            num_attention_heads=t2.get("num_attention_heads", 20),
            max_position_embeddings=t2.get("max_position_embeddings", 77),
            hidden_act=t2.get("hidden_act", "gelu"),
            projection_dim=t2.get("projection_dim", 1280),
            eos_token_id=t2.get("eos_token_id", 49407),
        )
        params["text2"] = _prep(_load_safetensors_dir(te2), dtype)
        tok2_dir = os.path.join(ckpt_dir, "tokenizer_2")
        tok2 = load_tok("tokenizer_2") if os.path.isdir(tok2_dir) else tokenizer
        return cfg, params, (tokenizer, tok2)
    return cfg, params, tokenizer
