"""Llama-family decoder (Llama 2/3, Mistral, Qwen2, TinyLlama; MoE variant for
Mixtral) as pure-functional JAX.

Design (TPU-first, not a llama.cpp translation):
- Parameters are a pytree of stacked per-layer weights ([L, ...] leading axis)
  and the forward pass is a single `lax.scan` over layers — one traced layer
  body regardless of depth, which keeps compile time flat for 80-layer models
  and lets XLA pipeline HBM weight streaming against MXU compute.
- Two entry points: `prefill` (dense causal attention over a bucketed prompt)
  and `decode_step` (one token per active slot against the slot KV cache).
  These are the programs the engine jits with shardings; the reference's
  equivalent split is llama.cpp's prompt-processing vs token-generation phases
  (timings surfaced at backend/backend.proto:169-170).
- GQA, RoPE (linear/llama3 scaling), RMSNorm, SwiGLU; optional qkv bias
  (Qwen2) and sparse-MoE MLP (Mixtral) chosen statically from ArchConfig.

Weight-name parity with HF checkpoints is handled in io.py (safetensors
loader), not here.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from localai_tpu.models.config import ArchConfig
from localai_tpu.models.quant import matmul, unembed_matmul
from localai_tpu.ops.attention import (
    decode_attention,  # noqa: F401 — public, used by tests/benchmarks
    decode_attention_appended,
    decode_attention_windowed,
    prefill_attention,
)
from localai_tpu.ops.norm import rms_norm
from localai_tpu.ops.rope import (
    apply_rope,
    rope_frequencies,
    rope_frequencies_local,
    rope_query_amp,
)

Params = dict[str, Any]


class KVCache(NamedTuple):
    """Slot KV cache: one contiguous region per batch slot.

    k, v: [L, B_slots, S_max, K_heads, head_dim]. Slot occupancy/lengths are
    tracked by the engine; shapes stay static under jit.

    Under MLA (DeepSeek-V2/V3, cfg.is_mla) the cache holds ONE latent row
    per token instead of per-head k/v: k is [L, B, S, 1, kv_lora_rank+rope]
    = [RMSNorm(c_kv) | RoPE(k_pe)] and v is zero-width ([..., 1, 0]) — the
    value read is served out of the same latent (absorbed-weight attention),
    so HBM per token is the published MLA number, not 2x it. Every write
    helper below is shape-generic, so the paged/windowed/fp8 machinery
    serves both layouts.
    """

    k: jnp.ndarray
    v: jnp.ndarray

    @staticmethod
    def zeros(cfg: ArchConfig, num_slots: int, max_seq: int, dtype=None) -> "KVCache":
        dtype = jnp.dtype(cfg.dtype) if dtype is None else dtype
        base = (cfg.num_layers, num_slots, max_seq, cfg.cache_kv_heads)
        return KVCache(
            k=jnp.zeros(base + (cfg.cache_k_dim,), dtype),
            v=jnp.zeros(base + (cfg.cache_v_dim,), dtype),
        )


def _dtype(cfg: ArchConfig):
    return jnp.dtype(cfg.dtype)


def _init_attn_layers(cfg: ArchConfig, rnd, keys, L: int) -> Params:
    """Attention + norm keys for a stack of L layers (standard or MLA)."""
    dt = _dtype(cfg)
    D = cfg.hidden_size
    H, K, Hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    layers: Params = {"attn_norm": jnp.ones((L, D), dt),
                      "mlp_norm": jnp.ones((L, D), dt)}
    if cfg.is_mla:
        r, rot = cfg.kv_lora_rank, cfg.qk_rope_head_dim
        n, vd = cfg.qk_nope_head_dim, cfg.v_head_dim
        if cfg.q_lora_rank:
            layers["wq_a"] = rnd(next(keys), (L, D, cfg.q_lora_rank))
            layers["q_norm_a"] = jnp.ones((L, cfg.q_lora_rank), dt)
            layers["wq_b"] = rnd(next(keys), (L, cfg.q_lora_rank, H * (n + rot)))
        else:
            layers["wq"] = rnd(next(keys), (L, D, H * (n + rot)))
        layers["wkv_a"] = rnd(next(keys), (L, D, r + rot))
        layers["kv_norm"] = jnp.ones((L, r), dt)
        # HF kv_b_proj [H·(n+v), r] split per head: w_kb maps latent→k_nope,
        # w_vb maps latent→v. Stored in HF's [out, in] orientation so the
        # absorbed einsums contract the shared r axis directly.
        layers["w_kb"] = rnd(next(keys), (L, H, n, r))
        layers["w_vb"] = rnd(next(keys), (L, H, vd, r))
        layers["wo"] = rnd(next(keys), (L, H * vd, D))
        return layers
    layers["wq"] = rnd(next(keys), (L, D, H * Hd))
    layers["wk"] = rnd(next(keys), (L, D, K * Hd))
    layers["wv"] = rnd(next(keys), (L, D, K * Hd))
    layers["wo"] = rnd(next(keys), (L, H * Hd, D))
    if cfg.post_norms:
        layers["post_attn_norm"] = jnp.ones((L, D), dt)
        layers["post_ffw_norm"] = jnp.ones((L, D), dt)
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((L, Hd), dt)
        layers["k_norm"] = jnp.ones((L, Hd), dt)
    if cfg.attn_qkv_bias:
        layers["bq"] = jnp.zeros((L, H * Hd), dt)
        layers["bk"] = jnp.zeros((L, K * Hd), dt)
        layers["bv"] = jnp.zeros((L, K * Hd), dt)
    return layers


def init_params(cfg: ArchConfig, key: jnp.ndarray, scale: float = 0.02) -> Params:
    """Random init with HF-compatible tree structure (stacked layers).

    DeepSeek-style models (first_k_dense > 0) split into two stacks:
    params["dense_layers"] holds the leading dense-MLP layers and
    params["layers"] the MoE layers (+ shared experts) — `_scan_layers`
    runs them as two scans with a shared layer body.
    """
    dt = _dtype(cfg)
    L, D, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    keys = iter(jax.random.split(key, 32))

    def rnd(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    kd = cfg.first_k_dense if cfg.is_moe else 0
    Lm = L - kd
    layers = _init_attn_layers(cfg, rnd, keys, Lm)
    if cfg.is_moe:
        E, Fm = cfg.num_experts, cfg.moe_inter_size
        layers["router"] = rnd(next(keys), (Lm, D, E))
        if cfg.router_bias:
            layers["router_bias"] = jnp.zeros((Lm, E), jnp.float32)
        layers["w_gate"] = rnd(next(keys), (Lm, E, D, Fm))
        layers["w_up"] = rnd(next(keys), (Lm, E, D, Fm))
        layers["w_down"] = rnd(next(keys), (Lm, E, Fm, D))
        if cfg.n_shared_experts:
            Fs = cfg.n_shared_experts * Fm
            layers["shared_gate"] = rnd(next(keys), (Lm, D, Fs))
            layers["shared_up"] = rnd(next(keys), (Lm, D, Fs))
            layers["shared_down"] = rnd(next(keys), (Lm, Fs, D))
    else:
        layers["w_gate"] = rnd(next(keys), (L, D, F))
        layers["w_up"] = rnd(next(keys), (L, D, F))
        layers["w_down"] = rnd(next(keys), (L, F, D))

    params: Params = {
        "embed": rnd(next(keys), (cfg.vocab_size, D)),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
    }
    if kd:
        dense = _init_attn_layers(cfg, rnd, keys, kd)
        dense["w_gate"] = rnd(next(keys), (kd, D, F))
        dense["w_up"] = rnd(next(keys), (kd, D, F))
        dense["w_down"] = rnd(next(keys), (kd, F, D))
        params["dense_layers"] = dense
    if not cfg.tie_embeddings:
        params["lm_head"] = rnd(next(keys), (cfg.vocab_size, D))
    return params


def _scan_layers(cfg: ArchConfig, params: Params, h, layer_fn, extras=()):
    """Scan the layer stack with a shared body. Homogeneous models run one
    scan; DeepSeek layouts run the dense-prefix stack then the MoE stack
    (the body's MLP branch keys statically on each stack's param tree), and
    per-layer outputs are re-concatenated to one [L, ...] stack. `extras`
    are per-layer arrays (cache slices) with a leading L axis."""
    L = cfg.num_layers
    kd = cfg.first_k_dense if ("dense_layers" in params) else 0
    if kd == 0:
        return jax.lax.scan(
            layer_fn, h, (params["layers"], jnp.arange(L)) + tuple(extras)
        )
    head = tuple(e[:kd] for e in extras)
    tail = tuple(e[kd:] for e in extras)
    h, out_d = jax.lax.scan(
        layer_fn, h, (params["dense_layers"], jnp.arange(kd)) + head
    )
    h, out_m = jax.lax.scan(
        layer_fn, h, (params["layers"], jnp.arange(kd, L)) + tail
    )
    out = jax.tree.map(lambda a, b: jnp.concatenate([a, b], axis=0), out_d, out_m)
    return h, out


def _moe_mm(x: jnp.ndarray, w, sub: str, impl: str = "auto",
            mesh=None) -> jnp.ndarray:
    """Per-expert matmul for plain or quantized expert weights. Quantized
    decode-shape calls dispatch to the fused Pallas dequant-matmul kernels
    (ops/quant_matmul, ISSUE 9); the einsum forms below stay the oracle."""
    if isinstance(w, dict):
        from localai_tpu.ops.quant_matmul import dispatch_moe_mm

        y = dispatch_moe_mm(x, w, sub, impl=impl, mesh=mesh)
        if y is not None:
            return y
        if "q" in w:
            out = jnp.einsum(sub, x, w["q"].astype(x.dtype))
            return out * w["s"].astype(x.dtype)[..., 0, :]
        return _moe_grouped_mm(x, w, sub)
    return jnp.einsum(sub, x, w)


def _moe_grouped_mm(x: jnp.ndarray, w: dict, sub: str) -> jnp.ndarray:
    """Grouped int4/int8 expert weights [E, G, gs(, packed), out] for the two
    MoE einsum shapes (see quant.grouped_matmul for the dequant math)."""
    from localai_tpu.models.quant import _grouped_values

    qv = _grouped_values(w, x.dtype)  # [E, G, gs, out]
    s = w["gs"].astype(x.dtype)[..., 0, :]  # [E, G, out]
    z = w["gz"].astype(x.dtype)[..., 0, :] if "gz" in w else None
    e, g, gs, n_out = qv.shape
    if sub == "...d,edf->...ef":  # x [..., D] shared across experts
        xg = x.reshape(*x.shape[:-1], g, gs)
        y = jnp.einsum("...gi,egin->...egn", xg, qv)
        out = (y * s).sum(axis=-2)
        if z is not None:
            out = out - jnp.einsum("...g,egn->...en", xg.sum(-1), z)
        return out
    if sub == "...ef,efd->...ed":  # x already per-expert [..., E, F]
        xg = x.reshape(*x.shape[:-2], e, g, gs)
        y = jnp.einsum("...egi,egin->...egn", xg, qv)
        out = (y * s).sum(axis=-2)
        if z is not None:
            out = out - jnp.einsum("...eg,egn->...en", xg.sum(-1), z)
        return out
    raise ValueError(f"unsupported MoE einsum {sub!r} for grouped weights")


def _moe_route(cfg: ArchConfig, lp: Params, x: jnp.ndarray):
    """Top-k router dispatch: returns (weights [..., k] f32, sel [..., k])."""
    if cfg.moe_family == "deepseek":
        return _deepseek_route(cfg, lp, x)
    router_logits = (x @ lp["router"]).astype(jnp.float32)  # [..., E]
    weights, sel = jax.lax.top_k(router_logits, cfg.num_experts_per_token)
    return jax.nn.softmax(weights, axis=-1), sel


def _deepseek_route(cfg: ArchConfig, lp: Params, x: jnp.ndarray):
    """DeepSeek-V2/V3 router (HF DeepseekV2MoEGate / DeepseekV3TopkRouter
    semantics): score ALL experts in f32 — softmax (V2) or sigmoid (V3) —
    then select top-k, optionally restricted to the topk_group best of
    n_group expert groups. V3 biases SELECTION by a learned per-expert
    correction (e_score_correction_bias) but weights by the unbiased scores,
    renormalized when norm_topk_prob. Returns weights already scaled by
    routed_scaling_factor."""
    E, k = cfg.num_experts, cfg.num_experts_per_token
    logits = jnp.einsum(
        "...d,de->...e", x.astype(jnp.float32), lp["router"].astype(jnp.float32)
    )
    sigmoid = cfg.scoring_func == "sigmoid"
    scores = jax.nn.sigmoid(logits) if sigmoid else jax.nn.softmax(logits, axis=-1)
    choice = scores + lp["router_bias"] if "router_bias" in lp else scores
    if cfg.n_group > 1:
        g = cfg.n_group
        cg = choice.reshape(*choice.shape[:-1], g, E // g)
        if sigmoid:  # V3: a group's score is the sum of its top-2 biased scores
            gscore = jax.lax.top_k(cg, 2)[0].sum(axis=-1)
        else:  # V2 group_limited_greedy: group max
            gscore = cg.max(axis=-1)
        _, gidx = jax.lax.top_k(gscore, cfg.topk_group)  # [..., topk_group]
        gmask = jax.nn.one_hot(gidx, g, dtype=jnp.float32).sum(axis=-2)  # [..., g]
        keep = jnp.repeat(gmask, E // g, axis=-1) > 0
        choice = jnp.where(keep, choice, 0.0)
    _, sel = jax.lax.top_k(choice, k)
    weights = jnp.take_along_axis(scores, sel, axis=-1)
    if cfg.norm_topk_prob and k > 1:
        weights = weights / (weights.sum(axis=-1, keepdims=True) + 1e-20)
        # Original DeepseekV2MoEGate: normalization REPLACES the scaling
        # factor on the softmax path; V3 (sigmoid) normalizes AND scales.
        if sigmoid:
            weights = weights * cfg.routed_scaling_factor
    else:
        weights = weights * cfg.routed_scaling_factor
    return weights, sel


def _moe_dense(cfg: ArchConfig, lp: Params, x: jnp.ndarray,
               mesh=None) -> jnp.ndarray:
    """All-experts MoE: every expert runs on every token, outputs combined by
    routing weight. FLOPs ∝ E, but the only path that works on quantized
    (int8/int4 grouped) expert weights without materializing a dequantized
    copy, and trivially shardable over "ep". Decode batches are tiny and
    weight-HBM-bound (every expert's weights are read regardless), so for
    quantized decode this is near-optimal anyway."""
    E = cfg.num_experts
    qk = cfg.quant_kernel
    weights, sel = _moe_route(cfg, lp, x)
    onehot = jax.nn.one_hot(sel, E, dtype=jnp.float32)  # [..., topk, E]
    combine = jnp.einsum("...te,...t->...e", onehot, weights)
    gate = _act(cfg, _moe_mm(x, lp["w_gate"], "...d,edf->...ef", qk, mesh))
    up = _moe_mm(x, lp["w_up"], "...d,edf->...ef", qk, mesh)
    expert_out = _moe_mm(gate * up, lp["w_down"], "...ef,efd->...ed", qk, mesh)  # [..., E, D]
    return jnp.einsum("...ed,...e->...d", expert_out.astype(jnp.float32), combine).astype(x.dtype)


def _moe_ragged(cfg: ArchConfig, lp: Params, x: jnp.ndarray) -> jnp.ndarray:
    """Exact top-k MoE via sort + `lax.ragged_dot`: per-token FLOPs ∝ top_k,
    not E (4× fewer than dense for Mixtral top-2-of-8).

    The (token, choice) pairs are stably sorted by expert id so each expert's
    rows are contiguous, then one grouped matmul per projection runs all
    experts without any capacity factor — no token is ever dropped, so the
    output is bit-comparable to the dense branch (up to f32 reduction order).
    The reference gets this for free from llama.cpp's per-expert CPU loops
    (ggml MoE graph); on TPU ragged_dot maps the grouped contraction onto the
    MXU with static shapes.
    """
    E, k = cfg.num_experts, cfg.num_experts_per_token
    lead, D = x.shape[:-1], x.shape[-1]
    xf = x.reshape(-1, D)
    N = xf.shape[0]
    weights, sel = _moe_route(cfg, lp, xf)  # [N, k]
    M = N * k
    e_flat = sel.reshape(M)
    order = jnp.argsort(e_flat, stable=True)  # expert-major, token-minor
    tok = order // k  # source token of each sorted row
    xg = jnp.take(xf, tok, axis=0)  # [M, D]
    if M < E:
        # Decode-scale batches can touch at most M of E experts. Gathering
        # just the active experts' weights bounds HBM weight traffic by
        # 2·M/E of the dense read — the mechanism that makes top-8-of-256
        # (DeepSeek-R1 class) MoE decode genuinely sparse, where
        # top-2-of-8 at batch ≥ 8 touches every expert anyway. `uniq` is
        # sorted, so the expert-major row order maps 1:1 onto gathered
        # group slots; pad slots (fill E, clipped for the gather) count
        # zero rows and contribute nothing.
        uniq = jnp.unique(e_flat, size=M, fill_value=E)  # [M] sorted ids
        gs = jnp.bincount(jnp.searchsorted(uniq, e_flat), length=M)
        gidx = jnp.minimum(uniq, E - 1)
        w_gate = jnp.take(lp["w_gate"], gidx, axis=0)
        w_up = jnp.take(lp["w_up"], gidx, axis=0)
        w_down = jnp.take(lp["w_down"], gidx, axis=0)
    else:
        gs = jnp.bincount(e_flat, length=E)  # rows per expert (sums to M)
        w_gate, w_up, w_down = lp["w_gate"], lp["w_up"], lp["w_down"]
    gate = _act(cfg, jax.lax.ragged_dot(xg, w_gate, gs))
    up = jax.lax.ragged_dot(xg, w_up, gs)
    dn = jax.lax.ragged_dot((gate * up).astype(xg.dtype), w_down, gs)  # [M, D]
    wf = jnp.take(weights.reshape(M), order)
    y = jnp.zeros((N, D), jnp.float32).at[tok].add(dn.astype(jnp.float32) * wf[:, None])
    return y.reshape(*lead, D).astype(x.dtype)


def _moe_capacity(cfg: ArchConfig, lp: Params, x: jnp.ndarray, block: int = 1024) -> jnp.ndarray:
    """GShard-style capacity-bucketed dispatch for expert-parallel meshes.

    Tokens are chunked into blocks; each block builds one-hot dispatch/combine
    tensors [Nb, E, C] with C = ceil(k·Nb/E · capacity_factor), so the expert
    contraction 'ecd,edf->ecf' has a static [E, C, D] operand whose E axis the
    SPMD partitioner places on the chips holding the "ep"-sharded weights —
    each chip computes only its local experts' rows and the combine einsum
    psums the outputs back. Total expert FLOPs ∝ k·cf, not E. Tokens past an
    expert's capacity are dropped (their routing weight renormalizes over the
    kept choices; if every choice drops, the residual passes through) — the
    standard GShard trade; capacity_factor=2 makes drops rare at inference.
    """
    E, k = cfg.num_experts, cfg.num_experts_per_token
    lead, D = x.shape[:-1], x.shape[-1]
    xf = x.reshape(-1, D)
    N = xf.shape[0]
    Nb = min(N, block)
    nblk = -(-N // Nb)
    pad = nblk * Nb - N
    if pad:
        xf = jnp.concatenate([xf, jnp.zeros((pad, D), xf.dtype)], axis=0)
    C = max(k, int(-(-k * Nb * cfg.moe_capacity_factor // E)))
    C = min(C, Nb)

    def blk(xb):  # [Nb, D]
        w, sel = _moe_route(cfg, lp, xb)  # [Nb, k] f32 / int
        oh = jax.nn.one_hot(sel, E, dtype=jnp.int32)  # [Nb, k, E]
        # Position of each (token, choice) in its expert's queue, in
        # flattened token-major order (earlier tokens win capacity).
        pos = jnp.cumsum(oh.reshape(Nb * k, E), axis=0).reshape(Nb, k, E) * oh - 1
        keep = (pos >= 0) & (pos < C)  # [Nb, k, E]
        slot = jax.nn.one_hot(jnp.where(keep, pos, 0), C, dtype=xb.dtype)
        slot = slot * keep[..., None].astype(xb.dtype)  # [Nb, k, E, C]
        disp = slot.sum(axis=1)  # [Nb, E, C] 0/1
        kept_k = keep.sum(axis=-1).astype(jnp.float32)  # [Nb, k] 0/1
        # Drop handling preserves each token's ORIGINAL routing-weight mass
        # (kept weights scale by total/kept): for Mixtral (softmaxed, total
        # = 1) this is the classic renormalization; for DeepSeek the
        # weights deliberately do NOT sum to 1 (sigmoid + routed_scaling),
        # so normalizing to 1 would corrupt every MoE output even with
        # nothing dropped.
        total = w.sum(axis=-1, keepdims=True)
        denom = jnp.maximum((w * kept_k).sum(axis=-1, keepdims=True), 1e-9)
        wr = w * kept_k * (total / denom)  # mass-preserving over kept choices
        comb = jnp.einsum("nk,nkec->nec", wr, slot.astype(jnp.float32))
        xe = jnp.einsum("nec,nd->ecd", disp, xb)  # [E, C, D]
        gate = _act(cfg, jnp.einsum("ecd,edf->ecf", xe, lp["w_gate"]))
        up = jnp.einsum("ecd,edf->ecf", xe, lp["w_up"])
        dn = jnp.einsum("ecf,efd->ecd", gate * up, lp["w_down"])
        return jnp.einsum("nec,ecd->nd", comb, dn.astype(jnp.float32))

    y = jax.lax.map(blk, xf.reshape(nblk, Nb, D)).reshape(nblk * Nb, D)[:N]
    return y.reshape(*lead, D).astype(x.dtype)


def _lora_add(cfg: ArchConfig, lora, key: str, x: jnp.ndarray,
              y: jnp.ndarray, part: str, mesh=None) -> jnp.ndarray:
    """y + the per-row ragged adapter delta for one target projection
    (multi-tenant runtime LoRA, ISSUE 10 / docs/LORA_SERVING.md): unmerged
    B·(A·x) beside the base matmul, so the base weights stay shared (and
    possibly int8/int4-quantized) while each row's tenant rides its own
    rank-r factors. lora = (per-layer stacks, ids) or None; stacks is the
    layer-scan slice {key: {"a": [NA, in, R], "b": [NA, R, out]}}; id 0 is
    the all-zero null adapter (exact no-op for adapter-less rows)."""
    if lora is None:
        return y
    la, ids = lora
    entry = la.get(key)
    if entry is None:
        return y
    from localai_tpu.ops.lora_matmul import lora_delta

    return y + lora_delta(
        x, entry, ids, impl=cfg.lora_kernel, mesh=mesh, part=part
    )


def _mlp(cfg: ArchConfig, lp: Params, x: jnp.ndarray, ep: int = 1,
         mesh=None, lora=None) -> jnp.ndarray:
    """SwiGLU MLP; dense or sparse-MoE (Mixtral/DeepSeek top-k routing).

    x: [..., D]. MoE is detected per-stack ("router" in lp) so DeepSeek's
    dense-prefix layers run the plain branch under the same body. MoE picks
    its implementation statically:
    - quantized expert weights → dense all-experts (the grouped-int kernels
      in models/quant.py only exist for the dense einsum shapes);
    - ep > 1 → GShard capacity dispatch (shards over the "ep" mesh axis);
    - otherwise → exact sort+ragged_dot top-k (FLOPs ∝ top_k; at decode
      batch sizes only the ACTIVE experts' weights are gathered, which is
      where top-8-of-256 models win — see _moe_ragged).
    DeepSeek MoE layers add an always-on shared-expert MLP (HF
    DeepseekV3MoE.shared_experts).
    """
    qk = cfg.quant_kernel
    if "router" not in lp:
        gate = _act(cfg, _lora_add(
            cfg, lora, "w_gate", x, matmul(x, lp["w_gate"], qk, mesh, "col"),
            "col", mesh,
        ))
        up = _lora_add(
            cfg, lora, "w_up", x, matmul(x, lp["w_up"], qk, mesh, "col"),
            "col", mesh,
        )
        gu = gate * up
        return _lora_add(
            cfg, lora, "w_down", gu, matmul(gu, lp["w_down"], qk, mesh, "row"),
            "row", mesh,
        ).astype(x.dtype)
    if isinstance(lp["w_gate"], dict):
        y = _moe_dense(cfg, lp, x, mesh=mesh)
    elif ep > 1:
        y = _moe_capacity(cfg, lp, x)
    else:
        y = _moe_ragged(cfg, lp, x)
    if "shared_gate" in lp:
        sg = _act(cfg, matmul(x, lp["shared_gate"], qk, mesh, "col"))
        y = y + matmul(sg * matmul(x, lp["shared_up"], qk, mesh, "col"),
                       lp["shared_down"], qk, mesh, "row").astype(x.dtype)
    return y


def _attn_out(cfg: ArchConfig, lp: Params, attn_flat: jnp.ndarray,
              mesh=None, lora=None) -> jnp.ndarray:
    """Output projection + optional gemma-2 post-attention sandwich norm.
    Shared by every layer body so per-arch structure changes in ONE place."""
    a = _lora_add(
        cfg, lora, "wo", attn_flat,
        matmul(attn_flat, lp["wo"], cfg.quant_kernel, mesh, "row"),
        "row", mesh,
    )
    if cfg.post_norms:
        a = rms_norm(a, lp["post_attn_norm"], cfg.rms_eps)
    return a


def _mlp_out(cfg: ArchConfig, lp: Params, x: jnp.ndarray, ep: int = 1,
             mesh=None, lora=None) -> jnp.ndarray:
    """MLP + optional gemma-2 post-feedforward sandwich norm."""
    m = _mlp(cfg, lp, x, ep, mesh=mesh, lora=lora)
    if cfg.post_norms:
        m = rms_norm(m, lp["post_ffw_norm"], cfg.rms_eps)
    return m


def _layer_sliding(cfg: ArchConfig, li: jnp.ndarray):
    """Which layers slide: li % pattern != pattern-1. Gemma-2 alternates
    (pattern 2: even layers slide, odd attend globally); gemma-3 runs
    5 local : 1 global (pattern 6). Returns a traced bool scalar (or None
    when the arch has no sliding windows)."""
    if not cfg.sliding_window:
        return None
    p = cfg.sliding_pattern
    return (li % p) != (p - 1)


def _layer_inv_freq(cfg: ArchConfig, inv_global, inv_local, li):
    """Per-layer rope schedule: gemma-3's sliding layers run their own
    unscaled local base while global layers use rope_theta (+ scaling)."""
    if inv_local is None:
        return inv_global
    sliding = _layer_sliding(cfg, li)
    return jnp.where(sliding, inv_local, inv_global)


def _attn_proj_qkv(cfg: ArchConfig, lp: Params, x: jnp.ndarray, mesh=None,
                   lora=None):
    """x: [..., D] -> q [..., H, Hd], k/v [..., K, Hd]."""
    H, K, Hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    qk = cfg.quant_kernel
    q = _lora_add(cfg, lora, "wq", x, matmul(x, lp["wq"], qk, mesh, "col"),
                  "col", mesh)
    k = _lora_add(cfg, lora, "wk", x, matmul(x, lp["wk"], qk, mesh, "col"),
                  "col", mesh)
    v = _lora_add(cfg, lora, "wv", x, matmul(x, lp["wv"], qk, mesh, "col"),
                  "col", mesh)
    if cfg.attn_qkv_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(*x.shape[:-1], H, Hd)
    k = k.reshape(*x.shape[:-1], K, Hd)
    v = v.reshape(*x.shape[:-1], K, Hd)
    if cfg.qk_norm:
        # Gemma-3: per-head RMS norms on q/k before rope.
        q = rms_norm(q, lp["q_norm"], cfg.rms_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_eps)
    if cfg.query_scale:
        # Gemma-2 scales attention by query_pre_attn_scalar^-0.5; the
        # attention kernels divide by sqrt(head_dim), so pre-multiply q by
        # the ratio (commutes with RoPE — a rotation).
        q = q * float((cfg.head_dim_ / cfg.query_scale) ** 0.5)
    amp = rope_query_amp(cfg)
    if amp != 1.0:
        # yarn/longrope attention-amplitude correction (m on both cos/sin
        # tables ≡ m² on q alone; K stays unmodified in the cache).
        q = q * float(amp)
    return q, k, v


# --------------------------------------------------------------------------- #
# Multi-head Latent Attention (DeepSeek-V2/V3; HF DeepseekV3Attention parity)
#
# Prefill runs full-rank: per-head k = [W_kb·c_kv | rope(k_pe)] and
# v = W_vb·c_kv are materialized (compute-bound phase, standard MHA shapes).
# Decode runs the absorbed-weight identity: q·k = [W_kbᵀq_nope | q_pe] ·
# [c_kv | k_pe], so attention is MQA against the cached LATENT rows, and the
# value read is served by passing the same latent array as the v operand —
# the output's first kv_lora_rank dims equal probs·c_kv, which W_vb lifts
# back to per-head values. One latent row per token is all HBM ever holds.
# --------------------------------------------------------------------------- #


def _mla_q(cfg: ArchConfig, lp: Params, x: jnp.ndarray, mesh=None) -> jnp.ndarray:
    """Query projection [..., H, qk_head_dim] (nope|rope concat, pre-rope);
    through the q-lora bottleneck when configured (V3) or direct (V2-Lite)."""
    qk = cfg.quant_kernel
    if cfg.q_lora_rank:
        # wq_a is replicated (the MLA bottleneck is tiny) — no shard part.
        ql = rms_norm(matmul(x, lp["wq_a"], qk), lp["q_norm_a"], cfg.rms_eps)
        q = matmul(ql, lp["wq_b"], qk, mesh, "col")
    else:
        q = matmul(x, lp["wq"], qk, mesh, "col")
    return q.reshape(*x.shape[:-1], cfg.num_heads, cfg.qk_head_dim)


def _mla_rows(cfg: ArchConfig, lp: Params, x: jnp.ndarray,
              positions: jnp.ndarray, inv: jnp.ndarray) -> jnp.ndarray:
    """Latent cache rows [B, T, 1, r+rot] = [RMSNorm(c_kv) | RoPE(k_pe)] for
    tokens x [B, T, D] at `positions` [B, T]. This is the ONLY thing MLA
    writes to the KV cache."""
    r = cfg.kv_lora_rank
    ckv = matmul(x, lp["wkv_a"], cfg.quant_kernel)  # [B, T, r+rot] (replicated weight)
    c = rms_norm(ckv[..., :r], lp["kv_norm"], cfg.rms_eps)
    k_pe = apply_rope(ckv[..., None, r:], positions, inv)  # [B, T, 1, rot]
    return jnp.concatenate([c[..., None, :], k_pe], axis=-1)


def _mla_full_qkv(cfg: ArchConfig, lp: Params, x: jnp.ndarray,
                  positions: jnp.ndarray, inv: jnp.ndarray, mesh=None):
    """Full-rank MLA projections for prefill. x [B, T, D] →
    (q [B,T,H,Dq], k [B,T,H,Dq], v [B,T,H,Dq] zero-padded from v_head_dim,
    rows [B,T,1,r+rot]). The ops reshape outputs to q's head dim, so v rides
    zero-padded and the caller slices [..., :v_head_dim]."""
    H = cfg.num_heads
    n, rot, vd = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
    r = cfg.kv_lora_rank
    q = _mla_q(cfg, lp, x, mesh)
    q = jnp.concatenate([q[..., :n], apply_rope(q[..., n:], positions, inv)], axis=-1)
    amp = rope_query_amp(cfg)
    if amp != 1.0:
        q = q * float(amp)
    rows = _mla_rows(cfg, lp, x, positions, inv)
    c, k_pe = rows[..., 0, :r], rows[..., :, r:]  # [B,T,r], [B,T,1,rot]
    k_nope = jnp.einsum("btr,hnr->bthn", c, lp["w_kb"]).astype(x.dtype)
    k = jnp.concatenate(
        [k_nope, jnp.broadcast_to(k_pe, (*k_pe.shape[:2], H, rot)).astype(x.dtype)],
        axis=-1,
    )
    v = jnp.einsum("btr,hvr->bthv", c, lp["w_vb"]).astype(x.dtype)
    v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, cfg.qk_head_dim - vd)))
    return q, k, v, rows


def _mla_absorbed_q(cfg: ArchConfig, lp: Params, x: jnp.ndarray,
                    positions: jnp.ndarray, inv: jnp.ndarray,
                    mesh=None) -> jnp.ndarray:
    """Absorbed decode query [B, T, H, r+rot] scoring directly against the
    latent cache. The attention ops scale by the OPERAND width (r+rot), so
    the sqrt((r+rot)/qk_head_dim) ratio is folded in here to restore the
    true 1/sqrt(qk_head_dim) softmax scale (same trick as query_scale)."""
    n = cfg.qk_nope_head_dim
    q = _mla_q(cfg, lp, x, mesh)
    q_pe = apply_rope(q[..., n:], positions, inv)
    q_lat = jnp.einsum("bthn,hnr->bthr", q[..., :n], lp["w_kb"]).astype(x.dtype)
    q_eff = jnp.concatenate([q_lat, q_pe.astype(x.dtype)], axis=-1)
    scale = ((cfg.kv_lora_rank + cfg.qk_rope_head_dim) / cfg.qk_head_dim) ** 0.5
    return q_eff * jnp.asarray(scale * rope_query_amp(cfg), x.dtype)


def _mla_unlatent(cfg: ArchConfig, lp: Params, attn: jnp.ndarray) -> jnp.ndarray:
    """Absorbed attention output [..., H, r+rot] → flat per-head values
    [..., H·v_head_dim] via W_vb (the deferred value up-projection)."""
    lat = attn[..., : cfg.kv_lora_rank]
    out = jnp.einsum("...hr,hvr->...hv", lat, lp["w_vb"].astype(lat.dtype))
    return out.reshape(*attn.shape[:-2], -1)


def _embed(cfg: ArchConfig, params: Params, tokens: jnp.ndarray) -> jnp.ndarray:
    """Token embedding lookup; Gemma scales hidden states by sqrt(D) here
    while the tied unembed reads the raw matrix."""
    h = params["embed"][tokens]
    if cfg.embed_scale:
        h = (h.astype(jnp.float32) * (cfg.hidden_size**0.5)).astype(h.dtype)
    return h


def _act(cfg: ArchConfig, x: jnp.ndarray) -> jnp.ndarray:
    """Gated-MLP activation: SwiGLU (llama family) or GeGLU (gemma)."""
    if cfg.activation == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _unembed(cfg: ArchConfig, params: Params, h: jnp.ndarray,
             mesh=None) -> jnp.ndarray:
    # bf16 (or int8-dequant) operands with f32 MXU accumulation: casting the
    # [V, D] matrix to f32 would double its HBM traffic on every decode step
    # (the unembed is the single largest weight read at 128k vocabs).
    w = params["embed"] if cfg.tie_embeddings else params["lm_head"]
    logits = unembed_matmul(h, w, cfg.quant_kernel, mesh)
    if cfg.final_softcap:
        logits = cfg.final_softcap * jnp.tanh(logits / cfg.final_softcap)
    return logits


def _forward_hidden(
    cfg: ArchConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32, right-padded
    lengths: jnp.ndarray,  # [B] int32 valid lengths
    collect_kv: bool,
    mesh=None,  # jax.sharding.Mesh with an "sp" axis > 1 → ring attention
    inject=None,  # (embeds [B, N, D], offsets [B]) — VLM image features
    ep: int = 1,  # expert-parallel degree (MoE implementation choice)
    mrope=None,  # [B, 3, S] (t, h, w) position streams — Qwen2-VL m-rope
    lora=None,  # (stacked adapter factors {key: {"a": [L,NA,in,R], "b":
    # [L,NA,R,out]}}, ids [B]) — per-row runtime LoRA (ISSUE 10)
):
    """Shared full-sequence forward. Returns (h [B,S,D] after final norm,
    length_mask [B,S], (ks, vs) or None). Single source of truth for the layer
    body used by both `prefill` and `encode`.

    With a mesh whose "sp" axis is > 1, attention runs as ring attention
    (localai_tpu.parallel.ring): the sequence axis shards over "sp" and KV
    blocks rotate neighbor-to-neighbor over ICI, so per-chip KV residency is
    S/sp — the long-context serving path (the reference has no sequence
    parallelism; SURVEY.md §5)."""
    B, S = tokens.shape
    use_ring = mesh is not None and mesh.shape.get("sp", 1) > 1
    if use_ring and S % mesh.shape["sp"] != 0:
        raise ValueError(f"sequence bucket {S} not divisible by sp={mesh.shape['sp']}")
    inv_freq = rope_frequencies(cfg)
    inv_local = rope_frequencies_local(cfg)
    positions = jnp.arange(S)[None, :].repeat(B, axis=0)  # [B, S]
    length_mask = jnp.arange(S)[None, :] < lengths[:, None]
    mrope_ang = None
    if mrope is not None:
        # Qwen2-VL m-rope (HF get_rope_index semantics): section-selected
        # per-frequency position streams; same split-half rotation.
        if not cfg.mrope_section:
            raise ValueError("mrope positions passed but cfg.mrope_section empty")
        if inv_local is not None:
            raise ValueError("mrope + per-layer local rope is unsupported")
        from localai_tpu.ops.rope import mrope_angles

        mrope_ang = mrope_angles(mrope, inv_freq, tuple(cfg.mrope_section))

    h = _embed(cfg, params, tokens)  # [B, S, D]
    if inject is not None:
        # Multimodal: overwrite the placeholder span with projected image
        # features (models/vision.py) — the llava injection point.
        embeds, offsets = inject
        h = jax.vmap(
            lambda hb, eb, ob: jax.lax.dynamic_update_slice(
                hb, eb.astype(hb.dtype), (ob, 0)
            )
        )(h, embeds, offsets)

    def layer(h, xs):
        if lora is None:
            lp, li = xs  # li: layer index (sliding windows alternate by layer)
            llora = None
        else:
            lp, li, la = xs  # la: this layer's adapter-factor slice
            llora = (la, lora[1])
        x = rms_norm(h, lp["attn_norm"], cfg.rms_eps)
        inv = _layer_inv_freq(cfg, inv_freq, inv_local, li)
        if cfg.is_mla:
            if use_ring:
                raise NotImplementedError(
                    "MLA + sequence parallelism is excluded this round "
                    "(PARITY.md: ring rotation of latent rows needs its own "
                    "kernel); shard MLA models over tp/ep instead"
                )
            q, k, v, rows = _mla_full_qkv(cfg, lp, x, positions, inv, mesh)
            # Dense path (no `lengths`): the flash kernel tiles head_dim in
            # 128-lane blocks and MLA's qk width (192) is not a multiple.
            attn = prefill_attention(q, k, v, length_mask)
            attn = attn[..., : cfg.v_head_dim]
            h = h + _attn_out(cfg, lp, attn.reshape(B, S, -1), mesh)
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
            h = h + _mlp_out(cfg, lp, x, ep, mesh)
            return h, (
                (rows, rows[..., :0]) if collect_kv else None
            )
        q, k, v = _attn_proj_qkv(cfg, lp, x, mesh, lora=llora)
        if mrope_ang is not None:
            from localai_tpu.ops.rope import rope_rotate

            q = rope_rotate(q, mrope_ang)
            k = rope_rotate(k, mrope_ang)
        else:
            q = apply_rope(q, positions, inv)
            k = apply_rope(k, positions, inv)
        if use_ring:
            from localai_tpu.parallel.ring import ring_prefill_attention

            attn = ring_prefill_attention(
                q, k, v, lengths, mesh,
                softcap=cfg.attn_softcap, window=cfg.sliding_window,
                sliding=_layer_sliding(cfg, li),
            )
        else:
            attn = prefill_attention(
                q, k, v, length_mask, lengths,
                softcap=cfg.attn_softcap, window=cfg.sliding_window,
                sliding=_layer_sliding(cfg, li), mesh=mesh,
            )
        h = h + _attn_out(cfg, lp, attn.reshape(B, S, -1), mesh, lora=llora)
        x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
        h = h + _mlp_out(cfg, lp, x, ep, mesh, lora=llora)
        return h, ((k, v) if collect_kv else None)

    extras = () if lora is None else (lora[0],)
    h, kv = _scan_layers(cfg, params, h, layer, extras)
    h = rms_norm(h, params["final_norm"], cfg.rms_eps)
    return h, length_mask, kv


def prefill(
    cfg: ArchConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32, right-padded
    lengths: jnp.ndarray,  # [B] int32 valid lengths
    mesh=None,  # Mesh with sp>1 → ring attention (sequence parallel)
    inject=None,  # (embeds [B, N, D], offsets [B]) — VLM image features
    ep: int = 1,
    mrope=None,  # [B, 3, S] m-rope position streams (Qwen2-VL)
    lora=None,  # (stacked adapter factors, ids [B]) — runtime LoRA
):
    """Prompt processing. Returns (last_logits [B, V] f32, k [L,B,S,K,Hd], v)."""
    h, _, (ks, vs) = _forward_hidden(
        cfg, params, tokens, lengths, collect_kv=True, mesh=mesh, inject=inject,
        ep=ep, mrope=mrope, lora=lora,
    )
    last_idx = jnp.maximum(lengths - 1, 0)  # empty prompt reads position 0, not wrap to S-1
    last = jnp.take_along_axis(h, last_idx[:, None, None], axis=1)[:, 0]  # [B, D]
    logits = _unembed(cfg, params, last, mesh)
    return logits, ks, vs


def encode(
    cfg: ArchConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32, right-padded
    lengths: jnp.ndarray,  # [B] int32
    mesh=None,
    ep: int = 1,
) -> jnp.ndarray:
    """Sentence embedding: masked mean-pool of final hidden states, L2-normed.

    Serves the Embedding RPC capability (reference: backend/backend.proto
    Embedding; backend/python/transformers SentenceTransformer branch) from the
    same decoder weights.
    """
    h, length_mask, _ = _forward_hidden(cfg, params, tokens, lengths, collect_kv=False, mesh=mesh, ep=ep)
    h = h.astype(jnp.float32)
    mask = length_mask[..., None].astype(jnp.float32)
    pooled = (h * mask).sum(axis=1) / jnp.maximum(mask.sum(axis=1), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)


def sequence_logprob(
    cfg: ArchConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, S] int32, right-padded
    lengths: jnp.ndarray,  # [B] int32 total valid length
    cond_lengths: jnp.ndarray,  # [B] int32 — score only positions >= cond_len
    mesh=None,
    ep: int = 1,
) -> jnp.ndarray:
    """Mean log P(tokens[cond_len:len] | tokens[:cond_len]) per row — the
    scoring primitive behind reranking (reference capability: core/backend/
    rerank.go RPC to a cross-encoder; here relevance is measured as the
    document's conditional likelihood under the LLM given the query)."""
    h, _, _ = _forward_hidden(cfg, params, tokens, lengths, collect_kv=False, mesh=mesh, ep=ep)
    logits = _unembed(cfg, params, h[:, :-1], mesh)  # [B, S-1, V] predicts tokens[1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    tgt = tokens[:, 1:]  # [B, S-1]
    tok_lp = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    pos = jnp.arange(tgt.shape[1])[None, :] + 1  # position of the target token
    valid = (pos >= cond_lengths[:, None]) & (pos < lengths[:, None])
    n = jnp.maximum(valid.sum(axis=-1), 1)
    return (tok_lp * valid).sum(axis=-1) / n  # [B]


def decode_step(
    cfg: ArchConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B] int32 current token per slot
    positions: jnp.ndarray,  # [B] int32 position of `tokens` in each sequence
    cache: KVCache,
    ep: int = 1,
    mesh=None,  # Mesh with sp>1 → the cache's sequence axis is sp-sharded
):
    """One decode step for the whole slot batch.

    Writes the new k/v at `positions` and attends over [0, positions]. Returns
    (logits [B, V] f32, new_cache). The engine jits this with the cache donated
    so XLA updates it in place in HBM.

    HBM-traffic design (found by profiling the serving engine on a v5e): the
    layer scan must NOT carry or re-emit the cache — stacking per-layer cache
    outputs rewrites the entire [L,B,S,K,Hd] buffer every token (hundreds of
    MB of pure waste). Instead each layer reads its cache slice (scan `xs`,
    a view), attends over `cache ⊕ current token` with the current k/v kept
    separate, and emits only the new [B,K,Hd] row; ONE scatter after the scan
    writes all L rows into the stacked cache in place.
    """
    B = tokens.shape[0]
    use_sp = mesh is not None and mesh.shape.get("sp", 1) > 1
    inv_freq = rope_frequencies(cfg)
    inv_local = rope_frequencies_local(cfg)
    h = _embed(cfg, params, tokens)  # [B, D]
    batch_idx = jnp.arange(B)

    def layer(h, xs):
        lp, li, kc, vc = xs
        x = rms_norm(h, lp["attn_norm"], cfg.rms_eps)
        inv = _layer_inv_freq(cfg, inv_freq, inv_local, li)
        if cfg.is_mla:
            if use_sp:
                raise NotImplementedError("MLA + sp is excluded (PARITY.md)")
            x1 = x[:, None]  # [B, 1, D]
            q_eff = _mla_absorbed_q(cfg, lp, x1, positions[:, None], inv, mesh)[:, 0]
            rows = _mla_rows(cfg, lp, x1, positions[:, None], inv)[:, 0]  # [B,1,r+rot]
            # The latent rides as BOTH k and v operands; [..., :r] of the
            # output is probs·c_kv (see the MLA section header).
            attn = decode_attention_appended(q_eff, kc, kc, rows, rows, positions)
            h = h + _attn_out(cfg, lp, _mla_unlatent(cfg, lp, attn), mesh)
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
            h = h + _mlp_out(cfg, lp, x, ep, mesh)
            return h, (rows, rows[..., :0])
        q, k, v = _attn_proj_qkv(cfg, lp, x, mesh)  # q [B,H,Hd], k/v [B,K,Hd]
        q = apply_rope(q[:, None], positions[:, None], inv)[:, 0]
        k = apply_rope(k[:, None], positions[:, None], inv)[:, 0]
        if use_sp:
            from localai_tpu.ops.attention import decode_attention_appended_sp

            attn = decode_attention_appended_sp(
                q, kc, vc, k, v, positions, mesh,
                softcap=cfg.attn_softcap, window=cfg.sliding_window,
                sliding=_layer_sliding(cfg, li),
            )
        else:
            attn = decode_attention_appended(
                q, kc, vc, k, v, positions,
                softcap=cfg.attn_softcap, window=cfg.sliding_window,
                sliding=_layer_sliding(cfg, li),
            )
        h = h + _attn_out(cfg, lp, attn.reshape(B, -1), mesh)
        x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
        h = h + _mlp_out(cfg, lp, x, ep, mesh)
        return h, (k, v)

    h, (new_k, new_v) = _scan_layers(
        cfg, params, h, layer, (cache.k, cache.v)
    )
    # One scatter: cache[l, b, positions[b]] = new row, all layers at once.
    k = cache.k.at[:, batch_idx, positions].set(new_k.astype(cache.k.dtype))
    v = cache.v.at[:, batch_idx, positions].set(new_v.astype(cache.v.dtype))
    h = rms_norm(h, params["final_norm"], cfg.rms_eps)
    logits = _unembed(cfg, params, h, mesh)
    return logits, KVCache(k=k, v=v)


def self_draft_view(cfg: ArchConfig, params: Params):
    """Early-exit draft view for `spec_mode=self_draft` (ISSUE 12,
    docs/SPECULATIVE.md): the target's first `cfg.self_draft_layers` layers
    plus the SHARED embed/final-norm/unembed act as the draft model, so one
    set of sharded weights serves both roles.

    Called INSIDE the traced spec program: the [:k] slices of the stacked
    [L, ...] layer tensors are views XLA fuses into the draft scan's operand
    reads — no second parameter tree is ever materialized in HBM (the whole
    point vs a separate draft checkpoint). Works for plain and quantized
    stacks alike (every leaf, scale tensors included, carries the leading L
    axis). Heterogeneous stacks (MoE / DeepSeek dense-prefix / MLA) are
    rejected at engine construction, not here.

    Returns (draft_cfg, draft_params): cfg with num_layers=k, params with
    only the sliced homogeneous "layers" stack swapped.
    """
    k = cfg.self_draft_layers
    assert 0 < k < cfg.num_layers, "engine validates self_draft_layers"
    view = {name: leaf for name, leaf in params.items() if name != "layers"}
    view["layers"] = jax.tree.map(lambda a: a[:k], params["layers"])
    import dataclasses as _dc

    return _dc.replace(cfg, num_layers=k), view


def decode_step_windowed(
    cfg: ArchConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B] current token per slot
    positions: jnp.ndarray,  # [B] its position
    cache: KVCache,  # READ-ONLY within a decode block
    local_k: jnp.ndarray,  # [L, B, n, K, Hd] — block-local KV window
    local_v: jnp.ndarray,
    step: jnp.ndarray,  # scalar index within the block
    ep: int = 1,
    mesh=None,  # Mesh: sp>1 → sp-sharded cache; tp>1 → head-sharded Pallas
    ptable=None,  # [B, MP] int32 → `cache` is a page pool (paged KV mode)
    paged_impl: str = "auto",  # paged attention kernel: auto|pallas|xla
    kv_scale=None,  # [2, K] f32 per-head (k, v) pool dequant scales (fp8 KV)
    rope_delta=None,  # [B] int32 — m-rope: rope at positions+delta (cache
    # rows stay at positions). After a Qwen2-VL image prefill the 3D
    # position streams are all equal and offset from the row index by a
    # per-request constant, so plain rope at the shifted position is exact.
    lora=None,  # (stacked adapter factors, ids [B]) — per-slot runtime
    # LoRA deltas applied unmerged beside the base matmuls (ISSUE 10)
):
    """One step of a fused decode block with a block-local KV window.

    The cache is never written here — each layer emits its new row, which is
    appended to the local window; the engine scatters the whole window into
    the cache once per block. Returns (logits [B, V] f32, local_k, local_v).
    One layer body serves all three cache layouts (dense / sp-sharded /
    paged) — only the attention call differs.
    """
    B = tokens.shape[0]
    use_sp = mesh is not None and mesh.shape.get("sp", 1) > 1
    inv_freq = rope_frequencies(cfg)
    inv_local = rope_frequencies_local(cfg)
    rope_pos = positions if rope_delta is None else positions + rope_delta
    h = _embed(cfg, params, tokens)

    def layer(h, xs):
        if lora is None:
            lp, li, kc, vc, lk, lv = xs
            llora = None
        else:
            lp, li, kc, vc, lk, lv, la = xs
            llora = (la, lora[1])
        x = rms_norm(h, lp["attn_norm"], cfg.rms_eps)
        inv = _layer_inv_freq(cfg, inv_freq, inv_local, li)
        if cfg.is_mla:
            if use_sp:
                raise NotImplementedError("MLA + sp is excluded (PARITY.md)")
            x1 = x[:, None]
            q_eff = _mla_absorbed_q(cfg, lp, x1, positions[:, None], inv, mesh)[:, 0]
            rows = _mla_rows(cfg, lp, x1, positions[:, None], inv)[:, 0]
            if ptable is not None:
                from localai_tpu.ops.attention import (
                    decode_attention_windowed_paged,
                )

                attn = decode_attention_windowed_paged(
                    q_eff, kc, kc, ptable, lk, lk, rows, rows, positions, step,
                    impl=paged_impl, kv_scale=kv_scale,
                )
            else:
                attn = decode_attention_windowed(
                    q_eff, kc, kc, lk, lk, rows, rows, positions, step,
                )
            h = h + _attn_out(cfg, lp, _mla_unlatent(cfg, lp, attn), mesh)
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
            h = h + _mlp_out(cfg, lp, x, ep, mesh)
            return h, (rows, rows[..., :0])
        q, k, v = _attn_proj_qkv(cfg, lp, x, mesh, lora=llora)
        q = apply_rope(q[:, None], rope_pos[:, None], inv)[:, 0]
        k = apply_rope(k[:, None], rope_pos[:, None], inv)[:, 0]
        if ptable is not None:
            from localai_tpu.ops.attention import decode_attention_windowed_paged

            attn = decode_attention_windowed_paged(
                q, kc, vc, ptable, lk, lv, k, v, positions, step,
                softcap=cfg.attn_softcap, window=cfg.sliding_window,
                sliding=_layer_sliding(cfg, li), impl=paged_impl, mesh=mesh,
                kv_scale=kv_scale, sink=cfg.attention_sink,
                swin=cfg.attention_window,
            )
        elif use_sp:
            from localai_tpu.ops.attention import decode_attention_windowed_sp

            attn = decode_attention_windowed_sp(
                q, kc, vc, lk, lv, k, v, positions, step, mesh,
                softcap=cfg.attn_softcap, window=cfg.sliding_window,
                sliding=_layer_sliding(cfg, li), sink=cfg.attention_sink,
                swin=cfg.attention_window,
            )
        else:
            attn = decode_attention_windowed(
                q, kc, vc, lk, lv, k, v, positions, step,
                softcap=cfg.attn_softcap, window=cfg.sliding_window,
                sliding=_layer_sliding(cfg, li), sink=cfg.attention_sink,
                swin=cfg.attention_window,
            )
        h = h + _attn_out(cfg, lp, attn.reshape(B, -1), mesh, lora=llora)
        x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
        h = h + _mlp_out(cfg, lp, x, ep, mesh, lora=llora)
        return h, (k, v)

    extras = (cache.k, cache.v, local_k, local_v)
    if lora is not None:
        extras = extras + (lora[0],)
    h, (new_k, new_v) = _scan_layers(cfg, params, h, layer, extras)
    local_k = jax.lax.dynamic_update_index_in_dim(
        local_k, new_k.astype(local_k.dtype), step, axis=2
    )
    local_v = jax.lax.dynamic_update_index_in_dim(
        local_v, new_v.astype(local_v.dtype), step, axis=2
    )
    h = rms_norm(h, params["final_norm"], cfg.rms_eps)
    return _unembed(cfg, params, h, mesh), local_k, local_v


def write_block_to_cache(
    cache: KVCache,
    local_k: jnp.ndarray,  # [L, B, n, K, Hd]
    local_v: jnp.ndarray,
    start_positions: jnp.ndarray,  # [B] — block start per slot
) -> KVCache:
    """Scatter a decode block's local KV window into the cache (once per
    block). Overshooting rows clamp to S-1 (host discards those tokens)."""
    L, B, n = local_k.shape[:3]
    S = cache.k.shape[2]
    span = jnp.minimum(start_positions[:, None] + jnp.arange(n)[None, :], S - 1)
    bi = jnp.arange(B)[:, None]
    k = cache.k.at[:, bi, span].set(local_k.astype(cache.k.dtype))
    v = cache.v.at[:, bi, span].set(local_v.astype(cache.v.dtype))
    return KVCache(k=k, v=v)


def decode_chunk(
    cfg: ArchConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, T] — T new tokens per slot (draft window)
    positions: jnp.ndarray,  # [B, T] int32 — their positions (contiguous per slot)
    cache: KVCache,
    ep: int = 1,
    ptable=None,  # [B, MP] int32 → `cache` is a page pool (paged KV mode)
    paged_impl: str = "auto",  # paged attention kernel: auto|pallas|xla
    mesh=None,  # Mesh with tp>1 → paged Pallas kernel head-sharded
    kv_scale=None,  # [2, K] f32 per-head (k, v) pool dequant scales (fp8 KV)
    lora=None,  # (stacked adapter factors, ids [B]) — per-slot runtime LoRA
    # deltas applied unmerged beside the base matmuls, so model-free spec
    # verify composes with multi-tenant adapters (ISSUE 12; the [B, T, in]
    # delta rides the XLA gather oracle, same as prefill)
):
    """Multi-token decode: write T new k/v per slot and return logits for all
    T positions — the verify pass of speculative decoding (the reference
    passes draft tokens to llama.cpp's batch decode; model_config.go:211
    draft_model). Positions must be contiguous per slot. Token t attends to
    the cache prefix (< positions[b, 0]) plus in-window tokens causally; the
    window k/v stay separate operands so — as in decode_step — the layer
    scan never re-emits the cache, and one scatter writes all L×T rows.
    With `ptable`, the prefix read walks the page pool (online-softmax
    partials) and the write routes through the table — speculative decoding
    composes with the paged cache."""
    B, T = tokens.shape
    inv_freq = rope_frequencies(cfg)
    h = _embed(cfg, params, tokens)  # [B, T, D]
    batch_idx = jnp.arange(B)[:, None].repeat(T, axis=1)  # [B, T]
    inv_local = rope_frequencies_local(cfg)
    scale = cfg.head_dim_**-0.5
    causal = jnp.tril(jnp.ones((T, T), bool))
    S = None if ptable is not None else cache.k.shape[2]
    # In-window distance t-u (positions are contiguous per slot), for the
    # gemma-2 sliding mask.
    win_dist = jnp.arange(T)[:, None] - jnp.arange(T)[None, :]

    def layer(h, xs):
        if lora is None:
            lp, li, kc, vc = xs
            llora = None
        else:
            lp, li, kc, vc, la = xs
            llora = (la, lora[1])
        sliding = _layer_sliding(cfg, li)
        x = rms_norm(h, lp["attn_norm"], cfg.rms_eps)
        inv = _layer_inv_freq(cfg, inv_freq, inv_local, li)
        if cfg.is_mla:
            # Absorbed MLA verify chunk: q_eff scores the latent cache and
            # the window's fresh latent rows; values come back out of the
            # same latents ([..., :r] → W_vb).
            q_eff = _mla_absorbed_q(cfg, lp, x, positions, inv, mesh)  # [B,T,H,De]
            rows = _mla_rows(cfg, lp, x, positions, inv)  # [B,T,1,De]
            if ptable is not None:
                from localai_tpu.ops.attention import (
                    _merge_partials_mq,
                    paged_partials_mq,
                )

                acc, m, l = paged_partials_mq(
                    q_eff, kc, kc, ptable, positions[:, 0], q_pos=positions,
                    impl=paged_impl, kv_scale=kv_scale,
                )
                attn = _merge_partials_mq(
                    q_eff, acc, m, l, rows, rows,  # [B, T, 1, De] = [B, E, K, D]
                    jnp.broadcast_to(causal[None], (B, T, T)),
                )
            else:
                De = q_eff.shape[-1]
                qf = (q_eff.astype(jnp.float32) / De**0.5)
                kcf = kc[..., 0, :].astype(jnp.float32)  # [B, S, De]
                rf = rows[..., 0, :].astype(jnp.float32)  # [B, T, De]
                sc = jnp.einsum("bthd,bsd->bhts", qf, kcf)
                prefix = jnp.arange(S)[None, None, :] < positions[:, :1, None]
                sc = jnp.where(prefix[:, None], sc, -1e30)
                sw = jnp.einsum("bthd,bud->bhtu", qf, rf)
                sw = jnp.where(causal[None, None], sw, -1e30)
                probs = jax.nn.softmax(jnp.concatenate([sc, sw], axis=-1), axis=-1)
                attn = jnp.einsum("bhts,bsd->bthd", probs[..., :S], kcf) + jnp.einsum(
                    "bhtu,bud->bthd", probs[..., S:], rf
                )
                attn = attn.astype(h.dtype)
            attn = _mla_unlatent(cfg, lp, attn)  # [B, T, H·v]
            h = h + _attn_out(cfg, lp, attn, mesh)
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
            h = h + _mlp_out(cfg, lp, x, ep, mesh)
            return h, (rows, rows[..., :0])
        q, k, v = _attn_proj_qkv(cfg, lp, x, mesh, lora=llora)  # q [B,T,H,Hd], k/v [B,T,K,Hd]
        q = apply_rope(q, positions, inv)
        k = apply_rope(k, positions, inv)
        K_h = kc.shape[2]
        G = q.shape[2] // K_h
        wmask = causal  # [T, T]
        if cfg.sliding_window and sliding is not None:
            wmask = wmask & (~sliding | (win_dist < cfg.sliding_window))
        if ptable is not None:
            from localai_tpu.ops.attention import (
                _merge_partials_mq,
                paged_partials_mq,
            )

            acc, m, l = paged_partials_mq(
                q, kc, vc, ptable, positions[:, 0],
                softcap=cfg.attn_softcap, window=cfg.sliding_window,
                sliding=sliding, q_pos=positions, impl=paged_impl, mesh=mesh,
                kv_scale=kv_scale,
            )
            attn = _merge_partials_mq(
                q, acc, m, l, k, v,
                jnp.broadcast_to(wmask[None], (B, T, T)),
                softcap=cfg.attn_softcap,
            ).reshape(B, T, -1).astype(h.dtype)
        else:
            qf = (q.astype(jnp.float32) * scale).reshape(B, T, K_h, G, cfg.head_dim_)
            # Cache prefix: rows before the window start (later rows stale).
            sc = jnp.einsum("btkgd,bskd->bkgts", qf, kc.astype(jnp.float32))
            if cfg.attn_softcap:
                sc = cfg.attn_softcap * jnp.tanh(sc / cfg.attn_softcap)
            prefix = jnp.arange(S)[None, None, :] < positions[:, :1, None]  # [B,1,S]
            if cfg.sliding_window and sliding is not None:
                dist = positions[:, :, None] - jnp.arange(S)[None, None, :]
                prefix = prefix & (~sliding | (dist < cfg.sliding_window))
            sc = jnp.where(prefix[:, None, None], sc, -1e30)
            # In-window causal attention against the fresh k.
            sw = jnp.einsum("btkgd,bukd->bkgtu", qf, k.astype(jnp.float32))
            if cfg.attn_softcap:
                sw = cfg.attn_softcap * jnp.tanh(sw / cfg.attn_softcap)
            sw = jnp.where(wmask[None, None, None], sw, -1e30)
            probs = jax.nn.softmax(jnp.concatenate([sc, sw], axis=-1), axis=-1)
            attn = jnp.einsum(
                "bkgts,bskd->btkgd", probs[..., :S], vc.astype(jnp.float32)
            ) + jnp.einsum("bkgtu,bukd->btkgd", probs[..., S:], v.astype(jnp.float32))
            attn = attn.reshape(B, T, -1).astype(h.dtype)
        h = h + _attn_out(cfg, lp, attn, mesh, lora=llora)
        x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
        h = h + _mlp_out(cfg, lp, x, ep, mesh, lora=llora)
        return h, (k, v)

    extras = (cache.k, cache.v)
    if lora is not None:
        extras = extras + (lora[0],)
    h, (new_k, new_v) = _scan_layers(cfg, params, h, layer, extras)
    if ptable is not None:
        cache = write_chunk_to_pool(cache, ptable, new_k, new_v, positions,
                                    kv_scale=kv_scale)
    else:
        k = cache.k.at[:, batch_idx, positions].set(new_k.astype(cache.k.dtype))
        v = cache.v.at[:, batch_idx, positions].set(new_v.astype(cache.v.dtype))
        cache = KVCache(k=k, v=v)
    h = rms_norm(h, params["final_norm"], cfg.rms_eps)
    logits = _unembed(cfg, params, h, mesh)  # [B, T, V]
    return logits, cache


def prefill_tail(
    cfg: ArchConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, T] int32 tail tokens, right-padded
    lengths: jnp.ndarray,  # [B] int32 valid tail lengths
    offsets: jnp.ndarray,  # [B] int32 cached-prefix lengths (tail starts here)
    prefix_k: jnp.ndarray,  # [L, B, P, K, Hd] cached prefix KV; rows >= offsets[b] ignored
    prefix_v: jnp.ndarray,
    ep: int = 1,
    mesh=None,  # Mesh with tp>1 → quantized matmuls shard_map over "tp"
):
    """Prefill a prompt *tail* against cached prefix KV — the compute half of
    the prompt/prefix cache (reference: `cache_prompt`,
    backend/cpp/llama-cpp/grpc-server.cpp:125; `prompt_cache_path`,
    core/config/model_config.go:185-187). Token t of the tail attends to the
    prefix rows [0, offsets) plus the tail causally; RoPE positions are offset
    by the prefix length so the result is identical to prefilling the whole
    prompt. Returns (last_logits [B, V] f32, tail_ks [L, B, T, K, Hd],
    tail_vs) — the engine writes the tail rows after the cached span.
    """
    B, T = tokens.shape
    P = prefix_k.shape[2]
    inv_freq = rope_frequencies(cfg)
    inv_local = rope_frequencies_local(cfg)
    positions = offsets[:, None] + jnp.arange(T)[None, :]  # [B, T] global
    length_mask = jnp.arange(T)[None, :] < lengths[:, None]
    h = _embed(cfg, params, tokens)  # [B, T, D]
    scale = cfg.head_dim_**-0.5
    causal = jnp.tril(jnp.ones((T, T), bool))
    pvalid = jnp.arange(P)[None, :] < offsets[:, None]  # [B, P]
    win_dist = jnp.arange(T)[:, None] - jnp.arange(T)[None, :]  # in-tail t-u

    def layer(h, xs):
        lp, li, kc, vc = xs  # kc/vc [B, P, K, Hd]
        sliding = _layer_sliding(cfg, li)
        x = rms_norm(h, lp["attn_norm"], cfg.rms_eps)
        inv = _layer_inv_freq(cfg, inv_freq, inv_local, li)
        if cfg.is_mla:
            # Absorbed tail prefill against cached LATENT prefix rows: the
            # identity q·k = q_eff·latent holds for the in-tail tokens too,
            # so both segments score in latent space.
            q_eff = _mla_absorbed_q(cfg, lp, x, positions, inv, mesh)  # [B,T,H,De]
            rows = _mla_rows(cfg, lp, x, positions, inv)  # [B,T,1,De]
            De = q_eff.shape[-1]
            qf = q_eff.astype(jnp.float32) / De**0.5
            kcf = kc[..., 0, :].astype(jnp.float32)  # [B, P, De]
            rf = rows[..., 0, :].astype(jnp.float32)  # [B, T, De]
            sc = jnp.einsum("bthd,bsd->bhts", qf, kcf)
            sc = jnp.where(pvalid[:, None, None], sc, -1e30)
            sw = jnp.einsum("bthd,bud->bhtu", qf, rf)
            wm = causal[None, None] & length_mask[:, None, None, :]
            sw = jnp.where(wm, sw, -1e30)
            probs = jax.nn.softmax(jnp.concatenate([sc, sw], axis=-1), axis=-1)
            attn = jnp.einsum("bhts,bsd->bthd", probs[..., :P], kcf) + jnp.einsum(
                "bhtu,bud->bthd", probs[..., P:], rf
            )
            attn = _mla_unlatent(cfg, lp, attn.astype(h.dtype))
            h = h + _attn_out(cfg, lp, attn, mesh)
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
            h = h + _mlp_out(cfg, lp, x, ep, mesh)
            return h, (rows, rows[..., :0])
        q, k, v = _attn_proj_qkv(cfg, lp, x, mesh)  # q [B,T,H,Hd], k/v [B,T,K,Hd]
        q = apply_rope(q, positions, inv)
        k = apply_rope(k, positions, inv)
        K_h = kc.shape[2]
        G = q.shape[2] // K_h
        qf = (q.astype(jnp.float32) * scale).reshape(B, T, K_h, G, cfg.head_dim_)
        sc = jnp.einsum("btkgd,bskd->bkgts", qf, kc.astype(jnp.float32))
        if cfg.attn_softcap:
            sc = cfg.attn_softcap * jnp.tanh(sc / cfg.attn_softcap)
        pmask = pvalid[:, None, :]  # [B, 1, P]
        if cfg.sliding_window and sliding is not None:
            dist = positions[:, :, None] - jnp.arange(P)[None, None, :]
            pmask = pmask & (~sliding | (dist < cfg.sliding_window))
        sc = jnp.where(pmask[:, None, None], sc, -1e30)
        sw = jnp.einsum("btkgd,bukd->bkgtu", qf, k.astype(jnp.float32))
        if cfg.attn_softcap:
            sw = cfg.attn_softcap * jnp.tanh(sw / cfg.attn_softcap)
        cmask = causal
        if cfg.sliding_window and sliding is not None:
            cmask = cmask & (~sliding | (win_dist < cfg.sliding_window))
        wmask = cmask[None, None, None] & length_mask[:, None, None, None, :]
        sw = jnp.where(wmask, sw, -1e30)
        probs = jax.nn.softmax(jnp.concatenate([sc, sw], axis=-1), axis=-1)
        attn = jnp.einsum(
            "bkgts,bskd->btkgd", probs[..., :P], vc.astype(jnp.float32)
        ) + jnp.einsum("bkgtu,bukd->btkgd", probs[..., P:], v.astype(jnp.float32))
        attn = attn.reshape(B, T, -1).astype(h.dtype)
        h = h + _attn_out(cfg, lp, attn, mesh)
        x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
        h = h + _mlp_out(cfg, lp, x, ep, mesh)
        return h, (k, v)

    h, (ks, vs) = _scan_layers(
        cfg, params, h, layer, (prefix_k, prefix_v)
    )
    h = rms_norm(h, params["final_norm"], cfg.rms_eps)
    last_idx = jnp.maximum(lengths - 1, 0)
    last = jnp.take_along_axis(h, last_idx[:, None, None], axis=1)[:, 0]  # [B, D]
    logits = _unembed(cfg, params, last, mesh)
    return logits, ks, vs


def write_prefill_to_cache(
    cache: KVCache,
    ks: jnp.ndarray,  # [L, B_new, S, K, Hd] from prefill
    vs: jnp.ndarray,
    slot: jnp.ndarray,  # scalar int32 — destination slot for batch row 0
) -> KVCache:
    """Copy a prefilled request's k/v into its slot (batch row 0 only).

    jit-friendly: dynamic_update_slice along the slot axis.
    """
    k = jax.lax.dynamic_update_slice(
        cache.k, ks[:, :1].astype(cache.k.dtype), (0, slot, 0, 0, 0)
    )
    v = jax.lax.dynamic_update_slice(
        cache.v, vs[:, :1].astype(cache.v.dtype), (0, slot, 0, 0, 0)
    )
    return KVCache(k=k, v=v)


# --------------------------------------------------------------------------- #
# Paged KV cache (page pool + per-slot page tables — ops/attention.py paged)
# --------------------------------------------------------------------------- #


def paged_cache_zeros(cfg: ArchConfig, num_pages: int, page_size: int,
                      dtype=None) -> KVCache:
    """Page pool: k/v [L, P, page, K, Hd]. One pool serves every slot; the
    engine assigns pages to slots and passes per-slot tables to each program.
    HBM scales with pages in use, not slots × max_seq (SURVEY §7 ragged KV).
    MLA pools hold latent rows (see KVCache docstring)."""
    dtype = jnp.dtype(cfg.dtype) if dtype is None else dtype
    base = (cfg.num_layers, num_pages, page_size, cfg.cache_kv_heads)
    return KVCache(
        k=jnp.zeros(base + (cfg.cache_k_dim,), dtype),
        v=jnp.zeros(base + (cfg.cache_v_dim,), dtype),
    )


def _pool_store(rows: jnp.ndarray, pool_dtype, scale_row) -> jnp.ndarray:
    """Cast KV rows [..., K, Hd] to the pool's storage dtype, dividing by
    the per-head kv scale first when the pool is SCALED fp8 (ISSUE 9):
    stored = value / scale, every reader multiplies back in-kernel. The
    division runs in f32 so bf16 rows keep their mantissa until the final
    fp8 cast."""
    if scale_row is None:
        return rows.astype(pool_dtype)
    return (rows.astype(jnp.float32) / scale_row[..., :, None]).astype(pool_dtype)


def write_block_to_pool(
    pool: KVCache,
    table: jnp.ndarray,  # [B, MP] int32
    local_k: jnp.ndarray,  # [L, B, n, K, Hd]
    local_v: jnp.ndarray,
    start_positions: jnp.ndarray,  # [B]
    kv_scale=None,  # [2, K] f32 → pool rows store value/scale (fp8 KV)
) -> KVCache:
    """Scatter a decode block's window into the page pool (once per block).
    Rows may straddle pages; each (slot, step) row lands at
    (table[b, row // page], row % page). Every slot is written every step —
    idle slots and rows past a slot's reservation resolve through the
    engine's SCRATCH-filled table entries to a page nobody attends, so they
    can never corrupt a live request."""
    from localai_tpu.ops import ptable as _pt

    L, B, n = local_k.shape[:3]
    page = pool.k.shape[2]
    MP = _pt.width(table)
    row = jnp.minimum(start_positions[:, None] + jnp.arange(n)[None, :],
                      MP * page - 1)  # [B, n]
    pid = _pt.gather_cols(table, row // page)  # [B, n]
    off = row % page
    ks = None if kv_scale is None else kv_scale[0]
    vs = None if kv_scale is None else kv_scale[1]
    k = pool.k.at[:, pid, off].set(_pool_store(local_k, pool.k.dtype, ks))
    v = pool.v.at[:, pid, off].set(_pool_store(local_v, pool.v.dtype, vs))
    return KVCache(k=k, v=v)


def write_chunk_to_pool(
    pool: KVCache,
    table: jnp.ndarray,  # [B, MP] int32
    new_k: jnp.ndarray,  # [L, B, T, K, Hd]
    new_v: jnp.ndarray,
    positions: jnp.ndarray,  # [B, T] row indices (contiguous per slot)
    kv_scale=None,  # [2, K] f32 → pool rows store value/scale (fp8 KV)
) -> KVCache:
    """Scatter a speculative verify chunk's rows into the page pool (the
    paged counterpart of decode_chunk's dense scatter). Rows resolve through
    the table like write_block_to_pool — rejected-window overshoot rows land
    in later pages of the same slot's reservation and are overwritten by the
    next round's writes at the same positions."""
    from localai_tpu.ops import ptable as _pt

    page = pool.k.shape[2]
    MP = _pt.width(table)
    row = jnp.minimum(positions, MP * page - 1)  # [B, T]
    pid = _pt.gather_cols(table, row // page)  # [B, T]
    off = row % page
    ks = None if kv_scale is None else kv_scale[0]
    vs = None if kv_scale is None else kv_scale[1]
    k = pool.k.at[:, pid, off].set(_pool_store(new_k, pool.k.dtype, ks))
    v = pool.v.at[:, pid, off].set(_pool_store(new_v, pool.v.dtype, vs))
    return KVCache(k=k, v=v)


def write_rows_to_pool(
    pool: KVCache,
    table_row: jnp.ndarray,  # [MP] int32 — the destination slot's pages
    ks: jnp.ndarray,  # [L, 1, R, K, Hd]
    vs: jnp.ndarray,
    start_row: jnp.ndarray,  # scalar int32 — first destination row
    kv_scale=None,  # [2, K] f32 → pool rows store value/scale (fp8 KV)
) -> KVCache:
    """Scatter R contiguous rows starting at `start_row` into one slot's
    pages (cached-admission tail rows, which start mid-sequence and are not
    page-aligned)."""
    from localai_tpu.ops import ptable as _pt

    R = ks.shape[2]
    page = pool.k.shape[2]
    MP = _pt.width(table_row)
    row = jnp.minimum(start_row + jnp.arange(R), MP * page - 1)  # [R]
    pid = _pt.row_lookup(table_row, row // page)  # [R]
    off = row % page
    ksc = None if kv_scale is None else kv_scale[0]
    vsc = None if kv_scale is None else kv_scale[1]
    k = pool.k.at[:, pid, off].set(_pool_store(ks[:, 0], pool.k.dtype, ksc))
    v = pool.v.at[:, pid, off].set(_pool_store(vs[:, 0], pool.v.dtype, vsc))
    return KVCache(k=k, v=v)


def gather_pages(
    pool: KVCache,
    pages: jnp.ndarray,  # [NP] int32 page ids (SCRATCH-padded past the span)
    kv_scale=None,  # [2, K] f32 → rows come back DEQUANTIZED (value·scale)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Materialize a page list as contiguous KV rows [L, 1, NP*page, K, Hd]
    — the read half of prefix-span sharing under the paged cache (the span's
    pages are mapped read-only; prefill_tail consumes a dense prefix
    operand)."""
    k = pool.k[:, pages]  # [L, NP, page, K, Hd]
    v = pool.v[:, pages]
    if kv_scale is not None:
        # A SCALED fp8 pool stores value/scale — the dense prefix operand
        # prefill_tail consumes must be real values again.
        k = k.astype(jnp.float32) * kv_scale[0][..., :, None]
        v = v.astype(jnp.float32) * kv_scale[1][..., :, None]
    L, NP, page, K, Hd = k.shape
    return (
        k.reshape(L, 1, NP * page, K, Hd),
        v.reshape(L, 1, NP * page, K, Hd),
    )


def prefill_chunk_paged(
    cfg: ArchConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B, T] int32 chunk tokens, right-padded
    lengths: jnp.ndarray,  # [B] int32 valid chunk lengths
    offsets: jnp.ndarray,  # [B] int32 rows already resident (chunk starts here)
    pool: KVCache,
    table,  # [B, MP] int32 page tables (prefix + destination pages), or
    # the hierarchical (l1, l0) pair (ops/ptable)
    ep: int = 1,
    paged_impl: str = "auto",
    with_logits: bool = True,
    mesh=None,  # Mesh with tp>1 → paged Pallas kernel head-sharded
    kv_scale=None,  # [2, K] f32 per-head (k, v) pool dequant scales (fp8 KV)
    sp_mesh=None,  # Mesh with sp>1 → the chunk's attention runs ring-
    # sharded over "sp" (parallel/ring.ring_chunk_paged_attention): each
    # shard holds T/sp chunk tokens, walks the slot's resident pages for
    # its own queries (pool replicated over sp) and rotates the in-chunk
    # K/V blocks neighbor-to-neighbor — per-chip chunk compute is T/sp
    # while the fresh K/V still scatters straight into pool pages
):
    """One chunk of a ragged chunked prefill, direct-to-page (ISSUE 2;
    sequence-parallel leg + windowed+sink prefix walk: ISSUE 14,
    docs/LONG_CONTEXT.md).

    Chunk token t attends the slot's already-written rows [0, offsets[b])
    through the paged-partials walk — the same scalar-prefetch page-table
    kernel as decode (ops/paged_flash, Pallas on TPU; the query-row axis is
    tiled so a whole chunk's online-softmax state fits VMEM) — plus the
    in-chunk causal window, and the chunk's fresh K/V rows scatter STRAIGHT
    into the slot's pages at rows [offsets, offsets+T). Unlike
    `prefill` + `write_prefill_to_pool` there is no dense full-bucket KV
    intermediate and no bucket→page scatter: per-chunk HBM traffic is the
    chunk itself plus one streamed read of the live prefix.

    Padding rows (t >= lengths[b]) write garbage rows past the prompt inside
    the slot's own reservation; decode overwrites each such row before any
    query can attend it (same invariant as the dense bucket's padding).
    Returns (last_logits [B, V] f32 | None, new_pool) — mid chunks skip the
    unembed entirely (with_logits=False).
    """
    B, T = tokens.shape
    from localai_tpu.ops.attention import (
        _merge_partials_mq,
        paged_prefill_partials,
    )

    inv_freq = rope_frequencies(cfg)
    inv_local = rope_frequencies_local(cfg)
    positions = offsets[:, None] + jnp.arange(T)[None, :]  # [B, T] global
    length_mask = jnp.arange(T)[None, :] < lengths[:, None]
    h = _embed(cfg, params, tokens)  # [B, T, D]
    causal = jnp.tril(jnp.ones((T, T), bool))
    win_dist = jnp.arange(T)[:, None] - jnp.arange(T)[None, :]  # in-chunk t-u

    def layer(h, xs):
        lp, li, kc, vc = xs  # kc/vc: [P, page, K, Hd] pool slices
        sliding = _layer_sliding(cfg, li)
        x = rms_norm(h, lp["attn_norm"], cfg.rms_eps)
        inv = _layer_inv_freq(cfg, inv_freq, inv_local, li)
        if cfg.is_mla:
            # Absorbed MLA chunk: q_eff scores the latent prefix pages and
            # the chunk's fresh latent rows (values come back out of the
            # same latents — see decode_chunk's MLA branch).
            q_eff = _mla_absorbed_q(cfg, lp, x, positions, inv, mesh)  # [B,T,H,De]
            rows = _mla_rows(cfg, lp, x, positions, inv)  # [B,T,1,De]
            acc, m, l = paged_prefill_partials(
                q_eff, kc, kc, table, offsets, q_pos=positions,
                impl=paged_impl, kv_scale=kv_scale,
            )
            wm = causal[None] & length_mask[:, None, :]  # [B, T, T]
            attn = _merge_partials_mq(q_eff, acc, m, l, rows, rows, wm)
            attn = _mla_unlatent(cfg, lp, attn)  # [B, T, H·v]
            h = h + _attn_out(cfg, lp, attn, mesh)
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
            h = h + _mlp_out(cfg, lp, x, ep, mesh)
            return h, (rows, rows[..., :0])
        q, k, v = _attn_proj_qkv(cfg, lp, x, mesh)  # q [B,T,H,Hd], k/v [B,T,K,Hd]
        q = apply_rope(q, positions, inv)
        k = apply_rope(k, positions, inv)
        if sp_mesh is not None:
            # Sequence-parallel chunk attention (ISSUE 14): ring over "sp".
            from localai_tpu.parallel.ring import ring_chunk_paged_attention

            attn = ring_chunk_paged_attention(
                q, k, v, offsets, lengths, kc, vc, table, sp_mesh,
                softcap=cfg.attn_softcap, window=cfg.sliding_window,
                sliding=sliding, sink=cfg.attention_sink,
                swin=cfg.attention_window, kv_scale=kv_scale,
            ).reshape(B, T, -1).astype(h.dtype)
            h = h + _attn_out(cfg, lp, attn, mesh)
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
            h = h + _mlp_out(cfg, lp, x, ep, mesh)
            return h, (k, v)
        wmask = causal[None] & length_mask[:, None, :]  # [B, T, T]
        if cfg.sliding_window and sliding is not None:
            wmask = wmask & (~sliding | (win_dist[None] < cfg.sliding_window))
        acc, m, l = paged_prefill_partials(
            q, kc, vc, table, offsets,
            softcap=cfg.attn_softcap, window=cfg.sliding_window,
            sliding=sliding, q_pos=positions, impl=paged_impl, mesh=mesh,
            kv_scale=kv_scale, sink=cfg.attention_sink,
            swin=cfg.attention_window,
        )
        attn = _merge_partials_mq(
            q, acc, m, l, k, v, wmask, softcap=cfg.attn_softcap,
        ).reshape(B, T, -1).astype(h.dtype)
        h = h + _attn_out(cfg, lp, attn, mesh)
        x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps)
        h = h + _mlp_out(cfg, lp, x, ep, mesh)
        return h, (k, v)

    h, (new_k, new_v) = _scan_layers(
        cfg, params, h, layer, (pool.k, pool.v)
    )
    pool = write_chunk_to_pool(pool, table, new_k, new_v, positions,
                               kv_scale=kv_scale)
    if not with_logits:
        return None, pool
    h = rms_norm(h, params["final_norm"], cfg.rms_eps)
    last_idx = jnp.maximum(lengths - 1, 0)
    last = jnp.take_along_axis(h, last_idx[:, None, None], axis=1)[:, 0]  # [B, D]
    return _unembed(cfg, params, last, mesh), pool


def write_rows_to_cache(
    cache: KVCache,
    slot: jnp.ndarray,  # scalar int32 — destination slot
    ks: jnp.ndarray,  # [L, 1, T, K, Hd]
    vs: jnp.ndarray,
    start_row: jnp.ndarray,  # scalar int32 — first destination row
) -> KVCache:
    """Write T contiguous rows starting at `start_row` into one DENSE slot —
    the dense-cache counterpart of write_rows_to_pool (chunked prefill
    writes each chunk's rows mid-sequence)."""
    k = jax.lax.dynamic_update_slice(
        cache.k, ks[:, :1].astype(cache.k.dtype), (0, slot, start_row, 0, 0)
    )
    v = jax.lax.dynamic_update_slice(
        cache.v, vs[:, :1].astype(cache.v.dtype), (0, slot, start_row, 0, 0)
    )
    return KVCache(k=k, v=v)


def write_prefill_to_pool(
    pool: KVCache,
    table_row: jnp.ndarray,  # [MP] int32 — the destination slot's pages
    ks: jnp.ndarray,  # [L, B_new, Sb, K, Hd] from prefill
    vs: jnp.ndarray,
    j: int,  # batch row within ks/vs (static)
    kv_scale=None,  # [2, K] f32 → pool rows store value/scale (fp8 KV)
) -> KVCache:
    """Copy one prefilled request's KV into its pages. The prompt starts at
    row 0, so writes are page-aligned; the (static) trailing partial page
    writes whatever fits. Chunked admission (EngineConfig.prefill_chunk)
    bypasses this dense-bucket scatter entirely — see prefill_chunk_paged."""
    from localai_tpu.ops import ptable as _pt

    Sb = ks.shape[2]
    page = pool.k.shape[2]
    k, v = pool.k, pool.v
    ksc = None if kv_scale is None else kv_scale[0]
    vsc = None if kv_scale is None else kv_scale[1]
    for p in range(-(-Sb // page)):  # static page count for this bucket
        lo = p * page
        chunk_k = ks[:, j, lo: lo + page]  # [L, c, K, Hd], c static
        chunk_v = vs[:, j, lo: lo + page]
        k = jax.lax.dynamic_update_slice(
            k, _pool_store(chunk_k, k.dtype, ksc)[:, None],
            (0, _pt.row_lookup(table_row, p), 0, 0, 0)
        )
        v = jax.lax.dynamic_update_slice(
            v, _pool_store(chunk_v, v.dtype, vsc)[:, None],
            (0, _pt.row_lookup(table_row, p), 0, 0, 0)
        )
    return KVCache(k=k, v=v)
