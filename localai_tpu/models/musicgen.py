"""MusicGen text-to-music in JAX: loads published HF checkpoints
(facebook/musicgen-small/-medium) and generates audio from a text prompt.

Reference parity: the reference backs its SoundGeneration capability with
transformers' MusicgenForConditionalGeneration
(/root/reference/backend/python/transformers/backend.py:489-539) behind
`/v1/sound-generation` (core/backend/soundgeneration.go). Here the three
sub-models run natively on TPU:

  text prompt → T5 encoder → enc_to_dec_proj
             → delay-pattern decoder LM over 4 EnCodec codebooks
               (classifier-free guidance, top-k sampling)
             → EnCodec SEANet decoder → 32 kHz waveform

TPU-first design decisions (not a port of the torch modules):
  * the whole autoregressive generation is ONE `lax.scan` under jit —
    static step count, preallocated KV cache, no host round-trips per token;
  * classifier-free guidance rides the batch axis (cond and null rows
    decoded in the same matmuls) instead of two forward passes;
  * the delay pattern is arithmetic on the scan counter (codebook k commits
    frame s−k at step s), not a materialized mask tensor;
  * EnCodec's LSTM is a `lax.scan` over frames; all convs are
    `lax.conv_general_dilated` in NCT layout with the asymmetric reflect
    padding resolved statically.

Weight layout follows HF `MusicgenForConditionalGeneration.state_dict()`
(weight-norm parametrizations materialized at load, like models/vits.py);
the math is an original JAX implementation checked against torch in
tests/test_musicgen.py.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]

NEG_INF = -1e9


@dataclasses.dataclass(frozen=True)
class MusicgenConfig:
    # --- T5 text encoder (config.json "text_encoder") ---
    t5_vocab_size: int = 32128
    t5_d_model: int = 768
    t5_d_kv: int = 64
    t5_d_ff: int = 3072
    t5_num_layers: int = 12
    t5_num_heads: int = 12
    t5_rel_buckets: int = 32
    t5_rel_max_distance: int = 128
    t5_gated_ff: bool = False  # "gated-gelu" checkpoints use wi_0/wi_1
    t5_eps: float = 1e-6
    # --- decoder LM (config.json "decoder") ---
    vocab_size: int = 2048
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    ffn_dim: int = 4096
    num_codebooks: int = 4
    pad_token_id: int = 2048  # also the decoder start token
    layer_norm_eps: float = 1e-5
    # --- EnCodec audio decoder (config.json "audio_encoder") ---
    enc_dim: int = 128  # quantizer / SEANet latent dimension
    enc_num_filters: int = 64
    enc_ratios: tuple = (8, 5, 4, 4)
    enc_kernel_size: int = 7
    enc_last_kernel_size: int = 7
    enc_residual_kernel_size: int = 3
    enc_dilation_growth_rate: int = 2
    enc_num_residual_layers: int = 1
    enc_num_lstm_layers: int = 2
    enc_causal: bool = False
    enc_norm_type: str = "weight_norm"
    enc_pad_mode: str = "reflect"
    enc_trim_right_ratio: float = 1.0
    enc_compress: int = 2
    enc_codebook_size: int = 2048
    sampling_rate: int = 32000
    # --- generation defaults (generation_config.json) ---
    guidance_scale: float = 3.0
    top_k: int = 250

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def hop_length(self) -> int:
        return int(np.prod(self.enc_ratios))

    @property
    def frame_rate(self) -> int:
        return math.ceil(self.sampling_rate / self.hop_length)


def config_from_hf(ckpt_dir: str) -> MusicgenConfig:
    with open(os.path.join(ckpt_dir, "config.json")) as f:
        d = json.load(f)
    t5 = d.get("text_encoder", {})
    dec = d.get("decoder", {})
    enc = d.get("audio_encoder", {})
    kw: dict[str, Any] = {}
    for src, dst in (
        ("vocab_size", "t5_vocab_size"), ("d_model", "t5_d_model"),
        ("d_kv", "t5_d_kv"), ("d_ff", "t5_d_ff"), ("num_layers", "t5_num_layers"),
        ("num_heads", "t5_num_heads"),
        ("relative_attention_num_buckets", "t5_rel_buckets"),
        ("relative_attention_max_distance", "t5_rel_max_distance"),
        ("layer_norm_epsilon", "t5_eps"),
    ):
        if src in t5:
            kw[dst] = t5[src]
    kw["t5_gated_ff"] = "gated" in t5.get("feed_forward_proj", "relu")
    for src in ("vocab_size", "hidden_size", "num_hidden_layers",
                "num_attention_heads", "ffn_dim", "num_codebooks", "pad_token_id"):
        if src in dec:
            kw[src] = dec[src]
    for src, dst in (
        ("hidden_size", "enc_dim"), ("num_filters", "enc_num_filters"),
        ("kernel_size", "enc_kernel_size"), ("last_kernel_size", "enc_last_kernel_size"),
        ("residual_kernel_size", "enc_residual_kernel_size"),
        ("dilation_growth_rate", "enc_dilation_growth_rate"),
        ("num_residual_layers", "enc_num_residual_layers"),
        ("num_lstm_layers", "enc_num_lstm_layers"),
        ("use_causal_conv", "enc_causal"), ("norm_type", "enc_norm_type"),
        ("pad_mode", "enc_pad_mode"), ("trim_right_ratio", "enc_trim_right_ratio"),
        ("compress", "enc_compress"), ("codebook_size", "enc_codebook_size"),
        ("sampling_rate", "sampling_rate"),
    ):
        if src in enc:
            kw[dst] = enc[src]
    if "upsampling_ratios" in enc:
        kw["enc_ratios"] = tuple(enc["upsampling_ratios"])
    gen_path = os.path.join(ckpt_dir, "generation_config.json")
    if os.path.isfile(gen_path):
        with open(gen_path) as f:
            g = json.load(f)
        if g.get("guidance_scale") is not None:
            kw["guidance_scale"] = float(g["guidance_scale"])
        if g.get("top_k") is not None:
            kw["top_k"] = int(g["top_k"])
    return MusicgenConfig(**kw)


def is_musicgen_dir(ckpt_dir: str) -> bool:
    cfg_path = os.path.join(ckpt_dir, "config.json")
    if not os.path.isfile(cfg_path):
        return False
    try:
        with open(cfg_path) as f:
            return json.load(f).get("model_type") == "musicgen"
    except (OSError, json.JSONDecodeError):
        return False


# --------------------------------------------------------------------------- #
# Weight loading (HF layout; weight-norm materialized like models/vits.py)
# --------------------------------------------------------------------------- #


def load_musicgen_params(ckpt_dir: str) -> Params:
    from safetensors import safe_open

    paths = []
    idx = os.path.join(ckpt_dir, "model.safetensors.index.json")
    if os.path.isfile(idx):
        with open(idx) as f:
            paths = sorted({os.path.join(ckpt_dir, v)
                            for v in json.load(f)["weight_map"].values()})
    else:
        single = os.path.join(ckpt_dir, "model.safetensors")
        if os.path.isfile(single):
            paths = [single]
    if not paths:
        raise FileNotFoundError(f"no safetensors weights under {ckpt_dir!r}")
    raw: dict[str, np.ndarray] = {}
    for path in paths:
        with safe_open(path, framework="numpy") as f:
            for name in f.keys():
                raw[name] = np.asarray(f.get_tensor(name), np.float32)
    out: dict[str, np.ndarray] = {}
    for name, arr in raw.items():
        if name.endswith("parametrizations.weight.original0"):
            base = name[: -len(".parametrizations.weight.original0")]
            v = raw[base + ".parametrizations.weight.original1"]
            norm = np.sqrt((v**2).sum(axis=tuple(range(1, v.ndim)), keepdims=True))
            out[base + ".weight"] = arr * v / np.maximum(norm, 1e-12)
        elif name.endswith("parametrizations.weight.original1"):
            continue
        elif name.startswith("audio_encoder.encoder."):
            continue  # serving only decodes; the SEANet encoder never runs
        elif name.endswith(("embed_avg", "cluster_size", "inited")):
            continue  # EMA training buffers of the quantizer codebooks
        else:
            out[name] = arr
    return {k: jnp.asarray(v) for k, v in out.items()}


# --------------------------------------------------------------------------- #
# T5 text encoder
# --------------------------------------------------------------------------- #


def _t5_rms_norm(x, w, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def _t5_bucket(rel_pos, num_buckets: int, max_dist: int):
    """Bidirectional T5 relative-position bucketing (modeling_t5.py:401-441)."""
    nb = num_buckets // 2
    buckets = (rel_pos > 0).astype(jnp.int32) * nb
    n = jnp.abs(rel_pos)
    max_exact = nb // 2
    large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / math.log(max_dist / max_exact) * (nb - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, nb - 1)
    return buckets + jnp.where(n < max_exact, n, large)


def t5_encode(cfg: MusicgenConfig, p: Params, ids, mask):
    """ids [B, T] int32, mask [B, T] (1 = real token) → hidden [B, T, d_model].

    T5 semantics: RMS pre-norms, un-scaled attention logits, a single
    relative-position bias table (block 0) shared by every layer.
    """
    h = p["text_encoder.shared.weight"][ids]
    B, T, _ = h.shape
    H, Dk = cfg.t5_num_heads, cfg.t5_d_kv

    rel = jnp.arange(T)[None, :] - jnp.arange(T)[:, None]  # memory - query
    bucket = _t5_bucket(rel, cfg.t5_rel_buckets, cfg.t5_rel_max_distance)
    table = p["text_encoder.encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"]
    kmask = (1.0 - mask[:, None, None, :]) * NEG_INF  # additive key mask
    bias = table[bucket].transpose(2, 0, 1)[None] + kmask  # [B, H, T, T]

    for i in range(cfg.t5_num_layers):
        pre = f"text_encoder.encoder.block.{i}"
        x = _t5_rms_norm(h, p[f"{pre}.layer.0.layer_norm.weight"], cfg.t5_eps)
        q = (x @ p[f"{pre}.layer.0.SelfAttention.q.weight"].T).reshape(B, T, H, Dk)
        k = (x @ p[f"{pre}.layer.0.SelfAttention.k.weight"].T).reshape(B, T, H, Dk)
        v = (x @ p[f"{pre}.layer.0.SelfAttention.v.weight"].T).reshape(B, T, H, Dk)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) + bias  # T5: no 1/sqrt(d)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, T, H * Dk)
        h = h + attn @ p[f"{pre}.layer.0.SelfAttention.o.weight"].T

        x = _t5_rms_norm(h, p[f"{pre}.layer.1.layer_norm.weight"], cfg.t5_eps)
        if cfg.t5_gated_ff:
            y = jax.nn.gelu(x @ p[f"{pre}.layer.1.DenseReluDense.wi_0.weight"].T,
                            approximate=False)
            y = y * (x @ p[f"{pre}.layer.1.DenseReluDense.wi_1.weight"].T)
        else:
            y = jax.nn.relu(x @ p[f"{pre}.layer.1.DenseReluDense.wi.weight"].T)
        h = h + y @ p[f"{pre}.layer.1.DenseReluDense.wo.weight"].T
    return _t5_rms_norm(h, p["text_encoder.final_layer_norm.weight"]
                        if "text_encoder.final_layer_norm.weight" in p
                        else p["text_encoder.encoder.final_layer_norm.weight"], cfg.t5_eps)


def encode_text(cfg: MusicgenConfig, p: Params, ids, mask):
    """T5 → enc_to_dec_proj → zero out padded positions (the order HF applies
    them: project first, then mask — modeling_musicgen.py:1802-1812)."""
    h = t5_encode(cfg, p, ids, mask)
    h = h @ p["enc_to_dec_proj.weight"].T + p["enc_to_dec_proj.bias"]
    return h * mask[..., None]


# --------------------------------------------------------------------------- #
# Decoder LM
# --------------------------------------------------------------------------- #


def _sin_positions(steps: int, dim: int):
    """MusicgenSinusoidalPositionalEmbedding.get_embedding: cat([cos, sin])."""
    half = dim // 2
    freq = np.exp(np.arange(half) * -(math.log(10000) / (half - 1)))
    ang = np.arange(steps)[:, None] * freq[None, :]
    emb = np.concatenate([np.cos(ang), np.sin(ang)], axis=1)
    if dim % 2 == 1:
        emb = np.concatenate([emb, np.zeros((steps, 1))], axis=1)
    return jnp.asarray(emb, jnp.float32)  # [steps, dim]


def _layer_norm(x, w, b, eps):
    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


def _embed_codebooks(cfg: MusicgenConfig, p: Params, tokens):
    """tokens [B, K, S] → summed embeddings [B, S, C]."""
    h = 0.0
    for k in range(cfg.num_codebooks):
        h = h + p[f"decoder.model.decoder.embed_tokens.{k}.weight"][tokens[:, k]]
    return h


def _attn_proj(p, pre, x, B, S, H, D):
    q = (x @ p[f"{pre}.q_proj.weight"].T).reshape(B, S, H, D)
    k = (x @ p[f"{pre}.k_proj.weight"].T).reshape(B, S, H, D)
    v = (x @ p[f"{pre}.v_proj.weight"].T).reshape(B, S, H, D)
    return q, k, v


def decoder_logits(cfg: MusicgenConfig, p: Params, tokens, enc_hidden, enc_mask):
    """Teacher-forced full-sequence logits (parity tests / prompt prefill).

    tokens [B, K, S] delay-pattern ids; enc_hidden [B, T, C] projected+masked
    text states; enc_mask [B, T]. Returns [B, K, S, vocab].
    """
    B, K, S = tokens.shape
    H, D = cfg.num_attention_heads, cfg.head_dim
    scale = D**-0.5
    h = _embed_codebooks(cfg, p, tokens) + _sin_positions(S, cfg.hidden_size)[None]
    causal = jnp.tril(jnp.ones((S, S), jnp.float32))
    cmask = (1.0 - causal)[None, None] * NEG_INF
    xmask = (1.0 - enc_mask[:, None, None, :]) * NEG_INF

    for i in range(cfg.num_hidden_layers):
        pre = f"decoder.model.decoder.layers.{i}"
        x = _layer_norm(h, p[f"{pre}.self_attn_layer_norm.weight"],
                        p[f"{pre}.self_attn_layer_norm.bias"], cfg.layer_norm_eps)
        q, k, v = _attn_proj(p, f"{pre}.self_attn", x, B, S, H, D)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k) + cmask
        attn = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), v)
        h = h + attn.reshape(B, S, H * D) @ p[f"{pre}.self_attn.out_proj.weight"].T

        x = _layer_norm(h, p[f"{pre}.encoder_attn_layer_norm.weight"],
                        p[f"{pre}.encoder_attn_layer_norm.bias"], cfg.layer_norm_eps)
        q = (x @ p[f"{pre}.encoder_attn.q_proj.weight"].T).reshape(B, S, H, D)
        ek = (enc_hidden @ p[f"{pre}.encoder_attn.k_proj.weight"].T).reshape(B, -1, H, D)
        ev = (enc_hidden @ p[f"{pre}.encoder_attn.v_proj.weight"].T).reshape(B, -1, H, D)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, ek) + xmask
        attn = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), ev)
        h = h + attn.reshape(B, S, H * D) @ p[f"{pre}.encoder_attn.out_proj.weight"].T

        x = _layer_norm(h, p[f"{pre}.final_layer_norm.weight"],
                        p[f"{pre}.final_layer_norm.bias"], cfg.layer_norm_eps)
        y = jax.nn.gelu(x @ p[f"{pre}.fc1.weight"].T, approximate=False)
        h = h + y @ p[f"{pre}.fc2.weight"].T

    h = _layer_norm(h, p["decoder.model.decoder.layer_norm.weight"],
                    p["decoder.model.decoder.layer_norm.bias"], cfg.layer_norm_eps)
    return jnp.stack(
        [h @ p[f"decoder.lm_heads.{k}.weight"].T for k in range(cfg.num_codebooks)],
        axis=1,
    )  # [B, K, S, V]


@partial(jax.jit, static_argnums=(0, 5, 8, 9))
def generate_codes(
    cfg: MusicgenConfig,
    p: Params,
    enc_hidden,  # [B, T, C] projected+masked conditional text states
    enc_mask,  # [B, T]
    key,  # PRNG key
    frames: int,  # static frame budget (steps = frames + K)
    guidance_scale,  # traced scalar; CFG rides the doubled batch axis
    temperature,  # traced scalar
    do_sample: bool,
    top_k: int,
):
    """One fused scan: delay-pattern autoregressive decode → [B, K, frames].

    The null (unconditional) branch is rows [B:2B) of every activation —
    zero encoder states under a zero cross-attention mask, exactly HF's
    doubled-batch CFG (ClassifierFreeGuidanceLogitsProcessor semantics:
    uncond + scale · (cond − uncond)).
    """
    B, T, C = enc_hidden.shape
    K = cfg.num_codebooks
    H, D = cfg.num_attention_heads, cfg.head_dim
    L = cfg.num_hidden_layers
    scale = D**-0.5
    steps = frames + K
    pad = cfg.pad_token_id

    # CFG: [cond; null] on the batch axis.
    ench = jnp.concatenate([enc_hidden, jnp.zeros_like(enc_hidden)], axis=0)
    encm = jnp.concatenate([enc_mask, jnp.zeros_like(enc_mask)], axis=0)
    B2 = 2 * B
    xmask = (1.0 - encm[:, None, None, :]) * NEG_INF  # [2B, 1, 1, T]

    # Cross-attention K/V are step-invariant: compute once, outside the scan.
    ek = jnp.stack([
        (ench @ p[f"decoder.model.decoder.layers.{i}.encoder_attn.k_proj.weight"].T)
        .reshape(B2, T, H, D) for i in range(L)
    ])  # [L, 2B, T, H, D]
    ev = jnp.stack([
        (ench @ p[f"decoder.model.decoder.layers.{i}.encoder_attn.v_proj.weight"].T)
        .reshape(B2, T, H, D) for i in range(L)
    ])

    positions = _sin_positions(steps, cfg.hidden_size)
    kcache = jnp.zeros((L, B2, steps, H, D), jnp.float32)
    vcache = jnp.zeros((L, B2, steps, H, D), jnp.float32)
    codes = jnp.full((B, K, frames), pad, jnp.int32)
    tokens = jnp.full((B, K), pad, jnp.int32)  # decoder start = pad
    karr = jnp.arange(K)

    def step(carry, s):
        tokens, kcache, vcache, codes, key = carry
        tok2 = jnp.concatenate([tokens, tokens], axis=0)  # [2B, K]
        h = _embed_codebooks(cfg, p, tok2[:, :, None])[:, 0] + positions[s]  # [2B, C]
        smask = (jnp.arange(steps) > s)[None, None, :] * NEG_INF  # causal over cache

        for i in range(L):
            pre = f"decoder.model.decoder.layers.{i}"
            x = _layer_norm(h, p[f"{pre}.self_attn_layer_norm.weight"],
                            p[f"{pre}.self_attn_layer_norm.bias"], cfg.layer_norm_eps)
            q = (x @ p[f"{pre}.self_attn.q_proj.weight"].T).reshape(B2, H, D)
            kk = (x @ p[f"{pre}.self_attn.k_proj.weight"].T).reshape(B2, H, D)
            vv = (x @ p[f"{pre}.self_attn.v_proj.weight"].T).reshape(B2, H, D)
            kcache = kcache.at[i, :, s].set(kk)
            vcache = vcache.at[i, :, s].set(vv)
            scores = jnp.einsum("bhd,bshd->bhs", q * scale, kcache[i]) + smask
            attn = jnp.einsum("bhs,bshd->bhd", jax.nn.softmax(scores, -1), vcache[i])
            h = h + attn.reshape(B2, H * D) @ p[f"{pre}.self_attn.out_proj.weight"].T

            x = _layer_norm(h, p[f"{pre}.encoder_attn_layer_norm.weight"],
                            p[f"{pre}.encoder_attn_layer_norm.bias"], cfg.layer_norm_eps)
            q = (x @ p[f"{pre}.encoder_attn.q_proj.weight"].T).reshape(B2, H, D)
            scores = jnp.einsum("bhd,bthd->bht", q * scale, ek[i]) + xmask[:, 0]
            attn = jnp.einsum("bht,bthd->bhd", jax.nn.softmax(scores, -1), ev[i])
            h = h + attn.reshape(B2, H * D) @ p[f"{pre}.encoder_attn.out_proj.weight"].T

            x = _layer_norm(h, p[f"{pre}.final_layer_norm.weight"],
                            p[f"{pre}.final_layer_norm.bias"], cfg.layer_norm_eps)
            y = jax.nn.gelu(x @ p[f"{pre}.fc1.weight"].T, approximate=False)
            h = h + y @ p[f"{pre}.fc2.weight"].T

        h = _layer_norm(h, p["decoder.model.decoder.layer_norm.weight"],
                        p["decoder.model.decoder.layer_norm.bias"], cfg.layer_norm_eps)
        logits = jnp.stack(
            [h @ p[f"decoder.lm_heads.{k}.weight"].T for k in range(K)], axis=1
        )  # [2B, K, V]
        cond, uncond = logits[:B], logits[B:]
        logits = uncond + (cond - uncond) * guidance_scale  # CFG combine

        key, sub = jax.random.split(key)
        if do_sample:
            logits = logits / jnp.maximum(temperature, 1e-5)
            if top_k > 0 and top_k < logits.shape[-1]:
                thresh = jax.lax.top_k(logits, top_k)[0][..., -1:]
                logits = jnp.where(logits < thresh, NEG_INF, logits)
            sampled = jax.random.categorical(sub, logits, axis=-1)  # [B, K]
        else:
            sampled = jnp.argmax(logits, axis=-1)
        sampled = sampled.astype(jnp.int32)

        # Delay pattern: codebook k's sample at step s is frame s − k.
        fidx = s - karr  # [K]
        inrange = (fidx >= 0) & (fidx < frames)
        cidx = jnp.clip(fidx, 0, frames - 1)
        old = codes[:, karr, cidx]
        codes = codes.at[:, karr, cidx].set(jnp.where(inrange[None], sampled, old))
        # Next step's input for codebook k: its committed frame (s+1)−1−k,
        # i.e. this step's sample when in range, else the delay pad token.
        tokens = jnp.where(inrange[None], sampled, pad)
        return (tokens, kcache, vcache, codes, key), None

    (_, _, _, codes, _), _ = jax.lax.scan(
        step, (tokens, kcache, vcache, codes, key), jnp.arange(steps)
    )
    return codes


# --------------------------------------------------------------------------- #
# EnCodec decoder (RVQ codebook sum → SEANet)
# --------------------------------------------------------------------------- #


def _enc_pad(x, left: int, right: int, mode: str):
    if left == 0 and right == 0:
        return x
    if mode == "reflect":
        # torch reflect pad requires pad < length; EnCodec pads an extra
        # zero column first when the signal is shorter (decoder inputs are
        # always ≥ kernel frames in practice, so the fast path dominates).
        if max(left, right) >= x.shape[-1]:
            extra = max(left, right) - x.shape[-1] + 1
            x = jnp.pad(x, ((0, 0), (0, 0), (0, extra)))
            y = jnp.pad(x, ((0, 0), (0, 0), (left, right)), mode="reflect")
            return y[..., : y.shape[-1] - extra]
        return jnp.pad(x, ((0, 0), (0, 0), (left, right)), mode="reflect")
    return jnp.pad(x, ((0, 0), (0, 0), (left, right)))


_DN = ("NCH", "OIH", "NCH")


def _enc_conv(cfg: MusicgenConfig, p: Params, pre: str, x, dilation: int = 1,
              stride: int = 1):
    """EncodecConv1d: asymmetric (or causal) pad, then valid conv."""
    w = p[f"{pre}.conv.weight"]
    b = p.get(f"{pre}.conv.bias")
    k_eff = (w.shape[-1] - 1) * dilation + 1
    pt = k_eff - stride
    if cfg.enc_causal:
        left, right = pt, 0
    else:
        right = pt // 2
        left = pt - right
    x = _enc_pad(x, left, right, cfg.enc_pad_mode)
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride,), padding=[(0, 0)],
        rhs_dilation=(dilation,), dimension_numbers=_DN,
    )
    if b is not None:
        y = y + b[None, :, None]
    if cfg.enc_norm_type == "time_group_norm":
        g, gb = p[f"{pre}.norm.weight"], p[f"{pre}.norm.bias"]
        mu = y.mean(axis=(1, 2), keepdims=True)
        var = ((y - mu) ** 2).mean(axis=(1, 2), keepdims=True)
        y = (y - mu) / jnp.sqrt(var + 1e-5) * g[None, :, None] + gb[None, :, None]
    return y


def _enc_conv_transpose(cfg: MusicgenConfig, p: Params, pre: str, x, stride: int):
    """EncodecConvTranspose1d: full transpose conv, then trim the fixed pad."""
    w = p[f"{pre}.conv.weight"]  # [in, out, k]
    b = p.get(f"{pre}.conv.bias")
    k = w.shape[-1]
    wt = jnp.flip(w, -1).transpose(1, 0, 2)
    y = jax.lax.conv_general_dilated(
        x, wt, window_strides=(1,), padding=[(k - 1, k - 1)],
        lhs_dilation=(stride,), dimension_numbers=_DN,
    )
    if b is not None:
        y = y + b[None, :, None]
    pt = k - stride
    if cfg.enc_causal:
        right = math.ceil(pt * cfg.enc_trim_right_ratio)
    else:
        right = pt // 2
    left = pt - right
    return y[..., left: y.shape[-1] - right]


def _enc_lstm(p: Params, pre: str, x, num_layers: int):
    """EncodecLSTM: multi-layer LSTM over time + residual. x [B, C, T]."""
    B, C, T = x.shape
    seq = x.transpose(2, 0, 1)  # [T, B, C]
    out = seq
    for layer in range(num_layers):
        wi = p[f"{pre}.lstm.weight_ih_l{layer}"]  # [4H, in]
        wh = p[f"{pre}.lstm.weight_hh_l{layer}"]
        bi = p[f"{pre}.lstm.bias_ih_l{layer}"]
        bh = p[f"{pre}.lstm.bias_hh_l{layer}"]
        Hn = wh.shape[1]

        def cell(carry, xt, wi=wi, wh=wh, bi=bi, bh=bh, Hn=Hn):
            h, c = carry
            gates = xt @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), h

        init = (jnp.zeros((B, Hn)), jnp.zeros((B, Hn)))
        _, out = jax.lax.scan(cell, init, out)
    return (out + seq).transpose(1, 2, 0)


def encodec_decode(cfg: MusicgenConfig, p: Params, codes):
    """codes [B, K, F] → waveform [B, F · hop_length].

    RVQ decode is the sum of per-codebook embeddings
    (EncodecResidualVectorQuantizer.decode); SEANet then upsamples through
    conv → LSTM → (ELU, convtranspose, resblocks) per ratio → ELU → conv.
    """
    B, K, F = codes.shape
    q = 0.0
    for k in range(K):
        q = q + p[f"audio_encoder.quantizer.layers.{k}.codebook.embed"][codes[:, k]]
    x = q.transpose(0, 2, 1)  # [B, dim, F]

    x = _enc_conv(cfg, p, "audio_encoder.decoder.layers.0", x)
    x = _enc_lstm(p, "audio_encoder.decoder.layers.1", x, cfg.enc_num_lstm_layers)
    li = 2
    for ratio in cfg.enc_ratios:
        x = jax.nn.elu(x)  # the bare nn.ELU() module at this index
        li += 1
        x = _enc_conv_transpose(cfg, p, f"audio_encoder.decoder.layers.{li}", x, ratio)
        li += 1
        for j in range(cfg.enc_num_residual_layers):
            pre = f"audio_encoder.decoder.layers.{li}"
            y = jax.nn.elu(x)
            y = _enc_conv(cfg, p, f"{pre}.block.1", y,
                          dilation=cfg.enc_dilation_growth_rate**j)
            y = jax.nn.elu(y)
            y = _enc_conv(cfg, p, f"{pre}.block.3", y)
            if f"{pre}.shortcut.conv.weight" in p:
                x = _enc_conv(cfg, p, f"{pre}.shortcut", x) + y
            else:
                x = x + y
            li += 1
    x = jax.nn.elu(x)
    li += 1
    x = _enc_conv(cfg, p, f"audio_encoder.decoder.layers.{li}", x)
    return x[:, 0, :]


# --------------------------------------------------------------------------- #
# Checkpoint entry point
# --------------------------------------------------------------------------- #


def load_musicgen(ckpt_dir: str):
    """(cfg, params) from an HF MusicgenForConditionalGeneration directory."""
    cfg = config_from_hf(ckpt_dir)
    params = load_musicgen_params(ckpt_dir)
    return cfg, params
