"""Weight-only int8 quantization for serving.

Reference capability: quantized GGUFs are llama.cpp's bread and butter (the
reference serves Q4/Q8 checkpoints everywhere). TPU-native shape: weight-only
per-output-channel symmetric int8, dequantized INSIDE the matmul — XLA fuses
the int8→bf16 convert into the dot's operand load, so HBM streams one byte
per weight instead of two. Measured on v5e (llama-3.2-1b bs8 decode):
~17% faster steps and half the weight footprint; quality cost is the usual
weight-only-int8 rounding (≈1e-2 relative per matmul).

A quantized tensor is the pytree {"q": int8 [..., in, out], "s": f32
[..., 1, out]}; `matmul(x, w)` in models/llama.py consumes either form.
Quantization happens on device AFTER sharded placement, so the q/s arrays
inherit the weight's sharding and no sharding-spec plumbing changes.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# 2D-matmul weights that benefit; embeddings stay bf16 (gather path).
QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_tensor(w: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """Per-output-channel symmetric int8 over the reduction (-2) axis."""
    wf = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(wf), axis=-2, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-9)
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def matmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """x @ w for plain or quantized w (dequant fused into the dot)."""
    if isinstance(w, dict):
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)[..., 0, :]
    return x @ w


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w


def quantize_tensor_np(arr, axis: int = -2) -> dict:
    """numpy variant for host-side load-time quantization (streaming a
    checkpoint too big for HBM in bf16 — e.g. 8B on one v5e chip)."""
    import numpy as np

    wf = np.asarray(arr, np.float32)
    s = np.max(np.abs(wf), axis=axis, keepdims=True) / 127.0
    s = np.maximum(s, 1e-9)
    q = np.clip(np.round(wf / s), -127, 127).astype(np.int8)
    return {"q": q, "s": s.astype(np.float32)}


def is_prequantized(params: Params) -> bool:
    layers = params.get("layers") or {}
    return any(isinstance(layers.get(k), dict) for k in QUANT_LAYER_KEYS)


def quantize_params(cfg, params: Params, mode: str = "int8") -> Params:
    """Quantize a llama-family param tree's matmul weights (jit-friendly;
    run AFTER device_put so outputs inherit shardings)."""
    if mode in ("", "none", None):
        return params
    if mode != "int8":
        raise ValueError(f"unsupported quantization mode {mode!r}")
    layers = dict(params["layers"])
    for key in QUANT_LAYER_KEYS:
        if key in layers:
            layers[key] = quantize_tensor(layers[key])
    out = dict(params)
    out["layers"] = layers
    # lm_head [V, D] is used transposed (h @ W.T): quantize over D so the
    # scale lands on the output (vocab) axis of the transposed matmul.
    if "lm_head" in params and not cfg.tie_embeddings:
        w = params["lm_head"].astype(jnp.float32)  # [V, D]
        s = jnp.max(jnp.abs(w), axis=-1, keepdims=True) / 127.0  # [V, 1]
        s = jnp.maximum(s, 1e-9)
        q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
        out["lm_head"] = {"q": q, "s": s}
    return out


def unembed_matmul(h: jnp.ndarray, w) -> jnp.ndarray:
    """h @ W.T for the (possibly quantized) lm_head/embed matrix → f32."""
    if isinstance(w, dict):
        logits = jnp.dot(
            h, w["q"].T.astype(h.dtype), preferred_element_type=jnp.float32
        )
        return logits * w["s"][:, 0].astype(jnp.float32)  # [V] broadcasts
    return jnp.dot(h.astype(w.dtype), w.T, preferred_element_type=jnp.float32)
