"""Weight-only quantization for serving: per-channel int8 and grouped int4/int8.

Reference capability: quantized GGUFs are llama.cpp's bread and butter (the
reference serves Q4/Q8 checkpoints everywhere). TPU-native shape: weight-only
quantization with dequant fused INSIDE the matmul — XLA folds the int→bf16
convert into the dot's operand load, so HBM streams 1 byte (int8) or 0.5+ε
bytes (packed int4) per weight instead of two. Measured on v5e
(llama-3.2-1b bs8 decode): ~17% faster steps at int8 and half the weight
footprint; int4 halves it again (llama.cpp Q4-class memory envelope).

XLA folds that convert reliably only for the flat int8 form. The grouped
int8 and packed int4 forms (reshape → unpack → concat → scale → dot) get a
materialized dequantized copy in HBM instead, so decode streamed ~2.5
bytes/weight at int4. ISSUE 9: decode-shape calls now dispatch to fused
Pallas dequant-matmul kernels (ops/quant_matmul.py, `quant_kernel` /
LOCALAI_QUANT_KERNEL — auto: Pallas on TPU) that unpack + scale in VMEM
registers; the XLA forms in this file remain the numeric oracle and the
prefill/compute-bound path.

Representations consumed by `matmul` / `unembed_matmul`:
- {"q": int8 [..., in, out], "s": f32 [..., 1, out]} — per-output-channel
  symmetric int8 (mode "int8").
- {"gq": int8 [..., G, gs, out], "gs": f32 [..., G, 1, out]} — group-wise
  symmetric int8 (GGUF q8_0 repacks losslessly; q5/q6_K regrid here).
- {"g4": uint8 [..., G, gs//2, out], "gs": ..., "gz": f32 [..., G, 1, out]}
  — group-wise affine 4-bit, two nibbles per byte along the in-group axis
  (low nibbles = first gs/2 elements). value = nibble * gs - gz. GGUF
  q4_0/q4_K blocks repack losslessly (mode "int4" for our own weights).

Quantization happens on device AFTER sharded placement, so the q/s arrays
inherit the weight's sharding (parallel/sharding.py aligns specs to either
form).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# 2D-matmul weights that benefit; embeddings stay bf16 (gather path).
# w_kb/w_vb (MLA latent up-projections) stay unquantized: they ride
# einsum paths with no grouped-int kernel and are small next to the MoE.
QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                    "wq_a", "wq_b", "wkv_a",
                    "shared_gate", "shared_up", "shared_down")


def quantize_tensor(w: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """Per-output-channel symmetric int8 over the reduction (-2) axis."""
    wf = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(wf), axis=-2, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-9)
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


GROUP_SIZE = 32  # matches GGUF q4_0/q8_0 blocks → lossless repack


def quantize_tensor_g4(w: jnp.ndarray, group: int = GROUP_SIZE) -> dict[str, jnp.ndarray]:
    """Group-wise affine 4-bit over the reduction (-2) axis; value =
    nibble * gs - gz, nibbles packed two per byte (low = first half of the
    group). jit-friendly."""
    *lead, n_in, n_out = w.shape
    if n_in % group:
        raise ValueError(f"in dim {n_in} not divisible by group {group}")
    g = n_in // group
    wf = w.astype(jnp.float32).reshape(*lead, g, group, n_out)
    mn = wf.min(axis=-2, keepdims=True)
    mx = wf.max(axis=-2, keepdims=True)
    s = jnp.maximum((mx - mn) / 15.0, 1e-9)
    nib = jnp.clip(jnp.round((wf - mn) / s), 0, 15).astype(jnp.uint8)
    half = group // 2
    packed = nib[..., :half, :] | (nib[..., half:, :] << 4)
    return {"g4": packed, "gs": s, "gz": -mn}


def _grouped_values(w, dtype) -> jnp.ndarray:
    """[..., G, gs, out] values (still un-scaled) from a grouped dict."""
    if "g4" in w:
        qp = w["g4"]
        lo = qp & jnp.uint8(0xF)
        hi = qp >> jnp.uint8(4)
        return jnp.concatenate([lo, hi], axis=-2).astype(dtype)
    return w["gq"].astype(dtype)


def grouped_matmul(x: jnp.ndarray, w: dict) -> jnp.ndarray:
    """x [..., in] @ grouped-quantized w [G, gs(, packed), out] → [..., out].

    One batched dot per group with the scale applied on the group partials —
    XLA fuses the unpack/convert into the dot's operand load, so HBM streams
    the packed bytes. The affine zero-point contributes Σ_i x_i · z per
    group, a cheap rank-1 correction."""
    qv = _grouped_values(w, x.dtype)  # [G, gs, out]
    g, gs, n_out = qv.shape
    xg = x.reshape(*x.shape[:-1], g, gs)
    y = jnp.einsum("...gi,gin->...gn", xg, qv)
    y = y * w["gs"].astype(x.dtype)[..., 0, :]
    out = y.sum(axis=-2)
    if "gz" in w:
        xsum = xg.sum(axis=-1)  # [..., G]
        out = out - jnp.einsum(
            "...g,gn->...n", xsum, w["gz"].astype(x.dtype)[..., 0, :]
        )
    return out


def matmul(x: jnp.ndarray, w, impl: str = "auto", mesh=None,
           part=None) -> jnp.ndarray:
    """x @ w for plain or quantized w.

    Quantized dispatch (ISSUE 9): decode-shape calls route to the fused
    Pallas dequant-matmul kernels (ops/quant_matmul — nibble unpack +
    affine scale in VMEM registers, f32 MXU accumulation; each packed byte
    crosses HBM once) per `impl` — "auto" is Pallas on TPU. Everything the
    kernels don't serve (prefill-scale rows, XLA impl, exotic shapes) falls
    through to the XLA forms below, which double as the kernels' numeric
    oracle. XLA folds the flat int8 convert into the dot's operand load;
    the grouped/packed forms are the ones it materializes — the kernels'
    whole reason to exist.

    mesh/part: under a tp>1 mesh the kernel runs in shard_map with the
    weight's own partitioning ("col" = out axis sharded, "row" = group/in
    axis sharded + psum at the declared boundary) — pallas_call is opaque
    to GSPMD, so unwrapped it would all-gather the sharded weight per call.
    """
    if isinstance(w, dict):
        from localai_tpu.ops.quant_matmul import dispatch_matmul

        y = dispatch_matmul(x, w, impl=impl, mesh=mesh, part=part)
        if y is not None:
            return y
        if "q" in w:
            return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)[..., 0, :]
        return grouped_matmul(x, w)
    return x @ w


def is_quantized(w) -> bool:
    return isinstance(w, dict) and ("q" in w or "gq" in w or "g4" in w)


def is_grouped(w) -> bool:
    return isinstance(w, dict) and ("gq" in w or "g4" in w)


def quantize_tensor_np(arr, axis: int = -2) -> dict:
    """numpy variant for host-side load-time quantization (streaming a
    checkpoint too big for HBM in bf16 — e.g. 8B on one v5e chip)."""
    import numpy as np

    wf = np.asarray(arr, np.float32)
    s = np.max(np.abs(wf), axis=axis, keepdims=True) / 127.0
    s = np.maximum(s, 1e-9)
    q = np.clip(np.round(wf / s), -127, 127).astype(np.int8)
    return {"q": q, "s": s.astype(np.float32)}


def quantize_tensor_np_g4(arr, group: int = GROUP_SIZE) -> dict:
    """numpy variant of `quantize_tensor_g4` (host-side int4 load path).
    arr [..., in, out] → grouped affine 4-bit over the in axis."""
    import numpy as np

    wf = np.asarray(arr, np.float32)
    *lead, n_in, n_out = wf.shape
    if n_in % group:
        raise ValueError(f"in dim {n_in} not divisible by group {group}")
    g = n_in // group
    wf = wf.reshape(*lead, g, group, n_out)
    mn = wf.min(axis=-2, keepdims=True)
    mx = wf.max(axis=-2, keepdims=True)
    s = np.maximum((mx - mn) / 15.0, 1e-9)
    nib = np.clip(np.round((wf - mn) / s), 0, 15).astype(np.uint8)
    half = group // 2
    packed = nib[..., :half, :] | (nib[..., half:, :] << 4)
    return {"g4": packed, "gs": s.astype(np.float32), "gz": (-mn).astype(np.float32)}


def is_prequantized(params: Params) -> bool:
    layers = params.get("layers") or {}
    return any(isinstance(layers.get(k), dict) for k in QUANT_LAYER_KEYS)


def dequantize_tensor(w) -> jnp.ndarray:
    """Back to a dense float tensor (tests / debugging)."""
    if not isinstance(w, dict):
        return w
    if "q" in w:
        return w["q"].astype(jnp.float32) * w["s"]
    qv = _grouped_values(w, jnp.float32)  # [..., G, gs, out]
    vals = qv * w["gs"]
    if "gz" in w:
        vals = vals - w["gz"]
    *lead, g, gs, n_out = vals.shape
    return vals.reshape(*lead, g * gs, n_out)


def quantize_params(cfg, params: Params, mode: str = "int8") -> Params:
    """Quantize a llama-family param tree's matmul weights (jit-friendly;
    run AFTER device_put so outputs inherit shardings)."""
    if mode in ("", "none", None):
        return params
    if mode == "int8":
        qfn = quantize_tensor
    elif mode == "int4":
        qfn = quantize_tensor_g4
    else:
        raise ValueError(f"unsupported quantization mode {mode!r}")
    out = dict(params)
    for stack in ("layers", "dense_layers"):
        if stack not in params:
            continue
        layers = dict(params[stack])
        for key in QUANT_LAYER_KEYS:
            if key in layers:
                layers[key] = qfn(layers[key])
        out[stack] = layers
    # lm_head [V, D] is used transposed (h @ W.T): quantize over D so the
    # scale lands on the output (vocab) axis of the transposed matmul.
    if "lm_head" in params and not cfg.tie_embeddings:
        w = params["lm_head"].astype(jnp.float32)  # [V, D]
        s = jnp.max(jnp.abs(w), axis=-1, keepdims=True) / 127.0  # [V, 1]
        s = jnp.maximum(s, 1e-9)
        q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
        out["lm_head"] = {"q": q, "s": s}
    return out


def init_params_quantized(
    cfg, key: jnp.ndarray, scale: float = 0.02, mode: str = "int8"
) -> Params:
    """Random init that lands directly in the quantized representation.

    Builds the same tree `quantize_params(mode=...)` would produce, but leaf
    by leaf: the bf16 tensor only ever exists as a transient inside one jit,
    so peak HBM ≈ the quantized tree + the largest single weight. This is how
    a synthetic llama-3-8b serves from a single 16 GB chip (a whole-tree bf16
    init is 2x HBM and OOMs before quantization could run).
    """
    from jax import tree_util as jtu

    from localai_tpu.models.llama import init_params

    if mode == "int8":
        qfn = quantize_tensor
    elif mode == "int4":
        qfn = quantize_tensor_g4
    else:
        raise ValueError(f"unsupported quantization mode {mode!r}")
    structure = jax.eval_shape(lambda k: init_params(cfg, k, scale), key)
    flat, treedef = jtu.tree_flatten_with_path(structure)
    keys = iter(jax.random.split(key, len(flat)))

    def leaf_name(path) -> str:
        last = path[-1]
        return getattr(last, "key", str(last))

    def build(path, sd):
        name = leaf_name(path)
        if "norm" in name:
            return jnp.ones(sd.shape, sd.dtype)
        if name in ("bq", "bk", "bv"):
            return jnp.zeros(sd.shape, sd.dtype)
        k = next(keys)
        if name in QUANT_LAYER_KEYS:
            return jax.jit(lambda kk: qfn(
                jax.random.normal(kk, sd.shape, jnp.float32) * scale
            ))(k)
        if name == "lm_head" and not cfg.tie_embeddings:
            def head(kk):
                w = jax.random.normal(kk, sd.shape, jnp.float32) * scale
                s = jnp.maximum(
                    jnp.max(jnp.abs(w), axis=-1, keepdims=True) / 127.0, 1e-9
                )
                q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
                return {"q": q, "s": s}

            return jax.jit(head)(k)
        return jax.jit(lambda kk: (
            jax.random.normal(kk, sd.shape, jnp.float32) * scale
        ).astype(sd.dtype))(k)

    leaves = [build(path, sd) for path, sd in flat]
    return jtu.tree_unflatten(treedef, leaves)


def unembed_matmul(h: jnp.ndarray, w, impl: str = "auto",
                   mesh=None) -> jnp.ndarray:
    """h @ W.T for the (possibly quantized) lm_head/embed matrix → f32.

    Quantized heads dispatch to the fused Pallas kernel at decode row
    counts (ops/quant_matmul.dispatch_unembed — out tiles stream contiguous
    weight rows, so the transpose never materializes); the XLA form below
    stays the oracle/fallback. Under tp>1 the kernel shard_maps over the
    vocab-parallel axis."""
    if isinstance(w, dict):
        from localai_tpu.ops.quant_matmul import dispatch_unembed

        y = dispatch_unembed(h, w, impl=impl, mesh=mesh)
        if y is not None:
            return y
        logits = jnp.dot(
            h, w["q"].T.astype(h.dtype), preferred_element_type=jnp.float32
        )
        return logits * w["s"][:, 0].astype(jnp.float32)  # [V] broadcasts
    return jnp.dot(h.astype(w.dtype), w.T, preferred_element_type=jnp.float32)
