"""Qwen2-VL: native-resolution vision tower + m-rope multimodal serving.

Reference: the vLLM backend serves Qwen2-VL through multimodal passthrough
(/root/reference/backend/python/vllm/backend.py:211-243); BASELINE.json's
VLM config names "Llava-1.6 / Qwen2-VL". Unlike the llava tower
(models/vision.py: fixed 336px grid, CLS+interp positions), Qwen2-VL
encodes at NATIVE resolution: images resize to the nearest multiple of
patch·merge (28), every 14px patch becomes a token with 2-axis rotary
positions, and a 2x2 patch merger compresses the grid into LLM tokens.
The language side applies M-RoPE — 3D (temporal, height, width) position
streams section-split across the rope frequencies (models/llama.py
`mrope`, ops/rope.mrope_angles).

TPU shape: the tower is one jitted dense program per (n_patches) bucket —
batched matmuls over the patch sequence (MXU), full (non-causal)
attention, fp32 softmax; the merger is a reshape + two matmuls. Position
streams and the decode-time rope delta are host-side numpy (tiny,
per-request).

HF layout (Qwen2VLForConditionalGeneration): visual.patch_embed.proj,
visual.blocks.{i}.{norm1,attn.qkv,attn.proj,norm2,mlp.fc1,mlp.fc2},
visual.merger.{ln_q,mlp.0,mlp.2}; the LLM under model.* (qwen2 names).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]

# CLIP normalization constants (Qwen2VLImageProcessor defaults)
IMAGE_MEAN = (0.48145466, 0.4578275, 0.40821073)
IMAGE_STD = (0.26862954, 0.26130258, 0.27577711)


@dataclasses.dataclass(frozen=True)
class Qwen2VLVisionConfig:
    depth: int = 32
    embed_dim: int = 1280
    num_heads: int = 16
    mlp_ratio: int = 4
    in_channels: int = 3
    patch_size: int = 14
    spatial_merge_size: int = 2
    temporal_patch_size: int = 2
    hidden_size: int = 3584  # LLM dim (merger output)
    # processor pixel budget (Qwen2VLImageProcessor defaults)
    min_pixels: int = 56 * 56
    max_pixels: int = 28 * 28 * 1280

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def patch_dim(self) -> int:
        return self.in_channels * self.temporal_patch_size * self.patch_size ** 2

    @property
    def merge_dim(self) -> int:
        return self.embed_dim * self.spatial_merge_size ** 2


def vision_config_from_hf(ckpt_dir: str) -> Qwen2VLVisionConfig:
    with open(os.path.join(ckpt_dir, "config.json")) as f:
        hf = json.load(f)
    vc = hf.get("vision_config") or {}
    return Qwen2VLVisionConfig(
        depth=vc.get("depth", 32),
        embed_dim=vc.get("embed_dim", 1280),
        num_heads=vc.get("num_heads", 16),
        mlp_ratio=vc.get("mlp_ratio", 4),
        in_channels=vc.get("in_channels", 3),
        patch_size=vc.get("patch_size", 14),
        spatial_merge_size=vc.get("spatial_merge_size", 2),
        temporal_patch_size=vc.get("temporal_patch_size", 2),
        hidden_size=vc.get("hidden_size", hf.get("hidden_size", 3584)),
    )


def is_qwen2_vl_dir(ckpt_dir: str) -> bool:
    cfg = os.path.join(ckpt_dir, "config.json")
    if not os.path.isfile(cfg):
        return False
    try:
        with open(cfg) as f:
            return json.load(f).get("model_type") == "qwen2_vl"
    except (OSError, ValueError):
        return False


# --------------------------------------------------------------------------- #
# Image preprocessing (Qwen2VLImageProcessor semantics)
# --------------------------------------------------------------------------- #


def smart_resize(h: int, w: int, factor: int = 28, min_pixels: int = 56 * 56,
                 max_pixels: int = 28 * 28 * 1280) -> tuple[int, int]:
    """Round to multiples of `factor` keeping total pixels inside the
    budget (HF qwen2_vl image_processing smart_resize)."""
    if max(h, w) / max(min(h, w), 1) > 200:
        raise ValueError("absurd aspect ratio")
    hbar = max(factor, round(h / factor) * factor)
    wbar = max(factor, round(w / factor) * factor)
    if hbar * wbar > max_pixels:
        beta = math.sqrt((h * w) / max_pixels)
        hbar = max(factor, math.floor(h / beta / factor) * factor)
        wbar = max(factor, math.floor(w / beta / factor) * factor)
    elif hbar * wbar < min_pixels:
        beta = math.sqrt(min_pixels / (h * w))
        hbar = math.ceil(h * beta / factor) * factor
        wbar = math.ceil(w * beta / factor) * factor
    return hbar, wbar


def preprocess(cfg: Qwen2VLVisionConfig, image: np.ndarray
               ) -> tuple[np.ndarray, tuple[int, int, int]]:
    """uint8 [H, W, 3] → (patches [n, patch_dim] f32, grid (t, gh, gw)).

    Matches the HF processor's flatten order exactly — the 2x2 merge
    groups are CONTIGUOUS in the sequence: (grid_t, gh/m, gw/m, m, m)
    outermost-to-innermost, features ordered (C, tps, ph, pw)."""
    from PIL import Image

    p, m, tps = cfg.patch_size, cfg.spatial_merge_size, cfg.temporal_patch_size
    H, W = image.shape[:2]
    rh, rw = smart_resize(H, W, p * m, cfg.min_pixels, cfg.max_pixels)
    img = np.asarray(
        Image.fromarray(np.asarray(image, np.uint8)).convert("RGB")
        .resize((rw, rh), Image.BICUBIC), np.float32) / 255.0
    img = (img - np.asarray(IMAGE_MEAN, np.float32)) / np.asarray(
        IMAGE_STD, np.float32)
    arr = img.transpose(2, 0, 1)[None]  # [1, C, H, W]
    arr = np.tile(arr, (tps, 1, 1, 1))  # temporal duplicate for still images
    gt, gh, gw = 1, rh // p, rw // p
    patches = arr.reshape(gt, tps, cfg.in_channels, gh // m, m, p, gw // m, m, p)
    patches = patches.transpose(0, 3, 6, 4, 7, 2, 1, 5, 8)
    return (patches.reshape(gt * gh * gw, cfg.patch_dim).astype(np.float32),
            (gt, gh, gw))


# --------------------------------------------------------------------------- #
# Vision tower forward
# --------------------------------------------------------------------------- #


def _vision_rope_angles(cfg: Qwen2VLVisionConfig, grid: tuple,
                        theta: float = 10000.0) -> np.ndarray:
    """[n_patches, head_dim/2] rotation angles: per-patch (row, col) ids in
    the merge-group order, each driving half the frequency ladder
    (Qwen2VisionTransformer rot_pos_emb + VisionRotaryEmbedding)."""
    t, gh, gw = grid
    m = cfg.spatial_merge_size
    hpos = np.broadcast_to(np.arange(gh)[:, None], (gh, gw))
    wpos = np.broadcast_to(np.arange(gw)[None, :], (gh, gw))

    def reorder(x):
        return (x.reshape(gh // m, m, gw // m, m).transpose(0, 2, 1, 3)
                .reshape(-1))

    hpos, wpos = reorder(hpos), reorder(wpos)
    dim = cfg.head_dim // 2  # rope dim per spatial axis pair
    inv = 1.0 / theta ** (np.arange(0, dim, 2, dtype=np.float32) / dim)
    ang = np.concatenate([
        hpos[:, None] * inv[None, :], wpos[:, None] * inv[None, :],
    ], axis=-1)  # [gh*gw, head_dim/2]
    return np.tile(ang, (t, 1)).astype(np.float32)


def vision_forward(cfg: Qwen2VLVisionConfig, p: Params, patches: jnp.ndarray,
                   angles: jnp.ndarray) -> jnp.ndarray:
    """patches [N, patch_dim], angles [N, head_dim/2] →
    merged tokens [N / merge², hidden_size]."""
    from localai_tpu.ops.rope import rope_rotate

    N = patches.shape[0]
    H, Dh = cfg.num_heads, cfg.head_dim
    w0 = p["patch_embed.weight"]
    # conv3d == linear over the flattened patch; cast to the weight dtype so
    # the whole trunk runs bf16 matmuls (norms/softmax stay fp32)
    h = (patches @ w0).astype(w0.dtype)

    def ln(x, pre):
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = xf.var(-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + 1e-6)
        return y * p[f"{pre}.weight"] + p[f"{pre}.bias"]

    ang = angles[None]  # [1, N, hd/2] — rope_rotate wants [..., seq, h, d]
    for i in range(cfg.depth):
        pre = f"blocks.{i}"
        x = ln(h, f"{pre}.norm1").astype(h.dtype)
        qkv = x @ p[f"{pre}.attn.qkv.weight"] + p[f"{pre}.attn.qkv.bias"]
        q, k, v = jnp.split(qkv.reshape(N, 3, H, Dh), 3, axis=1)
        q = rope_rotate(q.transpose(1, 0, 2, 3), ang)[0]  # [N, H, Dh]
        k = rope_rotate(k.transpose(1, 0, 2, 3), ang)[0]
        v = v[:, 0]
        scores = jnp.einsum("qhd,khd->hqk", q, k).astype(jnp.float32) / math.sqrt(Dh)
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        attn = jnp.einsum("hqk,khd->qhd", probs, v).reshape(N, -1)
        h = h + attn @ p[f"{pre}.attn.proj.weight"] + p[f"{pre}.attn.proj.bias"]
        x = ln(h, f"{pre}.norm2").astype(h.dtype)
        y = x @ p[f"{pre}.mlp.fc1.weight"] + p[f"{pre}.mlp.fc1.bias"]
        y = y * jax.nn.sigmoid(1.702 * y)  # QuickGELU
        h = h + y @ p[f"{pre}.mlp.fc2.weight"] + p[f"{pre}.mlp.fc2.bias"]

    # PatchMerger: ln_q per token, then 2x2 groups (contiguous by patch
    # order) concatenate and pass through a 2-layer MLP into the LLM dim.
    x = ln(h, "merger.ln_q").astype(h.dtype).reshape(-1, cfg.merge_dim)
    x = x @ p["merger.mlp.0.weight"] + p["merger.mlp.0.bias"]
    x = jax.nn.gelu(x, approximate=False)
    return x @ p["merger.mlp.2.weight"] + p["merger.mlp.2.bias"]


# --------------------------------------------------------------------------- #
# M-RoPE position ids (HF Qwen2VLForConditionalGeneration.get_rope_index)
# --------------------------------------------------------------------------- #


def mrope_positions_for_span(total_len: int, offset: int, grid: tuple,
                             merge: int = 2) -> tuple[np.ndarray, int]:
    """3D (t, h, w) position streams for a prompt whose [offset,
    offset+span) token range holds one image's merged patches.

    Text tokens advance all three streams together; image tokens freeze t
    at the preceding text position and spread (h, w) over the merged grid;
    text after the image resumes at max_position + 1. Returns (pos3
    [3, total_len] i32, rope_delta) with rope_delta = (max_pos + 1) -
    total_len — the constant that makes decode positions row_index + delta
    (HF returns the same as mrope_position_deltas)."""
    t, gh, gw = grid
    mh, mw = gh // merge, gw // merge
    span = t * mh * mw
    pos3 = np.zeros((3, total_len), np.int64)
    # text before the image
    pos3[:, :offset] = np.arange(offset)[None, :]
    st = offset
    tt = np.repeat(np.arange(t), mh * mw)
    hh = np.tile(np.repeat(np.arange(mh), mw), t)
    ww = np.tile(np.tile(np.arange(mw), mh), t)
    pos3[0, offset: offset + span] = st + tt
    pos3[1, offset: offset + span] = st + hh
    pos3[2, offset: offset + span] = st + ww
    nxt = st + int(max(t, mh, mw))  # max position inside the span + 1
    n_after = total_len - offset - span
    if n_after > 0:
        pos3[:, offset + span:] = nxt + np.arange(n_after)[None, :]
        max_pos = nxt + n_after - 1
    else:
        max_pos = nxt - 1
    return pos3.astype(np.int32), int(max_pos + 1 - total_len)


# --------------------------------------------------------------------------- #
# Checkpoint loading + encoder wrapper
# --------------------------------------------------------------------------- #


def load_hf_qwen2_vl_vision(cfg: Qwen2VLVisionConfig, ckpt_dir: str) -> Params:
    """visual.* tensors → flat dict with linears pre-transposed [in, out];
    the conv3d patch embed flattens to a [patch_dim, embed_dim] matmul."""
    from localai_tpu.engine.weights import _ShardReader

    # _ShardReader aliases model.visual.* → visual.*, so one spelling
    # addresses both the published and the nested transformers layouts.
    reader = _ShardReader(ckpt_dir)
    prefix = "visual."
    out: Params = {}
    w = reader.get(prefix + "patch_embed.proj.weight")  # [D, C, tps, p, p]
    out["patch_embed.weight"] = jnp.asarray(
        np.ascontiguousarray(w.reshape(w.shape[0], -1).T))
    names = ["merger.ln_q.weight", "merger.ln_q.bias",
             "merger.mlp.0.weight", "merger.mlp.0.bias",
             "merger.mlp.2.weight", "merger.mlp.2.bias"]
    for i in range(cfg.depth):
        for nm in ("norm1.weight", "norm1.bias", "attn.qkv.weight",
                   "attn.qkv.bias", "attn.proj.weight", "attn.proj.bias",
                   "norm2.weight", "norm2.bias", "mlp.fc1.weight",
                   "mlp.fc1.bias", "mlp.fc2.weight", "mlp.fc2.bias"):
            names.append(f"blocks.{i}.{nm}")
    for nm in names:
        arr = reader.get(prefix + nm)
        if arr.ndim == 2 and nm.endswith(".weight"):
            arr = arr.T
        out[nm] = jnp.asarray(np.ascontiguousarray(arr))
    return out


class Qwen2VLVisionEncoder:
    """Host-side wrapper: uint8 image → (merged tokens [n, llm_dim], grid).
    Jit-cached per patch-count bucket (native resolution varies)."""

    kind = "qwen2_vl"

    def __init__(self, cfg: Qwen2VLVisionConfig, params: Params):
        self.cfg = cfg
        self.params = params
        self._jit: dict[int, Any] = {}

    def encode_with_grid(self, image: np.ndarray
                         ) -> tuple[np.ndarray, tuple[int, int, int]]:
        patches, grid = preprocess(self.cfg, image)
        angles = _vision_rope_angles(self.cfg, grid)
        n = patches.shape[0]
        fn = self._jit.get(n)
        if fn is None:
            cfg = self.cfg
            fn = jax.jit(lambda p, x, a: vision_forward(cfg, p, x, a))
            if len(self._jit) >= 8:
                self._jit.pop(next(iter(self._jit)))
            self._jit[n] = fn
        feats = np.asarray(fn(self.params, jnp.asarray(patches),
                              jnp.asarray(angles)))
        return feats, grid

    @property
    def merge(self) -> int:
        return self.cfg.spatial_merge_size

    def encode(self, image: np.ndarray) -> np.ndarray:
        return self.encode_with_grid(image)[0]
