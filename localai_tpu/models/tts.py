"""Text-to-speech as pure-functional JAX: a FastSpeech-style non-autoregressive
acoustic model plus a Griffin-Lim vocoder.

The reference ships seven TTS backends (piper, bark, coqui, kokoro, chatterbox,
kitten, melotts — SURVEY.md §2.4; RPC TTS in backend/backend.proto and
endpoint core/http/endpoints/localai/tts.go). They are all torch/onnx
pipelines; this is a TPU-first redesign of the same capability:

- Char ids → transformer encoder → fixed-rate length regulator (static
  shapes; no data-dependent durations, so the whole utterance jits as one
  XLA program) → transformer decoder → mel head.
- Vocoder: mel → linear spectrum (filterbank pseudo-inverse matmul) →
  Griffin-Lim phase recovery as a `lax.fori_loop` of STFT/iSTFT pairs —
  batched FFTs and matmuls, no host round-trips.
- Speaker voices are learned embeddings added to the encoder output.

Weights use our own safetensors layout (save_tts / load_tts round-trip);
there is no de-facto HF-standard TTS checkpoint to be compatible with.
"""

from __future__ import annotations

import dataclasses
import json
import os
from functools import lru_cache
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TTSConfig:
    name: str = "tts"
    vocab_size: int = 256  # utf-8 bytes
    d_model: int = 256
    n_heads: int = 4
    enc_layers: int = 4
    dec_layers: int = 4
    ffn_mult: int = 4
    n_voices: int = 8
    max_text: int = 256  # chars per chunk
    frames_per_char: int = 6  # fixed-rate length regulator
    n_mels: int = 80
    n_fft: int = 1024
    hop: int = 256
    sample_rate: int = 22050

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def ffn(self) -> int:
        return self.d_model * self.ffn_mult

    @property
    def max_frames(self) -> int:
        return self.max_text * self.frames_per_char


TTS_PRESETS: dict[str, TTSConfig] = {
    "tts-test": TTSConfig(
        name="tts-test", d_model=32, n_heads=2, enc_layers=1, dec_layers=1,
        max_text=32, frames_per_char=2, n_mels=20, n_fft=256, hop=64,
        sample_rate=8000, n_voices=2,
    ),
    "tts-base": TTSConfig(name="tts-base"),
}


def _sinusoids(length: int, channels: int) -> np.ndarray:
    log_ts = np.log(10000.0) / max(channels // 2 - 1, 1)
    inv = np.exp(-log_ts * np.arange(channels // 2))
    scaled = np.arange(length)[:, None] * inv[None, :]
    return np.concatenate([np.sin(scaled), np.cos(scaled)], axis=1).astype(np.float32)


def _block_params(rnd, L, d, ffn) -> Params:
    return {
        "ln1_w": jnp.ones((L, d)), "ln1_b": jnp.zeros((L, d)),
        "q_w": rnd((L, d, d)), "k_w": rnd((L, d, d)), "v_w": rnd((L, d, d)),
        "o_w": rnd((L, d, d)),
        "ln2_w": jnp.ones((L, d)), "ln2_b": jnp.zeros((L, d)),
        "fc1_w": rnd((L, d, ffn)), "fc1_b": jnp.zeros((L, ffn)),
        "fc2_w": rnd((L, ffn, d)), "fc2_b": jnp.zeros((L, d)),
    }


def init_params(cfg: TTSConfig, key: jnp.ndarray, scale: float = 0.02) -> Params:
    keys = iter(jax.random.split(key, 64))

    def rnd(shape):
        return jax.random.normal(next(keys), shape, jnp.float32) * scale

    return {
        "embed": rnd((cfg.vocab_size, cfg.d_model)),
        "voice": rnd((cfg.n_voices, cfg.d_model)),
        "enc_pos": jnp.asarray(_sinusoids(cfg.max_text, cfg.d_model)),
        "dec_pos": jnp.asarray(_sinusoids(cfg.max_frames, cfg.d_model)),
        "enc": _block_params(rnd, cfg.enc_layers, cfg.d_model, cfg.ffn),
        "dec": _block_params(rnd, cfg.dec_layers, cfg.d_model, cfg.ffn),
        "mel_w": rnd((cfg.d_model, cfg.n_mels)),
        "mel_b": jnp.zeros((cfg.n_mels,)),
        "ln_out_w": jnp.ones((cfg.d_model,)), "ln_out_b": jnp.zeros((cfg.d_model,)),
    }


def _ln(x, w, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + eps) * w + b


def _blocks(cfg: TTSConfig, params_blk: Params, h: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Non-causal transformer stack. h [B, T, d]; mask [B, T] valid."""
    B, T, d = h.shape
    H, Dh = cfg.n_heads, cfg.head_dim

    def layer(h, lp):
        x = _ln(h, lp["ln1_w"], lp["ln1_b"])
        q = (x @ lp["q_w"]).reshape(B, T, H, Dh)
        k = (x @ lp["k_w"]).reshape(B, T, H, Dh)
        v = (x @ lp["v_w"]).reshape(B, T, H, Dh)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * Dh**-0.5
        scores = jnp.where(mask[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, T, d)
        h = h + attn @ lp["o_w"]
        x = _ln(h, lp["ln2_w"], lp["ln2_b"])
        h = h + jax.nn.gelu(x @ lp["fc1_w"] + lp["fc1_b"]) @ lp["fc2_w"] + lp["fc2_b"]
        return h, None

    h, _ = jax.lax.scan(layer, h, params_blk)
    return h


def text_to_mel(
    cfg: TTSConfig,
    params: Params,
    text_ids: jnp.ndarray,  # [B, max_text] int32, zero-padded
    lengths: jnp.ndarray,  # [B] int32
    voice: jnp.ndarray,  # [B] int32 speaker ids
):
    """Returns (mel [B, max_frames, n_mels] f32, frame_mask [B, max_frames])."""
    B, T = text_ids.shape
    r = cfg.frames_per_char
    text_mask = jnp.arange(T)[None, :] < lengths[:, None]

    h = params["embed"][text_ids] + params["enc_pos"][None, :T]
    h = h + params["voice"][voice][:, None, :]
    h = _blocks(cfg, params["enc"], h, text_mask)

    # Fixed-rate length regulator: repeat each char embedding r times.
    hf = jnp.repeat(h, r, axis=1)  # [B, T*r, d]
    frame_mask = jnp.repeat(text_mask, r, axis=1)
    hf = hf + params["dec_pos"][None, : hf.shape[1]]
    hf = _blocks(cfg, params["dec"], hf, frame_mask)
    hf = _ln(hf, params["ln_out_w"], params["ln_out_b"])
    mel = hf @ params["mel_w"] + params["mel_b"]
    mel = jnp.where(frame_mask[..., None], mel, jnp.log(jnp.float32(1e-5)))
    return mel, frame_mask


# --------------------------------------------------------------------------- #
# Vocoder: mel → waveform via Griffin-Lim (all-JAX)
# --------------------------------------------------------------------------- #


@lru_cache(maxsize=4)
def _mel_inverse(n_mels: int, n_fft: int, sr: int) -> np.ndarray:
    from localai_tpu.audio.features import mel_filterbank

    fb = mel_filterbank(n_mels, n_fft, sr)  # [n_mels, n_freqs]
    return np.linalg.pinv(fb).astype(np.float32)  # [n_freqs, n_mels]


def _stft(x: jnp.ndarray, n_fft: int, hop: int, window: jnp.ndarray) -> jnp.ndarray:
    n_frames = 1 + (x.shape[-1] - n_fft) // hop
    idx = jnp.arange(n_frames)[:, None] * hop + jnp.arange(n_fft)[None, :]
    return jnp.fft.rfft(x[..., idx] * window, axis=-1)  # [..., n_frames, n_freqs]


def _istft(spec: jnp.ndarray, n_fft: int, hop: int, window: jnp.ndarray, length: int) -> jnp.ndarray:
    frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) * window  # [n_frames, n_fft]
    n_frames = frames.shape[-2]
    idx = (jnp.arange(n_frames)[:, None] * hop + jnp.arange(n_fft)[None, :]).reshape(-1)
    x = jnp.zeros((length,), jnp.float32).at[idx].add(frames.reshape(-1))
    wsq = jnp.zeros((length,), jnp.float32).at[idx].add(
        jnp.tile(window**2, (n_frames, 1)).reshape(-1)
    )
    return x / jnp.maximum(wsq, 1e-8)


def mel_to_audio(
    cfg: TTSConfig,
    log_mel: jnp.ndarray,  # [T_frames, n_mels] natural-log mel magnitudes
    n_iter: int = 32,
) -> jnp.ndarray:
    """Griffin-Lim phase recovery. Returns [T_samples] float32."""
    inv = jnp.asarray(_mel_inverse(cfg.n_mels, cfg.n_fft, cfg.sample_rate))
    mag = jnp.maximum(jnp.exp(log_mel) @ inv.T, 0.0)  # [T_frames, n_freqs]
    window = jnp.asarray(np.hanning(cfg.n_fft + 1)[:-1].astype(np.float32))
    n_frames = mag.shape[0]
    length = (n_frames - 1) * cfg.hop + cfg.n_fft

    key = jax.random.key(0)
    phase = jax.random.uniform(key, mag.shape, jnp.float32, 0, 2 * np.pi)
    spec = mag * jnp.exp(1j * phase)

    def gl_iter(_, spec):
        x = _istft(spec, cfg.n_fft, cfg.hop, window, length)
        new = _stft(x, cfg.n_fft, cfg.hop, window)
        new = new[: n_frames]
        return mag * jnp.exp(1j * jnp.angle(new))

    spec = jax.lax.fori_loop(0, n_iter, gl_iter, spec)
    audio = _istft(spec, cfg.n_fft, cfg.hop, window, length)
    peak = jnp.max(jnp.abs(audio))
    return audio / jnp.maximum(peak, 1e-6) * 0.95


def synthesize(
    cfg: TTSConfig,
    params: Params,
    text_ids: jnp.ndarray,  # [max_text] int32 zero-padded
    length: jnp.ndarray,  # scalar int32
    voice: jnp.ndarray,  # scalar int32
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One utterance → (audio [max_samples] f32, n_samples scalar i32)."""
    mel, frame_mask = text_to_mel(
        cfg, params, text_ids[None], length[None], voice[None]
    )
    audio = mel_to_audio(cfg, mel[0])
    n_frames_valid = jnp.sum(frame_mask[0].astype(jnp.int32))
    n_samples = jnp.minimum(n_frames_valid * cfg.hop, audio.shape[0])
    return audio, n_samples


# --------------------------------------------------------------------------- #
# Checkpoint I/O (our safetensors layout)
# --------------------------------------------------------------------------- #


def save_tts(cfg: TTSConfig, params: Params, ckpt_dir: str) -> None:
    from safetensors.numpy import save_file

    os.makedirs(ckpt_dir, exist_ok=True)
    flat = {}
    for k, v in params.items():
        if isinstance(v, dict):
            for k2, v2 in v.items():
                flat[f"{k}.{k2}"] = np.asarray(v2, np.float32)
        else:
            flat[k] = np.asarray(v, np.float32)
    save_file(flat, os.path.join(ckpt_dir, "model.safetensors"))
    with open(os.path.join(ckpt_dir, "config.json"), "w") as f:
        json.dump({"model_type": "localai-tts", **dataclasses.asdict(cfg)}, f, indent=1)


def load_tts(ckpt_dir: str) -> tuple[TTSConfig, Params]:
    from safetensors import safe_open

    with open(os.path.join(ckpt_dir, "config.json")) as f:
        hf = json.load(f)
    hf.pop("model_type", None)
    cfg = TTSConfig(**hf)
    params: Params = {"enc": {}, "dec": {}}
    with safe_open(os.path.join(ckpt_dir, "model.safetensors"), framework="numpy") as f:
        for name in f.keys():
            arr = jnp.asarray(f.get_tensor(name))
            if "." in name:
                grp, sub = name.split(".", 1)
                params.setdefault(grp, {})[sub] = arr
            else:
                params[name] = arr
    return cfg, params
