"""Text-to-video: AnimateDiff-class motion modules over the SD UNet, in JAX.

Reference: the diffusers backend serves video through temporal pipelines
(/root/reference/backend/python/diffusers/backend.py:226-253, dispatched via
core/backend/video.go). The pragmatic open ecosystem for SD-1.5-class bases
is AnimateDiff (Guo et al.): a MotionAdapter checkpoint of temporal
transformer blocks inserted after every spatial block of the UNet, attending
ACROSS FRAMES at each spatial location. The base image checkpoint is reused
unchanged (models/latent_diffusion.py); the adapter is a separate published
artifact (e.g. guoyww/animatediff-motion-adapter-v1-5-2) in the diffusers
MotionAdapter layout, which this module loads directly.

TPU-native shape: frames ride the batch axis ([B·F, H, W, C] NHWC); motion
modules reshape to [B·H·W, F, C] so temporal attention is one batched matmul
over the (tiny) frame axis — XLA fuses the transposes, and the whole
denoising loop stays a single lax.scan program like the image path.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.models.latent_diffusion import (
    Params,
    SDPipelineConfig,
    UNetConfig,
    _conv,
    _group_norm,
    _layer_norm,
    _linear,
    _load_safetensors_dir,
    _prep,
    _resnet,
    _spatial_transformer,
    alphas_cumprod,
    clip_encode,
    ddim_step,
    ddim_timesteps,
    get_timestep_embedding,
    vae_decode,
    vae_encode,
)

log = logging.getLogger("localai_tpu.video_diffusion")


@dataclass
class MotionConfig:
    """diffusers MotionAdapter config subset (config.json of e.g.
    guoyww/animatediff-motion-adapter-v1-5-2)."""

    block_out_channels: tuple = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    mid_layers: int = 1
    num_heads: int = 8
    max_seq_length: int = 32
    norm_num_groups: int = 32
    use_mid: bool = True


def is_motion_adapter_dir(path: str) -> bool:
    cfg = os.path.join(path, "config.json")
    if not os.path.isfile(cfg):
        return False
    try:
        with open(cfg) as f:
            return json.load(f).get("_class_name") == "MotionAdapter"
    except Exception:  # noqa: BLE001 — not an adapter
        return False


def load_motion_adapter(path: str, dtype=jnp.float32):
    """(MotionConfig, params) from a diffusers MotionAdapter dir."""
    with open(os.path.join(path, "config.json")) as f:
        c = json.load(f)
    cfg = MotionConfig(
        block_out_channels=tuple(c.get("block_out_channels", (320, 640, 1280, 1280))),
        layers_per_block=int(c.get("motion_layers_per_block", 2)),
        mid_layers=int(c.get("motion_mid_block_layers_per_block", 1)),
        num_heads=int(c.get("motion_num_attention_heads", 8)),
        max_seq_length=int(c.get("motion_max_seq_length", 32)),
        norm_num_groups=int(c.get("motion_norm_num_groups", 32)),
        use_mid=bool(c.get("use_motion_mid_block", True)),
    )
    params = _prep(_load_safetensors_dir(path), dtype)
    return cfg, params


def _sin_pos_embed(n: int, dim: int) -> np.ndarray:
    """diffusers SinusoidalPositionalEmbedding: interleaved sin/cos [n, dim]."""
    pos = np.arange(n, dtype=np.float64)[:, None]
    div = np.exp(np.arange(0, dim, 2, dtype=np.float64) * (-np.log(10000.0) / dim))
    pe = np.zeros((n, dim), np.float32)
    pe[:, 0::2] = np.sin(pos * div)
    pe[:, 1::2] = np.cos(pos * div)
    return pe


def _temporal_attention(p: Params, pre: str, n: jnp.ndarray, heads: int) -> jnp.ndarray:
    """Self-attention over the frame axis. n: [N, F, C] (already normed +
    positionally encoded)."""
    N, F, C = n.shape
    hd = C // heads
    q = (n @ p[f"{pre}.to_q.weight"].astype(n.dtype)).reshape(N, F, heads, hd)
    k = (n @ p[f"{pre}.to_k.weight"].astype(n.dtype)).reshape(N, F, heads, hd)
    v = (n @ p[f"{pre}.to_v.weight"].astype(n.dtype)).reshape(N, F, heads, hd)
    sc = jnp.einsum("nqhd,nkhd->nhqk", q, k).astype(jnp.float32) / np.sqrt(hd)
    probs = jax.nn.softmax(sc, axis=-1).astype(v.dtype)
    out = jnp.einsum("nhqk,nkhd->nqhd", probs, v).reshape(N, F, C)
    return _linear(out, p, f"{pre}.to_out.0")


def _temporal_block(p: Params, pre: str, h: jnp.ndarray, heads: int,
                    pe: jnp.ndarray) -> jnp.ndarray:
    """diffusers BasicTransformerBlock with double self-attention and a
    sinusoidal positional embedding over frames (the AnimateDiff temporal
    block: Temporal_Self + Temporal_Self + GEGLU FF). h: [N, F, C]."""
    F = h.shape[1]
    pef = pe[None, :F].astype(h.dtype)
    n = _layer_norm(h, p[f"{pre}.norm1.weight"], p[f"{pre}.norm1.bias"]) + pef
    h = h + _temporal_attention(p, f"{pre}.attn1", n, heads)
    if f"{pre}.attn2.to_q.weight" in p:
        n = _layer_norm(h, p[f"{pre}.norm2.weight"], p[f"{pre}.norm2.bias"]) + pef
        h = h + _temporal_attention(p, f"{pre}.attn2", n, heads)
    n = _layer_norm(h, p[f"{pre}.norm3.weight"], p[f"{pre}.norm3.bias"])
    proj = _linear(n, p, f"{pre}.ff.net.0.proj")
    a, gate = jnp.split(proj, 2, axis=-1)
    return h + _linear(a * jax.nn.gelu(gate), p, f"{pre}.ff.net.2")


def _motion_module(mcfg: MotionConfig, mp: Params, pre: str, x: jnp.ndarray,
                   frames: int) -> jnp.ndarray:
    """One AnimateDiffTransformer3D: group-norm over the whole (F, H, W)
    volume, temporal transformer per spatial location, residual add.
    x: [B·F, H, W, C]."""
    BF, H, W, C = x.shape
    B = BF // frames
    r = x
    h5 = x.reshape(B, frames, H, W, C)
    hn = _group_norm(h5, mp[f"{pre}.norm.weight"], mp[f"{pre}.norm.bias"],
                     mcfg.norm_num_groups, eps=1e-5)
    h = hn.transpose(0, 2, 3, 1, 4).reshape(B * H * W, frames, C)
    h = _linear(h, mp, f"{pre}.proj_in")
    pe_name = f"{pre}.transformer_blocks.0.pos_embed.pe"
    if pe_name in mp:  # stored buffer (some exports keep it)
        pe = mp[pe_name].reshape(-1, C)
    else:
        pe = jnp.asarray(_sin_pos_embed(mcfg.max_seq_length, C))
    bi = 0
    while f"{pre}.transformer_blocks.{bi}.norm1.weight" in mp:
        h = _temporal_block(mp, f"{pre}.transformer_blocks.{bi}", h,
                            mcfg.num_heads, pe)
        bi += 1
    h = _linear(h, mp, f"{pre}.proj_out")
    h = h.reshape(B, H, W, frames, C).transpose(0, 3, 1, 2, 4).reshape(BF, H, W, C)
    return h + r


def motion_unet_forward(cfg: UNetConfig, mcfg: MotionConfig, p: Params,
                        mp: Params, sample: jnp.ndarray, t: jnp.ndarray,
                        ctx: jnp.ndarray, frames: int) -> jnp.ndarray:
    """UNet2DCondition + motion modules (diffusers UNetMotionModel order:
    resnet → spatial attention → motion module, per layer; mid block
    resnet → attention → motion → resnet). sample: [B·F, h, w, C_lat]."""
    g = cfg.norm_num_groups
    temb = get_timestep_embedding(
        t, cfg.block_out_channels[0], cfg.flip_sin_to_cos, cfg.freq_shift
    ).astype(sample.dtype)
    temb = _linear(temb, p, "time_embedding.linear_1")
    temb = _linear(jax.nn.silu(temb), p, "time_embedding.linear_2")

    h = _conv(sample, p["conv_in.weight"], p["conv_in.bias"])
    skips = [h]
    for bi, btype in enumerate(cfg.down_block_types):
        pre = f"down_blocks.{bi}"
        heads = cfg.heads_for(bi)
        for li in range(cfg.layers_per_block):
            h = _resnet(p, f"{pre}.resnets.{li}", h, temb, g)
            if btype in ("CrossAttnDownBlock2D", "CrossAttnDownBlockMotion"):
                h = _spatial_transformer(p, f"{pre}.attentions.{li}", h, ctx, heads, g)
            h = _motion_module(mcfg, mp, f"{pre}.motion_modules.{li}", h, frames)
            skips.append(h)
        if f"{pre}.downsamplers.0.conv.weight" in p:
            h = _conv(h, p[f"{pre}.downsamplers.0.conv.weight"],
                      p[f"{pre}.downsamplers.0.conv.bias"], stride=2)
            skips.append(h)

    h = _resnet(p, "mid_block.resnets.0", h, temb, g)
    h = _spatial_transformer(
        p, "mid_block.attentions.0", h, ctx,
        cfg.heads_for(len(cfg.block_out_channels) - 1), g,
    )
    if mcfg.use_mid and "mid_block.motion_modules.0.proj_in.weight" in mp:
        h = _motion_module(mcfg, mp, "mid_block.motion_modules.0", h, frames)
    h = _resnet(p, "mid_block.resnets.1", h, temb, g)

    for bi, btype in enumerate(cfg.up_block_types):
        pre = f"up_blocks.{bi}"
        heads = cfg.heads_for(len(cfg.block_out_channels) - 1 - bi)
        for li in range(cfg.layers_per_block + 1):
            skip = skips.pop()
            h = jnp.concatenate([h, skip], axis=-1)
            h = _resnet(p, f"{pre}.resnets.{li}", h, temb, g)
            if btype in ("CrossAttnUpBlock2D", "CrossAttnUpBlockMotion"):
                h = _spatial_transformer(p, f"{pre}.attentions.{li}", h, ctx, heads, g)
            h = _motion_module(mcfg, mp, f"{pre}.motion_modules.{li}", h, frames)
        if f"{pre}.upsamplers.0.conv.weight" in p:
            B, H, W, C = h.shape
            h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
            h = _conv(h, p[f"{pre}.upsamplers.0.conv.weight"],
                      p[f"{pre}.upsamplers.0.conv.bias"])

    h = _group_norm(h, p["conv_norm_out.weight"], p["conv_norm_out.bias"], g)
    return _conv(jax.nn.silu(h), p["conv_out.weight"], p["conv_out.bias"])


def generate_video(
    cfg: SDPipelineConfig,
    params: dict[str, Params],  # {"text", "unet", "vae"}
    mcfg: MotionConfig,
    mparams: Params,
    cond_ids: jnp.ndarray,  # [1, 77]
    uncond_ids: jnp.ndarray,
    key: jnp.ndarray,
    frames: int = 16,
    steps: int = 20,
    guidance: float = 7.5,
    height: int = 512,
    width: int = 512,
    init_image: Optional[jnp.ndarray] = None,  # [1, H, W, 3] in [0, 1]
    strength: float = 0.8,
) -> jnp.ndarray:
    """Text→video: DDIM over the motion UNet, shared text condition, one
    noise sample PER FRAME (the motion modules correlate frames — unlike the
    old latent-slerp sweep there is a real temporal model between them).
    Returns [frames, H, W, 3] float32 in [0, 1].

    Image→video (init_image set): the source is VAE-encoded and broadcast
    as every frame's init latent, re-noised `strength` of the way up the
    schedule with INDEPENDENT per-frame noise — the motion modules then
    animate around the anchored content while only the remaining steps run
    (init-latent img2vid; reference serves the same capability through
    WanImageToVideoPipeline / StableVideoDiffusionPipeline,
    diffusers backend.py:242-250, :280-284)."""
    if frames > mcfg.max_seq_length:
        raise ValueError(
            f"frames={frames} exceeds the motion adapter's max sequence "
            f"length {mcfg.max_seq_length}"
        )
    ctx_c = clip_encode(cfg.text, params["text"], cond_ids)
    ctx_u = clip_encode(cfg.text, params["text"], uncond_ids)
    F = frames
    ctx = jnp.concatenate([
        jnp.broadcast_to(ctx_u, (F, *ctx_u.shape[1:])),
        jnp.broadcast_to(ctx_c, (F, *ctx_c.shape[1:])),
    ], axis=0)  # [2F, 77, C] — uncond batch then cond batch (B=2 groups)
    vs = cfg.vae.spatial_scale
    lat_h, lat_w = height // vs, width // vs
    acp = jnp.asarray(alphas_cumprod(cfg))
    key, nk = jax.random.split(key)
    x = jax.random.normal(nk, (F, lat_h, lat_w, cfg.unet.in_channels), jnp.float32)

    ts = jnp.asarray(ddim_timesteps(cfg, steps))
    ratio = cfg.num_train_timesteps // steps

    i0 = 0
    if init_image is not None:
        strength = min(max(float(strength), 0.0), 1.0)
        i0 = steps - max(1, min(steps, int(round(steps * strength))))
        lat0 = vae_encode(cfg.vae, params["vae"], init_image)  # [1, h, w, C]
        a0 = acp[ts[i0]]
        x = jnp.sqrt(a0) * lat0 + jnp.sqrt(1.0 - a0) * x  # per-frame noise

    def cfg_eps(x_in, t):
        both = jnp.concatenate([x_in, x_in], axis=0)  # [2F, ...]
        tt = jnp.full((2 * F,), t, jnp.float32)
        out = motion_unet_forward(cfg.unet, mcfg, params["unet"], mparams,
                                  both, tt, ctx, frames=F)
        eps_u, eps_c = jnp.split(out, 2, axis=0)
        return eps_u + guidance * (eps_c - eps_u)

    def step(xc, i):
        t = ts[i]
        eps = cfg_eps(xc, t.astype(jnp.float32))
        return ddim_step(cfg, acp, eps, t, t - ratio, xc), None

    x, _ = jax.lax.scan(step, x, jnp.arange(i0, steps))
    return vae_decode(cfg.vae, params["vae"], x / cfg.vae.scaling_factor)
