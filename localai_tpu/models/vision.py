"""CLIP-style vision tower + Llava projector: image features for VLM chat.

Reference capability: multimodal chat via llava / Qwen2-VL through the vllm
backend (BASELINE.json configs; backend/python/vllm multimodal). TPU shape:
a ViT encoder (patch conv → pre-LN transformer) whose `select_layer` hidden
states (llava uses -2) pass through a 2-layer MLP projector into the LLM's
embedding space; the serving engine injects the projected tokens into the
prompt's embedding sequence at admission (models/llama.py `inject`).

HF weight mapping follows LlavaForConditionalGeneration
(`vision_tower.vision_model.*`, `multi_modal_projector.linear_{1,2}`).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    name: str = "clip-vit"
    image_size: int = 336
    patch: int = 14
    d_model: int = 1024
    layers: int = 24
    n_heads: int = 16
    ffn: int = 4096
    llm_dim: int = 4096  # projector output = LLM hidden size
    select_layer: int = -2  # llava: penultimate encoder layer
    layer_norm_eps: float = 1e-5

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


VISION_PRESETS: dict[str, VisionConfig] = {
    "vit-test": VisionConfig(
        name="vit-test", image_size=16, patch=8, d_model=32, layers=2,
        n_heads=2, ffn=64, llm_dim=64, select_layer=-1,
    ),
    "clip-vit-l-336": VisionConfig(name="clip-vit-l-336"),
}


def init_params(cfg: VisionConfig, key: jnp.ndarray, scale: float = 0.02) -> Params:
    keys = iter(jax.random.split(key, 32))
    D, L = cfg.d_model, cfg.layers

    def rnd(shape):
        return jax.random.normal(next(keys), shape, jnp.float32) * scale

    return {
        "patch_w": rnd((D, 3, cfg.patch, cfg.patch)),  # HF conv layout [D,C,k,k]
        "cls": rnd((D,)),
        "pos": rnd((cfg.n_patches + 1, D)),
        "pre_ln_w": jnp.ones((D,)), "pre_ln_b": jnp.zeros((D,)),
        "layers": {
            "ln1_w": jnp.ones((L, D)), "ln1_b": jnp.zeros((L, D)),
            "q_w": rnd((L, D, D)), "q_b": jnp.zeros((L, D)),
            "k_w": rnd((L, D, D)), "k_b": jnp.zeros((L, D)),
            "v_w": rnd((L, D, D)), "v_b": jnp.zeros((L, D)),
            "o_w": rnd((L, D, D)), "o_b": jnp.zeros((L, D)),
            "ln2_w": jnp.ones((L, D)), "ln2_b": jnp.zeros((L, D)),
            "fc1_w": rnd((L, D, cfg.ffn)), "fc1_b": jnp.zeros((L, cfg.ffn)),
            "fc2_w": rnd((L, cfg.ffn, D)), "fc2_b": jnp.zeros((L, D)),
        },
        "proj1_w": rnd((D, cfg.llm_dim)), "proj1_b": jnp.zeros((cfg.llm_dim,)),
        "proj2_w": rnd((cfg.llm_dim, cfg.llm_dim)), "proj2_b": jnp.zeros((cfg.llm_dim,)),
    }


def _ln(x, w, b, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + eps) * w + b


def encode_image(cfg: VisionConfig, params: Params, pixels: jnp.ndarray) -> jnp.ndarray:
    """pixels [B, H, W, 3] in [0, 1] → projected patch features
    [B, n_patches, llm_dim] (CLS dropped, llava default)."""
    B = pixels.shape[0]
    x = (pixels.astype(jnp.float32) - 0.5) / 0.5  # CLIP-style normalize
    x = x.transpose(0, 3, 1, 2)  # NCHW
    patches = jax.lax.conv_general_dilated(
        x, params["patch_w"], (cfg.patch, cfg.patch), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [B, D, H/p, W/p]
    h = patches.reshape(B, cfg.d_model, -1).transpose(0, 2, 1)  # [B, N, D]
    cls = jnp.broadcast_to(params["cls"][None, None], (B, 1, cfg.d_model))
    h = jnp.concatenate([cls, h], axis=1) + params["pos"][None]
    h = _ln(h, params["pre_ln_w"], params["pre_ln_b"], cfg.layer_norm_eps)

    H, Dh = cfg.n_heads, cfg.head_dim
    T = h.shape[1]

    def layer(h, lp):
        x = _ln(h, lp["ln1_w"], lp["ln1_b"], cfg.layer_norm_eps)
        q = (x @ lp["q_w"] + lp["q_b"]).reshape(B, T, H, Dh)
        k = (x @ lp["k_w"] + lp["k_b"]).reshape(B, T, H, Dh)
        v = (x @ lp["v_w"] + lp["v_b"]).reshape(B, T, H, Dh)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * Dh**-0.5
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, T, cfg.d_model)
        h = h + attn @ lp["o_w"] + lp["o_b"]
        x = _ln(h, lp["ln2_w"], lp["ln2_b"], cfg.layer_norm_eps)
        h = h + jax.nn.gelu(x @ lp["fc1_w"] + lp["fc1_b"], approximate=False) @ lp["fc2_w"] + lp["fc2_b"]
        return h, h

    _, per_layer = jax.lax.scan(layer, h, params["layers"])  # [L, B, T, D]
    feats = per_layer[cfg.select_layer]  # llava select_layer (-2 default)
    feats = feats[:, 1:]  # drop CLS
    proj = jax.nn.gelu(feats @ params["proj1_w"] + params["proj1_b"], approximate=False)
    return proj @ params["proj2_w"] + params["proj2_b"]  # [B, N, llm_dim]


class VisionEncoder:
    """Host-side wrapper: uint8 image → projected features, jit-cached."""

    def __init__(self, cfg: VisionConfig, params: Params):
        self.cfg = cfg
        self.params = params
        self._fn = jax.jit(lambda p, x: encode_image(cfg, p, x))

    @property
    def n_tokens(self) -> int:
        return self.cfg.n_patches

    def encode(self, image: np.ndarray) -> np.ndarray:
        """uint8 [H, W, 3] (any size) → float32 [n_patches, llm_dim]."""
        from PIL import Image

        s = self.cfg.image_size
        if image.shape[:2] != (s, s):
            image = np.asarray(Image.fromarray(image).resize((s, s), Image.BILINEAR))
        x = image.astype(np.float32)[None] / 255.0
        return np.asarray(self._fn(self.params, jnp.asarray(x)))[0]


# --------------------------------------------------------------------------- #
# HF checkpoint I/O (LlavaForConditionalGeneration names)
# --------------------------------------------------------------------------- #

_VT = "vision_tower.vision_model"

_LAYER_MAP = {
    "ln1_w": ("layer_norm1.weight", False), "ln1_b": ("layer_norm1.bias", False),
    "q_w": ("self_attn.q_proj.weight", True), "q_b": ("self_attn.q_proj.bias", False),
    "k_w": ("self_attn.k_proj.weight", True), "k_b": ("self_attn.k_proj.bias", False),
    "v_w": ("self_attn.v_proj.weight", True), "v_b": ("self_attn.v_proj.bias", False),
    "o_w": ("self_attn.out_proj.weight", True), "o_b": ("self_attn.out_proj.bias", False),
    "ln2_w": ("layer_norm2.weight", False), "ln2_b": ("layer_norm2.bias", False),
    "fc1_w": ("mlp.fc1.weight", True), "fc1_b": ("mlp.fc1.bias", False),
    "fc2_w": ("mlp.fc2.weight", True), "fc2_b": ("mlp.fc2.bias", False),
}


def load_hf_vision(cfg: VisionConfig, ckpt_dir: str) -> Params:
    from localai_tpu.engine.weights import _ShardReader

    reader = _ShardReader(ckpt_dir)

    def grab(name: str, transpose: bool = False) -> jnp.ndarray:
        arr = reader.get(name)
        if transpose and arr.ndim == 2:
            arr = arr.T
        return jnp.asarray(np.ascontiguousarray(arr))

    layers: Params = {}
    for our, (suffix, tr) in _LAYER_MAP.items():
        rows = [
            grab(f"{_VT}.encoder.layers.{i}.{suffix}", tr) for i in range(cfg.layers)
        ]
        layers[our] = jnp.stack(rows)
    return {
        "patch_w": grab(f"{_VT}.embeddings.patch_embedding.weight"),
        "cls": grab(f"{_VT}.embeddings.class_embedding").reshape(-1),
        "pos": grab(f"{_VT}.embeddings.position_embedding.weight"),
        "pre_ln_w": grab(f"{_VT}.pre_layrnorm.weight"),
        "pre_ln_b": grab(f"{_VT}.pre_layrnorm.bias"),
        "layers": layers,
        "proj1_w": grab("multi_modal_projector.linear_1.weight", True),
        "proj1_b": grab("multi_modal_projector.linear_1.bias"),
        "proj2_w": grab("multi_modal_projector.linear_2.weight", True),
        "proj2_b": grab("multi_modal_projector.linear_2.bias"),
    }


def save_hf_vision(cfg: VisionConfig, params: Params, ckpt_dir: str) -> None:
    """Inverse of load_hf_vision (fixture fabrication for tests); merges into
    an existing safetensors file when one is present."""
    from safetensors.numpy import save_file

    os.makedirs(ckpt_dir, exist_ok=True)
    tensors: dict[str, np.ndarray] = {}
    path = os.path.join(ckpt_dir, "model.safetensors")
    if os.path.exists(path):
        from safetensors import safe_open

        with safe_open(path, framework="numpy") as f:
            for name in f.keys():
                tensors[name] = f.get_tensor(name)

    def emit(name, arr, transpose=False):
        a = np.asarray(jnp.asarray(arr, jnp.float32))
        if transpose and a.ndim == 2:
            a = a.T
        tensors[name] = np.ascontiguousarray(a)

    emit(f"{_VT}.embeddings.patch_embedding.weight", params["patch_w"])
    emit(f"{_VT}.embeddings.class_embedding", params["cls"])
    emit(f"{_VT}.embeddings.position_embedding.weight", params["pos"])
    emit(f"{_VT}.pre_layrnorm.weight", params["pre_ln_w"])
    emit(f"{_VT}.pre_layrnorm.bias", params["pre_ln_b"])
    for our, (suffix, tr) in _LAYER_MAP.items():
        for i in range(cfg.layers):
            emit(f"{_VT}.encoder.layers.{i}.{suffix}", params["layers"][our][i], tr)
    emit("multi_modal_projector.linear_1.weight", params["proj1_w"], True)
    emit("multi_modal_projector.linear_1.bias", params["proj1_b"])
    emit("multi_modal_projector.linear_2.weight", params["proj2_w"], True)
    emit("multi_modal_projector.linear_2.bias", params["proj2_b"])
    save_file(tensors, path)
    vjson = os.path.join(ckpt_dir, "vision_config.json")
    with open(vjson, "w") as f:
        json.dump(dataclasses.asdict(cfg), f, indent=1)


def vision_config_from_hf(ckpt_dir: str) -> VisionConfig:
    """From our sidecar vision_config.json or an HF llava config.json."""
    side = os.path.join(ckpt_dir, "vision_config.json")
    if os.path.exists(side):
        with open(side) as f:
            return VisionConfig(**json.load(f))
    with open(os.path.join(ckpt_dir, "config.json")) as f:
        hf = json.load(f)
    vc = hf.get("vision_config") or {}
    return VisionConfig(
        name=vc.get("model_type", "clip-vit"),
        image_size=vc.get("image_size", 336),
        patch=vc.get("patch_size", 14),
        d_model=vc.get("hidden_size", 1024),
        layers=vc.get("num_hidden_layers", 24),
        n_heads=vc.get("num_attention_heads", 16),
        ffn=vc.get("intermediate_size", 4096),
        llm_dim=(hf.get("text_config") or {}).get("hidden_size", 4096),
        select_layer=hf.get("vision_feature_layer", -2),
    )
