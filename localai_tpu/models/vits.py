"""VITS text-to-speech in JAX: loads published HF checkpoints
(facebook/mms-tts-*, kakao-enterprise/vits-ljs) and synthesizes waveforms.

Reference parity: the reference ships 7 TTS backend families — piper
(/root/reference/backend/go/piper/piper.go), bark
(backend/go/bark-cpp/gobark.cpp) and the python TTS family; piper voices are
themselves VITS models exported to ONNX. Here VITS runs natively on TPU:
one jitted program covers text encoder → stochastic duration predictor
(reverse flow with rational-quadratic splines) → alignment expansion →
residual-coupling flow (reverse) → HiFi-GAN decoder.

The architecture follows the published VITS model (Kim et al. 2021) in the
HF `VitsModel` weight layout so real checkpoints load directly; the code is
an original JAX implementation (convolutions run through
`lax.conv_general_dilated` in NCT layout, flows are scan-free unrolled loops
— layer counts are static per checkpoint).

Determinism: pass noise_scale=0 and noise_scale_duration=0 for reproducible
output (also how the parity test pins JAX against the torch reference).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class VitsConfig:
    vocab_size: int = 38
    hidden_size: int = 192
    num_hidden_layers: int = 6
    num_attention_heads: int = 2
    window_size: int = 4
    ffn_dim: int = 768
    ffn_kernel_size: int = 3
    flow_size: int = 192
    prior_encoder_num_flows: int = 4
    prior_encoder_num_wavenet_layers: int = 4
    wavenet_kernel_size: int = 5
    wavenet_dilation_rate: int = 1
    use_stochastic_duration_prediction: bool = True
    duration_predictor_num_flows: int = 4
    duration_predictor_flow_bins: int = 10
    duration_predictor_tail_bound: float = 5.0
    duration_predictor_kernel_size: int = 3
    duration_predictor_filter_channels: int = 256
    depth_separable_channels: int = 2
    depth_separable_num_layers: int = 3
    upsample_initial_channel: int = 512
    upsample_rates: tuple = (8, 8, 2, 2)
    upsample_kernel_sizes: tuple = (16, 16, 4, 4)
    resblock_kernel_sizes: tuple = (3, 7, 11)
    resblock_dilation_sizes: tuple = ((1, 3, 5), (1, 3, 5), (1, 3, 5))
    leaky_relu_slope: float = 0.1
    sampling_rate: int = 16000
    speaker_embedding_size: int = 0
    num_speakers: int = 1
    noise_scale: float = 0.667
    noise_scale_duration: float = 0.8
    speaking_rate: float = 1.0
    layer_norm_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def sample_rate(self) -> int:  # engine-facing alias (TTSConfig parity)
        return self.sampling_rate


def config_from_hf(ckpt_dir: str) -> VitsConfig:
    with open(os.path.join(ckpt_dir, "config.json")) as f:
        d = json.load(f)
    fields = {f.name for f in dataclasses.fields(VitsConfig)}
    kw = {k: v for k, v in d.items() if k in fields}
    for k in ("upsample_rates", "upsample_kernel_sizes", "resblock_kernel_sizes"):
        if k in kw:
            kw[k] = tuple(kw[k])
    if "resblock_dilation_sizes" in kw:
        kw["resblock_dilation_sizes"] = tuple(tuple(x) for x in kw["resblock_dilation_sizes"])
    return VitsConfig(**kw)


# --------------------------------------------------------------------------- #
# Weight loading (HF VitsModel layout; weight-norm resolved at load)
# --------------------------------------------------------------------------- #


def load_vits_params(ckpt_dir: str) -> Params:
    """Flat {hf_name: f32 array} tree with weight-norm parametrizations
    (original0 = g, original1 = v → w = g·v/‖v‖) materialized."""
    from safetensors import safe_open

    path = os.path.join(ckpt_dir, "model.safetensors")
    raw: dict[str, np.ndarray] = {}
    with safe_open(path, framework="numpy") as f:
        for name in f.keys():
            raw[name] = np.asarray(f.get_tensor(name), np.float32)
    out: dict[str, np.ndarray] = {}
    for name, arr in raw.items():
        if name.endswith("parametrizations.weight.original0"):
            base = name[: -len(".parametrizations.weight.original0")]
            g = arr
            v = raw[base + ".parametrizations.weight.original1"]
            norm = np.sqrt((v**2).sum(axis=tuple(range(1, v.ndim)), keepdims=True))
            out[base + ".weight"] = g * v / np.maximum(norm, 1e-12)
        elif name.endswith("parametrizations.weight.original1"):
            continue
        elif name.endswith("weight_g"):  # legacy weight-norm naming
            base = name[: -len(".weight_g")]
            g, v = arr, raw[base + ".weight_v"]
            norm = np.sqrt((v**2).sum(axis=tuple(range(1, v.ndim)), keepdims=True))
            out[base + ".weight"] = g * v / np.maximum(norm, 1e-12)
        elif name.endswith("weight_v"):
            continue
        else:
            out[name] = arr
    return {k: jnp.asarray(v) for k, v in out.items()}


def is_vits_dir(ckpt_dir: str) -> bool:
    cfg_path = os.path.join(ckpt_dir, "config.json")
    if not os.path.isfile(cfg_path):
        return False
    try:
        with open(cfg_path) as f:
            return json.load(f).get("model_type") == "vits"
    except (OSError, json.JSONDecodeError):
        return False


# --------------------------------------------------------------------------- #
# Character tokenizer (HF VitsTokenizer semantics: lowercase, vocab filter,
# blank/pad interleave)
# --------------------------------------------------------------------------- #


class VitsTokenizer:
    def __init__(self, ckpt_dir: str):
        with open(os.path.join(ckpt_dir, "vocab.json")) as f:
            self.vocab: dict[str, int] = json.load(f)
        tc = {}
        tc_path = os.path.join(ckpt_dir, "tokenizer_config.json")
        if os.path.isfile(tc_path):
            with open(tc_path) as f:
                tc = json.load(f)
        self.add_blank = bool(tc.get("add_blank", True))
        self.normalize = bool(tc.get("normalize", True))
        self.pad_id = 0  # HF VitsTokenizer interleaves literal id 0

    def encode(self, text: str) -> list[int]:
        if self.normalize:
            text = text.lower()
        chars = [c for c in text if c in self.vocab]
        if not chars:
            chars = [c for c in self.vocab if c.strip()][:1] or list(self.vocab)[:1]
        ids = [self.vocab[c] for c in chars]
        if self.add_blank:
            # pad-token interleave: [pad, c1, pad, c2, ..., pad]
            inter = [self.pad_id] * (len(ids) * 2 + 1)
            inter[1::2] = ids
            ids = inter
        return ids


# --------------------------------------------------------------------------- #
# Primitive ops (NCT layout throughout, matching conv-weight [out, in, k])
# --------------------------------------------------------------------------- #

_DN = ("NCH", "OIH", "NCH")


def _conv1d(x, w, b=None, dilation: int = 1, groups: int = 1, padding: int | None = None):
    """x [B, C, T], w [out, in/groups, k]; torch Conv1d 'same-style' padding
    (k·d − d)//2 unless given."""
    k = w.shape[-1]
    pad = ((k - 1) * dilation) // 2 if padding is None else padding
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=(1,), padding=[(pad, pad)],
        rhs_dilation=(dilation,), dimension_numbers=_DN,
        feature_group_count=groups,
    )
    if b is not None:
        y = y + b[None, :, None]
    return y


def _conv_transpose1d(x, w, b, stride: int, padding: int):
    """torch ConvTranspose1d(stride, padding): w [in, out, k] →
    dilated conv with flipped kernel and pad k−1−p."""
    k = w.shape[-1]
    wt = jnp.flip(w, -1).transpose(1, 0, 2)  # [out, in, k]
    y = jax.lax.conv_general_dilated(
        x, wt, window_strides=(1,), padding=[(k - 1 - padding,) * 2],
        lhs_dilation=(stride,), dimension_numbers=_DN,
    )
    return y + b[None, :, None] if b is not None else y


def _layer_norm_c(x, w, b, eps):
    """LayerNorm over the channel axis of [B, C, T] (torch norms transposed)."""
    mu = x.mean(axis=1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w[None, :, None] + b[None, :, None]


def _gated(x):
    """WaveNet gate: tanh(first half) * sigmoid(second half) over channels."""
    C = x.shape[1] // 2
    return jnp.tanh(x[:, :C]) * jax.nn.sigmoid(x[:, C:])


# --------------------------------------------------------------------------- #
# Text encoder with windowed relative-position attention
# --------------------------------------------------------------------------- #


def _rel_to_abs(x):
    """[BH, T, 2T-1] relative logits → [BH, T, T] absolute (pad-reshape trick)."""
    bh, t, _ = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (0, 1)))
    x = x.reshape(bh, t * 2 * t)
    x = jnp.pad(x, ((0, 0), (0, t - 1)))
    x = x.reshape(bh, t + 1, 2 * t - 1)
    return x[:, :t, t - 1:]


def _abs_to_rel(x):
    """[BH, T, T] attention probs → [BH, T, 2T-1] relative layout."""
    bh, t, _ = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (0, t - 1)))
    x = x.reshape(bh, t * (2 * t - 1))
    x = jnp.pad(x, ((0, 0), (t, 0)))
    return x.reshape(bh, t, 2 * t)[:, :, 1:]


def _rel_embeddings(emb, t: int, window: int):
    """Slice/pad the [1, 2w+1, D] table to the [1, 2t-1, D] band for length t."""
    pad = max(t - (window + 1), 0)
    if pad > 0:
        emb = jnp.pad(emb, ((0, 0), (pad, pad), (0, 0)))
    start = max((window + 1) - t, 0)
    return emb[:, start: start + 2 * t - 1]


def _attention(cfg: VitsConfig, p: Params, pre: str, x, tmask=None):
    """x [B, T, C] → [B, T, C]. tmask [B, T] (1 = valid token) masks padded
    keys so a length-bucketed sequence attends identically to an exact-length
    one; None means full-valid (B=1 synthesis)."""
    B, T, C = x.shape
    H, D = cfg.num_attention_heads, cfg.head_dim
    scale = D**-0.5

    def proj(name):
        w, b = p[f"{pre}.{name}.weight"], p.get(f"{pre}.{name}.bias")
        y = x @ w.T
        return y + b if b is not None else y

    q = (proj("q_proj") * scale).reshape(B, T, H, D).transpose(0, 2, 1, 3).reshape(B * H, T, D)
    k = proj("k_proj").reshape(B, T, H, D).transpose(0, 2, 1, 3).reshape(B * H, T, D)
    v = proj("v_proj").reshape(B, T, H, D).transpose(0, 2, 1, 3).reshape(B * H, T, D)

    scores = q @ k.transpose(0, 2, 1)  # [BH, T, T]
    if cfg.window_size:
        rel_k = _rel_embeddings(p[f"{pre}.emb_rel_k"], T, cfg.window_size)  # [1, 2T-1, D]
        rel_logits = jnp.einsum("btd,osd->bts", q, rel_k)
        scores = scores + _rel_to_abs(rel_logits)
    if tmask is not None:
        km = jnp.broadcast_to(tmask[:, None, None, :], (B, H, 1, T))
        scores = scores + (1.0 - km.reshape(B * H, 1, T)) * -1e9
    probs = jax.nn.softmax(scores, axis=-1)
    out = probs @ v
    if cfg.window_size:
        rel_v = _rel_embeddings(p[f"{pre}.emb_rel_v"], T, cfg.window_size)
        out = out + jnp.einsum("bts,osd->btd", _abs_to_rel(probs), rel_v)
    out = out.reshape(B, H, T, D).transpose(0, 2, 1, 3).reshape(B, T, C)
    return out @ p[f"{pre}.out_proj.weight"].T + p[f"{pre}.out_proj.bias"]


def _layer_norm_t(x, w, b, eps):
    """LayerNorm over the last axis of [B, T, C]."""
    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


def text_encoder(cfg: VitsConfig, p: Params, ids, tmask=None):
    """ids [B, T] → (hidden [B, C, T], prior means [B, T, F], prior logvars).

    tmask [B, T] (1 = valid) makes padded positions inert: keys are masked in
    attention and every time-mixing conv sees zeros at pads — the same
    points HF's VitsModel applies `padding_mask`, so a bucketed sequence's
    valid positions match the exact-length result."""
    h = p["text_encoder.embed_tokens.weight"][ids] * math.sqrt(cfg.hidden_size)  # [B, T, C]
    mt = None if tmask is None else tmask[..., None].astype(h.dtype)  # [B, T, 1]
    pl, pr = (cfg.ffn_kernel_size - 1) // 2, cfg.ffn_kernel_size // 2
    for i in range(cfg.num_hidden_layers):
        pre = f"text_encoder.encoder.layers.{i}"
        h = _layer_norm_t(
            h + _attention(cfg, p, f"{pre}.attention", h, tmask),
            p[f"{pre}.layer_norm.weight"], p[f"{pre}.layer_norm.bias"],
            cfg.layer_norm_eps,
        )
        # Conv feed-forward runs in NCT with asymmetric torch-style padding.
        y = (h * mt if mt is not None else h).transpose(0, 2, 1)
        if cfg.ffn_kernel_size > 1:
            y = jnp.pad(y, ((0, 0), (0, 0), (pl, pr)))
        y = _conv1d(y, p[f"{pre}.feed_forward.conv_1.weight"],
                    p[f"{pre}.feed_forward.conv_1.bias"], padding=0)
        y = jax.nn.relu(y)
        if mt is not None:
            y = y * mt.transpose(0, 2, 1)
        if cfg.ffn_kernel_size > 1:
            y = jnp.pad(y, ((0, 0), (0, 0), (pl, pr)))
        y = _conv1d(y, p[f"{pre}.feed_forward.conv_2.weight"],
                    p[f"{pre}.feed_forward.conv_2.bias"], padding=0)
        h = _layer_norm_t(
            h + y.transpose(0, 2, 1),
            p[f"{pre}.final_layer_norm.weight"], p[f"{pre}.final_layer_norm.bias"],
            cfg.layer_norm_eps,
        )
    if mt is not None:
        h = h * mt
    hc = h.transpose(0, 2, 1)  # [B, C, T]
    stats = _conv1d(hc, p["text_encoder.project.weight"], p["text_encoder.project.bias"], padding=0)
    m_p = stats[:, : cfg.flow_size].transpose(0, 2, 1)  # [B, T, F]
    logs_p = stats[:, cfg.flow_size:].transpose(0, 2, 1)
    return hc, m_p, logs_p


# --------------------------------------------------------------------------- #
# WaveNet + residual-coupling flow (reverse)
# --------------------------------------------------------------------------- #


def _wavenet(cfg: VitsConfig, p: Params, pre: str, x, num_layers: int, mask=None):
    """mask [B, 1, T] zeroes frames past the valid length after every residual
    update — the static-shape equivalent of torch's exact-length tensors
    (conv at the boundary must see zeros, as implicit padding would be)."""
    C = cfg.hidden_size
    out = jnp.zeros_like(x)
    for i in range(num_layers):
        dil = cfg.wavenet_dilation_rate**i
        h = _conv1d(x, p[f"{pre}.in_layers.{i}.weight"], p[f"{pre}.in_layers.{i}.bias"],
                    dilation=dil)
        acts = _gated(h)
        rs = _conv1d(acts, p[f"{pre}.res_skip_layers.{i}.weight"],
                     p[f"{pre}.res_skip_layers.{i}.bias"], padding=0)
        if i < num_layers - 1:
            x = x + rs[:, :C]
            if mask is not None:
                x = x * mask
            out = out + rs[:, C:]
        else:
            out = out + rs
    return out * mask if mask is not None else out


def _flow_reverse(cfg: VitsConfig, p: Params, z, mask):
    """Residual-coupling block in reverse: z [B, F, T] → latents for HiFi-GAN.
    mask [B, 1, T] marks valid output frames."""
    half = cfg.flow_size // 2
    for i in reversed(range(cfg.prior_encoder_num_flows)):
        z = jnp.flip(z, axis=1)
        pre = f"flow.flows.{i}"
        z0, z1 = z[:, :half], z[:, half:]
        h = _conv1d(z0, p[f"{pre}.conv_pre.weight"], p[f"{pre}.conv_pre.bias"], padding=0) * mask
        h = _wavenet(cfg, p, f"{pre}.wavenet", h, cfg.prior_encoder_num_wavenet_layers, mask)
        m = _conv1d(h, p[f"{pre}.conv_post.weight"], p[f"{pre}.conv_post.bias"], padding=0) * mask
        z = jnp.concatenate([z0, (z1 - m) * mask], axis=1)
    return z


# --------------------------------------------------------------------------- #
# Stochastic duration predictor (reverse) with rational-quadratic splines
# --------------------------------------------------------------------------- #


def _dds(cfg: VitsConfig, p: Params, pre: str, x, cond=None, mask=None):
    """Dilated depth-separable conv stack; cond added at entry (HF DDS).
    mask [B, 1, T] zeroes pads before each dilated conv (HF padding_mask)."""
    if cond is not None:
        x = x + cond
    k = cfg.duration_predictor_kernel_size
    for i in range(cfg.depth_separable_num_layers):
        dil = k**i
        xin = x * mask if mask is not None else x
        h = _conv1d(xin, p[f"{pre}.convs_dilated.{i}.weight"], p[f"{pre}.convs_dilated.{i}.bias"],
                    dilation=dil, groups=x.shape[1])
        h = _layer_norm_c(h, p[f"{pre}.norms_1.{i}.weight"], p[f"{pre}.norms_1.{i}.bias"],
                          cfg.layer_norm_eps)
        h = jax.nn.gelu(h, approximate=False)
        h = _conv1d(h, p[f"{pre}.convs_pointwise.{i}.weight"], p[f"{pre}.convs_pointwise.{i}.bias"],
                    padding=0)
        h = _layer_norm_c(h, p[f"{pre}.norms_2.{i}.weight"], p[f"{pre}.norms_2.{i}.bias"],
                          cfg.layer_norm_eps)
        h = jax.nn.gelu(h, approximate=False)
        x = x + h
    return x


def _rq_spline_reverse(cfg: VitsConfig, inputs, uw, uh, ud):
    """Unconstrained rational-quadratic spline, reverse pass (Durkan et al.
    neural spline flows; VITS duration flow). inputs [...]; uw/uh/ud
    [..., bins(/bins+1)]. Identity outside ±tail_bound."""
    tb = cfg.duration_predictor_tail_bound
    nb = cfg.duration_predictor_flow_bins
    min_w = min_h = min_d = 1e-3
    inside = (inputs >= -tb) & (inputs <= tb)
    x = jnp.clip(inputs, -tb, tb)

    constant = math.log(math.exp(1 - min_d) - 1)
    ud = jnp.pad(ud, [(0, 0)] * (ud.ndim - 1) + [(1, 1)], constant_values=constant)

    widths = jax.nn.softmax(uw, axis=-1)
    widths = min_w + (1 - min_w * nb) * widths
    cumw = jnp.cumsum(widths, axis=-1)
    cumw = jnp.pad(cumw, [(0, 0)] * (cumw.ndim - 1) + [(1, 0)])
    cumw = 2 * tb * cumw - tb
    cumw = cumw.at[..., 0].set(-tb).at[..., -1].set(tb)
    widths = cumw[..., 1:] - cumw[..., :-1]

    derivs = min_d + jax.nn.softplus(ud)

    heights = jax.nn.softmax(uh, axis=-1)
    heights = min_h + (1 - min_h * nb) * heights
    cumh = jnp.cumsum(heights, axis=-1)
    cumh = jnp.pad(cumh, [(0, 0)] * (cumh.ndim - 1) + [(1, 0)])
    cumh = 2 * tb * cumh - tb
    cumh = cumh.at[..., 0].set(-tb).at[..., -1].set(tb)
    heights = cumh[..., 1:] - cumh[..., :-1]

    locations = cumh.at[..., -1].add(1e-6)  # reverse pass buckets on heights
    idx = jnp.clip(jnp.sum((x[..., None] >= locations).astype(jnp.int32), axis=-1) - 1, 0, nb - 1)

    def take(t):
        return jnp.take_along_axis(t, idx[..., None], axis=-1)[..., 0]

    in_cumw, in_w = take(cumw[..., :-1]), take(widths)
    in_cumh, in_h = take(cumh[..., :-1]), take(heights)
    delta = take(heights / widths)
    d0, d1 = take(derivs[..., :-1]), take(derivs[..., 1:])

    t1 = d0 + d1 - 2 * delta
    y = x - in_cumh
    t3 = y * t1
    a = in_h * (delta - d0) + t3
    b = in_h * d0 - t3
    c = -delta * y
    disc = b**2 - 4 * a * c
    root = (2 * c) / (-b - jnp.sqrt(jnp.maximum(disc, 0.0)))
    out = root * in_w + in_cumw
    return jnp.where(inside, out, inputs)


def _conv_flow_reverse(cfg: VitsConfig, p: Params, pre: str, z, cond, mask=None):
    """VITS ConvFlow reverse: spline-transform the second half given the first."""
    half = cfg.depth_separable_channels // 2
    z0, z1 = z[:, :half], z[:, half:]
    h = _conv1d(z0, p[f"{pre}.conv_pre.weight"], p[f"{pre}.conv_pre.bias"], padding=0)
    h = _dds(cfg, p, f"{pre}.conv_dds", h, cond=cond, mask=mask)
    h = _conv1d(h, p[f"{pre}.conv_proj.weight"], p[f"{pre}.conv_proj.bias"], padding=0)
    B, _, T = z0.shape
    nb = cfg.duration_predictor_flow_bins
    h = h.reshape(B, half, 3 * nb - 1, T).transpose(0, 1, 3, 2)  # [B, half, T, 3nb-1]
    s = math.sqrt(cfg.hidden_size)
    z1 = _rq_spline_reverse(cfg, z1, h[..., :nb] / s, h[..., nb: 2 * nb] / s, h[..., 2 * nb:])
    return jnp.concatenate([z0, z1], axis=1)


def _sdp_log_duration(cfg: VitsConfig, p: Params, hidden, noise, tmask=None):
    """Stochastic duration predictor, reverse. hidden [B, C, T];
    noise [B, 2, T] (pre-scaled). tmask [B, T] marks valid tokens.
    Returns log durations [B, 1, T]."""
    mc = None if tmask is None else tmask[:, None, :].astype(hidden.dtype)
    x = _conv1d(hidden, p["duration_predictor.conv_pre.weight"],
                p["duration_predictor.conv_pre.bias"], padding=0)
    x = _dds(cfg, p, "duration_predictor.conv_dds", x, mask=mc)
    x = _conv1d(x, p["duration_predictor.conv_proj.weight"],
                p["duration_predictor.conv_proj.bias"], padding=0)
    if mc is not None:
        x = x * mc

    # Reverse flow order: [convN, ..., conv2, affine] — conv1 ("useless
    # vflow") is skipped, matching VITS inference.
    z = noise
    order = list(range(2, cfg.duration_predictor_num_flows + 1))[::-1]
    for i in order:
        z = jnp.flip(z, axis=1)
        z = _conv_flow_reverse(cfg, p, f"duration_predictor.flows.{i}", z, x, mask=mc)
    z = jnp.flip(z, axis=1)
    tr = p["duration_predictor.flows.0.translate"][None]  # [1, 2, 1]
    ls = p["duration_predictor.flows.0.log_scale"][None]
    z = (z - tr) * jnp.exp(-ls)
    return z[:, :1]


def _dp_log_duration(cfg: VitsConfig, p: Params, hidden, tmask=None):
    """Deterministic duration predictor (use_stochastic=False checkpoints)."""
    mc = None if tmask is None else tmask[:, None, :].astype(hidden.dtype)
    k = cfg.duration_predictor_kernel_size
    x = _conv1d(hidden, p["duration_predictor.conv_1.weight"],
                p["duration_predictor.conv_1.bias"], padding=k // 2)
    x = _layer_norm_c(jax.nn.relu(x), p["duration_predictor.norm_1.weight"],
                      p["duration_predictor.norm_1.bias"], cfg.layer_norm_eps)
    if mc is not None:
        x = x * mc
    x = _conv1d(x, p["duration_predictor.conv_2.weight"],
                p["duration_predictor.conv_2.bias"], padding=k // 2)
    x = _layer_norm_c(jax.nn.relu(x), p["duration_predictor.norm_2.weight"],
                      p["duration_predictor.norm_2.bias"], cfg.layer_norm_eps)
    return _conv1d(x, p["duration_predictor.proj.weight"],
                   p["duration_predictor.proj.bias"], padding=0)


# --------------------------------------------------------------------------- #
# HiFi-GAN decoder
# --------------------------------------------------------------------------- #


def hifigan(cfg: VitsConfig, p: Params, spec, mask=None):
    """spec [B, F, T] → waveform [B, T·prod(rates)]. mask [B, 1, T] marks
    valid frames; re-applied (suitably upsampled) after every conv so the
    padded static tail never bleeds into valid samples."""
    x = _conv1d(spec, p["decoder.conv_pre.weight"], p["decoder.conv_pre.bias"], padding=3)
    if mask is not None:
        x = x * mask
    nk = len(cfg.resblock_kernel_sizes)
    slope = cfg.leaky_relu_slope
    for i, (rate, ks) in enumerate(zip(cfg.upsample_rates, cfg.upsample_kernel_sizes)):
        x = jax.nn.leaky_relu(x, slope)
        x = _conv_transpose1d(x, p[f"decoder.upsampler.{i}.weight"],
                              p[f"decoder.upsampler.{i}.bias"], rate, (ks - rate) // 2)
        if mask is not None:
            mask = jnp.repeat(mask, rate, axis=-1)
            x = x * mask
        acc = None
        for j, (rk, dils) in enumerate(zip(cfg.resblock_kernel_sizes, cfg.resblock_dilation_sizes)):
            pre = f"decoder.resblocks.{i * nk + j}"
            y = x
            for di, d in enumerate(dils):
                r = y
                y = jax.nn.leaky_relu(y, slope)
                y = _conv1d(y, p[f"{pre}.convs1.{di}.weight"], p[f"{pre}.convs1.{di}.bias"],
                            dilation=d)
                if mask is not None:
                    y = y * mask
                y = jax.nn.leaky_relu(y, slope)
                y = _conv1d(y, p[f"{pre}.convs2.{di}.weight"], p[f"{pre}.convs2.{di}.bias"])
                if mask is not None:
                    y = y * mask
                y = y + r
            acc = y if acc is None else acc + y
        x = acc / nk
    x = jax.nn.leaky_relu(x)  # torch default slope 0.01 for the final act
    x = _conv1d(x, p["decoder.conv_post.weight"], None, padding=3)
    if mask is not None:
        x = x * mask
    return jnp.tanh(x)[:, 0]


# --------------------------------------------------------------------------- #
# End-to-end synthesis
# --------------------------------------------------------------------------- #


def synthesize(
    cfg: VitsConfig,
    p: Params,
    ids: jnp.ndarray,  # [B, T] int32 (full-valid; B=1 serving)
    frames: int,  # static output frame budget (spectrogram length)
    dur_noise: jnp.ndarray,  # [B, 2, T] ~ N(0,1)·noise_scale_duration
    prior_noise: jnp.ndarray,  # [B, frames, F] ~ N(0,1)·noise_scale
    speaking_rate: float = 1.0,
    n_tokens: jnp.ndarray | None = None,  # [B] valid token counts (T bucketed)
):
    """Returns (waveform [B, frames·prod(rates)], valid_samples [B]).

    The frame budget is static (jit-friendly); durations are computed on
    device and clamped into it. valid_samples tells the host how much of the
    waveform is real speech. With n_tokens, T may be a padded bucket: pads
    are masked throughout and get zero duration, so the program compiles
    once per (token bucket, frame budget) instead of once per text length.
    """
    tmask = None
    if n_tokens is not None:
        T = ids.shape[1]
        tmask = (jnp.arange(T)[None, :] < n_tokens[:, None]).astype(jnp.float32)
    hidden, m_p, logs_p = text_encoder(cfg, p, ids, tmask)
    if cfg.use_stochastic_duration_prediction:
        log_d = _sdp_log_duration(cfg, p, hidden, dur_noise, tmask)
    else:
        log_d = _dp_log_duration(cfg, p, hidden, tmask)
    dur = jnp.ceil(jnp.exp(log_d[:, 0]) / speaking_rate)  # [B, T]
    if tmask is not None:
        dur = dur * tmask  # pads span zero frames → alignment skips them
    cum = jnp.cumsum(dur, axis=-1)
    total = jnp.minimum(cum[:, -1], frames)  # [B]

    # Alignment: output frame f attends to the token whose cumulative span
    # covers f — one-hot gather instead of the reference's mask-subtraction.
    fidx = jnp.arange(frames)[None, :, None]  # [1, frames, 1]
    starts = jnp.pad(cum[:, :-1], ((0, 0), (1, 0)))[:, None, :]  # [B, 1, T]
    attn = ((fidx >= starts) & (fidx < cum[:, None, :])).astype(m_p.dtype)  # [B, frames, T]
    m_up = attn @ m_p  # [B, frames, F]
    logs_up = attn @ logs_p

    mask = (jnp.arange(frames)[None, :] < total[:, None]).astype(m_p.dtype)[:, None]  # [B, 1, frames]
    z_p = (m_up + prior_noise * jnp.exp(logs_up)).transpose(0, 2, 1) * mask  # [B, F, frames]
    z = _flow_reverse(cfg, p, z_p, mask)
    wav = hifigan(cfg, p, z * mask, mask)  # [B, frames·up]
    up = int(np.prod(cfg.upsample_rates))
    return wav, (total * up).astype(jnp.int32)


def load_vits(ckpt_dir: str):
    """(cfg, params, tokenizer) from an HF VITS checkpoint directory."""
    cfg = config_from_hf(ckpt_dir)
    params = load_vits_params(ckpt_dir)
    tok = VitsTokenizer(ckpt_dir)
    return cfg, params, tok
