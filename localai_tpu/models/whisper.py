"""Whisper-family speech-to-text as pure-functional JAX.

The reference serves STT through whisper.cpp (backend/go/whisper/gowhisper.cpp,
RPC AudioTranscription in backend/backend.proto) running GGML CPU/CUDA
kernels. This is a TPU redesign, not a port:

- Encoder (conv1d ×2 → sinusoidal pos → pre-LN transformer) and decoder
  (learned pos, causal self-attn + cross-attn) are stacked-layer pytrees
  scanned with `lax.scan` — one traced block per stack, flat compile time.
- Transcription is ONE jitted program: mel → encoder → cross-KV precompute →
  prompt scan → greedy token scan with an EOT done-mask. No host round-trips
  inside an utterance; batch is a leading axis throughout, so a TPU chip
  transcribes B utterances at once.
- Weights load from HF safetensors (WhisperForConditionalGeneration names),
  matching engine/weights.py conventions.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    name: str = "whisper"
    vocab_size: int = 51865
    d_model: int = 384  # whisper-tiny
    enc_layers: int = 4
    dec_layers: int = 4
    n_heads: int = 6
    n_mels: int = 80
    n_audio_ctx: int = 1500  # 30 s of 10 ms frames, conv-halved
    n_text_ctx: int = 448
    ffn_mult: int = 4
    # Special tokens (HF whisper defaults; tiny test preset overrides).
    sot_id: int = 50258  # <|startoftranscript|>
    eot_id: int = 50257  # <|endoftext|>
    no_timestamps_id: int = 50363
    transcribe_id: int = 50359
    translate_id: int = 50358
    first_lang_id: int = 50259  # <|en|>
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def ffn(self) -> int:
        return self.d_model * self.ffn_mult


WHISPER_PRESETS: dict[str, WhisperConfig] = {
    # Hermetic test/CI preset: one audio "second" is 100 frames → 50 ctx.
    "whisper-test": WhisperConfig(
        name="whisper-test", vocab_size=128, d_model=32, enc_layers=2,
        dec_layers=2, n_heads=2, n_mels=16, n_audio_ctx=64, n_text_ctx=32,
        sot_id=1, eot_id=2, no_timestamps_id=3, transcribe_id=4,
        translate_id=5, first_lang_id=6,
    ),
    "whisper-tiny": WhisperConfig(name="whisper-tiny"),
    "whisper-base": WhisperConfig(
        name="whisper-base", d_model=512, enc_layers=6, dec_layers=6, n_heads=8
    ),
    "whisper-small": WhisperConfig(
        name="whisper-small", d_model=768, enc_layers=12, dec_layers=12, n_heads=12
    ),
    "whisper-large-v3": WhisperConfig(
        name="whisper-large-v3", vocab_size=51866, d_model=1280, enc_layers=32,
        dec_layers=32, n_heads=20, n_mels=128,
    ),
}


def sinusoids(length: int, channels: int) -> np.ndarray:
    """Whisper's fixed audio positional embedding."""
    log_timescale = np.log(10000.0) / (channels // 2 - 1)
    inv = np.exp(-log_timescale * np.arange(channels // 2))
    scaled = np.arange(length)[:, None] * inv[None, :]
    return np.concatenate([np.sin(scaled), np.cos(scaled)], axis=1).astype(np.float32)


class SelfCache(NamedTuple):
    """Decoder self-attention KV cache [L, B, n_text_ctx, H, Dh]."""

    k: jnp.ndarray
    v: jnp.ndarray


def _dt(cfg: WhisperConfig):
    return jnp.dtype(cfg.dtype)


def _attn_block_params(rnd, L, d, ffn, cross: bool) -> Params:
    p = {
        "ln1_w": jnp.ones((L, d)), "ln1_b": jnp.zeros((L, d)),
        "q_w": rnd((L, d, d)), "q_b": jnp.zeros((L, d)),
        "k_w": rnd((L, d, d)),  # whisper: no k bias
        "v_w": rnd((L, d, d)), "v_b": jnp.zeros((L, d)),
        "o_w": rnd((L, d, d)), "o_b": jnp.zeros((L, d)),
        "ln2_w": jnp.ones((L, d)), "ln2_b": jnp.zeros((L, d)),
        "fc1_w": rnd((L, d, ffn)), "fc1_b": jnp.zeros((L, ffn)),
        "fc2_w": rnd((L, ffn, d)), "fc2_b": jnp.zeros((L, d)),
    }
    if cross:
        p.update({
            "lnx_w": jnp.ones((L, d)), "lnx_b": jnp.zeros((L, d)),
            "xq_w": rnd((L, d, d)), "xq_b": jnp.zeros((L, d)),
            "xk_w": rnd((L, d, d)),
            "xv_w": rnd((L, d, d)), "xv_b": jnp.zeros((L, d)),
            "xo_w": rnd((L, d, d)), "xo_b": jnp.zeros((L, d)),
        })
    return p


def init_params(cfg: WhisperConfig, key: jnp.ndarray, scale: float = 0.02) -> Params:
    d, M = cfg.d_model, cfg.n_mels
    keys = iter(jax.random.split(key, 64))

    def rnd(shape):
        return jax.random.normal(next(keys), shape, jnp.float32) * scale

    enc = _attn_block_params(rnd, cfg.enc_layers, d, cfg.ffn, cross=False)
    dec = _attn_block_params(rnd, cfg.dec_layers, d, cfg.ffn, cross=True)
    return {
        "conv1_w": rnd((d, M, 3)), "conv1_b": jnp.zeros((d,)),
        "conv2_w": rnd((d, d, 3)), "conv2_b": jnp.zeros((d,)),
        "enc_pos": jnp.asarray(sinusoids(cfg.n_audio_ctx, d)),
        "enc": enc,
        "enc_ln_w": jnp.ones((d,)), "enc_ln_b": jnp.zeros((d,)),
        "embed": rnd((cfg.vocab_size, d)),
        "dec_pos": rnd((cfg.n_text_ctx, d)),
        "dec": dec,
        "dec_ln_w": jnp.ones((d,)), "dec_ln_b": jnp.zeros((d,)),
    }


def _ln(x, w, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * w + b).astype(x.dtype)


def _heads(cfg: WhisperConfig, x: jnp.ndarray) -> jnp.ndarray:
    """[..., d] → [..., H, Dh]"""
    return x.reshape(*x.shape[:-1], cfg.n_heads, cfg.head_dim)


def _mha(cfg, q, k, v, mask=None):
    """q [B,Tq,H,Dh], k/v [B,Tk,H,Dh] → [B,Tq,d]. mask [Tq,Tk] or [B,Tq,Tk]."""
    scale = cfg.head_dim ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if mask is not None:
        m = mask if mask.ndim == 3 else mask[None]
        scores = jnp.where(m[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.reshape(*out.shape[:-2], cfg.d_model).astype(q.dtype)


def encode(cfg: WhisperConfig, params: Params, mel: jnp.ndarray) -> jnp.ndarray:
    """mel [B, T_frames, n_mels] → encoder states [B, T_frames//2, d].

    T_frames must be 2 * n_audio_ctx (whisper pads/trims audio to 30 s; the
    serving layer handles that).
    """
    x = mel.transpose(0, 2, 1)  # [B, M, T] for NCH conv
    dn = ("NCH", "OIH", "NCH")
    x = jax.lax.conv_general_dilated(
        x, params["conv1_w"], (1,), [(1, 1)], dimension_numbers=dn
    ) + params["conv1_b"][None, :, None]
    x = jax.nn.gelu(x, approximate=False)
    x = jax.lax.conv_general_dilated(
        x, params["conv2_w"], (2,), [(1, 1)], dimension_numbers=dn
    ) + params["conv2_b"][None, :, None]
    x = jax.nn.gelu(x, approximate=False)
    h = x.transpose(0, 2, 1)  # [B, T_a, d]
    h = h + params["enc_pos"][None, : h.shape[1]]

    def layer(h, lp):
        x = _ln(h, lp["ln1_w"], lp["ln1_b"])
        q = _heads(cfg, x @ lp["q_w"] + lp["q_b"])
        k = _heads(cfg, x @ lp["k_w"])
        v = _heads(cfg, x @ lp["v_w"] + lp["v_b"])
        h = h + _mha(cfg, q, k, v) @ lp["o_w"] + lp["o_b"]
        x = _ln(h, lp["ln2_w"], lp["ln2_b"])
        h = h + jax.nn.gelu(x @ lp["fc1_w"] + lp["fc1_b"], approximate=False) @ lp["fc2_w"] + lp["fc2_b"]
        return h, None

    h, _ = jax.lax.scan(layer, h, params["enc"])
    return _ln(h, params["enc_ln_w"], params["enc_ln_b"])


def cross_kv(cfg: WhisperConfig, params: Params, enc_out: jnp.ndarray):
    """Precompute per-layer cross-attention K/V: [L, B, T_a, H, Dh] each."""

    def layer(_, lp):
        k = _heads(cfg, enc_out @ lp["xk_w"])
        v = _heads(cfg, enc_out @ lp["xv_w"] + lp["xv_b"])
        return None, (k, v)

    _, (ks, vs) = jax.lax.scan(layer, None, params["dec"])
    return ks, vs


def decode_step(
    cfg: WhisperConfig,
    params: Params,
    tokens: jnp.ndarray,  # [B] int32
    pos: jnp.ndarray,  # [B] int32 position of `tokens`
    cache: SelfCache,
    xk: jnp.ndarray,  # [L, B, T_a, H, Dh]
    xv: jnp.ndarray,
):
    """One decoder step. Returns (logits [B, V] f32, new cache).

    Same HBM-traffic shape as models/llama.py decode_step: the layer scan
    never re-emits the cache — it attends over cache-prefix ⊕ current token
    and outputs only the new [B, H, Dh] row; one scatter updates all layers.
    """
    B = tokens.shape[0]
    h = params["embed"][tokens] + params["dec_pos"][pos]  # [B, d]
    batch_idx = jnp.arange(B)
    T = cache.k.shape[2]

    def layer(h, xs):
        lp, kc, vc, xk_l, xv_l = xs
        x = _ln(h, lp["ln1_w"], lp["ln1_b"])
        q = _heads(cfg, x @ lp["q_w"] + lp["q_b"])  # [B, H, Dh]
        k = _heads(cfg, x @ lp["k_w"])
        v = _heads(cfg, x @ lp["v_w"] + lp["v_b"])
        valid = jnp.arange(T)[None, :] < pos[:, None]  # strictly before `pos`
        scores = jnp.einsum(
            "bhd,bthd->bht", q.astype(jnp.float32), kc.astype(jnp.float32)
        ) * cfg.head_dim**-0.5
        scores = jnp.where(valid[:, None], scores, -1e30)
        cur = jnp.einsum(
            "bhd,bhd->bh", q.astype(jnp.float32), k.astype(jnp.float32)
        )[..., None] * cfg.head_dim**-0.5  # [B, H, 1]
        probs = jax.nn.softmax(jnp.concatenate([scores, cur], axis=-1), axis=-1)
        attn = jnp.einsum(
            "bht,bthd->bhd", probs[..., :T], vc.astype(jnp.float32)
        ) + probs[..., T:] * v.astype(jnp.float32)
        h = h + attn.reshape(B, cfg.d_model).astype(h.dtype) @ lp["o_w"] + lp["o_b"]

        x = _ln(h, lp["lnx_w"], lp["lnx_b"])
        xq = _heads(cfg, x @ lp["xq_w"] + lp["xq_b"])
        xscores = jnp.einsum(
            "bhd,bthd->bht", xq.astype(jnp.float32), xk_l.astype(jnp.float32)
        ) * cfg.head_dim**-0.5
        xprobs = jax.nn.softmax(xscores, axis=-1)
        xattn = jnp.einsum("bht,bthd->bhd", xprobs, xv_l.astype(jnp.float32))
        h = h + xattn.reshape(B, cfg.d_model).astype(h.dtype) @ lp["xo_w"] + lp["xo_b"]

        x = _ln(h, lp["ln2_w"], lp["ln2_b"])
        h = h + jax.nn.gelu(x @ lp["fc1_w"] + lp["fc1_b"], approximate=False) @ lp["fc2_w"] + lp["fc2_b"]
        return h, (k, v)

    h, (new_k, new_v) = jax.lax.scan(
        layer, h, (params["dec"], cache.k, cache.v, xk, xv)
    )
    ks = cache.k.at[:, batch_idx, pos].set(new_k)
    vs = cache.v.at[:, batch_idx, pos].set(new_v)
    h = _ln(h, params["dec_ln_w"], params["dec_ln_b"])
    logits = h.astype(jnp.float32) @ params["embed"].astype(jnp.float32).T
    return logits, SelfCache(k=ks, v=vs)


def transcribe_greedy(
    cfg: WhisperConfig,
    params: Params,
    mel: jnp.ndarray,  # [B, 2*n_audio_ctx, n_mels]
    prompt_ids: jnp.ndarray,  # [P] int32 (sot, lang, task, no_timestamps)
    max_tokens: int,
):
    """Whole-utterance greedy transcription in one jitted program.

    Returns (tokens [B, max_tokens] i32 — eot-padded, n_valid [B] i32).
    """
    B = mel.shape[0]
    enc_out = encode(cfg, params, mel)
    xk, xv = cross_kv(cfg, params, enc_out)
    cache = SelfCache(
        k=jnp.zeros((cfg.dec_layers, B, cfg.n_text_ctx, cfg.n_heads, cfg.head_dim), jnp.float32),
        v=jnp.zeros((cfg.dec_layers, B, cfg.n_text_ctx, cfg.n_heads, cfg.head_dim), jnp.float32),
    )
    P = prompt_ids.shape[0]

    def prompt_step(carry, i):
        cache, _ = carry
        tok = jnp.full((B,), prompt_ids[i], jnp.int32)
        pos = jnp.full((B,), i, jnp.int32)
        logits, cache = decode_step(cfg, params, tok, pos, cache, xk, xv)
        return (cache, logits), None

    (cache, logits), _ = jax.lax.scan(
        prompt_step, (cache, jnp.zeros((B, cfg.vocab_size), jnp.float32)), jnp.arange(P)
    )

    def gen_step(carry, i):
        cache, logits, done = carry
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        tok = jnp.where(done, cfg.eot_id, tok)
        done = done | (tok == cfg.eot_id)
        pos = jnp.full((B,), P + i, jnp.int32)
        pos = jnp.minimum(pos, cfg.n_text_ctx - 1)
        logits, cache = decode_step(cfg, params, tok, pos, cache, xk, xv)
        return (cache, logits, done), tok

    (_, _, done), toks = jax.lax.scan(
        gen_step, (cache, logits, jnp.zeros((B,), bool)), jnp.arange(max_tokens)
    )
    toks = toks.T  # [B, max_tokens]
    n_valid = jnp.sum((toks != cfg.eot_id).astype(jnp.int32), axis=-1)
    return toks, n_valid


# --------------------------------------------------------------------------- #
# HF checkpoint I/O (WhisperForConditionalGeneration names)
# --------------------------------------------------------------------------- #

_ENC_MAP = {
    "ln1_w": ("self_attn_layer_norm.weight", False),
    "ln1_b": ("self_attn_layer_norm.bias", False),
    "q_w": ("self_attn.q_proj.weight", True),
    "q_b": ("self_attn.q_proj.bias", False),
    "k_w": ("self_attn.k_proj.weight", True),
    "v_w": ("self_attn.v_proj.weight", True),
    "v_b": ("self_attn.v_proj.bias", False),
    "o_w": ("self_attn.out_proj.weight", True),
    "o_b": ("self_attn.out_proj.bias", False),
    "ln2_w": ("final_layer_norm.weight", False),
    "ln2_b": ("final_layer_norm.bias", False),
    "fc1_w": ("fc1.weight", True),
    "fc1_b": ("fc1.bias", False),
    "fc2_w": ("fc2.weight", True),
    "fc2_b": ("fc2.bias", False),
}

_DEC_EXTRA = {
    "lnx_w": ("encoder_attn_layer_norm.weight", False),
    "lnx_b": ("encoder_attn_layer_norm.bias", False),
    "xq_w": ("encoder_attn.q_proj.weight", True),
    "xq_b": ("encoder_attn.q_proj.bias", False),
    "xk_w": ("encoder_attn.k_proj.weight", True),
    "xv_w": ("encoder_attn.v_proj.weight", True),
    "xv_b": ("encoder_attn.v_proj.bias", False),
    "xo_w": ("encoder_attn.out_proj.weight", True),
    "xo_b": ("encoder_attn.out_proj.bias", False),
}


def _stack(reader, prefix: str, L: int, layer_map: dict) -> Params:
    out: Params = {}
    for our, (suffix, transpose) in layer_map.items():
        rows = []
        for i in range(L):
            arr = reader.get(f"{prefix}.{i}.{suffix}")
            if transpose and arr.ndim == 2:
                arr = arr.T
            rows.append(np.ascontiguousarray(arr))
        out[our] = jnp.asarray(np.stack(rows))
    return out


def load_hf_whisper(cfg: WhisperConfig, ckpt_dir: str) -> Params:
    from localai_tpu.engine.weights import _ShardReader

    reader = _ShardReader(ckpt_dir)

    def grab(name: str) -> jnp.ndarray:
        return jnp.asarray(reader.get(name))

    dec_map = dict(_ENC_MAP, **_DEC_EXTRA)
    return {
        "conv1_w": grab("model.encoder.conv1.weight"),
        "conv1_b": grab("model.encoder.conv1.bias"),
        "conv2_w": grab("model.encoder.conv2.weight"),
        "conv2_b": grab("model.encoder.conv2.bias"),
        "enc_pos": grab("model.encoder.embed_positions.weight"),
        "enc": _stack(reader, "model.encoder.layers", cfg.enc_layers, _ENC_MAP),
        "enc_ln_w": grab("model.encoder.layer_norm.weight"),
        "enc_ln_b": grab("model.encoder.layer_norm.bias"),
        "embed": grab("model.decoder.embed_tokens.weight"),
        "dec_pos": grab("model.decoder.embed_positions.weight"),
        "dec": _stack(reader, "model.decoder.layers", cfg.dec_layers, dec_map),
        "dec_ln_w": grab("model.decoder.layer_norm.weight"),
        "dec_ln_b": grab("model.decoder.layer_norm.bias"),
    }


def save_hf_whisper(cfg: WhisperConfig, params: Params, ckpt_dir: str) -> None:
    """Inverse of load_hf_whisper — lets tests fabricate real checkpoints."""
    os.makedirs(ckpt_dir, exist_ok=True)
    tensors: dict[str, np.ndarray] = {}

    def emit(name: str, arr, transpose=False) -> None:
        a = np.asarray(jnp.asarray(arr, jnp.float32))
        if transpose and a.ndim == 2:
            a = a.T
        tensors[name] = np.ascontiguousarray(a)

    emit("model.encoder.conv1.weight", params["conv1_w"])
    emit("model.encoder.conv1.bias", params["conv1_b"])
    emit("model.encoder.conv2.weight", params["conv2_w"])
    emit("model.encoder.conv2.bias", params["conv2_b"])
    emit("model.encoder.embed_positions.weight", params["enc_pos"])
    emit("model.encoder.layer_norm.weight", params["enc_ln_w"])
    emit("model.encoder.layer_norm.bias", params["enc_ln_b"])
    emit("model.decoder.embed_tokens.weight", params["embed"])
    emit("model.decoder.embed_positions.weight", params["dec_pos"])
    emit("model.decoder.layer_norm.weight", params["dec_ln_w"])
    emit("model.decoder.layer_norm.bias", params["dec_ln_b"])
    for i in range(cfg.enc_layers):
        for our, (suffix, transpose) in _ENC_MAP.items():
            emit(f"model.encoder.layers.{i}.{suffix}", params["enc"][our][i], transpose)
    dec_map = dict(_ENC_MAP, **_DEC_EXTRA)
    for i in range(cfg.dec_layers):
        for our, (suffix, transpose) in dec_map.items():
            emit(f"model.decoder.layers.{i}.{suffix}", params["dec"][our][i], transpose)

    from safetensors.numpy import save_file

    save_file(tensors, os.path.join(ckpt_dir, "model.safetensors"))
    with open(os.path.join(ckpt_dir, "config.json"), "w") as f:
        json.dump({
            "model_type": "whisper",
            "vocab_size": cfg.vocab_size,
            "d_model": cfg.d_model,
            "encoder_layers": cfg.enc_layers,
            "decoder_layers": cfg.dec_layers,
            "encoder_attention_heads": cfg.n_heads,
            "decoder_attention_heads": cfg.n_heads,
            "num_mel_bins": cfg.n_mels,
            "max_source_positions": cfg.n_audio_ctx,
            "max_target_positions": cfg.n_text_ctx,
            "decoder_start_token_id": cfg.sot_id,
            "eos_token_id": cfg.eot_id,
        }, f, indent=1)


def whisper_config_from_hf(ckpt_dir: str) -> WhisperConfig:
    with open(os.path.join(ckpt_dir, "config.json")) as f:
        hf = json.load(f)
    return WhisperConfig(
        name=hf.get("_name_or_path", "whisper"),
        vocab_size=hf["vocab_size"],
        d_model=hf["d_model"],
        enc_layers=hf["encoder_layers"],
        dec_layers=hf["decoder_layers"],
        n_heads=hf["encoder_attention_heads"],
        n_mels=hf.get("num_mel_bins", 80),
        n_audio_ctx=hf.get("max_source_positions", 1500),
        n_text_ctx=hf.get("max_target_positions", 448),
        sot_id=hf.get("decoder_start_token_id", 50258),
        eot_id=(hf.get("eos_token_id") if isinstance(hf.get("eos_token_id"), int) else 50257) or 50257,
    )
