"""YOLOS object detection in JAX: loads published HF checkpoints
(hustvl/yolos-tiny / yolos-small) for the /v1/detection capability.

Reference parity: the reference serves detection through RF-DETR
(/root/reference/backend/python/rfdetr/backend.py, RPC Detect →
core/backend/detection.go:12). RF-DETR needs a convnet backbone + deformable
attention — poor fits for clean XLA lowering; YOLOS is the same DETR-family
set-prediction idea expressed as a pure ViT (patch embedding + transformer +
learned detection tokens + MLP heads), which maps directly onto the MXU with
static shapes. Original JAX implementation in the HF `YolosForObjectDetection`
weight layout so real checkpoints load directly.

Inputs are resized to the checkpoint's training resolution
(config.image_size), so position embeddings never need interpolation and the
jitted program compiles exactly once.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]

# ImageNet normalization (HF YolosImageProcessor defaults).
IMAGE_MEAN = (0.485, 0.456, 0.406)
IMAGE_STD = (0.229, 0.224, 0.225)


@dataclasses.dataclass(frozen=True)
class YolosConfig:
    hidden_size: int = 192
    num_hidden_layers: int = 12
    num_attention_heads: int = 3
    intermediate_size: int = 768
    image_height: int = 800
    image_width: int = 1333
    patch_size: int = 16
    num_detection_tokens: int = 100
    num_labels: int = 91
    use_mid_position_embeddings: bool = True
    layer_norm_eps: float = 1e-12
    id2label: tuple = ()

    @property
    def num_patches(self) -> int:
        return (self.image_height // self.patch_size) * (self.image_width // self.patch_size)

    @property
    def seq_len(self) -> int:
        return 1 + self.num_patches + self.num_detection_tokens


def config_from_hf(ckpt_dir: str) -> YolosConfig:
    with open(os.path.join(ckpt_dir, "config.json")) as f:
        d = json.load(f)
    size = d.get("image_size", [800, 1333])
    if isinstance(size, int):
        size = [size, size]
    id2label = d.get("id2label") or {}
    labels = tuple(
        id2label.get(str(i), id2label.get(i, f"label-{i}"))
        for i in range(len(id2label))
    )
    return YolosConfig(
        hidden_size=d.get("hidden_size", 192),
        num_hidden_layers=d.get("num_hidden_layers", 12),
        num_attention_heads=d.get("num_attention_heads", 3),
        intermediate_size=d.get("intermediate_size", 768),
        image_height=size[0], image_width=size[1],
        patch_size=d.get("patch_size", 16),
        num_detection_tokens=d.get("num_detection_tokens", 100),
        num_labels=len(id2label) or d.get("num_labels", 91),
        use_mid_position_embeddings=d.get("use_mid_position_embeddings", True),
        layer_norm_eps=d.get("layer_norm_eps", 1e-12),
        id2label=labels,
    )


def load_yolos_params(ckpt_dir: str) -> Params:
    from safetensors import safe_open

    path = os.path.join(ckpt_dir, "model.safetensors")
    out: Params = {}
    with safe_open(path, framework="numpy") as f:
        for name in f.keys():
            out[name] = jnp.asarray(np.asarray(f.get_tensor(name), np.float32))
    return out


def is_yolos_dir(ckpt_dir: str) -> bool:
    cfg_path = os.path.join(ckpt_dir, "config.json")
    if not os.path.isfile(cfg_path):
        return False
    try:
        with open(cfg_path) as f:
            return json.load(f).get("model_type") == "yolos"
    except (OSError, json.JSONDecodeError):
        return False


def _ln(x, w, b, eps):
    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


def _mlp_head(p: Params, pre: str, x, num_layers: int = 3):
    """YolosMLPPredictionHead: Linear+ReLU × (n−1), then Linear."""
    for i in range(num_layers):
        x = x @ p[f"{pre}.layers.{i}.weight"].T + p[f"{pre}.layers.{i}.bias"]
        if i < num_layers - 1:
            x = jax.nn.relu(x)
    return x


def forward(cfg: YolosConfig, p: Params, pixels: jnp.ndarray):
    """pixels [B, 3, H, W] (ImageNet-normalized, H/W = config resolution) →
    (class_logits [B, Q, num_labels+1], boxes [B, Q, 4] cxcywh in [0,1])."""
    B = pixels.shape[0]
    C = cfg.hidden_size
    patches = jax.lax.conv_general_dilated(
        pixels, p["vit.embeddings.patch_embeddings.projection.weight"],
        window_strides=(cfg.patch_size, cfg.patch_size), padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    ) + p["vit.embeddings.patch_embeddings.projection.bias"][None, :, None, None]
    patches = patches.reshape(B, C, -1).transpose(0, 2, 1)  # [B, P, C]

    cls = jnp.broadcast_to(p["vit.embeddings.cls_token"], (B, 1, C))
    det = jnp.broadcast_to(
        p["vit.embeddings.detection_tokens"], (B, cfg.num_detection_tokens, C)
    )
    h = jnp.concatenate([cls, patches, det], axis=1)
    h = h + p["vit.embeddings.position_embeddings"]

    H, D = cfg.num_attention_heads, cfg.hidden_size // cfg.num_attention_heads
    T = h.shape[1]
    for i in range(cfg.num_hidden_layers):
        pre = f"vit.encoder.layer.{i}"
        x = _ln(h, p[f"{pre}.layernorm_before.weight"], p[f"{pre}.layernorm_before.bias"],
                cfg.layer_norm_eps)

        def lin(name, t):
            return t @ p[f"{pre}.attention.attention.{name}.weight"].T + \
                p[f"{pre}.attention.attention.{name}.bias"]

        q = lin("query", x).reshape(B, T, H, D).transpose(0, 2, 1, 3)
        k = lin("key", x).reshape(B, T, H, D).transpose(0, 2, 1, 3)
        v = lin("value", x).reshape(B, T, H, D).transpose(0, 2, 1, 3)
        probs = jax.nn.softmax(q @ k.transpose(0, 1, 3, 2) * (D**-0.5), axis=-1)
        attn = (probs @ v).transpose(0, 2, 1, 3).reshape(B, T, C)
        attn = attn @ p[f"{pre}.attention.output.dense.weight"].T + \
            p[f"{pre}.attention.output.dense.bias"]
        h = h + attn

        x = _ln(h, p[f"{pre}.layernorm_after.weight"], p[f"{pre}.layernorm_after.bias"],
                cfg.layer_norm_eps)
        x = jax.nn.gelu(
            x @ p[f"{pre}.intermediate.dense.weight"].T + p[f"{pre}.intermediate.dense.bias"],
            approximate=False,
        )
        h = h + (x @ p[f"{pre}.output.dense.weight"].T + p[f"{pre}.output.dense.bias"])

        # YOLOS: learned per-layer position embeddings re-added between blocks.
        if cfg.use_mid_position_embeddings and i < cfg.num_hidden_layers - 1:
            h = h + p["vit.encoder.mid_position_embeddings"][i]

    h = _ln(h, p["vit.layernorm.weight"], p["vit.layernorm.bias"], cfg.layer_norm_eps)
    det_h = h[:, -cfg.num_detection_tokens:]
    logits = _mlp_head(p, "class_labels_classifier", det_h)
    boxes = jax.nn.sigmoid(_mlp_head(p, "bbox_predictor", det_h))
    return logits, boxes


def preprocess(image: np.ndarray, cfg: YolosConfig) -> np.ndarray:
    """uint8/float [H, W, 3] → normalized [1, 3, H_cfg, W_cfg] (bilinear)."""
    img = np.asarray(image)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    img = jax.image.resize(
        jnp.asarray(img, jnp.float32), (cfg.image_height, cfg.image_width, 3), "bilinear"
    )
    img = (img - jnp.asarray(IMAGE_MEAN)) / jnp.asarray(IMAGE_STD)
    return np.asarray(img.transpose(2, 0, 1)[None])


def postprocess(
    cfg: YolosConfig,
    logits: np.ndarray,  # [Q, num_labels+1]
    boxes: np.ndarray,  # [Q, 4] cxcywh normalized
    threshold: float = 0.5,
) -> list[dict]:
    """DETR post-processing: softmax scores excluding the trailing no-object
    class; cxcywh → normalized corner boxes."""
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    scores = probs[:, :-1]
    out = []
    for q in range(scores.shape[0]):
        c = int(scores[q].argmax())
        s = float(scores[q, c])
        if s < threshold:
            continue
        cx, cy, w, h = (float(v) for v in boxes[q])
        label = cfg.id2label[c] if c < len(cfg.id2label) else f"label-{c}"
        # Clip to the image by moving each edge independently — clamping
        # only the origin would translate edge boxes instead of shrinking.
        x0, x1 = max(0.0, cx - w / 2), min(1.0, cx + w / 2)
        y0, y1 = max(0.0, cy - h / 2), min(1.0, cy + h / 2)
        out.append({
            "x": x0, "y": y0, "width": max(0.0, x1 - x0),
            "height": max(0.0, y1 - y0), "confidence": s, "class_name": label,
        })
    return out


def load_yolos(ckpt_dir: str):
    return config_from_hf(ckpt_dir), load_yolos_params(ckpt_dir)
