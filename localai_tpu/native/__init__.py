"""Native (C++) runtime components, built on demand with the system g++.

The TPU compute path is already native (XLA-compiled); this package covers
host-side hot paths the reference implements in C++ — currently the
byte-level BPE merge engine (reference: llama.cpp's llm_tokenizer_bpe via
backend/cpp/llama-cpp). Build artifacts land in ~/.cache/localai_tpu/native
keyed by source hash; a missing/failed toolchain degrades to the pure-Python
paths, never to an error.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger("localai_tpu.native")

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
_lib_cache: dict[str, Optional[ctypes.CDLL]] = {}


def _build(name: str) -> Optional[ctypes.CDLL]:
    """Compile native/<name>.cpp → cached .so; None when unbuildable."""
    src = os.path.join(_SRC_DIR, f"{name}.cpp")
    if not os.path.exists(src):
        return None
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache_dir = os.path.expanduser("~/.cache/localai_tpu/native")
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"lib{name}-{digest}.so")
    if not os.path.exists(so_path):
        tmp = so_path + ".tmp"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so_path)
        except (subprocess.SubprocessError, OSError) as e:
            log.warning("native build of %s failed (%s); using Python path", name, e)
            return None
    try:
        return ctypes.CDLL(so_path)
    except OSError as e:
        log.warning("could not load %s: %s", so_path, e)
        return None


def load_library(name: str) -> Optional[ctypes.CDLL]:
    with _lock:
        if name not in _lib_cache:
            _lib_cache[name] = _build(name)
        return _lib_cache[name]


class NativeBPE:
    """ctypes wrapper over the C++ BPE merge engine.

    Raises RuntimeError when the native library is unavailable — callers
    (engine.bpe_fast.FastBPE) fall back to Python.
    """

    def __init__(self, vocab: dict[str, int], merges: list[tuple[str, str]]):
        lib = load_library("bpe")
        if lib is None:
            raise RuntimeError("native bpe library unavailable")
        lib.bpe_new.restype = ctypes.c_void_p
        lib.bpe_new.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                ctypes.c_char_p, ctypes.c_long]
        lib.bpe_encode_piece.restype = ctypes.c_int
        lib.bpe_encode_piece.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ]
        lib.bpe_free.argtypes = [ctypes.c_void_p]
        self._lib = lib

        # id = line number: emit vocab ordered by id (dense ids expected).
        n = max(vocab.values()) + 1 if vocab else 0
        by_id = [""] * n
        for tok, i in vocab.items():
            if 0 <= i < n:
                by_id[i] = tok
        vocab_blob = "\n".join(by_id).encode("utf-8")
        merges_blob = "\n".join(f"{a} {b}" for a, b in merges).encode("utf-8")
        self._handle = lib.bpe_new(vocab_blob, len(vocab_blob),
                                   merges_blob, len(merges_blob))
        if not self._handle:
            raise RuntimeError("bpe_new failed")

    def encode_piece(self, piece: str) -> list[int]:
        data = piece.encode("utf-8")
        # Per-call buffer: the server tokenizes from many threads and ctypes
        # releases the GIL during the foreign call, so a shared buffer races.
        # Byte-level BPE yields at most one id per input byte (merges only
        # shrink), so len(data) capacity can never be exceeded.
        out = (ctypes.c_int32 * max(64, len(data)))()
        n = self._lib.bpe_encode_piece(self._handle, data, len(data),
                                       out, len(out))
        if n < 0:
            raise ValueError(f"native BPE could not encode piece {piece!r}")
        return out[:n]

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            try:
                self._lib.bpe_free(handle)
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass
