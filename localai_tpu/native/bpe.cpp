// Byte-level BPE merge engine (C ABI, loaded via ctypes).
//
// The reference's tokenizer hot path is llama.cpp's C++ (llama_tokenize);
// here the same role: Python owns pre-tokenization (exact GPT-2/llama-3
// regex) and the byte→unicode mapping, C++ owns the merge loop — the
// O(pieces × merges) part that dominates long-prompt encoding.
//
// Contract:
//   bpe_new(vocab_blob, vocab_len, merges_blob, merges_len) -> handle
//     vocab_blob:  '\n'-separated token strings; id = line index.
//     merges_blob: '\n'-separated "left right" pairs; rank = line index.
//   bpe_encode_piece(handle, piece, len, out_ids, max_out) -> n_ids (or -1)
//     piece: one pre-tokenized piece in byte-level unicode form (UTF-8).
//   bpe_free(handle)
//
// Build: g++ -O2 -shared -fPIC bpe.cpp -o libbpe.so

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct PairHash {
    size_t operator()(const std::pair<uint32_t, uint32_t>& p) const {
        return (static_cast<size_t>(p.first) << 32) ^ p.second;
    }
};

struct BPE {
    std::unordered_map<std::string, int32_t> vocab;
    // merge rank keyed on (left symbol id, right symbol id) in vocab space:
    // every merge operand must itself be a vocab entry in well-formed BPE.
    std::unordered_map<std::pair<uint32_t, uint32_t>, int32_t, PairHash> ranks;
    std::vector<std::string> id_to_str;
};

std::vector<std::string> split_lines(const char* blob, long len) {
    std::vector<std::string> out;
    const char* end = blob + len;
    const char* start = blob;
    for (const char* p = blob; p <= end; ++p) {
        if (p == end || *p == '\n') {
            if (p > start) out.emplace_back(start, p - start);
            else out.emplace_back();
            start = p + 1;
        }
    }
    if (!out.empty() && out.back().empty()) out.pop_back();
    return out;
}

// Split a UTF-8 string into codepoint-granular symbol strings.
std::vector<std::string> utf8_symbols(const char* s, int len) {
    std::vector<std::string> out;
    int i = 0;
    while (i < len) {
        unsigned char c = static_cast<unsigned char>(s[i]);
        int n = 1;
        if ((c & 0x80) == 0x00) n = 1;
        else if ((c & 0xE0) == 0xC0) n = 2;
        else if ((c & 0xF0) == 0xE0) n = 3;
        else if ((c & 0xF8) == 0xF0) n = 4;
        if (i + n > len) n = 1;  // malformed tail: take the byte
        out.emplace_back(s + i, n);
        i += n;
    }
    return out;
}

}  // namespace

extern "C" {

void* bpe_new(const char* vocab_blob, long vocab_len,
              const char* merges_blob, long merges_len) {
    auto* bpe = new BPE();
    auto vlines = split_lines(vocab_blob, vocab_len);
    bpe->id_to_str = vlines;
    bpe->vocab.reserve(vlines.size() * 2);
    for (size_t i = 0; i < vlines.size(); ++i) {
        bpe->vocab.emplace(vlines[i], static_cast<int32_t>(i));
    }
    auto mlines = split_lines(merges_blob, merges_len);
    bpe->ranks.reserve(mlines.size() * 2);
    for (size_t r = 0; r < mlines.size(); ++r) {
        const std::string& line = mlines[r];
        size_t sp = line.find(' ');
        if (sp == std::string::npos) continue;
        auto li = bpe->vocab.find(line.substr(0, sp));
        auto ri = bpe->vocab.find(line.substr(sp + 1));
        if (li == bpe->vocab.end() || ri == bpe->vocab.end()) continue;
        std::pair<uint32_t, uint32_t> key(li->second, ri->second);
        if (bpe->ranks.find(key) == bpe->ranks.end()) {
            bpe->ranks.emplace(key, static_cast<int32_t>(r));
        }
    }
    return bpe;
}

void bpe_free(void* handle) { delete static_cast<BPE*>(handle); }

int bpe_encode_piece(void* handle, const char* piece, int len,
                     int32_t* out, int max_out) {
    BPE* bpe = static_cast<BPE*>(handle);
    // Symbols as vocab ids; unknown single codepoints are an error (-1):
    // byte-level alphabets always cover every byte char.
    auto syms_str = utf8_symbols(piece, len);
    std::vector<uint32_t> syms;
    syms.reserve(syms_str.size());
    for (auto& s : syms_str) {
        auto it = bpe->vocab.find(s);
        if (it == bpe->vocab.end()) return -1;
        syms.push_back(it->second);
    }

    // Greedy lowest-rank merge loop (quadratic worst case, tiny pieces in
    // practice — same shape as llama.cpp's llm_tokenizer_bpe).
    while (syms.size() >= 2) {
        int best_rank = INT32_MAX;
        size_t best_i = 0;
        for (size_t i = 0; i + 1 < syms.size(); ++i) {
            auto it = bpe->ranks.find({syms[i], syms[i + 1]});
            if (it != bpe->ranks.end() && it->second < best_rank) {
                best_rank = it->second;
                best_i = i;
            }
        }
        if (best_rank == INT32_MAX) break;
        const std::string merged =
            bpe->id_to_str[syms[best_i]] + bpe->id_to_str[syms[best_i + 1]];
        auto it = bpe->vocab.find(merged);
        if (it == bpe->vocab.end()) break;  // rank table out of sync — stop
        syms[best_i] = it->second;
        syms.erase(syms.begin() + best_i + 1);
    }

    if (static_cast<int>(syms.size()) > max_out) return -1;
    for (size_t i = 0; i < syms.size(); ++i) out[i] = static_cast<int32_t>(syms[i]);
    return static_cast<int>(syms.size());
}

}  // extern "C"
