"""Request-lifecycle tracing + engine flight recorder (ISSUE 11).

The serving path grew far past what one HTTP histogram can see: a request
crosses queue → (chunked) admission → decode blocks → preempt/swap/resume →
cluster span-transfer/reroute, and until now the only way to attribute a
stall was archaeology over logs (BENCH_r05 died as an rc=124 fifteen
minutes in). This package makes the lifecycle observable in four layers:

- `journal`  — a preallocated bounded ring buffer of typed events owned by
  the engine loop (append is lock-free from the loop thread, O(1), no
  Python-object allocation, no device sync). Cross-thread producers
  (submit, span export) stage into a small locked sidecar the loop drains.
- `trace`    — per-request span trees keyed by a request id that
  propagates as W3C `traceparent` from HTTP headers through GenRequest,
  cluster dispatch/reroute, federation proxying, and LAIKV span-transfer
  frames, so a disaggregated prefill→decode request is ONE trace.
- `timeline` — journal → Chrome trace-event JSON (Perfetto-loadable),
  served at `/debug/timeline`.
- `postmortem` — the flight recorder: on engine-loop death the last N
  journal events + an engine state snapshot dump to a JSON file whose path
  rides the `loop_dead` gauge labels and the manager log.

`fence` and `profile` are DECLARED sync points (LOCALAI_TRACE_FENCE /
LOCALAI_PROFILE debug paths) and are deliberately excluded from the
trace-safety lint targets, exactly like the engine drainer thread.
"""

from localai_tpu.observe.journal import EventJournal  # noqa: F401
from localai_tpu.observe.trace import (  # noqa: F401
    STORE,
    RequestTrace,
    TraceStore,
    format_traceparent,
    new_traceparent,
    parse_traceparent,
)
