"""Opt-in fenced device timing (LOCALAI_TRACE_FENCE=1), ISSUE 11.

This module is a DECLARED synchronization point and is deliberately
excluded from the trace-safety lint targets, exactly like the engine's
drainer thread: its whole purpose is to block on the device, and it only
runs when the operator explicitly asked for fenced per-dispatch device
times (which serializes the pipeline — a measurement mode, not a serving
mode). Everything else in localai_tpu/observe/ IS lint-covered and must
stay sync-free.
"""

from __future__ import annotations

import time
from typing import Any


def fenced_wait_ms(x: Any) -> float:
    """Block until `x` (an array or pytree of arrays) is ready; return the
    wait in milliseconds. Returns 0.0 on any failure — fencing is a debug
    measurement, never worth failing a dispatch over."""
    t0 = time.monotonic()
    try:
        import jax

        jax.block_until_ready(x)
    except Exception:  # noqa: BLE001 — measurement only
        return 0.0
    return (time.monotonic() - t0) * 1000.0
