"""Bounded ring-buffer event journal for the engine loop (ISSUE 11).

Design constraints, in order:

- The append path is called from the engine loop between decode-block
  dispatches, so it must be O(1), allocation-free, and never touch the
  device. Storage is a preallocated numpy structured array; appends write
  FIELD-WISE into fixed storage (no tuple/dict is built), and the only
  state change is a monotonically-growing sequence counter. The loop
  thread is the single writer — no lock on the hot path.
- Other threads DO emit lifecycle events (submit queues a request on a
  caller thread, span export runs on HTTP threads). Those `stage()` into a
  small locked sidecar list the loop thread drains at the top of each
  iteration (`drain_staged` — same idiom as the engine's span inbox), so
  the ring stays single-writer.
- Readers (`snapshot`) are best-effort: they copy the buffer and walk it
  by sequence number. A reader racing the writer can observe a freshly
  overwritten slot — acceptable for a flight recorder; the alternative is
  a lock on every append.

Event types are declared here (`EVENTS`); the fault subset
(`FAULT_EVENTS`) mirrors `localai_tpu.testing.faults.SITES` one-to-one and
the `journal-events` lint pass (tools/lint) checks BOTH directions, the
same contract the `fault-sites` pass enforces for `faults.fire()` calls.
"""

from __future__ import annotations

import threading
import time

import numpy as np

# Lifecycle + loop events. Order is the wire code (index), so append-only.
BASE_EVENTS = (
    "queued",        # request entered the pending queue (staged; rid)
    "admitted",      # slot claimed, admission program dispatched (slot, a=plen)
    "chunk",         # one mid prefill chunk dispatched (slot, a=tokens)
    "first_token",   # admission result produced the first token (slot)
    "decode_block",  # decode/spec block dispatched (a=block size, b=dispatch ms)
    "loop_iter",     # coalesced loop-iteration window (a=occupancy, b=host ms
    #                  spent this window — or fenced device ms under
    #                  trace_fence when the window dispatched; the per-phase
    #                  host-ms breakdown rides the `phases` vector, ISSUE 17)
    "preempt",       # slot preempted for pool pressure (slot, a=ctx rows)
    "swap_out",      # preempt-swap image written to the host tier (a=bytes)
    "swap_in",       # swap resume restored pool pages (slot, a=bytes)
    "resume",        # recompute resume re-admitted (slot)
    "prefix_hit",    # admission mapped a cached span (slot, a=matched tokens)
    "span_export",   # prefix span framed for transfer (staged; a=tokens)
    "span_import",   # transfer frame merged into the host tier (a=tokens)
    "terminal",      # request finished (slot, a=completion tokens)
    "error",         # a dispatch failed; affected requests got error events
    "loop_dead",     # the engine loop died (postmortem follows)
    "profile",       # a jax.profiler capture window ran (a=seconds)
    "spec_draft",    # verify round dispatched (a=drafted tokens, b=window)
    "spec_verify",   # verify round processed (a=drafted, b=emitted tokens)
    "page_spill",    # cold middle pages copied to host, device pages freed
    #                  (slot, a=pages, b=bytes; docs/LONG_CONTEXT.md)
    "page_restore",  # spilled pages swapped back into fresh pool pages
    #                  (slot, a=pages, b=bytes)
    "forked",        # slot forked off a freshly-admitted sibling (slot=branch,
    #                  a=shared prompt/boundary rows, b=source slot;
    #                  docs/TREE_SAMPLING.md)
    "member_state",  # cluster replica lifecycle transition (staged; rid=
    #                  replica name, a=new state index, b=old state index —
    #                  indices into cluster.scheduler.MEMBER_STATES;
    #                  docs/CLUSTER.md "Membership lifecycle", ISSUE 19)
    "breaker_open",  # per-replica circuit breaker tripped open (staged;
    #                  rid=replica name, a=consecutive failures)
    "breaker_probe", # half-open breaker admitted its ONE probe call
    #                  (staged; rid=replica name, a=total probes) — chaos
    #                  runs assert ≤1 per half-open window from these
    "breaker_close", # breaker closed again after a successful probe
    #                  (staged; rid=replica name)
    "reroute_replay",# grammar-bearing request rerouted mid-stream: emitted
    #                  tokens replayed through a fresh grammar machine on
    #                  the survivor (staged; rid, a=replayed tokens,
    #                  b=reroute attempt number; docs/CLUSTER.md)
    "affinity_handoff",  # a draining/dead replica's span affinity moved to
    #                  a survivor instead of being dropped (staged; rid=
    #                  source replica, a=digests moved)
)

# One journal event type per fault-injection site (faults.SITES), checked
# both directions by the journal-events lint pass: a site added without an
# event type (or vice versa) is a finding. Literal on purpose — the check
# is AST-level, like fault-sites.
FAULT_EVENTS = (
    "fault_device_dispatch",
    "fault_engine_loop",
    "fault_page_alloc",
    "fault_host_swap",
    "fault_manager_load",
    "fault_cluster_dispatch",
    "fault_span_transfer",
    "fault_host_partition",
    "fault_slow_network",
    "fault_collective_dispatch",
    "fault_adapter_fetch",
    "fault_spec_verify",
    "fault_page_spill",
    "fault_control_commit",
    "fault_slot_fork",
    "fault_gauge_scrape",
)

EVENTS = BASE_EVENTS + FAULT_EVENTS
CODES = {name: i for i, name in enumerate(EVENTS)}

# Host-phase names for one loop_iter window (engine/runtime.LOOP_PHASES is
# the writer-side source; this copy keeps the observe layer engine-free and
# a unit test pins the two tuples equal). The per-event `ph` vector stores
# milliseconds per phase in this order.
LOOP_PHASES = (
    "drain", "purge", "admit", "prep", "commit", "dispatch", "process",
    "housekeeping", "wait",
)

_DTYPE = np.dtype([
    ("t", np.float64),      # time.monotonic() at emit
    ("code", np.int16),     # index into EVENTS
    ("slot", np.int16),     # engine slot, -1 = engine-wide
    ("a", np.float64),      # event-specific scalar (see EVENTS comments)
    ("b", np.float64),      # second event-specific scalar
    ("rid", "U40"),         # request id (empty for engine-wide events)
    ("ph", np.float32, (len(LOOP_PHASES),)),  # loop_iter host-phase ms
])

_STAGED_CAP = 1024


class EventJournal:
    """Fixed-capacity ring of typed events. Single writer (the engine
    loop); `stage()` is the cross-thread entry point."""

    def __init__(self, capacity: int = 4096):
        self.capacity = max(int(capacity), 8)
        # thread: single-writer engine-loop — the ring is written by the
        # loop thread alone (appends + staged drain); snapshot() readers
        # are deliberately best-effort (may see a freshly overwritten slot)
        self._buf = np.zeros(self.capacity, dtype=_DTYPE)
        self.n = 0  # total events ever appended (monotonic sequence)
        self._staged: list[tuple] = []
        self._staged_lock = threading.Lock()
        self.dropped_staged = 0
        # Wall-clock anchor so exports can place monotonic stamps in time.
        self.t0_mono = time.monotonic()
        self.t0_wall = time.time()

    # ---------------- write side ---------------- #

    # thread: engine-loop-only
    def append(self, event: str, rid: str = "", slot: int = -1,
               a: float = 0.0, b: float = 0.0, phases=None) -> None:
        """Writer-thread append: O(1), no allocation, no lock, no device.
        The `# thread:` declaration makes the single-writer convention
        machine-checked (thread-affinity lint pass): any call chain from a
        non-loop root is a finding — cross-thread emitters use stage().
        `phases` (loop_iter only) is a LOOP_PHASES-ordered ms sequence."""
        self._append_raw(time.monotonic(), event, rid, slot, a, b, phases)

    def _append_raw(self, t: float, event: str, rid: str, slot: int,
                    a: float, b: float, phases=None) -> None:
        i = self.n % self.capacity
        buf = self._buf
        buf["t"][i] = t
        buf["code"][i] = CODES[event]
        buf["slot"][i] = slot
        buf["a"][i] = a
        buf["b"][i] = b
        buf["rid"][i] = rid
        buf["ph"][i] = phases if phases is not None else 0.0
        self.n += 1

    def stage(self, event: str, rid: str = "", slot: int = -1,
              a: float = 0.0, b: float = 0.0) -> None:
        """Cross-thread emit: park the event for the writer thread to
        append in order. Bounded — a stalled writer drops (and counts)
        staged events instead of growing without limit."""
        rec = (time.monotonic(), event, rid, slot, a, b)
        with self._staged_lock:
            if len(self._staged) >= _STAGED_CAP:
                self.dropped_staged += 1
                return
            self._staged.append(rec)

    # thread: engine-loop-only
    def drain_staged(self) -> None:
        """Writer thread: move staged events into the ring (original
        timestamps preserved)."""
        if not self._staged:  # unlocked peek — len() is atomic in CPython
            return
        with self._staged_lock:
            staged, self._staged = self._staged, []
        for rec in staged:
            self._append_raw(*rec)

    # ---------------- read side ---------------- #

    def snapshot(self, last: int | None = None) -> list[dict]:
        """Best-effort ordered copy of the retained events (ring tail +
        currently staged), oldest first. Safe from any thread."""
        n = self.n
        buf = self._buf.copy()
        start = max(0, n - self.capacity)
        out = []
        for seq in range(start, n):
            rec = buf[seq % self.capacity]
            d = {
                "seq": seq,
                "t": float(rec["t"]),
                "event": EVENTS[int(rec["code"])],
                "slot": int(rec["slot"]),
                "a": float(rec["a"]),
                "b": float(rec["b"]),
                "rid": str(rec["rid"]),
            }
            ph = rec["ph"]
            if ph.any():
                d["phases"] = {LOOP_PHASES[k]: float(v)
                               for k, v in enumerate(ph) if v}
            out.append(d)
        with self._staged_lock:
            staged = list(self._staged)
        for t, event, rid, slot, a, b in staged:
            out.append({
                "seq": -1, "t": float(t), "event": event, "slot": int(slot),
                "a": float(a), "b": float(b), "rid": str(rid),
            })
        out.sort(key=lambda e: e["t"])
        if last is not None and last >= 0:
            out = out[-last:]
        return out
