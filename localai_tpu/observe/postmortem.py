"""Engine flight-recorder dumps (ISSUE 11).

When an engine loop dies (or the manager quarantines a crash-looping
model), the dying thread writes the last N journal events plus an engine
state snapshot — live slots, pool accounting, pending depth — to a JSON
file. The path rides the `loop_dead` gauge labels and the manager log, so
the BENCH_r05 class (rc=124 after 15 silent minutes) becomes a five-minute
read: which requests were live, what the loop dispatched last, where the
pool stood.

Writes are atomic (tmp + rename) and best-effort: a full disk must never
mask the original crash.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

DEFAULT_DIRNAME = "localai-postmortems"


def default_dir() -> str:
    """Fallback postmortem directory when no `postmortem_dir` /
    LOCALAI_POSTMORTEM_DIR is configured: a stable tempdir child, so
    dumps survive the process but never litter a working tree."""
    return os.path.join(tempfile.gettempdir(), DEFAULT_DIRNAME)


def write(dirpath: str, name: str, payload: dict) -> str:
    """Atomically write one postmortem JSON; returns its path."""
    dirpath = dirpath or default_dir()
    os.makedirs(dirpath, exist_ok=True)
    safe = "".join(c if (c.isalnum() or c in "-_.") else "_" for c in name)
    fname = f"postmortem-{safe}-{int(time.time() * 1000)}-{os.getpid()}.json"
    path = os.path.join(dirpath, fname)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return path
