"""On-demand jax.profiler capture windows (/debug/profile), ISSUE 11.

Gated behind LOCALAI_PROFILE (the capture output directory): profiling
allocates device trace buffers and perturbs serving, so it must be an
explicit operator opt-in, not a reachable default. One capture at a time —
jax.profiler keeps process-global state. Like `fence`, this module is a
declared sync/measurement point outside the trace-safety lint targets.
"""

from __future__ import annotations

import threading
import time

_capture_lock = threading.Lock()

MAX_SECONDS = 30.0


def capture(dirpath: str, seconds: float) -> dict:
    """Run one profiler capture window (blocking). Raises RuntimeError
    when a capture is already in flight or the profiler fails."""
    seconds = max(0.1, min(float(seconds), MAX_SECONDS))
    if not _capture_lock.acquire(blocking=False):
        raise RuntimeError("a profiler capture is already running")
    try:
        import jax

        t0 = time.monotonic()
        jax.profiler.start_trace(dirpath)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        return {
            "dir": dirpath,
            "seconds": round(time.monotonic() - t0, 3),
        }
    finally:
        _capture_lock.release()
