"""Journal → Chrome trace-event JSON (Perfetto-loadable), ISSUE 11.

The export is the standard JSON-object form (`{"traceEvents": [...]}`)
that chrome://tracing and https://ui.perfetto.dev both load directly.
Mapping:

- one journal = one `pid` (process row), named by the engine/replica;
- `tid` is the engine slot (engine-wide events ride tid 0 labeled
  "engine-loop");
- events that carry a duration (`decode_block` dispatch wall, `loop_iter`
  fenced device time, `chunk`) become complete ("X") events ending at
  their journal timestamp; everything else is an instant ("i");
- timestamps are microseconds relative to the earliest journal anchor, so
  multi-journal exports (cluster replicas) share one timeline.
"""

from __future__ import annotations

from typing import Any

# Journal events whose `b` field is a duration in milliseconds.
_DUR_MS_EVENTS = {"decode_block", "loop_iter", "chunk"}


def chrome_trace(journals: dict[str, Any]) -> dict:
    """{"traceEvents": [...]} from {name: EventJournal}. Best-effort and
    read-only — safe to call against live engines."""
    events: list[dict] = []
    items = sorted(journals.items())
    anchor = min((j.t0_mono for _n, j in items), default=0.0)
    for pid, (name, j) in enumerate(items):
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name},
        })
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "engine-loop"},
        })
        for rec in j.snapshot():
            ts = max(0.0, (rec["t"] - anchor) * 1e6)
            tid = rec["slot"] if rec["slot"] >= 0 else 0
            args = {"seq": rec["seq"], "a": rec["a"], "b": rec["b"]}
            if rec["rid"]:
                args["rid"] = rec["rid"]
            if "phases" in rec:
                # loop_iter host-phase ms breakdown (ISSUE 17) — visible in
                # the Perfetto args panel per window.
                args["phases"] = rec["phases"]
            ev: dict = {
                "name": rec["event"], "cat": "engine",
                "pid": pid, "tid": tid, "args": args,
            }
            dur_us = (rec["b"] * 1000.0
                      if rec["event"] in _DUR_MS_EVENTS else 0.0)
            if dur_us > 0:
                ev["ph"] = "X"
                ev["ts"] = max(0.0, ts - dur_us)
                ev["dur"] = dur_us
            else:
                ev["ph"] = "i"
                ev["ts"] = ts
                ev["s"] = "t"
            events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"generator": "localai_tpu/observe"},
    }
