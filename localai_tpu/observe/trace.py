"""Per-request span trees + W3C traceparent propagation (ISSUE 11).

A `RequestTrace` is an append-only list of timestamped lifecycle notes for
ONE request leg on ONE engine; the span tree is DERIVED at read time (the
hot path only appends — `list.append` is the entire per-event cost). The
phase model tiles the request's wall clock exactly: consecutive notes
bound spans labeled by the state the earlier note entered, so phase
durations always sum to terminal−queued (the /debug/trace acceptance
contract: within 5% of measured wall time).

Trace identity follows W3C trace context: an incoming `traceparent` HTTP
header seeds the trace id; the id rides GenRequest.traceparent through
cluster dispatch/reroute, federation proxying (the front door injects one
when the client sent none), and LAIKV span-transfer frames — so a
disaggregated prefill→decode request is one trace with several legs, all
retrievable from the process-wide `STORE` by request id.

Thread model: notes are appended by whichever thread owns that lifecycle
step (engine loop, submit thread); readers snapshot via `list(events)`
(safe under the GIL against concurrent append). Terminal recording is
routed through the request handle's event queue (`engine.RequestHandle`),
so EVERY path that ends a stream — finish, cancel, deadline, loop death,
stop() — lands exactly one terminal note (later duplicates are ignored).
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from typing import Any, Optional

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)

# Lifecycle note → the phase the request is in FROM that note on. Notes
# absent here (prefix_hit, annotations, chunk progress) are decorations —
# they do not change the phase.
PHASE_OF = {
    "queued": "queue",
    "admitted": "admit",
    "swap_in": "admit",
    "first_token": "decode",
    "resumed": "decode",
    "preempt": "preempted",
}


def parse_traceparent(header: str) -> Optional[tuple[str, str]]:
    """(trace_id, parent_span_id) from a W3C traceparent header, or None
    on anything malformed (a bad header must never fail a request)."""
    m = _TRACEPARENT_RE.match((header or "").strip().lower())
    if not m:
        return None
    tid, sid = m.group(1), m.group(2)
    if tid == "0" * 32 or sid == "0" * 16:
        return None
    return tid, sid


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def new_traceparent() -> str:
    return format_traceparent(new_trace_id(), new_span_id())


class RequestTrace:
    """One request leg's lifecycle notes + derived span tree."""

    def __init__(self, request_id: str, traceparent: str = "",
                 engine: str = ""):
        parsed = parse_traceparent(traceparent)
        self.trace_id = parsed[0] if parsed else new_trace_id()
        self.parent_span_id = parsed[1] if parsed else ""
        self.span_id = new_span_id()
        self.request_id = request_id
        self.engine = engine
        self.events: list[tuple[float, str, Optional[dict]]] = []
        self.completed = False

    # ---------------- write side ---------------- #

    def note(self, name: str, **attrs: Any) -> None:
        """Record one lifecycle note. Hot-path cost: one list.append."""
        self.events.append((time.monotonic(), name, attrs or None))

    def terminal(self, ev: Any) -> None:
        """Record the terminal event (idempotent — only the FIRST terminal
        counts; stop()'s deliberate duplicate done events are ignored)."""
        if self.completed:
            return
        self.completed = True
        attrs: dict[str, Any] = {"kind": getattr(ev, "kind", "done")}
        reason = getattr(ev, "finish_reason", None)
        if reason:
            attrs["finish_reason"] = reason
        err = getattr(ev, "error", None)
        if err:
            attrs["error"] = str(err)
        ct = getattr(ev, "completion_tokens", 0)
        if ct:
            attrs["completion_tokens"] = ct
        self.events.append((time.monotonic(), "terminal", attrs))
        STORE.retire(self)

    # ---------------- read side ---------------- #

    def _ordered(self) -> list[tuple[float, str, Optional[dict]]]:
        evs = sorted(list(self.events), key=lambda e: e[0])
        out = []
        for e in evs:
            out.append(e)
            if e[1] == "terminal":
                break  # anything after the first terminal is noise
        return out

    def spans(self) -> list[dict]:
        """Phase spans tiling [first note, terminal]: each span runs from
        its entering note to the next phase-changing note (or terminal),
        so durations sum exactly to the leg's wall time."""
        evs = self._ordered()
        if not evs:
            return []
        marks = [(t, PHASE_OF[name], name) for t, name, _ in evs
                 if name in PHASE_OF]
        t_end = evs[-1][0]
        out = []
        for i, (t, phase, name) in enumerate(marks):
            nxt = marks[i + 1][0] if i + 1 < len(marks) else t_end
            out.append({
                "name": phase,
                "entered_by": name,
                "t_start": t,
                "t_end": nxt,
                "duration_ms": max(0.0, (nxt - t) * 1000.0),
            })
        return out

    def to_json(self) -> dict:
        evs = self._ordered()
        t0 = evs[0][0] if evs else 0.0
        t_end = evs[-1][0] if evs else 0.0
        return {
            "request_id": self.request_id,
            "engine": self.engine,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "traceparent": format_traceparent(self.trace_id, self.span_id),
            "complete": self.completed,
            "wall_ms": max(0.0, (t_end - t0) * 1000.0),
            "terminal_events": sum(1 for _, n, _a in evs if n == "terminal"),
            "spans": [
                {**s,
                 "t_start": round((s["t_start"] - t0) * 1000.0, 3),
                 "t_end": round((s["t_end"] - t0) * 1000.0, 3),
                 "duration_ms": round(s["duration_ms"], 3)}
                for s in self.spans()
            ],
            "events": [
                {"t_ms": round((t - t0) * 1000.0, 3), "name": n,
                 **({"attrs": a} if a else {})}
                for t, n, a in evs
            ],
        }


class TraceStore:
    """Process-wide registry of live + recently-completed request traces.

    The removal contract mirrors the engine's terminal-event discipline
    (and the terminal-event lint pass targets this class): the ONLY path
    that drops a live trace is `retire()`, which is invoked exactly by the
    trace's terminal recording — so a trace can never silently vanish
    while its request is still alive.
    """

    MAX_LIVE = 4096

    def __init__(self, keep: int = 256):
        self._lock = threading.Lock()
        self._live: dict[str, list[RequestTrace]] = {}
        self._done: deque[RequestTrace] = deque(maxlen=keep)
        self.dropped_live = 0

    def register(self, trace: RequestTrace) -> None:
        with self._lock:
            if len(self._live) >= self.MAX_LIVE and \
                    trace.request_id not in self._live:
                # Backstop against a producer that never terminates its
                # traces — bounded memory beats a perfect record.
                self.dropped_live += 1
                return
            self._live.setdefault(trace.request_id, []).append(trace)

    def retire(self, trace: RequestTrace) -> None:
        """Move a completed trace from the live table to the bounded done
        ring — the single sanctioned drop path from `_live`."""
        with self._lock:
            legs = self._live.get(trace.request_id)
            if legs is not None:
                legs = [t for t in legs if t is not trace]
                if legs:
                    self._live[trace.request_id] = legs
                else:
                    self._live.pop(trace.request_id, None)
            self._done.append(trace)

    def annotate(self, request_id: str, name: str, **attrs: Any) -> None:
        """Attach a note to the most recent LIVE leg of a request (the
        cluster layer marks reroutes/handoffs this way). No-op when the
        request is unknown or already completed."""
        with self._lock:
            legs = self._live.get(request_id)
            trace = legs[-1] if legs else None
        if trace is not None:
            trace.note(name, **attrs)

    def get(self, request_id: str) -> list[RequestTrace]:
        """All known legs for a request id, oldest first (live + done)."""
        with self._lock:
            live = list(self._live.get(request_id, ()))
            done = [t for t in self._done if t.request_id == request_id]
        seen: set[int] = set()
        out = []
        for t in done + live:
            if id(t) not in seen:
                seen.add(id(t))
                out.append(t)
        return out

    def get_json(self, request_id: str) -> Optional[dict]:
        legs = self.get(request_id)
        if not legs:
            return None
        return {
            "request_id": request_id,
            "trace_ids": sorted({t.trace_id for t in legs}),
            "legs": [t.to_json() for t in legs],
        }


STORE = TraceStore()
