"""TPU compute ops: norms, rotary embeddings, attention, sampling, KV cache.

Everything here is shape-static and jit-traceable; control flow uses lax
primitives so XLA can fuse and tile onto the MXU/VPU.
"""
