"""Attention ops: batched causal prefill and single-token decode against a
slot KV cache.

Design notes (TPU-first):
- Prefill attention is a dense causal softmax-attention over the bucketed
  prompt length. XLA fuses the mask+softmax chain; a Pallas flash-attention
  kernel (localai_tpu.ops.flash) can be swapped in for long buckets.
- Decode attention reads the whole slot cache [B, S_max, K, H] with a length
  mask. This is the JAX equivalent of llama.cpp's unified KV cache read in
  its slot loop (reference: backend/cpp/llama-cpp/grpc-server.cpp:679
  PredictStream -> server slots); instead of per-slot pointers we use one
  dense cache and mask, which keeps shapes static under jit.
- GQA: queries have H heads, cache has K kv-heads, H % K == 0; we reshape
  queries to [B, K, H//K, ...] and broadcast the cache.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def prefill_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, K, D]
    v: jnp.ndarray,  # [B, S, K, D]
    length_mask: jnp.ndarray | None,  # [B, S] bool
    lengths: jnp.ndarray | None = None,  # [B] int32 (enables flash path)
) -> jnp.ndarray:
    """Prefill attention dispatcher: Pallas flash kernel on TPU by default
    (opt out with LOCALAI_FLASH=0), dense math otherwise."""
    S = q.shape[1]
    if (
        lengths is not None
        and os.environ.get("LOCALAI_FLASH", "1") != "0"
        and jax.default_backend() == "tpu"
        and (S & (S - 1)) == 0  # power-of-two bucket, divisible by any block
    ):
        from localai_tpu.ops.flash import flash_prefill_attention

        blk = min(128, S)
        return flash_prefill_attention(q, k, v, lengths, block_q=blk, block_k=blk)
    return causal_prefill_attention(q, k, v, length_mask)


def causal_prefill_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, K, D]
    v: jnp.ndarray,  # [B, S, K, D]
    length_mask: jnp.ndarray | None = None,  # [B, S] bool, True = valid token
) -> jnp.ndarray:
    """Dense causal attention for prompt processing. Returns [B, S, H, D]."""
    B, S, H, D = q.shape
    K = k.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)

    qf = q.astype(jnp.float32).reshape(B, S, K, G, D)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    # scores: [B, K, G, S_q, S_k]
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qf, kf) * scale
    causal = jnp.tril(jnp.ones((S, S), dtype=bool))
    mask = causal[None, None, None, :, :]
    if length_mask is not None:
        mask = jnp.logical_and(mask, length_mask[:, None, None, None, :])
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, vf)
    return out.reshape(B, S, H, D).astype(q.dtype)


def decode_attention_appended(
    q: jnp.ndarray,  # [B, H, D] query for the single new token per slot
    k_cache: jnp.ndarray,  # [B, S_max, K, D] — cache WITHOUT the current token
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,  # [B, K, D] current token's key (not yet in the cache)
    v_new: jnp.ndarray,
    positions: jnp.ndarray,  # [B] int32 position of the current token
) -> jnp.ndarray:
    """Decode attention over `cache[0:pos] ⊕ current token`. Returns [B, H, D].

    The current token's k/v ride as separate operands so the cache write can
    happen ONCE outside the per-layer scan — rewriting the full cache per
    layer per token is the dominant HBM waste in a naive decode loop (see
    models/llama.py decode_step)."""
    B, H, D = q.shape
    S = k_cache.shape[1]
    K = k_cache.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)

    qf = q.astype(jnp.float32).reshape(B, K, G, D)
    scores = jnp.einsum(
        "bkgd,bskd->bkgs", qf, k_cache.astype(jnp.float32)
    ) * scale  # [B, K, G, S]
    # Cache rows at/after `positions` are stale (the current row is written
    # after the layer scan); mask them and score the current token separately.
    valid = jnp.arange(S)[None, :] < positions[:, None]  # [B, S]
    scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    cur = jnp.einsum(
        "bkgd,bkd->bkg", qf, k_new.astype(jnp.float32)
    )[..., None] * scale  # [B, K, G, 1]
    probs = jax.nn.softmax(jnp.concatenate([scores, cur], axis=-1), axis=-1)
    out = jnp.einsum(
        "bkgs,bskd->bkgd", probs[..., :S], v_cache.astype(jnp.float32)
    ) + probs[..., S:] * v_new.astype(jnp.float32)[:, :, None, :]
    return out.reshape(B, H, D).astype(q.dtype)


def decode_attention_windowed(
    q: jnp.ndarray,  # [B, H, D] current token's query
    k_cache: jnp.ndarray,  # [B, S, K, D] — READ-ONLY cache (pre-block rows)
    v_cache: jnp.ndarray,
    k_local: jnp.ndarray,  # [B, n, K, D] — this decode block's earlier tokens
    v_local: jnp.ndarray,
    k_new: jnp.ndarray,  # [B, K, D] current token
    v_new: jnp.ndarray,
    positions: jnp.ndarray,  # [B] current token's position
    step: jnp.ndarray,  # scalar: index of the current token within the block
) -> jnp.ndarray:
    """Decode attention over `cache[0:block_start] ⊕ local[0:step] ⊕ current`.

    Inside a fused N-step decode block the cache stays READ-ONLY (its
    in-block rows live in the local window), so the block's lax.scan carries
    only the tiny local buffer — the full cache is written ONCE per block.
    Profiling showed the carried-cache alternative costs a full cache copy
    per token (engine VERDICT-weak decode path)."""
    B, H, D = q.shape
    S = k_cache.shape[1]
    n = k_local.shape[1]
    K = k_cache.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)

    qf = (q.astype(jnp.float32) * scale).reshape(B, K, G, D)
    block_start = positions - step  # [B]
    sc = jnp.einsum("bkgd,bskd->bkgs", qf, k_cache.astype(jnp.float32))
    valid_c = jnp.arange(S)[None, :] < block_start[:, None]
    sc = jnp.where(valid_c[:, None, None, :], sc, NEG_INF)
    sl = jnp.einsum("bkgd,bnkd->bkgn", qf, k_local.astype(jnp.float32))
    valid_l = jnp.arange(n) < step  # [n] — same for every slot
    sl = jnp.where(valid_l[None, None, None, :], sl, NEG_INF)
    cur = jnp.einsum("bkgd,bkd->bkg", qf, k_new.astype(jnp.float32))[..., None]
    probs = jax.nn.softmax(jnp.concatenate([sc, sl, cur], axis=-1), axis=-1)
    out = (
        jnp.einsum("bkgs,bskd->bkgd", probs[..., :S], v_cache.astype(jnp.float32))
        + jnp.einsum("bkgn,bnkd->bkgd", probs[..., S:S + n], v_local.astype(jnp.float32))
        + probs[..., S + n:] * v_new.astype(jnp.float32)[:, :, None, :]
    )
    return out.reshape(B, H, D).astype(q.dtype)


def decode_attention(
    q: jnp.ndarray,  # [B, H, D] query for the single new token per slot
    k_cache: jnp.ndarray,  # [B, S_max, K, D]
    v_cache: jnp.ndarray,  # [B, S_max, K, D]
    cache_len: jnp.ndarray,  # [B] int32: number of valid cache entries (incl. current token)
) -> jnp.ndarray:
    """Single-step attention against the slot cache. Returns [B, H, D]."""
    B, H, D = q.shape
    S = k_cache.shape[1]
    K = k_cache.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)

    qf = q.astype(jnp.float32).reshape(B, K, G, D)
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)

    scores = jnp.einsum("bkgd,bskd->bkgs", qf, kf) * scale  # [B, K, G, S]
    valid = jnp.arange(S)[None, :] < cache_len[:, None]  # [B, S]
    scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, vf)
    return out.reshape(B, H, D).astype(q.dtype)
