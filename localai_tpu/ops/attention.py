"""Attention ops: batched causal prefill and single-token decode against a
slot KV cache.

Design notes (TPU-first):
- Prefill attention is a dense causal softmax-attention over the bucketed
  prompt length. XLA fuses the mask+softmax chain; a Pallas flash-attention
  kernel (localai_tpu.ops.flash) can be swapped in for long buckets.
- Decode attention reads the whole slot cache [B, S_max, K, H] with a length
  mask. This is the JAX equivalent of llama.cpp's unified KV cache read in
  its slot loop (reference: backend/cpp/llama-cpp/grpc-server.cpp:679
  PredictStream -> server slots); instead of per-slot pointers we use one
  dense cache and mask, which keeps shapes static under jit.
- GQA: queries have H heads, cache has K kv-heads, H % K == 0; we reshape
  queries to [B, K, H//K, ...] and broadcast the cache.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# Declared ICI-collective boundary (lint: sharding-consistency). The ONLY
# function in this module allowed to issue cross-chip collectives is the
# sp-axis flash-decoding combine — everything else must stay collective-free
# so the per-token path pays ICI exclusively at the o/down projections
# (GSPMD psums from the row-parallel specs in parallel/sharding.py).
COLLECTIVE_BOUNDARY = ("_sp_cache_partials",)


def softcap_scores(sc: jnp.ndarray, cap: float) -> jnp.ndarray:
    """Gemma-2 attention-logit softcapping: cap·tanh(sc/cap). Applied BEFORE
    masking (tanh of NEG_INF would be finite and corrupt the mask)."""
    return cap * jnp.tanh(sc / cap)


def _tp_degree(mesh) -> int:
    """Tensor-parallel degree of a mesh (0/1 when absent) — the gate for the
    head-sharded shard_map kernel paths (ISSUE 7)."""
    if mesh is None:
        return 1
    return int(mesh.shape.get("tp", 1))


def _head_shard_map(fn, mesh, in_specs, out_specs):
    """shard_map over the mesh's "tp" axis for per-head kernels. Pallas
    custom calls are opaque to the SPMD partitioner — under a tp-sharded
    GSPMD program XLA would all-gather their operands per call, exactly the
    per-token collective the sharded engine must not pay. Wrapping the
    kernel in shard_map hands each chip its OWN heads' q/k/v (and paged-pool
    shard) and runs the unmodified kernel on local shapes; no collective is
    introduced — the psum stays at the o-projection where GSPMD already puts
    it (row-parallel wo, parallel/sharding.py)."""
    from localai_tpu.parallel.mesh import shard_map as _shard_map

    return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False)


def prefill_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, K, D]
    v: jnp.ndarray,  # [B, S, K, D]
    length_mask: jnp.ndarray | None,  # [B, S] bool
    lengths: jnp.ndarray | None = None,  # [B] int32 (enables flash path)
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,  # traced bool scalar: this layer uses the sliding window
    mesh=None,  # Mesh with tp>1 → flash kernel head-sharded under shard_map
) -> jnp.ndarray:
    """Prefill attention dispatcher: Pallas flash kernel on TPU by default
    (opt out with LOCALAI_FLASH=0), dense math otherwise. Softcapping /
    sliding windows (gemma-2) force the dense path. With a tp>1 mesh the
    flash kernel runs head-sharded under shard_map (each chip computes its
    own heads; the dense-math path needs nothing — GSPMD partitions plain
    einsums over the head axis by propagation)."""
    S = q.shape[1]
    if (
        lengths is not None
        and not softcap
        and not window
        and os.environ.get("LOCALAI_FLASH", "1") != "0"
        and jax.default_backend() == "tpu"
        and (S & (S - 1)) == 0  # power-of-two bucket, divisible by any block
    ):
        from localai_tpu.ops.flash import flash_block_sizes, flash_prefill_attention

        bq, bk = flash_block_sizes(S)
        if _tp_degree(mesh) > 1:
            from jax.sharding import PartitionSpec as P

            fn = _head_shard_map(
                lambda qs, ks, vs, ln: flash_prefill_attention(
                    qs, ks, vs, ln, block_q=bq, block_k=bk
                ),
                mesh,
                in_specs=(P(None, None, "tp", None), P(None, None, "tp", None),
                          P(None, None, "tp", None), P(None)),
                out_specs=P(None, None, "tp", None),
            )
            return fn(q, k, v, lengths)
        return flash_prefill_attention(q, k, v, lengths, block_q=bq, block_k=bk)
    return causal_prefill_attention(q, k, v, length_mask, softcap=softcap,
                                    window=window, sliding=sliding)


def causal_prefill_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, K, D]
    v: jnp.ndarray,  # [B, S, K, D]
    length_mask: jnp.ndarray | None = None,  # [B, S] bool, True = valid token
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,
) -> jnp.ndarray:
    """Dense causal attention for prompt processing. Returns [B, S, H, D]."""
    B, S, H, D = q.shape
    K = k.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)

    qf = q.astype(jnp.float32).reshape(B, S, K, G, D)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    # scores: [B, K, G, S_q, S_k]
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qf, kf) * scale
    if softcap:
        scores = softcap_scores(scores, softcap)
    causal = jnp.tril(jnp.ones((S, S), dtype=bool))
    if window and sliding is not None:
        dist = jnp.arange(S)[:, None] - jnp.arange(S)[None, :]  # q_pos - k_pos
        causal = causal & (~sliding | (dist < window))
    mask = causal[None, None, None, :, :]
    if length_mask is not None:
        mask = jnp.logical_and(mask, length_mask[:, None, None, None, :])
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, vf)
    return out.reshape(B, S, H, D).astype(q.dtype)


def decode_attention_appended(
    q: jnp.ndarray,  # [B, H, D] query for the single new token per slot
    k_cache: jnp.ndarray,  # [B, S_max, K, D] — cache WITHOUT the current token
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,  # [B, K, D] current token's key (not yet in the cache)
    v_new: jnp.ndarray,
    positions: jnp.ndarray,  # [B] int32 position of the current token
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,
) -> jnp.ndarray:
    """Decode attention over `cache[0:pos] ⊕ current token`. Returns [B, H, D].

    The current token's k/v ride as separate operands so the cache write can
    happen ONCE outside the per-layer scan — rewriting the full cache per
    layer per token is the dominant HBM waste in a naive decode loop (see
    models/llama.py decode_step)."""
    B, H, D = q.shape
    S = k_cache.shape[1]
    K = k_cache.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)

    qf = q.astype(jnp.float32).reshape(B, K, G, D)
    scores = jnp.einsum(
        "bkgd,bskd->bkgs", qf, k_cache.astype(jnp.float32)
    ) * scale  # [B, K, G, S]
    if softcap:
        scores = softcap_scores(scores, softcap)
    # Cache rows at/after `positions` are stale (the current row is written
    # after the layer scan); mask them and score the current token separately.
    valid = jnp.arange(S)[None, :] < positions[:, None]  # [B, S]
    if window and sliding is not None:
        dist = positions[:, None] - jnp.arange(S)[None, :]
        valid = valid & (~sliding | (dist < window))
    scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    cur = jnp.einsum(
        "bkgd,bkd->bkg", qf, k_new.astype(jnp.float32)
    )[..., None] * scale  # [B, K, G, 1]
    if softcap:
        cur = softcap_scores(cur, softcap)
    probs = jax.nn.softmax(jnp.concatenate([scores, cur], axis=-1), axis=-1)
    out = jnp.einsum(
        "bkgs,bskd->bkgd", probs[..., :S], v_cache.astype(jnp.float32)
    ) + probs[..., S:] * v_new.astype(jnp.float32)[:, :, None, :]
    return out.reshape(B, H, D).astype(q.dtype)


def decode_attention_windowed(
    q: jnp.ndarray,  # [B, H, D] current token's query
    k_cache: jnp.ndarray,  # [B, S, K, D] — READ-ONLY cache (pre-block rows)
    v_cache: jnp.ndarray,
    k_local: jnp.ndarray,  # [B, n, K, D] — this decode block's earlier tokens
    v_local: jnp.ndarray,
    k_new: jnp.ndarray,  # [B, K, D] current token
    v_new: jnp.ndarray,
    positions: jnp.ndarray,  # [B] current token's position
    step: jnp.ndarray,  # scalar: index of the current token within the block
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,  # traced bool scalar: this layer uses the sliding window
    sink: int = 0,  # windowed+sink decode (docs/LONG_CONTEXT.md): rows
    swin: int = 0,  # attended iff gpos < sink or q_pos - gpos < swin
) -> jnp.ndarray:
    """Decode attention over `cache[0:block_start] ⊕ local[0:step] ⊕ current`.

    Inside a fused N-step decode block the cache stays READ-ONLY (its
    in-block rows live in the local window), so the block's lax.scan carries
    only the tiny local buffer — the full cache is written ONCE per block.
    Profiling showed the carried-cache alternative costs a full cache copy
    per token (engine VERDICT-weak decode path)."""
    B, H, D = q.shape
    S = k_cache.shape[1]
    n = k_local.shape[1]
    K = k_cache.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)

    qf = (q.astype(jnp.float32) * scale).reshape(B, K, G, D)
    block_start = positions - step  # [B]
    sc = jnp.einsum("bkgd,bskd->bkgs", qf, k_cache.astype(jnp.float32))
    if softcap:
        sc = softcap_scores(sc, softcap)
    valid_c = jnp.arange(S)[None, :] < block_start[:, None]
    if window and sliding is not None:
        # q position is `positions`; cache row s sits at position s.
        dist_c = positions[:, None] - jnp.arange(S)[None, :]
        valid_c = valid_c & (~sliding | (dist_c < window))
    if swin:
        dist_c = positions[:, None] - jnp.arange(S)[None, :]
        valid_c = valid_c & ((jnp.arange(S)[None, :] < sink) | (dist_c < swin))
    sc = jnp.where(valid_c[:, None, None, :], sc, NEG_INF)
    sl = jnp.einsum("bkgd,bnkd->bkgn", qf, k_local.astype(jnp.float32))
    if softcap:
        sl = softcap_scores(sl, softcap)
    valid_l = jnp.arange(n) < step  # [n] — same for every slot
    if window and sliding is not None:
        # local row i sits at distance step - i from the current token.
        valid_l = valid_l & (~sliding | ((step - jnp.arange(n)) < window))
    valid_l = jnp.broadcast_to(valid_l[None, :], (B, n))
    if swin:
        dist_l = (step - jnp.arange(n))[None, :]
        gpos_l = positions[:, None] - dist_l
        valid_l = valid_l & ((gpos_l < sink) | (dist_l < swin))
    sl = jnp.where(valid_l[:, None, None, :], sl, NEG_INF)
    cur = jnp.einsum("bkgd,bkd->bkg", qf, k_new.astype(jnp.float32))[..., None]
    if softcap:
        cur = softcap_scores(cur, softcap)
    probs = jax.nn.softmax(jnp.concatenate([sc, sl, cur], axis=-1), axis=-1)
    out = (
        jnp.einsum("bkgs,bskd->bkgd", probs[..., :S], v_cache.astype(jnp.float32))
        + jnp.einsum("bkgn,bnkd->bkgd", probs[..., S:S + n], v_local.astype(jnp.float32))
        + probs[..., S + n:] * v_new.astype(jnp.float32)[:, :, None, :]
    )
    return out.reshape(B, H, D).astype(q.dtype)


def _sp_cache_partials(q, k_cache, v_cache, limits, mesh,
                       softcap: float = 0.0, window: int = 0, sliding=None,
                       q_pos=None, sink: int = 0, swin: int = 0):
    """Online-softmax partial attention over an "sp"-sharded cache.

    The KV cache's sequence axis is sharded over the mesh's "sp" axis (see
    parallel/sharding.py cache_specs), so each chip holds S/sp rows and HBM
    residency — the serving-side half of the long-context story whose compute
    half is ring prefill (parallel/ring.py). Each shard computes its local
    (max, sum-exp, weighted-acc) over rows with global index < limits[b] and
    the three small partials combine with one pmax + two psums over "sp" —
    flash-decoding across chips, riding ICI.

    q: [B, H, D]; k/v_cache: [B, S, K, D] (S sp-sharded); limits: [B] row
    bound per slot. softcap/window/sliding are the gemma-2 semantics
    (softcap BEFORE masking; sliding layers mask rows further than `window`
    below the query's position `q_pos` [B]). Returns (acc [B, K, G, D],
    m [B, K, G, 1], l [B, K, G, 1]) replicated over sp, f32, with the
    1/sqrt(D) scale already applied to q.
    """
    from functools import partial

    from jax.sharding import PartitionSpec as P

    B, H, D = q.shape
    K = k_cache.shape[2]
    scale = 1.0 / (D**0.5)
    if q_pos is None:
        q_pos = limits  # plain decode: the query sits right after the rows

    def local(qb, kc, vc, lim, qp, sl):
        Bl, Hl, D_ = qb.shape
        Kl = kc.shape[2]
        G = Hl // Kl
        S_l = kc.shape[1]
        my = jax.lax.axis_index("sp")
        gpos = my * S_l + jnp.arange(S_l)  # global row indices of this shard
        qf = (qb.astype(jnp.float32) * scale).reshape(Bl, Kl, G, D_)
        sc = jnp.einsum("bkgd,bskd->bkgs", qf, kc.astype(jnp.float32))
        if softcap:
            sc = softcap_scores(sc, softcap)
        valid = gpos[None, :] < lim[:, None]
        if window and sliding is not None:
            dist = qp[:, None] - gpos[None, :]
            valid = valid & (~sl | (dist < window))
        if swin:
            dist = qp[:, None] - gpos[None, :]
            valid = valid & ((gpos[None, :] < sink) | (dist < swin))
        sc = jnp.where(valid[:, None, None, :], sc, NEG_INF)
        m = jnp.max(sc, axis=-1, keepdims=True)
        p = jnp.exp(sc - m)  # exp(NEG_INF - NEG_INF) rows zeroed by valid below
        p = jnp.where(valid[:, None, None, :], p, 0.0)
        l = jnp.sum(p, axis=-1, keepdims=True)
        acc = jnp.einsum("bkgs,bskd->bkgd", p, vc.astype(jnp.float32))
        m_g = jax.lax.pmax(m, "sp")
        alpha = jnp.exp(jnp.maximum(m - m_g, -80.0))  # -inf - -inf guard
        alpha = jnp.where(l > 0, alpha, 0.0)
        l_g = jax.lax.psum(l * alpha, "sp")
        acc_g = jax.lax.psum(acc * alpha, "sp")
        return acc_g, m_g, l_g

    # The sliding flag is a traced per-layer scalar — it rides as an explicit
    # replicated operand (closure capture of tracers is not valid under
    # shard_map).
    sl_in = sliding if sliding is not None else jnp.zeros((), bool)
    from localai_tpu.parallel.mesh import shard_map as _shard_map

    fn = _shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P("dp", "tp", None),
            P("dp", "sp", "tp", None),
            P("dp", "sp", "tp", None),
            P("dp"),
            P("dp"),
            P(),
        ),
        out_specs=(
            P("dp", "tp", None, None),
            P("dp", "tp", None, None),
            P("dp", "tp", None, None),
        ),
        check_vma=False,
    )
    return fn(q, k_cache, v_cache, limits, q_pos, sl_in)


def _merge_partials(q, acc_g, m_g, l_g, extra_k, extra_v, extra_mask,
                    softcap: float = 0.0):
    """Merge sharded-cache partials with a small dense tail (local window
    and/or the current token). extra_k: [B, E, K, D]; extra_mask: [B, E] or
    [E]. Returns [B, H, D] in q's dtype."""
    B, H, D = q.shape
    K = extra_k.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)
    qf = (q.astype(jnp.float32) * scale).reshape(B, K, G, D)
    se = jnp.einsum("bkgd,bekd->bkge", qf, extra_k.astype(jnp.float32))
    if softcap:
        se = softcap_scores(se, softcap)
    if extra_mask.ndim == 1:
        extra_mask = extra_mask[None, :]
    se = jnp.where(extra_mask[:, None, None, :], se, NEG_INF)
    m_e = jnp.max(se, axis=-1, keepdims=True)
    m_tot = jnp.maximum(m_g, m_e)
    p_e = jnp.exp(se - m_tot)
    p_e = jnp.where(extra_mask[:, None, None, :], p_e, 0.0)
    w_c = jnp.exp(jnp.maximum(m_g - m_tot, -80.0))
    w_c = jnp.where(l_g > 0, w_c, 0.0)
    num = acc_g * w_c + jnp.einsum("bkge,bekd->bkgd", p_e, extra_v.astype(jnp.float32))
    den = l_g * w_c + jnp.sum(p_e, axis=-1, keepdims=True)
    out = num / jnp.maximum(den, 1e-30)
    return out.reshape(B, H, D).astype(q.dtype)


def decode_attention_appended_sp(
    q: jnp.ndarray,  # [B, H, D]
    k_cache: jnp.ndarray,  # [B, S, K, D] — sequence axis sharded over "sp"
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,  # [B, K, D]
    v_new: jnp.ndarray,
    positions: jnp.ndarray,  # [B]
    mesh,
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,
) -> jnp.ndarray:
    """`decode_attention_appended` for an sp-sharded cache (see
    _sp_cache_partials). The current token is merged host-of-shard-map side
    since it is replicated over sp."""
    acc_g, m_g, l_g = _sp_cache_partials(
        q, k_cache, v_cache, positions, mesh,
        softcap=softcap, window=window, sliding=sliding, q_pos=positions,
    )
    ones = jnp.ones((q.shape[0], 1), bool)
    return _merge_partials(q, acc_g, m_g, l_g, k_new[:, None], v_new[:, None],
                           ones, softcap=softcap)


def decode_attention_windowed_sp(
    q: jnp.ndarray,  # [B, H, D]
    k_cache: jnp.ndarray,  # [B, S, K, D] — sequence axis sharded over "sp"
    v_cache: jnp.ndarray,
    k_local: jnp.ndarray,  # [B, n, K, D] block-local window (replicated)
    v_local: jnp.ndarray,
    k_new: jnp.ndarray,  # [B, K, D]
    v_new: jnp.ndarray,
    positions: jnp.ndarray,  # [B]
    step: jnp.ndarray,  # scalar
    mesh,
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,
    sink: int = 0,  # windowed+sink decode (docs/LONG_CONTEXT.md)
    swin: int = 0,
) -> jnp.ndarray:
    """`decode_attention_windowed` for an sp-sharded cache: sharded partials
    over cache[0:block_start], dense merge of the block-local window and the
    current token (both tiny and replicated)."""
    n = k_local.shape[1]
    acc_g, m_g, l_g = _sp_cache_partials(
        q, k_cache, v_cache, positions - step, mesh,
        softcap=softcap, window=window, sliding=sliding, q_pos=positions,
        sink=sink, swin=swin,
    )
    # f32 concat: the block-local window may live in the cache's storage
    # dtype (fp8 KV) while the current token is model-dtype.
    ek = jnp.concatenate([k_local.astype(jnp.float32),
                          k_new[:, None].astype(jnp.float32)], axis=1)
    ev = jnp.concatenate([v_local.astype(jnp.float32),
                          v_new[:, None].astype(jnp.float32)], axis=1)
    mask = jnp.concatenate(
        [jnp.arange(n) < step, jnp.ones((1,), bool)], axis=0
    )  # [n+1] — same for every slot
    if window and sliding is not None:
        # Local row i sits `step - i` behind the query; the current token is
        # distance 0. (The window bound never trips for these in practice —
        # n << window — but the mask keeps the semantics exact.)
        dist = jnp.concatenate([step - jnp.arange(n), jnp.zeros((1,), jnp.int32)])
        mask = mask & (~sliding | (dist < window))
    if swin:
        dist = jnp.concatenate(
            [step - jnp.arange(n), jnp.zeros((1,), jnp.int32)]
        )[None, :]
        gpos = positions[:, None] - dist
        mask = mask[None, :] & ((gpos < sink) | (dist < swin))
    return _merge_partials(q, acc_g, m_g, l_g, ek, ev, mask, softcap=softcap)


def decode_attention(
    q: jnp.ndarray,  # [B, H, D] query for the single new token per slot
    k_cache: jnp.ndarray,  # [B, S_max, K, D]
    v_cache: jnp.ndarray,  # [B, S_max, K, D]
    cache_len: jnp.ndarray,  # [B] int32: number of valid cache entries (incl. current token)
) -> jnp.ndarray:
    """Single-step attention against the slot cache. Returns [B, H, D]."""
    B, H, D = q.shape
    S = k_cache.shape[1]
    K = k_cache.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)

    qf = q.astype(jnp.float32).reshape(B, K, G, D)
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)

    scores = jnp.einsum("bkgd,bskd->bkgs", qf, kf) * scale  # [B, K, G, S]
    valid = jnp.arange(S)[None, :] < cache_len[:, None]  # [B, S]
    scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, vf)
    return out.reshape(B, H, D).astype(q.dtype)


# --------------------------------------------------------------------------- #
# Paged KV cache (vLLM-style page pool, XLA-native flash-decoding over pages)
# --------------------------------------------------------------------------- #


def _sink_window_cols(limits, q_min, page, MP, sink, swin):
    """Per-slot walk plan for windowed+sink attention (ISSUE 14): page
    columns outside `[0, ceil(sink/page)) ∪ [win_lo, np_live)` can never be
    attended (a row is live iff `gpos < sink` or `q_pos - gpos < swin`, and
    q_pos only grows), so the walk skips them entirely — the whole point of
    spilling cold middle pages to the host tier. Returns (sink_cols [B],
    win_lo [B], n_cols [B]): column j of the walk maps to table column
    `j < sink_cols ? j : j + (win_lo - sink_cols)`.

    Skipping is EXACT, not approximate: a skipped page's scores would be
    NEG_INF under the mask, contributing zero to (acc, l) and leaving m
    unchanged — identical online-softmax state either way."""
    sink_pages = -(-sink // page) if sink else 0
    np_live = jnp.minimum((limits + page - 1) // page, MP)
    sink_cols = jnp.minimum(sink_pages, np_live)
    win_lo = jnp.clip((q_min - swin + 1) // page, 0, np_live)
    win_lo = jnp.maximum(win_lo, sink_cols)
    return sink_cols, win_lo, sink_cols + np_live - win_lo


def _paged_cache_partials(q, k_pool, v_pool, table, limits,
                          softcap: float = 0.0, window: int = 0, sliding=None,
                          q_pos=None, kv_scale=None, sink: int = 0,
                          swin: int = 0):
    """Online-softmax partials over a paged cache — the static-shape TPU
    answer to ragged/paged KV (SURVEY §7; reference: llama.cpp's per-slot
    contiguous cache, vLLM's PagedAttention): HBM holds one shared page pool
    [P, page, K, D] and each slot attends only the pages its table lists.
    A fori_loop walks the table PAGE_CHUNK columns at a time, gathering a
    [B, CH·page, K, D] tile per step — the dense [B, S] view never
    materializes, and the trip count is bounded by the LONGEST live context
    in the batch (ceil(max(limits)/page/CH)), so per-step bandwidth scales
    with what is actually resident, not max_seq.

    q: [B, H, D]; k/v_pool: [P, page, K, D]; table: [B, MP] int32 page ids,
    or the hierarchical (l1, l0) pair (ops/ptable — a 1M-token slot's table
    resolves through an L1 directory instead of one giant row);
    limits: [B] — rows with global index >= limits[b] are masked.
    softcap/window/sliding: gemma-2 semantics (softcap BEFORE masking;
    sliding layers mask rows further than `window` below `q_pos` [B]).
    sink/swin: engine-level windowed+sink decode (docs/LONG_CONTEXT.md) —
    a row is attended iff `gpos < sink` or `q_pos - gpos < swin`; the walk
    additionally SKIPS page columns that are entirely masked (cold middle
    pages — possibly spilled off-device), per slot.
    kv_scale: optional [2, K] f32 per-head (k, v) dequant scales for a
    scaled fp8 pool (ISSUE 9) — applied to the gathered tile right at the
    convert, so XLA fuses cast+scale into the einsum's operand load and the
    dequantized copy never round-trips HBM (mirrors the in-register dequant
    the Pallas kernel does on its VMEM tile).
    Returns (acc [B, K, G, D], m [B, K, G, 1], l [B, K, G, 1]) f32, scale
    applied.
    """
    from localai_tpu.ops import ptable as _pt

    B, H, D = q.shape
    page = k_pool.shape[1]
    K = k_pool.shape[2]
    G = H // K
    MP = _pt.width(table)
    scale = 1.0 / (D**0.5)
    qf = (q.astype(jnp.float32) * scale).reshape(B, K, G, D)
    if q_pos is None:
        q_pos = limits

    # Pages walk in chunks of PAGE_CHUNK columns per loop step. One page per
    # step is latency-bound at long context — each iteration is a tiny
    # gather + einsum serialized through the running softmax state, and a
    # 32k context is 256 sequential iterations PER LAYER (measured ~2 tok/s
    # at 32k bs1). Chunking turns that into 32 steps of MXU-sized work.
    CH = min(8, MP)
    if swin:
        sink_cols, win_lo, n_cols = _sink_window_cols(
            limits, q_pos, page, MP, sink, swin
        )

    def body(p, carry):
        m, l, acc = carry
        j = p * CH + jnp.arange(CH)  # [CH] walk columns this step
        if swin:
            # Cold-middle skip: remap walk column → table column per slot.
            cols = jnp.where(j[None, :] < sink_cols[:, None], j[None, :],
                             j[None, :] + (win_lo - sink_cols)[:, None])
            col_ok = j[None, :] < n_cols[:, None]  # [B, CH]
        else:
            cols = jnp.broadcast_to(j[None, :], (B, CH))
            col_ok = jnp.broadcast_to((j < MP)[None, :], (B, CH))
        pids = _pt.gather_cols(table, jnp.minimum(cols, MP - 1))  # [B, CH]
        kp = k_pool[pids].astype(jnp.float32)  # [B, CH, page, K, D]
        vp = v_pool[pids].astype(jnp.float32)
        if kv_scale is not None:  # in-register fp8 dequant (fused into cast)
            kp = kp * kv_scale[0][None, None, None, :, None]
            vp = vp * kv_scale[1][None, None, None, :, None]
        kp = kp.reshape(B, CH * page, K, D)
        vp = vp.reshape(B, CH * page, K, D)
        sc = jnp.einsum("bkgd,bskd->bkgs", qf, kp)
        if softcap:
            sc = softcap_scores(sc, softcap)
        # global rows covered by this chunk (clamped duplicate columns are
        # masked out via col_ok, never double-counted)
        gpos = (cols[:, :, None] * page
                + jnp.arange(page)[None, None, :]).reshape(B, -1)
        valid = (gpos < limits[:, None]) & jnp.repeat(col_ok, page, axis=1)
        if window and sliding is not None:
            dist = q_pos[:, None] - gpos
            valid = valid & (~sliding | (dist < window))
        if swin:
            valid = valid & ((gpos < sink) | ((q_pos[:, None] - gpos) < swin))
        sc = jnp.where(valid[:, None, None, :], sc, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sc, axis=-1, keepdims=True))
        alpha = jnp.exp(jnp.maximum(m - m_new, -80.0))
        pr = jnp.exp(sc - m_new)
        pr = jnp.where(valid[:, None, None, :], pr, 0.0)
        l = l * alpha + jnp.sum(pr, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bkgs,bskd->bkgd", pr, vp)
        return m_new, l, acc

    m0 = jnp.full((B, K, G, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, K, G, 1), jnp.float32)
    a0 = jnp.zeros((B, K, G, D), jnp.float32)
    if swin:
        p_hi = jnp.max(n_cols).astype(jnp.int32)
    else:
        p_hi = jnp.minimum(
            (jnp.max(limits) + page - 1) // page, MP
        ).astype(jnp.int32)
    ch_hi = (p_hi + CH - 1) // CH
    m, l, acc = jax.lax.fori_loop(0, ch_hi, body, (m0, l0, a0))
    return acc, m, l


def _paged_pallas_sharded(kernel_fn, mesh, q, k_pool, v_pool, table, limits,
                          q_pos, sliding, mq: bool, kv_scale=None):
    """Run a Pallas paged-partials kernel head-sharded over the mesh's "tp"
    axis (ISSUE 7): q splits on its head axis, the pool on its kv-head axis
    (the layout the engine stores it in — pages live on the head shard that
    owns them), the page table/limits replicate (they are host-built i32
    control state, KBs), and the partials come back head-sharded for the
    (GSPMD-handled) o-projection psum. The kernel body is unchanged — it
    just sees K/tp kv heads. `sliding` is a traced per-layer scalar, so it
    rides as an explicit replicated operand (closure capture of tracers is
    not valid under shard_map)."""
    from jax.sharding import PartitionSpec as P

    from localai_tpu.ops import ptable as _pt

    sl_in = sliding if sliding is not None else jnp.zeros((), bool)
    # kv scales ride sharded on their head axis like the pool itself; ones
    # when the pool is unscaled (the kernel's multiply is exact identity).
    kvs = (jnp.ones((2, k_pool.shape[2]), jnp.float32) if kv_scale is None
           else kv_scale.astype(jnp.float32))

    def local(qs, kp, vp, tbl, lim, qp, sl, sc):
        return kernel_fn(qs, kp, vp, tbl, lim, q_pos=qp,
                         sliding=sl if sliding is not None else None,
                         kv_scale=sc)

    q_spec = P(None, None, "tp", None) if mq else P(None, "tp", None)
    qp_spec = P(None, None) if mq else P(None)
    # Flat tables are one replicated [B, MP] operand; the hierarchical pair
    # replicates both levels (host-built i32 control state, KBs).
    tbl_spec = _pt.shard_spec(table, P(None, None), P(None, None))
    out_specs = tuple(
        P(None, "tp", *([None] * (3 if mq else 2))) for _ in range(3)
    )
    fn = _head_shard_map(
        local, mesh,
        in_specs=(q_spec, P(None, None, "tp", None), P(None, None, "tp", None),
                  tbl_spec, P(None), qp_spec, P(), P(None, "tp")),
        out_specs=out_specs,
    )
    return fn(q, k_pool, v_pool, table, limits, q_pos, sl_in, kvs)


def paged_partials(q, k_pool, v_pool, table, limits, softcap: float = 0.0,
                   window: int = 0, sliding=None, q_pos=None,
                   impl: str = "auto", mesh=None, kv_scale=None,
                   sink: int = 0, swin: int = 0):
    """Paged online-softmax partials, dispatched: the fused Pallas ragged
    paged-attention kernel (ops/paged_flash — pages stream HBM→VMEM once,
    walk bounded per slot) or the XLA gather walk below (reference path and
    numeric oracle). Off-TPU the kernel runs in interpret mode, so CPU tier-1
    tests exercise the same kernel code that compiles for TPU. With a tp>1
    mesh the Pallas kernel runs head-sharded under shard_map (the XLA walk
    needs nothing — its gathers/einsums partition over the kv-head axis by
    GSPMD propagation, no collectives). sink/swin: windowed+sink mask +
    cold-page skip (ISSUE 14), identical semantics in both backends."""
    import functools

    from localai_tpu.ops.paged_flash import paged_decode_partials, use_pallas

    if use_pallas(impl):
        interp = jax.default_backend() != "tpu"
        if _tp_degree(mesh) > 1:
            return _paged_pallas_sharded(
                functools.partial(paged_decode_partials, softcap=softcap,
                                  window=window, interpret=interp,
                                  sink=sink, swin=swin),
                mesh, q, k_pool, v_pool, table, limits,
                limits if q_pos is None else q_pos, sliding, mq=False,
                kv_scale=kv_scale,
            )
        return paged_decode_partials(
            q, k_pool, v_pool, table, limits, softcap=softcap, window=window,
            sliding=sliding, q_pos=q_pos, interpret=interp, kv_scale=kv_scale,
            sink=sink, swin=swin,
        )
    return _paged_cache_partials(
        q, k_pool, v_pool, table, limits,
        softcap=softcap, window=window, sliding=sliding, q_pos=q_pos,
        kv_scale=kv_scale, sink=sink, swin=swin,
    )


def paged_partials_mq(q, k_pool, v_pool, table, limits, softcap: float = 0.0,
                      window: int = 0, sliding=None, q_pos=None,
                      impl: str = "auto", mesh=None, kv_scale=None,
                      sink: int = 0, swin: int = 0):
    """Multi-query `paged_partials` (speculative verify chunk) — same
    dispatch."""
    import functools

    from localai_tpu.ops.paged_flash import (
        paged_decode_partials_mq,
        use_pallas,
    )

    if use_pallas(impl):
        interp = jax.default_backend() != "tpu"
        if _tp_degree(mesh) > 1:
            T = q.shape[1]
            qp = (jnp.broadcast_to(limits[:, None], (q.shape[0], T))
                  if q_pos is None else q_pos)
            return _paged_pallas_sharded(
                functools.partial(paged_decode_partials_mq, softcap=softcap,
                                  window=window, interpret=interp,
                                  sink=sink, swin=swin),
                mesh, q, k_pool, v_pool, table, limits, qp, sliding, mq=True,
                kv_scale=kv_scale,
            )
        return paged_decode_partials_mq(
            q, k_pool, v_pool, table, limits, softcap=softcap, window=window,
            sliding=sliding, q_pos=q_pos, interpret=interp, kv_scale=kv_scale,
            sink=sink, swin=swin,
        )
    return _paged_cache_partials_mq(
        q, k_pool, v_pool, table, limits,
        softcap=softcap, window=window, sliding=sliding, q_pos=q_pos,
        kv_scale=kv_scale, sink=sink, swin=swin,
    )


def paged_prefill_partials(q, k_pool, v_pool, table, limits,
                           softcap: float = 0.0, window: int = 0,
                           sliding=None, q_pos=None, impl: str = "auto",
                           mesh=None, kv_scale=None, sink: int = 0,
                           swin: int = 0):
    """Paged partials for a PREFILL CHUNK (models/llama.prefill_chunk_paged):
    q [B, T, H, D] covers a whole chunk, limits[b] is the rows already
    resident (the chunk's start offset). Same dispatch as paged_partials_mq,
    but the Pallas side tiles the chunk's query rows so any chunk size fits
    the kernel's VMEM running state (ops/paged_flash.paged_prefill_partials_mq).
    With a tp>1 mesh the tiled kernel runs head-sharded under shard_map.
    sink/swin bound the prefix walk to the sink pages + trailing window —
    what makes a 512k-token chunked prefill linear instead of quadratic."""
    import functools

    from localai_tpu.ops.paged_flash import (
        paged_prefill_partials_mq,
        use_pallas,
    )

    if use_pallas(impl):
        interp = jax.default_backend() != "tpu"
        if _tp_degree(mesh) > 1:
            T = q.shape[1]
            qp = (jnp.broadcast_to(limits[:, None], (q.shape[0], T))
                  if q_pos is None else q_pos)
            return _paged_pallas_sharded(
                functools.partial(paged_prefill_partials_mq, softcap=softcap,
                                  window=window, interpret=interp,
                                  sink=sink, swin=swin),
                mesh, q, k_pool, v_pool, table, limits, qp, sliding, mq=True,
                kv_scale=kv_scale,
            )
        return paged_prefill_partials_mq(
            q, k_pool, v_pool, table, limits, softcap=softcap, window=window,
            sliding=sliding, q_pos=q_pos, interpret=interp, kv_scale=kv_scale,
            sink=sink, swin=swin,
        )
    return _paged_cache_partials_mq(
        q, k_pool, v_pool, table, limits,
        softcap=softcap, window=window, sliding=sliding, q_pos=q_pos,
        kv_scale=kv_scale, sink=sink, swin=swin,
    )


def decode_attention_windowed_paged(
    q: jnp.ndarray,  # [B, H, D]
    k_pool: jnp.ndarray,  # [P, page, K, D] shared page pool
    v_pool: jnp.ndarray,
    table: jnp.ndarray,  # [B, MP] int32 page ids per slot
    k_local: jnp.ndarray,  # [B, n, K, D] block-local window
    v_local: jnp.ndarray,
    k_new: jnp.ndarray,  # [B, K, D]
    v_new: jnp.ndarray,
    positions: jnp.ndarray,  # [B]
    step: jnp.ndarray,  # scalar
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,
    impl: str = "auto",
    mesh=None,  # Mesh with tp>1 → Pallas kernel head-sharded (shard_map)
    kv_scale=None,  # [2, K] f32 per-head (k, v) dequant scales (fp8 KV)
    sink: int = 0,  # windowed+sink decode (docs/LONG_CONTEXT.md): rows
    swin: int = 0,  # attended iff gpos < sink or q_pos - gpos < swin
) -> jnp.ndarray:
    """`decode_attention_windowed` over a paged pool: paged partials for
    rows [0, block_start), dense merge of the (tiny) local window + current
    token."""
    n = k_local.shape[1]
    acc, m, l = paged_partials(
        q, k_pool, v_pool, table, positions - step,
        softcap=softcap, window=window, sliding=sliding, q_pos=positions,
        impl=impl, mesh=mesh, kv_scale=kv_scale, sink=sink, swin=swin,
    )
    # f32 concat: the block-local window may live in the cache's storage
    # dtype (fp8 KV) while the current token is model-dtype.
    ek = jnp.concatenate([k_local.astype(jnp.float32),
                          k_new[:, None].astype(jnp.float32)], axis=1)
    ev = jnp.concatenate([v_local.astype(jnp.float32),
                          v_new[:, None].astype(jnp.float32)], axis=1)
    mask = jnp.concatenate([jnp.arange(n) < step, jnp.ones((1,), bool)], axis=0)
    if window and sliding is not None:
        dist = jnp.concatenate([step - jnp.arange(n), jnp.zeros((1,), jnp.int32)])
        mask = mask & (~sliding | (dist < window))
    mask = jnp.broadcast_to(mask[None, :], (q.shape[0], n + 1))
    if swin:
        # Exact mask on the local rows too: row i sits at global position
        # block_start + i = positions - step + i, distance step - i.
        dist = jnp.concatenate(
            [step - jnp.arange(n), jnp.zeros((1,), jnp.int32)]
        )[None, :]
        gpos = positions[:, None] - dist
        mask = mask & ((gpos < sink) | (dist < swin))
    return _merge_partials(q, acc, m, l, ek, ev, mask, softcap=softcap)


def _paged_cache_partials_mq(q, k_pool, v_pool, table, limits,
                             softcap: float = 0.0, window: int = 0,
                             sliding=None, q_pos=None, kv_scale=None,
                             sink: int = 0, swin: int = 0):
    """Multi-query `_paged_cache_partials` for the speculative verify chunk
    and the chunked-prefill prefix walk: q [B, T, H, D], one page walk
    shared by all T queries. limits [B] bounds the cache prefix every query
    may see (the chunk's in-window causal part is merged separately).
    table is flat [B, MP] or the hierarchical (l1, l0) pair; sink/swin add
    the windowed+sink mask AND the per-slot cold-page skip (see
    _paged_cache_partials — the skip is bounded by the SMALLEST query
    position in the chunk, so every query's window stays covered). Returns
    (acc [B, K, G, T, D], m [B, K, G, T, 1], l [B, K, G, T, 1])."""
    from localai_tpu.ops import ptable as _pt

    B, T, H, D = q.shape
    page = k_pool.shape[1]
    K = k_pool.shape[2]
    G = H // K
    MP = _pt.width(table)
    scale = 1.0 / (D**0.5)
    qf = (q.astype(jnp.float32) * scale).reshape(B, T, K, G, D)
    if swin:
        sink_cols, win_lo, n_cols = _sink_window_cols(
            limits, jnp.min(q_pos, axis=1), page, MP, sink, swin
        )

    def body(p, carry):
        m, l, acc = carry
        if swin:
            col = jnp.where(p < sink_cols, p, p + (win_lo - sink_cols))  # [B]
            col_ok = p < n_cols  # [B]
        else:
            col = jnp.broadcast_to(p, (B,))
            col_ok = jnp.ones((B,), bool)
        pids = _pt.gather_cols(
            table, jnp.minimum(col, MP - 1)[:, None]
        )[:, 0]  # [B]
        kp = k_pool[pids].astype(jnp.float32)  # [B, page, K, D]
        vp = v_pool[pids].astype(jnp.float32)
        if kv_scale is not None:  # in-register fp8 dequant (fused into cast)
            kp = kp * kv_scale[0][None, None, :, None]
            vp = vp * kv_scale[1][None, None, :, None]
        sc = jnp.einsum("btkgd,bskd->bkgts", qf, kp)  # [B, K, G, T, page]
        if softcap:
            sc = softcap_scores(sc, softcap)
        gpos = col[:, None] * page + jnp.arange(page)[None, :]  # [B, page]
        valid = (gpos < limits[:, None]) & col_ok[:, None]  # [B, page]
        valid = valid[:, None, :]  # [B, 1, page]
        if window and sliding is not None:
            dist = q_pos[:, :, None] - gpos[:, None, :]  # [B, T, page]
            valid = valid & (~sliding | (dist < window))
        if swin:
            dist = q_pos[:, :, None] - gpos[:, None, :]  # [B, T, page]
            valid = valid & ((gpos[:, None, :] < sink) | (dist < swin))
        vmask = valid[:, None, None]  # [B, 1, 1, T|1, page]
        sc = jnp.where(vmask, sc, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sc, axis=-1, keepdims=True))
        alpha = jnp.exp(jnp.maximum(m - m_new, -80.0))
        pr = jnp.exp(sc - m_new)
        pr = jnp.where(vmask, pr, 0.0)
        l = l * alpha + jnp.sum(pr, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bkgts,bskd->bkgtd", pr, vp)
        return m_new, l, acc

    m0 = jnp.full((B, K, G, T, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, K, G, T, 1), jnp.float32)
    a0 = jnp.zeros((B, K, G, T, D), jnp.float32)
    if swin:
        p_hi = jnp.max(n_cols).astype(jnp.int32)
    else:
        p_hi = jnp.minimum(
            (jnp.max(limits) + page - 1) // page, MP
        ).astype(jnp.int32)
    m, l, acc = jax.lax.fori_loop(0, p_hi, body, (m0, l0, a0))
    return acc, m, l


def _merge_partials_mq(q, acc_g, m_g, l_g, extra_k, extra_v, extra_mask,
                       softcap: float = 0.0):
    """Multi-query `_merge_partials`: q [B, T, H, D], partials [..., T, ...],
    extra_k/v [B, E, K, D], extra_mask [B, T, E]. Returns [B, T, H, D]."""
    B, T, H, D = q.shape
    K = extra_k.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)
    qf = (q.astype(jnp.float32) * scale).reshape(B, T, K, G, D)
    se = jnp.einsum("btkgd,bekd->bkgte", qf, extra_k.astype(jnp.float32))
    if softcap:
        se = softcap_scores(se, softcap)
    emask = extra_mask[:, None, None]  # [B, 1, 1, T, E]
    se = jnp.where(emask, se, NEG_INF)
    m_e = jnp.max(se, axis=-1, keepdims=True)
    m_tot = jnp.maximum(m_g, m_e)
    p_e = jnp.exp(se - m_tot)
    p_e = jnp.where(emask, p_e, 0.0)
    w_c = jnp.exp(jnp.maximum(m_g - m_tot, -80.0))
    w_c = jnp.where(l_g > 0, w_c, 0.0)
    num = acc_g * w_c + jnp.einsum("bkgte,bekd->bkgtd", p_e, extra_v.astype(jnp.float32))
    den = l_g * w_c + jnp.sum(p_e, axis=-1, keepdims=True)
    out = num / jnp.maximum(den, 1e-30)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, T, H, D).astype(q.dtype)
