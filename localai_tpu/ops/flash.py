"""Pallas flash attention (causal, GQA, length-masked) for TPU prefill.

The prefill hot op: dense attention materializes [B, H, S, S] scores in HBM
(O(S²) memory traffic); this kernel streams KV blocks through VMEM with the
online-softmax recurrence, so HBM traffic is O(S) per query block and the
matmuls hit the MXU at block size 128. Reference equivalent: llama.cpp's
flash-attn path (grpc-server.cpp params_parse `flash_attention`).

Layout: q [B, H, S, D] (head-major so a (q-block, head) grid step is one
contiguous VMEM tile), kv [B, K_heads, S, D]; GQA maps query head h to kv
head h // (H // K). Causal + per-row validity masking via the `lengths` [B]
scalar-prefetch argument.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _flash_kernel(
    lengths_ref,  # scalar-prefetch [B]
    q_ref,  # [1, 1, BQ, D]
    k_ref,  # [1, 1, S, D]
    v_ref,  # [1, 1, S, D]
    o_ref,  # [1, 1, BQ, D]
    *,
    block_q: int,
    block_k: int,
    seq_len: int,
    scale: float,
):
    import jax.experimental.pallas as pl

    b = pl.program_id(0)
    qi = pl.program_id(2)

    length = lengths_ref[b]
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [BQ, D]
    bq = q.shape[0]

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    num_kv_blocks = pl.cdiv(
        jnp.minimum((qi + 1) * block_q, seq_len), block_k
    )

    def body(ck, carry):
        acc, m, l = carry
        k_blk = k_ref[0, 0, pl.ds(ck * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(ck * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        kv_pos = ck * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = (kv_pos <= q_pos) & (kv_pos < length)
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    d = q.shape[-1]
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kv_blocks, body, (acc0, m0, l0))

    # Padding query rows (q_pos >= length) attend over the valid prefix and
    # would emit finite garbage; zero them explicitly so the output contract
    # is "padded rows are zeros" for any downstream pooling without a mask.
    q_row = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    o = jnp.where(q_row < length, acc / jnp.maximum(l, 1e-30), 0.0)
    o_ref[0, 0] = o.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret")
)
def flash_prefill_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, K, D]
    v: jnp.ndarray,  # [B, S, K, D]
    lengths: jnp.ndarray,  # [B] int32 valid lengths
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Causal GQA flash attention. Returns [B, S, H, D] in q.dtype."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    K = k.shape[2]
    G = H // K
    if S % block_q or S % block_k:
        raise ValueError(f"seq len {S} must be a multiple of block sizes ({block_q},{block_k})")
    scale = 1.0 / (D**0.5)

    # Head-major layout: one (b, h, q-block) grid step reads contiguous tiles.
    qh = q.transpose(0, 2, 1, 3)  # [B, H, S, D]
    kh = k.transpose(0, 2, 1, 3)  # [B, K, S, D]
    vh = v.transpose(0, 2, 1, 3)

    grid = (B, H, S // block_q)
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, seq_len=S, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                # index maps take (*grid_ids, *scalar_prefetch_refs)
                pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, *_: (b, h, i, 0)),
                pl.BlockSpec((1, 1, S, D), lambda b, h, i, *_: (b, h // G, 0, 0)),
                pl.BlockSpec((1, 1, S, D), lambda b, h, i, *_: (b, h // G, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, *_: (b, h, i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qh, kh, vh)
    return out.transpose(0, 2, 1, 3)  # [B, S, H, D]
