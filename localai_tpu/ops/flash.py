"""Pallas flash attention (causal, GQA, length-masked) for TPU prefill.

The prefill hot op: dense attention materializes [B, H, S, S] scores in HBM
(O(S²) memory traffic); this kernel streams KV blocks through VMEM with the
online-softmax recurrence, so HBM traffic is O(S) per query block and the
matmuls hit the MXU at block size 128. Reference equivalent: llama.cpp's
flash-attn path (grpc-server.cpp params_parse `flash_attention`).

The KV axis is a GRID dimension (innermost, with the softmax running state
carried in VMEM scratch across its iterations) — NOT a whole-sequence VMEM
block with an in-kernel loop. A [S, D] KV block is 4 MB per operand at
S=32k, which double-buffered blows the 16 MB scoped-VMEM limit; per-block
tiles keep VMEM usage constant in S, so 32k+ contexts compile.

Layout: q [B, H, S, D] (head-major so a (q-block, head) grid step is one
contiguous VMEM tile), kv [B, K_heads, S, D]; GQA maps query head h to kv
head h // (H // K). Causal + per-row validity masking via the `lengths` [B]
scalar-prefetch argument.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def flash_block_sizes(S: int) -> tuple[int, int]:
    """(block_q, block_k) for a length-S prefill. Bigger tiles at long
    context: the grid is B·H·(S/bq)·(S/bk) steps and per-step fixed cost
    dominates past ~8k (a 32k prefill at 128×128 tiles is ~1M grid steps);
    VMEM per step stays tiny (bq·D + 2·bk·D floats). Shared by the dense
    prefill dispatcher (ops/attention.prefill_attention) and the chunked
    admission path so both pick identical tiles for a given bucket."""
    return min(256, S), min(512, S)


def _flash_kernel(
    lengths_ref,  # scalar-prefetch [B]
    q_ref,  # [1, 1, BQ, D]
    k_ref,  # [1, 1, BK, D]
    v_ref,  # [1, 1, BK, D]
    o_ref,  # [1, 1, BQ, D]
    acc_ref,  # VMEM scratch [BQ, D] f32
    m_ref,  # VMEM scratch [BQ, 1] f32
    l_ref,  # VMEM scratch [BQ, 1] f32
    *,
    block_q: int,
    block_k: int,
    num_kv_blocks: int,
    scale: float,
):
    import jax.experimental.pallas as pl

    b = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = lengths_ref[b]
    bq = q_ref.shape[2]

    # Causal: kv blocks entirely above this q block contribute nothing —
    # skip their (masked-to-NEG_INF) compute.
    @pl.when(ki * block_k < (qi + 1) * block_q)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # [BQ, D]
        k_blk = k_ref[0, 0].astype(jnp.float32)  # [BK, D]
        v_blk = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
        kv_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = (kv_pos <= q_pos) & (kv_pos < length)
        s = jnp.where(mask, s, NEG_INF)

        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = m_new

    @pl.when(ki == num_kv_blocks - 1)
    def _finish():
        # Padding query rows (q_pos >= length) attend over the valid prefix
        # and would emit finite garbage; zero them explicitly so the output
        # contract is "padded rows are zeros" for any downstream pooling.
        q_row = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
        o = jnp.where(
            q_row < length,
            acc_ref[...] / jnp.maximum(l_ref[...], 1e-30),
            0.0,
        )
        o_ref[0, 0] = o.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret")
)
def flash_prefill_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, K, D]
    v: jnp.ndarray,  # [B, S, K, D]
    lengths: jnp.ndarray,  # [B] int32 valid lengths
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Causal GQA flash attention. Returns [B, S, H, D] in q.dtype."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    K = k.shape[2]
    G = H // K
    if S % block_q or S % block_k:
        raise ValueError(f"seq len {S} must be a multiple of block sizes ({block_q},{block_k})")
    scale = 1.0 / (D**0.5)

    # Head-major layout: one (b, h, q-block) grid step reads contiguous tiles.
    qh = q.transpose(0, 2, 1, 3)  # [B, H, S, D]
    kh = k.transpose(0, 2, 1, 3)  # [B, K, S, D]
    vh = v.transpose(0, 2, 1, 3)

    num_kv_blocks = S // block_k
    grid = (B, H, S // block_q, num_kv_blocks)
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k,
        num_kv_blocks=num_kv_blocks, scale=scale,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                # index maps take (*grid_ids, *scalar_prefetch_refs)
                pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j, *_: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j, *_: (b, h // G, j, 0)),
                pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j, *_: (b, h // G, j, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, block_q, D), lambda b, h, i, j, *_: (b, h, i, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((block_q, D), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qh, kh, vh)
    return out.transpose(0, 2, 1, 3)  # [B, S, H, D]
