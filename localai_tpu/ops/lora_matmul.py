"""Ragged per-slot LoRA delta kernel for multi-tenant decode (ISSUE 10).

One engine serves many tenants: shared (possibly int8/int4-quantized) base
weights plus per-tenant LoRA adapters applied UNMERGED beside each base
matmul — y = W·x + B·(A·x) with the rank-r factors of every device-resident
adapter stacked along a leading adapter axis. Each decode row (engine slot)
carries an adapter id, so one batch freely mixes tenants; id 0 is the
all-zero null adapter, making adapter-less rows bit-exact no-ops.

The Pallas kernel is the segmented/ragged shape the paged-attention walk
already uses (ops/paged_flash): the per-row adapter ids ride as a
scalar-prefetch operand and each grid step's BlockSpec index map gathers
THAT row's A/B factor blocks out of the stacked HBM tensors — a grouped
matmul over ragged segments, with the grid pipeline double-buffering the
factor DMAs exactly like quant_matmul streams weight tiles. Decode rows are
bounded by max_slots, so x, the rank-r intermediate, and the out tile all
sit in VMEM; consecutive rows of the same tenant revisit the same factor
block without a fresh DMA.

The XLA gather path below (`lora_delta_xla`) stays the numeric oracle,
dispatched behind EngineConfig.lora_kernel exactly like paged_kernel /
quant_kernel ("auto" = Pallas on TPU; tests run the kernel in interpret
mode on CPU against the oracle).

Sharding (tp>1): pallas_call is opaque to GSPMD, so the kernel runs under
shard_map with the factor partitioning matching the base weight's role —
column-parallel targets (wq/wk/wv/w_gate/w_up) replicate A and shard B on
the out axis; row-parallel targets (wo/w_down) shard A on the in axis
(their x arrives "tp"-sharded) and psum the partial deltas inside the
declared boundary below, the same ICI boundary the base matmul already
pays at the o/down projection.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

# The ONLY function here allowed to issue cross-chip collectives: the
# row-parallel shard_map closure psums its partial B·(A·x) deltas over
# "tp" (lint: sharding-consistency C3).
COLLECTIVE_BOUNDARY = ("_sharded_lora_delta",)

# Rows above which the kernel disengages (prefill-scale deltas are
# compute-bound and ride the XLA path, which GSPMD shards by propagation).
LORA_PALLAS_MAX_ROWS = 256

# Base-weight role per LoRA target key: decides the tp partitioning of the
# stacked factors (parallel/sharding._layer_specs assigns the same roles to
# the base weights themselves).
LORA_PART = {
    "wq": "col", "wk": "col", "wv": "col",
    "w_gate": "col", "w_up": "col",
    "wo": "row", "w_down": "row",
}


def use_pallas_lora(impl: str = "auto") -> bool:
    """Resolve the LoRA-delta kernel choice. impl: "auto" (Pallas on TPU,
    XLA gather elsewhere), "pallas", or "xla". LOCALAI_LORA_KERNEL env var
    overrides — same escape hatch as LOCALAI_QUANT_KERNEL."""
    impl = os.environ.get("LOCALAI_LORA_KERNEL", "") or impl or "auto"
    if impl == "auto":
        return jax.default_backend() == "tpu"
    if impl not in ("pallas", "xla"):
        raise ValueError(f"lora kernel impl {impl!r}: use auto|pallas|xla")
    return impl == "pallas"


def lora_factor_specs(part: str):
    """PartitionSpecs for one target's stacked factors
    a [L, NA, in, R] / b [L, NA, R, out] under a tp mesh (see module
    docstring: col shards b's out axis, row shards a's in axis)."""
    from jax.sharding import PartitionSpec as P

    if part == "row":
        return {"a": P(None, None, "tp", None), "b": P(None, None, None, None)}
    return {"a": P(None, None, None, None), "b": P(None, None, None, "tp")}


def _tile(n: int, targets=(512, 256, 128)) -> int:
    for t in targets:
        if t <= n and n % t == 0:
            return t
    return n


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _tp_degree(mesh) -> int:
    if mesh is None:
        return 1
    return int(mesh.shape.get("tp", 1))


# --------------------------------------------------------------------------- #
# Kernel
# --------------------------------------------------------------------------- #


def _lora_kernel(ids_ref, x_ref, a_ref, b_ref, o_ref):
    """One (row, out-tile) grid step: this row's delta tile
    B[id][:, tile] · (A[id]ᵀ·x). The id-indexed factor blocks were DMA'd by
    the grid pipeline via the scalar-prefetched ids (see _lora_call); the
    rank-r intermediate lives only in registers."""
    del ids_ref  # consumed by the BlockSpec index maps, not the body
    x = x_ref[...].astype(jnp.float32)  # [1, IN]
    a = a_ref[0].astype(jnp.float32)  # [IN, R]
    b = b_ref[0].astype(jnp.float32)  # [R, bo]
    t = jax.lax.dot_general(
        x, a, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # [1, R]
    y = jax.lax.dot_general(
        t, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # [1, bo]
    o_ref[...] = y.astype(o_ref.dtype)


def _lora_call(x2, a, b, ids):
    """pallas_call launch on local (possibly shard-local) shapes.

    x2 [N, IN] float; a [NA, IN, R]; b [NA, R, OUT]; ids [N] int32.
    Returns [N, OUT] in x2.dtype. Grid (N, out-tiles); the adapter ids ride
    scalar prefetch so the factor BlockSpecs gather per-row segments."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, k_in = x2.shape
    na, _, r = a.shape
    out = b.shape[-1]
    bo = _tile(out)
    grid = (n, out // bo)
    return pl.pallas_call(
        _lora_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, k_in), lambda i, j, ids: (i, 0)),
                pl.BlockSpec((1, k_in, r), lambda i, j, ids: (ids[i], 0, 0)),
                pl.BlockSpec((1, r, bo), lambda i, j, ids: (ids[i], 0, j)),
            ],
            out_specs=pl.BlockSpec((1, bo), lambda i, j, ids: (i, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((n, out), x2.dtype),
        interpret=_interpret(),
    )(ids, x2, a, b)


# --------------------------------------------------------------------------- #
# XLA oracle
# --------------------------------------------------------------------------- #


def lora_delta_xla(x, a, b, ids):
    """Per-row ragged delta, gather form: rows of x (leading axis) select
    their adapter's factors. x [B, ..., in]; a [NA, in, R]; b [NA, R, out];
    ids [B] int32 (0 = null adapter → exact zero). Returns [B, ..., out] in
    x.dtype, accumulated in f32 (the delta runs bf16/f32 even when the base
    matmul is int8/int4 — docs/LORA_SERVING.md)."""
    a_sel = jnp.take(a, ids, axis=0).astype(x.dtype)  # [B, in, R]
    b_sel = jnp.take(b, ids, axis=0).astype(x.dtype)  # [B, R, out]
    t = jnp.einsum(
        "b...i,bir->b...r", x, a_sel, preferred_element_type=jnp.float32
    )
    y = jnp.einsum(
        "b...r,bro->b...o", t.astype(x.dtype), b_sel,
        preferred_element_type=jnp.float32,
    )
    return y.astype(x.dtype)


# --------------------------------------------------------------------------- #
# Sharded dispatch (tp>1 — shard_map over the factors' own partitioning)
# --------------------------------------------------------------------------- #


def _sharded_lora_delta(x, a, b, ids, mesh, part: str):
    """Run the local kernel per tp shard; row-parallel partial deltas psum
    over "tp" here (the declared ICI boundary — see COLLECTIVE_BOUNDARY)."""
    from jax.sharding import PartitionSpec as P

    from localai_tpu.parallel.mesh import shard_map as _shard_map

    row = part == "row"
    fspecs = lora_factor_specs(part)
    # The engine's stacked factors carry a leading L axis the per-layer
    # slice has already consumed — drop it from the specs.
    a_spec = P(*tuple(fspecs["a"])[1:])
    b_spec = P(*tuple(fspecs["b"])[1:])
    x_spec = P(None, "tp") if row else P(None, None)
    o_spec = P(None, None) if row else P(None, "tp")

    def local(xl, al, bl, idsl):
        y = _lora_call(xl, al, bl, idsl)
        if row:
            y = jax.lax.psum(y, "tp")
        return y

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(x_spec, a_spec, b_spec, P(None)),
        out_specs=o_spec,
        check_vma=False,
    )
    return fn(x, a, b, ids)


# --------------------------------------------------------------------------- #
# Dispatcher
# --------------------------------------------------------------------------- #


def _shardable(x, a, b, part: str, tp: int) -> bool:
    if part == "row":
        return x.shape[-1] % tp == 0 and a.shape[1] % tp == 0
    return b.shape[-1] % tp == 0


def lora_delta(x, factors, ids, impl: str = "auto", mesh=None,
               part: str = "col"):
    """Per-row LoRA delta y = B[id]·(A[id]·x) for one target projection.

    factors: {"a": [NA, in, R], "b": [NA, R, out]} per-layer slices of the
    engine's stacked adapter tensors; ids [B] int32 device-adapter rows
    (0 = none). Decode-shape 2-D x routes to the Pallas ragged kernel per
    `impl` ("auto" = Pallas on TPU); everything else — prefill [B, S, in],
    interpret-unfriendly shapes, non-divisible tp splits — falls back to
    the XLA gather oracle, which GSPMD partitions by propagation."""
    a, b = factors["a"], factors["b"]
    engaged = (
        use_pallas_lora(impl)
        and x.ndim == 2
        and jnp.issubdtype(x.dtype, jnp.floating)
        and 0 < x.shape[0] <= LORA_PALLAS_MAX_ROWS
    )
    if engaged:
        tp = _tp_degree(mesh)
        if tp > 1 and part in ("col", "row"):
            if _shardable(x, a, b, part, tp):
                return _sharded_lora_delta(x, a, b, ids, mesh, part)
        else:
            return _lora_call(x, a, b, ids)
    return lora_delta_xla(x, a, b, ids)
