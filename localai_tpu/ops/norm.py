"""RMSNorm, computed in float32 for stability and cast back.

Reference equivalent: ggml's rms_norm inside llama.cpp (vendored by
backend/cpp/llama-cpp). XLA fuses this into the surrounding matmuls, so no
Pallas kernel is needed for it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)
