"""Fused ragged paged-attention decode kernel (Pallas, TPU).

The paged decode hot op. The XLA reference path
(ops/attention._paged_cache_partials) gathers page tiles into HBM scratch
each fori_loop step — `k_pool[pids]` materializes a [B, CH·page, K, D]
buffer per chunk, so every live KV byte is read from HBM, written back to
HBM, and read again by the einsum (3x the traffic that the math needs), and
the gather itself cannot overlap the matmul. BENCH_r04 put paged decode at
0.73x of the dense cache for exactly this reason.

This kernel walks each slot's page table IN-KERNEL ("Ragged Paged
Attention", PAPERS.md): the pool stays in HBM (memory_space=ANY), and the
kernel streams the listed pages through a double-buffered VMEM scratch with
explicit async DMAs — page j+1 is in flight while page j is scored against
the online-softmax running state. Each live KV byte crosses HBM→VMEM exactly
once, the walk stops at the slot's OWN live-prefix bound (ragged, not the
batch max), and idle slots (limits == 0) cost nothing.

Shapes (matching the XLA reference):
- q rows     [B, K, QR, Dk] f32, 1/sqrt(D) pre-applied; QR = G query rows
  per kv head (G·T for the multi-query verify chunk).
- k/v pool   [P, page, K, Dk|Dv] in the cache storage dtype (bf16/fp8 —
  cast to f32 on read, same contract as every other cache reader).
- table      [B, MP] int32 page ids (scalar-prefetch: the DMA descriptors
  are computed from it before the body runs).
- limits     [B] int32 — rows with global index >= limits[b] are masked;
  the page walk is bounded by ceil(limits[b]/page).
- qpos       [B, QR] int32 query positions (sliding-window distance).
- sliding    [1] int32 — traced per-layer flag (gemma-2 alternates
  sliding/global layers inside a scanned stack, so it cannot be static).

Returns online-softmax partials (acc, m, l) — f32, exactly the reference's
contract — which the existing _merge_partials/_merge_partials_mq fold with
the block-local window and current token. Keeping the merge in XLA keeps
ONE numeric tail for both paths, so the reference doubles as the kernel's
oracle (tests/test_paged_flash.py runs this kernel in interpret mode on
CPU against it).

The m/l outputs are padded to 128 lanes (STAT_LANES) and sliced by the
wrapper: a 1-wide lane dimension is a legal VMEM scratch shape but a
pathological output tiling on real hardware.

The same kernel also serves CHUNKED RAGGED PREFILL (docs/CHUNKED_PREFILL.md):
paged_prefill_partials_mq tiles a prefill chunk's T·G query rows so the
online-softmax running state fits VMEM, and models/llama.prefill_chunk_paged
folds the partials with the in-chunk causal window and scatters the chunk's
fresh K/V straight into the slot's pages — no dense-bucket intermediate.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

NEG_INF = -1e30
STAT_LANES = 128


def use_pallas(impl: str = "auto") -> bool:
    """Resolve the paged-attention implementation choice.

    impl: "auto" (Pallas on TPU, XLA reference elsewhere), "pallas", or
    "xla". The LOCALAI_PAGED_KERNEL env var overrides — same escape hatch
    as LOCALAI_FLASH for the prefill kernel. "pallas" off-TPU runs in
    interpret mode (slow; tests only).
    """
    impl = os.environ.get("LOCALAI_PAGED_KERNEL", "") or impl or "auto"
    if impl == "auto":
        return jax.default_backend() == "tpu"
    if impl not in ("pallas", "xla"):
        raise ValueError(f"paged kernel impl {impl!r}: use auto|pallas|xla")
    return impl == "pallas"


def _ragged_paged_kernel(
    *refs,  # scalar-prefetch table refs (see below), then operands/outs
    page: int,
    num_kv: int,
    softcap: float,
    window: int,
    sink: int = 0,
    swin: int = 0,
    l1_span: int = 0,
):
    """Kernel body. Scalar-prefetch layout depends on the table layout:

    FLAT (l1_span == 0):  table_ref [B, MP] i32
    HIER (l1_span  > 0):  l1_ref [B, ML1] i32, l0_ref [NTP, SPAN] i32 — a
        slot's page COLUMN j resolves through l0[l1[b, j // SPAN], j % SPAN]
        (ops/ptable), so one 1M-token slot ships a 64-entry directory row
        instead of an 8192-wide flat row that blows the SMEM prefetch
        budget.

    Then: limits_ref [B] i32, sliding_ref [1] i32 (both prefetch), and the
    regular operands q_ref [1, K, QR, Dk] f32, qpos_ref [1, QR] i32,
    kvs_ref [2, K] f32 SMEM, k_hbm/v_hbm pools (ANY), outputs acc/m/l, VMEM
    scratch kbuf/vbuf/acc_s/m_s/l_s and the DMA semaphores.

    sink/swin (windowed+sink decode, docs/LONG_CONTEXT.md): a row is
    attended iff `gpos < sink` or `q_pos - gpos < swin`. The page walk then
    SKIPS the cold middle — it visits columns [0, sink_cols) ∪ [win_lo,
    np_live) via an index remap, so a spilled slot streams only its sink
    pages + trailing window from HBM. Exact: skipped pages are fully masked
    either way.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if l1_span:
        l1_ref, l0_ref = refs[0], refs[1]
        refs = refs[2:]
        table_width = l1_ref.shape[1] * l1_span
    else:
        table_ref = refs[0]
        refs = refs[1:]
        table_width = table_ref.shape[1]
    (
        limits_ref,  # scalar-prefetch [B] i32
        sliding_ref,  # scalar-prefetch [1] i32
        q_ref,  # [1, K, QR, Dk] f32 (scale applied)
        qpos_ref,  # [1, QR] i32
        kvs_ref,  # [2, K] f32 SMEM — per-head (k, v) dequant scales (fp8
        # KV); ones when the pool is unscaled (multiply is exact identity)
        k_hbm,  # [P, page, K, Dk] pool dtype, memory_space=ANY
        v_hbm,  # [P, page, K, Dv]
        acc_ref,  # out [1, K, QR, Dv] f32
        m_ref,  # out [1, K, QR, STAT_LANES] f32
        l_ref,  # out [1, K, QR, STAT_LANES] f32
        kbuf,  # VMEM scratch [2, page, K, Dk] pool dtype
        vbuf,  # VMEM scratch [2, page, K, Dv]
        acc_s,  # VMEM scratch [K, QR, Dv] f32
        m_s,  # VMEM scratch [K, QR, 1] f32
        l_s,  # VMEM scratch [K, QR, 1] f32
        sem,  # DMA semaphores [2, 2]
    ) = refs

    b = pl.program_id(0)
    QR = q_ref.shape[2]
    lim = limits_ref[b]
    # This slot's own page count (ragged), clamped to the table width so a
    # bad limit can never index the table out of bounds.
    np_live = jnp.minimum((lim + page - 1) // page, table_width)

    if swin:
        # Cold-middle skip: walk iteration j covers table column col(j).
        sink_cols = jnp.minimum(-(-sink // page) if sink else 0, np_live)
        qmin = jnp.min(qpos_ref[0])
        win_lo = jnp.clip((qmin - swin + 1) // page, 0, np_live)
        win_lo = jnp.maximum(win_lo, sink_cols)
        n_iter = sink_cols + np_live - win_lo
        gap = win_lo - sink_cols

        def col_of(j):
            return jnp.where(j < sink_cols, j, j + gap)
    else:
        n_iter = np_live

        def col_of(j):
            return j

    def tbl(j):
        col = col_of(j)
        if l1_span:
            return l0_ref[l1_ref[b, col // l1_span], col % l1_span]
        return table_ref[b, col]

    def dma_k(slot, j):
        return pltpu.make_async_copy(
            k_hbm.at[tbl(j)], kbuf.at[slot], sem.at[slot, 0]
        )

    def dma_v(slot, j):
        return pltpu.make_async_copy(
            v_hbm.at[tbl(j)], vbuf.at[slot], sem.at[slot, 1]
        )

    acc_s[...] = jnp.zeros_like(acc_s)
    m_s[...] = jnp.full_like(m_s, NEG_INF)
    l_s[...] = jnp.zeros_like(l_s)

    @pl.when(n_iter > 0)
    def _warmup():
        dma_k(0, 0).start()
        dma_v(0, 0).start()

    def body(j, carry):
        slot = j % 2

        @pl.when(j + 1 < n_iter)
        def _prefetch():  # next page rides the wire while this one computes
            dma_k((j + 1) % 2, j + 1).start()
            dma_v((j + 1) % 2, j + 1).start()

        dma_k(slot, j).wait()
        dma_v(slot, j).wait()

        # Global row indices covered by the table column this step visits.
        gpos = col_of(j) * page + jax.lax.broadcasted_iota(
            jnp.int32, (QR, page), 1
        )
        valid = gpos < lim
        if window:
            qp = qpos_ref[0]  # [QR]
            sl = sliding_ref[0] > 0
            dist = qp[:, None] - gpos
            valid = valid & (~sl | (dist < window))
        if swin:
            qp = qpos_ref[0]  # [QR]
            dist = qp[:, None] - gpos
            valid = valid & ((gpos < sink) | (dist < swin))

        for kh in range(num_kv):  # static unroll — one MXU pass per kv head
            q = q_ref[0, kh]  # [QR, Dk]
            # fp8 KV dequant happens HERE, in registers on the VMEM tile the
            # DMA just landed — the pool's stored bytes never exist in HBM
            # at any wider dtype (per-head scale: ISSUE 9).
            kp = kbuf[slot, :, kh, :].astype(jnp.float32) * kvs_ref[0, kh]
            s = jax.lax.dot_general(
                q, kp, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [QR, page]
            if softcap:
                s = softcap * jnp.tanh(s / softcap)
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_s[kh]  # [QR, 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(jnp.maximum(m_prev - m_new, -80.0))
            p = jnp.exp(s - m_new)
            p = jnp.where(valid, p, 0.0)
            l_s[kh] = l_s[kh] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            vp = vbuf[slot, :, kh, :].astype(jnp.float32) * kvs_ref[1, kh]
            acc_s[kh] = acc_s[kh] * alpha + jax.lax.dot_general(
                p, vp, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_s[kh] = m_new
        return carry

    jax.lax.fori_loop(0, n_iter, body, 0)

    acc_ref[0] = acc_s[...]
    m_ref[0] = jnp.broadcast_to(m_s[...], m_ref.shape[1:])
    l_ref[0] = jnp.broadcast_to(l_s[...], l_ref.shape[1:])


def _paged_partials_rows(
    qr: jnp.ndarray,  # [B, K, QR, Dk] f32, scale applied
    qpos_rows: jnp.ndarray,  # [B, QR] i32
    k_pool: jnp.ndarray,  # [P, page, K, Dk]
    v_pool: jnp.ndarray,  # [P, page, K, Dv]
    table,  # [B, MP] i32, or hierarchical (l1 [B, ML1], l0 [NTP, SPAN])
    limits: jnp.ndarray,  # [B] i32
    softcap: float,
    window: int,
    sliding,
    interpret: bool,
    kv_scale=None,  # [2, K] f32 per-head (k, v) dequant scales, or None
    sink: int = 0,  # windowed+sink decode (docs/LONG_CONTEXT.md)
    swin: int = 0,
):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from localai_tpu.ops import ptable as _pt

    B, K, QR, Dk = qr.shape
    page = k_pool.shape[1]
    Dv = v_pool.shape[3]
    sl_arr = jnp.asarray(
        sliding if sliding is not None else False
    ).reshape(1).astype(jnp.int32)
    kvs = (jnp.ones((2, K), jnp.float32) if kv_scale is None
           else kv_scale.astype(jnp.float32))
    if _pt.is_hier(table):
        l1, l0 = table
        l1_span = int(l0.shape[-1])
        tbl_args = (l1.astype(jnp.int32), l0.astype(jnp.int32))
    else:
        l1_span = 0
        tbl_args = (table.astype(jnp.int32),)
    kernel = functools.partial(
        _ragged_paged_kernel, page=page, num_kv=K,
        softcap=float(softcap), window=int(window),
        sink=int(sink), swin=int(swin), l1_span=l1_span,
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(tbl_args) + 2,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, K, QR, Dk), lambda b, *_: (b, 0, 0, 0)),
                pl.BlockSpec((1, QR), lambda b, *_: (b, 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),  # [2, K] kv scales
                pl.BlockSpec(memory_space=pltpu.ANY),  # pool stays in HBM
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=[
                pl.BlockSpec((1, K, QR, Dv), lambda b, *_: (b, 0, 0, 0)),
                pl.BlockSpec((1, K, QR, STAT_LANES), lambda b, *_: (b, 0, 0, 0)),
                pl.BlockSpec((1, K, QR, STAT_LANES), lambda b, *_: (b, 0, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, page, K, Dk), k_pool.dtype),
                pltpu.VMEM((2, page, K, Dv), v_pool.dtype),
                pltpu.VMEM((K, QR, Dv), jnp.float32),
                pltpu.VMEM((K, QR, 1), jnp.float32),
                pltpu.VMEM((K, QR, 1), jnp.float32),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, K, QR, Dv), jnp.float32),
            jax.ShapeDtypeStruct((B, K, QR, STAT_LANES), jnp.float32),
            jax.ShapeDtypeStruct((B, K, QR, STAT_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(
        *tbl_args, limits.astype(jnp.int32), sl_arr,
        qr, qpos_rows.astype(jnp.int32), kvs, k_pool, v_pool,
    )
    return acc, m[..., :1], l[..., :1]


def paged_decode_partials(
    q: jnp.ndarray,  # [B, H, D]
    k_pool: jnp.ndarray,  # [P, page, K, Dk]
    v_pool: jnp.ndarray,  # [P, page, K, Dv]
    table: jnp.ndarray,  # [B, MP] int32
    limits: jnp.ndarray,  # [B] int32
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,
    q_pos=None,
    interpret: bool = False,
    kv_scale=None,  # [2, K] f32 per-head (k, v) dequant scales (fp8 KV)
    sink: int = 0,  # windowed+sink decode (docs/LONG_CONTEXT.md)
    swin: int = 0,
):
    """Drop-in for attention._paged_cache_partials: returns
    (acc [B, K, G, Dv], m [B, K, G, 1], l [B, K, G, 1]) f32, scale applied."""
    B, H, D = q.shape
    K = k_pool.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)
    if q_pos is None:
        q_pos = limits
    if sliding is None:
        window = 0
    qr = (q.astype(jnp.float32) * scale).reshape(B, K, G, D)
    qpos_rows = jnp.broadcast_to(q_pos[:, None], (B, G))
    return _paged_partials_rows(
        qr, qpos_rows, k_pool, v_pool, table, limits,
        softcap, window, sliding, interpret, kv_scale=kv_scale,
        sink=sink, swin=swin,
    )


def paged_decode_partials_mq(
    q: jnp.ndarray,  # [B, T, H, D]
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    table: jnp.ndarray,
    limits: jnp.ndarray,
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,
    q_pos=None,  # [B, T]
    interpret: bool = False,
    kv_scale=None,  # [2, K] f32 per-head (k, v) dequant scales (fp8 KV)
    sink: int = 0,  # windowed+sink decode (docs/LONG_CONTEXT.md)
    swin: int = 0,
):
    """Drop-in for attention._paged_cache_partials_mq (speculative verify
    chunk): one page walk shared by all T queries. Returns
    (acc [B, K, G, T, Dv], m [B, K, G, T, 1], l [B, K, G, T, 1])."""
    B, T, H, D = q.shape
    K = k_pool.shape[2]
    G = H // K
    Dv = v_pool.shape[3]
    scale = 1.0 / (D**0.5)
    if q_pos is None:
        q_pos = jnp.broadcast_to(limits[:, None], (B, T))
    if sliding is None:
        window = 0
    # Row r = t*G + g — all T queries fold into one kernel launch.
    qr = (
        (q.astype(jnp.float32) * scale)
        .reshape(B, T, K, G, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B, K, T * G, D)
    )
    qpos_rows = jnp.repeat(q_pos, G, axis=1)  # [B, T*G]
    acc, m, l = _paged_partials_rows(
        qr, qpos_rows, k_pool, v_pool, table, limits,
        softcap, window, sliding, interpret, kv_scale=kv_scale,
        sink=sink, swin=swin,
    )
    acc = acc.reshape(B, K, T, G, Dv).transpose(0, 1, 3, 2, 4)
    m = m.reshape(B, K, T, G, 1).transpose(0, 1, 3, 2, 4)
    l = l.reshape(B, K, T, G, 1).transpose(0, 1, 3, 2, 4)
    return acc, m, l


# Query rows the ragged kernel may hold in VMEM at once. The kernel keeps
# every query row's running (acc, m, l) in VMEM scratch for the whole page
# walk — at 8 kv heads × Dv 128 that is ~4 KB of f32 per row, so a 512-token
# prefill chunk with G=4 query rows per kv head (2048 rows ≈ 8 MB of acc
# alone, plus the q tile) blows the 16 MB scoped-VMEM budget. Prefill chunks
# therefore tile the token axis; each tile re-streams the prefix pages —
# the same O(T/tile) prefix re-read the dense flash kernel pays per q block.
PREFILL_MAX_QROWS = 512


def paged_prefill_partials_mq(
    q: jnp.ndarray,  # [B, T, H, D] — T = prefill-chunk tokens
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    table: jnp.ndarray,
    limits: jnp.ndarray,  # [B] — rows already resident (the chunk's offset)
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,
    q_pos=None,  # [B, T] global positions of the chunk tokens
    interpret: bool = False,
    max_qrows: int = PREFILL_MAX_QROWS,
    kv_scale=None,  # [2, K] f32 per-head (k, v) dequant scales (fp8 KV)
    sink: int = 0,  # windowed+sink prefix walk (docs/LONG_CONTEXT.md)
    swin: int = 0,
):
    """`paged_decode_partials_mq` for prefill-chunk query counts: the T·G
    query-row axis is tiled to `max_qrows` per kernel launch so the chunked
    ragged prefill (models/llama.prefill_chunk_paged) rides the same
    scalar-prefetch page-table kernel as decode at any chunk size. Tiles
    are a static unroll (T and the tile are both static under jit); partials
    concatenate back along T — each token's (acc, m, l) is independent, so
    tiling is exact."""
    B, T, H, D = q.shape
    K = k_pool.shape[2]
    G = H // K
    if q_pos is None:
        q_pos = jnp.broadcast_to(limits[:, None], (B, T))
    tq = max(1, max_qrows // max(G, 1))  # tokens per tile
    if T <= tq:
        return paged_decode_partials_mq(
            q, k_pool, v_pool, table, limits, softcap=softcap, window=window,
            sliding=sliding, q_pos=q_pos, interpret=interpret,
            kv_scale=kv_scale, sink=sink, swin=swin,
        )
    parts = []
    for lo in range(0, T, tq):
        hi = min(lo + tq, T)
        parts.append(paged_decode_partials_mq(
            q[:, lo:hi], k_pool, v_pool, table, limits, softcap=softcap,
            window=window, sliding=sliding, q_pos=q_pos[:, lo:hi],
            interpret=interpret, kv_scale=kv_scale, sink=sink, swin=swin,
        ))
    return tuple(
        jnp.concatenate([p[i] for p in parts], axis=3) for i in range(3)
    )
