"""Page-table representations shared by the paged-attention ops, the model
write paths and the engine (ISSUE 14, docs/LONG_CONTEXT.md).

Two layouts resolve a slot-local page COLUMN index to a pool page id:

- FLAT  — `table[..., MP] int32`: one page id per column. Fine up to tens of
  thousands of rows per slot, but a 1M-token slot at page 128 needs an
  8192-wide row; shipped per dispatch for every slot and scalar-prefetched
  whole into SMEM by the Pallas ragged kernel, that blows the prefetch/VMEM
  budget long before the pool does.
- HIER  — `(l1 [..., ML1] int32, l0 [NTP, SPAN] int32)`: a two-level radix.
  Column j resolves through `l0[l1[..., j // SPAN], j % SPAN]`. The L1
  directory is MP/SPAN entries per slot (64 at 1M tokens, SPAN 128) and the
  L0 table-page pool is GLOBAL — shared CoW across slots, so N slots over
  one long prefix pay its directory once, exactly like its KV pages.

Every consumer goes through these helpers, so one code path serves both
layouts; the engine picks per `EngineConfig.kv_l1_span` (0 = flat).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def is_hier(table) -> bool:
    """True when `table` is the hierarchical (l1, l0) pair."""
    return isinstance(table, (tuple, list))


def width(table) -> int:
    """Logical column count MP (static)."""
    if is_hier(table):
        l1, l0 = table
        return l1.shape[-1] * l0.shape[-1]
    return table.shape[-1]


def gather_cols(table, cols: jnp.ndarray) -> jnp.ndarray:
    """Resolve per-slot column indices to page ids.

    table: flat [B, MP] or hier ([B, ML1], [NTP, SPAN]); cols: [B, N] int.
    Returns [B, N] int32 page ids. Out-of-range columns are the CALLER's
    responsibility to clamp (both layouts index-error past their width)."""
    if is_hier(table):
        l1, l0 = table
        span = l0.shape[-1]
        tp = jnp.take_along_axis(l1, cols // span, axis=-1)  # [B, N]
        return l0[tp, cols % span]
    return jnp.take_along_axis(table, cols, axis=-1)


def row_lookup(table_row, idx):
    """Resolve column indices of ONE slot's table row.

    table_row: flat [MP] or hier ([ML1], [NTP, SPAN]); idx: int array or a
    static python int. Returns page ids shaped like idx."""
    if is_hier(table_row):
        l1, l0 = table_row
        span = l0.shape[-1]
        return l0[l1[idx // span], idx % span]
    return table_row[idx]


def select_row(table, j):
    """Row j of a batched table: flat [m, MP] → [MP]; hier ([m, ML1], l0) →
    ([ML1], l0) — the l0 pool is global, so it rides whole."""
    if is_hier(table):
        l1, l0 = table
        return (l1[j], l0)
    return table[j]


def shard_spec(table, flat_spec, rep_spec):
    """shard_map in_spec for a table operand: the flat layout takes
    `flat_spec`; the hier pair replicates both levels (`rep_spec` each) —
    they are host-built i32 control state, KBs."""
    if is_hier(table):
        return (rep_spec, rep_spec)
    return flat_spec


def batch_row(table_row):
    """Lift one slot's table row to the batched form the chunk programs
    take: flat [MP] → [1, MP]; hier ([ML1], l0) → ([1, ML1], l0)."""
    if is_hier(table_row):
        l1, l0 = table_row
        return (l1[None], l0)
    return table_row[None]


# ------------------------------------------------------------------ #
# Host-side diff/commit helpers (ISSUE 17, engine/runtime.ControlStager):
# the pipelined loop compares each control operand's host bytes against
# its last upload and ships only what changed — usually nothing (steady
# decode) or a handful of table rows (one slot grew).
# ------------------------------------------------------------------ #

def dirty_rows(prev: np.ndarray, cur: np.ndarray) -> np.ndarray:
    """Leading-axis indices where two equal-shape host arrays differ
    (every index for 0-/1-d arrays with any difference, so callers can
    treat `rows.size == 0` uniformly as "unchanged")."""
    if prev.shape != cur.shape or prev.dtype != cur.dtype:
        raise ValueError(
            f"dirty_rows: shape/dtype mismatch {prev.shape}/{prev.dtype} "
            f"vs {cur.shape}/{cur.dtype} — re-key the operand instead"
        )
    neq = prev != cur
    if not neq.any():
        return np.empty((0,), np.int64)
    if cur.ndim < 2:
        return np.arange(cur.shape[0] if cur.ndim else 1, dtype=np.int64)
    return np.nonzero(neq.any(axis=tuple(range(1, cur.ndim))))[0]


def host_equal(prev: np.ndarray, cur: np.ndarray) -> bool:
    """Byte equality of two host tables (shape + dtype + content)."""
    return (prev.shape == cur.shape and prev.dtype == cur.dtype
            and bool(np.array_equal(prev, cur)))
