"""Fused dequant-matmul Pallas kernels for quantized decode (ISSUE 9).

Why this exists: models/quant.py stores weights int8/int4 and relies on XLA
folding the int→float convert into the dot's operand load. That folding is
reliable ONLY for the flat per-channel int8 form. The grouped int8 and
packed-nibble int4 forms go through reshape → unpack lo/hi → concat → scale
→ dot, and XLA materializes the dequantized bf16 copy in HBM first — int4
decode streams ~2.5 bytes/weight instead of ~0.5, which is the whole ballgame
for an HBM-bound decode step (r04: 85.3% of roofline; the gap is exactly
these extra passes).

These kernels do the unpack + affine scale in VMEM registers on the weight
block the Pallas pipeline is already streaming HBM→VMEM (double-buffered
block DMA between grid steps), and accumulate in f32 on the MXU — each
packed weight byte crosses HBM exactly once. Decode-shape only: the row
count (batch × window) is small enough that x and the f32 accumulator sit
whole in VMEM, so the grid walks (expert, out-tile, k-chunk) with the
k-chunk axis innermost, revisiting one out block per (expert, out-tile).

Forms served (matching models/quant.py representations):
- flat int8      {"q": [in, out] i8,      "s": [1, out] f32}
- grouped int8   {"gq": [G, gs, out] i8,  "gs": [G, 1, out] f32}
- packed int4    {"g4": [G, gs/2, out] u8, "gs", "gz": [G, 1, out] f32}
  (value = nibble·s − z; the −z side is a rank-1 correction: −Σᵢx·z per
  group, one extra tiny MXU dot on the per-group x sums)
- MoE variants of all three with a leading expert axis, for the two
  _moe_dense einsum shapes (shared-x and per-expert-x)
- unembed        {"q": [V, D] i8, "s": [V, 1] f32} used transposed (h @ qᵀ·s)

Sharding (ISSUE 7 shard_map wrapping): pallas_call is opaque to GSPMD, so
under a tp>1 mesh the kernels run inside shard_map with the weight specs
parallel/sharding.py already assigns to the q/s/g4 forms — column-parallel
weights shard their out axis ("tp" on the last dim of every leaf),
row-parallel weights shard the group/in axis, and the row-parallel partial
sums psum over "tp" inside the declared boundary below (the same ICI
boundary GSPMD would have placed at the o/down projection).

Dispatch: models/quant.matmul / unembed_matmul and models/llama._moe_mm call
the dispatch_* helpers here; a None return means "not engaged" and the
caller falls through to its XLA form, which stays the numeric oracle
(tests/test_quant.py runs these kernels in interpret mode on CPU against
it, exactly like ops/paged_flash vs the XLA page walk).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

# The ONLY function here allowed to issue cross-chip collectives: the
# row-parallel shard_map closure psums its partial products over "tp" —
# the same o/down-projection boundary GSPMD places for the XLA path
# (lint: sharding-consistency C3).
COLLECTIVE_BOUNDARY = ("_sharded_quant_matmul",)

# Rows (flattened leading dims of x) above which the kernels disengage and
# the XLA path serves: prefill-scale matmuls are compute-bound (the dequant
# copy amortizes over S·D² FLOPs) and their x/accumulator would not fit the
# VMEM-resident decode layout below. Decode blocks (B ≤ max_slots), spec
# verify chunks (B·(k+1)) and short cached-admit tails all sit far under it.
QUANT_PALLAS_MAX_ROWS = 256


def use_pallas_quant(impl: str = "auto") -> bool:
    """Resolve the quantized-matmul kernel choice.

    impl: "auto" (Pallas on TPU, XLA dequant elsewhere), "pallas", or
    "xla". The LOCALAI_QUANT_KERNEL env var overrides — same escape hatch
    as LOCALAI_PAGED_KERNEL for the paged decode kernel. "pallas" off-TPU
    runs in interpret mode (slow; tests only).
    """
    impl = os.environ.get("LOCALAI_QUANT_KERNEL", "") or impl or "auto"
    if impl == "auto":
        return jax.default_backend() == "tpu"
    if impl not in ("pallas", "xla"):
        raise ValueError(f"quant kernel impl {impl!r}: use auto|pallas|xla")
    return impl == "pallas"


def _tile(n: int, targets=(512, 256, 128)) -> int:
    """Largest target that divides n, else n whole (tiny test shapes)."""
    for t in targets:
        if t <= n and n % t == 0:
            return t
    return n


def _rows(x: jnp.ndarray, tail: int = 1) -> int:
    r = 1
    for d in x.shape[: x.ndim - tail]:
        r *= int(d)
    return r


def _tp_degree(mesh) -> int:
    if mesh is None:
        return 1
    return int(mesh.shape.get("tp", 1))


# --------------------------------------------------------------------------- #
# Kernels
# --------------------------------------------------------------------------- #


def _qmm_kernel(x_ref, w_ref, s_ref, *rest, gs: int, gc: int, packed: bool):
    """One (expert, out-tile, k-chunk) grid step of the dequant-matmul.

    Blocks: x (1, N, kc) float, w (1, kc[/2], bo) i8/u8, s (1, gc|1, bo)
    f32, optional z (1, gc, bo) f32, out (1, N, bo), acc scratch (N, bo)
    f32. gs == 0 means the flat per-channel form (scale applied once at the
    final write); packed means two nibbles per weight byte along the
    in-group axis (low nibble = first gs/2 elements — models/quant.py).
    """
    import jax.experimental.pallas as pl

    z_ref = rest[0] if len(rest) == 3 else None
    o_ref, acc_ref = rest[-2], rest[-1]
    k = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xb = x_ref[0].astype(jnp.float32)  # [N, kc]
    wb = w_ref[0]  # [kc(,/2), bo] int8/uint8
    bo = wb.shape[-1]
    if packed:
        half = gs // 2
        wp = wb.reshape(gc, half, bo)
        nib = jnp.concatenate([wp & jnp.uint8(0xF), wp >> jnp.uint8(4)],
                              axis=1)  # [gc, gs, bo]
        wf = nib.astype(jnp.float32)
    elif gs:
        wf = wb.reshape(gc, gs, bo).astype(jnp.float32)
    else:
        wf = wb.astype(jnp.float32)  # flat: [kc, bo]
    if gs:
        # Dequant in registers: the scaled f32 weight tile exists only in
        # VMEM for this one MXU pass — never written back to HBM.
        sb = s_ref[0].astype(jnp.float32)  # [gc, bo]
        wf = (wf * sb[:, None, :]).reshape(gc * gs, bo)
    acc_ref[...] += jax.lax.dot_general(
        xb, wf, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if z_ref is not None:
        # Affine zero point: −Σᵢ x_{g,i} · z_{g,o} per group.
        zb = z_ref[0].astype(jnp.float32)  # [gc, bo]
        xs = xb.reshape(xb.shape[0], gc, gs).sum(axis=-1)  # [N, gc]
        acc_ref[...] -= jax.lax.dot_general(
            xs, zb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(k == nk - 1)
    def _emit():
        res = acc_ref[...]
        if not gs:
            res = res * s_ref[0].astype(jnp.float32)  # [1, bo] broadcasts
        o_ref[0] = res.astype(o_ref.dtype)


def _unembed_kernel(h_ref, w_ref, s_ref, o_ref, acc_ref):
    """h @ qᵀ · s for the vocab-major lm_head layout {"q": [V, D],
    "s": [V, 1]} — each out tile streams contiguous weight ROWS, so the
    transpose never materializes. Blocks: h (N, kc), w (bv, kc), s (bv, 1),
    out (N, bv) f32."""
    import jax.experimental.pallas as pl

    k = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    hb = h_ref[...].astype(jnp.float32)  # [N, kc]
    wb = w_ref[...].astype(jnp.float32)  # [bv, kc]
    acc_ref[...] += jax.lax.dot_general(
        hb, wb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == nk - 1)
    def _emit():
        o_ref[...] = acc_ref[...] * s_ref[...][:, 0][None, :]


# --------------------------------------------------------------------------- #
# pallas_call wrappers (local shapes — shard_map hands these per-chip views)
# --------------------------------------------------------------------------- #


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _qmm_call(x3, wq, s3, z3, *, gs: int, packed: bool, out_dtype,
              x_per_expert: bool):
    """Grid launch over (E, out-tiles, k-chunks).

    x3 [Ex, N, Kin] float (Ex = E when per-expert, else 1); wq [E, Kin(/2),
    out] int; s3 [E, G|1, out] f32; z3 [E, G, out] f32 or None. Returns
    [E, N, out] in out_dtype.
    """
    import jax.experimental.pallas as pl

    E, kin_w, out = wq.shape
    _, N, kin = x3.shape
    if gs:
        g = kin // gs
        gc = _tile(g, (16, 8, 4, 2))
        kc = gc * gs
        kc_w = kc // 2 if packed else kc
    else:
        kc = _tile(kin)
        kc_w = kc
        gc = 1
    bo = _tile(out)
    nk = kin // kc
    grid = (E, out // bo, nk)

    def xi(e, j, k):
        return ((e, 0, k) if x_per_expert else (0, 0, k))

    in_specs = [
        pl.BlockSpec((1, N, kc), xi),
        pl.BlockSpec((1, kc_w, bo), lambda e, j, k: (e, k, j)),
        pl.BlockSpec(
            (1, gc if gs else 1, bo),
            (lambda e, j, k: (e, k, j)) if gs else (lambda e, j, k: (e, 0, j)),
        ),
    ]
    args = [x3, wq, s3]
    if z3 is not None:
        in_specs.append(pl.BlockSpec((1, gc, bo), lambda e, j, k: (e, k, j)))
        args.append(z3)
    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(_qmm_kernel, gs=gs, gc=gc, packed=packed)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, N, bo), lambda e, j, k: (e, 0, j)),
        out_shape=jax.ShapeDtypeStruct((E, N, out), out_dtype),
        scratch_shapes=[pltpu.VMEM((N, bo), jnp.float32)],
        interpret=_interpret(),
    )(*args)


def _plain_matmul(x: jnp.ndarray, w: dict) -> jnp.ndarray:
    """Non-MoE quantized x @ w on local (possibly shard-local) shapes."""
    lead = x.shape[:-1]
    n = _rows(x)
    x3 = x.reshape(1, n, x.shape[-1])
    if "q" in w:
        out = _qmm_call(
            x3, w["q"][None], w["s"].reshape(1, 1, -1), None,
            gs=0, packed=False, out_dtype=x.dtype, x_per_expert=False,
        )
        return out.reshape(*lead, -1)
    packed = "g4" in w
    wq = (w["g4"] if packed else w["gq"])  # [G, gs(/2), out]
    g, gsw, out_dim = wq.shape
    gs_width = gsw * (2 if packed else 1)
    s3 = w["gs"][..., 0, :][None]  # [1, G, out]
    z3 = w["gz"][..., 0, :][None] if "gz" in w else None
    out = _qmm_call(
        x3, wq.reshape(1, g * gsw, out_dim), s3, z3,
        gs=gs_width, packed=packed, out_dtype=x.dtype, x_per_expert=False,
    )
    return out.reshape(*lead, -1)


def _plain_moe_mm(x: jnp.ndarray, w: dict, sub: str) -> jnp.ndarray:
    """MoE dequant-matmul for the two _moe_dense einsum shapes."""
    per_expert = sub == "...ef,efd->...ed"
    if per_expert:
        lead = x.shape[:-2]
        e = x.shape[-2]
        n = _rows(x, tail=2)
        # [.., E, F] → [E, N, F]
        x3 = jnp.moveaxis(x.reshape(n, e, x.shape[-1]), 1, 0)
    else:
        lead = x.shape[:-1]
        n = _rows(x)
        x3 = x.reshape(1, n, x.shape[-1])
    if "q" in w:
        out = _qmm_call(
            x3, w["q"], w["s"], None,  # s already [E, 1, out]
            gs=0, packed=False, out_dtype=x.dtype, x_per_expert=per_expert,
        )
    else:
        packed = "g4" in w
        wq3 = w["g4"] if packed else w["gq"]  # [E, G, gs(/2), out]
        e_, g, gsw, out_dim = wq3.shape
        gs_width = gsw * (2 if packed else 1)
        out = _qmm_call(
            x3, wq3.reshape(e_, g * gsw, out_dim),
            w["gs"][..., 0, :], w["gz"][..., 0, :] if "gz" in w else None,
            gs=gs_width, packed=packed, out_dtype=x.dtype,
            x_per_expert=per_expert,
        )
    # out [E, N, F|D] → [.., E, F|D]
    y = jnp.moveaxis(out, 0, 1)  # [N, E, F|D]
    return y.reshape(*lead, y.shape[1], y.shape[2])


def _plain_unembed(h: jnp.ndarray, w: dict) -> jnp.ndarray:
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    lead = h.shape[:-1]
    n = _rows(h)
    d = h.shape[-1]
    v = w["q"].shape[0]
    h2 = h.reshape(n, d)
    bv = _tile(v)
    kc = _tile(d)
    out = pl.pallas_call(
        _unembed_kernel,
        grid=(v // bv, d // kc),
        in_specs=[
            pl.BlockSpec((n, kc), lambda j, k: (0, k)),
            pl.BlockSpec((bv, kc), lambda j, k: (j, k)),
            pl.BlockSpec((bv, 1), lambda j, k: (j, 0)),
        ],
        out_specs=pl.BlockSpec((n, bv), lambda j, k: (0, j)),
        out_shape=jax.ShapeDtypeStruct((n, v), jnp.float32),
        scratch_shapes=[pltpu.VMEM((n, bv), jnp.float32)],
        interpret=_interpret(),
    )(h2, w["q"], w["s"].astype(jnp.float32))
    return out.reshape(*lead, v)


# --------------------------------------------------------------------------- #
# Sharded dispatch (tp>1 — shard_map over the weight's own partitioning)
# --------------------------------------------------------------------------- #


def _w_specs(w: dict, part: str, moe: bool):
    """PartitionSpecs for a quantized dict's leaves, mirroring
    parallel/sharding.param_shardings_for: col shards every leaf's out
    (last) axis; row shards the group/in axis (the flat scale is per-out
    and stays replicated)."""
    from jax.sharding import PartitionSpec as P

    off = 1 if moe else 0
    specs = {}
    for key, leaf in w.items():
        ax = [None] * leaf.ndim
        if part in ("col", "unembed"):
            # unembed's out axis is the leading V axis of [V, D]/[V, 1].
            ax[0 if part == "unembed" else -1] = "tp"
        elif key != "s":  # row: q in-axis / grouped G-axis; flat s replicated
            ax[off] = "tp"
        specs[key] = P(*ax)
    return specs


def _sharded_quant_matmul(x, w, mesh, part: str, moe_sub=None):
    """Run the local kernel per tp shard; row-parallel partials psum over
    "tp" here (the declared ICI boundary — see COLLECTIVE_BOUNDARY)."""
    from jax.sharding import PartitionSpec as P

    from localai_tpu.parallel.mesh import shard_map as _shard_map

    row = part == "row"
    x_ax = [None] * x.ndim
    if row:
        x_ax[-1] = "tp"
    if part == "unembed":
        out_ndim = x.ndim
    elif moe_sub == "...d,edf->...ef":
        out_ndim = x.ndim + 1
    else:
        out_ndim = x.ndim
    o_ax = [None] * out_ndim
    if not row:
        o_ax[-1] = "tp"

    def local(xl, wl):
        if part == "unembed":
            y = _plain_unembed(xl, wl)
        elif moe_sub is not None:
            y = _plain_moe_mm(xl, wl, moe_sub)
        else:
            y = _plain_matmul(xl, wl)
        if row:
            y = jax.lax.psum(y, "tp")
        return y

    leaf = w.get("q", w.get("gq", w.get("g4")))
    moe = leaf.ndim == (3 if "q" in w else 4)
    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(P(*x_ax), _w_specs(w, part, moe=moe)),
        out_specs=P(*o_ax),
        check_vma=False,
    )
    return fn(x, w)


# --------------------------------------------------------------------------- #
# Dispatchers (return None → caller falls back to its XLA oracle form)
# --------------------------------------------------------------------------- #


def _engaged(x, impl: str, tail: int = 1) -> bool:
    return (
        use_pallas_quant(impl)
        and jnp.issubdtype(x.dtype, jnp.floating)
        and _rows(x, tail) <= QUANT_PALLAS_MAX_ROWS
        and _rows(x, tail) > 0
    )


def _shardable(x, w: dict, part: str, tp: int, moe_off: int = 0) -> bool:
    """Every axis a tp shard_map would split must divide by tp — otherwise
    fall back to the XLA path (which GSPMD partitions or replicates as it
    can). col splits the out axis; row splits x's reduction axis and the
    weight's in/group axis."""
    leaf = w.get("q", w.get("gq", w.get("g4")))
    if part in ("col", "unembed"):
        out_ax = 0 if part == "unembed" else leaf.ndim - 1
        return leaf.shape[out_ax] % tp == 0
    return (x.shape[-1] % tp == 0
            and leaf.shape[moe_off] % tp == 0)


def dispatch_matmul(x, w: dict, impl: str = "auto", mesh=None, part=None):
    """Fused x @ w for the non-MoE quantized forms, or None to fall back."""
    leaf = w.get("q", w.get("gq", w.get("g4")))
    if leaf is None or leaf.ndim != (2 if "q" in w else 3):
        return None
    if not _engaged(x, impl):
        return None
    tp = _tp_degree(mesh)
    if tp > 1 and part in ("col", "row"):
        if not _shardable(x, w, part, tp):
            return None
        return _sharded_quant_matmul(x, w, mesh, part)
    return _plain_matmul(x, w)


def dispatch_moe_mm(x, w: dict, sub: str, impl: str = "auto", mesh=None):
    """Fused MoE dequant-matmul for _moe_dense's two einsum shapes, or
    None to fall back. Part is implied by the shape: edf projects OUT to
    the tp-sharded F axis (col), efd contracts the sharded F axis (row).
    Expert-parallel (ep>1) meshes fall back to the XLA path."""
    if sub not in ("...d,edf->...ef", "...ef,efd->...ed"):
        return None
    per_expert = sub == "...ef,efd->...ed"
    if not _engaged(x, impl, tail=2 if per_expert else 1):
        return None
    tp = _tp_degree(mesh)
    if tp > 1:
        part = "row" if per_expert else "col"
        if int(mesh.shape.get("ep", 1)) > 1:
            return None
        if not _shardable(x, w, part, tp, moe_off=1):
            return None
        return _sharded_quant_matmul(x, w, mesh, part, moe_sub=sub)
    return _plain_moe_mm(x, w, sub)


def dispatch_unembed(h, w: dict, impl: str = "auto", mesh=None):
    """Fused h @ qᵀ·s for the quantized lm_head, or None to fall back."""
    if "q" not in w or w["q"].ndim != 2 or w["s"].shape[-1] != 1:
        return None
    if not _engaged(h, impl):
        return None
    tp = _tp_degree(mesh)
    if tp > 1:
        if not _shardable(h, w, "unembed", tp):
            return None
        return _sharded_quant_matmul(h, w, mesh, "unembed")
    return _plain_unembed(h, w)
