"""Rotary position embeddings with linear and llama3 frequency scaling.

The reference forwards rope knobs to llama.cpp (core/config/model_config.go:231-237
`rope_scaling`, `rope_freq_base`); here the same knobs select the frequency
schedule used by the JAX model. Frequencies are computed once per call in
float32; XLA constant-folds them under jit when positions are traced but the
config is static.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from localai_tpu.models.config import ArchConfig


def rope_frequencies(cfg: ArchConfig) -> jnp.ndarray:
    """Per-pair inverse frequencies [head_dim/2], float32."""
    hd = cfg.head_dim_
    inv_freq = 1.0 / (cfg.rope_theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    if cfg.rope_scaling == "linear":
        inv_freq = inv_freq / cfg.rope_scaling_factor
    elif cfg.rope_scaling == "llama3":
        # Llama-3.1/3.2 long-context NTK-by-parts scaling.
        low_wavelen = cfg.rope_original_max_position / cfg.rope_low_freq_factor
        high_wavelen = cfg.rope_original_max_position / cfg.rope_high_freq_factor
        wavelen = 2.0 * math.pi / inv_freq
        scaled = inv_freq / cfg.rope_scaling_factor
        smooth = (cfg.rope_original_max_position / wavelen - cfg.rope_low_freq_factor) / (
            cfg.rope_high_freq_factor - cfg.rope_low_freq_factor
        )
        smooth = jnp.clip(smooth, 0.0, 1.0)
        mid = (1.0 - smooth) * scaled + smooth * inv_freq
        inv_freq = jnp.where(wavelen > low_wavelen, scaled, jnp.where(wavelen < high_wavelen, inv_freq, mid))
    return inv_freq


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, inv_freq: jnp.ndarray) -> jnp.ndarray:
    """Rotate half-pairs. x: [..., seq, heads, head_dim], positions: [..., seq]."""
    angles = positions[..., :, None].astype(jnp.float32) * inv_freq  # [..., seq, hd/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., seq, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
