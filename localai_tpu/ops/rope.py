"""Rotary position embeddings with linear and llama3 frequency scaling.

The reference forwards rope knobs to llama.cpp (core/config/model_config.go:231-237
`rope_scaling`, `rope_freq_base`); here the same knobs select the frequency
schedule used by the JAX model. Frequencies are computed once per call in
float32; XLA constant-folds them under jit when positions are traced but the
config is static.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from localai_tpu.models.config import ArchConfig


def rope_frequencies(cfg: ArchConfig) -> jnp.ndarray:
    """Per-pair inverse frequencies [head_dim/2], float32.

    Implements every scaling family the reference forwards to its engines
    (core/config/model_config.go:231-237 rope_scaling/yarn params →
    grpc-server.cpp params_parse): linear, llama-3 NTK-by-parts, yarn, and
    phi-3 longrope. The matching attention-amplitude factor (yarn mscale /
    longrope scaling) is served by `rope_query_amp`."""
    # Under MLA only the qk_rope_head_dim slice of q/k rotates (HF deepseek
    # configs set head_dim to the same value, but don't rely on it).
    hd = cfg.qk_rope_head_dim if cfg.is_mla else cfg.head_dim_
    dims = jnp.arange(0, hd, 2, dtype=jnp.float32)
    inv_freq = 1.0 / (cfg.rope_theta ** (dims / hd))
    if cfg.rope_scaling == "linear":
        inv_freq = inv_freq / cfg.rope_scaling_factor
    elif cfg.rope_scaling == "llama3":
        # Llama-3.1/3.2 long-context NTK-by-parts scaling.
        low_wavelen = cfg.rope_original_max_position / cfg.rope_low_freq_factor
        high_wavelen = cfg.rope_original_max_position / cfg.rope_high_freq_factor
        wavelen = 2.0 * math.pi / inv_freq
        scaled = inv_freq / cfg.rope_scaling_factor
        smooth = (cfg.rope_original_max_position / wavelen - cfg.rope_low_freq_factor) / (
            cfg.rope_high_freq_factor - cfg.rope_low_freq_factor
        )
        smooth = jnp.clip(smooth, 0.0, 1.0)
        mid = (1.0 - smooth) * scaled + smooth * inv_freq
        inv_freq = jnp.where(wavelen > low_wavelen, scaled, jnp.where(wavelen < high_wavelen, inv_freq, mid))
    elif cfg.rope_scaling == "yarn":
        # YaRN (Peng et al.): interpolate low frequencies by `factor`,
        # extrapolate high frequencies unchanged, with a linear ramp between
        # the beta_fast/beta_slow rotation counts (HF _compute_yarn_parameters).
        factor = cfg.rope_scaling_factor
        orig = cfg.rope_original_max_position

        def correction_dim(n_rot: float) -> float:
            return (hd * math.log(orig / (n_rot * 2 * math.pi))) / (
                2 * math.log(cfg.rope_theta)
            )

        low = max(math.floor(correction_dim(cfg.rope_beta_fast)), 0)
        high = min(math.ceil(correction_dim(cfg.rope_beta_slow)), hd - 1)
        ramp = jnp.clip((dims / 2 - low) / max(high - low, 1e-3), 0.0, 1.0)
        extrapolation_factor = 1.0 - ramp
        inv_freq = (
            inv_freq / factor * (1.0 - extrapolation_factor)
            + inv_freq * extrapolation_factor
        )
    elif cfg.rope_scaling == "longrope":
        # Phi-3 LongRoPE ("su"): a published per-frequency rescale table.
        # The long table serves when the deployment window exceeds the
        # original training window (the static serving choice; the reference
        # delegates the same decision to its engines per max context).
        use_long = cfg.max_position > cfg.rope_original_max_position
        table = cfg.rope_long_factor if use_long else cfg.rope_short_factor
        if table is None:
            raise ValueError(
                "rope_scaling 'longrope' requires long/short factor tables"
            )
        ext = jnp.asarray(table, jnp.float32)
        if ext.shape[0] != hd // 2:
            raise ValueError(
                f"longrope factor table has {ext.shape[0]} entries, head_dim "
                f"{hd} needs {hd // 2}"
            )
        inv_freq = 1.0 / (ext * cfg.rope_theta ** (dims / hd))
    elif cfg.rope_scaling not in (None, ""):
        raise ValueError(f"unknown rope_scaling {cfg.rope_scaling!r}")
    return inv_freq


def rope_frequencies_local(cfg: ArchConfig) -> jnp.ndarray | None:
    """Sliding (local) layers' inverse frequencies, or None when all layers
    share one schedule. Gemma-3 runs local layers on their own UNSCALED base
    (rope_local_base_freq) while global layers use rope_theta + scaling."""
    if not cfg.rope_local_theta:
        return None
    hd = cfg.head_dim_
    dims = jnp.arange(0, hd, 2, dtype=jnp.float32)
    return 1.0 / (cfg.rope_local_theta ** (dims / hd))


def rope_query_amp(cfg: ArchConfig) -> float:
    """Static query pre-multiplier carrying the scaling family's attention-
    amplitude correction. HF scales BOTH cos/sin tables by `attention_factor`
    m (so scores gain m²); scaling q alone by m² is mathematically identical
    and keeps the cached K unmodified."""
    if cfg.rope_scaling == "yarn":
        m = (
            cfg.rope_attn_factor
            if cfg.rope_attn_factor is not None
            else 0.1 * math.log(cfg.rope_scaling_factor) + 1.0
        )
        return float(m * m)
    if cfg.rope_scaling == "longrope":
        if cfg.rope_attn_factor is not None:
            m = cfg.rope_attn_factor
        else:
            factor = cfg.max_position / max(cfg.rope_original_max_position, 1)
            m = (
                math.sqrt(1.0 + math.log(factor) / math.log(cfg.rope_original_max_position))
                if factor > 1.0
                else 1.0
            )
        return float(m * m)
    return 1.0


def rope_rotate(x: jnp.ndarray, angles: jnp.ndarray) -> jnp.ndarray:
    """Split-half rotation from precomputed angles [..., seq, head_dim/2];
    x: [..., seq, heads, head_dim]."""
    cos = jnp.cos(angles)[..., None, :]  # [..., seq, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, inv_freq: jnp.ndarray) -> jnp.ndarray:
    """Rotate half-pairs. x: [..., seq, heads, head_dim], positions: [..., seq]."""
    angles = positions[..., :, None].astype(jnp.float32) * inv_freq  # [..., seq, hd/2]
    return rope_rotate(x, angles)


def mrope_angles(pos3: jnp.ndarray, inv_freq: jnp.ndarray,
                 sections: tuple) -> jnp.ndarray:
    """Qwen2-VL multimodal rope angles.

    pos3 [B, 3, S] carries (temporal, height, width) position streams per
    token; `sections` (e.g. (16, 24, 24), summing to head_dim/2) assigns
    each frequency index to one stream — HF Qwen2VLAttention splits the
    duplicated cos/sin tables into mrope_section*2 chunks and takes chunk i
    from stream i%3, which reduces to per-frequency stream selection over
    the first half. Returns angles [B, S, head_dim/2] for rope_rotate.
    Text-only prompts (all three streams equal) reduce exactly to
    apply_rope; that is what makes plain-rope decode with a per-slot
    position delta valid after a multimodal prefill."""
    import numpy as np

    assert sum(sections) == inv_freq.shape[0], (sections, inv_freq.shape)
    axis_of = jnp.asarray(np.repeat(np.arange(3), sections))  # [hd/2]
    pos_sel = jnp.take(pos3, axis_of, axis=1)  # [B, hd/2, S]
    return pos_sel.transpose(0, 2, 1).astype(jnp.float32) * inv_freq
