"""Batched, per-slot parameterized token sampling.

The reference's sampler lives inside llama.cpp (params parsed at
backend/cpp/llama-cpp/grpc-server.cpp:118 parse_options: temperature, top_k,
top_p, min_p, repeat/presence/frequency penalties, seed, logit bias). Here the
whole chain is one jitted function over the decode batch: every slot carries
its own sampling parameters as array entries, so one compiled program serves
heterogeneous requests (no recompile per request — that is the continuous-
batching contract).

Grammar-constrained decoding plugs in through `logit_bias`: the engine writes
-inf outside the grammar-allowed token set (reference equivalent: GBNF
sampling inside llama.cpp, pkg/functions grammar generation).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


class SamplingParams(NamedTuple):
    """Per-slot sampling parameters; every field has shape [B]."""

    temperature: jnp.ndarray  # f32; <= 0 means greedy
    top_k: jnp.ndarray  # i32; 0 disables
    top_p: jnp.ndarray  # f32; >= 1 disables
    min_p: jnp.ndarray  # f32; 0 disables
    repeat_penalty: jnp.ndarray  # f32; 1.0 disables (llama.cpp semantics)
    presence_penalty: jnp.ndarray  # f32; 0 disables
    frequency_penalty: jnp.ndarray  # f32; 0 disables

    @staticmethod
    def make(
        batch: int,
        temperature=0.0,
        top_k=0,
        top_p=1.0,
        min_p=0.0,
        repeat_penalty=1.0,
        presence_penalty=0.0,
        frequency_penalty=0.0,
    ) -> "SamplingParams":
        full = lambda v, dt: jnp.full((batch,), v, dtype=dt)
        return SamplingParams(
            temperature=full(temperature, jnp.float32),
            top_k=full(top_k, jnp.int32),
            top_p=full(top_p, jnp.float32),
            min_p=full(min_p, jnp.float32),
            repeat_penalty=full(repeat_penalty, jnp.float32),
            presence_penalty=full(presence_penalty, jnp.float32),
            frequency_penalty=full(frequency_penalty, jnp.float32),
        )


def apply_penalties(
    logits: jnp.ndarray,  # [B, V] f32
    counts: jnp.ndarray,  # [B, V] i32 — occurrences of each token so far (prompt+generated)
    params: SamplingParams,
) -> jnp.ndarray:
    seen = counts > 0
    rp = params.repeat_penalty[:, None]
    penalized = jnp.where(logits > 0, logits / rp, logits * rp)
    logits = jnp.where(seen, penalized, logits)
    logits = logits - params.presence_penalty[:, None] * seen.astype(jnp.float32)
    logits = logits - params.frequency_penalty[:, None] * counts.astype(jnp.float32)
    return logits


def _filter_sorted(sorted_logits: jnp.ndarray, params: SamplingParams) -> jnp.ndarray:
    """Apply top-k, then top-p, then min-p on descending-sorted logits [B, K].

    Chain semantics match llama.cpp: each stage renormalizes over the
    candidate set left by the previous stage (top-p mass is measured over the
    post-top-k distribution, min-p against the surviving max-probability).
    K may be a partial candidate set (see `sample`); top_k larger than K is
    clamped to K.
    """
    B, V = sorted_logits.shape
    ranks = jnp.arange(V)[None, :]

    k = jnp.where(params.top_k <= 0, V, jnp.minimum(params.top_k, V))[:, None]
    keep = ranks < k

    # Renormalized softmax over the top-k survivors (masked-out rows get 0).
    probs = jax.nn.softmax(jnp.where(keep, sorted_logits, NEG_INF), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep tokens until the cumulative mass *before* this token reaches top_p
    # (always keeps the first token).
    keep_p = (cum - probs) < params.top_p[:, None]
    keep = jnp.logical_and(keep, keep_p)

    # min-p over the post-top-p survivors, renormalized.
    probs = jax.nn.softmax(jnp.where(keep, sorted_logits, NEG_INF), axis=-1)
    keep_mp = probs >= params.min_p[:, None] * probs[:, :1]
    keep = jnp.logical_and(keep, keep_mp)

    keep = keep.at[:, 0].set(True)  # never mask everything
    return jnp.where(keep, sorted_logits, NEG_INF)


def sample(
    logits: jnp.ndarray,  # [B, V] any float dtype
    rng: jnp.ndarray,  # [B] batch of PRNG keys (jax.random.key dtype)
    params: SamplingParams,
    counts: jnp.ndarray | None = None,  # [B, V] i32
    logit_bias: jnp.ndarray | None = None,  # [B, V] f32 (grammar masks, user bias)
    num_candidates: int = 64,
) -> jnp.ndarray:
    """Sample one token per slot. Returns [B] int32.

    TPU note: a full-vocab sort is a multi-ms operation at V=128k, so the
    filter chain runs over a partial top-`num_candidates` candidate set
    (exact when V <= num_candidates, e.g. every test arch). Consequences on
    a real vocab: `top_k` is clamped to num_candidates (llama.cpp default is
    40), and top-p mass is measured over the renormalized top-candidate head
    — the tail mass beyond 64 candidates is negligible for any top_p < 1.
    Slots with no filters active sample the exact full distribution via
    `jax.random.categorical` (Gumbel argmax — no sort at all).
    """
    logits = logits.astype(jnp.float32)
    if counts is not None:
        logits = apply_penalties(logits, counts, params)
    if logit_bias is not None:
        logits = logits + logit_bias

    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temp = jnp.maximum(params.temperature, 1e-6)[:, None]

    # llama.cpp chain order: top-k/top-p/min-p filter on unscaled logits,
    # temperature last — so the kept support is temperature-independent.
    K = min(num_candidates, logits.shape[-1])
    sorted_logits, sorted_idx = jax.lax.top_k(logits, K)
    filtered = _filter_sorted(sorted_logits, params)
    filtered = jnp.where(filtered <= NEG_INF, NEG_INF, filtered / temp)

    def draw(key, row):
        return jax.random.categorical(key, row)

    pos = jax.vmap(draw)(rng, filtered)
    cand_tok = jnp.take_along_axis(sorted_idx, pos[:, None], axis=-1)[:, 0].astype(jnp.int32)

    # Exact full-distribution draw for unfiltered slots.
    free_tok = jax.vmap(draw)(rng, logits / temp).astype(jnp.int32)

    needs_filter = (params.top_k > 0) | (params.top_p < 1.0) | (params.min_p > 0.0)
    sampled_tok = jnp.where(needs_filter, cand_tok, free_tok)
    return jnp.where(params.temperature <= 0.0, greedy_tok, sampled_tok)


def sample_simple(
    logits: jnp.ndarray,  # [B, V]
    rng: jnp.ndarray,  # [B] PRNG keys
    params: SamplingParams,
    counts: jnp.ndarray | None = None,
    logit_bias: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Greedy + exact unfiltered categorical only — no top-k/top-p/min-p.

    The engine dispatches this variant when no active slot has filters
    enabled; it avoids the partial-sort entirely (one Gumbel argmax pass).
    """
    logits = logits.astype(jnp.float32)
    if counts is not None:
        logits = apply_penalties(logits, counts, params)
    if logit_bias is not None:
        logits = logits + logit_bias
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    free_tok = jax.vmap(jax.random.categorical)(rng, logits / temp).astype(jnp.int32)
    return jnp.where(params.temperature <= 0.0, greedy_tok, free_tok)


def sample_greedy(
    logits: jnp.ndarray,  # [B, V]
    params: SamplingParams,
    counts: jnp.ndarray | None = None,
    logit_bias: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Pure argmax (with penalties/bias) — the cheapest per-step sampler."""
    logits = logits.astype(jnp.float32)
    if counts is not None:
        logits = apply_penalties(logits, counts, params)
    if logit_bias is not None:
        logits = logits + logit_bias
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def update_counts(counts: jnp.ndarray, tokens: jnp.ndarray, active: jnp.ndarray) -> jnp.ndarray:
    """counts[b, tokens[b]] += 1 for active slots. All shapes static."""
    B = counts.shape[0]
    inc = active.astype(counts.dtype)
    return counts.at[jnp.arange(B), tokens].add(inc)


def deterministic_accept(
    pl: jnp.ndarray,  # [B, V] target processed log-probs (processed_logprobs)
    x: jnp.ndarray,  # [B] int32 draft token under test
):
    """Speculative accept inputs for a DETERMINISTIC draft source (prompt
    lookup, ISSUE 12): the proposal distribution q is a point mass at x, so
    the canonical test accept-w.p.-min(1, p(x)/q(x)) reduces to p(x), and
    the rejection draw normalize(max(p - q, 0)) reduces to p with x zeroed,
    renormalized. Returns (log_ratio [B] = log p(x), residual_logprobs
    [B, V]); greedy (one-hot p) degenerates to exact argmax agreement —
    reject unless x IS the argmax, then resample lands on the argmax.
    """
    B, V = pl.shape
    idx = jnp.arange(B)
    log_ratio = pl[idx, x]
    res = jnp.where(jnp.arange(V)[None, :] == x[:, None], 0.0, jnp.exp(pl))
    mass = res.sum(axis=-1, keepdims=True)
    res_log = jnp.where(
        mass > 1e-9,
        jnp.log(res / jnp.maximum(mass, 1e-9) + 1e-38),
        pl,  # residual mass ~0: the draft matched p's entire support
    )
    return log_ratio, res_log


def processed_logprobs(
    logits: jnp.ndarray,  # [B, V] any float dtype
    params: SamplingParams,
    counts: jnp.ndarray | None = None,  # [B, V] i32
    logit_bias: jnp.ndarray | None = None,  # [B, V] f32
    num_candidates: int = 64,
) -> jnp.ndarray:
    """Full post-chain sampling distribution as log-probs [B, V] f32.

    Exactly the distribution `sample` draws from — penalties, bias, the
    top-k/top-p/min-p chain over the partial candidate set, temperature, and
    the temperature==0 greedy degenerate (one-hot). Speculative decoding's
    stochastic verify (accept w.p. min(1, p/q), resample from max(p-q, 0))
    needs the *distributions* of both models, and using one shared
    implementation for p and q is what makes the acceptance test exact.
    """
    logits = logits.astype(jnp.float32)
    if counts is not None:
        logits = apply_penalties(logits, counts, params)
    if logit_bias is not None:
        logits = logits + logit_bias
    B, V = logits.shape

    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    has_filter = (
        (params.top_k > 0) | (params.top_p < 1.0) | (params.min_p > 0.0)
    )[:, None]

    K = min(num_candidates, V)
    sorted_logits, sorted_idx = jax.lax.top_k(logits, K)
    filtered = _filter_sorted(sorted_logits, params)
    scattered = jnp.full((B, V), NEG_INF, jnp.float32)
    scattered = scattered.at[jnp.arange(B)[:, None], sorted_idx].set(filtered)

    eff = jnp.where(has_filter, scattered, logits) / temp
    # temperature == 0 → degenerate one-hot on the argmax (greedy)
    greedy_tok = jnp.argmax(logits, axis=-1)
    onehot = jnp.where(
        jnp.arange(V)[None, :] == greedy_tok[:, None], 0.0, NEG_INF
    )
    eff = jnp.where((params.temperature <= 0.0)[:, None], onehot, eff)
    return jax.nn.log_softmax(eff, axis=-1)
