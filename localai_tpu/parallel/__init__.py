"""Parallelism: device meshes, sharding plans, and collective patterns.

TPU-native replacement for the reference's delegated parallelism
(llama.cpp `tensor_split` across GPUs, vLLM `tensor_parallel_size`, llama.cpp
RPC layer split over libp2p tunnels — SURVEY.md §2.5). Here every strategy is
a mesh axis:

  dp — data/batch parallel (request-level)
  tp — tensor parallel (Megatron column/row splits over ICI)
  ep — expert parallel (MoE expert axis)
  sp — sequence/context parallel (ring attention for long context)

XLA inserts the collectives (psum/all_gather/reduce_scatter/ppermute) from the
shardings; nothing here opens a socket.
"""

from localai_tpu.parallel.mesh import MeshPlan, build_mesh  # noqa: F401
from localai_tpu.parallel.sharding import (  # noqa: F401
    ShardingPlanError,
    cache_shardings,
    max_valid_tp,
    param_shardings,
)
