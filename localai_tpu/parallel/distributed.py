"""Multi-host bootstrap: jax.distributed over DCN.

The reference scales out with llama.cpp RPC workers over TCP and a libp2p
DHT for discovery (core/p2p/p2p.go). The TPU equivalent is structurally
different and simpler: within a slice, chips already share ICI and XLA
compiles the collectives; across hosts/slices, `jax.distributed.initialize`
wires the processes into one global mesh over DCN, and the HTTP-level
federation router (localai_tpu.federation) spreads requests across
independent serving processes.

Env contract (mirrors the reference's worker env flags, core/cli/worker):
  LOCALAI_COORDINATOR     host:port of process 0
  LOCALAI_NUM_PROCESSES   total process count
  LOCALAI_PROCESS_ID      this process's rank
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger("localai_tpu.distributed")


def init_distributed(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed from args or LOCALAI_* env; returns True
    when a multi-process runtime was started, False for single-process runs.

    After this returns True, `jax.devices()` spans every host and a Mesh
    built from it shards programs across the whole pod (dp/tp/... axes ride
    ICI within a slice and DCN across slices).
    """
    coordinator = coordinator or os.environ.get("LOCALAI_COORDINATOR")
    if not coordinator:
        return False
    num_processes = int(
        num_processes
        if num_processes is not None
        else os.environ.get("LOCALAI_NUM_PROCESSES", "1")
    )
    process_id = int(
        process_id if process_id is not None else os.environ.get("LOCALAI_PROCESS_ID", "0")
    )
    if num_processes <= 1:
        return False

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        "jax.distributed up: process %d/%d via %s — %d global devices",
        process_id, num_processes, coordinator, len(jax.devices()),
    )
    return True
