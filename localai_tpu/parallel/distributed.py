"""Multi-host bootstrap: jax.distributed over DCN.

The reference scales out with llama.cpp RPC workers over TCP and a libp2p
DHT for discovery (core/p2p/p2p.go). The TPU equivalent is structurally
different and simpler: within a slice, chips already share ICI and XLA
compiles the collectives; across hosts/slices, `jax.distributed.initialize`
wires the processes into one global mesh over DCN, and the HTTP-level
federation router (localai_tpu.federation) spreads requests across
independent serving processes.

Env contract (mirrors the reference's worker env flags, core/cli/worker):
  LOCALAI_COORDINATOR     host:port of process 0
  LOCALAI_NUM_PROCESSES   total process count
  LOCALAI_PROCESS_ID      this process's rank
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional

log = logging.getLogger("localai_tpu.distributed")


@dataclasses.dataclass(frozen=True)
class ProcessTopology:
    """This process's place in the (possibly single-process) job."""

    process_id: int = 0
    num_processes: int = 1
    coordinator: str = ""

    @property
    def multiprocess(self) -> bool:
        return self.num_processes > 1


# Set by init_distributed() so the serving path (manager, bench, tests) can
# ask "did the bootstrap run?" without re-deriving env state.
_TOPOLOGY = ProcessTopology()


def init_distributed(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed from args or LOCALAI_* env; returns True
    when a multi-process runtime was started, False for single-process runs.

    After this returns True, `jax.devices()` spans every host and a Mesh
    built from it shards programs across the whole pod (dp/tp/... axes ride
    ICI within a slice and DCN across slices).
    """
    global _TOPOLOGY
    if _TOPOLOGY.multiprocess:
        return True  # idempotent: the bootstrap already ran this process
    coordinator = coordinator or os.environ.get("LOCALAI_COORDINATOR")
    if not coordinator:
        return False
    num_processes = int(
        num_processes
        if num_processes is not None
        else os.environ.get("LOCALAI_NUM_PROCESSES", "1")
    )
    process_id = int(
        process_id if process_id is not None else os.environ.get("LOCALAI_PROCESS_ID", "0")
    )
    if num_processes <= 1:
        return False

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _TOPOLOGY = ProcessTopology(
        process_id=process_id, num_processes=num_processes,
        coordinator=coordinator,
    )
    log.info(
        "jax.distributed up: process %d/%d via %s — %d global devices",
        process_id, num_processes, coordinator, len(jax.devices()),
    )
    return True


def init_from_config(app_cfg) -> bool:
    """Serving-path bootstrap (ISSUE 13): wire this process into the global
    mesh from ApplicationConfig knobs (`coordinator_address` /
    `num_processes` / `process_id`, env mirrors LOCALAI_COORDINATOR /
    LOCALAI_NUM_PROCESSES / LOCALAI_PROCESS_ID). Must run before any jax
    computation; a no-op (False) for single-process deployments."""
    return init_distributed(
        coordinator=getattr(app_cfg, "coordinator_address", "") or None,
        num_processes=getattr(app_cfg, "num_processes", 0) or None,
        process_id=getattr(app_cfg, "process_id", 0),
    )


def topology() -> ProcessTopology:
    """The bootstrap's view of this process, falling back to the live jax
    runtime (covers callers that ran jax.distributed.initialize themselves,
    e.g. the train dryrun)."""
    if _TOPOLOGY.multiprocess:
        return _TOPOLOGY
    import jax

    n = jax.process_count()
    if n > 1:
        return ProcessTopology(process_id=jax.process_index(),
                               num_processes=n)
    return _TOPOLOGY


def is_multiprocess() -> bool:
    return topology().multiprocess


def multihost_plan(num_processes: int, local_devices: int, tp: int = 0,
                   ep: int = 1, sp: int = 1):
    """The multi-host serving mesh plan: dp ACROSS hosts (each host serves
    its own replica of the batch over DCN-free decode steps) × tp WITHIN a
    host (the collectives stay on ICI). Pure function — unit-testable
    without a multi-process runtime.

    tp=0 means "all local devices left after ep/sp"; a tp the local chip
    count cannot hold is an error here (silent spill onto DCN would turn
    every layer's psum into a cross-host hop)."""
    from localai_tpu.parallel.mesh import MeshPlan

    if num_processes < 1:
        raise ValueError(f"num_processes must be >= 1, got {num_processes}")
    room = local_devices // max(1, ep * sp)
    if room < 1:
        raise ValueError(
            f"ep={ep} sp={sp} needs {ep * sp} local devices, "
            f"have {local_devices}")
    tp = tp or room
    if tp * ep * sp > local_devices:
        raise ValueError(
            f"tp={tp} ep={ep} sp={sp} spans {tp * ep * sp} devices but this "
            f"host holds {local_devices} — tp must stay within one host "
            f"(ICI); scale dp across hosts instead")
    return MeshPlan(dp=num_processes, tp=tp, ep=ep, sp=sp)


def serving_devices():
    """The global device list ordered host-major (process_index, then id) —
    reshaped by build_mesh into (dp, tp, ...) this puts each host's devices
    on one dp row, so the dp axis strides across hosts and tp stays on
    local ICI."""
    import jax

    return sorted(jax.devices(),
                  key=lambda d: (d.process_index, d.id))


def local_view(mesh):
    """This process's addressable devices within a global mesh — what the
    engine/manager use to size host-side staging and per-process work."""
    import jax

    me = jax.process_index()
    return [d for d in mesh.devices.flat if d.process_index == me]
