"""Device-mesh construction.

The reference configures parallelism per engine (`tensor_split`,
`TensorParallelSize` — backend/backend.proto:193,233); here a MeshPlan is the
single declaration: axis sizes over the available devices, validated against
the architecture, reused by every jitted program.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "tp", "ep", "sp")


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Sizes for each mesh axis; product must equal the device count in use."""

    dp: int = 1
    tp: int = 1
    ep: int = 1
    sp: int = 1

    @property
    def total(self) -> int:
        return self.dp * self.tp * self.ep * self.sp

    def axis_sizes(self) -> tuple[int, ...]:
        return (self.dp, self.tp, self.ep, self.sp)


def build_mesh(plan: MeshPlan, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if plan.total > len(devs):
        raise ValueError(f"mesh plan {plan} needs {plan.total} devices, have {len(devs)}")
    devs = devs[: plan.total]
    arr = np.array(devs).reshape(plan.axis_sizes())
    return Mesh(arr, AXES)


def plan_for_devices(n: int, tp: Optional[int] = None) -> MeshPlan:
    """Default plan: prefer tensor parallel within a slice (ICI-bound), data
    parallel over what's left. Matches the scaling-book recipe of putting the
    fastest-varying parallelism on the fastest interconnect."""
    if tp is None:
        tp = n
    if n % tp != 0:
        raise ValueError(f"{n} devices not divisible by tp={tp}")
    return MeshPlan(dp=n // tp, tp=tp)


def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """`jax.shard_map` across jax versions: the top-level binding (and its
    `check_vma` kwarg) landed after the 0.4.x series; older releases ship it
    as `jax.experimental.shard_map` with the same semantics under
    `check_rep`. Every shard_map in this repo routes through here so the sp
    matrix runs on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)
