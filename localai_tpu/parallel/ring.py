"""Ring attention: sequence-parallel causal attention over the "sp" mesh axis.

The reference has no sequence/context parallelism at all (SURVEY.md §5
"Long-context: not implemented — green-field"). Here it is first-class: the
sequence axis is sharded over "sp"; each device computes attention for its
query block while KV blocks rotate around the ring via ppermute (one ICI hop
per step), accumulating with the online-softmax recurrence — so a context of
length S needs only S/n KV residency per chip and the collective traffic
rides neighbor-to-neighbor ICI links (Liu et al., Ring Attention; the
public scaling-book recipe).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from localai_tpu.parallel.mesh import shard_map as _shard_map

NEG_INF = -1e30

# Declared ICI-collective boundary (lint: sharding-consistency): the ring
# rotation itself. KV blocks ppermute neighbor-to-neighbor inside
# _local_ring's shard_map body; no other function here may touch ICI.
COLLECTIVE_BOUNDARY = ("_local_ring",)


def _local_ring(q, k, v, lengths, *, axis: str, n_shards: int,
                softcap: float = 0.0, window: int = 0, sliding=None):
    """Per-shard body under shard_map.

    q: [B, S_l, H, D], k/v: [B, S_l, K, D] — the local sequence block.
    lengths: [B] global valid lengths (replicated). softcap/window/sliding
    are the gemma-2 semantics (softcap BEFORE masking; sliding layers only
    attend within `window` positions back).
    """
    B, S_l, H, D = q.shape
    K = k.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)
    my = jax.lax.axis_index(axis)

    qf = (q.astype(jnp.float32) * scale).reshape(B, S_l, K, G, D)
    q_pos = my * S_l + jnp.arange(S_l)  # [S_l] global query positions

    acc0 = jnp.zeros((B, K, G, S_l, D), jnp.float32)
    m0 = jnp.full((B, K, G, S_l, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, K, G, S_l, 1), jnp.float32)

    def step(s, carry):
        k_blk, v_blk, acc, m, l = carry
        src = (my - s) % n_shards  # global index of the block we hold now
        kv_pos = src * S_l + jnp.arange(S_l)  # [S_l]

        scores = jnp.einsum(
            "bqkgd,bskd->bkgqs", qf, k_blk.astype(jnp.float32)
        )  # [B, K, G, S_q, S_kv]
        if softcap:
            scores = softcap * jnp.tanh(scores / softcap)
        causal = kv_pos[None, :] <= q_pos[:, None]  # [S_q, S_kv]
        if window and sliding is not None:
            dist = q_pos[:, None] - kv_pos[None, :]
            causal = causal & (~sliding | (dist < window))
        valid = kv_pos[None, :] < lengths[:, None]  # [B, S_kv]
        full_mask = causal[None, None, None] & valid[:, None, None, None, :]
        scores = jnp.where(full_mask, scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bkgqs,bskd->bkgqd", p, v_blk.astype(jnp.float32)
        )

        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        return k_blk, v_blk, acc_new, m_new, l_new

    _, _, acc, m, l = jax.lax.fori_loop(0, n_shards, step, (k, v, acc0, m0, l0))
    out = acc / jnp.maximum(l, 1e-30)
    # Padding query rows attend over the valid prefix (finite garbage); zero
    # them so the contract is "padded rows are zeros" (matches ops/flash.py).
    valid_q = (q_pos[None, :] < lengths[:, None])[:, None, None, :, None]
    out = jnp.where(valid_q, out, 0.0)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, S_l, H, D).astype(q.dtype)


def ring_prefill_attention(
    q: jnp.ndarray,  # [B, S, H, D] sharded on S over `axis`
    k: jnp.ndarray,  # [B, S, K, D]
    v: jnp.ndarray,
    lengths: jnp.ndarray,  # [B]
    mesh: Mesh,
    axis: str = "sp",
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,
) -> jnp.ndarray:
    """Causal GQA attention with the sequence axis sharded over `axis`."""
    n = mesh.shape[axis]
    seq_spec = P(None, axis, None, None)
    if sliding is None:
        fn = _shard_map(
            partial(_local_ring, axis=axis, n_shards=n, softcap=softcap),
            mesh=mesh,
            in_specs=(seq_spec, seq_spec, seq_spec, P(None)),
            out_specs=seq_spec,
            check_vma=False,
        )
        return fn(q, k, v, lengths)
    # `sliding` is a traced bool scalar (layer alternation) — it rides as a
    # replicated operand so one shard_map serves both layer kinds.
    fn = _shard_map(
        lambda q_, k_, v_, l_, sl_: _local_ring(
            q_, k_, v_, l_, axis=axis, n_shards=n, softcap=softcap,
            window=window, sliding=sl_,
        ),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, P(None), P()),
        out_specs=seq_spec,
        check_vma=False,
    )
    return fn(q, k, v, lengths, sliding)
