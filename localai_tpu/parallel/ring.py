"""Ring attention: sequence-parallel causal attention over the "sp" mesh axis.

The reference has no sequence/context parallelism at all (SURVEY.md §5
"Long-context: not implemented — green-field"). Here it is first-class: the
sequence axis is sharded over "sp"; each device computes attention for its
query block while KV blocks rotate around the ring via ppermute (one ICI hop
per step), accumulating with the online-softmax recurrence — so a context of
length S needs only S/n KV residency per chip and the collective traffic
rides neighbor-to-neighbor ICI links (Liu et al., Ring Attention; the
public scaling-book recipe).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from localai_tpu.parallel.mesh import shard_map as _shard_map

NEG_INF = -1e30

# Declared ICI-collective boundary (lint: sharding-consistency): the ring
# rotations themselves. KV blocks ppermute neighbor-to-neighbor inside
# _local_ring's / _local_ring_chunk's shard_map bodies; no other function
# here may touch ICI.
COLLECTIVE_BOUNDARY = ("_local_ring", "_local_ring_chunk")


def _local_ring(q, k, v, lengths, *, axis: str, n_shards: int,
                softcap: float = 0.0, window: int = 0, sliding=None):
    """Per-shard body under shard_map.

    q: [B, S_l, H, D], k/v: [B, S_l, K, D] — the local sequence block.
    lengths: [B] global valid lengths (replicated). softcap/window/sliding
    are the gemma-2 semantics (softcap BEFORE masking; sliding layers only
    attend within `window` positions back).
    """
    B, S_l, H, D = q.shape
    K = k.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)
    my = jax.lax.axis_index(axis)

    qf = (q.astype(jnp.float32) * scale).reshape(B, S_l, K, G, D)
    q_pos = my * S_l + jnp.arange(S_l)  # [S_l] global query positions

    acc0 = jnp.zeros((B, K, G, S_l, D), jnp.float32)
    m0 = jnp.full((B, K, G, S_l, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, K, G, S_l, 1), jnp.float32)

    def step(s, carry):
        k_blk, v_blk, acc, m, l = carry
        src = (my - s) % n_shards  # global index of the block we hold now
        kv_pos = src * S_l + jnp.arange(S_l)  # [S_l]

        scores = jnp.einsum(
            "bqkgd,bskd->bkgqs", qf, k_blk.astype(jnp.float32)
        )  # [B, K, G, S_q, S_kv]
        if softcap:
            scores = softcap * jnp.tanh(scores / softcap)
        causal = kv_pos[None, :] <= q_pos[:, None]  # [S_q, S_kv]
        if window and sliding is not None:
            dist = q_pos[:, None] - kv_pos[None, :]
            causal = causal & (~sliding | (dist < window))
        valid = kv_pos[None, :] < lengths[:, None]  # [B, S_kv]
        full_mask = causal[None, None, None] & valid[:, None, None, None, :]
        scores = jnp.where(full_mask, scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bkgqs,bskd->bkgqd", p, v_blk.astype(jnp.float32)
        )

        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        return k_blk, v_blk, acc_new, m_new, l_new

    _, _, acc, m, l = jax.lax.fori_loop(0, n_shards, step, (k, v, acc0, m0, l0))
    out = acc / jnp.maximum(l, 1e-30)
    # Padding query rows attend over the valid prefix (finite garbage); zero
    # them so the contract is "padded rows are zeros" (matches ops/flash.py).
    valid_q = (q_pos[None, :] < lengths[:, None])[:, None, None, :, None]
    out = jnp.where(valid_q, out, 0.0)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, S_l, H, D).astype(q.dtype)


def ring_prefill_attention(
    q: jnp.ndarray,  # [B, S, H, D] sharded on S over `axis`
    k: jnp.ndarray,  # [B, S, K, D]
    v: jnp.ndarray,
    lengths: jnp.ndarray,  # [B]
    mesh: Mesh,
    axis: str = "sp",
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,
) -> jnp.ndarray:
    """Causal GQA attention with the sequence axis sharded over `axis`."""
    n = mesh.shape[axis]
    seq_spec = P(None, axis, None, None)
    if sliding is None:
        fn = _shard_map(
            partial(_local_ring, axis=axis, n_shards=n, softcap=softcap),
            mesh=mesh,
            in_specs=(seq_spec, seq_spec, seq_spec, P(None)),
            out_specs=seq_spec,
            check_vma=False,
        )
        return fn(q, k, v, lengths)
    # `sliding` is a traced bool scalar (layer alternation) — it rides as a
    # replicated operand so one shard_map serves both layer kinds.
    fn = _shard_map(
        lambda q_, k_, v_, l_, sl_: _local_ring(
            q_, k_, v_, l_, axis=axis, n_shards=n, softcap=softcap,
            window=window, sliding=sl_,
        ),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, P(None), P()),
        out_specs=seq_spec,
        check_vma=False,
    )
    return fn(q, k, v, lengths, sliding)


def _local_ring_chunk(q, k, v, offsets, lengths, kpool, vpool, table, kvs,
                      sl, *, axis: str, n_shards: int, softcap: float,
                      window: int, has_sliding: bool, sink: int, swin: int,
                      scaled: bool):
    """Per-shard body of the sequence-parallel PREFILL CHUNK (ISSUE 14).

    The chunk's token axis is sharded over `axis`: this shard holds T/n
    query tokens (q [B, T_l, H, D]) and the matching in-chunk K/V block
    (k/v [B, T_l, K, D]). Two attention sources fold into one online-softmax
    state:

    1. The slot's RESIDENT pages — walked locally for this shard's queries
       through the replicated pool + table (ops.attention's multi-query
       page walk, windowed+sink skip included). No collective: every shard
       reads its own slice of a replicated pool.
    2. The IN-CHUNK causal part — K/V blocks rotate around the ring via
       ppermute (one ICI hop per step, the _local_ring recurrence) with the
       causal/length/sink/window masks evaluated on GLOBAL positions
       (offsets[b] + chunk index).

    Returns this shard's attention rows [B, T_l, H, D] in q's dtype; fresh
    K/V still scatters into pool pages OUTSIDE the shard_map (the chunk's
    k/v are returned by the layer body as usual)."""
    from localai_tpu.ops.attention import _paged_cache_partials_mq

    B, T_l, H, D = q.shape
    K = k.shape[2]
    G = H // K
    scale = 1.0 / (D**0.5)
    my = jax.lax.axis_index(axis)

    qpos = offsets[:, None] + my * T_l + jnp.arange(T_l)[None, :]  # [B, T_l]
    acc0, m0, l0 = _paged_cache_partials_mq(
        q, kpool, vpool, table, offsets,
        softcap=softcap, window=window,
        sliding=sl if has_sliding else None, q_pos=qpos,
        kv_scale=kvs if scaled else None, sink=sink, swin=swin,
    )  # acc [B, K, G, T_l, D], m/l [B, K, G, T_l, 1]

    qf = (q.astype(jnp.float32) * scale).reshape(B, T_l, K, G, D)

    def step(s, carry):
        k_blk, v_blk, acc, m, l = carry
        src = (my - s) % n_shards  # global shard index of the block we hold
        idx = src * T_l + jnp.arange(T_l)  # [T_l] in-chunk indices
        kv_pos = offsets[:, None] + idx[None, :]  # [B, T_l] global positions

        scores = jnp.einsum(
            "bqkgd,bskd->bkgqs", qf, k_blk.astype(jnp.float32)
        )  # [B, K, G, T_q, T_kv]
        if softcap:
            scores = softcap * jnp.tanh(scores / softcap)
        valid = (kv_pos[:, None, :] <= qpos[:, :, None])  # causal, global
        valid = valid & (idx[None, None, :] < lengths[:, None, None])
        dist = qpos[:, :, None] - kv_pos[:, None, :]
        if window and has_sliding:
            valid = valid & (~sl | (dist < window))
        if swin:
            valid = valid & ((kv_pos[:, None, :] < sink) | (dist < swin))
        vmask = valid[:, None, None]  # [B, 1, 1, T_q, T_kv]
        scores = jnp.where(vmask, scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        p = jnp.where(vmask, p, 0.0)
        alpha = jnp.exp(jnp.maximum(m - m_new, -80.0))
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bkgqs,bskd->bkgqd", p, v_blk.astype(jnp.float32)
        )

        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        return k_blk, v_blk, acc_new, m_new, l_new

    _, _, acc, m, l = jax.lax.fori_loop(
        0, n_shards, step, (k, v, acc0, m0, l0)
    )
    out = acc / jnp.maximum(l, 1e-30)  # [B, K, G, T_l, D]
    # Padding query rows (in-chunk index >= lengths) carry finite garbage;
    # zero them so the contract matches prefill_chunk_paged's dense merge.
    q_idx = my * T_l + jnp.arange(T_l)
    valid_q = (q_idx[None, :] < lengths[:, None])[:, None, None, :, None]
    out = jnp.where(valid_q, out, 0.0)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, T_l, H, D).astype(q.dtype)


def ring_chunk_paged_attention(
    q: jnp.ndarray,  # [B, T, H, D] chunk queries (T divisible by sp)
    k: jnp.ndarray,  # [B, T, K, D] the chunk's fresh K rows
    v: jnp.ndarray,
    offsets: jnp.ndarray,  # [B] rows already resident (chunk starts here)
    lengths: jnp.ndarray,  # [B] valid chunk lengths
    k_pool: jnp.ndarray,  # [P, page, K, D] page pool (replicated over sp)
    v_pool: jnp.ndarray,
    table,  # [B, MP] int32 page table, or hierarchical (l1, l0) pair
    mesh: Mesh,
    axis: str = "sp",
    softcap: float = 0.0,
    window: int = 0,
    sliding=None,
    sink: int = 0,
    swin: int = 0,
    kv_scale=None,  # [2, K] f32 per-head pool dequant scales (fp8 KV)
) -> jnp.ndarray:
    """Sequence-parallel attention for one direct-to-page prefill chunk
    (models/llama.prefill_chunk_paged's sp leg): chunk tokens shard over
    `axis`, each shard walks the slot's resident pages for its own queries
    while the in-chunk K/V rotates around the ring. Composes with tp>1 —
    heads additionally shard over "tp" like every other kernel path."""
    from localai_tpu.ops import ptable as _pt

    n = mesh.shape[axis]
    tp = mesh.shape.get("tp", 1) > 1
    hspec = "tp" if tp else None
    seq_spec = P(None, axis, hspec, None)
    pool_spec = P(None, None, hspec, None)
    kvs = (jnp.ones((2, k_pool.shape[2]), jnp.float32) if kv_scale is None
           else kv_scale.astype(jnp.float32))
    sl_in = sliding if sliding is not None else jnp.zeros((), bool)
    tbl_spec = _pt.shard_spec(table, P(None, None), P(None, None))
    fn = _shard_map(
        partial(
            _local_ring_chunk, axis=axis, n_shards=n, softcap=softcap,
            window=window, has_sliding=sliding is not None, sink=sink,
            swin=swin, scaled=kv_scale is not None,
        ),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, P(None), P(None),
                  pool_spec, pool_spec, tbl_spec, P(None, hspec), P()),
        out_specs=seq_spec,
        check_vma=False,
    )
    return fn(q, k, v, offsets, lengths, k_pool, v_pool, table, kvs, sl_in)
