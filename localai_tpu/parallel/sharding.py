"""Sharding plans for Llama-family parameters, KV caches, and activations.

Megatron-style tensor parallel mapped onto a named mesh:
- attention q/k/v projections: column-parallel (heads split over "tp")
- attention output projection: row-parallel
- MLP gate/up: column-parallel; down: row-parallel
- embeddings / lm_head: vocab-parallel (logits all-gathered by XLA only at
  the sampling boundary)
- MoE expert weights: expert axis over "ep" (falls back to "tp" when ep==1
  so Mixtral still tensor-parallelizes inside each expert)
- KV cache: kv-heads over "tp", slots over "dp"

The reference reaches the same goals by passing `tensor_split` to llama.cpp
(grpc-server.cpp:493-496) or `tensor_parallel_size` to vLLM
(backend/python/vllm/backend.py:106-107); here the plan is explicit
PartitionSpecs and XLA compiles the collectives.

Runtime LoRA factor stacks (ISSUE 10) are NOT part of the param tree and
keep their specs next to their kernel in ops/lora_matmul.lora_factor_specs:
column-parallel targets replicate A and shard B on the out axis, row-parallel
targets shard A on the in axis (mirroring the roles _layer_specs assigns the
base weights below) — the sharding-consistency lint pins THIS file's spec
names 1:1 against the llama param tree, so tenant state that lives outside
the tree must not add names here.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from localai_tpu.models.config import ArchConfig

Params = dict[str, Any]


class ShardingPlanError(ValueError):
    """A mesh plan cannot shard this architecture evenly (ISSUE 7).

    Subclasses ValueError so existing `except ValueError` probes keep
    working, but carries structure the engine uses to DEGRADE instead of
    crash at load: `max_tp` is the largest tp <= the requested one that the
    architecture supports (via max_valid_tp), or 0 when the failure is not
    a tp-divisibility problem (e.g. an ep mismatch)."""

    def __init__(self, message: str, *, axis: str = "tp", requested: int = 0,
                 max_tp: int = 0) -> None:
        super().__init__(message)
        self.axis = axis
        self.requested = requested
        self.max_tp = max_tp


def _attn_specs(cfg: ArchConfig) -> dict[str, P]:
    """Attention-side specs shared by both layer stacks. MLA shards the
    per-head tensors over "tp" on the HEAD axis (q_b columns, w_kb/w_vb
    leading head dim, wo rows); the low-rank a-projections and the latent
    cache are replicated — they are the whole point of MLA (tiny)."""
    specs: dict[str, P] = {
        "attn_norm": P(None, None),
        "mlp_norm": P(None, None),
    }
    if cfg.is_mla:
        if cfg.q_lora_rank:
            specs["wq_a"] = P(None, None, None)
            specs["q_norm_a"] = P(None, None)
            specs["wq_b"] = P(None, None, "tp")
        else:
            specs["wq"] = P(None, None, "tp")
        specs["wkv_a"] = P(None, None, None)
        specs["kv_norm"] = P(None, None)
        specs["w_kb"] = P(None, "tp", None, None)
        specs["w_vb"] = P(None, "tp", None, None)
        specs["wo"] = P(None, "tp", None)
        return specs
    specs.update({
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
    })
    if cfg.post_norms:  # gemma-2 sandwich norms — replicated like the rest
        specs["post_attn_norm"] = P(None, None)
        specs["post_ffw_norm"] = P(None, None)
    if cfg.qk_norm:
        specs["q_norm"] = P(None, None)
        specs["k_norm"] = P(None, None)
    if cfg.attn_qkv_bias:
        specs["bq"] = P(None, "tp")
        specs["bk"] = P(None, "tp")
        specs["bv"] = P(None, "tp")
    return specs


def _layer_specs(cfg: ArchConfig) -> dict[str, P]:
    # Leading axis of every layer param is the stacked layer dim (never sharded:
    # lax.scan iterates over it).
    specs = _attn_specs(cfg)
    if cfg.is_moe:
        specs["router"] = P(None, None, None)
        if cfg.router_bias:
            specs["router_bias"] = P(None, None)
        specs["w_gate"] = P(None, "ep", None, "tp")
        specs["w_up"] = P(None, "ep", None, "tp")
        specs["w_down"] = P(None, "ep", "tp", None)
        if cfg.n_shared_experts:
            specs["shared_gate"] = P(None, None, "tp")
            specs["shared_up"] = P(None, None, "tp")
            specs["shared_down"] = P(None, "tp", None)
    else:
        specs["w_gate"] = P(None, None, "tp")
        specs["w_up"] = P(None, None, "tp")
        specs["w_down"] = P(None, "tp", None)
    return specs


def _dense_layer_specs(cfg: ArchConfig) -> dict[str, P]:
    """DeepSeek dense-prefix stack: attention like the MoE stack, plain MLP."""
    specs = _attn_specs(cfg)
    specs["w_gate"] = P(None, None, "tp")
    specs["w_up"] = P(None, None, "tp")
    specs["w_down"] = P(None, "tp", None)
    return specs


def param_specs(cfg: ArchConfig) -> Params:
    specs: Params = {
        "embed": P("tp", None),
        "layers": _layer_specs(cfg),
        "final_norm": P(None),
    }
    if cfg.is_moe and cfg.first_k_dense:
        specs["dense_layers"] = _dense_layer_specs(cfg)
    if not cfg.tie_embeddings:
        specs["lm_head"] = P("tp", None)
    return specs


def param_shardings(cfg: ArchConfig, mesh: Mesh) -> Params:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P),
    )


def param_shardings_for(cfg: ArchConfig, mesh: Mesh, params: Params) -> Params:
    """Sharding tree structurally aligned to `params`, which may contain
    quantized {"q", "s"} or grouped {"g4"/"gq", "gs"[, "gz"]} leaves
    (models/quant.py). The quantized payload keeps the weight's spec (grouped
    forms shard the group axis the way the in axis was sharded; the
    within-group axis never shards); scales drop spec axes where their
    dimension is 1.

    The fused dequant-matmul kernels consume EXACTLY this partitioning under
    their tp shard_map (ops/quant_matmul._w_specs rebuilds it per call from
    the col/row role — out axis for column-parallel, group/in axis for
    row-parallel). Keep the two in sync: a spec change here that _w_specs
    does not mirror makes the sharded Pallas path reshard every weight per
    decode step (ISSUE 9)."""
    specs = param_specs(cfg)

    def scale_spec(base: tuple, shape: tuple) -> P:
        spec_t = tuple(base) + (None,) * (len(shape) - len(tuple(base)))
        return P(*[
            None if shape[i] == 1 else spec_t[i] for i in range(len(shape))
        ])

    def align(spec, leaf):
        if isinstance(leaf, dict) and "q" in leaf:
            return {
                "q": NamedSharding(mesh, spec),
                "s": NamedSharding(mesh, scale_spec(spec, leaf["s"].shape)),
            }
        if isinstance(leaf, dict):  # grouped quantized tensor
            gspec = tuple(spec)[:-1] + (None, tuple(spec)[-1])
            out = {
                k: NamedSharding(mesh, P(*gspec))
                for k in ("g4", "gq") if k in leaf
            }
            for k in ("gs", "gz"):
                if k in leaf:
                    out[k] = NamedSharding(
                        mesh, scale_spec(gspec, leaf[k].shape)
                    )
            return out
        return NamedSharding(mesh, spec)

    return jax.tree.map(
        align, specs, params,
        is_leaf=lambda x: isinstance(x, P),
    )


def cache_specs(sp: int = 1, mla: bool = False) -> tuple[P, P]:
    # [L, B_slots, S_max, K, Hd]: slots over dp, kv heads over tp. With sp>1
    # the sequence axis shards over "sp" so per-chip KV residency is S/sp —
    # the serving-side guarantee behind ring prefill (parallel/ring.py) and
    # sp decode attention (ops/attention.py decode_attention_*_sp): servable
    # context scales with the sp degree, not just prefill compute.
    # MLA caches hold ONE latent pseudo-head — replicated over tp (every
    # chip's head shard scores against the full latent; it is 1/2·H·Hd/576
    # the size of a dense cache, so replication is the cheap choice).
    spec = P(None, "dp", "sp" if sp > 1 else None, None if mla else "tp", None)
    return spec, spec


def cache_shardings(mesh: Mesh, sp: int = 1,
                    mla: bool = False) -> tuple[NamedSharding, NamedSharding]:
    ks, vs = cache_specs(sp, mla)
    return NamedSharding(mesh, ks), NamedSharding(mesh, vs)


def _tp_violation(cfg: ArchConfig, tp: int) -> Optional[str]:
    """First tp-divisibility violation, or None. Shared by validate_plan
    (raises) and max_valid_tp (probes) so probing never constructs
    exceptions n² deep."""
    if not cfg.is_mla and cfg.num_kv_heads % tp != 0:
        # MLA has no per-head kv cache to shard — the latent replicates and
        # only the H-axis tensors (q_b, w_kb/w_vb, wo) split over tp.
        return (
            f"num_kv_heads={cfg.num_kv_heads} not divisible by tp={tp}; "
            f"choose tp in divisors of kv heads for {cfg.name}"
        )
    if cfg.num_heads % tp != 0:
        return f"num_heads={cfg.num_heads} not divisible by tp={tp}"
    if cfg.intermediate_size % tp != 0:
        return f"intermediate_size={cfg.intermediate_size} not divisible by tp={tp}"
    if cfg.vocab_size % tp != 0:
        return (
            f"vocab_size={cfg.vocab_size} not divisible by tp={tp} "
            "(embed/lm_head are vocab-parallel)"
        )
    if cfg.is_moe:
        if cfg.moe_inter_size % tp != 0:
            return (
                f"moe_intermediate_size={cfg.moe_inter_size} not divisible by tp={tp}"
            )
        if cfg.n_shared_experts and (cfg.n_shared_experts * cfg.moe_inter_size) % tp != 0:
            return (
                f"shared-expert width {cfg.n_shared_experts * cfg.moe_inter_size} "
                f"not divisible by tp={tp}"
            )
    return None


def max_valid_tp(cfg: ArchConfig, n_devices: int) -> int:
    """Largest tp ≤ n_devices that divides every sharded dimension.

    Any tp ≤ n_devices is legal (build_mesh truncates unused devices), so all
    integers are probed — e.g. 6 kv-heads on 8 devices serves at tp=6.
    """
    for tp in range(n_devices, 1, -1):
        if _tp_violation(cfg, tp) is None:
            return tp
    return 1


def validate_plan(cfg: ArchConfig, tp: int, ep: int = 1) -> None:
    """Fail fast on shapes that cannot shard evenly (XLA would pad silently).

    tp failures raise ShardingPlanError with `max_tp` naming the largest tp
    this architecture supports at or below the requested one — the engine
    auto-degrades to it instead of crashing at load (ISSUE 7)."""
    msg = _tp_violation(cfg, tp)
    if msg is not None:
        max_tp = max_valid_tp(cfg, tp)
        raise ShardingPlanError(
            f"{msg} (max valid tp for {cfg.name}: {max_tp})",
            axis="tp", requested=tp, max_tp=max_tp,
        )
    if cfg.is_moe and cfg.num_experts % ep != 0:
        raise ShardingPlanError(
            f"num_experts={cfg.num_experts} not divisible by ep={ep}",
            axis="ep", requested=ep, max_tp=0,
        )
