"""HTTP API server: OpenAI-compatible REST surface over the JAX engine.

Reference: core/http (echo server, routes/openai.go + routes/localai.go).
Rebuilt on the Python stdlib (ThreadingHTTPServer) — no web framework
dependency — with SSE streaming wired straight to the engine's token queues.
"""

from localai_tpu.server.manager import ModelManager, ModelQuarantinedError  # noqa: F401
from localai_tpu.server.app import create_server, Router  # noqa: F401
