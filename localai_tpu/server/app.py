"""Stdlib HTTP server: routing, auth, SSE streaming, Prometheus metrics.

Reference: core/http/app.go:45-226 (echo middleware chain: body limit, error
handler, request logging, CORS, API-key auth, metrics) — rebuilt with
http.server.ThreadingHTTPServer so the framework dependency is zero and the
streaming path is a direct engine-queue → chunked-write loop.
"""

from __future__ import annotations

import hmac
import json
import logging
import re
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Iterator, Optional
from urllib.parse import parse_qs, urlparse

from localai_tpu.config import ApplicationConfig

log = logging.getLogger("localai_tpu.http")

MAX_BODY = 100 * 1024 * 1024  # reference uses a 50MB gRPC cap; HTTP gets 100MB


@dataclass
class Request:
    method: str
    path: str
    params: dict[str, str]
    query: dict[str, list[str]]
    headers: dict[str, str]
    body: Optional[dict[str, Any]]
    raw_body: bytes = b""

    def form(self) -> dict[str, tuple[Optional[str], bytes]]:
        """Parse a multipart/form-data body → {field: (filename|None, bytes)}.

        Used by upload endpoints (audio transcription, model import) — the
        reference gets this from echo's form binding; here it is a direct
        RFC 7578 boundary parse over raw_body.
        """
        ctype = self.headers.get("content-type", "")
        if "multipart/form-data" not in ctype:
            raise ApiError(400, "expected multipart/form-data")
        m = re.search(r'boundary="?([^";]+)"?', ctype)
        if not m:
            raise ApiError(400, "multipart body missing boundary")
        boundary = m.group(1).encode()
        out: dict[str, tuple[Optional[str], bytes]] = {}
        for part in self.raw_body.split(b"--" + boundary):
            part = part.strip(b"\r\n")
            if not part or part == b"--":
                continue
            if b"\r\n\r\n" in part:
                head, _, content = part.partition(b"\r\n\r\n")
            else:
                head, _, content = part.partition(b"\n\n")
            name = filename = None
            for line in head.decode("utf-8", "replace").splitlines():
                if line.lower().startswith("content-disposition"):
                    nm = re.search(r'name="([^"]*)"', line)
                    fm = re.search(r'filename="([^"]*)"', line)
                    name = nm.group(1) if nm else None
                    filename = fm.group(1) if fm else None
            if name is not None:
                out[name] = (filename, content)
        return out


@dataclass
class Response:
    status: int = 200
    body: Any = None  # dict → JSON; str/bytes → raw
    content_type: str = "application/json"
    headers: dict[str, str] = field(default_factory=dict)


class RawStream:
    """Handler return value that streams raw byte chunks (chunked encoding) —
    e.g. streaming TTS audio (reference: TTSStreamEndpoint, tts.go:71-80)."""

    def __init__(self, chunks: Iterator[bytes], content_type: str = "application/octet-stream"):
        self.chunks = chunks
        self.content_type = content_type


class SSEStream:
    """Handler return value that streams `data:` frames from a generator.

    `on_disconnect` (optional) is invoked when the client drops mid-stream so
    the producer can cancel upstream work — e.g. the engine request handle —
    instead of decoding to max_new_tokens into an unread queue.
    """

    def __init__(self, events: Iterator[Any], on_disconnect: Optional[Callable[[], None]] = None):
        self.events = events
        self.on_disconnect = on_disconnect


class ApiError(Exception):
    def __init__(self, status: int, message: str, kind: str = "invalid_request_error",
                 retry_after: Optional[float] = None):
        super().__init__(message)
        self.status = status
        self.kind = kind
        # Backpressure/quarantine errors (429/503, ISSUE 4) carry a
        # Retry-After hint derived from observed admission latency or the
        # remaining quarantine window.
        self.retry_after = retry_after

    def to_response(self) -> Response:
        # OpenAI-style error envelope (reference: core/http error handler).
        headers = {}
        if self.retry_after is not None:
            headers["Retry-After"] = str(max(1, int(-(-self.retry_after // 1))))
        return Response(
            status=self.status,
            body={"error": {"message": str(self), "type": self.kind, "code": self.status}},
            headers=headers,
        )


class Metrics:
    """Named, labeled duration histograms + engine gauges, Prometheus text
    format.

    Reference: core/services/metrics.go:28-46 (OTel histogram `api_call`).
    Generalized (ISSUE 11): `observe(name, seconds, labels)` records into
    any histogram — `api_call` by path as before, plus the per-model
    request-lifecycle histograms (ttft, inter_token, queue_wait, admit)
    the API layer feeds from terminal-event timings. Each histogram
    renders its own `# HELP`/`# TYPE` block.

    Gauges come from two places: `gauge()` for values the server pushes,
    and `add_gauge_source()` callbacks polled at scrape time — how the
    per-model engine gauges (kv pages, queue depth, preemptions, swap
    bytes, prefix host tier — Engine.metrics()) reach /metrics without the
    HTTP layer holding engine references (ISSUE 3 satellite)."""

    BUCKETS = (0.005, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0, float("inf"))

    # # HELP text per histogram (unknown names get a generic line).
    HELP = {
        "api_call": "API call duration seconds",
        "ttft": "Time to first token seconds (queue wait included)",
        "inter_token": "Mean inter-token interval seconds per request",
        "queue_wait": "Seconds a request waited in the pending queue",
        "admit": "Admission-to-first-token seconds (prefill + sample)",
    }

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # Histograms keyed by (name, sorted label items).
        self._hist: dict[tuple[str, tuple], list[int]] = {}
        self._sum: dict[tuple[str, tuple], float] = {}
        self._count: dict[tuple[str, tuple], int] = {}
        self._gauges: dict[tuple[str, tuple], float] = {}
        self._gauge_sources: list[Callable[[], Any]] = []

    def observe(self, name: str, seconds: float,
                labels: Optional[dict[str, str]] = None) -> None:
        """Record one duration sample into the named histogram."""
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            h = self._hist.setdefault(key, [0] * len(self.BUCKETS))
            for i, b in enumerate(self.BUCKETS):
                if seconds <= b:
                    h[i] += 1
            self._sum[key] = self._sum.get(key, 0.0) + seconds
            self._count[key] = self._count.get(key, 0) + 1

    def gauge(self, name: str, value: float,
              labels: Optional[dict[str, str]] = None) -> None:
        """Set a gauge sample (push path). `name` should already carry the
        localai_ prefix convention."""
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            self._gauges[key] = float(value)

    def add_gauge_source(self, fn: Callable[[], Any]) -> None:
        """Register a scrape-time callback yielding (name, labels, value)
        triples — polled fresh on every /metrics render. Registration is
        locked: render() snapshots this list under the same lock (the
        unguarded append/iterate pair was a cross-thread race)."""
        with self._lock:
            self._gauge_sources.append(fn)

    @staticmethod
    def _fmt_labels(labels: tuple, extra: str = "") -> str:
        inner = ",".join(f'{k}="{v}"' for k, v in labels)
        if extra:
            inner = f"{inner},{extra}" if inner else extra
        if not inner:
            return ""
        return "{" + inner + "}"

    def render(self) -> str:
        lines: list[str] = []
        with self._lock:
            hist = {k: list(v) for k, v in self._hist.items()}
            sums = dict(self._sum)
            counts = dict(self._count)
            samples = dict(self._gauges)
            sources = list(self._gauge_sources)
        by_hist: dict[str, list[tuple]] = {}
        for name, labels in hist:
            by_hist.setdefault(name, []).append(labels)
        for name in sorted(by_hist):
            help_text = self.HELP.get(name, f"{name} duration seconds")
            lines.append(f"# HELP localai_{name} {help_text}")
            lines.append(f"# TYPE localai_{name} histogram")
            for labels in sorted(by_hist[name]):
                key = (name, labels)
                h = hist[key]
                for i, b in enumerate(self.BUCKETS):
                    le = "+Inf" if b == float("inf") else repr(b)
                    le_label = f'le="{le}"'
                    lines.append(
                        f"localai_{name}_bucket"
                        f"{self._fmt_labels(labels, le_label)} {h[i]}"
                    )
                lines.append(
                    f"localai_{name}_sum{self._fmt_labels(labels)} {sums[key]}"
                )
                lines.append(
                    f"localai_{name}_count{self._fmt_labels(labels)} "
                    f"{counts[key]}"
                )
        # Gauge sources run OUTSIDE the lock (they may scrape engines).
        for src in sources:
            try:
                for name, labels, value in src():
                    key = (name, tuple(sorted((labels or {}).items())))
                    samples[key] = float(value)
            except Exception:  # noqa: BLE001 — a scrape must never 500
                log.exception("gauge source failed during /metrics render")
        by_name: dict[str, list[tuple[tuple, float]]] = {}
        for (name, labels), value in samples.items():
            by_name.setdefault(name, []).append((labels, value))
        for name in sorted(by_name):
            lines.append(f"# TYPE {name} gauge")
            for labels, value in sorted(by_name[name]):
                lines.append(f"{name}{self._fmt_labels(labels)} {value}")
        return "\n".join(lines) + "\n"


Handler = Callable[[Request], "Response | SSEStream"]


class Router:
    def __init__(self) -> None:
        # thread: single-writer main — the route table is built during
        # startup, before create_server() spawns handler threads; handlers
        # only read it
        self.routes: list[tuple[str, re.Pattern, Handler]] = []
        # Original (method, pattern, handler) tuples — the OpenAPI doc and
        # WebUI introspect these (reference: swagger route).
        # thread: single-writer main — same startup-only build as routes
        self.declared: list[tuple[str, str, Handler]] = []

    def add(self, method: str, pattern: str, handler: Handler) -> None:
        """Pattern params as `:name` segments, e.g. `/models/jobs/:uuid`."""
        regex = re.sub(r":(\w+)", r"(?P<\1>[^/]+)", pattern)
        self.routes.append((method.upper(), re.compile(f"^{regex}$"), handler))
        self.declared.append((method.upper(), pattern, handler))

    def match(self, method: str, path: str) -> Optional[tuple[Handler, dict[str, str]]]:
        for m, rx, h in self.routes:
            if m != method.upper():
                continue
            match = rx.match(path)
            if match:
                return h, match.groupdict()
        return None

    def methods_for(self, path: str) -> set[str]:
        return {m for m, rx, _ in self.routes if rx.match(path)}


# Paths that never require auth (reference: auth.go exempts health endpoints).
AUTH_EXEMPT = {"/healthz", "/readyz", "/version"}


def create_server(app_cfg: ApplicationConfig, router: Router) -> ThreadingHTTPServer:
    metrics = Metrics()
    # Per-model engine gauges: an API layer that registered a gauge source
    # on the router (OpenAIApi.register) gets polled at every scrape.
    src = getattr(router, "gauge_source", None)
    if src is not None:
        metrics.add_gauge_source(src)
    router.metrics = metrics
    router.add("GET", "/metrics", lambda req: Response(
        body=metrics.render(), content_type="text/plain; version=0.0.4"
    ))

    class RequestHandlerImpl(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "localai-tpu"

        def log_message(self, fmt, *args):  # route through logging, not stderr
            log.debug("%s " + fmt, self.address_string(), *args)

        def _deny(self, status: int, msg: str) -> None:
            self._respond(ApiError(status, msg).to_response())

        def _authed(self) -> bool:
            if not app_cfg.api_keys:
                return True
            path = urlparse(self.path).path
            if path in AUTH_EXEMPT:
                return True
            header = self.headers.get("Authorization", "")
            token = header[7:] if header.startswith("Bearer ") else header
            if not token:
                token = self.headers.get("x-api-key", "") or self.headers.get("xi-api-key", "")
            # Constant-time compare over bytes (reference: auth.go constant-
            # time option); bytes form tolerates non-ASCII header values.
            tb = token.encode("utf-8", "surrogateescape")
            if any(hmac.compare_digest(tb, k.encode()) for k in app_cfg.api_keys):
                return True
            # Minted realtime client secrets admit realtime paths only
            # (RealtimeApi attaches the registry at route registration).
            eph = getattr(router, "ephemeral_keys", None)
            return eph is not None and eph.valid(token, path)

        def _common_headers(self) -> dict[str, str]:
            h = {}
            if app_cfg.cors:
                h["Access-Control-Allow-Origin"] = "*"
                h["Access-Control-Allow-Headers"] = "Authorization, Content-Type, Extra-Usage"
                h["Access-Control-Allow-Methods"] = "GET, POST, DELETE, OPTIONS"
            if app_cfg.machine_tag:
                h["LocalAI-Machine-Tag"] = app_cfg.machine_tag
            # Cluster role advertisement (ISSUE 6): health probes from the
            # federation front door read this to role-type affinity picks
            # (prefill/decode workers need no side-channel registration).
            role = (app_cfg.cluster_role or "").split(",")[0].strip()
            if role and role != "mixed":
                h["LocalAI-Cluster-Role"] = role
            return h

        def _respond(self, resp: Response) -> None:
            body = resp.body
            if isinstance(body, (dict, list)):
                data = json.dumps(body).encode()
            elif isinstance(body, str):
                data = body.encode()
            elif body is None:
                data = b""
            else:
                data = body
            self.send_response(resp.status)
            self.send_header("Content-Type", resp.content_type)
            self.send_header("Content-Length", str(len(data)))
            for k, v in {**self._common_headers(), **resp.headers}.items():
                self.send_header(k, v)
            self.end_headers()
            if self.command != "HEAD":
                self.wfile.write(data)

        def _respond_sse(self, stream: SSEStream) -> None:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "keep-alive")
            self.send_header("Transfer-Encoding", "chunked")
            for k, v in self._common_headers().items():
                self.send_header(k, v)
            self.end_headers()

            def write_chunk(payload: bytes) -> None:
                self.wfile.write(f"{len(payload):X}\r\n".encode() + payload + b"\r\n")
                self.wfile.flush()

            try:
                for ev in stream.events:
                    if isinstance(ev, (dict, list)):
                        ev = json.dumps(ev)
                    write_chunk(f"data: {ev}\n\n".encode())
                write_chunk(b"data: [DONE]\n\n")
            except (BrokenPipeError, ConnectionResetError):
                log.debug("SSE client disconnected")
                if stream.on_disconnect is not None:
                    try:
                        stream.on_disconnect()
                    except Exception:  # noqa: BLE001
                        log.exception("SSE on_disconnect callback failed")
            finally:
                # Close the generator so its finally blocks (lease release)
                # run deterministically even when the client dropped early.
                stream.events.close()
                try:
                    write_chunk(b"")  # terminating chunk
                except (BrokenPipeError, ConnectionResetError):
                    pass

        def _upgrade_websocket(self, upgrade) -> None:
            from localai_tpu.server.ws import WebSocket, accept_key

            key = self.headers.get("Sec-WebSocket-Key")
            if (self.headers.get("Upgrade", "").lower() != "websocket") or not key:
                self._deny(400, "expected a WebSocket upgrade request")
                return
            self.send_response(101, "Switching Protocols")
            self.send_header("Upgrade", "websocket")
            self.send_header("Connection", "Upgrade")
            self.send_header("Sec-WebSocket-Accept", accept_key(key))
            self.end_headers()
            self.wfile.flush()
            ws = WebSocket(self.rfile, self.wfile)
            try:
                upgrade.session(ws)
            except (BrokenPipeError, ConnectionResetError, ConnectionError):
                log.debug("websocket client disconnected")
            finally:
                ws.close()
                self.close_connection = True

        def _respond_raw_stream(self, stream: "RawStream") -> None:
            self.send_response(200)
            self.send_header("Content-Type", stream.content_type)
            self.send_header("Transfer-Encoding", "chunked")
            for k, v in self._common_headers().items():
                self.send_header(k, v)
            self.end_headers()
            try:
                for chunk in stream.chunks:
                    if chunk:
                        self.wfile.write(f"{len(chunk):X}\r\n".encode() + chunk + b"\r\n")
                        self.wfile.flush()
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                log.debug("raw-stream client disconnected")
            finally:
                if hasattr(stream.chunks, "close"):
                    stream.chunks.close()

        def _handle(self) -> None:
            start = time.monotonic()
            parsed = urlparse(self.path)
            path = parsed.path.rstrip("/") or "/"
            if self.command == "OPTIONS":
                self.send_response(204)
                for k, v in self._common_headers().items():
                    self.send_header(k, v)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            if not self._authed():
                self._deny(401, "invalid or missing API key")
                return

            matched = router.match(self.command, path)
            if matched is None:
                if router.methods_for(path):
                    self._deny(405, f"method {self.command} not allowed for {path}")
                else:
                    self._deny(404, f"no route for {path}")
                return
            handler, params = matched

            raw = b""
            length = int(self.headers.get("Content-Length") or 0)
            if length > MAX_BODY:
                self._deny(413, "request body too large")
                return
            if length:
                raw = self.rfile.read(length)
            body = None
            if raw:
                ctype = self.headers.get("Content-Type", "")
                if "json" in ctype or raw.lstrip()[:1] in (b"{", b"["):
                    try:
                        body = json.loads(raw)
                    except json.JSONDecodeError as e:
                        self._deny(400, f"invalid JSON body: {e}")
                        return

            req = Request(
                method=self.command,
                path=path,
                params=params,
                query=parse_qs(parsed.query),
                headers={k.lower(): v for k, v in self.headers.items()},
                body=body,
                raw_body=raw,
            )
            try:
                result = handler(req)
                from localai_tpu.server.ws import WebSocketUpgrade

                if isinstance(result, WebSocketUpgrade):
                    self._upgrade_websocket(result)
                    return
            except ApiError as e:
                self._respond(e.to_response())
                return
            except Exception as e:  # noqa: BLE001
                log.exception("handler error for %s %s", self.command, path)
                self._respond(ApiError(500, f"{type(e).__name__}: {e}", "server_error").to_response())
                return
            finally:
                metrics.observe("api_call", time.monotonic() - start,
                                {"path": path})

            if isinstance(result, SSEStream):
                self._respond_sse(result)
            elif isinstance(result, RawStream):
                self._respond_raw_stream(result)
            else:
                self._respond(result)

        def do_GET(self):  # noqa: N802
            self._handle()

        do_POST = do_DELETE = do_PUT = do_HEAD = do_OPTIONS = do_GET

    class Server(ThreadingHTTPServer):
        # The socketserver default backlog of 5 RSTs connection bursts —
        # any concurrent client fan-in (n>1 requests, federation, stress)
        # trips it. Match a production accept queue.
        request_queue_size = 128
        daemon_threads = True

    server = Server((app_cfg.address, app_cfg.port), RequestHandlerImpl)
    server.daemon_threads = True
    return server
