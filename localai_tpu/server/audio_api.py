"""Audio endpoints: transcription (STT), speech (TTS), sound generation, VAD.

Reference routes: core/http/endpoints/openai/transcription.go (multipart file
→ whisper), endpoints/localai/tts.go + endpoints/elevenlabs (TTS),
endpoints/localai/vad.go (silero VAD RPC). Handlers resolve the model by
usecase exactly like the text endpoints do.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from localai_tpu.config import Usecase
from localai_tpu.server.app import ApiError, Request, Response, Router
from localai_tpu.server.manager import ModelManager
from localai_tpu.server.openai_api import OpenAIApi


class AudioApi:
    def __init__(self, manager: ModelManager, base: OpenAIApi):
        self.manager = manager
        self._base = base  # reuse model resolution helpers

    def register(self, r: Router) -> None:
        r.add("POST", "/v1/audio/transcriptions", self.transcribe)
        r.add("POST", "/v1/audio/translations", self.translate)
        r.add("POST", "/v1/audio/speech", self.speech)
        r.add("POST", "/tts", self.speech)  # LocalAI native route
        r.add("POST", "/v1/audio/speech/stream", self.speech_stream)
        r.add("POST", "/tts/stream", self.speech_stream)
        # elevenlabs-compatible aliases (reference: routes/elevenlabs.go)
        r.add("POST", "/v1/text-to-speech/:voice_id", self.speech_elevenlabs)
        r.add("POST", "/v1/sound-generation", self.sound_generation)
        r.add("POST", "/vad", self.vad)
        r.add("POST", "/v1/vad", self.vad)

    # ------------------------------------------------------------------ #
    # STT
    # ------------------------------------------------------------------ #

    def _transcribe_impl(self, req: Request, translate: bool) -> Response:
        from localai_tpu.audio import read_wav, resample

        form = req.form()
        if "file" not in form:
            raise ApiError(400, "missing form field 'file'")
        _fname, blob = form["file"]

        def field(name: str, default: str = "") -> str:
            if name in form:
                return form[name][1].decode("utf-8", "replace").strip()
            return default

        model = field("model")
        language = field("language") or None
        response_format = field("response_format", "json")

        try:
            audio, sr = read_wav(blob)
        except Exception as e:  # noqa: BLE001
            raise ApiError(400, f"could not decode audio file (WAV required): {e}") from None
        audio = resample(audio, sr, 16_000)

        fake = Request(
            method=req.method, path=req.path, params=req.params, query=req.query,
            headers=req.headers, body={"model": model} if model else {},
        )
        lm, lease = self._base._resolve(fake, Usecase.TRANSCRIPT)
        try:
            result = lm.engine.transcribe(audio, language=language, translate=translate)
        finally:
            lease.release()

        if response_format == "text":
            return Response(body=result["text"], content_type="text/plain; charset=utf-8")
        if response_format == "verbose_json":
            return Response(body={
                "task": "translate" if translate else "transcribe",
                "language": result["language"],
                "duration": result["duration"],
                "text": result["text"],
                "segments": result["segments"],
            })
        return Response(body={"text": result["text"], "segments": result["segments"]})

    def transcribe(self, req: Request) -> Response:
        return self._transcribe_impl(req, translate=False)

    def translate(self, req: Request) -> Response:
        return self._transcribe_impl(req, translate=True)

    # ------------------------------------------------------------------ #
    # TTS / sound generation
    # ------------------------------------------------------------------ #

    def _tts_impl(self, req: Request, usecase: Usecase) -> Response:
        from localai_tpu.audio import write_wav

        body = req.body or {}
        text = body.get("input") or body.get("text")
        if not text or not isinstance(text, str):
            raise ApiError(400, "input text is required")
        fmt = (body.get("response_format") or "wav").lower()
        if fmt not in ("wav", "pcm"):
            raise ApiError(400, f"response_format {fmt!r} not supported (wav, pcm)")

        lm, lease = self._base._resolve(req, usecase)
        try:
            samples, sr = lm.engine.synthesize(text, voice=body.get("voice"))
        finally:
            lease.release()
        if fmt == "pcm":
            pcm16 = (np.clip(samples, -1, 1) * 32767.0).astype(np.int16)
            return Response(body=pcm16.tobytes(), content_type="audio/pcm",
                            headers={"X-Sample-Rate": str(sr)})
        return Response(body=write_wav(samples, sr), content_type="audio/wav")

    def speech(self, req: Request) -> Response:
        return self._tts_impl(req, Usecase.TTS)

    def speech_elevenlabs(self, req: Request) -> Response:
        """elevenlabs contract: voice in the route, text in body `text`."""
        body = dict(req.body or {})
        body.setdefault("voice", req.params.get("voice_id"))
        patched = Request(
            method=req.method, path=req.path, params=req.params,
            query=req.query, headers=req.headers, body=body,
        )
        return self._tts_impl(patched, Usecase.TTS)

    def speech_stream(self, req: Request):
        """Chunked streaming TTS: WAV header + PCM chunks as each text
        segment is synthesized (reference: TTSStreamEndpoint)."""
        import struct

        from localai_tpu.server.app import RawStream

        body = req.body or {}
        text = body.get("input") or body.get("text")
        if not text or not isinstance(text, str):
            raise ApiError(400, "input text is required")
        lm, lease = self._base._resolve(req, Usecase.TTS)
        sr = lm.engine.cfg.sample_rate

        def chunks():
            try:
                # Streaming WAV: RIFF/data sizes set to the unknown-length
                # sentinel (players and ffmpeg accept this for live streams).
                hdr = (b"RIFF" + struct.pack("<I", 0xFFFFFFFF) + b"WAVE"
                       + b"fmt " + struct.pack("<IHHIIHH", 16, 1, 1, sr, sr * 2, 2, 16)
                       + b"data" + struct.pack("<I", 0xFFFFFFFF))
                yield hdr
                for samples in lm.engine.synthesize_stream(text, voice=body.get("voice")):
                    pcm16 = (np.clip(samples, -1, 1) * 32767.0).astype(np.int16)
                    yield pcm16.tobytes()
            finally:
                lease.release()

        return RawStream(chunks(), content_type="audio/wav")

    def sound_generation(self, req: Request) -> Response:
        """Prompt → audio (music/sfx). ElevenLabs-shaped request like the
        reference (schema.ElevenLabsSoundGenerationRequest: text, model_id,
        duration_seconds, prompt_influence, do_sample); served by a MusicGen
        engine when one resolves, with TTS synthesis as the fallback for
        voice-only deployments."""
        from localai_tpu.audio import write_wav

        body = dict(req.body or {})
        text = body.get("text") or body.get("input")
        if not text or not isinstance(text, str):
            raise ApiError(400, "text is required")
        fmt = (body.get("response_format") or "wav").lower()
        if fmt not in ("wav", "pcm"):
            raise ApiError(400, f"response_format {fmt!r} not supported (wav, pcm)")
        if body.get("model_id") and not body.get("model"):
            body["model"] = body["model_id"]
        seed = body.get("seed")
        if seed is not None:
            try:
                seed = int(seed)
            except (TypeError, ValueError):
                raise ApiError(400, "seed must be an integer") from None
        duration = body.get("duration_seconds")
        if duration is None:
            duration = body.get("duration")
        patched = Request(
            method=req.method, path=req.path, params=req.params,
            query=req.query, headers=req.headers, body=body,
        )
        lm, lease = self._base._resolve(patched, Usecase.SOUND_GENERATION)
        try:
            if hasattr(lm.engine, "generate_sound"):
                # The reference's python backend maps `temperature` onto
                # MusicGen's guidance scale (transformers backend.py:527-529);
                # prompt_influence is the elevenlabs field name for it.
                guidance = body.get("prompt_influence", body.get("temperature"))
                try:
                    samples, sr = lm.engine.generate_sound(
                        text,
                        duration_s=None if duration is None else float(duration),
                        do_sample=bool(body.get("do_sample", True)),
                        guidance_scale=None if guidance is None else float(guidance),
                        seed=seed,
                    )
                except ValueError as e:
                    raise ApiError(400, str(e)) from None
            else:
                samples, sr = lm.engine.synthesize(text, voice=body.get("voice"))
        finally:
            lease.release()
        if fmt == "pcm":
            pcm16 = (np.clip(samples, -1, 1) * 32767.0).astype(np.int16)
            return Response(body=pcm16.tobytes(), content_type="audio/pcm",
                            headers={"X-Sample-Rate": str(sr)})
        return Response(body=write_wav(samples, sr), content_type="audio/wav")

    # ------------------------------------------------------------------ #
    # VAD
    # ------------------------------------------------------------------ #

    def vad(self, req: Request) -> Response:
        body = req.body or {}
        audio = body.get("audio")
        if not isinstance(audio, list) or not audio:
            raise ApiError(400, "audio must be a non-empty array of float samples")
        sr = int(body.get("sample_rate") or 16_000)
        x = np.asarray(audio, np.float32)

        lm, lease = self._base._resolve(req, Usecase.VAD)
        try:
            segments = lm.engine.detect(x, sr)
        finally:
            lease.release()
        return Response(body={"segments": segments})
