"""Gallery HTTP endpoints: browse, install (async job), poll, delete.

Reference routes (core/http/routes/localai.go:43-74 + endpoints/localai/
gallery.go): GET /models/available, POST /models/apply, GET
/models/jobs/:uuid, POST/DELETE /models/galleries, DELETE /models/:name
(endpoints/localai/import_model.go handles raw installs the same way the
inline files/overrides form does here).
"""

from __future__ import annotations

from typing import Any

from localai_tpu.gallery import GalleryService
from localai_tpu.server.app import ApiError, Request, Response, Router


class GalleryApi:
    def __init__(self, service: GalleryService, manager=None):
        self.service = service
        self.manager = manager  # for unloading deleted models

    def register(self, r: Router) -> None:
        r.add("GET", "/models/available", self.available)
        r.add("POST", "/models/apply", self.apply)
        r.add("GET", "/models/jobs/:uuid", self.job)
        r.add("GET", "/models/galleries", self.galleries)
        r.add("POST", "/models/galleries", self.add_gallery)
        r.add("DELETE", "/models/galleries", self.remove_gallery)
        r.add("POST", "/models/delete/:name", self.delete_model)

    def available(self, req: Request) -> Response:
        return Response(body=self.service.list_available())

    def apply(self, req: Request) -> Response:
        body: dict[str, Any] = req.body or {}
        try:
            uuid = self.service.apply(
                entry_id=body.get("id"),
                name=body.get("name"),
                overrides=body.get("overrides") or body.get("config_overrides"),
                files=body.get("files"),
            )
        except KeyError as e:
            raise ApiError(404, str(e)) from None
        except ValueError as e:
            raise ApiError(400, str(e)) from None
        return Response(body={"uuid": uuid, "status": f"/models/jobs/{uuid}"})

    def job(self, req: Request) -> Response:
        j = self.service.job(req.params["uuid"])
        if j is None:
            raise ApiError(404, f"job {req.params['uuid']!r} not found")
        return Response(body=j)

    def galleries(self, req: Request) -> Response:
        return Response(body=[
            {"name": g.name, "url": g.url} for g in self.service.galleries
        ])

    def add_gallery(self, req: Request) -> Response:
        body = req.body or {}
        name, url = body.get("name"), body.get("url")
        if not name or not url:
            raise ApiError(400, "name and url are required")
        try:
            self.service.add_gallery(name, url)
        except ValueError as e:
            raise ApiError(409, str(e)) from None
        return Response(body={"status": "ok"})

    def remove_gallery(self, req: Request) -> Response:
        body = req.body or {}
        name = body.get("name")
        if not name:
            raise ApiError(400, "name is required")
        if not self.service.remove_gallery(name):
            raise ApiError(404, f"gallery {name!r} not found")
        return Response(body={"status": "ok"})

    def delete_model(self, req: Request) -> Response:
        name = req.params["name"]
        try:
            # Verify it is actually gallery-installed BEFORE unloading, so a
            # 404 never tears down a running model configured elsewhere.
            if not self.service._installed(name):
                raise ApiError(404, f"model {name!r} is not installed")
            if self.manager is not None:
                self.manager.unload(name)
            self.service.delete_model(name)
        except ValueError as e:
            raise ApiError(400, str(e)) from None
        return Response(body={"status": "ok"})
