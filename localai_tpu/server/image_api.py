"""Image and video generation endpoints.

Reference: core/http/endpoints/openai/image.go (b64/url response, files
under generated_content_dir served back over HTTP) and endpoints/openai/
video.go. PNG/GIF encoding via PIL on the host; generation on the TPU.
"""

from __future__ import annotations

import base64
import io
import os
import re
import time
import uuid

from localai_tpu.config import Usecase
from localai_tpu.server.app import ApiError, Request, Response, Router
from localai_tpu.server.manager import ModelManager
from localai_tpu.server.openai_api import OpenAIApi

_SIZE_RE = re.compile(r"^(\d+)x(\d+)$")
_NAME_RE = re.compile(r"^[A-Za-z0-9._-]+$")


class ImageApi:
    def __init__(self, manager: ModelManager, base: OpenAIApi, content_dir: str):
        self.manager = manager
        self._base = base
        self.content_dir = content_dir

    def register(self, r: Router) -> None:
        r.add("POST", "/v1/images/generations", self.generations)
        r.add("POST", "/images/generations", self.generations)
        r.add("POST", "/v1/images/inpainting", self.inpainting)
        r.add("POST", "/v1/videos", self.videos)
        r.add("GET", "/generated-images/:name", self.serve_image)
        r.add("GET", "/generated-videos/:name", self.serve_video)

    # ------------------------------------------------------------------ #

    def _parse_size(self, body) -> tuple[int, int] | None:
        size = body.get("size")
        if not size:
            return None
        m = _SIZE_RE.match(str(size))
        if not m:
            raise ApiError(400, f"invalid size {size!r} (expected WxH)")
        w, h = int(m.group(1)), int(m.group(2))
        if not (8 <= w <= 4096 and 8 <= h <= 4096):
            raise ApiError(400, "size out of range")
        return (w, h)

    @staticmethod
    def _decode_b64_image(body: dict, *keys: str, field: str = "image"):
        """First present key → decoded RGB np array; malformed input → 400."""
        from PIL import Image
        import numpy as np

        blob64 = next((body[k] for k in keys if body.get(k)), None)
        if blob64 is None:
            return None
        try:
            blob = base64.b64decode(blob64)
            return np.asarray(Image.open(io.BytesIO(blob)).convert("RGB"))
        except Exception as e:  # noqa: BLE001
            raise ApiError(400, f"{field} is not a decodable image: {e}") from None

    @staticmethod
    def _num_field(body: dict, key: str) -> float | None:
        """Numeric body field or 400 (never a 500 from a bad string)."""
        if body.get(key) is None:
            return None
        try:
            return float(body[key])
        except (TypeError, ValueError):
            raise ApiError(400, f"{key} must be a number") from None

    def generations(self, req: Request) -> Response:
        from PIL import Image

        body = req.body or {}
        prompt = body.get("prompt")
        if not prompt or not isinstance(prompt, str):
            raise ApiError(400, "prompt is required")
        n = int(body.get("n") or 1)
        if not 1 <= n <= 8:
            raise ApiError(400, "n must be between 1 and 8")
        steps = int(body.get("step") or body.get("steps") or 20)
        size = self._parse_size(body)
        response_format = body.get("response_format") or "url"

        kw = {}
        init = self._decode_b64_image(body, "image", "src")
        if init is not None:
            # img2img: base64 source + strength (reference: request.src ->
            # StableDiffusionImg2ImgPipeline, diffusers backend.py:198)
            kw["init_image"] = init
            strength = self._num_field(body, "strength")
            if strength is not None:
                kw["strength"] = strength
        ctrl = self._decode_b64_image(body, "control_image",
                                      field="control_image")
        if ctrl is not None:
            # ControlNet conditioning (diffusers ControlNet pipelines; the
            # checkpoint must ship a controlnet/ subdir): base64 PNG/JPEG.
            kw["control_image"] = ctrl
            scale = self._num_field(body, "control_scale")
            if scale is not None:
                kw["control_scale"] = scale

        lm, lease = self._base._resolve(req, Usecase.IMAGE)
        try:
            images = lm.engine.generate(
                prompt, n=n, steps=steps, seed=body.get("seed"), size=size,
                guidance=float(body.get("guidance_scale") or 4.0), **kw,
            )
        except (ValueError, TypeError) as e:
            # e.g. control_image against a checkpoint without controlnet/
            raise ApiError(400, str(e)) from None
        finally:
            lease.release()

        os.makedirs(self.content_dir, exist_ok=True)
        data = []
        for img in images:
            buf = io.BytesIO()
            Image.fromarray(img).save(buf, format="PNG")
            png = buf.getvalue()
            if response_format == "b64_json":
                data.append({"b64_json": base64.b64encode(png).decode()})
            else:
                name = f"{uuid.uuid4().hex}.png"
                with open(os.path.join(self.content_dir, name), "wb") as f:
                    f.write(png)
                data.append({"url": f"/generated-images/{name}"})
        return Response(body={"created": int(time.time()), "data": data})

    def inpainting(self, req: Request) -> Response:
        """Image inpainting: multipart form with `image` and `mask` files
        (white = repaint), `prompt`, optional `steps`/`seed`/`model`
        (reference: endpoints/openai/inpainting.go)."""
        import numpy as np
        from PIL import Image
        import io as _io

        form = req.form()
        for field in ("image", "mask"):
            if field not in form:
                raise ApiError(400, f"missing form field {field!r}")

        def text_field(name: str, default: str = "") -> str:
            return form[name][1].decode("utf-8", "replace").strip() if name in form else default

        prompt = text_field("prompt")
        if not prompt:
            raise ApiError(400, "prompt is required")
        try:
            img = np.asarray(Image.open(_io.BytesIO(form["image"][1])).convert("RGB"))
            mask = np.asarray(Image.open(_io.BytesIO(form["mask"][1])).convert("L"))
        except Exception as e:  # noqa: BLE001
            raise ApiError(400, f"could not decode image/mask: {e}") from None
        if mask.shape != img.shape[:2]:
            mask = np.asarray(
                Image.fromarray(mask).resize((img.shape[1], img.shape[0]), Image.NEAREST)
            )
        steps = int(text_field("steps", "") or 25)
        seed = text_field("seed", "")
        model = text_field("model", "")
        response_format = text_field("response_format", "url")

        fake = Request(
            method=req.method, path=req.path, params=req.params, query=req.query,
            headers=req.headers, body={"model": model} if model else {},
        )
        lm, lease = self._base._resolve(fake, Usecase.IMAGE)
        try:
            out = lm.engine.inpaint(
                prompt, img, mask, steps=steps,
                seed=int(seed) if seed else None,
            )
        except ValueError as e:
            # e.g. a Flux checkpoint (no inpainting path)
            raise ApiError(400, str(e)) from None
        finally:
            lease.release()

        buf = io.BytesIO()
        Image.fromarray(out).save(buf, format="PNG")
        png = buf.getvalue()
        if response_format == "b64_json":
            data = [{"b64_json": base64.b64encode(png).decode()}]
        else:
            os.makedirs(self.content_dir, exist_ok=True)
            name = f"{uuid.uuid4().hex}.png"
            with open(os.path.join(self.content_dir, name), "wb") as f:
                f.write(png)
            data = [{"url": f"/generated-images/{name}"}]
        return Response(body={"created": int(time.time()), "data": data})

    def videos(self, req: Request) -> Response:
        from PIL import Image

        body = req.body or {}
        prompt = body.get("prompt")
        if not prompt or not isinstance(prompt, str):
            raise ApiError(400, "prompt is required")
        n_frames = int(body.get("n_frames") or 8)
        if not 2 <= n_frames <= 64:
            raise ApiError(400, "n_frames must be between 2 and 64")
        steps = int(body.get("step") or body.get("steps") or 12)
        fmt = str(body.get("format") or "mp4")
        if fmt not in ("mp4", "gif"):
            raise ApiError(400, "format must be mp4 or gif")
        # validate BEFORE generating — a bad value must not waste the run
        frame_ms = int(self._num_field(body, "frame_ms") or 125)

        kw = {}
        init = self._decode_b64_image(body, "image", "file", "src")
        if init is not None:
            # image→video: base64 source anchors every frame's init latent
            # (reference: WanImageToVideoPipeline / SVD img2vid,
            # diffusers backend.py:242-250, :280-284).
            kw["init_image"] = init
            strength = self._num_field(body, "strength")
            if strength is not None:
                kw["strength"] = strength

        lm, lease = self._base._resolve(req, Usecase.VIDEO)
        try:
            frames = lm.engine.generate_video(
                prompt, n_frames=n_frames, steps=steps, seed=body.get("seed"),
                negative_prompt=str(body.get("negative_prompt") or ""), **kw,
            )
        except ValueError as e:
            # e.g. n_frames beyond the motion adapter's trained window
            raise ApiError(400, str(e)) from None
        finally:
            lease.release()

        from localai_tpu.utils.video_io import write_video

        name, _ctype = write_video(self.content_dir, frames,
                                   frame_ms=frame_ms, fmt=fmt)
        return Response(body={
            "created": int(time.time()),
            "data": [{"url": f"/generated-videos/{name}"}],
        })

    # ------------------------------------------------------------------ #

    def _serve(self, name: str, ctype: str) -> Response:
        if not _NAME_RE.match(name):
            raise ApiError(400, "invalid file name")
        path = os.path.join(self.content_dir, name)
        if not os.path.exists(path):
            raise ApiError(404, f"{name} not found")
        with open(path, "rb") as f:
            return Response(body=f.read(), content_type=ctype)

    def serve_image(self, req: Request) -> Response:
        return self._serve(req.params["name"], "image/png")

    def serve_video(self, req: Request) -> Response:
        from localai_tpu.utils.video_io import CONTENT_TYPES

        name = req.params["name"]
        ext = os.path.splitext(name)[1]
        return self._serve(name, CONTENT_TYPES.get(ext, "video/mp4"))
