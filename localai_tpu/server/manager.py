"""Model lifecycle manager: lazy engine loading with singleflight + LRU.

TPU re-design of pkg/model (loader.go singleflight :163-221, watchdog LRU
eviction :135-195): "loading" compiles and shards weights into the resident
process; "evicting" drops an engine's HBM buffers instead of killing a
subprocess. One manager owns all engines for the slice.
"""

from __future__ import annotations

import gc
import logging
import threading
import time
from typing import Optional

import jax

from localai_tpu.config import ApplicationConfig, ModelConfig, ModelConfigLoader
from localai_tpu.engine import Engine, EngineConfig
from localai_tpu.engine.tokenizer import load_tokenizer
from localai_tpu.parallel.mesh import MeshPlan
from localai_tpu.templates import Evaluator
from localai_tpu.testing import faults

log = logging.getLogger("localai_tpu.manager")


class ModelQuarantinedError(RuntimeError):
    """The model's engine died more than restart_budget times inside
    restart_window_s, so the manager stopped respawning it (crash-only
    supervision with a bounded restart budget — ISSUE 4; the reference
    watchdog can kill a backend but relies on the operator to notice a
    crash loop). Requests get this clean, typed error — mapped to HTTP 503
    + Retry-After — instead of feeding an expensive reload/crash cycle."""

    def __init__(self, name: str, retry_after_s: float, deaths: int) -> None:
        super().__init__(
            f"model {name!r} quarantined after {deaths} engine deaths in "
            f"its restart window — retry in ~{retry_after_s:.0f}s"
        )
        self.model = name
        self.retry_after_s = max(1.0, retry_after_s)
        self.deaths = deaths


class LoadedModel:
    def __init__(self, cfg: ModelConfig, engine: Engine, evaluator: Evaluator):
        self.cfg = cfg
        # thread: instance-owned — teardown mutates engine/params only
        # after winning the `_loaded.pop()` ownership handoff, so exactly
        # one thread ever tears a given instance down
        self.engine = engine
        self.evaluator = evaluator
        self.loaded_at = time.monotonic()
        self.last_used = time.monotonic()
        self.busy_since: Optional[float] = None
        self.in_flight = 0
        self._lock = threading.Lock()

    def touch(self) -> None:
        self.last_used = time.monotonic()

    def acquire(self) -> None:
        with self._lock:
            self.in_flight += 1
            if self.busy_since is None:
                self.busy_since = time.monotonic()
            self.touch()

    def release(self) -> None:
        with self._lock:
            self.in_flight = max(0, self.in_flight - 1)
            if self.in_flight == 0:
                self.busy_since = None
            self.touch()

    def lease(self) -> "Lease":
        return Lease(self)


class VirtualModel(LoadedModel):
    """A per-tenant view over a shared base engine (ISSUE 10,
    docs/LORA_SERVING.md): same Engine object (the adapter is registered
    as a runtime tenant and every request carries `adapter=<name>`), but
    the tenant's OWN ModelConfig — templates, system prompt, generation
    defaults — so the OpenAI `model` field selects a fully-skinned tenant
    while N virtual models share one set of base weights. In-flight
    accounting delegates to the base LoadedModel so eviction/drain logic
    sees the engine's true load."""

    def __init__(self, cfg: ModelConfig, base: LoadedModel, adapter: str,
                 evaluator: Evaluator):
        super().__init__(cfg, base.engine, evaluator)
        self.base = base
        self.adapter = adapter

    def touch(self) -> None:
        super().touch()
        self.base.touch()

    def acquire(self) -> None:
        self.base.acquire()

    def release(self) -> None:
        self.base.release()


class Lease:
    """Idempotent in-flight marker: release() is safe to call from both a
    streaming generator's finally and an error path without double-counting."""

    def __init__(self, lm: "LoadedModel"):
        self._lm = lm
        self._released = False
        self._lock = threading.Lock()
        lm.acquire()

    def release(self) -> None:
        with self._lock:
            if self._released:
                return
            self._released = True
        self._lm.release()


class ModelManager:
    def __init__(self, app_cfg: ApplicationConfig, config_loader: Optional[ModelConfigLoader] = None):
        self.app_cfg = app_cfg
        self.configs = config_loader or ModelConfigLoader(app_cfg.models_dir)
        self.configs.load_all()
        self._loaded: dict[str, LoadedModel] = {}
        self._lock = threading.Lock()
        self._loading: dict[str, threading.Event] = {}
        # Crash-only supervision (ISSUE 4): per-model engine-death
        # timestamps inside the rolling restart window, lifetime totals,
        # and the quarantine clock (monotonic deadline; 0/absent = clear).
        self._death_times: dict[str, list[float]] = {}
        self._restart_total: dict[str, int] = {}
        self._quarantined_until: dict[str, float] = {}
        self._quarantine_total: dict[str, int] = {}
        faults.ensure_env_installed()
        # Multi-host serving bootstrap (ISSUE 13): wire this process into
        # the global device mesh BEFORE any engine touches jax. Idempotent
        # — a no-op for single-process deployments and for entrypoints
        # (__main__) that already ran it.
        if app_cfg.coordinator_address:
            from localai_tpu.parallel import distributed

            distributed.init_from_config(app_cfg)
        self._wd_stop = threading.Event()
        self._wd_thread: Optional[threading.Thread] = None
        if app_cfg.watchdog_idle_timeout_s > 0 or app_cfg.watchdog_busy_timeout_s > 0:
            self._wd_thread = threading.Thread(
                target=self._watchdog_loop, daemon=True, name="watchdog"
            )
            self._wd_thread.start()
        self._cw_thread: Optional[threading.Thread] = None
        if app_cfg.watch_configs:
            self.start_config_watcher(app_cfg.config_watch_interval_s)

    # ------------------------------------------------------------------ #

    def list_configs(self) -> list[ModelConfig]:
        return [self.configs.get(n) for n in self.configs.names()]

    def loaded_names(self) -> list[str]:
        with self._lock:
            return sorted(self._loaded)

    @staticmethod
    def _engine_dead(lm: LoadedModel) -> bool:
        """Crash-only death probe; non-LLM engines never report dead."""
        return bool(getattr(lm.engine, "is_dead", False))

    def _note_death_locked(self, name: str, now: float) -> None:
        """Record one observed engine death; trip the quarantine when the
        restart budget for the rolling window is exhausted. Caller holds
        self._lock."""
        window = max(0.0, self.app_cfg.restart_window_s)
        times = [t for t in self._death_times.get(name, ())
                 if now - t < window]
        times.append(now)
        self._death_times[name] = times
        self._restart_total[name] = self._restart_total.get(name, 0) + 1
        budget = self.app_cfg.restart_budget
        if budget >= 0 and len(times) > budget:
            self._quarantined_until[name] = now + self.app_cfg.quarantine_s
            self._quarantine_total[name] = self._quarantine_total.get(name, 0) + 1
            log.error(
                "model %s: %d engine deaths within %.0fs (budget %d) — "
                "quarantined for %.0fs", name, len(times), window, budget,
                self.app_cfg.quarantine_s,
            )

    def _reap_dead(self, name: str) -> bool:
        """Evict a loaded model whose engine loop died (the in-process
        analogue of a crashed backend process): the next get() loads a
        fresh engine — transparent restart — unless the restart budget is
        exhausted, in which case the model sits in quarantine and callers
        get ModelQuarantinedError until it expires. Returns True if a dead
        engine was reaped."""
        with self._lock:
            lm = self._loaded.get(name)
            if lm is None or not self._engine_dead(lm):
                return False
            self._loaded.pop(name)
            self._note_death_locked(name, time.monotonic())
        pm = getattr(lm.engine, "postmortem_path", "")
        log.warning(
            "model %s: engine loop died (%s) — evicted for crash-only "
            "restart%s",
            name, getattr(lm.engine, "_loop_dead", "?"),
            f" — postmortem: {pm}" if pm else "",
        )
        threading.Thread(
            target=self._teardown, args=(lm,), daemon=True,
            name="model-teardown",
        ).start()
        return True

    def _check_quarantine(self, name: str) -> None:
        with self._lock:
            until = self._quarantined_until.get(name, 0.0)
            now = time.monotonic()
            if until > now:
                deaths = len(self._death_times.get(name, ()))
                raise ModelQuarantinedError(name, until - now, deaths)
            if until:
                self._quarantined_until.pop(name, None)

    def restart_stats(self, name: str) -> dict:
        """Supervision counters for one model (monitoring surface)."""
        with self._lock:
            now = time.monotonic()
            return {
                "restarts_total": self._restart_total.get(name, 0),
                "deaths_in_window": len(self._death_times.get(name, ())),
                "quarantines_total": self._quarantine_total.get(name, 0),
                "quarantined_for_s": max(
                    0.0, self._quarantined_until.get(name, 0.0) - now
                ),
            }

    def health_gauges(self):
        """(name, labels, value) supervision gauges for the /metrics scrape
        (rides the same gauge source as the per-engine gauges)."""
        with self._lock:
            restarts = dict(self._restart_total)
            quarantines = dict(self._quarantine_total)
            until = dict(self._quarantined_until)
        now = time.monotonic()
        out = []
        for n, c in restarts.items():
            out.append(("localai_model_restarts", {"model": n}, float(c)))
        for n, c in quarantines.items():
            out.append(("localai_model_quarantines", {"model": n}, float(c)))
            out.append((
                "localai_model_quarantined", {"model": n},
                1.0 if until.get(n, 0.0) > now else 0.0,
            ))
        return out

    def get(self, name: str) -> LoadedModel:
        """Singleflight load (reference: loader.go:163-221). Raises KeyError
        for unknown models, ModelQuarantinedError while the model's restart
        budget is exhausted. Virtual models (base_model + adapter,
        ISSUE 10) resolve to the base's shared engine with the adapter
        registered as a runtime tenant."""
        vcfg = self.configs.get(name)
        if vcfg is not None and (vcfg.base_model or vcfg.adapter):
            return self._get_virtual(name, vcfg)
        while True:
            self._reap_dead(name)
            self._check_quarantine(name)
            with self._lock:
                lm = self._loaded.get(name)
                if lm is not None and not self._engine_dead(lm):
                    lm.touch()
                    return lm
                if lm is not None:
                    continue  # died between reap and here — re-reap
                ev = self._loading.get(name)
                if ev is None:
                    ev = threading.Event()
                    self._loading[name] = ev
                    break  # we are the loader
            ev.wait()  # someone else is loading; retry

        try:
            cfg = self.configs.get(name)
            if cfg is None:
                raise KeyError(f"model {name!r} not found")
            try:
                lm = self._load(cfg)
            except (KeyError, RuntimeError):
                raise
            except Exception as e:
                # Containment: a failed load (bad checkpoint, HBM OOM,
                # compile error) errors this one call and leaves serving up
                # (reference: initializers.go:123-150).
                gc.collect()
                raise RuntimeError(f"failed to load model {name!r}: {e}") from e
            with self._lock:
                self._loaded[name] = lm
                self._evict_lru_locked(protect=name)
            return lm
        finally:
            with self._lock:
                self._loading.pop(name, None)
            ev.set()

    def _get_virtual(self, name: str, cfg: ModelConfig) -> "VirtualModel":
        """Resolve a virtual model (ISSUE 10): load/reuse the base engine,
        register the adapter as a runtime tenant (idempotent — the loop
        thread fetches/promotes its factors lazily at first admission),
        and hand back a per-tenant view. Rebuilt per call so a crash-only
        base restart transparently re-registers every tenant."""
        from localai_tpu.config.model_config import LoraConfigError

        cfg.validate()  # typed LoraConfigError on half-configured entries
        base_cfg = self.configs.get(cfg.base_model)
        if base_cfg is None:
            raise KeyError(
                f"virtual model {name!r}: base model {cfg.base_model!r} "
                "not found"
            )
        if base_cfg.base_model or base_cfg.adapter:
            raise LoraConfigError(
                f"virtual model {name!r}: base {cfg.base_model!r} is itself "
                "a virtual model — adapters do not nest"
            )
        if base_cfg.lora_adapters:
            # The merge/runtime seam (ISSUE 10 satellite): the base already
            # folded adapters into its weights at load; registering another
            # runtime tenant on top would serve base+merged+runtime deltas
            # with no way to reason about which tenant sees what.
            raise LoraConfigError(
                f"virtual model {name!r}: base {cfg.base_model!r} merges "
                "`lora_adapters` at load — a base serving runtime adapter "
                "tenants must keep its weights pristine "
                "(docs/LORA_SERVING.md)"
            )
        base = self.get(cfg.base_model)
        engine = base.engine
        if not hasattr(engine, "register_adapter"):
            raise LoraConfigError(
                f"virtual model {name!r}: backend {base_cfg.backend!r} has "
                "no runtime adapter support"
            )
        engine.register_adapter(
            name, self._resolve_ckpt_dir(cfg.adapter),
            weight=cfg.adapter_weight,
        )
        return VirtualModel(
            cfg, base, adapter=name,
            evaluator=Evaluator(cfg, engine.tokenizer),
        )

    def lease(self, name: str) -> tuple[LoadedModel, Lease]:
        """get() + acquire, atomically w.r.t. eviction: the lease is taken
        while the model is verifiably still resident, so LRU/drain logic sees
        in_flight > 0 before any teardown can start. Virtual models anchor
        on their BASE LoadedModel (they are never in _loaded themselves)."""
        while True:
            lm = self.get(name)
            anchor = getattr(lm, "base", lm)
            with self._lock:
                if self._loaded.get(anchor.cfg.name) is anchor:
                    return lm, lm.lease()
            # evicted in the window between get() and now — reload and retry

    def peek(self, name: str) -> Optional[LoadedModel]:
        """Loaded model without triggering a load (monitoring paths)."""
        with self._lock:
            return self._loaded.get(name)

    def unload(self, name: str, drain_s: float = 30.0) -> bool:
        """Shutdown endpoint semantics (reference: /backend/shutdown).

        Drains in-flight requests (up to drain_s) in the background before
        dropping HBM buffers, so an active stream isn't cut mid-generation.
        """
        with self._lock:
            lm = self._loaded.pop(name, None)
        if lm is None:
            return False
        threading.Thread(
            target=self._drain_and_teardown, args=(lm, drain_s), daemon=True,
            name="unload-drain",
        ).start()
        return True

    def _drain_and_teardown(self, lm: LoadedModel, drain_s: float) -> None:
        deadline = time.monotonic() + drain_s
        while lm.in_flight > 0 and time.monotonic() < deadline:
            time.sleep(0.1)
        self._teardown(lm)

    def shutdown(self) -> None:
        self._wd_stop.set()
        with self._lock:
            loaded = list(self._loaded.values())
            self._loaded.clear()
        for lm in loaded:
            self._teardown(lm)

    # ------------------------------------------------------------------ #
    # Config hot-reload (reference: startup.go:209-319 fsnotify watcher on
    # the models dir; here mtime polling — no inotify dependency, works on
    # network filesystems TPU pods actually mount)
    # ------------------------------------------------------------------ #

    def ensure_watchdog(self) -> None:
        """Start the watchdog thread if settings enabled it at runtime."""
        if self._wd_thread is None:
            self._wd_thread = threading.Thread(
                target=self._watchdog_loop, daemon=True, name="watchdog"
            )
            self._wd_thread.start()

    def start_config_watcher(self, interval_s: float = 2.0) -> None:
        if self._cw_thread is not None:
            return
        # Baseline taken synchronously: changes made after construction are
        # always detected, even if the thread is slow to start.
        baseline = self._config_snapshot()
        self._cw_thread = threading.Thread(
            target=self._config_watch_loop, args=(interval_s, baseline),
            daemon=True, name="config-watcher",
        )
        self._cw_thread.start()

    def _config_snapshot(self) -> dict[str, float]:
        import os

        out: dict[str, float] = {}
        try:
            for fname in os.listdir(self.app_cfg.models_dir):
                if fname.endswith((".yaml", ".yml")):
                    path = os.path.join(self.app_cfg.models_dir, fname)
                    try:
                        out[path] = os.stat(path).st_mtime
                    except OSError:
                        pass
        except OSError:
            pass
        return out

    def _config_watch_loop(self, interval_s: float, last: dict[str, float]) -> None:
        while not self._wd_stop.wait(interval_s):
            snap = self._config_snapshot()
            if snap == last:
                continue
            last = snap
            try:
                self.reload_configs()
            except Exception:  # noqa: BLE001 — a bad yaml must not kill the loop
                log.exception("config reload failed")

    def reload_configs(self) -> int:
        """Re-read every model YAML; evict loaded models whose config changed
        or disappeared (the next request reloads them fresh). Returns the
        number of evictions."""
        old = {n: self.configs.get(n) for n in self.configs.names()}
        self.configs.load_all()
        evicted = 0
        with self._lock:
            loaded = list(self._loaded.keys())
        for name in loaded:
            new_cfg = self.configs.get(name)
            if new_cfg is None or new_cfg != old.get(name):
                log.info("config for %s changed — evicting for reload", name)
                self.unload(name, drain_s=10.0)
                evicted += 1
        return evicted

    # ------------------------------------------------------------------ #
    # Watchdog (reference: pkg/model/watchdog.go:197-279)
    # ------------------------------------------------------------------ #

    def _watchdog_loop(self) -> None:
        while not self._wd_stop.wait(self.app_cfg.watchdog_interval_s):
            try:
                self._watchdog_tick()
            except Exception:  # noqa: BLE001 — the watchdog must survive
                log.exception("watchdog tick failed")

    def _watchdog_tick(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        idle_t = self.app_cfg.watchdog_idle_timeout_s
        busy_t = self.app_cfg.watchdog_busy_timeout_s
        with self._lock:
            snapshot = list(self._loaded.items())
        for name, lm in snapshot:
            if self._engine_dead(lm):
                # Crash-only supervision (ISSUE 4): don't wait for the next
                # request to notice — reap the corpse now so its HBM frees
                # and the restart-budget clock starts from the real death.
                self._reap_dead(name)
                continue
            if busy_t > 0 and lm.busy_since is not None and now - lm.busy_since > busy_t:
                # A wedged generation holds its slot forever otherwise. The
                # reference kills the backend process (watchdog.go:250-279);
                # here the engine's requests are cancelled (slots drain to
                # their clients as finish_reason=stop) and the engine is
                # evicted so the next request gets a fresh one.
                n = lm.engine.cancel_all()
                log.warning(
                    "watchdog: model %s busy for >%gs — cancelled %d requests and evicting",
                    name, busy_t, n,
                )
                self.unload(name, drain_s=5.0)
            elif idle_t > 0 and lm.in_flight == 0 and now - lm.last_used > idle_t:
                log.info("watchdog: model %s idle for >%gs — evicting", name, idle_t)
                self.unload(name, drain_s=0.0)

    # ------------------------------------------------------------------ #

    def _teardown(self, lm: LoadedModel) -> None:
        log.info("evicting model %s from HBM", lm.cfg.name)
        lm.engine.stop()
        # Drop device buffer references; XLA frees HBM when the last ref dies.
        lm.engine.params = None
        lm.engine.cache = None
        gc.collect()

    def _evict_lru_locked(self, protect: str = "") -> None:
        """Reference: watchdog.go:135-195 LRU to MaxActiveBackends.

        `protect` is the model a get() is about to hand to its caller — never
        evict it, even though its lease hasn't been acquired yet."""
        budget = self.app_cfg.max_active_models
        if budget <= 0:
            return  # unlimited — HBM is the only budget (reference default)
        while len(self._loaded) > budget:
            idle = [
                (lm.last_used, n)
                for n, lm in self._loaded.items()
                if lm.in_flight == 0 and n != protect
            ]
            if not idle:
                return  # everything busy; let the next call retry
            _, victim = min(idle)
            lm = self._loaded.pop(victim)
            threading.Thread(
                target=self._drain_and_teardown, args=(lm, 30.0), daemon=True,
                name="unload-drain",
            ).start()

    def _resolve_ckpt_dir(self, model: str) -> str:
        import os

        ckpt_dir = model
        if not os.path.isabs(ckpt_dir):
            ckpt_dir = os.path.join(self.app_cfg.models_dir, ckpt_dir)
        return ckpt_dir

    def _parse_lora_entries(self, cfg: ModelConfig) -> list[tuple[str, float]]:
        """lora_adapters YAML entries → [(resolved_path, weight)] (entries:
        "path" or {"path": ..., "weight": 1.0}; reference: backend.proto
        LoraAdapter/LoraScale)."""
        out = []
        for entry in cfg.lora_adapters:
            if isinstance(entry, dict):
                apath = str(entry.get("path", ""))
                w = float(entry.get("weight", 1.0))
            else:
                apath, w = str(entry), 1.0
            if not apath:
                raise ValueError(
                    f"model {cfg.name!r}: lora_adapters entry missing a path"
                )
            out.append((self._resolve_ckpt_dir(apath), w))
        return out

    def _load(self, cfg: ModelConfig) -> LoadedModel:
        import os

        faults.fire("manager_load")  # injected load failure (ISSUE 4)

        from localai_tpu.models.config import PRESETS, get_arch
        from localai_tpu.models.llama import init_params

        # Non-text backends have their own loaders (reference: the model
        # loader spawns a different gRPC backend binary per modality —
        # initializers.go:50-154; here each returns a resident engine).
        backend_loaders = {
            "whisper": self._load_whisper,
            "tts": self._load_tts,
            "vad": self._load_vad,
            "diffusion": self._load_diffusion,
            "diffusers": self._load_diffusion,
            "stablediffusion": self._load_diffusion,
            "detection": self._load_detection,
            "musicgen": self._load_musicgen,
            "soundgen": self._load_musicgen,
            "sound-generation": self._load_musicgen,
            "remote": self._load_remote,
            "subprocess": self._load_subprocess,
            "bert": self._load_bert,
        }
        vlm = cfg.backend in ("llava", "vlm", "multimodal")
        loader = backend_loaders.get(cfg.backend) if not vlm else None
        if loader is None and not vlm and cfg.backend == "llama" and (
            cfg.model in whisper_presets() or "whisper" in cfg.model
        ):
            loader = self._load_whisper
        if loader is not None:
            t0 = time.monotonic()
            lm = loader(cfg)
            log.info(
                "loaded model %s (backend=%s) in %.1fs",
                cfg.name, cfg.backend, time.monotonic() - t0,
            )
            return lm

        t0 = time.monotonic()

        ckpt_dir: Optional[str] = None
        gguf_params = None
        gguf_tok_dir = None
        if cfg.model.endswith(".gguf"):
            # GGUF ingestion (reference: gguf.go:15-60 introspection +
            # grpc-server.cpp GGUF serving). Quantized tensors keep their
            # bits via grouped repack — engine/gguf.py.
            from localai_tpu.engine.gguf import load_gguf_checkpoint

            path = self._resolve_ckpt_dir(cfg.model)
            if not os.path.isfile(path):
                raise FileNotFoundError(f"model {cfg.name!r}: {path!r} not found")
            arch, gguf_params, gguf_tok_dir = load_gguf_checkpoint(path)
        elif cfg.model in PRESETS:
            arch = get_arch(cfg.model)
        else:
            ckpt_dir = cfg.model
            if not os.path.isabs(ckpt_dir):
                ckpt_dir = os.path.join(self.app_cfg.models_dir, ckpt_dir)
            if not os.path.isdir(ckpt_dir):
                raise FileNotFoundError(
                    f"model {cfg.name!r}: checkpoint dir {ckpt_dir!r} not found "
                    f"and not an arch preset ({sorted(PRESETS)})"
                )
            from localai_tpu.engine.weights import arch_from_hf_config

            arch = arch_from_hf_config(ckpt_dir)

        arch = _apply_rope_overrides(arch, cfg)

        from localai_tpu.parallel import distributed
        from localai_tpu.parallel.sharding import max_valid_tp

        par = cfg.parallel
        engine_devices = None
        if distributed.is_multiprocess():
            # Multi-host replica (ISSUE 13): dp strides ACROSS hosts, tp
            # stays within this host's chips (collectives on ICI, not DCN).
            # The engine/manager see the process-local device view of the
            # global mesh; weights shard-load per process via sharded_put.
            topo = distributed.topology()
            n_local = jax.local_device_count()
            tp = cfg.tensor_parallel if cfg.tensor_parallel > 0 else par.tp
            avail = n_local // max(1, par.ep * par.sp)
            tp = tp or max_valid_tp(arch, max(1, avail))
            tp = min(max(1, tp), max(1, avail))
            plan = distributed.multihost_plan(
                topo.num_processes, n_local, tp=tp, ep=par.ep, sp=par.sp)
            engine_devices = distributed.serving_devices()
            log.info(
                "model %s: multi-host plan dp=%d (hosts) x tp=%d (local "
                "chips) — process %d/%d",
                cfg.name, plan.dp, plan.tp, topo.process_id,
                topo.num_processes,
            )
        else:
            n_devices = len(jax.devices())
            avail = n_devices // max(1, par.dp * par.ep * par.sp)
            # tensor_parallel (ISSUE 7): the flat YAML knob wins over the
            # nested parallel.tp; -1/"auto" and 0 both fall back to the auto
            # pick (all devices left after dp/ep/sp, degraded to
            # max_valid_tp).
            tp = cfg.tensor_parallel if cfg.tensor_parallel > 0 else par.tp
            tp = tp or max_valid_tp(arch, max(1, avail))
            tp = min(max(1, tp), max(1, avail))
            plan = MeshPlan(dp=par.dp, tp=tp, ep=par.ep, sp=par.sp)

        tok_path = cfg.tokenizer or gguf_tok_dir or (ckpt_dir if ckpt_dir else None)
        if (tok_path and tok_path != "synthetic-bytes"
                and not _has_tokenizer_files(tok_path)):
            tok_path = None
        tokenizer = load_tokenizer(tok_path, vocab_size=arch.vocab_size)
        tv = getattr(tokenizer, "vocab_size", None)
        if tv and tv != arch.vocab_size:
            log.warning(
                "model %s: tokenizer vocab (%d) != arch vocab (%d); "
                "ids beyond the tokenizer are masked from sampling",
                cfg.name, tv, arch.vocab_size,
            )

        if cfg.lora_adapters and ckpt_dir is None:
            # Adapters need bf16 base tensors to merge into; GGUF payloads
            # are already quantized and synthetic presets have no checkpoint.
            # Failing loudly beats silently serving the unmodified base.
            raise ValueError(
                f"model {cfg.name!r}: lora_adapters require an HF safetensors "
                "checkpoint (not GGUF or a synthetic preset)"
            )
        if gguf_params is not None:
            params = gguf_params
        elif ckpt_dir is not None:
            from localai_tpu.engine.weights import load_hf_checkpoint

            lora = self._parse_lora_entries(cfg)
            # Load-time host quantization: the bf16 tree never touches HBM,
            # so int8 checkpoints up to ~2x HBM serve from one chip. LoRA
            # deltas merge on the host in the same pass, before quantizing.
            put = None
            if plan.total > 1:
                # Sharded placement AS EACH TENSOR IS READ (ISSUE 7):
                # jax.device_put with the param's NamedSharding ships every
                # chip its shard only — the full tree never materializes
                # replicated in HBM. (Quantized leaves keep their own
                # placement path and are re-placed by the engine.)
                from localai_tpu.engine.weights import sharded_put
                from localai_tpu.parallel.mesh import build_mesh

                put = sharded_put(arch, build_mesh(plan, engine_devices))
            params = load_hf_checkpoint(
                arch, ckpt_dir, put=put, quantize=cfg.quantization,
                lora=lora or None,
            )
            for adir, w in lora:
                log.info("model %s: merged lora adapter %s (weight=%.2f)",
                         cfg.name, adir, w)
        elif cfg.quantization and cfg.quantization not in ("none",):
            # Synthetic preset + quantization: init leaf-wise into the
            # quantized form so big archs fit (same ~2x HBM envelope).
            from localai_tpu.models.quant import init_params_quantized

            params = init_params_quantized(
                arch, jax.random.key(0), mode=cfg.quantization
            )
        else:
            params = jax.jit(lambda k: init_params(arch, k))(jax.random.key(0))

        draft_arch = None
        draft_params = None
        if cfg.draft_model and cfg.spec_mode in ("prompt_lookup",
                                                 "self_draft"):
            # Model-free spec (ISSUE 12): the draft checkpoint would sit
            # dead in HBM — the target's own weights / the host-visible
            # token streams do the drafting. Don't even load it.
            log.info(
                "model %s: spec_mode=%s is model-free — skipping draft "
                "checkpoint %s", cfg.name, cfg.spec_mode, cfg.draft_model,
            )
        elif cfg.draft_model:
            if cfg.draft_model in PRESETS:
                draft_arch = get_arch(cfg.draft_model)
                draft_params = jax.jit(lambda k: init_params(draft_arch, k))(
                    jax.random.key(1)
                )
            else:
                from localai_tpu.engine.weights import (
                    arch_from_hf_config,
                    load_hf_checkpoint,
                )

                dd = self._resolve_ckpt_dir(cfg.draft_model)
                draft_arch = arch_from_hf_config(dd)
                draft_params = load_hf_checkpoint(draft_arch, dd)

        engine = Engine(
            arch,
            params,
            tokenizer,
            mesh_plan=plan,
            devices=engine_devices,
            engine_cfg=EngineConfig(
                max_slots=cfg.max_slots, max_seq=cfg.context_size,
                tensor_parallel=cfg.tensor_parallel,
                kv_pages=cfg.kv_pages, kv_page_size=cfg.kv_page_size,
                kv_page_headroom=cfg.kv_page_headroom,
                kv_preempt=cfg.kv_preempt,
                kv_swap_bytes=cfg.kv_swap_bytes,
                kv_cache_dtype=cfg.kv_cache_dtype,
                paged_kernel=cfg.paged_kernel,
                quant_kernel=cfg.quant_kernel,
                lora_kernel=cfg.lora_kernel,
                adapter_cache_bytes=cfg.adapter_cache_bytes,
                kv_scale=cfg.kv_scale,
                prefill_chunk=cfg.prefill_chunk,
                attention_sink=cfg.attention_sink,
                attention_window=cfg.attention_window,
                kv_spill_bytes=cfg.kv_spill_bytes,
                kv_l1_span=cfg.kv_l1_span,
                sp_prefill=cfg.sp_prefill,
                fork_sampling=cfg.fork_sampling,
                max_pending=cfg.max_pending,
                queue_timeout_s=cfg.queue_timeout_s,
                deadline_s=cfg.deadline_s,
                trace_journal_events=cfg.trace_journal_events,
                postmortem_dir=self.app_cfg.postmortem_dir,
                spec_mode=cfg.spec_mode,
                self_draft_layers=cfg.self_draft_layers,
                spec_accept_ewma=cfg.spec_accept_ewma,
                spec_draft_buckets=tuple(cfg.spec_draft_buckets),
            ),
            draft_cfg=draft_arch,
            draft_params=draft_params,
            n_draft=cfg.n_draft,
            quantization=cfg.quantization,
        )
        engine.start()
        # Cluster fan-out (ISSUE 6, docs/CLUSTER.md): cluster_replicas >= 2
        # serves this model through N same-host engine replicas (shared
        # weight tree, per-replica KV pools/loops) behind the prefix-
        # affinity scheduler — the ClusterEngine facade keeps the Engine
        # surface, so every API/watchdog/metrics path is unchanged. Draft
        # and vision engines stay single-replica (their side state has no
        # transfer story yet).
        from localai_tpu.cluster.replica import parse_peers

        n_replicas = self.app_cfg.cluster_replicas
        peers = [] if (draft_arch is not None or vlm) else parse_peers(
            self.app_cfg.cluster_peers)
        if (n_replicas >= 2 or peers) and draft_arch is None and not vlm:
            from localai_tpu.cluster import (
                ClusterEngine,
                LocalReplica,
                RemoteReplica,
                parse_roles,
            )

            n_local = max(1, n_replicas)
            roles = parse_roles(n_local, self.app_cfg.cluster_role)
            replicas = [LocalReplica("r0", engine, role=roles[0])]
            for i in range(1, n_local):
                extra = Engine(
                    arch, params, tokenizer, mesh_plan=plan,
                    devices=engine_devices,
                    engine_cfg=engine.ecfg, quantization=cfg.quantization,
                )
                extra.start()
                replicas.append(LocalReplica(f"r{i}", extra, role=roles[i]))
            # Remote peers (ISSUE 13): workers on OTHER machines, reached
            # over HTTP. Roles come from their LocalAI-Cluster-Role header
            # at the first gauge refresh; the scheduler treats them as
            # prefill-handoff/affinity targets, never in-process dispatch.
            for pname, purl in peers:
                replicas.append(RemoteReplica(
                    pname, purl, model=cfg.name,
                    gauge_stale_s=self.app_cfg.cluster_gauge_stale_s,
                    chunk_bytes=self.app_cfg.transfer_chunk_bytes,
                    verify=self.app_cfg.transfer_checksum,
                    max_resumes=self.app_cfg.transfer_resumes,
                ))
            engine = ClusterEngine(
                replicas,
                transfer_max_bytes=self.app_cfg.transfer_max_bytes,
                affinity_spans=self.app_cfg.affinity_spans,
            )
            log.info(
                "model %s: fanned out to %d cluster replicas (roles=%s)"
                "%s",
                cfg.name, n_local, ",".join(roles),
                f" + {len(peers)} remote peer(s)" if peers else "",
            )
        evaluator = Evaluator(cfg, tokenizer)
        lm = LoadedModel(cfg, engine, evaluator)
        if vlm:
            # Multimodal: attach the vision tower; the chat handler injects
            # projected image tokens at admission. Two families —
            # llava-style (fixed-grid CLIP tower) and Qwen2-VL (native
            # resolution + m-rope; reference: vllm/backend.py:211-243).
            from localai_tpu.models import qwen2_vl as QV
            from localai_tpu.models import vision as V

            varch = cfg.options.get("vision", "")
            if ckpt_dir is not None and QV.is_qwen2_vl_dir(ckpt_dir):
                qcfg = QV.vision_config_from_hf(ckpt_dir)
                if qcfg.hidden_size != arch.hidden_size:
                    raise ValueError(
                        f"qwen2-vl merger dim {qcfg.hidden_size} != LLM "
                        f"hidden {arch.hidden_size}"
                    )
                lm.vision = QV.Qwen2VLVisionEncoder(
                    qcfg, QV.load_hf_qwen2_vl_vision(qcfg, ckpt_dir)
                )
            else:
                if varch in V.VISION_PRESETS:
                    vcfg = V.VISION_PRESETS[varch]
                    vparams = V.init_params(vcfg, jax.random.key(2))
                elif ckpt_dir is not None:
                    vcfg = V.vision_config_from_hf(ckpt_dir)
                    vparams = V.load_hf_vision(vcfg, ckpt_dir)
                else:
                    raise ValueError(
                        f"model {cfg.name!r}: vlm backend needs options.vision "
                        f"(preset) or a checkpoint with a vision tower"
                    )
                if vcfg.llm_dim != arch.hidden_size:
                    raise ValueError(
                        f"vision projector dim {vcfg.llm_dim} != LLM hidden "
                        f"{arch.hidden_size}"
                    )
                lm.vision = V.VisionEncoder(vcfg, vparams)
        log.info(
            "loaded model %s (arch=%s mesh=%s%s) in %.1fs",
            cfg.name, arch.name, plan, " +vision" if vlm else "",
            time.monotonic() - t0,
        )
        return lm

    # ------------------------------------------------------------------ #
    # Audio backends
    # ------------------------------------------------------------------ #

    def _load_whisper(self, cfg: ModelConfig) -> LoadedModel:
        import os

        import jax as _jax

        from localai_tpu.engine.audio_engine import WhisperEngine
        from localai_tpu.models import whisper as W

        if cfg.model in W.WHISPER_PRESETS:
            wcfg = W.WHISPER_PRESETS[cfg.model]
            params = W.init_params(wcfg, _jax.random.key(0))
            tokenizer = None
        else:
            ckpt_dir = self._resolve_ckpt_dir(cfg.model)
            if not os.path.isdir(ckpt_dir):
                raise FileNotFoundError(
                    f"model {cfg.name!r}: whisper checkpoint {ckpt_dir!r} not found"
                )
            wcfg = W.whisper_config_from_hf(ckpt_dir)
            params = W.load_hf_whisper(wcfg, ckpt_dir)
            tokenizer = None
            if _has_tokenizer_files(ckpt_dir):
                from transformers import AutoTokenizer

                tokenizer = AutoTokenizer.from_pretrained(ckpt_dir)
        return LoadedModel(cfg, WhisperEngine(wcfg, params, tokenizer), None)

    def _load_tts(self, cfg: ModelConfig) -> LoadedModel:
        import os

        import jax as _jax

        from localai_tpu.engine.audio_engine import TTSEngine
        from localai_tpu.models import tts as T

        if cfg.model in T.TTS_PRESETS:
            tcfg = T.TTS_PRESETS[cfg.model]
            params = T.init_params(tcfg, _jax.random.key(0))
        else:
            ckpt_dir = self._resolve_ckpt_dir(cfg.model)
            if not os.path.isdir(ckpt_dir):
                raise FileNotFoundError(
                    f"model {cfg.name!r}: tts checkpoint {ckpt_dir!r} not found"
                )
            from localai_tpu.models import musicgen as MG
            from localai_tpu.models import vits as V

            if MG.is_musicgen_dir(ckpt_dir):
                # A MusicGen checkpoint configured under the tts/soundgen
                # usecase — route to the sound-generation engine.
                return self._load_musicgen(cfg)
            if V.is_vits_dir(ckpt_dir):
                # Real published voice (facebook/mms-tts-*, vits-ljs) in the
                # HF VITS layout — the neural path; Griffin-Lim stays the
                # fallback for own-format checkpoints.
                from localai_tpu.engine.audio_engine import VitsEngine

                vcfg, vparams, vtok = V.load_vits(ckpt_dir)
                return LoadedModel(
                    cfg,
                    VitsEngine(vcfg, vparams, vtok, voices=cfg.options.get("voices")),
                    None,
                )
            tcfg, params = T.load_tts(ckpt_dir)
        return LoadedModel(cfg, TTSEngine(tcfg, params, voices=cfg.options.get("voices")), None)

    def _load_musicgen(self, cfg: ModelConfig) -> LoadedModel:
        """Text-to-music (SoundGeneration): published MusicGen checkpoints
        (reference: backend/python/transformers/backend.py:489-539)."""
        import os

        from localai_tpu.engine.audio_engine import MusicgenEngine
        from localai_tpu.models import musicgen as MG

        ckpt_dir = self._resolve_ckpt_dir(cfg.model)
        if not os.path.isdir(ckpt_dir):
            raise FileNotFoundError(
                f"model {cfg.name!r}: musicgen checkpoint {ckpt_dir!r} not found"
            )
        if not MG.is_musicgen_dir(ckpt_dir):
            raise ValueError(
                f"model {cfg.name!r}: {ckpt_dir!r} is not a musicgen checkpoint "
                "(config.json model_type must be 'musicgen')"
            )
        if not _has_tokenizer_files(ckpt_dir):
            raise FileNotFoundError(
                f"model {cfg.name!r}: musicgen checkpoint {ckpt_dir!r} has no "
                "text tokenizer files (tokenizer.json / tokenizer_config.json)"
            )
        from localai_tpu.engine.tokenizer import HFTokenizer

        mcfg, params = MG.load_musicgen(ckpt_dir)
        return LoadedModel(cfg, MusicgenEngine(mcfg, params, HFTokenizer(ckpt_dir)), None)

    def _load_vad(self, cfg: ModelConfig) -> LoadedModel:
        import os

        from localai_tpu.engine.audio_engine import VADEngine

        from localai_tpu.audio import learned_vad as _LV

        if cfg.model in ("", "builtin", "base", "vad-base", "silero"):
            # Default: the shipped pretrained net (assets/vad-base.safetensors,
            # trained offline on the formant-synthesis corpus — the silero
            # role, reference vad.go:13-33). `model: energy` still selects
            # the weightless detector explicitly.
            packaged = _LV.packaged_weights()
            if packaged is not None:
                params = _LV.load_params(packaged)
                return LoadedModel(
                    cfg, VADEngine(_LV.config_from_params(params), params), None
                )
        if cfg.model and cfg.model != "energy":
            # Any other configured checkpoint that can't be found is an
            # error, not a silent fall-through (same standard as the
            # tts/detection loaders above).
            ckpt_dir = self._resolve_ckpt_dir(cfg.model)
            if not os.path.isdir(ckpt_dir):
                raise FileNotFoundError(
                    f"model {cfg.name!r}: vad checkpoint {ckpt_dir!r} not found"
                )
            from localai_tpu.audio import learned_vad as LV

            weights = LV.find_weights(ckpt_dir)
            if not weights:
                raise FileNotFoundError(
                    f"model {cfg.name!r}: no vad.safetensors/model.safetensors "
                    f"in {ckpt_dir!r}"
                )
            # Learned VAD net (silero role) from safetensors; the net shape
            # is recovered from the weights themselves.
            params = LV.load_params(weights)
            return LoadedModel(
                cfg, VADEngine(LV.config_from_params(params), params), None
            )
        return LoadedModel(cfg, VADEngine(), None)

    def _load_bert(self, cfg: ModelConfig) -> LoadedModel:
        import os

        import jax as _jax

        from localai_tpu.engine.bert_engine import BertEngine
        from localai_tpu.models import bert as B

        if cfg.model in B.BERT_PRESETS:
            bcfg = B.BERT_PRESETS[cfg.model]
            params = B.init_params(bcfg, _jax.random.key(0))
            tok_path = cfg.tokenizer or None
        else:
            ckpt_dir = self._resolve_ckpt_dir(cfg.model)
            if not os.path.isdir(ckpt_dir):
                raise FileNotFoundError(
                    f"model {cfg.name!r}: bert checkpoint {ckpt_dir!r} not found"
                )
            bcfg = B.bert_config_from_hf(ckpt_dir)
            params = B.load_hf_bert(bcfg, ckpt_dir)
            tok_path = cfg.tokenizer or ckpt_dir
        if tok_path and not _has_tokenizer_files(tok_path):
            tok_path = None
        tokenizer = load_tokenizer(tok_path, vocab_size=bcfg.vocab_size)
        return LoadedModel(cfg, BertEngine(bcfg, params, tokenizer), None)

    def _load_remote(self, cfg: ModelConfig) -> LoadedModel:
        from localai_tpu.engine.remote import RemoteEngine

        url = cfg.options.get("url")
        if not url:
            raise ValueError(f"model {cfg.name!r}: backend remote needs options.url")
        eng = RemoteEngine(
            url,
            remote_model=cfg.options.get("remote_model", ""),
            api_key=cfg.options.get("api_key", ""),
        )
        return LoadedModel(cfg, eng, None)

    def _load_subprocess(self, cfg: ModelConfig) -> LoadedModel:
        import os

        from localai_tpu.engine.remote import SubprocessEngine

        child = dict(cfg.options.get("child") or {})
        if not child:
            child = {"model": cfg.model, "context_size": cfg.context_size,
                     "max_tokens": cfg.max_tokens, "max_slots": cfg.max_slots}
        eng = SubprocessEngine(
            cfg.name, child,
            workdir=os.path.join(self.app_cfg.models_dir, f".subprocess-{cfg.name}"),
            env_extra=cfg.options.get("env") or {},
        )
        return LoadedModel(cfg, eng, None)

    def _load_detection(self, cfg: ModelConfig) -> LoadedModel:
        import os

        import jax as _jax

        from localai_tpu.engine.image_engine import DetectionEngine
        from localai_tpu.models import detection as Det

        if cfg.model in Det.DETECTION_PRESETS:
            dcfg = Det.DETECTION_PRESETS[cfg.model]
            params = Det.init_params(dcfg, _jax.random.key(0))
        else:
            ckpt_dir = self._resolve_ckpt_dir(cfg.model)
            if not os.path.isdir(ckpt_dir):
                raise FileNotFoundError(
                    f"model {cfg.name!r}: detection checkpoint {ckpt_dir!r} not found"
                )
            from localai_tpu.models import yolos as Y

            if Y.is_yolos_dir(ckpt_dir):
                # Real published detector (hustvl/yolos-*) in the HF layout.
                from localai_tpu.engine.image_engine import YolosEngine

                ycfg, yparams = Y.load_yolos(ckpt_dir)
                return LoadedModel(cfg, YolosEngine(ycfg, yparams), None)
            dcfg, params = Det.load_detection(ckpt_dir)
        return LoadedModel(cfg, DetectionEngine(dcfg, params), None)

    def _load_diffusion(self, cfg: ModelConfig) -> LoadedModel:
        import os

        import jax as _jax

        from localai_tpu.engine.image_engine import DiffusionEngine
        from localai_tpu.models import diffusion as D

        if cfg.model in D.DIFFUSION_PRESETS:
            if cfg.lora_adapters:
                # Failing loudly beats silently serving the unmodified base
                # (same contract as the LLM loader above).
                raise ValueError(
                    f"model {cfg.name!r}: lora_adapters need a diffusers-"
                    "layout SD/SDXL checkpoint (not a synthetic preset)"
                )
            dcfg = D.DIFFUSION_PRESETS[cfg.model]
            params = D.init_params(dcfg, _jax.random.key(0))
        else:
            ckpt_dir = self._resolve_ckpt_dir(cfg.model)
            if not os.path.isdir(ckpt_dir):
                raise FileNotFoundError(
                    f"model {cfg.name!r}: diffusion checkpoint {ckpt_dir!r} not found"
                )
            from localai_tpu.models import flux as FX
            from localai_tpu.models import latent_diffusion as LD

            if FX.is_flux_dir(ckpt_dir):
                # Flux.1-class rectified-flow checkpoint (reference:
                # diffusers backend.py:218-224, :594-603).
                from localai_tpu.engine.image_engine import FluxEngine

                if cfg.lora_adapters:
                    raise ValueError(
                        f"model {cfg.name!r}: lora_adapters target SD/SDXL "
                        "checkpoints (kohya format); Flux LoRA is unsupported"
                    )
                # bf16 by default (fp32 Flux.1-dev is ~68 GB — beyond any
                # single chip); the model YAML may override via
                # `options.dtype` like the LLM loader's quantization knob.
                import jax.numpy as _jnp

                dtypes = {
                    "bfloat16": _jnp.bfloat16, "bf16": _jnp.bfloat16,
                    "float32": _jnp.float32, "fp32": _jnp.float32,
                }
                opt = str(cfg.options.get("dtype", "bfloat16")).lower()
                if opt not in dtypes:
                    raise ValueError(
                        f"model {cfg.name!r}: options.dtype {opt!r} — use "
                        "bfloat16 or float32"
                    )
                fcfg, fparams, ftoks = FX.load_flux_pipeline(
                    ckpt_dir, dtype=dtypes[opt]
                )
                return LoadedModel(cfg, FluxEngine(fcfg, fparams, ftoks), None)
            if LD.is_diffusers_dir(ckpt_dir):
                # Real published checkpoint (SD-1.5-class diffusers layout) —
                # reference: backend/python/diffusers/backend.py:27-120.
                from localai_tpu.engine.image_engine import LatentDiffusionEngine

                ldcfg, ldparams, tok = LD.load_pipeline(ckpt_dir)
                # Civitai-style SD/SDXL LoRA (kohya format) merged at load
                # (reference: diffusers backend.py:456-533 load_lora_weights).
                for apath, w in self._parse_lora_entries(cfg):
                    n_merged = LD.load_diffusion_lora(apath, ldparams, w)
                    if n_merged == 0:
                        raise ValueError(
                            f"model {cfg.name!r}: lora adapter {apath!r} "
                            "matched no unet/text-encoder tensors"
                        )
                    log.info("model %s: merged %d lora tensors from %s "
                             "(weight=%.2f)", cfg.name, n_merged, apath, w)
                # AnimateDiff-class motion adapter: a `motion_adapter` dir in
                # the model YAML, or one bundled inside the checkpoint (the
                # diffusers AnimateDiffPipeline save layout) — /v1/videos
                # then runs a real temporal model instead of the latent sweep
                # (reference: diffusers backend.py:226-253 video pipelines).
                from localai_tpu.models import video_diffusion as VD

                motion = None
                mdir = cfg.options.get("motion_adapter") or ""
                if mdir:
                    mdir = self._resolve_ckpt_dir(str(mdir))
                elif VD.is_motion_adapter_dir(
                    os.path.join(ckpt_dir, "motion_adapter")
                ):
                    mdir = os.path.join(ckpt_dir, "motion_adapter")
                if mdir:
                    if not VD.is_motion_adapter_dir(mdir):
                        raise FileNotFoundError(
                            f"model {cfg.name!r}: motion_adapter {mdir!r} is "
                            "not a diffusers MotionAdapter dir"
                        )
                    motion = VD.load_motion_adapter(mdir)
                    log.info("model %s: motion adapter loaded from %s",
                             cfg.name, mdir)
                sched = str(cfg.options.get("scheduler", "ddim"))
                if sched not in LD.SUPPORTED_SCHEDULERS:
                    # Fail at LOAD, not at the first generation request.
                    raise ValueError(
                        f"model {cfg.name!r}: unknown scheduler {sched!r} "
                        f"(supported: {', '.join(sorted(LD.SUPPORTED_SCHEDULERS))})"
                    )
                eng = LatentDiffusionEngine(
                    ldcfg, ldparams, tok,
                    default_scheduler=sched,
                    motion=motion,
                )
                return LoadedModel(cfg, eng, None)
            if cfg.lora_adapters:
                raise ValueError(
                    f"model {cfg.name!r}: lora_adapters need a diffusers-"
                    "layout SD/SDXL checkpoint (this is an own-format "
                    "diffusion checkpoint)"
                )
            dcfg, params = D.load_diffusion(ckpt_dir)
        return LoadedModel(cfg, DiffusionEngine(dcfg, params), None)


def whisper_presets() -> dict:
    from localai_tpu.models.whisper import WHISPER_PRESETS

    return WHISPER_PRESETS


def _apply_rope_overrides(arch, cfg):
    """YAML rope knobs override the checkpoint's (reference parity:
    model_config.go rope_scaling/rope_freq_base are user config, forwarded
    over the checkpoint's own values)."""
    import dataclasses as _dc

    updates = {}
    if cfg.rope_freq_base:
        updates["rope_theta"] = float(cfg.rope_freq_base)
    rs = cfg.rope_scaling
    if rs:
        stype = rs.get("rope_type") or rs.get("type")
        if stype == "su":
            stype = "longrope"
        if stype not in ("linear", "llama3", "yarn", "longrope"):
            # Fail at LOAD, not at first admission trace — and never let a
            # factor-only dict silently null the checkpoint's own scaling
            # while still lifting the window.
            raise ValueError(
                f"model {cfg.name!r}: rope_scaling needs rope_type in "
                f"linear/llama3/yarn/longrope (got {stype!r})"
            )
        updates["rope_scaling"] = stype
        if "factor" in rs:
            updates["rope_scaling_factor"] = float(rs["factor"])
        if "original_max_position_embeddings" in rs:
            updates["rope_original_max_position"] = int(
                rs["original_max_position_embeddings"]
            )
        if "low_freq_factor" in rs:
            updates["rope_low_freq_factor"] = float(rs["low_freq_factor"])
        if "high_freq_factor" in rs:
            updates["rope_high_freq_factor"] = float(rs["high_freq_factor"])
        if "beta_fast" in rs:
            updates["rope_beta_fast"] = float(rs["beta_fast"])
        if "beta_slow" in rs:
            updates["rope_beta_slow"] = float(rs["beta_slow"])
        if rs.get("long_factor"):
            updates["rope_long_factor"] = tuple(rs["long_factor"])
        if rs.get("short_factor"):
            updates["rope_short_factor"] = tuple(rs["short_factor"])
        if rs.get("attention_factor") is not None:
            updates["rope_attn_factor"] = float(rs["attention_factor"])
        # A scaled rope serves past the checkpoint's advertised window; lift
        # max_position to the deployment context so longrope's long/short
        # choice and prompt admission agree with the YAML.
        updates["max_position"] = max(arch.max_position, cfg.context_size)
    if not updates:
        return arch
    return _dc.replace(arch, **updates)


def _has_tokenizer_files(path: str) -> bool:
    import os

    return any(
        os.path.exists(os.path.join(path, f))
        for f in ("tokenizer.json", "tokenizer.model", "tokenizer_config.json")
    )
