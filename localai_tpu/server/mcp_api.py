"""MCP agent endpoint + agent-jobs CRUD.

Reference: endpoints/localai/mcp.go (POST /mcp/v1/chat/completions — chat
with an MCP tool-calling loop) and the agent-jobs routes over
core/services/agent_jobs.go.
"""

from __future__ import annotations

from typing import Optional

from localai_tpu.config import Usecase
from localai_tpu.server.app import ApiError, Request, Response, Router
from localai_tpu.server.manager import ModelManager
from localai_tpu.server.openai_api import OpenAIApi, _fingerprint, _now
from localai_tpu.services.agent_jobs import AgentJob, AgentJobService


class McpApi:
    def __init__(self, manager: ModelManager, base: OpenAIApi,
                 jobs: Optional[AgentJobService] = None):
        self.manager = manager
        self._base = base
        self.jobs = jobs

    def register(self, r: Router) -> None:
        r.add("POST", "/mcp/v1/chat/completions", self.mcp_chat)
        r.add("POST", "/mcp/chat/completions", self.mcp_chat)
        if self.jobs is not None:
            r.add("GET", "/agent-jobs", self.list_jobs)
            r.add("POST", "/agent-jobs", self.create_job)
            r.add("GET", "/agent-jobs/:id", self.get_job)
            r.add("PUT", "/agent-jobs/:id", self.update_job)
            r.add("DELETE", "/agent-jobs/:id", self.delete_job)
            r.add("POST", "/agent-jobs/:id/run", self.run_job)
            r.add("GET", "/agent-jobs/:id/history", self.job_history)

    # ------------------------------------------------------------------ #
    # MCP chat
    # ------------------------------------------------------------------ #

    def mcp_chat(self, req: Request) -> Response:
        from localai_tpu.mcp import agent_loop
        from localai_tpu.mcp.agent import make_engine_chat_fn
        from localai_tpu.mcp.client import clients_from_config

        body = req.body or {}
        messages = body.get("messages")
        if not messages or not isinstance(messages, list):
            raise ApiError(400, "messages is required and must be a non-empty array")
        lm, lease = self._base._resolve(req, Usecase.CHAT)
        clients = []
        try:
            mcp_cfg = lm.cfg.options.get("mcp") or {}
            clients = clients_from_config(mcp_cfg)
            chat_fn = make_engine_chat_fn(
                lm,
                max_tokens=int(body.get("max_tokens") or lm.cfg.max_tokens),
                temperature=body.get("temperature"),
            )
            result = agent_loop(
                chat_fn, messages, clients,
                max_iterations=int(body.get("max_iterations") or 10),
            )
        finally:
            lease.release()
            for c in clients:
                if hasattr(c, "close"):
                    c.close()
        return Response(body={
            "id": f"mcpcmpl-{_now()}",
            "object": "chat.completion",
            "created": _now(),
            "model": lm.cfg.name,
            "system_fingerprint": _fingerprint(),
            "choices": [{
                "index": 0,
                "message": result["message"],
                "finish_reason": "stop",
            }],
            "agent": {
                "iterations": result["iterations"],
                "tool_calls": result["tool_calls"],
            },
        })

    # ------------------------------------------------------------------ #
    # Agent jobs
    # ------------------------------------------------------------------ #

    def _job(self, req: Request) -> AgentJob:
        job = self.jobs.get(req.params["id"])
        if job is None:
            raise ApiError(404, f"agent job {req.params['id']!r} not found")
        return job

    @staticmethod
    def _render(job: AgentJob, with_history: bool = False) -> dict:
        d = job.to_dict()
        if not with_history:
            d["history_len"] = len(d.pop("history"))
        return d

    def list_jobs(self, req: Request) -> Response:
        """List agent jobs."""
        return Response(body={"jobs": [self._render(j) for j in self.jobs.list()]})

    def create_job(self, req: Request) -> Response:
        """Create an agent job ({name, model, prompt, schedule, enabled})."""
        body = req.body or {}
        try:
            job = self.jobs.create(
                name=body.get("name", ""),
                model=body.get("model", ""),
                prompt=body.get("prompt", ""),
                schedule=body.get("schedule", ""),
                enabled=bool(body.get("enabled", True)),
            )
        except ValueError as e:
            raise ApiError(400, str(e)) from None
        return Response(status=201, body=self._render(job))

    def get_job(self, req: Request) -> Response:
        return Response(body=self._render(self._job(req), with_history=True))

    def update_job(self, req: Request) -> Response:
        body = req.body or {}
        try:
            job = self.jobs.update(req.params["id"], **{
                k: body.get(k) for k in ("name", "model", "prompt", "schedule", "enabled")
            })
        except ValueError as e:
            raise ApiError(400, str(e)) from None
        if job is None:
            raise ApiError(404, f"agent job {req.params['id']!r} not found")
        return Response(body=self._render(job))

    def delete_job(self, req: Request) -> Response:
        if not self.jobs.delete(req.params["id"]):
            raise ApiError(404, f"agent job {req.params['id']!r} not found")
        return Response(body={"status": "deleted"})

    def run_job(self, req: Request) -> Response:
        """Trigger a job immediately; returns the history entry."""
        entry = self.jobs.run_now(req.params["id"])
        if entry is None:
            raise ApiError(404, f"agent job {req.params['id']!r} not found")
        return Response(body=entry)

    def job_history(self, req: Request) -> Response:
        return Response(body={"history": self._job(req).history})


def make_job_runner(manager: ModelManager):
    """Default job runner: agent loop over the job's model (MCP tools from
    the model's config)."""

    def run(job: AgentJob) -> str:
        from localai_tpu.mcp import agent_loop
        from localai_tpu.mcp.agent import make_engine_chat_fn
        from localai_tpu.mcp.client import clients_from_config

        lm, lease = manager.lease(job.model)
        clients = []
        try:
            clients = clients_from_config(lm.cfg.options.get("mcp") or {})
            result = agent_loop(
                make_engine_chat_fn(lm),
                [{"role": "user", "content": job.prompt}],
                clients,
            )
            return result["message"].get("content") or ""
        finally:
            lease.release()
            for c in clients:
                if hasattr(c, "close"):
                    c.close()

    return run
