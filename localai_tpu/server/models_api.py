"""Model import / edit / reload endpoints.

Reference: endpoints/localai/import_model.go (POST /models/import,
/models/import-uri with config discovery) and edit_model.go (edit +
ReloadModelsEndpoint). Import writes a YAML into the models dir through the
same loader the boot path uses; URI imports run as async jobs (HF repo
checkpoints fetched file-by-file with resume).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
import uuid
from typing import Any, Optional

from localai_tpu.config import ModelConfig
from localai_tpu.server.app import ApiError, Request, Response, Router
from localai_tpu.server.manager import ModelManager

log = logging.getLogger("localai_tpu.models_api")


def discover_model_config(uri: str, name: str = "",
                          preferences: Optional[dict] = None) -> dict[str, Any]:
    """Build a model-config dict from a URI (reference: importers.
    DiscoverModelConfig). Supported: arch presets, file:// checkpoint dirs,
    huggingface://owner/repo."""
    from localai_tpu.models.config import PRESETS

    prefs = preferences or {}
    if uri in PRESETS:
        return {"name": name or uri, "model": uri, **prefs}
    if uri.startswith("file://"):
        path = uri[len("file://"):]
        if not os.path.isdir(path):
            raise ApiError(400, f"checkpoint dir {path!r} not found")
        return {"name": name or os.path.basename(path.rstrip("/")), "model": path, **prefs}
    if uri.startswith("huggingface://"):
        repo = uri[len("huggingface://"):].strip("/")
        if repo.count("/") != 1:
            raise ApiError(400, "huggingface:// import needs owner/repo")
        default = repo.split("/")[1].lower()
        cfg = {"name": name or default, "model": repo, "_hf_repo": repo, **prefs}
        if "whisper" in repo.lower():
            cfg.setdefault("backend", "whisper")
        if any(k in repo.lower() for k in ("bge", "minilm", "e5-")):
            cfg.setdefault("backend", "bert")
        return cfg
    raise ApiError(400, f"cannot discover a model config from {uri!r}")


class ModelsApi:
    def __init__(self, manager: ModelManager):
        self.manager = manager
        self._jobs: dict[str, dict[str, Any]] = {}
        self._lock = threading.Lock()

    def register(self, r: Router) -> None:
        r.add("POST", "/models/import", self.import_model)
        r.add("POST", "/models/import-uri", self.import_uri)
        r.add("GET", "/models/import-jobs/:uuid", self.import_job)
        r.add("GET", "/models/config/:name", self.get_config)
        r.add("POST", "/models/edit/:name", self.edit_model)
        r.add("PUT", "/models/edit/:name", self.edit_model)
        r.add("POST", "/models/reload", self.reload)

    # ------------------------------------------------------------------ #

    def import_model(self, req: Request) -> Response:
        """Create a model config from an explicit dict (import_model.go)."""
        body = req.body or {}
        if not isinstance(body, dict) or not body:
            raise ApiError(400, "model config object required")
        try:
            cfg = ModelConfig.from_dict(dict(body))
            if not cfg.name:
                raise ValueError("name is required")
            path = self.manager.configs.write(cfg)
        except (ValueError, TypeError) as e:
            raise ApiError(400, f"invalid model config: {e}") from None
        return Response(status=201, body={"name": cfg.name, "path": path})

    def import_uri(self, req: Request) -> Response:
        """Discover + install a model from a URI; async for HF repos."""
        body = req.body or {}
        uri = body.get("uri")
        if not uri or not isinstance(uri, str):
            raise ApiError(400, "uri is required")
        cfg_dict = discover_model_config(
            uri, name=body.get("name", ""), preferences=body.get("preferences") or {}
        )
        repo = cfg_dict.pop("_hf_repo", None)
        if repo is None:
            cfg = ModelConfig.from_dict(cfg_dict)
            path = self.manager.configs.write(cfg)
            return Response(status=201, body={
                "name": cfg.name, "path": path, "status": "installed",
            })

        job_id = uuid.uuid4().hex
        job = {"uuid": job_id, "name": cfg_dict["name"], "uri": uri,
               "processed": False, "error": None, "message": "downloading",
               "progress": 0.0, "started_at": time.time()}
        with self._lock:
            self._jobs[job_id] = job

        def run() -> None:
            try:
                from localai_tpu.downloader import fetch_hf_model

                dest = os.path.join(self.manager.app_cfg.models_dir, cfg_dict["name"])

                def progress(fname, done, total):
                    job["message"] = f"downloading {fname}"
                    if total > 0:
                        job["progress"] = round(done / total * 100.0, 1)

                fetch_hf_model(repo, dest, progress=progress)
                cfg_dict["model"] = dest
                cfg = ModelConfig.from_dict(cfg_dict)
                self.manager.configs.write(cfg)
                job["message"] = "installed"
            except Exception as e:  # noqa: BLE001 — surfaced via the job
                job["error"] = f"{type(e).__name__}: {e}"
                job["message"] = "failed"
                log.warning("import of %s failed: %s", uri, e)
            finally:
                job["processed"] = True

        threading.Thread(target=run, daemon=True,
                         name="models-import").start()
        return Response(status=202, body={"uuid": job_id, "name": cfg_dict["name"]})

    def import_job(self, req: Request) -> Response:
        with self._lock:
            job = self._jobs.get(req.params["uuid"])
        if job is None:
            raise ApiError(404, f"import job {req.params['uuid']!r} not found")
        return Response(body=job)

    # ------------------------------------------------------------------ #

    def get_config(self, req: Request) -> Response:
        """Full persisted config for one model (the WebUI editor's source)."""
        name = req.params["name"]
        cfg = self.manager.configs.get(name)
        if cfg is None:
            raise ApiError(404, f"model {name!r} not found")
        return Response(body=cfg.to_dict())

    def edit_model(self, req: Request) -> Response:
        """Patch + persist a model config; the loaded engine is evicted so
        the next request serves the new config (edit_model.go)."""
        name = req.params["name"]
        cfg = self.manager.configs.get(name)
        if cfg is None:
            raise ApiError(404, f"model {name!r} not found")
        body = req.body or {}
        if not isinstance(body, dict) or not body:
            raise ApiError(400, "patch object required")
        merged = cfg.to_dict()
        merged.update(body)
        merged["name"] = name  # renames go through import+delete
        try:
            new_cfg = ModelConfig.from_dict(merged)
            self.manager.configs.write(new_cfg)
        except (ValueError, TypeError) as e:
            raise ApiError(400, f"invalid model config: {e}") from None
        if self.manager.peek(name) is not None:
            self.manager.unload(name, drain_s=10.0)
        return Response(body=new_cfg.to_dict())

    def reload(self, req: Request) -> Response:
        """Re-read every model YAML (ReloadModelsEndpoint)."""
        evicted = self.manager.reload_configs()
        return Response(body={
            "status": "reloaded",
            "models": self.manager.configs.names(),
            "evicted": evicted,
        })
