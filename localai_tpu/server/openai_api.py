"""OpenAI-compatible endpoints + LocalAI native endpoints.

Reference: core/http/endpoints/openai/*.go (chat.go:27 SSE+tools,
completion.go, edit.go, embeddings.go, list.go) and endpoints/localai
(tokenize.go, system.go, backend.go monitor/shutdown). Handlers translate
HTTP requests into engine GenRequests; the streaming path iterates the
engine's per-request event queue directly into SSE frames.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Iterator, Optional

from localai_tpu import __version__
from localai_tpu.config import Usecase
from localai_tpu.engine import GenRequest
from localai_tpu.server.app import ApiError, Request, Response, Router, SSEStream
from localai_tpu.server.manager import LoadedModel, ModelManager


def _now() -> int:
    return int(time.time())


def _fingerprint() -> str:
    return f"localai-tpu-{__version__}"


class OpenAIApi:
    def __init__(self, manager: ModelManager):
        self.manager = manager
        self.started_at = time.time()

    # ------------------------------------------------------------------ #
    # Routing
    # ------------------------------------------------------------------ #

    def register(self, r: Router) -> None:
        for prefix in ("/v1", ""):
            r.add("POST", f"{prefix}/chat/completions", self.chat)
            r.add("POST", f"{prefix}/completions", self.completion)
            r.add("POST", f"{prefix}/edits", self.edit)
            r.add("POST", f"{prefix}/embeddings", self.embeddings)
            r.add("GET", f"{prefix}/models", self.list_models)
        r.add("GET", "/v1/models/:name", self.get_model)
        r.add("POST", "/v1/tokenize", self.tokenize)
        r.add("POST", "/tokenize", self.tokenize)
        r.add("GET", "/healthz", self.health)
        r.add("GET", "/readyz", self.health)
        r.add("GET", "/version", self.version)
        r.add("GET", "/system", self.system)
        r.add("GET", "/backend/monitor", self.backend_monitor)
        r.add("POST", "/backend/monitor", self.backend_monitor)
        r.add("POST", "/backend/shutdown", self.backend_shutdown)

    # ------------------------------------------------------------------ #
    # Helpers
    # ------------------------------------------------------------------ #

    def _resolve_name(self, req: Request, usecase: Usecase) -> str:
        """Model from body, else first config serving the usecase (reference:
        middleware/request.go:92 BuildFilteredFirstAvailableDefaultModel)."""
        body = req.body or {}
        name = body.get("model") or (req.params or {}).get("name")
        if not name:
            cfg = self.manager.configs.first_with(usecase)
            if cfg is None:
                raise ApiError(404, f"no model configured for {usecase}")
            name = cfg.name
        cfg = self.manager.configs.get(name)
        if cfg is None:
            raise ApiError(404, f"model {name!r} not found")
        if not cfg.has_usecase(usecase):
            raise ApiError(400, f"model {name!r} does not support {usecase}")
        return name

    def _resolve(self, req: Request, usecase: Usecase):
        """Loaded model + idempotent lease, taken atomically w.r.t. eviction."""
        name = self._resolve_name(req, usecase)
        try:
            return self.manager.lease(name)
        except KeyError:
            raise ApiError(404, f"model {name!r} not found") from None

    def _gen_request(self, lm: LoadedModel, body: dict[str, Any], prompt_ids: list[int],
                     extra_stop: Optional[list[str]] = None) -> GenRequest:
        cfg = lm.cfg
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        stop = list(stop) + [s for s in (extra_stop or []) if s not in stop]
        max_tokens = body.get("max_completion_tokens") or body.get("max_tokens") or cfg.max_tokens

        def pick(key: str, default):
            v = body.get(key)
            return default if v is None else v

        logit_bias = {}
        for k, v in (body.get("logit_bias") or {}).items():
            try:
                logit_bias[int(k)] = float(v)
            except (TypeError, ValueError):
                raise ApiError(400, f"invalid logit_bias entry {k!r}") from None

        return GenRequest(
            prompt_ids=prompt_ids,
            max_new_tokens=int(max_tokens),
            temperature=float(pick("temperature", cfg.temperature)),
            top_k=int(pick("top_k", cfg.top_k)),
            top_p=float(pick("top_p", cfg.top_p)),
            min_p=float(pick("min_p", cfg.min_p)),
            repeat_penalty=float(pick("repeat_penalty", cfg.repeat_penalty)),
            presence_penalty=float(pick("presence_penalty", cfg.presence_penalty)),
            frequency_penalty=float(pick("frequency_penalty", cfg.frequency_penalty)),
            stop=stop,
            seed=body.get("seed", cfg.seed),
            logit_bias=logit_bias,
        )

    @staticmethod
    def _usage(final, extra: bool) -> dict[str, Any]:
        u = {
            "prompt_tokens": final.prompt_tokens,
            "completion_tokens": final.completion_tokens,
            "total_tokens": final.prompt_tokens + final.completion_tokens,
        }
        if extra:
            # reference: Extra-Usage header surfaces backend timings
            # (chat.go:47-50; proto Reply timing fields).
            u["timing_prompt_processing"] = final.timing_prompt_processing
            u["timing_token_generation"] = final.timing_token_generation
        return u

    # ------------------------------------------------------------------ #
    # Chat
    # ------------------------------------------------------------------ #

    def chat(self, req: Request) -> Response | SSEStream:
        body = req.body or {}
        messages = body.get("messages")
        if not messages or not isinstance(messages, list):
            raise ApiError(400, "messages is required and must be a non-empty array")
        lm, lease = self._resolve(req, Usecase.CHAT)
        try:
            return self._chat_inner(req, lm, lease, body)
        except BaseException:
            lease.release()  # idempotent — safe even if the inner path released
            raise

    def _chat_inner(self, req: Request, lm: LoadedModel, lease, body: dict[str, Any]) -> Response | SSEStream:
        from localai_tpu.functions import tools_prompt_for, parse_function_calls
        from localai_tpu.functions.jsonschema import GrammarConstraint, tool_call_schema

        tools = body.get("tools") or []
        if body.get("functions"):  # legacy field
            tools = [{"type": "function", "function": f} for f in body["functions"]]
        tool_choice = body.get("tool_choice")
        if tool_choice == "none":
            tools = []
        tprompt = tools_prompt_for(tools) if tools else ""

        # Constrained decoding (reference: chat.go:224-253 grammar generation
        # for tools / response_format; here a token-mask grammar).
        grammar = None
        rf = body.get("response_format") or {}
        if rf.get("type") == "json_object":
            grammar = GrammarConstraint({"type": "object"})
        elif rf.get("type") == "json_schema":
            schema = (rf.get("json_schema") or {}).get("schema") or {}
            grammar = GrammarConstraint(schema)
        if tools and (tool_choice == "required" or isinstance(tool_choice, dict)):
            selected = tools
            if isinstance(tool_choice, dict):
                fname = (tool_choice.get("function") or {}).get("name")
                named = [t for t in tools if (t.get("function") or {}).get("name") == fname]
                if not named:
                    raise ApiError(400, f"tool_choice names unknown function {fname!r}")
                selected = named
            grammar = GrammarConstraint(tool_call_schema(selected))

        prompt = lm.evaluator.template_messages(body["messages"], tools_prompt=tprompt)
        add_bos = not lm.cfg.template.use_tokenizer_template
        ids = lm.engine.tokenizer.encode(prompt, add_bos=add_bos)
        gen = self._gen_request(lm, body, ids, extra_stop=lm.evaluator.stop_sequences())
        gen.grammar = grammar

        rid = f"chatcmpl-{uuid.uuid4().hex[:28]}"
        created = _now()
        model_name = lm.cfg.name
        extra_usage = "extra-usage" in req.headers

        if body.get("stream"):
            handle = lm.engine.submit(gen)

            def events() -> Iterator[dict]:
                try:
                    base = {
                        "id": rid, "object": "chat.completion.chunk",
                        "created": created, "model": model_name,
                        "system_fingerprint": _fingerprint(),
                    }
                    yield {**base, "choices": [{"index": 0, "delta": {"role": "assistant", "content": ""}, "finish_reason": None}]}
                    final = None
                    if tools:
                        # Tool calls must stream as tool_calls deltas, not raw
                        # JSON content (reference: chat.go streams function-
                        # call deltas) — but plain-text answers should still
                        # stream live. Decide from the first non-whitespace
                        # output: JSON/`<function=` heads buffer for parsing,
                        # anything else streams immediately.
                        parts: list[str] = []
                        emitted = 0  # tokens already streamed as content
                        buffering: Optional[bool] = None
                        for ev in handle:
                            if ev.kind == "token":
                                parts.append(ev.text)
                                if buffering is None:
                                    head = "".join(parts).lstrip()
                                    if head:
                                        buffering = head[0] in "{[<"
                                if buffering is False:
                                    chunk = "".join(parts[emitted:])
                                    emitted = len(parts)
                                    yield {**base, "choices": [{"index": 0, "delta": {"content": chunk}, "finish_reason": None}]}
                            elif ev.kind == "error":
                                yield {"error": {"message": ev.error, "type": "server_error"}}
                                return
                            else:
                                final = ev
                        text = "".join(parts)
                        if buffering:
                            calls = parse_function_calls(text, lm.cfg)
                            if calls:
                                deltas = [{**c, "index": i} for i, c in enumerate(calls)]
                                yield {**base, "choices": [{"index": 0, "delta": {"tool_calls": deltas}, "finish_reason": None}]}
                                finish = "tool_calls"
                            else:
                                if text:
                                    yield {**base, "choices": [{"index": 0, "delta": {"content": text}, "finish_reason": None}]}
                                finish = final.finish_reason
                        else:
                            tail = "".join(parts[emitted:])
                            if tail:  # e.g. whitespace-only generation
                                yield {**base, "choices": [{"index": 0, "delta": {"content": tail}, "finish_reason": None}]}
                            finish = final.finish_reason
                    else:
                        for ev in handle:
                            if ev.kind == "token":
                                yield {**base, "choices": [{"index": 0, "delta": {"content": ev.text}, "finish_reason": None}]}
                            elif ev.kind == "error":
                                yield {"error": {"message": ev.error, "type": "server_error"}}
                                return
                            else:
                                final = ev
                        finish = final.finish_reason
                    out = {**base, "choices": [{"index": 0, "delta": {}, "finish_reason": finish}]}
                    out["usage"] = self._usage(final, extra_usage)
                    yield out
                finally:
                    lease.release()

            return SSEStream(events(), on_disconnect=handle.cancel)

        try:
            text, final = lm.engine.submit(gen).result()
        finally:
            lease.release()

        message: dict[str, Any] = {"role": "assistant", "content": text}
        finish = final.finish_reason
        if tools:
            calls = parse_function_calls(text, lm.cfg)
            if calls:
                message = {"role": "assistant", "content": None, "tool_calls": calls}
                finish = "tool_calls"
        return Response(body={
            "id": rid, "object": "chat.completion", "created": created,
            "model": model_name, "system_fingerprint": _fingerprint(),
            "choices": [{"index": 0, "message": message, "finish_reason": finish}],
            "usage": self._usage(final, extra_usage),
        })

    # ------------------------------------------------------------------ #
    # Completion / edit
    # ------------------------------------------------------------------ #

    def completion(self, req: Request) -> Response | SSEStream:
        body = req.body or {}
        prompts = body.get("prompt", "")
        if isinstance(prompts, str):
            prompts = [prompts]
        if not prompts or not all(isinstance(p, str) for p in prompts):
            raise ApiError(400, "prompt must be a string or array of strings")
        lm, lease = self._resolve(req, Usecase.COMPLETION)
        rid = f"cmpl-{uuid.uuid4().hex[:28]}"
        created = _now()
        extra_usage = "extra-usage" in req.headers
        try:
            return self._completion_inner(lm, lease, body, prompts, rid, created, extra_usage)
        except BaseException:
            lease.release()
            raise

    def _completion_inner(self, lm, lease, body, prompts, rid, created, extra_usage) -> Response | SSEStream:
        if body.get("stream"):
            if len(prompts) != 1:
                raise ApiError(400, "streaming supports a single prompt")
            templated = lm.evaluator.template_completion(prompts[0])
            ids = lm.engine.tokenizer.encode(templated, add_bos=True)
            handle = lm.engine.submit(self._gen_request(lm, body, ids))

            def events() -> Iterator[dict]:
                base = {"id": rid, "object": "text_completion", "created": created,
                        "model": lm.cfg.name}
                try:
                    final = None
                    for ev in handle:
                        if ev.kind == "token":
                            yield {**base, "choices": [{"index": 0, "text": ev.text, "finish_reason": None}]}
                        elif ev.kind == "error":
                            yield {"error": {"message": ev.error, "type": "server_error"}}
                            return
                        else:
                            final = ev
                    yield {**base,
                           "choices": [{"index": 0, "text": "", "finish_reason": final.finish_reason}],
                           "usage": self._usage(final, extra_usage)}
                finally:
                    lease.release()

            return SSEStream(events(), on_disconnect=handle.cancel)

        try:
            choices = []
            pt = ct = 0
            tpp = ttg = 0.0
            for i, p in enumerate(prompts):
                templated = lm.evaluator.template_completion(p)
                ids = lm.engine.tokenizer.encode(templated, add_bos=True)
                text, final = lm.engine.submit(self._gen_request(lm, body, ids)).result()
                if body.get("echo"):
                    text = p + text
                choices.append({"index": i, "text": text, "finish_reason": final.finish_reason})
                pt += final.prompt_tokens
                ct += final.completion_tokens
                tpp += final.timing_prompt_processing
                ttg += final.timing_token_generation
        finally:
            lease.release()

        usage = {"prompt_tokens": pt, "completion_tokens": ct, "total_tokens": pt + ct}
        if extra_usage:
            usage["timing_prompt_processing"] = tpp
            usage["timing_token_generation"] = ttg
        return Response(body={
            "id": rid, "object": "text_completion", "created": created,
            "model": lm.cfg.name, "choices": choices, "usage": usage,
        })

    def edit(self, req: Request) -> Response:
        body = req.body or {}
        instruction = body.get("instruction", "")
        if not instruction:
            raise ApiError(400, "instruction is required")
        lm, lease = self._resolve(req, Usecase.EDIT)
        try:
            prompt = lm.evaluator.template_edit(instruction, body.get("input", ""))
            ids = lm.engine.tokenizer.encode(prompt, add_bos=True)
            text, final = lm.engine.submit(self._gen_request(lm, body, ids)).result()
        finally:
            lease.release()
        return Response(body={
            "object": "edit", "created": _now(),
            "choices": [{"index": 0, "text": text}],
            "usage": self._usage(final, "extra-usage" in req.headers),
        })

    # ------------------------------------------------------------------ #
    # Embeddings / tokenize
    # ------------------------------------------------------------------ #

    def embeddings(self, req: Request) -> Response:
        body = req.body or {}
        inputs = body.get("input", "")
        if isinstance(inputs, str):
            inputs = [inputs]
        if not isinstance(inputs, list) or not inputs:
            raise ApiError(400, "input must be a non-empty string or array")
        lm, lease = self._resolve(req, Usecase.EMBEDDINGS)
        try:
            tok = lm.engine.tokenizer
            ids_batch: list[list[int]] = []
            for item in inputs:
                if isinstance(item, str):
                    ids_batch.append(tok.encode(item) or [0])
                elif isinstance(item, list):  # pre-tokenized input
                    ids_batch.append([int(t) for t in item] or [0])
                else:
                    raise ApiError(400, "input items must be strings or token arrays")
            vecs = lm.engine.embed(ids_batch)
        finally:
            lease.release()
        n_tokens = sum(len(x) for x in ids_batch)
        return Response(body={
            "object": "list", "model": lm.cfg.name,
            "data": [
                {"object": "embedding", "index": i, "embedding": [float(x) for x in vec]}
                for i, vec in enumerate(vecs)
            ],
            "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
        })

    def tokenize(self, req: Request) -> Response:
        body = req.body or {}
        content = body.get("content", "")
        lm, lease = self._resolve(req, Usecase.TOKENIZE)
        try:
            ids = lm.engine.tokenizer.encode(content)
        finally:
            lease.release()
        return Response(body={"tokens": ids})

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #

    def list_models(self, req: Request) -> Response:
        data = [
            {"id": cfg.name, "object": "model", "created": _now(), "owned_by": "localai-tpu"}
            for cfg in self.manager.list_configs()
        ]
        return Response(body={"object": "list", "data": data})

    def get_model(self, req: Request) -> Response:
        name = req.params["name"]
        if self.manager.configs.get(name) is None:
            raise ApiError(404, f"model {name!r} not found")
        return Response(body={"id": name, "object": "model", "created": _now(), "owned_by": "localai-tpu"})

    def health(self, req: Request) -> Response:
        return Response(body={"status": "ok"})

    def version(self, req: Request) -> Response:
        return Response(body={"version": __version__})

    def system(self, req: Request) -> Response:
        import jax

        loaded = self.manager.loaded_names()
        backends = {}
        for n in loaded:
            lm = self.manager.peek(n)  # never trigger a load from a monitoring poll
            if lm is not None:
                backends[n] = lm.engine.metrics()
        return Response(body={
            "backends": backends,
            "loaded_models": loaded,
            "configured_models": self.manager.configs.names(),
            "devices": [str(d) for d in jax.devices()],
            "uptime_s": time.time() - self.started_at,
            "version": __version__,
        })

    def backend_monitor(self, req: Request) -> Response:
        body = req.body or {}
        name = body.get("model") or (req.query.get("model") or [None])[0]
        if not name:
            raise ApiError(400, "model is required")
        lm = self.manager.peek(name)
        if lm is None:
            raise ApiError(404, f"model {name!r} is not loaded")
        return Response(body={
            "model": name,
            "metrics": lm.engine.metrics(),
            "loaded_for_s": time.monotonic() - lm.loaded_at,
            "in_flight": lm.in_flight,
        })

    def backend_shutdown(self, req: Request) -> Response:
        body = req.body or {}
        name = body.get("model")
        if not name:
            raise ApiError(400, "model is required")
        if not self.manager.unload(name):
            raise ApiError(404, f"model {name!r} is not loaded")
        return Response(body={"status": "ok"})
