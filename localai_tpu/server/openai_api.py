"""OpenAI-compatible endpoints + LocalAI native endpoints.

Reference: core/http/endpoints/openai/*.go (chat.go:27 SSE+tools,
completion.go, edit.go, embeddings.go, list.go) and endpoints/localai
(tokenize.go, system.go, backend.go monitor/shutdown). Handlers translate
HTTP requests into engine GenRequests; the streaming path iterates the
engine's per-request event queue directly into SSE frames.
"""

from __future__ import annotations

import queue
import threading
import time
import uuid
from typing import Any, Callable, Iterator, Optional

from localai_tpu import __version__
from localai_tpu.config import LoraConfigError, Usecase
from localai_tpu.engine import AdapterError, GenRequest, QueueFullError
from localai_tpu.server.app import (
    ApiError,
    RawStream,
    Request,
    Response,
    Router,
    SSEStream,
)
from localai_tpu.server.manager import (
    LoadedModel,
    ModelManager,
    ModelQuarantinedError,
)


def _now() -> int:
    return int(time.time())


def _extract_images(messages: list) -> list:
    """Decode image_url content parts (data: URIs) → uint8 arrays
    (reference: message.go content-part parsing feeding multimodal
    backends)."""
    import base64
    import io

    out = []
    for m in messages:
        content = m.get("content")
        if not isinstance(content, list):
            continue
        for part in content:
            if not isinstance(part, dict) or part.get("type") != "image_url":
                continue
            url = (part.get("image_url") or {}).get("url", "")
            if not url.startswith("data:"):
                continue  # zero-egress: only inline data URIs
            try:
                raw = base64.b64decode(url.split(",", 1)[-1])
                from PIL import Image
                import numpy as np

                out.append(np.asarray(Image.open(io.BytesIO(raw)).convert("RGB")))
            except Exception:  # noqa: BLE001 — bad image part is skipped
                continue
    return out


def _fingerprint() -> str:
    return f"localai-tpu-{__version__}"


class OpenAIApi:
    def __init__(self, manager: ModelManager):
        self.manager = manager
        self.started_at = time.time()
        # Set by register(): the router carries the Metrics registry
        # (create_server attaches it) that the per-model lifecycle
        # histograms (ttft/inter_token/queue_wait/admit, ISSUE 11) feed.
        self.router: Optional[Router] = None

    # ------------------------------------------------------------------ #
    # Routing
    # ------------------------------------------------------------------ #

    def register(self, r: Router) -> None:
        self.router = r
        for prefix in ("/v1", ""):
            r.add("POST", f"{prefix}/chat/completions", self.chat)
            r.add("POST", f"{prefix}/completions", self.completion)
            r.add("POST", f"{prefix}/edits", self.edit)
            r.add("POST", f"{prefix}/embeddings", self.embeddings)
            r.add("GET", f"{prefix}/models", self.list_models)
        r.add("GET", "/v1/models/:name", self.get_model)
        r.add("POST", "/v1/tokenize", self.tokenize)
        r.add("POST", "/tokenize", self.tokenize)
        r.add("GET", "/healthz", self.health)
        r.add("GET", "/readyz", self.health)
        r.add("GET", "/version", self.version)
        r.add("GET", "/system", self.system)
        r.add("GET", "/backend/monitor", self.backend_monitor)
        r.add("POST", "/backend/monitor", self.backend_monitor)
        r.add("POST", "/backend/shutdown", self.backend_shutdown)
        # Cluster control plane (ISSUE 6, docs/CLUSTER.md): role/status
        # introspection plus the KV-span transfer seam — a prefill-role
        # worker answers /cluster/span/export with a versioned binary frame
        # and a decode-role worker lands it via /cluster/span/import, which
        # is all a network-hop disaggregation deployment needs.
        r.add("GET", "/cluster/status", self.cluster_status)
        r.add("POST", "/cluster/span/export", self.cluster_span_export)
        r.add("POST", "/cluster/span/import", self.cluster_span_import)
        # Elastic membership (ISSUE 19, docs/CLUSTER.md "Membership
        # lifecycle"): join a remote worker at runtime, drain a member
        # (in-flight streams finish, no new picks), leave gracefully
        # (drain, then removal once in-flight hits zero).
        for prefix in ("/v1", ""):
            r.add("POST", f"{prefix}/cluster/join", self.cluster_join)
            r.add("POST", f"{prefix}/cluster/drain", self.cluster_drain)
            r.add("POST", f"{prefix}/cluster/leave", self.cluster_leave)
        # Request-lifecycle observability (ISSUE 11, docs/OBSERVABILITY.md):
        # per-request span trees (W3C traceparent propagated), the engine
        # journal as Perfetto-loadable Chrome trace JSON, and an opt-in
        # jax.profiler capture window (LOCALAI_PROFILE gates it).
        r.add("GET", "/debug/trace/:request_id", self.debug_trace)
        r.add("GET", "/debug/timeline", self.debug_timeline)
        r.add("POST", "/debug/profile", self.debug_profile)
        # Engine gauges (kv pages free/total, queue depth, preemptions,
        # swap bytes, prefix host tier, ...) ride the Prometheus scrape as
        # localai_engine_*{model=...} — create_server polls this at every
        # /metrics render (previously reachable only via the JSON
        # backend-monitor endpoint).
        r.gauge_source = self.engine_gauges

    # ------------------------------------------------------------------ #
    # Helpers
    # ------------------------------------------------------------------ #

    def _resolve_name(self, req: Request, usecase: Usecase) -> str:
        """Model resolution tiers mirroring the reference extractor
        (middleware/request.go:47-92): body → route param → query param →
        bearer token naming a configured model → first config serving the
        usecase."""
        body = req.body or {}
        name = body.get("model") or (req.params or {}).get("name")
        if not name:
            name = (req.query.get("model") or [None])[0]
        if not name:
            auth = req.headers.get("authorization", "")
            token = auth[7:] if auth.startswith("Bearer ") else ""
            if token and self.manager.configs.get(token) is not None:
                name = token
        if not name:
            cfg = self.manager.configs.first_with(usecase)
            if cfg is None:
                raise ApiError(404, f"no model configured for {usecase}")
            name = cfg.name
        cfg = self.manager.configs.get(name)
        if cfg is None:
            raise ApiError(404, f"model {name!r} not found")
        if not cfg.has_usecase(usecase):
            raise ApiError(400, f"model {name!r} does not support {usecase}")
        return name

    def _resolve(self, req: Request, usecase: Usecase):
        """Loaded model + idempotent lease, taken atomically w.r.t. eviction."""
        name = self._resolve_name(req, usecase)
        try:
            return self.manager.lease(name)
        except KeyError:
            raise ApiError(404, f"model {name!r} not found") from None
        except LoraConfigError as e:
            # Contradictory virtual-model / merge-at-load setup (ISSUE 10):
            # a clean 400 for this one model, serving stays up.
            raise ApiError(400, str(e)) from None
        except ModelQuarantinedError as e:
            # Crash-only supervision tripped its restart budget (ISSUE 4):
            # a clean 503 with the remaining quarantine window, not a
            # respawn loop.
            raise ApiError(
                503, str(e), "server_error", retry_after=e.retry_after_s
            ) from None

    @staticmethod
    def _submit_all(lm: LoadedModel, gens: list, group: int = 1) -> list:
        """Submit every GenRequest, mapping engine backpressure to HTTP:
        a full queue (QueueFullError) becomes 429 + Retry-After derived
        from the engine's observed admission latency, and any handles
        already submitted are cancelled so a partially-admitted multi-
        choice request never leaks slots.

        `group` > 1 routes each run of `group` consecutive same-prompt
        requests (one n>1 / best_of choice group) through ONE fork
        admission (ISSUE 18, docs/TREE_SAMPLING.md): the group pays a
        single prefill and the engine forks the slot CoW per branch.
        Engines without the fork surface (remote proxies, cluster
        facades) and conditions the engine can't fork (dense cache,
        draft model, fork_sampling off) fall back to independent clone
        submits with identical outputs."""
        handles = []
        try:
            if group > 1 and hasattr(lm.engine, "submit_fork"):
                for k in range(0, len(gens), group):
                    handles.extend(lm.engine.submit_fork(gens[k:k + group]))
            else:
                for g in gens:
                    handles.append(lm.engine.submit(g))
        except QueueFullError as e:
            for h in handles:
                h.cancel()
            raise ApiError(
                429, str(e), "rate_limit_exceeded",
                retry_after=e.retry_after_s,
            ) from None
        except AdapterError as e:
            # Tenant-identity failure (ISSUE 10): the adapter vanished
            # between resolution and submit, or the base cannot serve it.
            for h in handles:
                h.cancel()
            raise ApiError(400, str(e)) from None
        return handles

    def _proxy_remote(self, req: Request, lm: LoadedModel, lease) -> Response | SSEStream:
        """Relay a request to an out-of-process backend (backend: remote or
        subprocess — the L7 seam; reference: every backend is a separate
        gRPC process, initializers.go:50-154)."""
        import urllib.error

        eng = lm.engine
        stream = bool((req.body or {}).get("stream"))
        try:
            # Per-call deadline (ISSUE 19): the request's own remaining
            # budget bounds the proxy socket instead of a flat 600 s —
            # body deadline_s, else the model's configured deadline.
            deadline = float((req.body or {}).get("deadline_s")
                             or getattr(lm.cfg, "deadline_s", 0.0) or 0.0)
        except (TypeError, ValueError):
            deadline = 0.0
        try:
            resp = eng.request(req.path, req.body, method=req.method,
                               deadline_s=deadline)
        except urllib.error.HTTPError as e:
            body = e.read()
            lease.release()
            return Response(
                status=e.code, body=body,
                content_type=e.headers.get("Content-Type", "application/json"),
            )
        except Exception as e:  # noqa: BLE001
            lease.release()
            raise ApiError(502, f"remote backend failed: {e}", "server_error") from None
        if stream and "event-stream" in (resp.headers.get("Content-Type") or ""):
            def events():
                try:
                    for raw in resp:
                        line = raw.decode("utf-8", "replace").strip()
                        if line.startswith("data: "):
                            payload = line[6:]
                            if payload != "[DONE]":  # our writer adds its own
                                yield payload
                finally:
                    resp.close()
                    lease.release()

            return SSEStream(events())
        try:
            data = resp.read()
        finally:
            resp.close()
            lease.release()
        return Response(
            body=data, content_type=resp.headers.get("Content-Type", "application/json")
        )

    def _gen_request(self, lm: LoadedModel, body: dict[str, Any], prompt_ids: list[int],
                     extra_stop: Optional[list[str]] = None) -> GenRequest:
        cfg = lm.cfg
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        stop = list(stop) + [s for s in (extra_stop or []) if s not in stop]
        max_tokens = body.get("max_completion_tokens") or body.get("max_tokens") or cfg.max_tokens

        def pick(key: str, default):
            v = body.get(key)
            return default if v is None else v

        logit_bias = {}
        for k, v in (body.get("logit_bias") or {}).items():
            try:
                logit_bias[int(k)] = float(v)
            except (TypeError, ValueError):
                raise ApiError(400, f"invalid logit_bias entry {k!r}") from None

        return GenRequest(
            prompt_ids=prompt_ids,
            max_new_tokens=int(max_tokens),
            temperature=float(pick("temperature", cfg.temperature)),
            top_k=int(pick("top_k", cfg.top_k)),
            top_p=float(pick("top_p", cfg.top_p)),
            min_p=float(pick("min_p", cfg.min_p)),
            repeat_penalty=float(pick("repeat_penalty", cfg.repeat_penalty)),
            presence_penalty=float(pick("presence_penalty", cfg.presence_penalty)),
            frequency_penalty=float(pick("frequency_penalty", cfg.frequency_penalty)),
            stop=stop,
            seed=body.get("seed", cfg.seed),
            logit_bias=logit_bias,
            # vLLM-style extension: benchmarking/testing wants fixed-length
            # generations regardless of what the model samples.
            ignore_eos=bool(body.get("ignore_eos", False)),
            # End-to-end deadline (ISSUE 4): body overrides the model
            # YAML's default; past it, pending requests shed and active
            # ones cancel (docs/ROBUSTNESS.md).
            deadline_s=float(pick("deadline_s", cfg.deadline_s)),
            # Multi-tenant LoRA (ISSUE 10): a virtual model resolves to
            # the base's shared engine + this tenant's adapter name.
            adapter=getattr(lm, "adapter", None),
        )

    @staticmethod
    def _n_choices(body: dict[str, Any]) -> int:
        n = body.get("n") or 1
        try:
            n = int(n)
        except (TypeError, ValueError):
            raise ApiError(400, "n must be an integer") from None
        if n < 1 or n > 64:
            raise ApiError(400, "n must be between 1 and 64")
        return n

    @staticmethod
    def _best_of(body: dict[str, Any], n: int) -> int:
        """Validated `best_of` branch count (docs/TREE_SAMPLING.md):
        generate best_of branches off one shared prefill, rank by
        cumulative logprob, return the top n. Defaults to n (no
        over-generation); streaming cannot rank after the fact, so
        best_of > n on a stream is a client error (OpenAI semantics)."""
        bo = body.get("best_of")
        if bo is None:
            return n
        try:
            bo = int(bo)
        except (TypeError, ValueError):
            raise ApiError(400, "best_of must be an integer") from None
        if bo < n:
            raise ApiError(400, "best_of must be >= n")
        if bo > 64:
            raise ApiError(400, "best_of must be between n and 64")
        if bo > n and body.get("stream"):
            raise ApiError(400, "best_of > n cannot be used with streaming")
        return bo

    @staticmethod
    def _select_best(results: list, n: int) -> list:
        """best_of ranking for one choice group: highest cumulative token
        logprob first (ties keep submission order), top n re-indexed in
        rank order."""
        def score(r) -> float:
            return sum(ev.logprob for ev in r[1] if ev.logprob is not None)

        order = sorted(range(len(results)),
                       key=lambda i: (-score(results[i]), i))
        return [results[i] for i in order[:n]]

    @staticmethod
    def _merge_streams(handles: list) -> Iterator[tuple[int, Any]]:
        """Interleave events from several engine handles as (index, event).

        Each handle is drained by its own reader thread into one queue, so
        slow consumers of one choice never stall the engine-side queues of
        the others (multi-slot fan-out for n>1 — reference: the proto's
        one-stream-per-call model never needed this; slots make it natural).
        """
        if len(handles) == 1:
            for ev in handles[0]:
                yield 0, ev
            return
        q: "queue.Queue[tuple[int, Any]]" = queue.Queue()

        def reader(idx: int, h) -> None:
            for ev in h:
                q.put((idx, ev))

        for idx, h in enumerate(handles):
            threading.Thread(target=reader, args=(idx, h), daemon=True,
                             name=f"stream-reader-{idx}").start()
        done = 0
        while done < len(handles):
            idx, ev = q.get()
            if ev.kind in ("done", "error"):
                done += 1
            yield idx, ev

    @staticmethod
    def _collect(handle) -> tuple[str, list, Any]:
        """Drain one handle → (text, token events, final event)."""
        parts: list[str] = []
        toks: list = []
        final = None
        for ev in handle:
            if ev.kind == "token":
                parts.append(ev.text)
                toks.append(ev)
            elif ev.kind == "error":
                raise ApiError(500, ev.error)
            else:
                final = ev
        return "".join(parts), toks, final

    @staticmethod
    def _tag_requests(gens: list, rid: str, traceparent: str) -> None:
        """Stamp lifecycle-tracing identity onto each GenRequest (ISSUE 11):
        the response id keys /debug/trace/{id} (choice i > 0 gets `-i`),
        and a traceparent is minted when the client sent none so every
        leg — cluster replicas, disaggregated prefill/decode — shares one
        trace id."""
        from localai_tpu.observe.trace import new_traceparent, parse_traceparent

        if not (traceparent and parse_traceparent(traceparent)):
            traceparent = new_traceparent()
        for i, g in enumerate(gens):
            g.request_id = rid if i == 0 else f"{rid}-{i}"
            g.traceparent = traceparent

    def _note_request_metrics(self, model_name: str, finals: list) -> None:
        """Feed the per-model lifecycle histograms (ISSUE 11) from the
        terminal events' timing fields. No-op when the router has no
        Metrics yet (unit tests that call handlers directly)."""
        m = getattr(self.router, "metrics", None) if self.router else None
        if m is None:
            return
        labels = {"model": model_name}
        for f in finals:
            if f is None or getattr(f, "kind", "done") != "done":
                continue
            m.observe("queue_wait", f.timing_queue_wait, labels)
            m.observe("admit", f.timing_prompt_processing, labels)
            m.observe(
                "ttft", f.timing_queue_wait + f.timing_prompt_processing,
                labels,
            )
            if f.completion_tokens > 1 and f.timing_token_generation > 0:
                m.observe(
                    "inter_token",
                    f.timing_token_generation / (f.completion_tokens - 1),
                    labels,
                )

    @staticmethod
    def _sum_usage(finals: list, extra: bool) -> dict[str, Any]:
        pt = sum(f.prompt_tokens for f in finals)
        ct = sum(f.completion_tokens for f in finals)
        u = {"prompt_tokens": pt, "completion_tokens": ct, "total_tokens": pt + ct}
        if extra:
            u["timing_prompt_processing"] = sum(f.timing_prompt_processing for f in finals)
            u["timing_token_generation"] = sum(f.timing_token_generation for f in finals)
        return u

    def _chat_logprobs(self, body: dict[str, Any]) -> int:
        """Parsed chat logprobs request: 0 = off, else top-N to return."""
        if not body.get("logprobs"):
            return 0
        top = body.get("top_logprobs")
        top = 1 if top is None else int(top)
        if top < 0 or top > 20:
            raise ApiError(400, "top_logprobs must be between 0 and 20")
        return max(top, 1)

    @staticmethod
    def _lp_entry(lm, ev) -> dict[str, Any]:
        """One OpenAI chat logprobs content entry from a token event."""
        s = lm.engine.token_text(ev.token_id)
        return {
            "token": s,
            "logprob": ev.logprob,
            "bytes": list(s.encode("utf-8")),
            "top_logprobs": [
                {
                    "token": lm.engine.token_text(i),
                    "logprob": v,
                    "bytes": list(lm.engine.token_text(i).encode("utf-8")),
                }
                for i, v in (ev.top_logprobs or [])
            ],
        }

    def _chat_lp_content(self, lm, tok_events: list) -> dict[str, Any]:
        return {
            "content": [
                self._lp_entry(lm, ev) for ev in tok_events if ev.logprob is not None
            ]
        }

    @staticmethod
    def _usage(final, extra: bool) -> dict[str, Any]:
        u = {
            "prompt_tokens": final.prompt_tokens,
            "completion_tokens": final.completion_tokens,
            "total_tokens": final.prompt_tokens + final.completion_tokens,
        }
        if extra:
            # reference: Extra-Usage header surfaces backend timings
            # (chat.go:47-50; proto Reply timing fields).
            u["timing_prompt_processing"] = final.timing_prompt_processing
            u["timing_token_generation"] = final.timing_token_generation
        return u

    # ------------------------------------------------------------------ #
    # Chat
    # ------------------------------------------------------------------ #

    def chat(self, req: Request) -> Response | SSEStream:
        body = req.body or {}
        messages = body.get("messages")
        if not messages or not isinstance(messages, list):
            raise ApiError(400, "messages is required and must be a non-empty array")
        lm, lease = self._resolve(req, Usecase.CHAT)
        from localai_tpu.engine.remote import RemoteEngine

        if isinstance(lm.engine, RemoteEngine):
            return self._proxy_remote(req, lm, lease)
        try:
            return self._chat_inner(req, lm, lease, body)
        except BaseException:
            lease.release()  # idempotent — safe even if the inner path released
            raise

    @staticmethod
    def _gbnf_factory(body: dict[str, Any]) -> Optional[Callable[[], Any]]:
        """Factory for a raw `grammar` (GBNF) body field, or None. Malformed
        grammars — including pathological depth — are a 400, not a 500."""
        gbnf_text = body.get("grammar")
        if not (isinstance(gbnf_text, str) and gbnf_text.strip()):
            return None
        from localai_tpu.functions.gbnf import (
            CompiledGrammar,
            GbnfConstraint,
            GbnfParseError,
        )

        try:
            compiled = CompiledGrammar(gbnf_text)
        except (GbnfParseError, RecursionError, MemoryError) as e:
            raise ApiError(400, f"invalid grammar: {e}") from None
        return lambda: GbnfConstraint(compiled)

    def _chat_inner(self, req: Request, lm: LoadedModel, lease, body: dict[str, Any]) -> Response | SSEStream:
        from localai_tpu.functions import tools_prompt_for, parse_function_calls
        from localai_tpu.functions.jsonschema import GrammarConstraint, tool_call_schema

        tools = body.get("tools") or []
        if body.get("functions"):  # legacy field
            tools = [{"type": "function", "function": f} for f in body["functions"]]
        tool_choice = body.get("tool_choice")
        if tool_choice == "none":
            tools = []
        tprompt = tools_prompt_for(tools) if tools else ""

        # Constrained decoding (reference: chat.go:224-253 grammar generation
        # for tools / response_format; here a token-mask grammar). A factory,
        # not an instance: the pushdown machine is mutable per-request state,
        # and n>1 needs one machine per choice.
        make_grammar: Optional[Callable[[], Any]] = None
        rf = body.get("response_format") or {}
        if rf.get("type") == "json_object":
            make_grammar = lambda: GrammarConstraint({"type": "object"})
        elif rf.get("type") == "json_schema":
            schema = (rf.get("json_schema") or {}).get("schema") or {}
            make_grammar = lambda: GrammarConstraint(schema)
        if tools and (tool_choice == "required" or isinstance(tool_choice, dict)):
            selected = tools
            if isinstance(tool_choice, dict):
                fname = (tool_choice.get("function") or {}).get("name")
                named = [t for t in tools if (t.get("function") or {}).get("name") == fname]
                if not named:
                    raise ApiError(400, f"tool_choice names unknown function {fname!r}")
                selected = named
            make_grammar = lambda: GrammarConstraint(tool_call_schema(selected))
        # Raw GBNF grammar (reference: backend.proto:139 `Grammar` forwarded
        # verbatim to llama.cpp). Checked LAST: an explicit grammar takes
        # precedence over response_format AND tool_choice, like the
        # reference passes an explicit grammar through untouched.
        make_grammar = self._gbnf_factory(body) or make_grammar

        prompt = lm.evaluator.template_messages(body["messages"], tools_prompt=tprompt)
        add_bos = not lm.cfg.template.use_tokenizer_template
        ids = lm.engine.tokenizer.encode(prompt, add_bos=add_bos)
        n = self._n_choices(body)
        lp_n = self._chat_logprobs(body)

        # Multimodal: project the first image and reserve a placeholder span
        # right after BOS (llava injection — models/vision.py). Qwen2-VL
        # encoders additionally yield the native-resolution grid, from which
        # the 3D m-rope position streams are derived
        # (models/qwen2_vl.mrope_positions_for_span).
        image_embeds = None
        image_offset = 0
        mrope_positions = None
        images = _extract_images(body["messages"])
        vision = getattr(lm, "vision", None)
        if images and vision is not None:
            image_offset = 1 if (add_bos and ids) else 0
            grid = None
            if getattr(vision, "kind", "") == "qwen2_vl":
                image_embeds, grid = vision.encode_with_grid(images[0])
            else:
                image_embeds = vision.encode(images[0])
            filler = [0] * image_embeds.shape[0]
            ids = ids[:image_offset] + filler + ids[image_offset:]
            if grid is not None:
                from localai_tpu.models.qwen2_vl import mrope_positions_for_span

                mrope_positions, _delta = mrope_positions_for_span(
                    len(ids), image_offset, grid, merge=vision.merge
                )

        # Independent GenRequest per branch: fresh grammar machine (the
        # pushdown state is mutable), decorrelated seeds when one was
        # given. best_of > n over-generates and ranks by cumulative
        # logprob, so ranking forces per-token logprobs internally (the
        # response strips them unless the client asked).
        bo = self._best_of(body, n)
        gens = []
        for i in range(bo):
            g = self._gen_request(lm, body, ids, extra_stop=lm.evaluator.stop_sequences())
            g.grammar = make_grammar() if make_grammar else None
            g.logprobs = lp_n if bo == n else max(lp_n, 1)
            g.image_embeds = image_embeds
            g.image_offset = image_offset
            g.mrope_positions = mrope_positions
            if g.seed is not None and bo > 1:
                g.seed = int(g.seed) + i
            gens.append(g)

        rid = f"chatcmpl-{uuid.uuid4().hex[:28]}"
        self._tag_requests(gens, rid, req.headers.get("traceparent", ""))
        created = _now()
        model_name = lm.cfg.name
        extra_usage = "extra-usage" in req.headers

        if body.get("stream"):
            handles = self._submit_all(lm, gens, group=len(gens))

            def cancel_all() -> None:
                for h in handles:
                    h.cancel()

            def events() -> Iterator[dict]:
                try:
                    base = {
                        "id": rid, "object": "chat.completion.chunk",
                        "created": created, "model": model_name,
                        "system_fingerprint": _fingerprint(),
                    }

                    def chunk(idx: int, delta: dict, finish=None, ev=None) -> dict:
                        c: dict[str, Any] = {"index": idx, "delta": delta, "finish_reason": finish}
                        if lp_n and ev is not None and ev.logprob is not None:
                            c["logprobs"] = {"content": [self._lp_entry(lm, ev)]}
                        return {**base, "choices": [c]}

                    for idx in range(n):
                        yield chunk(idx, {"role": "assistant", "content": ""})
                    finals: list[Any] = [None] * n
                    # Per-choice buffering state for tool-call detection:
                    # JSON/`<function=` heads buffer for parsing, anything
                    # else streams live (reference: chat.go streams function-
                    # call deltas, not raw JSON content).
                    st = [
                        {"parts": [], "events": [], "emitted": 0, "buffering": None}
                        for _ in range(n)
                    ]
                    for idx, ev in self._merge_streams(handles):
                        s = st[idx]
                        if ev.kind == "token":
                            s["parts"].append(ev.text)
                            s["events"].append(ev)
                            if not tools:
                                yield chunk(idx, {"content": ev.text}, ev=ev)
                                continue
                            if s["buffering"] is None:
                                head = "".join(s["parts"]).lstrip()
                                if head:
                                    s["buffering"] = head[0] in "{[<"
                            if s["buffering"] is False:
                                text = "".join(s["parts"][s["emitted"]:])
                                s["emitted"] = len(s["parts"])
                                yield chunk(idx, {"content": text}, ev=ev)
                        elif ev.kind == "error":
                            # A failed choice abandons the whole stream:
                            # cancel the siblings so their slots stop
                            # decoding into it (ISSUE 18 satellite).
                            cancel_all()
                            yield {"error": {"message": ev.error, "type": "server_error"}}
                            return
                        else:
                            finals[idx] = ev
                    done_finals = [f for f in finals if f is not None]
                    self._note_request_metrics(model_name, done_finals)
                    for idx in range(n):
                        s, final = st[idx], finals[idx]
                        if final is None:
                            continue
                        finish = final.finish_reason
                        if tools:
                            text = "".join(s["parts"])
                            if s["buffering"]:
                                calls = parse_function_calls(text, lm.cfg)
                                if calls:
                                    deltas = [{**c, "index": i} for i, c in enumerate(calls)]
                                    yield chunk(idx, {"tool_calls": deltas})
                                    finish = "tool_calls"
                                elif text:
                                    yield chunk(idx, {"content": text})
                            else:
                                tail = "".join(s["parts"][s["emitted"]:])
                                if tail:  # e.g. whitespace-only generation
                                    yield chunk(idx, {"content": tail})
                        out = chunk(idx, {}, finish=finish)
                        if idx == n - 1:
                            out["usage"] = self._sum_usage(done_finals, extra_usage)
                        yield out
                finally:
                    lease.release()

            return SSEStream(events(), on_disconnect=cancel_all)

        try:
            handles = self._submit_all(lm, gens, group=len(gens))
            try:
                results = [self._collect(h) for h in handles]
            except BaseException:
                for h in handles:
                    h.cancel()
                raise
        finally:
            lease.release()

        from localai_tpu.utils.finetune import finetune, needs_finetune

        # Usage/metrics count every generated branch (the client paid for
        # best_of completions); choices carry only the ranked top n.
        self._note_request_metrics(model_name, [r[2] for r in results])
        all_finals = [r[2] for r in results]
        if bo > n:
            results = self._select_best(results, n)
        choices = []
        for idx, (text, toks, final) in enumerate(results):
            if needs_finetune(lm.cfg):
                # Reference: Finetune post-processing on every prediction
                # (llm.go:217-265); the non-stream path only — streams are raw.
                text = finetune(lm.cfg, prompt, text)
            message: dict[str, Any] = {"role": "assistant", "content": text}
            finish = final.finish_reason
            if tools:
                calls = parse_function_calls(text, lm.cfg)
                if calls:
                    message = {"role": "assistant", "content": None, "tool_calls": calls}
                    finish = "tool_calls"
            choice: dict[str, Any] = {"index": idx, "message": message, "finish_reason": finish}
            if lp_n:
                choice["logprobs"] = self._chat_lp_content(lm, toks)
            choices.append(choice)
        return Response(body={
            "id": rid, "object": "chat.completion", "created": created,
            "model": model_name, "system_fingerprint": _fingerprint(),
            "choices": choices,
            "usage": self._sum_usage(all_finals, extra_usage),
        })

    # ------------------------------------------------------------------ #
    # Completion / edit
    # ------------------------------------------------------------------ #

    def completion(self, req: Request) -> Response | SSEStream:
        body = req.body or {}
        prompts = body.get("prompt", "")
        if isinstance(prompts, str):
            prompts = [prompts]
        if not prompts or not all(isinstance(p, str) for p in prompts):
            raise ApiError(400, "prompt must be a string or array of strings")
        lm, lease = self._resolve(req, Usecase.COMPLETION)
        from localai_tpu.engine.remote import RemoteEngine

        if isinstance(lm.engine, RemoteEngine):
            return self._proxy_remote(req, lm, lease)
        rid = f"cmpl-{uuid.uuid4().hex[:28]}"
        created = _now()
        extra_usage = "extra-usage" in req.headers
        try:
            return self._completion_inner(
                lm, lease, body, prompts, rid, created, extra_usage,
                traceparent=req.headers.get("traceparent", ""),
            )
        except BaseException:
            lease.release()
            raise

    def _completion_lp(self, body: dict[str, Any]) -> int:
        lp = body.get("logprobs")
        if lp is None or lp is False:
            return 0
        lp = 1 if lp is True else int(lp)
        if lp < 0 or lp > 20:
            raise ApiError(400, "logprobs must be between 0 and 20")
        return lp

    def _completion_lp_block(self, lm, toks: list, offset0: int) -> dict[str, Any]:
        """Legacy completions logprobs block for one choice."""
        tokens, token_lps, tops, offsets = [], [], [], []
        off = offset0
        for ev in toks:
            if ev.logprob is None:
                continue
            s = lm.engine.token_text(ev.token_id)
            tokens.append(s)
            token_lps.append(ev.logprob)
            tops.append({lm.engine.token_text(i): v for i, v in (ev.top_logprobs or [])})
            offsets.append(off)
            off += len(s)
        return {
            "tokens": tokens, "token_logprobs": token_lps,
            "top_logprobs": tops, "text_offset": offsets,
        }

    def _completion_inner(self, lm, lease, body, prompts, rid, created,
                          extra_usage, traceparent="") -> Response | SSEStream:
        n = self._n_choices(body)
        bo = self._best_of(body, n)
        lp_n = self._completion_lp(body)

        # Raw GBNF grammar on completions too (the reference's Grammar field
        # rides PredictOptions for every text endpoint).
        make_grammar = self._gbnf_factory(body)

        # One GenRequest per (prompt, branch): all submitted up front so
        # free slots run them concurrently (multi-prompt requests
        # previously ran serially — VERDICT weak #7); each prompt's
        # branches form one fork group (shared prefill). best_of > n
        # forces internal logprobs for the ranking pass.
        gens = []
        templated_prompts = []
        for p in prompts:
            templated = lm.evaluator.template_completion(p)
            templated_prompts.append(templated)
            ids = lm.engine.tokenizer.encode(templated, add_bos=True)
            for j in range(bo):
                g = self._gen_request(lm, body, ids)
                g.grammar = make_grammar() if make_grammar else None
                g.logprobs = lp_n if bo == n else max(lp_n, 1)
                if g.seed is not None and bo > 1:
                    g.seed = int(g.seed) + j
                gens.append(g)
        self._tag_requests(gens, rid, traceparent)

        if body.get("stream"):
            handles = self._submit_all(lm, gens, group=bo)

            def cancel_all() -> None:
                for h in handles:
                    h.cancel()

            def events() -> Iterator[dict]:
                base = {"id": rid, "object": "text_completion", "created": created,
                        "model": lm.cfg.name}
                try:
                    finals = [None] * len(handles)
                    for idx, ev in self._merge_streams(handles):
                        if ev.kind == "token":
                            c: dict[str, Any] = {"index": idx, "text": ev.text, "finish_reason": None}
                            if lp_n and ev.logprob is not None:
                                c["logprobs"] = self._completion_lp_block(lm, [ev], 0)
                            yield {**base, "choices": [c]}
                        elif ev.kind == "error":
                            # A failed choice abandons the whole stream:
                            # cancel the siblings so their slots stop
                            # decoding into it (ISSUE 18 satellite).
                            cancel_all()
                            yield {"error": {"message": ev.error, "type": "server_error"}}
                            return
                        else:
                            finals[idx] = ev
                    done = [f for f in finals if f is not None]
                    self._note_request_metrics(lm.cfg.name, done)
                    for idx, final in enumerate(finals):
                        if final is None:
                            continue
                        out = {**base, "choices": [{"index": idx, "text": "", "finish_reason": final.finish_reason}]}
                        if idx == len(finals) - 1:
                            out["usage"] = self._sum_usage(done, extra_usage)
                        yield out
                finally:
                    lease.release()

            return SSEStream(events(), on_disconnect=cancel_all)

        try:
            handles = self._submit_all(lm, gens, group=bo)
            try:
                results = [self._collect(h) for h in handles]
            except BaseException:
                for h in handles:
                    h.cancel()
                raise
        finally:
            lease.release()

        from localai_tpu.utils.finetune import finetune, needs_finetune

        # Usage/metrics count every generated branch (the client paid for
        # best_of completions); choices carry only each prompt's top n.
        self._note_request_metrics(lm.cfg.name, [r[2] for r in results])
        all_finals = [r[2] for r in results]
        if bo > n:
            results = [r for k in range(0, len(results), bo)
                       for r in self._select_best(results[k:k + bo], n)]
        choices = []
        for idx, (text, toks, final) in enumerate(results):
            prompt = prompts[idx // n]
            if needs_finetune(lm.cfg):
                text = finetune(lm.cfg, templated_prompts[idx // n], text)
            offset0 = 0
            # body-level echo (raw prompt) unless config echo already did it
            if body.get("echo") and not lm.cfg.echo:
                text = prompt + text
                offset0 = len(prompt)
            choice: dict[str, Any] = {"index": idx, "text": text, "finish_reason": final.finish_reason}
            if lp_n:
                choice["logprobs"] = self._completion_lp_block(lm, toks, offset0)
            choices.append(choice)
        return Response(body={
            "id": rid, "object": "text_completion", "created": created,
            "model": lm.cfg.name, "choices": choices,
            "usage": self._sum_usage(all_finals, extra_usage),
        })

    def edit(self, req: Request) -> Response:
        from localai_tpu.utils.finetune import finetune, needs_finetune

        body = req.body or {}
        instruction = body.get("instruction", "")
        if not instruction:
            raise ApiError(400, "instruction is required")
        lm, lease = self._resolve(req, Usecase.EDIT)
        try:
            prompt = lm.evaluator.template_edit(instruction, body.get("input", ""))
            ids = lm.engine.tokenizer.encode(prompt, add_bos=True)
            g = self._gen_request(lm, body, ids)
            self._tag_requests(
                [g], f"edit-{uuid.uuid4().hex[:28]}",
                req.headers.get("traceparent", ""),
            )
            text, final = self._submit_all(lm, [g])[0].result()
        finally:
            lease.release()
        self._note_request_metrics(lm.cfg.name, [final])
        if needs_finetune(lm.cfg):
            text = finetune(lm.cfg, prompt, text)
        return Response(body={
            "object": "edit", "created": _now(),
            "choices": [{"index": 0, "text": text}],
            "usage": self._usage(final, "extra-usage" in req.headers),
        })

    # ------------------------------------------------------------------ #
    # Embeddings / tokenize
    # ------------------------------------------------------------------ #

    def embeddings(self, req: Request) -> Response:
        body = req.body or {}
        inputs = body.get("input", "")
        if isinstance(inputs, str):
            inputs = [inputs]
        if not isinstance(inputs, list) or not inputs:
            raise ApiError(400, "input must be a non-empty string or array")
        lm, lease = self._resolve(req, Usecase.EMBEDDINGS)
        from localai_tpu.engine.remote import RemoteEngine

        if isinstance(lm.engine, RemoteEngine):
            return self._proxy_remote(req, lm, lease)
        try:
            tok = lm.engine.tokenizer
            ids_batch: list[list[int]] = []
            for item in inputs:
                if isinstance(item, str):
                    ids_batch.append(tok.encode(item) or [0])
                elif isinstance(item, list):  # pre-tokenized input
                    ids_batch.append([int(t) for t in item] or [0])
                else:
                    raise ApiError(400, "input items must be strings or token arrays")
            vecs = lm.engine.embed(ids_batch)
        finally:
            lease.release()
        n_tokens = sum(len(x) for x in ids_batch)
        return Response(body={
            "object": "list", "model": lm.cfg.name,
            "data": [
                {"object": "embedding", "index": i, "embedding": [float(x) for x in vec]}
                for i, vec in enumerate(vecs)
            ],
            "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
        })

    def tokenize(self, req: Request) -> Response:
        body = req.body or {}
        content = body.get("content", "")
        lm, lease = self._resolve(req, Usecase.TOKENIZE)
        from localai_tpu.engine.remote import RemoteEngine

        if isinstance(lm.engine, RemoteEngine):
            return self._proxy_remote(req, lm, lease)
        try:
            ids = lm.engine.tokenizer.encode(content)
        finally:
            lease.release()
        return Response(body={"tokens": ids})

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #

    def list_models(self, req: Request) -> Response:
        data = [
            {"id": cfg.name, "object": "model", "created": _now(), "owned_by": "localai-tpu"}
            for cfg in self.manager.list_configs()
        ]
        return Response(body={"object": "list", "data": data})

    def get_model(self, req: Request) -> Response:
        name = req.params["name"]
        if self.manager.configs.get(name) is None:
            raise ApiError(404, f"model {name!r} not found")
        return Response(body={"id": name, "object": "model", "created": _now(), "owned_by": "localai-tpu"})

    def health(self, req: Request) -> Response:
        return Response(body={"status": "ok"})

    def version(self, req: Request) -> Response:
        return Response(body={"version": __version__})

    def system(self, req: Request) -> Response:
        import jax

        from localai_tpu.utils.sysinfo import device_info, recommend_mesh

        loaded = self.manager.loaded_names()
        backends = {}
        for n in loaded:
            lm = self.manager.peek(n)  # never trigger a load from a monitoring poll
            if lm is not None:
                backends[n] = lm.engine.metrics()
        return Response(body={
            "backends": backends,
            "loaded_models": loaded,
            "configured_models": self.manager.configs.names(),
            "devices": [str(d) for d in jax.devices()],
            "sysinfo": device_info(),
            "recommended_mesh": recommend_mesh(),
            "uptime_s": time.time() - self.started_at,
            "version": __version__,
        })

    def engine_gauges(self):
        """(name, labels, value) triples for every loaded model's engine —
        the Prometheus face of Engine.metrics(). peek() only: a monitoring
        scrape must never trigger a model load."""
        out = []
        for n in self.manager.loaded_names():
            lm = self.manager.peek(n)
            if lm is None:
                continue
            try:
                gauges = lm.engine.metrics()
            except Exception:  # noqa: BLE001 — scrape survives a dying engine
                continue
            for k, v in gauges.items():
                labels = {"model": n}
                if k == "loop_dead":
                    # Flight recorder (ISSUE 11): a dead loop's gauge
                    # carries the postmortem path so the on-call can jump
                    # from the alert straight to the dump.
                    pm = getattr(lm.engine, "postmortem_path", "")
                    if pm:
                        labels["postmortem"] = pm
                out.append((f"localai_engine_{k}", labels, v))
        # Supervision gauges (ISSUE 4): restart / quarantine counters live
        # on the manager, not the (replaceable) engines.
        out.extend(self.manager.health_gauges())
        return out

    def backend_monitor(self, req: Request) -> Response:
        body = req.body or {}
        name = body.get("model") or (req.query.get("model") or [None])[0]
        if not name:
            raise ApiError(400, "model is required")
        lm = self.manager.peek(name)
        if lm is None:
            raise ApiError(404, f"model {name!r} is not loaded")
        return Response(body={
            "model": name,
            "metrics": lm.engine.metrics(),
            "loaded_for_s": time.monotonic() - lm.loaded_at,
            "in_flight": lm.in_flight,
            "supervision": self.manager.restart_stats(name),
        })

    def backend_shutdown(self, req: Request) -> Response:
        body = req.body or {}
        name = body.get("model")
        if not name:
            raise ApiError(400, "model is required")
        if not self.manager.unload(name):
            raise ApiError(404, f"model {name!r} is not loaded")
        return Response(body={"status": "ok"})

    # ------------------------------------------------------------------ #
    # Cluster control plane (ISSUE 6, docs/CLUSTER.md)
    # ------------------------------------------------------------------ #

    def cluster_status(self, req: Request) -> Response:
        app_cfg = self.manager.app_cfg
        engines = {}
        for n in self.manager.loaded_names():
            lm = self.manager.peek(n)
            if lm is None:
                continue
            client = getattr(lm.engine, "client", None)
            if client is not None:  # ClusterEngine fan-out
                engines[n] = {
                    "replicas": client.scheduler.snapshot(),
                    "metrics": client.metrics(),
                    # Membership/breaker/failover event tail (ISSUE 19) —
                    # what the chaos driver asserts its invariants from.
                    "events": client.scheduler.journal_events(last=100),
                }
        return Response(body={
            "role": app_cfg.cluster_role,
            "cluster_replicas": app_cfg.cluster_replicas,
            "cluster_peers": list(app_cfg.cluster_peers),
            "affinity_spans": app_cfg.affinity_spans,
            "transfer_max_bytes": app_cfg.transfer_max_bytes,
            "transfer_chunk_bytes": app_cfg.transfer_chunk_bytes,
            "engines": engines,
        })

    def _cluster_client(self, name: Optional[str]):
        """The ClusterClient behind a loaded cluster-served model (never
        triggers a load — membership changes on an unloaded model are
        meaningless; its cluster doesn't exist yet)."""
        if not name:
            raise ApiError(400, "model is required")
        lm = self.manager.peek(name)
        if lm is None:
            raise ApiError(404, f"model {name!r} is not loaded")
        client = getattr(lm.engine, "client", None)
        if client is None:
            raise ApiError(
                400, f"model {name!r} is not served by a cluster engine "
                     "(cluster_replicas >= 2 or cluster_peers required)")
        return client

    def cluster_join(self, req: Request) -> Response:
        """Runtime membership join (ISSUE 19): register a remote worker as
        a replica while traffic flows. The member enters the lifecycle at
        `joining` and becomes routable on its first successful gauge
        scrape — a joiner that never comes up never attracts traffic."""
        body = req.body or {}
        client = self._cluster_client(body.get("model"))
        name = str(body.get("name") or "").strip()
        url = str(body.get("url") or "").strip()
        if not name or not url:
            raise ApiError(400, "replica name and url are required")
        from localai_tpu.cluster.replica import RemoteReplica
        from localai_tpu.cluster.scheduler import ROLES

        role = str(body.get("role") or "mixed")
        if role not in ROLES:
            raise ApiError(400, f"cluster role {role!r} not in {ROLES}")
        if any(r.name == name for r in client.replicas):
            # Fast refusal before the (probing) RemoteReplica construction.
            raise ApiError(409, f"replica {name!r} is already a member",
                           kind="conflict")
        rep = RemoteReplica(
            name, url, role=role,
            model=str(body.get("remote_model") or body.get("model") or ""))
        # Check-and-register atomically: two concurrent joins with the same
        # name must not both pass the duplicate check — the loser 409s.
        with client._lock:
            if any(r.name == name for r in client.replicas):
                raise ApiError(409, f"replica {name!r} is already a member",
                               kind="conflict")
            client.replicas.append(rep)
            client.scheduler.add_replica(
                rep.name, target=rep, role=rep.role, gauge_fn=rep.gauges,
                dispatchable=False)
        # One immediate probe round so a ready worker serves from this
        # response on, not from the next natural gauge tick.
        client.scheduler.refresh(force=True)
        return Response(body={
            "joined": name,
            "state": client.scheduler.state(name),
            "replicas": client.scheduler.snapshot(),
        })

    def cluster_drain(self, req: Request) -> Response:
        """Drain a member: no NEW requests route to it, in-flight streams
        finish, and its span affinity moves to a survivor."""
        body = req.body or {}
        client = self._cluster_client(body.get("model"))
        name = str(body.get("name") or "").strip()
        if not name:
            raise ApiError(400, "replica name is required")
        if not client.scheduler.begin_drain(name):
            raise ApiError(404, f"replica {name!r} is not a drainable "
                                "member (unknown, dead, or removed)")
        return Response(body={
            "draining": name,
            "state": client.scheduler.state(name),
            "replicas": client.scheduler.snapshot(),
        })

    def cluster_leave(self, req: Request) -> Response:
        """Graceful removal: drain, then drop the member once its last
        in-flight stream ends (`force: true` removes immediately). The
        response reports the resulting state — "draining" means removal is
        deferred on live streams and completes automatically."""
        body = req.body or {}
        client = self._cluster_client(body.get("model"))
        name = str(body.get("name") or "").strip()
        if not name:
            raise ApiError(400, "replica name is required")
        state = client.scheduler.leave(name, force=bool(body.get("force")))
        if state == "removed":
            # The scheduler's table is the routing truth; the client's list
            # only feeds facade metrics — prune it for a clean status view.
            # Rebuild under the client lock so a concurrent join's append
            # is not lost to this list swap.
            with client._lock:
                client.replicas = [
                    r for r in client.replicas if r.name != name]
        return Response(body={
            "name": name,
            "state": state,
            "replicas": client.scheduler.snapshot(),
        })

    def _cluster_engine(self, name: Optional[str]):
        """A loaded engine with span transfer hooks (never triggers a
        load — transfer is an optimization, not worth paging a model in)."""
        if not name:
            raise ApiError(400, "model is required")
        lm = self.manager.peek(name)
        if lm is None:
            raise ApiError(404, f"model {name!r} is not loaded")
        eng = lm.engine
        if not hasattr(eng, "export_prefix_span"):
            # Cluster fan-out: export/import from the least-loaded live
            # replica is equivalent (spans are replica-local); use r0.
            reps = getattr(eng, "replicas", None)
            if reps:
                eng = reps[0].engine
        if not hasattr(eng, "export_prefix_span"):
            raise ApiError(400, f"model {name!r} has no KV span transfer "
                                "(paged LLM engines only)")
        return eng

    def cluster_span_export(self, req: Request) -> "Response | RawStream":
        """KV span out. Plain mode returns the raw LAIKV frame (back-compat
        with the ISSUE 6 single-host seam); `stream: true` (ISSUE 13)
        returns the chunked LAIKV-STREAM wire format — per-chunk CRC32s, a
        digest-pinned control header, and resume-from-`offset` support —
        and `compute: true` admits the prompt first when no span is stored
        yet (the remote-prefill entry point: one round trip computes AND
        streams the span)."""
        body = req.body or {}
        eng = self._cluster_engine(body.get("model"))
        prompt_ids = body.get("prompt_ids")
        if not isinstance(prompt_ids, list) or not prompt_ids:
            raise ApiError(400, "prompt_ids (non-empty token id list) required")
        app_cfg = self.manager.app_cfg
        pids = [int(t) for t in prompt_ids]
        trace = str(body.get("trace") or "")
        frame = eng.export_prefix_span(
            pids, max_bytes=app_cfg.transfer_max_bytes, trace_id=trace)
        if frame is None and body.get("compute"):
            # Prefill-on-demand: one probe admission saves the span in the
            # prefix cache (the same shape ClusterClient's in-process
            # handoff uses); it traces as the "<trace>:prefill" leg under
            # the caller's traceparent so a disaggregated request stays ONE
            # trace across machines (ISSUE 11/13).
            eng.generate(
                pids, max_new_tokens=1, ignore_eos=True,
                request_id=(trace + ":prefill") if trace else "",
                traceparent=req.headers.get("traceparent", ""))
            frame = eng.export_prefix_span(
                pids, max_bytes=app_cfg.transfer_max_bytes, trace_id=trace)
        if frame is None:
            raise ApiError(404, "no exportable span stored for this prompt")
        if not body.get("stream"):
            return Response(body=frame, content_type="application/octet-stream")
        from localai_tpu.cluster import netspan

        digest = netspan.frame_digest(frame)
        want = str(body.get("digest") or "")
        if want and want != digest:
            # The span was re-admitted/evicted between resume attempts —
            # the client must restart (or recompute), never splice frames.
            raise ApiError(409, "span changed since the transfer began",
                           kind="conflict")
        offset = int(body.get("offset") or 0)
        if offset < 0 or offset > len(frame):
            raise ApiError(400, f"offset {offset} outside the "
                                f"{len(frame)}-byte frame")
        chunk = int(body.get("chunk_bytes") or 0) or app_cfg.transfer_chunk_bytes
        return RawStream(
            netspan.encode_stream(frame, chunk_bytes=chunk, offset=offset,
                                  trace=trace),
            content_type="application/x-laikv-stream",
        )

    def cluster_span_import(self, req: Request) -> Response:
        """KV span in. Accepts a raw LAIKV frame (back-compat) or the
        LAIKV-STREAM wire format (detected by its chunk magic) — the
        latter is CRC/digest-verified chunk by chunk with the size cap
        enforced mid-walk, and a rejected stream reports `imported: false`
        plus the typed reason instead of landing corrupt KV."""
        name = (req.query.get("model") or [None])[0]
        eng = self._cluster_engine(name)
        if not req.raw_body:
            raise ApiError(400, "span frame bytes required as request body")
        app_cfg = self.manager.app_cfg
        raw = req.raw_body
        from localai_tpu.cluster import netspan
        from localai_tpu.cluster.transfer import SpanTransferError

        if raw[:len(netspan.CHUNK_MAGIC)] == netspan.CHUNK_MAGIC:
            try:
                raw, _meta = netspan.assemble(
                    raw, max_bytes=app_cfg.transfer_max_bytes,
                    verify=app_cfg.transfer_checksum)
            except SpanTransferError as e:
                return Response(body={"imported": False, "error": str(e)})
        ok = eng.import_span_bytes(
            raw, max_bytes=app_cfg.transfer_max_bytes
        )
        return Response(body={"imported": bool(ok)})

    # ------------------------------------------------------------------ #
    # Request-lifecycle observability (ISSUE 11, docs/OBSERVABILITY.md)
    # ------------------------------------------------------------------ #

    def debug_trace(self, req: Request) -> Response:
        """Span tree(s) for one request id — every leg the process saw
        (engine, cluster coordinator, disaggregated prefill), grouped by
        trace id. The id is the OpenAI response id (`chatcmpl-*`/`cmpl-*`,
        `-i` suffix for choice i > 0)."""
        from localai_tpu.observe.trace import STORE

        rid = req.params["request_id"]
        data = STORE.get_json(rid)
        if data is None:
            raise ApiError(
                404,
                f"no trace recorded for request {rid!r} (traces are kept "
                "for the most recent requests only)",
            )
        return Response(body=data)

    def _engine_journals(self, model: Optional[str]) -> dict:
        """{display name: EventJournal} across loaded engines (peek only —
        a debug pull must never trigger a model load). Cluster fan-outs
        contribute one journal per replica."""
        out: dict = {}
        for n in self.manager.loaded_names():
            if model and n != model:
                continue
            lm = self.manager.peek(n)
            if lm is None:
                continue
            eng = lm.engine
            journals = getattr(eng, "journals", None)
            if callable(journals):  # ClusterEngine: one row per replica
                for rname, j in journals().items():
                    out[f"{n}/{rname}"] = j
                continue
            j = getattr(eng, "journal", None)
            if j is not None:
                out[n] = j
        return out

    def debug_timeline(self, req: Request) -> Response:
        """The engine journal(s) as Chrome trace-event JSON — load the
        response body directly in Perfetto / chrome://tracing. `?model=`
        narrows to one model; cluster replicas render as process rows."""
        from localai_tpu.observe.timeline import chrome_trace

        model = (req.query.get("model") or [None])[0]
        journals = self._engine_journals(model)
        if not journals:
            raise ApiError(
                404,
                "no event journal available"
                + (f" for model {model!r}" if model else "")
                + " — is the model loaded and trace_journal_events > 0?",
            )
        return Response(body=chrome_trace(journals))

    def debug_profile(self, req: Request) -> Response:
        """Run one jax.profiler capture window (POST {"seconds": N}).
        Gated behind LOCALAI_PROFILE=<output dir>: profiling perturbs
        serving and writes device traces to disk, so it is an explicit
        operator opt-in."""
        import os

        from localai_tpu.observe import profile as oprofile

        prof_dir = os.environ.get("LOCALAI_PROFILE", "")
        if not prof_dir:
            raise ApiError(
                403,
                "profiling is disabled — set LOCALAI_PROFILE=<output dir> "
                "to allow /debug/profile capture windows",
            )
        seconds = float((req.body or {}).get("seconds", 1.0))
        try:
            result = oprofile.capture(prof_dir, seconds)
        except RuntimeError as e:
            raise ApiError(409, str(e)) from None
        # Mark the capture window in every journal so the timeline and the
        # profiler trace can be lined up.
        for j in self._engine_journals(None).values():
            j.stage("profile", a=result["seconds"])
        return Response(body={"status": "ok", **result})
