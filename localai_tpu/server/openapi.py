"""OpenAPI 3.0 document generated from the live routing table.

Reference: the swagger module (core/http/app.go mounts /swagger with
generated docs). Here the doc is built from Router.declared at request time
— every registered route appears, summaries come from handler docstrings,
and known OpenAI-compatible paths carry request-body schemas.
"""

from __future__ import annotations

from typing import Any

from localai_tpu.server.app import Request, Response, Router

_BODY_SCHEMAS: dict[str, dict[str, Any]] = {
    "/v1/chat/completions": {
        "required": ["messages"],
        "properties": {
            "model": {"type": "string"},
            "messages": {"type": "array", "items": {"type": "object"}},
            "stream": {"type": "boolean"},
            "max_tokens": {"type": "integer"},
            "temperature": {"type": "number"},
            "top_p": {"type": "number"},
            "n": {"type": "integer"},
            "logprobs": {"type": "boolean"},
            "top_logprobs": {"type": "integer"},
            "tools": {"type": "array", "items": {"type": "object"}},
            "response_format": {"type": "object"},
            "seed": {"type": "integer"},
            "stop": {"type": "array", "items": {"type": "string"}},
            "grammar": {"type": "string",
                        "description": "raw GBNF grammar constraining output"},
        },
    },
    "/v1/completions": {
        "required": ["prompt"],
        "properties": {
            "model": {"type": "string"},
            "prompt": {"oneOf": [{"type": "string"}, {"type": "array"}]},
            "stream": {"type": "boolean"},
            "max_tokens": {"type": "integer"},
            "n": {"type": "integer"},
            "logprobs": {"type": "integer"},
            "echo": {"type": "boolean"},
            "grammar": {"type": "string",
                        "description": "raw GBNF grammar constraining output"},
        },
    },
    "/v1/embeddings": {
        "required": ["input"],
        "properties": {
            "model": {"type": "string"},
            "input": {"oneOf": [{"type": "string"}, {"type": "array"}]},
        },
    },
    "/v1/images/generations": {
        "required": ["prompt"],
        "properties": {
            "model": {"type": "string"}, "prompt": {"type": "string"},
            "n": {"type": "integer"}, "size": {"type": "string"},
            "steps": {"type": "integer"}, "seed": {"type": "integer"},
            "response_format": {"type": "string", "enum": ["url", "b64_json"]},
            "control_image": {"type": "string",
                              "description": "base64 PNG/JPEG ControlNet condition"},
            "control_scale": {"type": "number"},
            "image": {"type": "string",
                      "description": "base64 img2img source (alias: src)"},
            "strength": {"type": "number"},
        },
    },
    "/v1/videos": {
        "required": ["prompt"],
        "properties": {
            "model": {"type": "string"}, "prompt": {"type": "string"},
            "n_frames": {"type": "integer"}, "steps": {"type": "integer"},
            "seed": {"type": "integer"},
            "negative_prompt": {"type": "string"},
            "image": {"type": "string",
                      "description": "base64 image→video source (aliases: "
                                     "file, src)"},
            "strength": {"type": "number"},
            "format": {"type": "string", "enum": ["mp4", "gif"]},
            "frame_ms": {"type": "integer"},
        },
    },
    "/v1/sound-generation": {
        "required": ["text"],
        "properties": {
            "model_id": {"type": "string"}, "text": {"type": "string"},
            "duration_seconds": {"type": "number"},
            "prompt_influence": {"type": "number"},
            "do_sample": {"type": "boolean"}, "seed": {"type": "integer"},
            "response_format": {"type": "string", "enum": ["wav", "pcm"]},
        },
    },
    "/v1/audio/speech": {
        "required": ["input"],
        "properties": {
            "model": {"type": "string"}, "input": {"type": "string"},
            "voice": {"type": "string"},
            "response_format": {"type": "string", "enum": ["wav", "pcm"]},
        },
    },
    "/v1/rerank": {
        "required": ["query", "documents"],
        "properties": {
            "model": {"type": "string"}, "query": {"type": "string"},
            "documents": {"type": "array"}, "top_n": {"type": "integer"},
        },
    },
}


def build_openapi(router: Router, title: str = "localai-tpu") -> dict[str, Any]:
    from localai_tpu import __version__

    paths: dict[str, dict[str, Any]] = {}
    for method, pattern, handler in router.declared:
        # OpenAPI path templating: `:name` → `{name}`
        path = "/".join(
            "{" + seg[1:] + "}" if seg.startswith(":") else seg
            for seg in pattern.split("/")
        )
        doc = (handler.__doc__ or "").strip().splitlines()
        summary = doc[0] if doc else ""
        op: dict[str, Any] = {
            "summary": summary,
            "responses": {"200": {"description": "success"}},
        }
        params = [seg[1:] for seg in pattern.split("/") if seg.startswith(":")]
        if params:
            op["parameters"] = [
                {"name": p, "in": "path", "required": True, "schema": {"type": "string"}}
                for p in params
            ]
        schema = _BODY_SCHEMAS.get(pattern)
        if schema and method == "POST":
            op["requestBody"] = {
                "required": True,
                "content": {"application/json": {"schema": {"type": "object", **schema}}},
            }
        paths.setdefault(path, {})[method.lower()] = op
    return {
        "openapi": "3.0.3",
        "info": {
            "title": title,
            "version": __version__,
            "description": "TPU-native LocalAI-compatible API",
        },
        "paths": dict(sorted(paths.items())),
    }


def register_openapi(router: Router) -> None:
    def swagger_json(req: Request) -> Response:
        """OpenAPI 3.0 document for every registered route."""
        return Response(body=build_openapi(router))

    def swagger_html(req: Request) -> Response:
        """Interactive API browser (no external assets)."""
        return Response(body=_SWAGGER_HTML, content_type="text/html; charset=utf-8")

    router.add("GET", "/swagger.json", swagger_json)
    router.add("GET", "/swagger", swagger_html)


_SWAGGER_HTML = """<!doctype html><html><head><meta charset="utf-8">
<title>localai-tpu API</title><style>
body{font-family:system-ui,sans-serif;margin:2rem;max-width:960px}
.op{border:1px solid #ddd;border-radius:6px;margin:.5rem 0;padding:.6rem 1rem}
.m{display:inline-block;min-width:4rem;font-weight:700}
.m.get{color:#0a7} .m.post{color:#07a} .m.delete{color:#a33}
code{background:#f5f5f5;padding:.1rem .3rem;border-radius:3px}
pre{background:#f8f8f8;padding:.6rem;border-radius:4px;overflow-x:auto}
</style></head><body><h1>localai-tpu API</h1><div id="ops">loading…</div>
<script>
fetch('/swagger.json').then(r=>r.json()).then(doc=>{
  const el=document.getElementById('ops');el.innerHTML='';
  for(const [path,ops] of Object.entries(doc.paths)){
    for(const [m,op] of Object.entries(ops)){
      const d=document.createElement('div');d.className='op';
      let html=`<span class="m ${m}">${m.toUpperCase()}</span> <code>${path}</code>`;
      if(op.summary) html+=`<div>${op.summary}</div>`;
      if(op.requestBody){
        const s=op.requestBody.content['application/json'].schema;
        html+=`<pre>${JSON.stringify(s,null,1)}</pre>`;
      }
      d.innerHTML=html;el.appendChild(d);
    }
  }
});
</script></body></html>"""
