"""Federation / P2P status for the WebUI and operators.

Reference: the LocalAI WebUI's p2p views (core/http/views/p2p.html +
core/p2p) show the swarm this node belongs to. Here the swarm is the
token-gated federation router (localai_tpu/federation) plus the explorer
directory; this endpoint aggregates both SERVER-SIDE (the browser never
talks cross-origin, and only the CONFIGURED urls are fetched — no
client-supplied targets, so no SSRF surface).
"""

from __future__ import annotations

import json
import os
import urllib.request
from typing import Optional

from localai_tpu.server.app import Request, Response, Router


class P2pApi:
    def __init__(self, federator: Optional[str] = None,
                 worker_name: Optional[str] = None,
                 explorer: Optional[str] = None):
        self._federator = federator
        self._worker_name = worker_name
        self._explorer = explorer

    def register(self, r: Router) -> None:
        r.add("GET", "/p2p/status", self.status)

    def _fetch_json(self, url: str):
        req = urllib.request.Request(url, headers={"Accept": "application/json"})
        token = os.environ.get("LOCALAI_FEDERATION_TOKEN", "")
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        with urllib.request.urlopen(req, timeout=3) as resp:
            return json.loads(resp.read())

    def status(self, req: Request) -> Response:
        federator = self._federator or os.environ.get("LOCALAI_FEDERATOR") or None
        explorer = self._explorer or os.environ.get("LOCALAI_EXPLORER") or None
        body = {
            "federator": federator,
            "worker_name": self._worker_name,
            "explorer": explorer,
            "workers": [],
            "networks": [],
            "errors": [],
        }
        if federator:
            try:
                d = self._fetch_json(federator.rstrip("/") + "/federation/workers")
                body["workers"] = d.get("workers", d) or []
            except Exception as e:  # noqa: BLE001 — status stays best-effort
                body["errors"].append(f"federator: {type(e).__name__}: {e}")
        if explorer:
            try:
                d = self._fetch_json(explorer.rstrip("/") + "/networks")
                body["networks"] = d.get("networks", d) or []
            except Exception as e:  # noqa: BLE001
                body["errors"].append(f"explorer: {type(e).__name__}: {e}")
        return Response(body=body)
