"""Federation / P2P status for the WebUI and operators.

Reference: the LocalAI WebUI's p2p views (core/http/views/p2p.html +
core/p2p) show the swarm this node belongs to. Here the swarm is the
token-gated federation router (localai_tpu/federation) plus the explorer
directory; this endpoint aggregates both SERVER-SIDE (the browser never
talks cross-origin, and only the CONFIGURED urls are fetched — no
client-supplied targets, so no SSRF surface).
"""

from __future__ import annotations

import json
import os
import urllib.request
from typing import Optional

from localai_tpu.server.app import Request, Response, Router


class P2pApi:
    def __init__(self, federator: Optional[str] = None,
                 worker_name: Optional[str] = None,
                 explorer: Optional[str] = None,
                 cluster_peers: Optional[list] = None):
        self._federator = federator
        self._worker_name = worker_name
        self._explorer = explorer
        self._cluster_peers = list(cluster_peers or [])

    def register(self, r: Router) -> None:
        r.add("GET", "/p2p/status", self.status)
        r.add("GET", "/p2p/cluster", self.cluster)

    def cluster(self, req: Request) -> Response:
        """Configured cluster peers (ISSUE 13) probed SERVER-SIDE: name,
        URL, reachability, and the role each advertises via its
        LocalAI-Cluster-Role header — the discovery seam remote replicas
        are built from. Only CONFIGURED urls are probed (no SSRF surface),
        and a dead peer reports unreachable instead of failing the view."""
        from localai_tpu.cluster.replica import parse_peers, probe_worker_role

        peers = []
        for name, url in parse_peers(self._cluster_peers):
            entry = {"name": name, "url": url,
                     "reachable": False, "role": None}
            try:
                entry["role"] = probe_worker_role(url, timeout=3)
                entry["reachable"] = True
            except Exception as e:  # noqa: BLE001 — view stays best-effort
                entry["error"] = f"{type(e).__name__}: {e}"
            peers.append(entry)
        return Response(body={"cluster_peers": peers})

    def _fetch_json(self, url: str):
        req = urllib.request.Request(url, headers={"Accept": "application/json"})
        token = os.environ.get("LOCALAI_FEDERATION_TOKEN", "")
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        with urllib.request.urlopen(req, timeout=3) as resp:
            return json.loads(resp.read())

    def status(self, req: Request) -> Response:
        federator = self._federator or os.environ.get("LOCALAI_FEDERATOR") or None
        explorer = self._explorer or os.environ.get("LOCALAI_EXPLORER") or None
        body = {
            "federator": federator,
            "worker_name": self._worker_name,
            "explorer": explorer,
            "workers": [],
            "networks": [],
            "errors": [],
        }
        if federator:
            try:
                d = self._fetch_json(federator.rstrip("/") + "/federation/workers")
                body["workers"] = d.get("workers", d) or []
            except Exception as e:  # noqa: BLE001 — status stays best-effort
                body["errors"].append(f"federator: {type(e).__name__}: {e}")
        if explorer:
            try:
                d = self._fetch_json(explorer.rstrip("/") + "/networks")
                body["networks"] = d.get("networks", d) or []
            except Exception as e:  # noqa: BLE001
                body["errors"].append(f"explorer: {type(e).__name__}: {e}")
        return Response(body=body)
