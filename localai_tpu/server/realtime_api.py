"""OpenAI-realtime-compatible voice WebSocket.

Reference: core/http/endpoints/openai/realtime.go (1,301 LoC; session event
loop over a websocket: audio in → transcription → LLM → TTS audio out) and
its types file. This is the same protocol subset on the TPU stack: whisper
for STT, the llama engine for the turn, the TTS engine for audio out —
each resolved through the same ModelManager usecases as the REST routes.

Supported client events: session.update, input_audio_buffer.append /
commit / clear, conversation.item.create, response.create, response.cancel.
Server events mirror OpenAI's: session.created/updated, committed,
item.created, response.created, response.audio_transcript.delta,
response.text.delta, response.audio.delta, response.done, error.
"""

from __future__ import annotations

import base64
import logging
import uuid
from typing import Any, Optional

import numpy as np

from localai_tpu.config import Usecase
from localai_tpu.server.app import Request, Router
from localai_tpu.server.manager import ModelManager
from localai_tpu.server.openai_api import OpenAIApi
from localai_tpu.server.ws import WebSocket, WebSocketUpgrade

log = logging.getLogger("localai_tpu.realtime")


def _rid(prefix: str) -> str:
    return f"{prefix}_{uuid.uuid4().hex[:20]}"


class RealtimeSession:
    def __init__(self, api: "RealtimeApi", query_model: Optional[str]):
        self.api = api
        self.config: dict[str, Any] = {
            "id": _rid("sess"),
            "model": query_model or "",
            "modalities": ["text", "audio"],
            "instructions": "",
            "voice": "",
            "input_audio_format": "pcm16",
            "output_audio_format": "pcm16",
            "input_sample_rate": 24_000,
            "output_sample_rate": 24_000,
            "temperature": 0.7,
            "max_response_output_tokens": 512,
            # {"type": "server_vad", "silence_duration_ms": 500} enables
            # automatic turn detection (reference: realtime.go server VAD
            # via silero; here audio/vad.py energy detection).
            "turn_detection": None,
            "transcription_model": None,
            "tts_model": None,
        }
        self.conversation: list[dict[str, str]] = []
        self.audio_buffer = bytearray()
        self._speech_started = False

    # ------------------------------------------------------------------ #

    def run(self, ws: WebSocket) -> None:
        ws.send_json({"type": "session.created", "session": self.config})
        while True:
            ev = ws.recv_json()
            if ev is None:
                return
            try:
                self.handle(ws, ev)
            except Exception as e:  # noqa: BLE001 — error event, keep session
                log.exception("realtime event failed")
                ws.send_json({"type": "error", "error": {
                    "type": "server_error", "message": f"{type(e).__name__}: {e}",
                }})

    def handle(self, ws: WebSocket, ev: dict) -> None:
        kind = ev.get("type")
        if kind == "session.update":
            patch = ev.get("session") or {}
            for k, v in patch.items():
                if k in self.config and k != "id":
                    self.config[k] = v
            ws.send_json({"type": "session.updated", "session": self.config})
        elif kind == "input_audio_buffer.append":
            self.audio_buffer.extend(base64.b64decode(ev.get("audio") or ""))
            self._maybe_auto_commit(ws)
        elif kind == "input_audio_buffer.clear":
            self.audio_buffer.clear()
            ws.send_json({"type": "input_audio_buffer.cleared"})
        elif kind == "input_audio_buffer.commit":
            self._commit_audio(ws)
        elif kind == "conversation.item.create":
            item = ev.get("item") or {}
            text = " ".join(
                c.get("text", "") for c in item.get("content") or []
                if c.get("type") in ("input_text", "text")
            ).strip()
            role = item.get("role", "user")
            if text:
                self.conversation.append({"role": role, "content": text})
            ws.send_json({"type": "conversation.item.created", "item": {
                "id": item.get("id") or _rid("item"), "type": "message",
                "role": role,
                "content": [{"type": "input_text", "text": text}],
            }})
        elif kind == "response.create":
            self._respond(ws, ev.get("response") or {})
        elif kind == "response.cancel":
            ws.send_json({"type": "response.cancelled"})
        else:
            ws.send_json({"type": "error", "error": {
                "type": "invalid_request_error",
                "message": f"unknown event type {kind!r}",
            }})

    # ------------------------------------------------------------------ #

    def _vad_segments(self, audio16: np.ndarray) -> list[tuple[float, float]]:
        """(start, end) speech spans. Uses a configured vad-backend model —
        the learned conv+GRU net fills the silero role (reference:
        realtime.go server VAD via silero) — falling back to the weightless
        energy detector when none is configured."""
        cfg = self.api.manager.configs.first_with(Usecase.VAD)
        if cfg is not None:
            lm, lease = self.api.manager.lease(cfg.name)
            try:
                return [(d["start"], d["end"]) for d in lm.engine.detect(audio16, 16_000)]
            finally:
                lease.release()
        from localai_tpu.audio import learned_vad as LV

        packaged = LV.packaged_weights()
        if packaged is not None:
            # No VAD model configured: the shipped pretrained net (silero
            # role) still beats the energy heuristic for turn detection.
            if not hasattr(self.api, "_builtin_vad"):
                params = LV.load_params(packaged)
                self.api._builtin_vad = (LV.config_from_params(params), params)
            vcfg, params = self.api._builtin_vad
            return [(s.start, s.end)
                    for s in LV.detect(vcfg, params, audio16, 16_000)]
        from localai_tpu.audio.vad import energy_vad

        return [(s.start, s.end) for s in energy_vad(audio16, 16_000)]

    def _maybe_auto_commit(self, ws: WebSocket) -> None:
        """Server-VAD turn detection: commit + respond once speech is
        followed by enough trailing silence."""
        td = self.config.get("turn_detection") or {}
        if td.get("type") != "server_vad" or not self.audio_buffer:
            return
        from localai_tpu.audio import resample

        sr = int(self.config["input_sample_rate"])
        pcm = np.frombuffer(bytes(self.audio_buffer), np.int16).astype(np.float32) / 32768.0
        audio16 = resample(pcm, sr, 16_000)
        segs = self._vad_segments(audio16)
        if not segs:
            return
        if not self._speech_started:
            self._speech_started = True
            ws.send_json({"type": "input_audio_buffer.speech_started"})
        silence_s = float(td.get("silence_duration_ms", 500)) / 1000.0
        trailing = len(audio16) / 16_000.0 - segs[-1][1]
        if trailing >= silence_s:
            ws.send_json({"type": "input_audio_buffer.speech_stopped"})
            self._speech_started = False
            self._commit_audio(ws)
            self._respond(ws, {})

    def _commit_audio(self, ws: WebSocket) -> None:
        from localai_tpu.audio import resample

        item_id = _rid("item")
        if not self.audio_buffer:
            ws.send_json({"type": "error", "error": {
                "type": "invalid_request_error",
                "message": "input audio buffer is empty",
            }})
            return
        pcm = np.frombuffer(bytes(self.audio_buffer), np.int16).astype(np.float32) / 32768.0
        self.audio_buffer.clear()
        sr = int(self.config["input_sample_rate"])
        audio16 = resample(pcm, sr, 16_000)

        lm, lease = self.api._lease(Usecase.TRANSCRIPT, self.config.get("transcription_model"))
        try:
            result = lm.engine.transcribe(audio16)
        finally:
            lease.release()
        text = result["text"]
        self.conversation.append({"role": "user", "content": text})
        ws.send_json({"type": "input_audio_buffer.committed", "item_id": item_id})
        ws.send_json({"type": "conversation.item.created", "item": {
            "id": item_id, "type": "message", "role": "user",
            "content": [{"type": "input_audio", "transcript": text}],
        }})

    def _respond(self, ws: WebSocket, overrides: dict) -> None:
        from localai_tpu.engine import GenRequest

        resp_id = _rid("resp")
        modalities = overrides.get("modalities") or self.config["modalities"]
        instructions = overrides.get("instructions") or self.config["instructions"]
        ws.send_json({"type": "response.created", "response": {"id": resp_id}})

        messages = []
        if instructions:
            messages.append({"role": "system", "content": instructions})
        messages.extend(self.conversation)
        if not messages:
            messages = [{"role": "user", "content": ""}]

        lm, lease = self.api._lease(Usecase.CHAT, self.config.get("model") or None)
        try:
            prompt = lm.evaluator.template_messages(messages)
            ids = lm.engine.tokenizer.encode(
                prompt, add_bos=not lm.cfg.template.use_tokenizer_template
            )
            gen = GenRequest(
                prompt_ids=ids,
                max_new_tokens=int(self.config["max_response_output_tokens"]),
                temperature=float(self.config["temperature"]),
                stop=lm.evaluator.stop_sequences(),
            )
            handle = lm.engine.submit(gen)
            parts: list[str] = []
            delta_type = (
                "response.audio_transcript.delta"
                if "audio" in modalities else "response.text.delta"
            )
            for tev in handle:
                if tev.kind == "token":
                    parts.append(tev.text)
                    ws.send_json({
                        "type": delta_type, "response_id": resp_id,
                        "delta": tev.text,
                    })
                elif tev.kind == "error":
                    ws.send_json({"type": "error", "error": {
                        "type": "server_error", "message": tev.error,
                    }})
                    return
        finally:
            lease.release()
        text = "".join(parts)
        self.conversation.append({"role": "assistant", "content": text})

        if "audio" in modalities:
            self._send_audio(ws, resp_id, text)

        ws.send_json({"type": "response.done", "response": {
            "id": resp_id, "status": "completed",
            "output": [{
                "type": "message", "role": "assistant",
                "content": [{"type": "text", "text": text}],
            }],
        }})

    def _send_audio(self, ws: WebSocket, resp_id: str, text: str) -> None:
        from localai_tpu.audio import resample

        try:
            lm, lease = self.api._lease(Usecase.TTS, self.config.get("tts_model"))
        except Exception:  # noqa: BLE001 — no TTS model configured: text only
            return
        try:
            samples, sr = lm.engine.synthesize(text or " ", voice=self.config.get("voice"))
        finally:
            lease.release()
        out_sr = int(self.config["output_sample_rate"])
        pcm = resample(samples, sr, out_sr)
        pcm16 = (np.clip(pcm, -1, 1) * 32767.0).astype(np.int16).tobytes()
        chunk = out_sr * 2 // 10  # 100 ms per delta
        for off in range(0, len(pcm16), chunk):
            ws.send_json({
                "type": "response.audio.delta", "response_id": resp_id,
                "delta": base64.b64encode(pcm16[off: off + chunk]).decode(),
            })
        ws.send_json({"type": "response.audio.done", "response_id": resp_id})


class EphemeralKeys:
    """Short-lived client secrets for realtime connects.

    POST /v1/realtime/sessions mints one; the WS handshake (and nothing
    else) accepts it as a bearer token. The reference stubs this endpoint
    with a 501 (realtime.go:185-189); OpenAI's real contract returns a
    session object whose client_secret.value expires in ~60 s — that is
    what browsers need to connect without the server API key.
    """

    TTL_S = 60.0
    # Exactly the WS connect path: admitting /v1/realtime/sessions would let
    # an ephemeral secret mint its own replacement forever.
    WS_PATH = "/v1/realtime"

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._keys: dict[str, tuple[float, dict]] = {}  # secret -> (expiry, session cfg)

    def mint(self, session_cfg: dict) -> tuple[str, int]:
        import secrets
        import time

        value = "ek_" + secrets.token_hex(16)
        expires = time.time() + self.TTL_S
        with self._lock:
            now = time.time()
            for k in [k for k, (exp, _) in self._keys.items() if exp < now]:
                del self._keys[k]
            self._keys[value] = (expires, session_cfg)
        return value, int(expires)

    def valid(self, token: str, path: str) -> bool:
        """Auth-hook: a live ephemeral key admits the WS connect only."""
        import time

        if path != self.WS_PATH:
            return False
        with self._lock:
            entry = self._keys.get(token)
            return entry is not None and entry[0] >= time.time()

    def session_for(self, token: str) -> Optional[dict]:
        import time

        with self._lock:
            entry = self._keys.get(token)
            if entry is None or entry[0] < time.time():
                return None
            return entry[1]


class RealtimeApi:
    def __init__(self, manager: ModelManager, base: OpenAIApi):
        self.manager = manager
        self._base = base
        self.ephemeral = EphemeralKeys()

    def register(self, r: Router) -> None:
        r.add("GET", "/v1/realtime", self.realtime)
        # REST session endpoints (reference routes openai.go:21-22; its
        # handler is a 501 stub — this is the real OpenAI contract).
        r.add("POST", "/v1/realtime/sessions", self.create_session)
        r.add("POST", "/v1/realtime/transcription_session",
              self.create_transcription_session)
        # OpenAI's documented path is the plural form; the reference
        # registers the singular (openai.go:22) — serve both.
        r.add("POST", "/v1/realtime/transcription_sessions",
              self.create_transcription_session)
        # create_server's auth consults this for realtime-scoped bearers.
        r.ephemeral_keys = self.ephemeral

    def _lease(self, usecase: Usecase, name: Optional[str]):
        if not name:
            cfg = self.manager.configs.first_with(usecase)
            if cfg is None:
                raise RuntimeError(f"no model configured for {usecase}")
            name = cfg.name
        return self.manager.lease(name)

    def _mint_session(self, body: dict, obj: str) -> "Response":
        from localai_tpu.server.app import Response

        template = RealtimeSession(self, body.get("model"))
        for k, v in (body or {}).items():
            if k in template.config and k != "id":
                template.config[k] = v
        secret, expires_at = self.ephemeral.mint(dict(template.config))
        session = dict(template.config)
        session["object"] = obj
        session["client_secret"] = {"value": secret, "expires_at": expires_at}
        return Response(body=session)

    def create_session(self, req: Request) -> "Response":
        return self._mint_session(req.body or {}, "realtime.session")

    def create_transcription_session(self, req: Request) -> "Response":
        body = dict(req.body or {})
        # transcription sessions carry the STT model in input_audio_transcription
        iat = body.get("input_audio_transcription") or {}
        if iat.get("model"):
            body["transcription_model"] = iat["model"]
        resp = self._mint_session(body, "realtime.transcription_session")
        resp.body["input_audio_transcription"] = iat or {"model": ""}
        return resp

    def realtime(self, req: Request) -> WebSocketUpgrade:
        model = (req.query.get("model") or [None])[0]
        session = RealtimeSession(self, model)
        # A connect with a minted client_secret resumes its session config.
        header = req.headers.get("authorization", "") or req.headers.get("Authorization", "")
        token = header[7:] if header.startswith("Bearer ") else header
        stored = self.ephemeral.session_for(token) if token else None
        if stored:
            session.config.update(stored)  # minted configs carry their own id
            if model:
                session.config["model"] = model
        return WebSocketUpgrade(session.run)
