"""Rerank (jina-compatible) and object-detection endpoints.

Reference: core/http/routes/jina.go → endpoints/jina/rerank.go (POST
/v1/rerank: query + documents → relevance-sorted results) and
endpoints/localai/detection.go (POST /v1/detection: image → boxes).
"""

from __future__ import annotations

import base64

import numpy as np

from localai_tpu.config import Usecase
from localai_tpu.server.app import ApiError, Request, Response, Router
from localai_tpu.server.manager import ModelManager
from localai_tpu.server.openai_api import OpenAIApi


class RerankApi:
    def __init__(self, manager: ModelManager, base: OpenAIApi):
        self.manager = manager
        self._base = base

    def register(self, r: Router) -> None:
        r.add("POST", "/v1/rerank", self.rerank)
        r.add("POST", "/rerank", self.rerank)
        r.add("POST", "/v1/detection", self.detection)

    def rerank(self, req: Request) -> Response:
        body = req.body or {}
        query = body.get("query")
        documents = body.get("documents")
        if not query or not isinstance(query, str):
            raise ApiError(400, "query is required")
        if not documents or not isinstance(documents, list):
            raise ApiError(400, "documents must be a non-empty array")
        docs = [d.get("text", "") if isinstance(d, dict) else str(d) for d in documents]
        top_n = int(body.get("top_n") or len(docs))

        lm, lease = self._base._resolve(req, Usecase.RERANK)
        try:
            tok = lm.engine.tokenizer
            q_ids = tok.encode(query) or [0]
            d_ids = [tok.encode(d) or [0] for d in docs]
            scores = lm.engine.rerank(q_ids, d_ids)
        finally:
            lease.release()

        order = np.argsort(-scores)[:top_n]
        results = [
            {
                "index": int(i),
                "relevance_score": float(scores[i]),
                "document": {"text": docs[i]},
            }
            for i in order
        ]
        n_tokens = len(q_ids) + sum(len(d) for d in d_ids)
        return Response(body={
            "model": lm.cfg.name,
            "results": results,
            "usage": {"total_tokens": n_tokens, "prompt_tokens": n_tokens},
        })

    def detection(self, req: Request) -> Response:
        body = req.body or {}
        img_b64 = body.get("image")
        if not img_b64 or not isinstance(img_b64, str):
            raise ApiError(400, "image (base64) is required")
        if img_b64.startswith("data:"):
            img_b64 = img_b64.split(",", 1)[-1]
        try:
            raw = base64.b64decode(img_b64)
        except Exception:  # noqa: BLE001
            raise ApiError(400, "invalid base64 image") from None
        import io

        from PIL import Image

        try:
            img = np.asarray(Image.open(io.BytesIO(raw)).convert("RGB"))
        except Exception as e:  # noqa: BLE001
            raise ApiError(400, f"could not decode image: {e}") from None

        thr = body.get("threshold")
        thr = 0.5 if thr is None else float(thr)  # 0.0 is a valid threshold
        lm, lease = self._base._resolve(req, Usecase.DETECTION)
        try:
            detections = lm.engine.detect(img, threshold=thr)
        finally:
            lease.release()
        return Response(body={"detections": detections})
