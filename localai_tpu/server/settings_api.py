"""Runtime settings API.

Reference: the settings endpoints + runtime_settings.json tier (LocalAI
persists mutable settings and applies them over flags at boot). GET returns
the mutable set; PUT applies changes live (watchdog budgets, LRU budget,
machine tag) and persists them when a runtime_settings_path is configured.
"""

from __future__ import annotations

from localai_tpu.config import ApplicationConfig
from localai_tpu.server.app import ApiError, Request, Response, Router
from localai_tpu.server.manager import ModelManager


class SettingsApi:
    def __init__(self, app_cfg: ApplicationConfig, manager: ModelManager):
        self.app_cfg = app_cfg
        self.manager = manager

    def register(self, r: Router) -> None:
        r.add("GET", "/settings", self.get)
        r.add("PUT", "/settings", self.put)
        r.add("POST", "/settings", self.put)

    def get(self, req: Request) -> Response:
        """Current mutable runtime settings."""
        return Response(body={
            k: getattr(self.app_cfg, k) for k in ApplicationConfig.RUNTIME_MUTABLE
        })

    def put(self, req: Request) -> Response:
        """Apply + persist runtime settings ({key: value} subset)."""
        body = req.body or {}
        unknown = set(body) - set(ApplicationConfig.RUNTIME_MUTABLE)
        if unknown:
            raise ApiError(400, f"unknown or immutable settings: {sorted(unknown)}")
        for k, v in body.items():
            field_type = type(getattr(self.app_cfg, k))
            try:
                setattr(self.app_cfg, k, field_type(v))
            except (TypeError, ValueError):
                raise ApiError(400, f"invalid value for {k}: {v!r}") from None
        # Live application: the watchdog thread may need to exist now.
        if (
            self.app_cfg.watchdog_idle_timeout_s > 0
            or self.app_cfg.watchdog_busy_timeout_s > 0
        ):
            self.manager.ensure_watchdog()
        self.app_cfg.save_runtime_settings()
        return Response(body=self.get(req).body)
