"""/stores/{set,get,delete,find} endpoints.

Reference: core/http/endpoints/localai/stores.go + core/backend/stores.go;
request/response shapes follow core/schema (StoresSet/Get/Delete/Find).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from localai_tpu.server.app import ApiError, Request, Response, Router
from localai_tpu.stores import StoreRegistry


class StoresApi:
    def __init__(self, registry: StoreRegistry | None = None):
        self.registry = registry or StoreRegistry()

    def register(self, r: Router) -> None:
        r.add("POST", "/stores/set", self.set)
        r.add("POST", "/stores/get", self.get)
        r.add("POST", "/stores/delete", self.delete)
        r.add("POST", "/stores/find", self.find)

    def _store(self, body: dict[str, Any]):
        return self.registry.get(body.get("store") or "")

    @staticmethod
    def _keys(body: dict[str, Any], field: str = "keys") -> np.ndarray:
        keys = body.get(field)
        if not isinstance(keys, list) or not keys:
            raise ApiError(400, f"{field} must be a non-empty array of float arrays")
        try:
            return np.asarray(keys, np.float32)
        except (ValueError, TypeError):
            raise ApiError(400, f"{field} must be rectangular float arrays") from None

    def set(self, req: Request) -> Response:
        body = req.body or {}
        keys = self._keys(body)
        values = body.get("values")
        if not isinstance(values, list) or len(values) != len(keys):
            raise ApiError(400, "values must be an array matching keys length")
        if not all(isinstance(v, str) for v in values):
            raise ApiError(400, "values must be strings (serialize structured data as JSON)")
        try:
            self._store(body).set(keys, [v.encode() for v in values])
        except ValueError as e:
            raise ApiError(400, str(e)) from None
        return Response(body={})

    def get(self, req: Request) -> Response:
        body = req.body or {}
        keys = self._keys(body)
        values = self._store(body).get(keys)
        found_keys, found_vals = [], []
        for k, v in zip(keys, values):
            if v is not None:
                found_keys.append([float(x) for x in k])
                found_vals.append(v.decode("utf-8", "replace"))
        return Response(body={"keys": found_keys, "values": found_vals})

    def delete(self, req: Request) -> Response:
        body = req.body or {}
        keys = self._keys(body)
        self._store(body).delete(keys)
        return Response(body={})

    def find(self, req: Request) -> Response:
        body = req.body or {}
        key = body.get("key")
        if not isinstance(key, list) or not key:
            raise ApiError(400, "key must be a non-empty float array")
        topk_raw = body.get("topk", 10)
        if not isinstance(topk_raw, int) or isinstance(topk_raw, bool) or topk_raw < 0:
            raise ApiError(400, "topk must be a non-negative integer")
        topk = topk_raw
        try:
            keys, values, sims = self._store(body).find(np.asarray(key, np.float32), topk)
        except ValueError as e:
            raise ApiError(400, str(e)) from None
        return Response(body={
            "keys": [[float(x) for x in k] for k in keys],
            "values": [v.decode("utf-8", "replace") for v in values],
            "similarities": [float(s) for s in sims],
        })
