"""Embedded WebUI.

Reference: core/http/views (23 templates: chat, models, model editor,
gallery install, agent jobs, tts, image generation, talk) + ui.go/
ui_api.go routes. Here: one dependency-free single-page app served at `/`
that drives the same public APIs the CLI and SDKs use — chat with SSE
streaming, realtime talk over the /v1/realtime WebSocket (text turns +
mic streaming with server-VAD), model list + load state, a model config
editor (import/edit/delete/reload), gallery browse/install with job
polling, an agent-jobs panel (create/run/toggle/history), TTS playback,
image generation. No build step, no external assets (air-gapped TPU pods).
"""

from __future__ import annotations

from localai_tpu.server.app import Request, Response, Router


def register_webui(router: Router) -> None:
    def index(req: Request) -> Response:
        """WebUI single-page app."""
        return Response(body=_HTML, content_type="text/html; charset=utf-8")

    router.add("GET", "/", index)
    router.add("GET", "/index.html", index)


_HTML = r"""<!doctype html>
<html><head><meta charset="utf-8"><meta name="viewport" content="width=device-width,initial-scale=1">
<title>localai-tpu</title><style>
:root{--b:#0a6b5d;--bg:#fafafa;--fg:#1c1c1c;--mut:#777;--line:#e3e3e3}
*{box-sizing:border-box}body{margin:0;font-family:system-ui,sans-serif;background:var(--bg);color:var(--fg)}
header{display:flex;align-items:center;gap:1.5rem;padding:.8rem 1.4rem;background:#fff;border-bottom:1px solid var(--line)}
header h1{font-size:1.05rem;margin:0}
nav button{background:none;border:none;padding:.45rem .8rem;font-size:.95rem;cursor:pointer;border-radius:6px;color:var(--mut)}
nav button.on{background:var(--b);color:#fff}
main{max-width:900px;margin:1.2rem auto;padding:0 1rem}
.card{background:#fff;border:1px solid var(--line);border-radius:8px;padding:1rem;margin-bottom:1rem}
select,input,textarea{font:inherit;padding:.45rem .6rem;border:1px solid var(--line);border-radius:6px;width:100%}
button.act{background:var(--b);color:#fff;border:none;border-radius:6px;padding:.5rem 1rem;font:inherit;cursor:pointer}
button.act:disabled{opacity:.5}
#log{display:flex;flex-direction:column;gap:.6rem;min-height:200px;max-height:55vh;overflow-y:auto;padding:.4rem}
.msg{padding:.55rem .8rem;border-radius:10px;max-width:85%;white-space:pre-wrap}
.msg.user{align-self:flex-end;background:var(--b);color:#fff}
.msg.assistant{align-self:flex-start;background:#efefef}
.row{display:flex;gap:.6rem;margin-top:.6rem}
table{width:100%;border-collapse:collapse}td,th{text-align:left;padding:.45rem;border-bottom:1px solid var(--line)}
.pill{font-size:.75rem;padding:.1rem .5rem;border-radius:999px;background:#eee;color:var(--mut)}
.pill.loaded{background:#d9f2ea;color:var(--b)}
#imgout img{max-width:256px;border-radius:8px;margin:.3rem}
.small{font-size:.8rem;color:var(--mut)}
</style></head><body>
<header><h1>localai-tpu</h1><nav id="nav"></nav>
<span style="flex:1"></span><a class="small" href="/swagger">API docs</a></header>
<main id="main"></main>
<script>
const TABS={chat:Chat,talk:Talk,models:Models,editor:Editor,gallery:GalleryTab,jobs:Jobs,tts:TTS,image:Images};
let tab='chat';
function nav(){const n=document.getElementById('nav');n.innerHTML='';
 for(const t of Object.keys(TABS)){const b=document.createElement('button');
  b.textContent=t;b.className=t===tab?'on':'';b.onclick=()=>{tab=t;render()};n.appendChild(b)}}
function render(){nav();document.getElementById('main').innerHTML='';TABS[tab](document.getElementById('main'))}
async function models(uc){const r=await fetch('/v1/models');const d=await r.json();return d.data.map(m=>m.id)}
// All server-sourced strings (model names, gallery entries, job fields) go
// through esc() before any innerHTML interpolation — they are API-writable.
function esc(s){return String(s==null?'':s).replace(/[&<>"']/g,c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]))}
function sel(opts,id){return `<select id="${id}">`+opts.map(o=>`<option>${esc(o)}</option>`).join('')+`</select>`}

function Chat(el){
 el.innerHTML=`<div class="card"><div class="row"><div style="flex:1" id="mslot"></div></div>
 <div id="log"></div><div class="row"><textarea id="inp" rows="2" placeholder="Say something…"></textarea>
 <button class="act" id="send">Send</button></div></div>`;
 models().then(ms=>{document.getElementById('mslot').innerHTML=sel(ms,'model')});
 const hist=[];
 document.getElementById('send').onclick=async()=>{
  const inp=document.getElementById('inp');const text=inp.value.trim();if(!text)return;
  inp.value='';hist.push({role:'user',content:text});
  const log=document.getElementById('log');
  log.insertAdjacentHTML('beforeend',`<div class="msg user"></div>`);
  log.lastChild.textContent=text;
  log.insertAdjacentHTML('beforeend',`<div class="msg assistant"></div>`);
  const out=log.lastChild;log.scrollTop=log.scrollHeight;
  const resp=await fetch('/v1/chat/completions',{method:'POST',headers:{'Content-Type':'application/json'},
   body:JSON.stringify({model:document.getElementById('model').value,messages:hist,stream:true})});
  const rd=resp.body.getReader();const dec=new TextDecoder();let buf='',acc='';
  for(;;){const{done,value}=await rd.read();if(done)break;buf+=dec.decode(value,{stream:true});
   let i;while((i=buf.indexOf('\n\n'))>=0){const f=buf.slice(0,i);buf=buf.slice(i+2);
    const line=f.split('\n').find(l=>l.startsWith('data: '));if(!line)continue;
    const data=line.slice(6);if(data==='[DONE]')continue;
    try{const c=JSON.parse(data);const d=c.choices&&c.choices[0].delta;
     if(d&&d.content){acc+=d.content;out.textContent=acc;log.scrollTop=log.scrollHeight}}catch(e){}}}
  hist.push({role:'assistant',content:acc});};
}

async function Models(el){
 el.innerHTML=`<div class="card"><table id="mt"><tr><th>model</th><th>backend</th><th>state</th><th></th></tr></table></div>`;
 const sys=await(await fetch('/system')).json();
 const loaded=new Set(sys.loaded_models||[]);
 const list=await(await fetch('/v1/models')).json();
 const t=document.getElementById('mt');
 for(const m of list.data){const tr=document.createElement('tr');
  tr.innerHTML=`<td>${esc(m.id)}</td><td class="small">${esc(m.owned_by)}</td>
  <td><span class="pill ${loaded.has(m.id)?'loaded':''}">${loaded.has(m.id)?'loaded':'idle'}</span></td>
  <td>${loaded.has(m.id)?`<button class="act" data-m="${esc(m.id)}">unload</button>`:''}</td>`;
  t.appendChild(tr)}
 t.onclick=async e=>{const m=e.target.dataset&&e.target.dataset.m;if(!m)return;
  await fetch('/backend/shutdown',{method:'POST',headers:{'Content-Type':'application/json'},body:JSON.stringify({model:m})});
  Models(el)};
}

async function GalleryTab(el){
 el.innerHTML=`<div class="card" id="gl">loading gallery…</div>`;
 const g=document.getElementById('gl');
 try{
  const d=await(await fetch('/models/available')).json();
  if(!d.length){g.textContent='no galleries configured';return}
  g.innerHTML=`<table>`+d.map(m=>`<tr><td>${esc(m.name)}</td><td class="small">${esc(m.description||'')}</td>
   <td><button class="act" data-n="${esc(m.gallery?m.gallery+'@':'')}${esc(m.name)}">install</button></td></tr>`).join('')+`</table><div id="job"></div>`;
  g.onclick=async e=>{const n=e.target.dataset&&e.target.dataset.n;if(!n)return;
   const r=await(await fetch('/models/apply',{method:'POST',headers:{'Content-Type':'application/json'},body:JSON.stringify({id:n})})).json();
   const poll=async()=>{const s=await(await fetch('/models/jobs/'+r.uuid)).json();
    document.getElementById('job').textContent=`${n}: ${s.message||''} ${s.processed?'done':''}`;
    if(!s.processed)setTimeout(poll,500)};poll()};
 }catch(e){g.textContent='gallery unavailable: '+e}
}

function TTS(el){
 el.innerHTML=`<div class="card"><div id="ts"></div>
 <div class="row"><input id="txt" placeholder="Text to speak"><button class="act" id="go">Speak</button></div>
 <div class="row"><audio id="au" controls style="width:100%"></audio></div></div>`;
 models().then(ms=>{document.getElementById('ts').innerHTML=sel(ms,'tmodel')});
 document.getElementById('go').onclick=async()=>{
  const r=await fetch('/v1/audio/speech',{method:'POST',headers:{'Content-Type':'application/json'},
   body:JSON.stringify({model:document.getElementById('tmodel').value,input:document.getElementById('txt').value})});
  if(!r.ok){alert('tts failed: '+(await r.text()));return}
  document.getElementById('au').src=URL.createObjectURL(await r.blob())};
}

async function Editor(el){
 // Model config editor (reference: views/model-editor.html): pick a config,
 // edit the persisted dict as JSON, save via /models/edit, create new via
 // /models/import, delete, hot-reload all configs.
 el.innerHTML=`<div class="card"><div class="row"><div style="flex:1" id="es"></div>
 <button class="act" id="new">New</button><button class="act" id="reload">Reload configs</button></div>
 <textarea id="cfg" rows="16" spellcheck="false" style="font-family:monospace;margin-top:.6rem"></textarea>
 <div class="row"><button class="act" id="save">Save</button>
 <button class="act" id="del" style="background:#a33">Delete</button>
 <span class="small" id="emsg"></span></div></div>`;
 const msg=t=>{document.getElementById('emsg').textContent=t};
 async function pick(name){
  const r=await fetch('/models/config/'+encodeURIComponent(name));
  if(!r.ok){msg('load failed: '+await r.text());return}
  document.getElementById('cfg').value=JSON.stringify(await r.json(),null,2)}
 async function refresh(){
  const ms=await models();
  document.getElementById('es').innerHTML=sel(ms,'emodel');
  document.getElementById('emodel').onchange=e=>pick(e.target.value);
  if(ms.length)pick(ms[0])}
 refresh();
 document.getElementById('new').onclick=()=>{
  document.getElementById('cfg').value=JSON.stringify({name:'my-model',model:'',backend:'llama'},null,2)};
 document.getElementById('save').onclick=async()=>{
  let d;try{d=JSON.parse(document.getElementById('cfg').value)}catch(e){msg('invalid JSON: '+e);return}
  const exists=(await models()).includes(d.name);
  const r=exists?await fetch('/models/edit/'+encodeURIComponent(d.name),{method:'POST',
    headers:{'Content-Type':'application/json'},body:JSON.stringify(d)})
   :await fetch('/models/import',{method:'POST',headers:{'Content-Type':'application/json'},body:JSON.stringify(d)});
  msg(r.ok?'saved':'save failed: '+await r.text());if(r.ok)refresh()};
 document.getElementById('del').onclick=async()=>{
  const n=document.getElementById('emodel').value;if(!n)return;
  const r=await fetch('/models/delete/'+encodeURIComponent(n),{method:'POST'});
  msg(r.ok?'deleted '+n:'delete failed: '+await r.text());refresh()};
 document.getElementById('reload').onclick=async()=>{
  const r=await fetch('/models/reload',{method:'POST'});
  msg(r.ok?'reloaded':'reload failed')};
}

async function Jobs(el){
 // Agent jobs panel (reference: views/agent-jobs.html): list, create with a
 // cron schedule, run now, enable/disable, delete, view run history.
 el.innerHTML=`<div class="card"><table id="jt"><tr><th>name</th><th>model</th><th>schedule</th><th>enabled</th><th></th></tr></table>
 <div class="row"><input id="jn" placeholder="name"><input id="jm" placeholder="model">
 <input id="js" placeholder="schedule e.g. */5 * * * *"></div>
 <div class="row"><textarea id="jp" rows="2" placeholder="prompt"></textarea>
 <button class="act" id="jc">Create</button></div>
 <pre class="small" id="jh"></pre></div>`;
 async function refresh(){
  const t=document.getElementById('jt');
  const r=await fetch('/agent-jobs');
  if(!r.ok){t.innerHTML='<tr><td class="small">agent jobs unavailable (no MCP/agent service)</td></tr>';return}
  const jobs=(await r.json()).jobs||[];
  t.innerHTML='<tr><th>name</th><th>model</th><th>schedule</th><th>enabled</th><th></th></tr>';
  for(const j of jobs){const tr=document.createElement('tr');const id=esc(j.id);
   tr.innerHTML=`<td>${esc(j.name)}</td><td class="small">${esc(j.model)}</td><td class="small">${esc(j.schedule||'')}</td>
   <td><button class="act" data-a="toggle" data-id="${id}" data-en="${j.enabled}">${j.enabled?'on':'off'}</button></td>
   <td><button class="act" data-a="run" data-id="${id}">run</button>
   <button class="act" data-a="hist" data-id="${id}">history</button>
   <button class="act" data-a="del" data-id="${id}" style="background:#a33">x</button></td>`;
   t.appendChild(tr)}
  t.onclick=async e=>{const a=e.target.dataset&&e.target.dataset.a;if(!a)return;
   const id=e.target.dataset.id;
   if(a==='run')await fetch('/agent-jobs/'+id+'/run',{method:'POST'});
   if(a==='del')await fetch('/agent-jobs/'+id,{method:'DELETE'});
   if(a==='toggle')await fetch('/agent-jobs/'+id,{method:'PUT',headers:{'Content-Type':'application/json'},
    body:JSON.stringify({enabled:e.target.dataset.en!=='true'})});
   if(a==='hist'){const h=await(await fetch('/agent-jobs/'+id+'/history')).json();
    document.getElementById('jh').textContent=JSON.stringify(h,null,2);return}
   refresh()};
 }
 refresh();
 document.getElementById('jc').onclick=async()=>{
  const r=await fetch('/agent-jobs',{method:'POST',headers:{'Content-Type':'application/json'},
   body:JSON.stringify({name:document.getElementById('jn').value,model:document.getElementById('jm').value,
    prompt:document.getElementById('jp').value,schedule:document.getElementById('js').value})});
  if(!r.ok)alert('create failed: '+await r.text());refresh()};
}

function Talk(el){
 // Realtime talk page (reference: views/talk.html) against the existing
 // WS /v1/realtime: text turns always work; the mic button streams pcm16
 // with server-VAD turn detection; response audio deltas play back.
 el.innerHTML=`<div class="card"><div class="row"><div style="flex:1" id="tsl"></div>
 <button class="act" id="conn">Connect</button><button class="act" id="mic" disabled>Mic</button></div>
 <div id="log"></div>
 <div class="row"><textarea id="tinp" rows="2" placeholder="Type a turn…"></textarea>
 <button class="act" id="tsend" disabled>Send</button></div>
 <div class="small" id="tst">disconnected</div></div>`;
 models().then(ms=>{document.getElementById('tsl').innerHTML=sel(ms,'tkmodel')});
 let ws=null,ac=null,micNode=null,micStream=null,playT=0,out=null;
 function micOff(){if(micNode){micNode.disconnect();micNode=null}
  if(micStream){micStream.getTracks().forEach(t=>t.stop());micStream=null}
  const b=document.getElementById('mic');if(b)b.textContent='Mic'}
 const st=t=>{document.getElementById('tst').textContent=t};
 const log=document.getElementById('log');
 function playPcm(b64){
  if(!ac)ac=new AudioContext({sampleRate:24000});
  const raw=atob(b64);const n=raw.length/2;const f=new Float32Array(n);
  for(let i=0;i<n;i++){let v=(raw.charCodeAt(2*i)|(raw.charCodeAt(2*i+1)<<8));if(v>=32768)v-=65536;f[i]=v/32768}
  const buf=ac.createBuffer(1,n,24000);buf.copyToChannel(f,0);
  const src=ac.createBufferSource();src.buffer=buf;src.connect(ac.destination);
  playT=Math.max(playT,ac.currentTime);src.start(playT);playT+=n/24000}
 document.getElementById('conn').onclick=()=>{
  if(ws){ws.close();return}
  const m=document.getElementById('tkmodel').value;
  ws=new WebSocket((location.protocol==='https:'?'wss://':'ws://')+location.host+'/v1/realtime?model='+encodeURIComponent(m));
  ws.onopen=()=>{st('connected');document.getElementById('tsend').disabled=false;
   document.getElementById('mic').disabled=false;
   ws.send(JSON.stringify({type:'session.update',session:{turn_detection:{type:'server_vad',silence_duration_ms:500}}}))};
  ws.onclose=()=>{st('disconnected');ws=null;micOff();
   document.getElementById('tsend').disabled=true;
   document.getElementById('mic').disabled=true};
  ws.onmessage=e=>{const ev=JSON.parse(e.data);
   if(ev.type==='conversation.item.created'&&ev.item.role==='user'){
    const c=ev.item.content[0];const txt=c.transcript!==undefined?c.transcript:c.text;
    log.insertAdjacentHTML('beforeend','<div class="msg user"></div>');log.lastChild.textContent=txt}
   if(ev.type==='response.created'){log.insertAdjacentHTML('beforeend','<div class="msg assistant"></div>');
    out=log.lastChild}
   if((ev.type==='response.text.delta'||ev.type==='response.audio_transcript.delta')&&out){
    out.textContent+=ev.delta;log.scrollTop=log.scrollHeight}
   if(ev.type==='response.audio.delta')playPcm(ev.delta);
   if(ev.type==='error')st('error: '+ev.error.message)}};
 document.getElementById('tsend').onclick=()=>{
  const t=document.getElementById('tinp').value.trim();if(!t||!ws)return;
  document.getElementById('tinp').value='';
  ws.send(JSON.stringify({type:'conversation.item.create',item:{type:'message',role:'user',
   content:[{type:'input_text',text:t}]}}));
  ws.send(JSON.stringify({type:'response.create'}))};
 document.getElementById('mic').onclick=async()=>{
  if(micNode){micOff();return}
  micStream=await navigator.mediaDevices.getUserMedia({audio:true});
  if(!ac)ac=new AudioContext({sampleRate:24000});
  const src=ac.createMediaStreamSource(micStream);
  micNode=ac.createScriptProcessor(4096,1,1);
  micNode.onaudioprocess=e=>{if(!ws)return;
   const f=e.inputBuffer.getChannelData(0);const b=new Int16Array(f.length);
   for(let i=0;i<f.length;i++)b[i]=Math.max(-32768,Math.min(32767,f[i]*32768));
   const u8=new Uint8Array(b.buffer);let s='';for(let i=0;i<u8.length;i++)s+=String.fromCharCode(u8[i]);
   ws.send(JSON.stringify({type:'input_audio_buffer.append',audio:btoa(s)}))};
  src.connect(micNode);micNode.connect(ac.destination);
  document.getElementById('mic').textContent='Stop'};
}

function Images(el){
 el.innerHTML=`<div class="card"><div id="is"></div>
 <div class="row"><input id="prompt" placeholder="Prompt"><button class="act" id="gen">Generate</button></div>
 <div id="imgout"></div></div>`;
 models().then(ms=>{document.getElementById('is').innerHTML=sel(ms,'imodel')});
 document.getElementById('gen').onclick=async()=>{
  const r=await fetch('/v1/images/generations',{method:'POST',headers:{'Content-Type':'application/json'},
   body:JSON.stringify({model:document.getElementById('imodel').value,prompt:document.getElementById('prompt').value,response_format:'b64_json'})});
  if(!r.ok){alert('generation failed: '+(await r.text()));return}
  const d=await r.json();
  document.getElementById('imgout').innerHTML=d.data.map(x=>`<img src="data:image/png;base64,${x.b64_json}">`).join('')};
}
render();
</script></body></html>"""
