"""Minimal RFC 6455 WebSocket support for the stdlib HTTP server.

The reference's realtime endpoint rides gofiber's websocket upgrade
(core/http/endpoints/openai/realtime.go). Here the handshake and framing are
implemented directly — ~150 lines, no dependency — and handlers return a
`WebSocketUpgrade` from the router to take over the connection.
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
from typing import Any, Callable, Optional

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT, OP_TEXT, OP_BIN, OP_CLOSE, OP_PING, OP_PONG = 0x0, 0x1, 0x2, 0x8, 0x9, 0xA

# Largest accepted message (single frame or fragmented total). Realtime audio
# chunks are well under this; anything bigger is a memory-exhaustion attempt.
MAX_MESSAGE_BYTES = 16 * 1024 * 1024


class WebSocketUpgrade:
    """Handler return value: accept the upgrade, then run `session(ws)`."""

    def __init__(self, session: Callable[["WebSocket"], None]):
        self.session = session


def accept_key(client_key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((client_key + _GUID).encode()).digest()
    ).decode()


class WebSocket:
    """Blocking frame-level API over an upgraded socket."""

    def __init__(self, rfile, wfile):
        self._r = rfile
        self._w = wfile
        self.open = True

    # ------------------------------------------------------------------ #
    # Receive
    # ------------------------------------------------------------------ #

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._r.read(n - len(buf))
            if not chunk:
                raise ConnectionError("websocket peer closed")
            buf += chunk
        return buf

    def recv(self) -> Optional[tuple[int, bytes]]:
        """Next complete message → (opcode, payload); None once closed.
        Handles fragmentation, ping/pong, and unmasking."""
        message = b""
        msg_op = None
        while True:
            if not self.open:
                return None
            try:
                b1, b2 = self._read_exact(2)
            except ConnectionError:
                self.open = False
                return None
            fin = bool(b1 & 0x80)
            op = b1 & 0x0F
            masked = bool(b2 & 0x80)
            ln = b2 & 0x7F
            if ln == 126:
                (ln,) = struct.unpack(">H", self._read_exact(2))
            elif ln == 127:
                (ln,) = struct.unpack(">Q", self._read_exact(8))
            # The length field is client-controlled; cap it (and the
            # accumulated fragmented message) before buffering anything, or a
            # client claiming a multi-GB payload drives unbounded allocation.
            # Control frames may interleave a fragmented message and never
            # join it — but RFC 6455 §5.5 bounds them to 125 bytes (protocol
            # error beyond that, 1002), which also blocks ping amplification.
            if op >= 0x8:
                if ln > 125:
                    self.close(code=1002)
                    return None
            elif ln + len(message) > MAX_MESSAGE_BYTES:
                self.close(code=1009)  # Message Too Big
                return None
            mask = self._read_exact(4) if masked else None
            payload = self._read_exact(ln)
            if mask:
                payload = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
            if op == OP_CLOSE:
                self._send_frame(OP_CLOSE, b"")
                self.open = False
                return None
            if op == OP_PING:
                self._send_frame(OP_PONG, payload)
                continue
            if op == OP_PONG:
                continue
            if op in (OP_TEXT, OP_BIN):
                msg_op = op
                message = payload
            elif op == OP_CONT:
                message += payload
            if fin:
                return (msg_op or OP_TEXT), message

    def recv_json(self) -> Optional[dict]:
        while True:
            msg = self.recv()
            if msg is None:
                return None
            op, payload = msg
            if op != OP_TEXT:
                continue
            try:
                return json.loads(payload)
            except json.JSONDecodeError:
                self.send_json({"type": "error", "error": {
                    "message": "invalid JSON frame"}})

    # ------------------------------------------------------------------ #
    # Send
    # ------------------------------------------------------------------ #

    def _send_frame(self, op: int, payload: bytes) -> None:
        header = bytes([0x80 | op])
        n = len(payload)
        if n < 126:
            header += bytes([n])
        elif n < (1 << 16):
            header += bytes([126]) + struct.pack(">H", n)
        else:
            header += bytes([127]) + struct.pack(">Q", n)
        try:
            self._w.write(header + payload)
            self._w.flush()
        except (BrokenPipeError, ConnectionResetError):
            self.open = False

    def send_text(self, text: str) -> None:
        self._send_frame(OP_TEXT, text.encode())

    def send_json(self, obj: dict[str, Any]) -> None:
        self.send_text(json.dumps(obj))

    def send_bytes(self, data: bytes) -> None:
        self._send_frame(OP_BIN, data)

    def close(self, code: int = 1000) -> None:
        if self.open:
            self._send_frame(OP_CLOSE, struct.pack(">H", code))
            self.open = False
