"""Application services (reference: core/services — gallery installs, agent
jobs, metrics)."""

from localai_tpu.services.agent_jobs import AgentJob, AgentJobService  # noqa: F401
