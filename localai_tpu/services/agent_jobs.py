"""Agent jobs: persistent scheduled prompts run through the agent loop.

Reference: core/services/agent_jobs.go (1,382 LoC — a JSON-persisted job
store with cron scheduling and run history, driving agentic prompts). Same
contract here: jobs persist across restarts, fire on `@every Ns`/`@every Nm`
intervals or a 5-field cron subset, keep bounded history, and can be
triggered manually.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Callable, Optional

log = logging.getLogger("localai_tpu.agent_jobs")

HISTORY_LIMIT = 50


def _parse_every(s: str) -> Optional[float]:
    """`@every 30s` / `@every 5m` / `@every 1h` → seconds."""
    if not s.startswith("@every "):
        return None
    v = s[len("@every "):].strip()
    mult = {"s": 1.0, "m": 60.0, "h": 3600.0}.get(v[-1:])
    if mult is None:
        raise ValueError(f"invalid @every duration {v!r}")
    return float(v[:-1]) * mult


def _cron_field_matches(field: str, value: int) -> bool:
    for part in field.split(","):
        if part == "*":
            return True
        if part.startswith("*/"):
            if value % int(part[2:]) == 0:
                return True
        elif "-" in part:
            lo, hi = part.split("-")
            if int(lo) <= value <= int(hi):
                return True
        elif part.isdigit() and int(part) == value:
            return True
    return False


def cron_matches(expr: str, t: time.struct_time) -> bool:
    """5-field cron subset: minute hour dom month dow (*, */n, a-b, lists)."""
    fields = expr.split()
    if len(fields) != 5:
        raise ValueError(f"cron expression needs 5 fields: {expr!r}")
    minute, hour, dom, mon, dow = fields
    return (
        _cron_field_matches(minute, t.tm_min)
        and _cron_field_matches(hour, t.tm_hour)
        and _cron_field_matches(dom, t.tm_mday)
        and _cron_field_matches(mon, t.tm_mon)
        and _cron_dow_matches(dow, t.tm_wday)
    )


def _cron_dow_matches(field: str, tm_wday: int) -> bool:
    # cron day-of-week is 0=Sunday (7 also accepted as Sunday); python
    # tm_wday is 0=Monday. Convert, and let Sunday match either spelling.
    v = (tm_wday + 1) % 7
    return _cron_field_matches(field, v) or (
        v == 0 and _cron_field_matches(field, 7)
    )


@dataclasses.dataclass
class AgentJob:
    id: str
    name: str
    model: str
    prompt: str
    schedule: str  # "@every 30s" | "m h dom mon dow" | "" (manual only)
    enabled: bool = True
    created_at: float = 0.0
    last_run: float = 0.0
    history: list = dataclasses.field(default_factory=list)

    def validate(self) -> None:
        if not self.name:
            raise ValueError("job name required")
        if not self.prompt:
            raise ValueError("job prompt required")
        if self.schedule and _parse_every(self.schedule) is None:
            cron_matches(self.schedule, time.localtime())  # raises if invalid

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


Runner = Callable[[AgentJob], str]


class AgentJobService:
    """JSON-persisted job store + scheduler thread."""

    def __init__(self, store_path: str, runner: Runner, tick_s: float = 1.0):
        self.store_path = store_path
        self.runner = runner
        self.tick_s = tick_s
        self._jobs: dict[str, AgentJob] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_cron_minute = -1
        self._load()

    # ------------------------------------------------------------------ #
    # Persistence
    # ------------------------------------------------------------------ #

    def _load(self) -> None:
        if not os.path.exists(self.store_path):
            return
        try:
            with open(self.store_path) as f:
                data = json.load(f)
            for j in data.get("jobs", []):
                job = AgentJob(**j)
                self._jobs[job.id] = job
        except (json.JSONDecodeError, TypeError, KeyError) as e:
            log.warning("could not load agent jobs from %s: %s", self.store_path, e)

    def _save_locked(self) -> None:
        os.makedirs(os.path.dirname(self.store_path) or ".", exist_ok=True)
        tmp = self.store_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"jobs": [j.to_dict() for j in self._jobs.values()]}, f, indent=1)
        os.replace(tmp, self.store_path)

    # ------------------------------------------------------------------ #
    # CRUD
    # ------------------------------------------------------------------ #

    def list(self) -> list[AgentJob]:
        with self._lock:
            return sorted(self._jobs.values(), key=lambda j: j.created_at)

    def get(self, job_id: str) -> Optional[AgentJob]:
        with self._lock:
            return self._jobs.get(job_id)

    def create(self, name: str, model: str, prompt: str, schedule: str = "",
               enabled: bool = True) -> AgentJob:
        job = AgentJob(
            id=uuid.uuid4().hex[:12], name=name, model=model, prompt=prompt,
            schedule=schedule, enabled=enabled, created_at=time.time(),
        )
        job.validate()
        with self._lock:
            self._jobs[job.id] = job
            self._save_locked()
        return job

    def update(self, job_id: str, **patch) -> Optional[AgentJob]:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            for k, v in patch.items():
                if k in ("name", "model", "prompt", "schedule", "enabled") and v is not None:
                    setattr(job, k, v)
            job.validate()
            self._save_locked()
            return job

    def delete(self, job_id: str) -> bool:
        with self._lock:
            if self._jobs.pop(job_id, None) is None:
                return False
            self._save_locked()
            return True

    # ------------------------------------------------------------------ #
    # Execution
    # ------------------------------------------------------------------ #

    def run_now(self, job_id: str) -> Optional[dict]:
        job = self.get(job_id)
        if job is None:
            return None
        return self._execute(job)

    def _execute(self, job: AgentJob) -> dict:
        t0 = time.time()
        entry: dict[str, Any] = {"started_at": t0}
        try:
            entry["result"] = self.runner(job)
            entry["ok"] = True
        except Exception as e:  # noqa: BLE001 — recorded, scheduler survives
            entry["ok"] = False
            entry["error"] = f"{type(e).__name__}: {e}"
            log.warning("agent job %s (%s) failed: %s", job.name, job.id, e)
        entry["duration_s"] = time.time() - t0
        with self._lock:
            job.last_run = t0
            job.history.append(entry)
            del job.history[:-HISTORY_LIMIT]
            self._save_locked()
        return entry

    # ------------------------------------------------------------------ #
    # Scheduler
    # ------------------------------------------------------------------ #

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="agent-jobs")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            now = time.time()
            lt = time.localtime(now)
            cron_minute = lt.tm_min if self._last_cron_minute != lt.tm_min else None
            for job in self.list():
                if not job.enabled or not job.schedule:
                    continue
                try:
                    every = _parse_every(job.schedule)
                except ValueError:
                    continue
                due = False
                if every is not None:
                    due = now - job.last_run >= every
                elif cron_minute is not None:
                    try:
                        due = cron_matches(job.schedule, lt) and now - job.last_run >= 60
                    except ValueError:
                        due = False
                if due:
                    self._execute(job)
            if cron_minute is not None:
                self._last_cron_minute = lt.tm_min
