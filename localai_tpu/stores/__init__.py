"""Vector stores: the /stores/{set,get,delete,find} capability.

Reference: backend/go/local-store/store.go:18-47 (in-memory brute-force store
behind the Stores* RPCs; cosine similarity with a normalized fast path) and
pkg/store/client.go. TPU-native difference: similarity search is one batched
matmul — exactly what the MXU is for — instead of a Go loop over entries.
"""

from localai_tpu.stores.store import StoreRegistry, VectorStore  # noqa: F401
