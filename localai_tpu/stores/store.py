"""In-memory vector store with JAX-accelerated cosine top-k.

Semantics mirror backend/go/local-store/store.go:
- set: upsert by exact key (float bit-pattern equality);
- get/delete: exact-key lookup;
- find: cosine-similarity top-k, with the normalized fast path (when every
  stored vector and the query are unit-norm, cosine == dot product and the
  normalization divide is skipped — store.go's `normalized` flag).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np


class VectorStore:
    def __init__(self, dim: Optional[int] = None):
        self.dim = dim
        self._lock = threading.Lock()
        self._keys: np.ndarray = np.zeros((0, 0), np.float32)
        self._values: list[bytes] = []
        self._index: dict[bytes, int] = {}  # key bytes -> row
        self._all_normalized = True

    def __len__(self) -> int:
        return len(self._values)

    def _check_dim(self, arr: np.ndarray) -> None:
        if self.dim is None:
            self.dim = arr.shape[-1]
            self._keys = np.zeros((0, self.dim), np.float32)
        elif arr.shape[-1] != self.dim:
            raise ValueError(f"key dim {arr.shape[-1]} != store dim {self.dim}")

    def set(self, keys: np.ndarray, values: list[bytes]) -> None:
        keys = np.asarray(keys, np.float32)
        if keys.ndim != 2 or len(keys) != len(values):
            raise ValueError("keys must be [N, D] with one value per key")
        with self._lock:
            self._check_dim(keys)
            new_rows: list[np.ndarray] = []
            for k, v in zip(keys, values):
                kb = k.tobytes()
                row = self._index.get(kb)
                if row is not None:
                    self._values[row] = v  # upsert (also dedupes within a batch)
                else:
                    self._index[kb] = len(self._values)
                    self._values.append(v)
                    new_rows.append(k)
            if new_rows:
                stacked = np.stack(new_rows)
                self._keys = np.concatenate([self._keys, stacked], axis=0)
                # Incremental: only the new rows need checking (O(new), not O(N)).
                self._all_normalized = self._all_normalized and bool(
                    np.allclose(np.linalg.norm(stacked, axis=-1), 1.0, atol=1e-3)
                )

    def get(self, keys: np.ndarray) -> list[Optional[bytes]]:
        keys = np.asarray(keys, np.float32)
        with self._lock:
            out = []
            for k in keys:
                row = self._index.get(k.tobytes())
                out.append(self._values[row] if row is not None else None)
            return out

    def delete(self, keys: np.ndarray) -> int:
        keys = np.asarray(keys, np.float32)
        with self._lock:
            rows = sorted(
                {r for k in keys if (r := self._index.get(k.tobytes())) is not None},
                reverse=True,
            )
            if not rows:
                return 0
            keep = np.ones(len(self._values), bool)
            for r in rows:
                keep[r] = False
            self._keys = self._keys[keep]
            self._values = [v for i, v in enumerate(self._values) if keep[i]]
            self._index = {k.tobytes(): i for i, k in enumerate(self._keys)}
            if not self._all_normalized and len(self._keys):
                # Removing the offending rows may restore the fast path.
                self._all_normalized = bool(
                    np.allclose(np.linalg.norm(self._keys, axis=-1), 1.0, atol=1e-3)
                )
            elif not len(self._keys):
                self._all_normalized = True
            return len(rows)

    def find(self, key: np.ndarray, topk: int) -> tuple[np.ndarray, list[bytes], np.ndarray]:
        """Returns (keys [K, D], values, similarities [K]) sorted descending."""
        import jax.numpy as jnp

        q = np.asarray(key, np.float32).reshape(-1)
        with self._lock:
            if not len(self._values):
                return np.zeros((0, self.dim or len(q)), np.float32), [], np.zeros((0,), np.float32)
            if self.dim is not None and len(q) != self.dim:
                raise ValueError(f"query dim {len(q)} != store dim {self.dim}")
            mat = self._keys
            values = list(self._values)
            normalized = self._all_normalized  # snapshot with mat, same lock
        k = min(topk, len(values))
        if k <= 0:
            return np.zeros((0, self.dim), np.float32), [], np.zeros((0,), np.float32)
        # The query is always normalized (store.go:500 requires isNormalized
        # on both sides before the fast path; normalizing q is cheap and makes
        # the flag only about the stored rows).
        qn = q / max(float(np.linalg.norm(q)), 1e-9)
        if normalized:
            sims = jnp.asarray(mat) @ jnp.asarray(qn)  # cosine == dot (fast path)
        else:
            norms = jnp.linalg.norm(jnp.asarray(mat), axis=-1).clip(1e-9)
            sims = (jnp.asarray(mat) @ jnp.asarray(qn)) / norms
        import jax

        vals, idx = jax.lax.top_k(sims, k)
        idx = np.asarray(idx)
        return mat[idx], [values[i] for i in idx], np.asarray(vals)


class StoreRegistry:
    """Named stores, created on first use (reference: one store per loaded
    local-store backend instance; here a name → store map)."""

    def __init__(self) -> None:
        self._stores: dict[str, VectorStore] = {}
        self._lock = threading.Lock()

    def get(self, name: str = "") -> VectorStore:
        with self._lock:
            if name not in self._stores:
                self._stores[name] = VectorStore()
            return self._stores[name]

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._stores)
