"""Prompt templating.

Python re-design of the reference's Go text/template evaluator
(core/templates/evaluator.go:58-230; 5 template types, per-message loop,
function-grammar injection) using jinja2 — the same engine HF chat templates
use, so custom templates and tokenizer templates share one mental model.
"""

from localai_tpu.templates.evaluator import Evaluator, FAMILY_TEMPLATES  # noqa: F401
