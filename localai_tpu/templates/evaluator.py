"""Template evaluation for chat / completion / edit prompts.

Selection order per request type (reference: evaluator.go:58-90 per-type
selection; :96-230 message loop):

  chat:       tokenizer chat template (use_tokenizer_template)
            → custom `template.chat` (jinja2 over the whole conversation)
            → custom `template.chat_message` (jinja2 per message, joined)
            → built-in `template.family` (llama3 / chatml / mistral / alpaca)
            → plain role-prefixed fallback
  completion: custom `template.completion` → prompt as-is
  edit:       custom `template.edit` → instruction+input fallback

Tool/function definitions are injected as a system-prompt suffix
(`tools_prompt`), the moral equivalent of the reference's function-grammar
injection into the Functions template (evaluator.go:96-230).
"""

from __future__ import annotations

from typing import Any, Optional

import jinja2

from localai_tpu.config.model_config import ModelConfig

_ENV = jinja2.Environment(
    loader=jinja2.BaseLoader(),
    undefined=jinja2.ChainableUndefined,
    trim_blocks=True,
    lstrip_blocks=True,
)

# Built-in conversation templates for common model families. Each receives
# `messages` (normalized role/content dicts) and `add_generation_prompt`.
FAMILY_TEMPLATES: dict[str, str] = {
    "llama3": (
        "{% for m in messages %}"
        "<|start_header_id|>{{ m.role }}<|end_header_id|>\n\n{{ m.content }}<|eot_id|>"
        "{% endfor %}"
        "{% if add_generation_prompt %}<|start_header_id|>assistant<|end_header_id|>\n\n{% endif %}"
    ),
    "chatml": (
        "{% for m in messages %}"
        "<|im_start|>{{ m.role }}\n{{ m.content }}<|im_end|>\n"
        "{% endfor %}"
        "{% if add_generation_prompt %}<|im_start|>assistant\n{% endif %}"
    ),
    "mistral": (
        "{% for m in messages %}"
        "{% if m.role == 'user' %}[INST] {{ m.content }} [/INST]"
        "{% elif m.role == 'assistant' %}{{ m.content }}</s>"
        "{% else %}{{ m.content }}\n{% endif %}"
        "{% endfor %}"
    ),
    "alpaca": (
        "{% for m in messages %}"
        "{% if m.role == 'system' %}{{ m.content }}\n\n"
        "{% elif m.role == 'user' %}### Instruction:\n{{ m.content }}\n\n"
        "{% else %}### Response:\n{{ m.content }}\n\n{% endif %}"
        "{% endfor %}"
        "{% if add_generation_prompt %}### Response:\n{% endif %}"
    ),
}


def normalize_messages(messages: list[dict[str, Any]]) -> list[dict[str, str]]:
    """Flatten OpenAI message content (string or content-part list) to text.

    Reference: core/schema/message.go content-part parsing. Image/audio parts
    are dropped here; multimodal models consume them separately.
    """
    out = []
    for m in messages:
        content = m.get("content")
        if isinstance(content, list):
            texts = [p.get("text", "") for p in content if isinstance(p, dict) and p.get("type") == "text"]
            content = "\n".join(t for t in texts if t)
        elif content is None:
            content = ""
        role = m.get("role", "user")
        if m.get("tool_calls"):
            calls = [
                f'{{"name": "{tc["function"]["name"]}", "arguments": {tc["function"]["arguments"]}}}'
                for tc in m["tool_calls"]
                if "function" in tc
            ]
            content = (content + "\n" if content else "") + "\n".join(calls)
        out.append({"role": role, "content": str(content)})
    return out


class Evaluator:
    """Renders final prompt strings for one model's configuration."""

    def __init__(self, cfg: ModelConfig, tokenizer=None):
        self.cfg = cfg
        self.tokenizer = tokenizer
        self._cache: dict[str, jinja2.Template] = {}

    def _tmpl(self, source: str) -> jinja2.Template:
        if source not in self._cache:
            self._cache[source] = _ENV.from_string(source)
        return self._cache[source]

    def template_messages(
        self,
        messages: list[dict[str, Any]],
        tools_prompt: str = "",
        add_generation_prompt: bool = True,
    ) -> str:
        msgs = normalize_messages(messages)
        if self.cfg.system_prompt and not any(m["role"] == "system" for m in msgs):
            msgs = [{"role": "system", "content": self.cfg.system_prompt}] + msgs
        if tools_prompt:
            for m in msgs:
                if m["role"] == "system":
                    m["content"] = m["content"] + "\n" + tools_prompt
                    break
            else:
                msgs = [{"role": "system", "content": tools_prompt}] + msgs

        t = self.cfg.template
        if t.use_tokenizer_template and getattr(self.tokenizer, "chat_template", None):
            return self.tokenizer.apply_chat_template(
                msgs, add_generation_prompt=add_generation_prompt
            )
        if t.chat:
            return self._tmpl(t.chat).render(
                messages=msgs, add_generation_prompt=add_generation_prompt
            )
        if t.chat_message:
            rendered = [
                self._tmpl(t.chat_message).render(
                    role=m["role"], content=m["content"], index=i
                )
                for i, m in enumerate(msgs)
            ]
            text = "\n".join(rendered)
            return text + ("\n" if add_generation_prompt else "")
        family = t.family or "chatml"
        if family in FAMILY_TEMPLATES:
            return self._tmpl(FAMILY_TEMPLATES[family]).render(
                messages=msgs, add_generation_prompt=add_generation_prompt
            )
        # Plain fallback.
        text = "\n".join(f"{m['role']}: {m['content']}" for m in msgs)
        return text + ("\nassistant: " if add_generation_prompt else "")

    def template_completion(self, prompt: str) -> str:
        t = self.cfg.template
        if t.completion:
            return self._tmpl(t.completion).render(input=prompt, prompt=prompt)
        return prompt

    def template_edit(self, instruction: str, input_text: str) -> str:
        t = self.cfg.template
        if t.edit:
            return self._tmpl(t.edit).render(instruction=instruction, input=input_text)
        return (
            f"Below is an instruction that describes a task, paired with an input.\n\n"
            f"### Instruction:\n{instruction}\n\n### Input:\n{input_text}\n\n### Response:\n"
        )

    def stop_sequences(self) -> list[str]:
        """Family-implied stop strings merged with configured ones."""
        stops = list(self.cfg.stop)
        fam = self.cfg.template.family
        extra = {
            "llama3": ["<|eot_id|>"],
            "chatml": ["<|im_end|>"],
            "mistral": ["</s>"],
            "alpaca": ["### Instruction:"],
        }.get(fam or "", [])
        for s in extra:
            if s not in stops:
                stops.append(s)
        return stops
