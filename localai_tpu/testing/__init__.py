"""Deterministic test instrumentation (fault injection — see faults.py).

Nothing in here may import jax or any other heavy dependency: the hooks sit
on serving hot paths and must cost one attribute load when disabled.
"""
